"""Prompt builders for the agent loop.  Functional parity with the
reference's inline prompts (agent_graph.py:198-516) in this framework's
five-scope vocabulary (catalog/repo/module/file/chunk instead of
project/package/file/code)."""

from __future__ import annotations

import json

from githubrepostorag_tpu.retrieval.retrievers import SCOPE_LADDER as SCOPES


def plan_prompt(query: str) -> str:
    return (
        "Pick the retrieval scope that best fits this question about a code "
        "knowledge base. Scopes, from broadest to narrowest: catalog (what "
        "projects exist), repo (whole-repository summaries), module "
        "(directory-level summaries), file (per-file summaries), chunk "
        "(actual code fragments).\n"
        'Reply with JSON only: {"scope": "catalog|repo|module|file|chunk", '
        '"filters": {"repo": "...", "module": "...", "topics": "..."}} '
        "(filters optional).\n"
        f"Question: {query}\n"
        "JSON:"
    )


def expansion_prompt(query: str, repo: str | None, scope: str | None) -> str:
    ctx = ""
    if repo:
        ctx += f" Repository under discussion: {repo}."
    if scope:
        ctx += f" Current search scope: {scope}."
    return (
        "Produce 3-4 alternative search queries that could surface the same "
        "information as the question below — use technical synonyms, related "
        "subsystem names, and rephrasings. Reply with a JSON array of "
        "strings only.\n"
        f"Question: {query}{ctx}\n"
        "JSON array:"
    )


def judge_prompt(query: str, inventory: list[dict], current_scope: str) -> str:
    deeper = SCOPES[min(SCOPES.index(current_scope) + 1, len(SCOPES) - 1)] if current_scope in SCOPES else "chunk"
    return (
        "Assess whether the retrieved items below can answer the question. "
        "Weigh both the metadata and the content previews. Reply with JSON "
        'only: {"coverage": 0.0-1.0, "needs_more": true|false, '
        '"suggest_filters": {"repo": "...", "module": "...", "topics": "..."}, '
        '"stage_down": "<a NARROWER scope than the current one, or null>", '
        '"rewrite": "optional better query"}.\n'
        f"Current scope: {current_scope} (narrower scopes: "
        f"{', '.join(SCOPES[SCOPES.index(current_scope) + 1:]) if current_scope in SCOPES else deeper} )\n"
        f"Question: {query}\n"
        f"Retrieved items: {json.dumps(inventory, ensure_ascii=False)}\n"
        "JSON:"
    )


def rewrite_prompt(query: str, context: str) -> str:
    return (
        f"Rephrase this question about a codebase so a vector search finds "
        f"more specific matches: '{query}'"
        + (f" (context: {context})" if context else "")
        + "\nReply with the rephrased question only:"
    )


def synthesis_prompt(query: str, blocks: list[str], overview: bool) -> str:
    if overview:
        style = (
            "You are a senior engineer summarizing a code knowledge base. "
            "Build a thorough answer from the context blocks, citing them as "
            "[1], [2], ... . When asked what projects or components exist, "
            "describe every one visible in the context."
        )
    else:
        style = (
            "You are a senior engineer answering a question about a "
            "codebase. Ground every claim in the context blocks and cite "
            "them as [1], [2], ... . If the context lacks the answer, say "
            "which repo or module likely contains it."
        )
    return f"{style}\n\nQuestion: {query}\n\nContext:\n" + "\n\n".join(blocks) + "\n\nAnswer:"


def longctx_synthesis_prompt(query: str, repo: str, repo_text: str) -> str:
    """Whole-repo answer mode: the assembled repository (every ingested
    chunk, file-ordered — retrieval/assembler.py) IS the context, so the
    style asks for cross-cutting structure instead of block citations."""
    style = (
        f"You are a senior engineer who has just read the ENTIRE {repo} "
        "repository, reproduced below with ### file headers. Answer from "
        "the whole codebase: describe how the pieces fit together, citing "
        "files by path where it helps."
    )
    return (
        f"{style}\n\nQuestion: {query}\n\nRepository {repo}:\n{repo_text}"
        "\n\nAnswer:"
    )


def encouraging_synthesis_prompt(query: str, blocks: list[str]) -> str:
    style = (
        "You are a helpful engineer. The context below genuinely contains "
        "relevant material — use it. Describe what the context shows rather "
        "than declining to answer, citing blocks as [1], [2], ... ."
    )
    return f"{style}\n\nQuestion: {query}\n\nContext:\n" + "\n\n".join(blocks) + "\n\nAnswer:"
