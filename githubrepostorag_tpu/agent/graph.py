"""The agentic query loop: plan_scope -> [retrieve -> judge -> rewrite?]* ->
synthesize.

Behavioral rebuild of the reference's LangGraph agent (agent_graph.py) as an
explicit state machine — same stages, same JSON-robustness fallbacks, same
truncation budgets, with the scope ladder extended to the full five-level
hierarchy (the reference never queried its catalog table — Appendix A of
SURVEY.md) and a per-run progress context instead of the racy instance-level
callback swap (agent_graph.py:526-543).

Stage semantics (reference file:line):
  plan_scope  — LLM JSON {scope, filters}; heuristic fallback looks_codey
                (:33-38), repo-hint regex (:40-42), tech synonyms (:31)
  retrieve    — scope retriever; on <3 hits or retry, LLM semantic expansion
                with content-hash dedup capped at ROUTER_TOP_K (:241-302)
  judge       — LLM JSON coverage/needs_more/suggest_filters/stage_down/
                rewrite; parse-fail auto-stage-down; coverage<0.3 ladder
                progression (:304-384)
  rewrite     — attempt 1: LLM rewrite; later: semantic expansion; stuck
                detection forces file scope (:386-446)
  synthesize  — <=5 blocks x 800 chars, citation prompts split
                overview/specific, anti-conservative retry (:448-516)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable

from githubrepostorag_tpu.agent import prompts
from githubrepostorag_tpu.agent.state import AgentState, ProgressCallback
from githubrepostorag_tpu.config import get_settings
from githubrepostorag_tpu.llm import LLM
from githubrepostorag_tpu.obs.trace import TraceContext, span, trace_scope
from githubrepostorag_tpu.resilience.policy import Deadline, DeadlineExceeded, deadline_scope
from githubrepostorag_tpu.retrieval import RetrievedDoc, RetrieverFactory
from githubrepostorag_tpu.retrieval.retrievers import SCOPE_LADDER
from githubrepostorag_tpu.utils.json_utils import extract_json, truncate
from githubrepostorag_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Heuristic scope fallback (agent_graph.py:33-38): code-smelling questions
# start narrow, everything else starts broad.
_CODEY_TERMS = (
    "stacktrace", "traceback", "exception", "error", "class ", "function ",
    "method ", "nullpointer", "undefined", "timeout", "reconnect", "retry",
    "implement", "bug", "regex", "snippet",
)

# Tech synonym -> topics filter (agent_graph.py:31).  Extensible map.
TECH_SYNONYMS: dict[str, tuple[str, ...]] = {
    "activemq": ("activemq", "jms", "amq", "broker", "stomp"),
    "kafka": ("kafka", "consumer group", "partition"),
    "redis": ("redis", "pubsub", "cache"),
    "cassandra": ("cassandra", "cql", "keyspace"),
    "kubernetes": ("kubernetes", "k8s", "helm", "kubectl"),
}

_REPO_HINT_RE = re.compile(r"(?:repo(?:sitory)?[:\s]+)([\w\-./]+)", re.IGNORECASE)
_OVERVIEW_TERMS = ("projects", "repositories", "overview", "tell me about", "what is", "describe")
# Architecture-class questions: cross-cutting structure that no 5-block
# chunk context can answer well.  With a repo identified, these route to
# the whole-repo long-context mode (retrieval/assembler.py feeds the
# serving stack's ring-prefill path) instead of the iterative RAG loop.
_ARCHITECTURE_TERMS = (
    "architecture", "how does", "how do", "design", "structure",
    "data flow", "end to end", "end-to-end", "walk me through",
    "walk through", "overall", "interact", "fit together", "lifecycle",
)
_CONSERVATIVE_PHRASES = (
    "insufficient", "don't see enough", "don't have enough", "can't answer",
    "not enough information", "cannot answer", "no information",
)

SOURCE_TEXT_BUDGET = 1200  # chars carried per source (agent_graph.py:84)
JUDGE_PREVIEW_BUDGET = 200  # chars per judge preview (agent_graph.py:314)
SYNTH_BLOCK_BUDGET = 800  # chars per synthesis block (agent_graph.py:453-459)
SYNTH_MAX_BLOCKS = 5


def looks_codey(query: str) -> bool:
    ql = query.lower()
    return any(term in ql for term in _CODEY_TERMS)


def wants_whole_repo(query: str) -> bool:
    """Architecture-class question — the whole repo beats any 5 chunks.
    Snippet-smelling questions (looks_codey) stay on chunk RAG: they want
    one precise fragment, not 11k tokens of everything."""
    ql = query.lower()
    return any(term in ql for term in _ARCHITECTURE_TERMS) and not looks_codey(query)


def extract_repo_hint(query: str) -> str | None:
    m = _REPO_HINT_RE.search(query)
    return m.group(1) if m else None


def next_scope_down(scope: str) -> str:
    try:
        idx = SCOPE_LADDER.index(scope)
    except ValueError:
        return "chunk"
    return SCOPE_LADDER[min(idx + 1, len(SCOPE_LADDER) - 1)]


class RunCancelled(Exception):
    """Raised between stages when the caller's should_stop probe fires
    (cooperative cancellation — the reference only checked once before any
    work, worker.py:121-124)."""


@dataclass
class AgentResult:
    answer: str
    sources: list[dict[str, Any]]
    debug: dict[str, Any] = field(default_factory=dict)


class GraphAgent:
    def __init__(
        self,
        llm: LLM,
        retrievers: RetrieverFactory | None = None,
        max_iters: int | None = None,
        namespace: str | None = None,
    ) -> None:
        s = get_settings()
        self.llm = llm
        self.retrievers = retrievers or RetrieverFactory()
        self.max_iters = max_iters or s.max_rag_attempts
        self.namespace = namespace
        self.router_top_k = s.router_top_k
        self.longctx = s.agent_longctx

    # ------------------------------------------------------------- stages

    def plan_scope(self, state: AgentState, force_level: str | None = None) -> None:
        q = state.query
        if self.namespace:
            state.filters.setdefault("namespace", self.namespace)
        hint = extract_repo_hint(q)
        if hint:
            state.filters["repo"] = hint

        if force_level in SCOPE_LADDER:
            # skip the planning round-trip entirely; hint/synonym filters
            # above still apply
            scope = force_level
        else:
            raw = self.llm.complete(prompts.plan_prompt(q))
            data = extract_json(raw, default=None)
            if isinstance(data, dict) and data.get("scope") in SCOPE_LADDER:
                scope = data["scope"]
                self._merge_filters(state.filters, data.get("filters"))
            else:
                scope = "chunk" if looks_codey(q) else "repo"

        for tech, terms in TECH_SYNONYMS.items():
            if "topics" in state.filters:
                break
            if any(t in q.lower() for t in terms):
                state.filters["topics"] = tech
                break

        state.scope = scope
        # whole-repo long-context routing: an architecture-class question
        # with the repo pinned down (hint or planner filter) skips the
        # iterative loop and reads the assembled repo in one ring-prefill
        # pass.  force_level is an explicit caller scope — honor it.
        if (
            self.longctx
            and force_level not in SCOPE_LADDER
            and state.filters.get("repo")
            and wants_whole_repo(q)
        ):
            state.mode = "longctx"
        state.breadcrumb(
            "plan", scope=scope, mode=state.mode, filters=dict(state.filters),
            attempt=state.attempt, forced=force_level in SCOPE_LADDER or None,
        )

    def retrieve(self, state: AgentState) -> None:
        retriever = self.retrievers.for_scope(state.scope)
        cap = state.top_k if state.top_k and state.top_k > 0 else self.router_top_k
        docs = retriever.retrieve(state.query, state.filters, top_k=state.top_k)
        original_count = len(docs)

        if (len(docs) < 3 or state.attempt > 0) and len(docs) < cap:
            expanded = self._expand_query(state.query, state.filters.get("repo"), state.scope)
            # collect every expansion candidate first, then rank — capping by
            # insertion order would drop stronger docs from later queries.
            # The whole expansion set goes out as ONE batched wave (one
            # encoder forward + one seed dispatch) instead of per-query
            # sequential retrievals.
            seen = {hash(d.text) for d in docs}
            extras: list[RetrievedDoc] = []
            retrieve_many = getattr(retriever, "retrieve_many", None)
            try:
                if callable(retrieve_many):
                    alt_lists = retrieve_many(expanded, state.filters,
                                              top_k=state.top_k)
                else:  # duck-typed retriever without the batched API
                    alt_lists = [retriever.retrieve(alt, state.filters,
                                                    top_k=state.top_k)
                                 for alt in expanded]
            except Exception as exc:  # noqa: BLE001 - expansion is best-effort
                logger.warning("expanded queries %r failed: %s", expanded, exc)
                alt_lists = []
            for alt_docs in alt_lists:
                for doc in alt_docs:
                    h = hash(doc.text)
                    if h not in seen:
                        seen.add(h)
                        extras.append(doc)
            extras.sort(key=lambda d: d.score, reverse=True)
            all_docs = (list(docs) + extras)[:cap]
            if len(all_docs) > original_count:
                state.breadcrumb(
                    "retrieve_expanded",
                    original_hits=original_count,
                    expanded_hits=len(all_docs),
                    expanded_queries=expanded,
                )
            docs = all_docs

        docs.sort(key=lambda d: d.score, reverse=True)
        state.docs = docs
        if docs:
            state.best_docs = docs
        state.breadcrumb(
            "retrieve", scope=state.scope, filters=dict(state.filters),
            hits=len(docs), original_hits=original_count, attempt=state.attempt,
        )

    def judge(self, state: AgentState) -> None:
        inventory = [
            {
                "i": i,
                "repo": d.metadata.get("repo", ""),
                "module": d.metadata.get("module", ""),
                "file": d.metadata.get("file_path", ""),
                "topics": d.metadata.get("topics", ""),
                "content_preview": truncate(d.text, JUDGE_PREVIEW_BUDGET),
                "relevance_score": round(d.score, 4),
            }
            for i, d in enumerate(state.docs, start=1)
        ]
        raw = self.llm.complete(prompts.judge_prompt(state.query, inventory, state.scope))
        data = extract_json(raw, default=None)
        if not isinstance(data, dict):
            # parse failure: the ladder keeps moving instead of stalling
            # (agent_graph.py:346-355)
            if state.scope in ("catalog", "repo", "module"):
                data = {"coverage": 0.2, "needs_more": True, "stage_down": next_scope_down(state.scope)}
            else:
                data = {"coverage": 0.4, "needs_more": False}

        self._merge_filters(state.filters, data.get("suggest_filters"))

        stage_down = data.get("stage_down")
        cur_idx = SCOPE_LADDER.index(state.scope) if state.scope in SCOPE_LADDER else 0
        if (
            stage_down in SCOPE_LADDER
            and SCOPE_LADDER.index(stage_down) > cur_idx  # only ever drill DOWN
        ):
            state.scope = stage_down
        elif _as_float(data.get("coverage")) < 0.3 and state.docs:
            state.scope = next_scope_down(state.scope)

        state.needs_more = bool(data.get("needs_more"))
        state.rewrite = data.get("rewrite") if isinstance(data.get("rewrite"), str) else None
        state.breadcrumb("judge", decision=data)

    def rewrite_or_end(self, state: AgentState) -> str:
        """Returns "synthesize" or "retry"."""
        if not state.needs_more:
            return "synthesize"
        attempt = state.attempt + 1
        if attempt >= self.max_iters:
            state.attempt = attempt
            state.breadcrumb("rewrite", action="end", reason="max_iters", attempt=attempt)
            return "synthesize"
        state.attempt = attempt

        # stuck detection: only summary-level docs while scoped broad ->
        # force the file level (agent_graph.py:396-404)
        if attempt > 1 and state.docs:
            all_summary_level = all(not d.metadata.get("file_path") for d in state.docs)
            if all_summary_level and state.scope in ("catalog", "repo", "module"):
                state.scope = "file"
                state.breadcrumb("rewrite", action="force_drill_down", scope="file", attempt=attempt)
                return "retry"

        base_query = state.rewrite or state.query
        context = " ".join(
            state.filters[k] for k in ("repo", "module") if state.filters.get(k)
        )
        if attempt == 1:
            raw = self.llm.complete(prompts.rewrite_prompt(base_query, context))
            sharpened = raw.strip().strip("\"'").strip()
            if not sharpened or len(sharpened) < 10 or sharpened.lower().startswith("error"):
                sharpened = f"{base_query} in {context}" if context else base_query
        else:
            expanded = self._expand_query(base_query, state.filters.get("repo"), state.scope)
            sharpened = expanded[0] if expanded else base_query

        state.query = sharpened
        state.breadcrumb("rewrite", action="retry", attempt=attempt, query=sharpened,
                         filters=dict(state.filters))
        return "retry"

    def synthesize(self, state: AgentState, token_cb: Callable[[str], None] | None = None) -> None:
        # Two robustness improvements over the reference, which synthesizes
        # over whatever the LAST retrieve returned (possibly nothing): fall
        # back to the best non-empty retrieval of the run, and as a last
        # resort try the chunk scope with the original query.
        docs = state.docs or state.best_docs
        if not docs:
            flt = {k: v for k, v in state.filters.items() if k == "namespace"}
            try:
                docs = self.retrievers.retrieve("chunk", state.original_query,
                                                flt, top_k=state.top_k)
            except Exception:  # noqa: BLE001
                docs = []
            if docs:
                state.breadcrumb("retrieve", scope="chunk", filters=flt,
                                 hits=len(docs), last_resort=True)
        blocks: list[str] = []
        sources: list[dict[str, Any]] = []
        for i, d in enumerate(docs[:SYNTH_MAX_BLOCKS], start=1):
            md = d.metadata
            snippet = truncate(d.text, SYNTH_BLOCK_BUDGET)
            blocks.append(
                f"[{i}] repo={md.get('repo', '')} module={md.get('module', '')} "
                f"file={md.get('file_path', '')}\n{snippet}"
            )
            sources.append(
                {
                    "id": i,
                    "doc_id": d.doc_id,
                    "repo": md.get("repo", ""),
                    "module": md.get("module", ""),
                    "file_path": md.get("file_path", ""),
                    "scope": md.get("scope", state.scope),
                    "score": round(d.score, 4),
                    "text": truncate(d.text, SOURCE_TEXT_BUDGET),
                }
            )

        ql = state.original_query.lower()
        overview = any(term in ql for term in _OVERVIEW_TERMS)
        has_content = any(len(b.split("\n", 1)[-1].strip()) > 50 for b in blocks)

        synth_prompt = prompts.synthesis_prompt(
            state.original_query, blocks, overview and has_content
        )
        text = self._complete(synth_prompt, token_cb)

        # anti-conservative retry (agent_graph.py:489-503)
        if has_content and len(docs) >= 3 and _sounds_conservative(text):
            retry_text = self.llm.complete(
                prompts.encouraging_synthesis_prompt(state.original_query, blocks)
            )
            if retry_text and not _sounds_conservative(retry_text):
                # replaces the streamed draft; "final" is authoritative and
                # incremental consumers re-render from it
                text = retry_text
                state.debug["synthesis_retry"] = "overcame_conservative_answer"
            else:
                state.debug["synthesis_issue"] = "LLM_overly_conservative"

        state.answer = text
        state.sources = sources
        state.debug.update(
            final_ctx_blocks=len(blocks),
            sources_count=len(sources),
            final_scope=state.scope,
            question_type="overview" if overview else "specific",
            answer_length=len(text),
        )
        state.breadcrumb(
            "synthesize", final_ctx_blocks=len(blocks), sources_count=len(sources),
            answer_length=len(text), synthesis_issue=state.debug.get("synthesis_issue"),
        )

    def _complete(self, prompt: str, token_cb: Callable[[str], None] | None) -> str:
        """One completion, streamed into ``token_cb`` when given — real
        token streaming into the job event path (the reference promised
        this and faked it: qwen_llm.py:149-151 returns the whole completion
        as one "stream" chunk)."""
        if token_cb is None:
            return self.llm.complete(prompt)
        from githubrepostorag_tpu.llm import postprocess_completion

        pieces: list[str] = []
        for delta in self.llm.stream_complete(prompt):
            pieces.append(delta)
            if token_cb is not None:
                try:
                    token_cb(delta)
                except Exception:  # noqa: BLE001 - streaming must not kill the run
                    token_cb = None
        # same post-processing as the non-streamed path, so the stored
        # answer is identical whether or not a consumer streamed it
        return postprocess_completion(prompt, "".join(pieces))

    def synthesize_longctx(
        self, state: AgentState, token_cb: Callable[[str], None] | None = None
    ) -> bool:
        """Whole-repo answer: assemble the planned repo's chunks into one
        ordered document (retrieval/assembler.py) and synthesize from ALL
        of it in a single completion — served as one long prompt, which the
        engine runs through segment-packed ring prefill past
        SP_PREFILL_THRESHOLD.  Returns False (after resetting the mode and
        leaving a fallback breadcrumb) when the repo has no chunks or blows
        the token budget; the caller rejoins the normal RAG loop."""
        from githubrepostorag_tpu.retrieval import assemble_repo, longctx_token_budget

        repo = state.filters.get("repo", "")
        budget = longctx_token_budget()
        try:
            asm = assemble_repo(
                self.retrievers.store, repo,
                namespace=state.filters.get("namespace"), token_budget=budget,
            )
        except Exception as exc:  # noqa: BLE001 - mode is an optimization
            logger.warning("assemble_repo(%s) failed: %s", repo, exc)
            asm = None
        if asm is None or asm.truncated:
            state.mode = "rag"
            state.breadcrumb(
                "longctx_fallback",
                reason="no_chunks" if asm is None else "over_budget",
                repo=repo, budget=budget,
                token_estimate=asm.token_estimate if asm else 0,
            )
            return False

        state.breadcrumb(
            "assemble", repo=repo, files=asm.files, chunks=asm.chunks,
            token_estimate=asm.token_estimate,
        )
        text = self._complete(
            prompts.longctx_synthesis_prompt(state.original_query, repo, asm.text),
            token_cb,
        )
        state.answer = text
        state.sources = [
            {
                "id": 1,
                "doc_id": f"repo:{repo}",
                "repo": repo,
                "module": "",
                "file_path": "",
                "scope": "repo",
                "score": 1.0,
                "text": truncate(
                    f"whole repository: {asm.files} files, {asm.chunks} chunks",
                    SOURCE_TEXT_BUDGET,
                ),
            }
        ]
        state.debug.update(
            mode="longctx", longctx_files=asm.files, longctx_chunks=asm.chunks,
            longctx_tokens=asm.token_estimate, final_scope="repo",
            sources_count=1, answer_length=len(text),
        )
        state.breadcrumb(
            "synthesize", mode="longctx", files=asm.files,
            token_estimate=asm.token_estimate, answer_length=len(text),
        )
        return True

    # ------------------------------------------------------------- driver

    def run(
        self,
        question: str,
        namespace: str | None = None,
        progress_cb: ProgressCallback | None = None,
        force_level: str | None = None,
        should_stop: Callable[[], bool] | None = None,
        token_cb: Callable[[str], None] | None = None,
        top_k: int | None = None,
        deadline: Deadline | None = None,
        trace: "TraceContext | None" = None,
    ) -> AgentResult:
        state = AgentState(query=question, original_query=question,
                           progress_cb=progress_cb, top_k=top_k)
        if namespace or self.namespace:
            state.filters["namespace"] = namespace or self.namespace

        def check_cancel() -> None:
            if should_stop is not None and should_stop():
                raise RunCancelled()
            if deadline is not None and deadline.expired:
                raise DeadlineExceeded("agent budget exhausted at a stage boundary")

        # the deadline rides a thread-local scope for the duration of the
        # run so every llm.complete inside any stage sees the SAME budget
        # without widening the LLM protocol signature; the trace context
        # rides a contextvar scope the same way (run executes on an
        # executor thread, which inherits neither — both cross explicitly)
        with deadline_scope(deadline), trace_scope(trace):
            with span("agent.run") as run_sp:
                check_cancel()
                # force_level honored (the reference read it but ignored it —
                # worker.py:101-107, SURVEY.md Appendix A) and skips the plan LLM call
                with span("agent.plan"):
                    self.plan_scope(state, force_level=force_level)

                if state.mode == "longctx":
                    check_cancel()
                    with span("agent.longctx"):
                        served = self.synthesize_longctx(state, token_cb=token_cb)
                    if served:
                        run_sp.set_attr("sources", len(state.sources))
                        return AgentResult(
                            answer=state.answer or "",
                            sources=state.sources, debug=state.debug,
                        )
                    # fell back (no chunks / over budget): the normal
                    # loop below runs with the planned scope untouched

                while True:
                    check_cancel()
                    with span("agent.retrieve", scope=state.scope or ""):
                        self.retrieve(state)
                    check_cancel()
                    with span("agent.judge"):
                        self.judge(state)
                    check_cancel()  # rewrite pays an LLM call; don't start it cancelled
                    with span("agent.rewrite"):
                        decision = self.rewrite_or_end(state)
                    if decision == "synthesize":
                        break
                check_cancel()
                with span("agent.synthesize"):
                    self.synthesize(state, token_cb=token_cb)
                run_sp.set_attr("sources", len(state.sources))
        return AgentResult(answer=state.answer or "", sources=state.sources, debug=state.debug)

    # ------------------------------------------------------------ helpers

    def _expand_query(self, query: str, repo: str | None, scope: str | None) -> list[str]:
        raw = self.llm.complete(prompts.expansion_prompt(query, repo, scope))
        data = extract_json(raw, default=None)
        if isinstance(data, list):
            out = [q.strip() for q in data if isinstance(q, str) and q.strip()]
            if out:
                return out[:4]
        # keyword fallback (agent_graph.py:137-150)
        ql = query.lower()
        fallbacks: list[str] = []
        if "auth" in ql or "login" in ql:
            fallbacks += ["authentication mechanism", "security configuration"]
        if "cache" in ql or "caching" in ql:
            fallbacks += ["caching strategy", "cache configuration"]
        if "config" in ql:
            fallbacks += ["application settings", "environment configuration"]
        return fallbacks[:3] if fallbacks else [query]

    @staticmethod
    def _merge_filters(filters: dict[str, str], suggested: Any) -> None:
        """Accept string or single-element-list values.  LLMs sometimes
        pluralize keys ("repos": [...]) — depluralize only when that maps
        onto a canonical metadata key, never mangle canonical keys that
        already end in 's' (like "topics")."""
        canonical = {"namespace", "repo", "module", "file_path", "topics", "scope"}
        if not isinstance(suggested, dict):
            return
        for key, val in suggested.items():
            if key not in canonical and key.endswith("s") and key[:-1] in canonical:
                key = key[:-1]
            if key not in canonical:
                # an unknown key would become an exact-match filter no stored
                # doc can satisfy, zeroing every later retrieval
                continue
            if isinstance(val, str) and val:
                filters[key] = val
            elif isinstance(val, list) and val and isinstance(val[0], str):
                filters[key] = val[0]


def _sounds_conservative(text: str) -> bool:
    tl = text.lower()
    return any(phrase in tl for phrase in _CONSERVATIVE_PHRASES)


def _as_float(value: Any) -> float:
    try:
        return float(value)
    except (TypeError, ValueError):
        return 0.0
