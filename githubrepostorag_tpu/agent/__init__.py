"""L3: the agentic query engine — plan -> retrieve -> judge -> rewrite ->
synthesize, rebuilt from the reference's LangGraph agent
(rag_worker/src/worker/services/agent_graph.py) as a plain state machine."""

from githubrepostorag_tpu.agent.graph import AgentResult, GraphAgent, RunCancelled
from githubrepostorag_tpu.agent.state import AgentState

__all__ = ["GraphAgent", "AgentResult", "AgentState", "RunCancelled"]
