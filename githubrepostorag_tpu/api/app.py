"""L5: the REST control plane (aiohttp).

Same route surface as the reference's FastAPI app (rest_api/src/app/):
  POST /rag/jobs                  -> {"job_id": ...} (uuid4 hex, enqueued)
  GET  /rag/jobs/{id}/events      -> SSE stream from the progress bus
  POST /rag/jobs/{id}/cancel      -> sets the cooperative cancel flag
  GET  /rag/jobs/{id}/result      -> kept result (keep_result window)
  GET  /health                    -> deep aggregate (503 when DOWN)
  GET  /metrics                   -> Prometheus exposition
  /static/index.html              -> chat UI
with CORS and the per-request count/latency middleware
(rest_api main.py:43-57).
"""

from __future__ import annotations

import concurrent.futures
import time
import uuid
from pathlib import Path

from aiohttp import web

from githubrepostorag_tpu.config import get_settings
from githubrepostorag_tpu.events.base import CancelFlags, JobQueue, ProgressBus
from githubrepostorag_tpu.metrics import HTTP_LATENCY, HTTP_REQUESTS, JOBS_SHED, render
from githubrepostorag_tpu.models_dto import QueryRequest
from githubrepostorag_tpu.obs import current_context, get_recorder, root_span
from githubrepostorag_tpu.resilience.policy import Deadline
from githubrepostorag_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_STATIC_DIR = Path(__file__).resolve().parent / "static"

# health probes get their own pool: the default executor is shared with
# agent jobs (worker.py run_in_executor), so a busy pod would otherwise
# queue liveness probes behind minutes of RAG work and get itself killed
_HEALTH_POOL = concurrent.futures.ThreadPoolExecutor(
    max_workers=2, thread_name_prefix="health-probe"
)


@web.middleware
async def _metrics_middleware(request: web.Request, handler):
    start = time.monotonic()
    status = 500
    try:
        response = await handler(request)
        status = response.status
        return response
    except web.HTTPException as exc:
        status = exc.status
        raise
    finally:
        resource = request.match_info.route.resource if request.match_info.route else None
        # unmatched routes (404 scans) must not mint unbounded label values
        path = resource.canonical if resource else "unmatched"
        HTTP_REQUESTS.labels(request.method, path, str(status)).inc()
        HTTP_LATENCY.labels(request.method, path).observe(time.monotonic() - start)


@web.middleware
async def _trace_middleware(request: web.Request, handler):
    """Root span per /rag request (the job-facing surface; scrape and
    debug endpoints would just fill the recorder ring with noise).  An
    incoming ``traceparent`` header is continued, so an upstream gateway's
    trace connects straight through to engine decode spans."""
    if not request.path.startswith("/rag"):
        return await handler(request)
    resource = request.match_info.route.resource if request.match_info.route else None
    route = resource.canonical if resource else "unmatched"
    with root_span(f"http {request.method} {route}",
                   wire=request.headers.get("traceparent")) as sp:
        try:
            response = await handler(request)
        except web.HTTPException as exc:
            if exc.status >= 500:
                sp.set_status(f"error: http {exc.status}")
            raise
        if response.status >= 500:
            sp.set_status(f"error: http {response.status}")
        sp.set_attr("status", response.status)
        return response


@web.middleware
async def _cors_middleware(request: web.Request, handler):
    if request.method == "OPTIONS":
        response = web.Response(status=204)
    else:
        response = await handler(request)
    response.headers["Access-Control-Allow-Origin"] = "*"
    response.headers["Access-Control-Allow-Methods"] = "GET, POST, OPTIONS"
    response.headers["Access-Control-Allow-Headers"] = "Content-Type"
    return response


class RagApi:
    def __init__(self, bus: ProgressBus, flags: CancelFlags, queue: JobQueue) -> None:
        self.bus = bus
        self.flags = flags
        self.queue = queue
        self._runner: web.AppRunner | None = None

    def make_app(self) -> web.Application:
        app = web.Application(
            middlewares=[_cors_middleware, _metrics_middleware, _trace_middleware]
        )
        app.router.add_post("/rag/jobs", self.create_job)
        app.router.add_get("/rag/jobs/{job_id}/events", self.job_events)
        app.router.add_post("/rag/jobs/{job_id}/cancel", self.cancel_job)
        app.router.add_get("/rag/jobs/{job_id}/result", self.job_result)
        app.router.add_get("/debug/traces", self.debug_traces)
        app.router.add_get("/debug/traces/{trace_id}", self.debug_trace)
        app.router.add_get("/debug/slo", self.debug_slo)
        app.router.add_get("/debug/fleet", self.debug_fleet)
        app.router.add_get("/debug/index", self.debug_index)
        app.router.add_get("/debug/hbm", self.debug_hbm)
        app.router.add_get("/debug/timeline", self.debug_timeline)
        app.router.add_get("/health", self.health)
        app.router.add_get("/metrics", self.metrics)
        app.router.add_get("/", self.index_redirect)
        if _STATIC_DIR.is_dir():
            app.router.add_static("/static/", _STATIC_DIR)
        return app

    async def start(self, host: str = "0.0.0.0", port: int = 8080) -> int:
        # import now so the health module's uptime clock starts with the
        # server, not with the first probe request
        from githubrepostorag_tpu.api import health  # noqa: F401

        self._runner = web.AppRunner(self.make_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        bound = site._server.sockets[0].getsockname()[1]  # noqa: SLF001
        logger.info("RAG API on %s:%d", host, bound)
        return bound

    async def stop(self) -> None:
        # capture-and-clear before awaiting: two concurrent stop() calls must
        # not both see the runner and double-cleanup it
        runner, self._runner = self._runner, None
        if runner is not None:
            await runner.cleanup()

    # ------------------------------------------------------------ handlers

    async def create_job(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            req = QueryRequest(**body)
        except Exception as exc:  # noqa: BLE001
            return web.json_response({"error": f"invalid request: {exc}"}, status=400)
        s = get_settings()
        if req.deadline_ms is not None and (
            isinstance(req.deadline_ms, bool) or req.deadline_ms <= 0
        ):
            return web.json_response(
                {"error": "deadline_ms must be a positive integer"}, status=400
            )
        # backpressure: shed before enqueueing once the queue is saturated,
        # so the client backs off instead of the backlog growing unbounded
        try:
            depth = await self.queue.depth()
        except Exception:  # noqa: BLE001 - a flaky depth probe must not block intake
            depth = 0
        if depth >= s.job_queue_max_depth:
            JOBS_SHED.inc()
            retry_after = max(1, int(s.job_timeout_seconds // 10))
            return web.json_response(
                {"error": f"job queue full ({depth} queued); retry later"},
                status=429,
                headers={"Retry-After": str(retry_after)},
            )
        # SLO-plane admission decision, per priority class: a critical burn
        # rate sheds BEFORE the queue saturates — rejecting now is cheaper
        # than timing out later (the burn only worsens if the backlog keeps
        # growing).  Non-shed rungs (throttle/preempt) still admit here;
        # the engine applies them where the pages are.
        from githubrepostorag_tpu.resilience.admission import should_shed

        if should_shed(req.priority or s.priority_default_class):
            JOBS_SHED.inc()
            return web.json_response(
                {"error": "SLO burn rate critical; shedding load, retry later"},
                status=429,
                headers={"Retry-After": "1"},
            )
        job_id = uuid.uuid4().hex
        cap_ms = s.job_timeout_seconds * 1000
        budget_ms = min(req.deadline_ms or cap_ms, cap_ms)
        # the trace context (opened by _trace_middleware) crosses the queue
        # on the envelope next to the deadline; the worker continues it
        ctx = current_context()
        await self.queue.enqueue_job(
            "run_rag_job",
            job_id,
            req.model_dump(),
            _job_id=job_id,
            deadline=Deadline(budget_ms / 1000.0).to_wire(),
            trace=ctx.to_wire() if ctx is not None and ctx.sampled else None,
        )
        body = {"job_id": job_id}
        if ctx is not None and ctx.sampled:
            body["trace_id"] = ctx.trace_id
        return web.json_response(body)

    async def job_events(self, request: web.Request) -> web.StreamResponse:
        job_id = request.match_info["job_id"]
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            },
        )
        await resp.prepare(request)
        import asyncio
        import json as _json

        heartbeat = get_settings().sse_heartbeat_seconds
        it = self.bus.stream(job_id).__aiter__()
        # the pending __anext__ must survive heartbeat timeouts: wait_for
        # would cancel it, and cancelling an async generator's __anext__
        # kills the generator mid-await
        pending: asyncio.Task | None = None
        try:
            while True:
                if pending is None:
                    pending = asyncio.ensure_future(it.__anext__())
                done, _ = await asyncio.wait({pending}, timeout=heartbeat)
                if not done:
                    # comment frame: keeps proxies/LBs from idling the
                    # connection out while the agent thinks
                    await resp.write(b": heartbeat\n\n")
                    continue
                step, pending = pending, None
                try:
                    frame = step.result()
                except StopAsyncIteration:
                    break
                except (ConnectionError, OSError):
                    raise
                except Exception as exc:  # noqa: BLE001 - bus died mid-stream
                    logger.exception("bus stream failed for %s", job_id)
                    err = _json.dumps(
                        {"event": "error", "data": {"error": f"event stream failed: {exc}"}}
                    )
                    await resp.write(f"data: {err}\n\n".encode())
                    break
                await resp.write(frame.encode())
                # close the stream after the terminal event so EventSource
                # clients do not reconnect forever
                if '"event": "final"' in frame or '"event": "error"' in frame:
                    break
        except (ConnectionError, OSError):
            pass  # client went away; nothing left to tell it
        finally:
            if pending is not None:
                pending.cancel()
            aclose = getattr(it, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:  # noqa: BLE001
                    pass
        return resp

    async def cancel_job(self, request: web.Request) -> web.Response:
        job_id = request.match_info["job_id"]
        await self.flags.cancel(job_id)
        return web.json_response({"job_id": job_id, "cancelled": True})

    async def job_result(self, request: web.Request) -> web.Response:
        job_id = request.match_info["job_id"]
        result = await self.queue.get_result(job_id)
        if result is None:
            return web.json_response({"error": "no result (pending, expired, or unknown)"}, status=404)
        return web.json_response(result)

    async def debug_traces(self, request: web.Request) -> web.Response:
        return web.json_response(get_recorder().summaries_payload())

    async def debug_trace(self, request: web.Request) -> web.Response:
        payload = get_recorder().trace_payload(request.match_info["trace_id"])
        if payload is None:
            return web.json_response({"error": "unknown trace (evicted or never recorded)"},
                                     status=404)
        return web.json_response(payload)

    async def debug_slo(self, request: web.Request) -> web.Response:
        from githubrepostorag_tpu.obs.slo import get_slo_plane

        return web.json_response(get_slo_plane().slo_payload())

    async def debug_fleet(self, request: web.Request) -> web.Response:
        from githubrepostorag_tpu.obs.slo import get_slo_plane

        return web.json_response(get_slo_plane().fleet_payload())

    async def debug_index(self, request: web.Request) -> web.Response:
        from githubrepostorag_tpu.retrieval.live_index import live_index_payload

        return web.json_response(live_index_payload())

    async def debug_hbm(self, request: web.Request) -> web.Response:
        from githubrepostorag_tpu.obs.hbm import get_hbm_plane

        return web.json_response(get_hbm_plane().payload())

    async def debug_timeline(self, request: web.Request) -> web.Response:
        """One Perfetto trace for the recent past (?window_s= bounds it);
        save the body and open it in ui.perfetto.dev."""
        from githubrepostorag_tpu.obs.timeline import build_timeline

        try:
            window_s = float(request.query["window_s"]) \
                if "window_s" in request.query else None
        except ValueError:
            return web.json_response(
                {"error": "window_s must be a number"}, status=400)
        return web.json_response(build_timeline(window_s=window_s))

    async def health(self, request: web.Request) -> web.Response:
        import asyncio

        from githubrepostorag_tpu.api.health import health_report

        # queue depth is async-only (RESP round trip); resolve it here and
        # hand the value to the sync report
        try:
            queue_depth = await self.queue.depth()
        except Exception:  # noqa: BLE001
            queue_depth = None
        # health probes do blocking I/O (HTTP to the LLM backend, store
        # connectivity); keep them off the event loop so SSE streams and
        # enqueues never stall behind a slow probe
        payload, status = await asyncio.get_running_loop().run_in_executor(
            _HEALTH_POOL, lambda: health_report(queue_depth=queue_depth)
        )
        return web.json_response(payload, status=status)

    async def metrics(self, request: web.Request) -> web.Response:
        return web.Response(body=render(), content_type="text/plain")

    async def index_redirect(self, request: web.Request) -> web.Response:
        raise web.HTTPFound("/static/index.html")


def build_app(bus=None, flags=None, queue=None) -> RagApi:
    """Default wiring: in-memory bus/flags/queue for single-pod mode; pass
    Redis implementations for split deployments."""
    from githubrepostorag_tpu.events import MemoryBus, MemoryCancelFlags, MemoryJobQueue

    return RagApi(
        bus=bus or MemoryBus(),
        flags=flags or MemoryCancelFlags(),
        queue=queue or MemoryJobQueue(),
    )
