"""Deep /health probe, Spring-Actuator-shaped.

Rebuild of rest_api/src/app/health.py:22-142: aggregate UP/DOWN with
components for the vector store (connectivity + index presence), the LLM
backend, and system stats (psutil cpu/mem/disk + uptime); HTTP 503 when any
required component is DOWN.
"""

from __future__ import annotations

import time

from githubrepostorag_tpu.config import get_settings
from githubrepostorag_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_START_TIME = time.monotonic()


def format_uptime(seconds: float) -> str:
    seconds = int(seconds)
    days, rem = divmod(seconds, 86400)
    hours, rem = divmod(rem, 3600)
    minutes, secs = divmod(rem, 60)
    parts = []
    if days:
        parts.append(f"{days}d")
    if hours or days:
        parts.append(f"{hours}h")
    if minutes or hours or days:
        parts.append(f"{minutes}m")
    parts.append(f"{secs}s")
    return " ".join(parts)


def _store_component() -> dict:
    try:
        from githubrepostorag_tpu.store import get_store

        health = get_store().health()
        tables = health.get("tables", {})
        chunk_table = get_settings().embeddings_table_chunk
        indexed = tables.get(chunk_table, 0)
        detail = {
            "status": health.get("status", "DOWN"),
            "details": {
                "backend": get_settings().store_backend,
                "tables": tables,
                "vector_index": "ready" if indexed else "empty",
            },
        }
        return detail
    except Exception as exc:  # noqa: BLE001
        return {"status": "DOWN", "details": {"error": str(exc)}}


def _llm_component() -> dict:
    s = get_settings()
    backend = s.llm_backend.lower()
    try:
        if backend == "http":
            import requests

            resp = requests.get(f"{s.qwen_endpoint.rstrip('/')}/health", timeout=5)
            ok = resp.status_code == 200
            return {
                "status": "UP" if ok else "DOWN",
                "details": {"backend": "http", "endpoint": s.qwen_endpoint,
                            "http_status": resp.status_code},
            }
        if backend == "fake":
            return {"status": "UP", "details": {"backend": "fake"}}
        # inprocess: report engine stats when one is wired
        from githubrepostorag_tpu.llm import _llm  # noqa: PLC0415

        details: dict = {"backend": "inprocess"}
        engine = getattr(_llm, "engine", None)
        if engine is not None:
            details.update(engine.stats())
        return {"status": "UP", "details": details}
    except Exception as exc:  # noqa: BLE001
        return {"status": "DOWN", "details": {"backend": backend, "error": str(exc)}}


def _system_component() -> dict:
    try:
        import psutil

        vm = psutil.virtual_memory()
        disk = psutil.disk_usage("/")
        return {
            "status": "UP",
            "details": {
                "cpu_percent": psutil.cpu_percent(interval=None),
                "memory_percent": vm.percent,
                "disk_percent": disk.percent,
                "uptime": format_uptime(time.monotonic() - _START_TIME),
            },
        }
    except Exception as exc:  # noqa: BLE001
        return {"status": "UP", "details": {"error": str(exc)}}


def _resilience_component(queue_depth: int | None) -> dict:
    """Breaker states, queue depth, and in-flight jobs.  DOWN while any
    circuit is open: the pod is refusing work on that dependency, so load
    balancers should steer traffic elsewhere until the breaker half-opens."""
    try:
        from githubrepostorag_tpu.metrics import JOBS_IN_FLIGHT, counter_value
        from githubrepostorag_tpu.resilience.policy import breaker_states

        breakers = breaker_states()
        any_open = any(b["state"] == "open" for b in breakers.values())
        details: dict = {
            "breakers": breakers,
            "jobs_in_flight": int(counter_value(JOBS_IN_FLIGHT)),
        }
        if queue_depth is not None:
            details["queue_depth"] = queue_depth
        return {"status": "DOWN" if any_open else "UP", "details": details}
    except Exception as exc:  # noqa: BLE001
        return {"status": "UP", "details": {"error": str(exc)}}


def health_report(queue_depth: int | None = None) -> tuple[dict, int]:
    """-> (payload, http_status).  503 when store, LLM, or resilience (an
    open circuit breaker) is DOWN."""
    components = {
        "vectorStore": _store_component(),
        "llm": _llm_component(),
        "system": _system_component(),
        "resilience": _resilience_component(queue_depth),
    }
    required = ("vectorStore", "llm", "resilience")
    overall = "UP" if all(components[c]["status"] == "UP" for c in required) else "DOWN"
    return {"status": overall, "components": components}, (200 if overall == "UP" else 503)
