"""Run the RAG service: ``python -m githubrepostorag_tpu.api``.

Single-pod mode (default): API + worker + agent share one process over the
in-memory bus, the configured store, and the configured LLM backend
(LLM_BACKEND=fake for smoke tests; =http against a separate model server;
=inprocess with MODEL_WEIGHTS_PATH for the full TPU stack).  With
REDIS_URL set and --redis, the bus/queue ride the in-tree RESP client so
separate API and worker pods interoperate like the reference deployment.
"""

from __future__ import annotations

import argparse
import asyncio

from githubrepostorag_tpu.config import get_settings
from githubrepostorag_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _build_llm():
    s = get_settings()
    backend = s.llm_backend.lower()
    if backend == "inprocess":
        import jax

        from githubrepostorag_tpu.llm import InProcessLLM
        from githubrepostorag_tpu.models.hf_loader import load_qwen2
        from githubrepostorag_tpu.serving import Engine
        from githubrepostorag_tpu.serving.async_engine import AsyncEngine
        from githubrepostorag_tpu.serving.tokenizer import make_tokenizer

        if not s.model_weights_path:
            raise SystemExit("LLM_BACKEND=inprocess requires MODEL_WEIGHTS_PATH")
        import ml_dtypes

        params, cfg = load_qwen2(
            s.model_weights_path, dtype=ml_dtypes.bfloat16, quantize=s.quantize_weights,
            moe_capacity_factor=s.moe_capacity_factor,
        )
        engine = Engine(
            params, cfg,
            max_num_seqs=s.max_num_seqs,
            num_pages=s.kv_num_pages,
            page_size=s.kv_page_size,
            max_seq_len=s.context_window,
            prefill_chunk=s.prefill_chunk,
            prefill_widths=s.prefill_widths,
            kv_quant=s.kv_quant,
            use_pallas=jax.default_backend() == "tpu",
            preempt=s.preempt,
            preempt_headroom_pages=s.preempt_headroom_pages,
            default_priority=s.priority_default_class,
            protected_priority=s.priority_protected_class,
        )
        return InProcessLLM(AsyncEngine(engine), make_tokenizer(s.model_weights_path))
    from githubrepostorag_tpu.llm import get_llm

    return get_llm()


async def serve(host: str, port: int, use_redis: bool, run_worker: bool = True) -> None:
    from githubrepostorag_tpu.api.app import RagApi

    if use_redis:
        from githubrepostorag_tpu.events.redis import RedisBus, RedisCancelFlags, RedisJobQueue

        bus, flags, queue = RedisBus(), RedisCancelFlags(), RedisJobQueue()
    else:
        from githubrepostorag_tpu.events import MemoryBus, MemoryCancelFlags, MemoryJobQueue

        bus, flags, queue = MemoryBus(), MemoryCancelFlags(), MemoryJobQueue()

    api = RagApi(bus, flags, queue)
    await api.start(host=host, port=port)
    logger.info("service up — UI at http://%s:%d/static/index.html", host, port)

    if not run_worker:
        # split deployment (rag-api pod): jobs are consumed by a separate
        # ``python -m githubrepostorag_tpu.worker`` pod over Redis, like the
        # reference's rag-api / rag-worker pair
        while True:
            await asyncio.sleep(3600)

    from githubrepostorag_tpu.agent import GraphAgent
    from githubrepostorag_tpu.llm import set_llm
    from githubrepostorag_tpu.metrics import MeteredLLM
    from githubrepostorag_tpu.worker import RagWorker

    raw_llm = _build_llm()
    set_llm(raw_llm)  # health.py probes the shared instance for engine stats
    agent = GraphAgent(MeteredLLM(raw_llm))
    worker = RagWorker(agent, bus, flags, queue)
    await worker.run_forever()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description="Run the RAG API + worker")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--redis", action="store_true",
                        help="use Redis (REDIS_URL) for bus/queue instead of in-memory")
    parser.add_argument("--no-worker", action="store_true",
                        help="API only; a separate `python -m githubrepostorag_tpu.worker` "
                             "pod consumes the queue (requires --redis)")
    args = parser.parse_args(argv)
    if args.no_worker and not args.redis:
        parser.error("--no-worker requires --redis (the queue must be shared)")
    asyncio.run(serve(args.host, args.port, args.redis, run_worker=not args.no_worker))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
