from githubrepostorag_tpu.api.app import RagApi, build_app

__all__ = ["RagApi", "build_app"]
