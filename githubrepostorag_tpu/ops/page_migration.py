"""Batched device<->host KV page migration dispatches.

Two programs per migration-burst bucket: ``gather_pages`` reads a burst of
pages out of the pools into a fresh contiguous buffer (the engine starts
its host DMA with ``copy_to_host_async`` and reads it ONE step later, so
the driver thread never blocks on the transfer), and ``scatter_pages``
writes a burst of host payloads back into the pools (donated — XLA updates
the pools in place, same commit economics as the prefill/decode scatters).

Both take a fixed-width ``[nb]`` page-index vector padded with -1 so the
compiled-shape zoo is exactly the power-of-two bucket ladder warmup
precompiles: gather clamps padding to page 0 (the rows are discarded
host-side), scatter drops padding via out-of-bounds semantics.  Quantized
(int8) pools migrate their per-page scales alongside the payload in the
same program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def migrate_buckets(burst: int) -> list[int]:
    """Power-of-two bucket ladder for migration burst sizes, capped at the
    configured burst: the complete set of compiled shapes warmup builds."""
    out: set[int] = set()
    b = 1
    while b < burst:
        out.add(b)
        b *= 2
    out.add(max(1, burst))
    return sorted(out)


def split_page_payloads(bufs, n: int) -> list[tuple]:
    """Slice a landed gather burst into per-page payload tuples.

    ``bufs`` is the ``(k, v, ks, vs, dk, dv)`` buffer tuple a
    ``gather_pages`` burst produced (host-readable; any member may be
    None) and ``n`` the number of real pages in it.  Each payload copies
    its ``[:, :, i]`` slice out of the burst buffer — a view would pin
    the whole burst in host RAM for as long as any one page stays cached.
    The tuple layout is THE page-payload wire format: writeback landing
    (``complete_writeback``), fault-in dispatch, and the disagg
    export/import transport all speak it, so the three paths can never
    drift."""
    import numpy as np

    host = [None if a is None else np.asarray(a) for a in bufs]
    return [
        tuple(None if a is None else a[:, :, i].copy() for a in host)
        for i in range(n)
    ]


@jax.jit
def gather_pages(
    k_pages: jnp.ndarray,  # [L, n_kv, P, ps, hd]
    v_pages: jnp.ndarray,
    idx: jnp.ndarray,  # [nb] int32 page indices, -1 padding
    k_scales: jnp.ndarray | None = None,  # [L, n_kv, P] f32 (int8 pools)
    v_scales: jnp.ndarray | None = None,
):
    """Read a migration burst into fresh [L, n_kv, nb, ps, hd] buffers.

    NOT donated: the pools stay live (device->host is a residency copy,
    not a release).  Padding indices clamp to page 0 — the engine only
    consumes the first ``len(plan)`` rows of the result."""
    safe = jnp.maximum(idx, 0)
    k = jnp.take(k_pages, safe, axis=2)
    v = jnp.take(v_pages, safe, axis=2)
    ks = None if k_scales is None else jnp.take(k_scales, safe, axis=2)
    vs = None if v_scales is None else jnp.take(v_scales, safe, axis=2)
    return k, v, ks, vs


@partial(jax.jit, donate_argnums=(0, 1, 4, 5))
def scatter_pages(
    k_pages: jnp.ndarray,  # [L, n_kv, P, ps, hd] donated
    v_pages: jnp.ndarray,  # donated
    idx: jnp.ndarray,  # [nb] int32 page indices, -1 padding
    k_vals: jnp.ndarray,  # [L, n_kv, nb, ps, hd] host payloads
    k_scales: jnp.ndarray | None = None,  # [L, n_kv, P] f32, donated
    v_scales: jnp.ndarray | None = None,  # donated
    v_vals: jnp.ndarray | None = None,  # split from k_vals' position so the
    # donated args stay at fixed argnums; always passed by the engine
    ks_vals: jnp.ndarray | None = None,  # [L, n_kv, nb] f32
    vs_vals: jnp.ndarray | None = None,
):
    """Write a fault-in burst into the pools at ``idx`` (padding drops via
    out-of-bounds scatter semantics, the pools are donated so XLA commits
    in place).  Returns (k_pages, v_pages, k_scales, v_scales)."""
    # -1 padding must be remapped to an index that is ACTUALLY out of
    # bounds: jnp normalizes negative indices (-1 -> P-1) before the
    # mode="drop" check, which would overwrite the pool's last page with
    # the padding rows' zeros on every non-full burst
    safe = jnp.where(idx < 0, k_pages.shape[2], idx)
    k_pages = k_pages.at[:, :, safe].set(k_vals, mode="drop")
    v_pages = v_pages.at[:, :, safe].set(v_vals, mode="drop")
    if k_scales is not None:
        k_scales = k_scales.at[:, :, safe].set(ks_vals, mode="drop")
        v_scales = v_scales.at[:, :, safe].set(vs_vals, mode="drop")
    return k_pages, v_pages, k_scales, v_scales
