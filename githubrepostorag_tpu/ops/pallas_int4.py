"""Pallas TPU int4 weight-only GEMM: in-VMEM dequant fused into the dot.

Why a kernel: XLA does not fuse the int4 unpack chain (nibble shift ->
group reshape -> scale multiply -> concat) into a dot operand the way it
fuses int8's convert+scale — device traces of the 7B int4 decode burst
show it materializing reshaped/scaled copies at ~37 ms/step of reshapes
plus ~26 ms/step of copies, making int4 3-6x SLOWER than int8.  Here the
packed tile is DMA'd to VMEM (half the int8 bytes off HBM — the entire
point of int4), unpacked and dequantized in VMEM, and fed straight to the
MXU.

Weights are the in-group plane-packed ``QuantizedLinear4`` layout
(models/quant.py): byte row j of group g holds original rows (g*gsz + j)
in the low nibble and (g*gsz + j + gsz/2) in the high nibble, so an
input-tile that is a whole number of groups unpacks with one in-VMEM
concat and its scale rows align exactly.

Stacked [L, in/2, out] weights ride in WHOLE with the layer index as a
prefetched scalar (same discipline as the rank-5 KV pools in
pallas_paged.py): the burst's layer loop never dynamic-slices a weight
into a materialized copy.

Oracle: models/quant.py::q4_matmul (the two-dot XLA formulation) — exact
same math, used on CPU and in interpret-mode tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# JAX renamed TPUCompilerParams -> CompilerParams; support both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def _pick_tile(total: int, unit: int, target: int) -> int:
    """Largest multiple of ``unit`` that divides ``total``, is <= target,
    AND keeps the TPU lane constraint (multiple of 128, unless it is the
    whole dimension — Pallas requires block minor dims be 128-aligned or
    full).  Falls back to ``total``."""
    import math

    step = math.lcm(unit, 128)
    t = (target // step) * step
    while t >= step:
        if total % t == 0:
            return t
        t -= step
    return total


def _int4_kernel(*refs, half: int, n_gt: int, layered: bool, sliced: bool):
    ii = pl.program_id(2)
    n_ii = pl.num_programs(2)
    # the scale blocks carry the FULL group axis (their shape must be
    # 8/128-aligned or full); this in-tile's rows slice out at the REF.
    # ``sliced`` is static: with one in-tile the whole block is the tile
    # (and Mosaic needs no provably-8-aligned dynamic sublane offset —
    # the wrapper guarantees n_gt % 8 == 0 whenever sliced)
    if layered:
        (_li_ref, xa_ref, xb_ref, q_ref, s_ref, zs_ref, out_ref, acc_ref) = refs
        pq = q_ref[0]  # [IT/2, OT]
        s = s_ref[0, pl.ds(ii * n_gt, n_gt)] if sliced else s_ref[0]
        zs = zs_ref[0, pl.ds(ii * n_gt, n_gt)] if sliced else zs_ref[0]
    else:
        (xa_ref, xb_ref, q_ref, s_ref, zs_ref, out_ref, acc_ref) = refs
        pq = q_ref[...]
        s = s_ref[pl.ds(ii * n_gt, n_gt)] if sliced else s_ref[...]
        zs = zs_ref[pl.ds(ii * n_gt, n_gt)] if sliced else zs_ref[...]

    @pl.when(ii == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ot = pq.shape[-1]
    dt = xa_ref.dtype  # bf16 serving; f32 in CPU-geometry tests
    # The unpack is VPU-bound (every weight element pays mask+cast+scale
    # while the MXU waits), so shave VPU work: no shift — the high nibble
    # stays in place (pq & 0xF0 = 16*nib) with 1/16 folded into its scale
    # — and no concat — the two nibble planes go to the MXU as TWO dots
    # against the matching halves of x (in-group plane packing makes both
    # planes contiguous row ranges).  Widening runs through int32: Mosaic
    # legalizes neither uint8 shifts nor uint8->bf16 casts.
    sdt = s.astype(dt)[:, None, :]
    zdt = zs.astype(dt)[:, None, :]
    # Unpack via int32 widening (Mosaic legalizes neither uint8 shifts nor
    # uint8->bf16 casts; an int8-domain bitcast variant measured ~12%
    # SLOWER — the convert path widens internally regardless).  No shift:
    # the high nibble stays in place (pq & 0xF0 = 16*nib) with 1/16 folded
    # into its scale.  Each plane's rows are distinct original rows, every
    # one dequantizing as nib*s - zs — both planes subtract the FULL zs.
    # The remaining cost is fundamental per-element convert throughput on
    # the VPU (the kernel is compute-bound, not HBM-bound, at 7B: ~2.9 GB
    # of int4 reads vs ~19 ms/step measured); the next step beyond this is
    # W4A8 — int8 activations on the MXU's native int8 path with per-group
    # int32 partial sums — which changes the accuracy contract.
    pq32 = pq.astype(jnp.int32)
    lo = (pq32 & 0x0F).astype(dt).reshape(n_gt, half, ot) * sdt - zdt
    hi = (pq32 & 0xF0).astype(dt).reshape(n_gt, half, ot) * (sdt / 16) - zdt
    # x arrives PRE-SPLIT into the two plane halves (wrapper-side — the
    # [MT, n_gt, gsz] lane slicing is an unsupported shape cast in Mosaic,
    # and activations are tiny for XLA to split)
    x_a = xa_ref[...]  # [MT, IT/2]
    x_b = xb_ref[...]
    dn = (((1,), (0,)), ((), ()))
    acc_ref[...] += jax.lax.dot_general(
        x_a, lo.reshape(n_gt * half, ot), dn, preferred_element_type=jnp.float32
    ) + jax.lax.dot_general(
        x_b, hi.reshape(n_gt * half, ot), dn, preferred_element_type=jnp.float32
    )

    @pl.when(ii == n_ii - 1)
    def _():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _w4a8_kernel(*refs, half: int, n_gt: int, layered: bool, sliced: bool):
    """W4A8 tile: nibbles->int8 on the VPU's cheap integer path, the dots
    on the MXU's NATIVE int8 path (int8 x int8 -> int32), one dot per
    weight GROUP so each int32 partial picks up its own group scale at
    f32 accumulation.  Versus the bf16-dequant kernel (_int4_kernel) the
    per-weight-element VPU work drops from mask+cast+scale+subtract in
    bf16 to mask/shift+int8-cast — the group-scale multiply runs on the
    [MT, OT] partial (1/gsz of the weight elements per group) and the
    zero-point term leaves the kernel entirely (wrapper-side XLA dot).

    Both nibble planes stack into ONE [gsz, OT] int8 operand per group
    (a VMEM scratch written with two static half-slices), so the group
    dot runs at the full K=gsz MXU depth: the in-group plane packing puts
    plane rows at original positions [g*gsz, g*gsz+half) and
    [g*gsz+half, (g+1)*gsz), i.e. stacked [lo; hi] IS group g's rows in
    natural order, matching the wrapper's group-major activations.  The
    earlier two-dots-per-group form (one per plane) halved MXU weight
    throughput: a K=half dot occupies the same systolic passes as K=gsz.

    Accuracy contract: activations are quantized per token row to
    symmetric int8 (the wrapper's x/amax*127), so results differ from the
    bf16-dequant math by the activation-quant error (~1e-2 relative) —
    gated by parity tests mirroring int8's (tests/test_quant4.py)."""
    ii = pl.program_id(2)
    n_ii = pl.num_programs(2)
    if layered:
        (_li_ref, x_ref, q_ref, s_ref, out_ref, acc_ref, w_ref) = refs
        pq = q_ref[0]  # [IT/2, OT] uint8
        s = s_ref[0, pl.ds(ii * n_gt, n_gt)] if sliced else s_ref[0]
    else:
        (x_ref, q_ref, s_ref, out_ref, acc_ref, w_ref) = refs
        pq = q_ref[...]
        s = s_ref[pl.ds(ii * n_gt, n_gt)] if sliced else s_ref[...]

    @pl.when(ii == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    s_f = s.astype(jnp.float32)  # [n_gt, OT]
    dn = (((1,), (0,)), ((), ()))
    for g in range(n_gt):  # static unroll: n_gt <= 16 by tile choice
        # unpack PER GROUP ([half, OT] at a time, static 32-row slices):
        # a whole-tile int32 widen materializes it/2 x OT x 4B of VMEM and
        # capped OT at ~1k for the big projections (259 grid steps for
        # wgu); group-at-a-time intermediates are ~100x smaller, so OT can
        # cover 4-9k columns and the grid shrinks ~10x.  int32 widen
        # because Mosaic legalizes neither uint8 shifts nor narrow casts.
        # (An explicitly double-buffered unpack/dot pipeline measured
        # NEUTRAL on-chip — Mosaic already schedules around the single
        # buffer's write-after-read hazard, so keep the simple form.)
        pq32 = pq[g * half : (g + 1) * half].astype(jnp.int32)
        w_ref[:half] = (pq32 & 0x0F).astype(jnp.int8)
        w_ref[half:] = (pq32 >> 4).astype(jnp.int8)
        p = jax.lax.dot_general(
            x_ref[g], w_ref[...], dn, preferred_element_type=jnp.int32
        )
        acc_ref[...] += p.astype(jnp.float32) * s_f[g][None, :]

    @pl.when(ii == n_ii - 1)
    def _():
        out_ref[...] = acc_ref[...]


def _tiles_and_maps(in_dim: int, out: int, gsz: int, n_g: int,
                    layered: bool, layer, wide_ot: bool = False):
    """Tile sizes + (q, s) block specs shared by both int4 routes: the
    in-tile is a multiple of 8 GROUPS (scale slice offsets must be provable
    sublane multiples; single in-tile when it falls back to the whole input
    dim), and stacked weights address (layer, tile) through the prefetched
    scalar so the layer loop never materializes a per-layer copy.
    ``wide_ot``: the W4A8 route unpacks per group (no whole-tile int32
    materialization), so its OT budget is ~4x the bf16-dequant route's —
    which matters: a wider OT shrinks the grid (fewer per-step fixed
    costs) ~10x for the 19k/38k-column projections."""
    it = _pick_tile(in_dim, gsz * 8, 1024)
    ot_budget = (6 * 2**20) // it if wide_ot else (3 * 2**20) // (2 * it)
    ot = _pick_tile(out, 1, max(512, ot_budget))
    n_gt = it // gsz

    def out_map(mi, oi, ii, *refs):
        return (mi, oi)

    if layered:
        def q_map(mi, oi, ii, li):
            return (li[0], ii, oi)

        def s_map(mi, oi, ii, li):
            return (li[0], 0, oi)

        q_block = (1, it // 2, ot)
        s_block = (1, n_g, ot)
        scalars = [jnp.reshape(layer, (1,)).astype(jnp.int32)]
    else:
        def q_map(mi, oi, ii, *refs):
            return (ii, oi)

        def s_map(mi, oi, ii, *refs):
            return (0, oi)

        q_block = (it // 2, ot)
        s_block = (n_g, ot)
        scalars = []
    return it, ot, n_gt, out_map, q_map, s_map, q_block, s_block, scalars


def _w4a8_matmul(x, q, s, zs, layer, out_dtype, interpret: bool):
    """The W4A8 route of ``int4_matmul`` (decode-sized batches).  The
    wrapper quantizes activations to per-row int8, lays them out
    group-major ([n_g, M, gsz] — static leading-axis indexing; in-kernel
    lane slicing at sub-128 offsets is not Mosaic-legal), and folds the
    zero-point term into one small XLA dot:

        y[m,o] = sxn[m] * (Sum_g s[g,o]*P[g,m,o] - Sum_g R[m,g]*zs[g,o])

    with P the kernel's int32 group partials, R the per-group sums of the
    quantized activations, sxn = rowmax|x|/127."""
    layered = q.ndim == 3
    if layered:
        assert layer is not None, "stacked int4 weights need the layer index"
    lead = x.shape[:-1]
    in_dim = x.shape[-1]
    out = q.shape[-1]
    n_g = s.shape[-2]
    gsz = in_dim // n_g
    half = gsz // 2
    out_dtype = out_dtype or x.dtype

    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, in_dim)
    # per-row symmetric int8 activation quant (f32 math: bf16 rounding
    # would double-quantize)
    xf = x2.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)  # [m, 1]
    sxn = amax / 127.0
    xq = jnp.where(
        amax > 0, jnp.round(xf * (127.0 / jnp.maximum(amax, 1e-30))), 0.0
    ).astype(jnp.int8)

    # zero-point term in XLA: R[m, g] = sum of xq over the group
    r = xq.reshape(m, n_g, gsz).sum(axis=-1, dtype=jnp.int32)
    zsl = zs
    if layered:
        zsl = jax.lax.dynamic_index_in_dim(zs, layer, 0, keepdims=False)
    zs_term = jax.lax.dot_general(
        r.astype(jnp.float32), zsl.astype(jnp.float32),
        (((1,), (0,)), ((), ())),
    )  # [m, out]

    # group-major activation layout for the kernel: [n_g, m, gsz] — group
    # g's rows in natural order, matching the stacked [lo; hi] weight
    # operand the kernel assembles per group
    xg = jnp.transpose(xq.reshape(m, n_g, gsz), (1, 0, 2))
    m_padded = -(-m // 8) * 8
    mt = m_padded
    if m_padded != m:
        xg = jnp.pad(xg, ((0, 0), (0, m_padded - m), (0, 0)))

    it, ot, n_gt, out_map, q_map, s_map, q_block, s_block, scalars = \
        _tiles_and_maps(in_dim, out, gsz, n_g, layered, layer, wide_ot=True)
    grid = (m_padded // mt, out // ot, in_dim // it)

    def x_map(mi, oi, ii, *refs):
        return (ii, mi, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=grid,
        in_specs=[
            pl.BlockSpec((n_gt, mt, gsz), x_map),
            pl.BlockSpec(q_block, q_map),
            pl.BlockSpec(s_block, s_map),
        ],
        out_specs=pl.BlockSpec((mt, ot), out_map),
        scratch_shapes=[
            pltpu.VMEM((mt, ot), jnp.float32),
            pltpu.VMEM((gsz, ot), jnp.int8),  # per-group stacked [lo; hi]
        ],
    )
    kernel = functools.partial(
        _w4a8_kernel, half=half, n_gt=n_gt, layered=layered,
        sliced=in_dim // it > 1,
    )
    acc = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_padded, out), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*scalars, xg, q, s)
    y = sxn * (acc[:m] - zs_term)
    return y.astype(out_dtype).reshape(*lead, out)


def _w4a8_enabled() -> bool:
    import os

    return os.environ.get("INT4_W4A8", "1") != "0"


def int4_matmul(
    x: jnp.ndarray,  # [..., IN]
    q: jnp.ndarray,  # [IN/2, OUT] or [L, IN/2, OUT] uint8 (in-group packed)
    s: jnp.ndarray,  # [(L,) n_g, OUT] bf16 group scales
    zs: jnp.ndarray,  # [(L,) n_g, OUT] bf16 (zero * scale)
    layer: jnp.ndarray | None = None,  # scalar int32, REQUIRED when stacked
    out_dtype=None,  # default x.dtype; jnp.float32 for logits
    interpret: bool = False,
    w4a8: bool | None = None,  # None: W4A8 for decode-sized batches unless
    # INT4_W4A8=0 — the MXU-int8 route is what makes 4-bit FASTER than
    # int8 instead of VPU-dequant-bound (accuracy contract: + per-row
    # int8 activation quant, ~1e-2 relative)
) -> jnp.ndarray:
    """``x @ dequant(q, s, zs)`` with the dequant in VMEM.  Returns
    [..., OUT] in ``out_dtype``."""
    m = 1
    for d in x.shape[:-1]:
        m *= d
    if w4a8 is None:
        # decode-sized rows only: prefill stays on exact bf16-dequant (it
        # is MXU-compute-bound there, and the f32 [m, out] partial would
        # be large), so prompt processing keeps the stricter contract
        w4a8 = m <= 256 and _w4a8_enabled()
    if w4a8 and not interpret:
        # the kernel's stacked [lo; hi] scratch stores slice the int8
        # sublane axis at offset gsz/2, which Mosaic only legalizes at
        # 32-row multiples — serving group sizes (64 default, AWQ 128)
        # qualify; anything smaller routes to the exact bf16-dequant
        # kernel instead of failing to compile (interpret mode has no
        # such constraint, so CPU tests still exercise the W4A8 math at
        # tiny group sizes)
        n_g_chk = s.shape[-2]
        if (x.shape[-1] // n_g_chk) // 2 % 32:
            w4a8 = False
    if w4a8:
        return _w4a8_matmul(x, q, s, zs, layer, out_dtype, interpret)
    layered = q.ndim == 3
    if layered:
        assert layer is not None, "stacked int4 weights need the layer index"
    lead = x.shape[:-1]
    in_dim = x.shape[-1]
    out = q.shape[-1]
    n_g = s.shape[-2]
    gsz = in_dim // n_g
    half = gsz // 2
    out_dtype = out_dtype or x.dtype

    m = 1
    for d in lead:
        m *= d
    # pre-split x into the two in-group nibble plane halves, group-major
    # ([m, n_g*half] each): tile ii's columns are then exactly groups
    # [ii*n_gt, (ii+1)*n_gt)'s half-rows for both planes (the in-kernel
    # lane slicing this replaces is an unsupported Mosaic shape cast)
    xg = x.reshape(m, n_g, gsz)
    xa = xg[:, :, :half].reshape(m, n_g * half)
    xb = xg[:, :, half:].reshape(m, n_g * half)

    # row tiling: whole batch in one tile up to 256 rows (decode), 256-row
    # tiles beyond (prefill); padded rows compute garbage that is sliced off
    if m <= 256:
        m_padded = -(-m // 8) * 8
        mt = m_padded
    else:
        m_padded = -(-m // 256) * 256
        mt = 256
    if m_padded != m:
        xa = jnp.pad(xa, ((0, m_padded - m), (0, 0)))
        xb = jnp.pad(xb, ((0, m_padded - m), (0, 0)))

    it, ot, n_gt, out_map, q_map, s_map, q_block, s_block, scalars = \
        _tiles_and_maps(in_dim, out, gsz, n_g, layered, layer)
    grid = (m_padded // mt, out // ot, in_dim // it)

    def x_map(mi, oi, ii, *refs):
        return (mi, ii)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=grid,
        in_specs=[
            pl.BlockSpec((mt, it // 2), x_map),
            pl.BlockSpec((mt, it // 2), x_map),
            pl.BlockSpec(q_block, q_map),
            pl.BlockSpec(s_block, s_map),
            pl.BlockSpec(s_block, s_map),
        ],
        out_specs=pl.BlockSpec((mt, ot), out_map),
        scratch_shapes=[pltpu.VMEM((mt, ot), jnp.float32)],
    )
    kernel = functools.partial(
        _int4_kernel, half=half, n_gt=n_gt, layered=layered,
        sliced=in_dim // it > 1,
    )
    y = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_padded, out), out_dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*scalars, xa, xb, q, s, zs)
    return y[:m].reshape(*lead, out)
