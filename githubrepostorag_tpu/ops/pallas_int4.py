"""Pallas TPU int4 weight-only GEMM: in-VMEM dequant fused into the dot.

Why a kernel: XLA does not fuse the int4 unpack chain (nibble shift ->
group reshape -> scale multiply -> concat) into a dot operand the way it
fuses int8's convert+scale — device traces of the 7B int4 decode burst
show it materializing reshaped/scaled copies at ~37 ms/step of reshapes
plus ~26 ms/step of copies, making int4 3-6x SLOWER than int8.  Here the
packed tile is DMA'd to VMEM (half the int8 bytes off HBM — the entire
point of int4), unpacked and dequantized in VMEM, and fed straight to the
MXU.

Weights are the in-group plane-packed ``QuantizedLinear4`` layout
(models/quant.py): byte row j of group g holds original rows (g*gsz + j)
in the low nibble and (g*gsz + j + gsz/2) in the high nibble, so an
input-tile that is a whole number of groups unpacks with one in-VMEM
concat and its scale rows align exactly.

Stacked [L, in/2, out] weights ride in WHOLE with the layer index as a
prefetched scalar (same discipline as the rank-5 KV pools in
pallas_paged.py): the burst's layer loop never dynamic-slices a weight
into a materialized copy.

Oracle: models/quant.py::q4_matmul (the two-dot XLA formulation) — exact
same math, used on CPU and in interpret-mode tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _pick_tile(total: int, unit: int, target: int) -> int:
    """Largest multiple of ``unit`` that divides ``total``, is <= target,
    AND keeps the TPU lane constraint (multiple of 128, unless it is the
    whole dimension — Pallas requires block minor dims be 128-aligned or
    full).  Falls back to ``total``."""
    import math

    step = math.lcm(unit, 128)
    t = (target // step) * step
    while t >= step:
        if total % t == 0:
            return t
        t -= step
    return total


def _int4_kernel(*refs, half: int, n_gt: int, layered: bool, sliced: bool):
    ii = pl.program_id(2)
    n_ii = pl.num_programs(2)
    # the scale blocks carry the FULL group axis (their shape must be
    # 8/128-aligned or full); this in-tile's rows slice out at the REF.
    # ``sliced`` is static: with one in-tile the whole block is the tile
    # (and Mosaic needs no provably-8-aligned dynamic sublane offset —
    # the wrapper guarantees n_gt % 8 == 0 whenever sliced)
    if layered:
        (_li_ref, xa_ref, xb_ref, q_ref, s_ref, zs_ref, out_ref, acc_ref) = refs
        pq = q_ref[0]  # [IT/2, OT]
        s = s_ref[0, pl.ds(ii * n_gt, n_gt)] if sliced else s_ref[0]
        zs = zs_ref[0, pl.ds(ii * n_gt, n_gt)] if sliced else zs_ref[0]
    else:
        (xa_ref, xb_ref, q_ref, s_ref, zs_ref, out_ref, acc_ref) = refs
        pq = q_ref[...]
        s = s_ref[pl.ds(ii * n_gt, n_gt)] if sliced else s_ref[...]
        zs = zs_ref[pl.ds(ii * n_gt, n_gt)] if sliced else zs_ref[...]

    @pl.when(ii == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ot = pq.shape[-1]
    dt = xa_ref.dtype  # bf16 serving; f32 in CPU-geometry tests
    # The unpack is VPU-bound (every weight element pays mask+cast+scale
    # while the MXU waits), so shave VPU work: no shift — the high nibble
    # stays in place (pq & 0xF0 = 16*nib) with 1/16 folded into its scale
    # — and no concat — the two nibble planes go to the MXU as TWO dots
    # against the matching halves of x (in-group plane packing makes both
    # planes contiguous row ranges).  Widening runs through int32: Mosaic
    # legalizes neither uint8 shifts nor uint8->bf16 casts.
    sdt = s.astype(dt)[:, None, :]
    zdt = zs.astype(dt)[:, None, :]
    # Unpack via int32 widening (Mosaic legalizes neither uint8 shifts nor
    # uint8->bf16 casts; an int8-domain bitcast variant measured ~12%
    # SLOWER — the convert path widens internally regardless).  No shift:
    # the high nibble stays in place (pq & 0xF0 = 16*nib) with 1/16 folded
    # into its scale.  Each plane's rows are distinct original rows, every
    # one dequantizing as nib*s - zs — both planes subtract the FULL zs.
    # The remaining cost is fundamental per-element convert throughput on
    # the VPU (the kernel is compute-bound, not HBM-bound, at 7B: ~2.9 GB
    # of int4 reads vs ~19 ms/step measured); the next step beyond this is
    # W4A8 — int8 activations on the MXU's native int8 path with per-group
    # int32 partial sums — which changes the accuracy contract.
    pq32 = pq.astype(jnp.int32)
    lo = (pq32 & 0x0F).astype(dt).reshape(n_gt, half, ot) * sdt - zdt
    hi = (pq32 & 0xF0).astype(dt).reshape(n_gt, half, ot) * (sdt / 16) - zdt
    # x arrives PRE-SPLIT into the two plane halves (wrapper-side — the
    # [MT, n_gt, gsz] lane slicing is an unsupported shape cast in Mosaic,
    # and activations are tiny for XLA to split)
    x_a = xa_ref[...]  # [MT, IT/2]
    x_b = xb_ref[...]
    dn = (((1,), (0,)), ((), ()))
    acc_ref[...] += jax.lax.dot_general(
        x_a, lo.reshape(n_gt * half, ot), dn, preferred_element_type=jnp.float32
    ) + jax.lax.dot_general(
        x_b, hi.reshape(n_gt * half, ot), dn, preferred_element_type=jnp.float32
    )

    @pl.when(ii == n_ii - 1)
    def _():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def int4_matmul(
    x: jnp.ndarray,  # [..., IN]
    q: jnp.ndarray,  # [IN/2, OUT] or [L, IN/2, OUT] uint8 (in-group packed)
    s: jnp.ndarray,  # [(L,) n_g, OUT] bf16 group scales
    zs: jnp.ndarray,  # [(L,) n_g, OUT] bf16 (zero * scale)
    layer: jnp.ndarray | None = None,  # scalar int32, REQUIRED when stacked
    out_dtype=None,  # default x.dtype; jnp.float32 for logits
    interpret: bool = False,
) -> jnp.ndarray:
    """``x @ dequant(q, s, zs)`` with the dequant in VMEM.  Returns
    [..., OUT] in ``out_dtype``."""
    layered = q.ndim == 3
    if layered:
        assert layer is not None, "stacked int4 weights need the layer index"
    lead = x.shape[:-1]
    in_dim = x.shape[-1]
    out = q.shape[-1]
    n_g = s.shape[-2]
    gsz = in_dim // n_g
    half = gsz // 2
    out_dtype = out_dtype or x.dtype

    m = 1
    for d in lead:
        m *= d
    # pre-split x into the two in-group nibble plane halves, group-major
    # ([m, n_g*half] each): tile ii's columns are then exactly groups
    # [ii*n_gt, (ii+1)*n_gt)'s half-rows for both planes (the in-kernel
    # lane slicing this replaces is an unsupported Mosaic shape cast)
    xg = x.reshape(m, n_g, gsz)
    xa = xg[:, :, :half].reshape(m, n_g * half)
    xb = xg[:, :, half:].reshape(m, n_g * half)

    # row tiling: whole batch in one tile up to 256 rows (decode), 256-row
    # tiles beyond (prefill); padded rows compute garbage that is sliced off
    if m <= 256:
        m_padded = -(-m // 8) * 8
        mt = m_padded
    else:
        m_padded = -(-m // 256) * 256
        mt = 256
    if m_padded != m:
        xa = jnp.pad(xa, ((0, m_padded - m), (0, 0)))
        xb = jnp.pad(xb, ((0, m_padded - m), (0, 0)))

    # in-tile: a multiple of 8 GROUPS (so the scale slice offset is a
    # provable sublane multiple), falling back to the whole input dim
    # (single in-tile, no slicing)
    it = _pick_tile(in_dim, gsz * 8, 1024)
    # VMEM budget: dequantized w tile (bf16) + packed tile + acc
    ot = _pick_tile(out, 1, max(512, (3 * 2**20) // (2 * it)))
    n_gt = it // gsz

    grid = (m_padded // mt, out // ot, in_dim // it)

    def x_map(mi, oi, ii, *refs):
        return (mi, ii)

    def out_map(mi, oi, ii, *refs):
        return (mi, oi)

    if layered:
        def q_map(mi, oi, ii, li):
            return (li[0], ii, oi)

        def s_map(mi, oi, ii, li):
            return (li[0], 0, oi)

        q_block = (1, it // 2, ot)
        s_block = (1, n_g, ot)
        scalars = [jnp.reshape(layer, (1,)).astype(jnp.int32)]
    else:
        def q_map(mi, oi, ii, *refs):
            return (ii, oi)

        def s_map(mi, oi, ii, *refs):
            return (0, oi)

        q_block = (it // 2, ot)
        s_block = (n_g, ot)
        scalars = []

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=grid,
        in_specs=[
            pl.BlockSpec((mt, it // 2), x_map),
            pl.BlockSpec((mt, it // 2), x_map),
            pl.BlockSpec(q_block, q_map),
            pl.BlockSpec(s_block, s_map),
            pl.BlockSpec(s_block, s_map),
        ],
        out_specs=pl.BlockSpec((mt, ot), out_map),
        scratch_shapes=[pltpu.VMEM((mt, ot), jnp.float32)],
    )
    kernel = functools.partial(
        _int4_kernel, half=half, n_gt=n_gt, layered=layered,
        sliced=in_dim // it > 1,
    )
    y = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_padded, out), out_dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*scalars, xa, xb, q, s, zs)
    return y[:m].reshape(*lead, out)
