"""Fused decode-step attention: spec-verify windows + paged attention +
mixed prefill/decode segments in ONE Pallas launch, over fp/int8/int4 pages.

Before this op the decode hot path was a chain of separately-shaped
dispatches: the spec-verify forward scored its k+1 candidate positions
through the PALLAS DECODE kernel's gather fallback (ops/pallas_paged.py
routes any S > 1 window to gather_kv + dense — a full [B, mp*ps, n_kv, hd]
HBM materialization per layer), quantized pools forced the same fallback
even at S == 1, and packed prefill rows needed their own program.  This
module is one generalized flash kernel that covers all of it:

  - WINDOW attention: every row scores an S-token window (S = k+1 for
    spec verify, S = 1 for plain decode) starting at its ``cached_lens``
    base against its block-table pages — online softmax across the page
    walk, nothing materialized in HBM.  The verify dispatch and the
    decode dispatch become the same program shape.
  - SEGMENT-packed grids: the ops/packed_prefill.py scatter idiom re-pads
    a [T]-packed mixed wave into the segment-major [R, tq] view — which
    IS the window layout — so chunked-prefill rows and decode rows ride
    one grid (`fused_packed_attention`), one compiled program per
    (row-bucket, tq).
  - Quantized pages IN-KERNEL: int8 pages dequantize by the per-page
    scalar-prefetched scale at the dot; int4 pages (kv_cache.pack_int4's
    nibble planes, uint8 [ps, hd//2]) widen through int32 (Mosaic
    legalizes neither uint8 shifts nor uint8->bf16 casts — the
    ops/pallas_int4.py rule) and score as TWO plane dots against the
    matching halves of q, never materializing the unpacked page.

Oracle: ``paged_attention_ref`` (gather_kv unpacks/dequantizes the same
bit pattern), which tests/test_fused_decode.py holds this kernel to across
row buckets, k widths, quant modes, and block-table holes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from githubrepostorag_tpu.ops.packed_prefill import _segment_scatter_indices

NEG_INF = -1e30

# JAX renamed TPUCompilerParams -> CompilerParams; support both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def _fused_window_kernel(
    # scalar prefetch (quant == 0 omits the two scale refs)
    *refs,
    page_size: int,
    scale: float,
    quant: int,  # 0 = full precision, 8 = int8 pages, 4 = int4 nibble pages
):
    if quant:
        (block_tables_ref, cached_lens_ref, total_lens_ref, ks_ref, vs_ref,
         q_ref, k_ref, v_ref, out_ref, m_ref, l_ref, acc_ref) = refs
    else:
        (block_tables_ref, cached_lens_ref, total_lens_ref,
         q_ref, k_ref, v_ref, out_ref, m_ref, l_ref, acc_ref) = refs

    bi = pl.program_id(0)
    hi = pl.program_id(1)
    pi = pl.program_id(2)
    num_pi = pl.num_programs(2)

    @pl.when(pi == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    cached = cached_lens_ref[bi]  # each q row's base position in the window
    total = total_lens_ref[bi]  # valid kv length for this row
    page_start = pi * page_size

    @pl.when(page_start < total)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)  # [group, W, hd]
        half = q.shape[-1] // 2

        if quant == 4:
            # nibble planes: byte c = component c | component c+half << 4
            # of the SAME token (kv_cache.pack_int4).  Widen through int32
            # — Mosaic has no uint8 shift/compare lowering — and
            # sign-extend two's-complement nibbles in-register.
            ki = k_ref[0, 0].astype(jnp.int32)  # [page_size, hd//2]
            k_lo = (((ki & 0xF) ^ 8) - 8).astype(jnp.float32)
            k_hi = (((ki >> 4) ^ 8) - 8).astype(jnp.float32)
            # two plane dots against the matching q halves — equivalent to
            # one dot against the unpacked [page_size, hd] page, which
            # never materializes
            s = jax.lax.dot_general(
                q[..., :half], k_lo, (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) + jax.lax.dot_general(
                q[..., half:], k_hi, (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            k = k_ref[0, 0].astype(jnp.float32)  # [page_size, hd]
            s = jax.lax.dot_general(
                q, k, (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [group, W, page_size]

        if quant:
            # per-page scalar dequant rides the softmax scale: this grid
            # step covers exactly one (kv head, page) pair
            page = block_tables_ref[bi, pi]
            s = s * (scale * ks_ref[hi, page])
        else:
            s = s * scale

        # causal within the window: q row ti sits at absolute position
        # cached + ti; kv beyond the row's valid length is padding
        kv_pos = page_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        q_pos = cached + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where((kv_pos <= q_pos) & (kv_pos < total), s, NEG_INF)

        m_prev = m_ref[:, :, :1]  # [group, W, 1]
        l_prev = l_ref[:, :, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [group, W, page_size]
        l_ref[:, :, :1] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:, :, :1] = m_new

        if quant == 4:
            vi = v_ref[0, 0].astype(jnp.int32)  # [page_size, hd//2]
            v_lo = (((vi & 0xF) ^ 8) - 8).astype(jnp.float32)
            v_hi = (((vi >> 4) ^ 8) - 8).astype(jnp.float32)
            vs = vs_ref[hi, block_tables_ref[bi, pi]]
            o_lo = jax.lax.dot_general(
                p, v_lo, (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * vs
            o_hi = jax.lax.dot_general(
                p, v_hi, (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * vs
            # plane outputs land in their own halves of the accumulator —
            # static ref slices, no in-kernel concat
            acc = acc_ref[...]
            acc_ref[:, :, :half] = acc[:, :, :half] * alpha + o_lo
            acc_ref[:, :, half:] = acc[:, :, half:] * alpha + o_hi
        else:
            v = v_ref[0, 0].astype(jnp.float32)
            o = jax.lax.dot_general(
                p, v, (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if quant:
                o = o * vs_ref[hi, block_tables_ref[bi, pi]]
            acc_ref[...] = acc_ref[...] * alpha + o

    @pl.when(pi == num_pi - 1)
    def _():
        # inactive / bucket-padding rows (total == 0) never hit the
        # accumulate branch; guard the 0/0
        l = l_ref[:, :, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = (acc_ref[...] / safe_l).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_window_attention(
    q_win: jnp.ndarray,  # [B, S, n_q, hd] — per-row windows based at cached_lens
    k_pages: jnp.ndarray,  # [n_kv, P, page_size, hd] (or [.., hd//2] uint8 int4)
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_pages]
    cached_lens: jnp.ndarray,  # [B]
    new_lens: jnp.ndarray,  # [B] valid new tokens (<= S) — already committed
    k_scales: jnp.ndarray | None = None,  # [n_kv, P] f32 per-page (quant pools)
    v_scales: jnp.ndarray | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """ONE Pallas launch for every row's S-token window: grid
    (B, n_kv, max_pages), one page slab in VMEM per step.  Same contract
    as ``paged_attention_ref`` (its oracle)."""
    b, s_w, n_q, hd = q_win.shape
    n_kv, _, page_size, hd_store = k_pages.shape
    group = n_q // n_kv
    max_pages = block_tables.shape[1]
    scale = 1.0 / (hd ** 0.5)
    if k_scales is None:
        quant = 0
    else:
        quant = 4 if k_pages.dtype == jnp.uint8 else 8

    total_lens = (cached_lens + new_lens).astype(jnp.int32)
    # [B, S, n_kv, group, hd] -> [B, n_kv, group, S, hd]: one kv head's
    # whole query group rides each grid step's MXU dots
    q_r = q_win.reshape(b, s_w, n_kv, group, hd).transpose(0, 2, 3, 1, 4)

    def q_map(bi, hi, pi, *scalars):
        return (bi, hi, 0, 0, 0)

    def kv_map(bi, hi, pi, bt, cl, tl, *scalars):
        # Clamp the walk to allocated pages: beyond the row's length the
        # kernel skips compute, so any valid page id works — page 0.
        page = jax.lax.select(pi * page_size < tl[bi], bt[bi, pi], 0)
        return (hi, page, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5 if quant else 3,
        grid=(b, n_kv, max_pages),
        in_specs=[
            pl.BlockSpec((1, 1, group, s_w, hd), q_map),
            pl.BlockSpec((1, 1, page_size, hd_store), kv_map),
            pl.BlockSpec((1, 1, page_size, hd_store), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, group, s_w, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((group, s_w, 128), jnp.float32),
            pltpu.VMEM((group, s_w, 128), jnp.float32),
            pltpu.VMEM((group, s_w, hd), jnp.float32),
        ],
    )

    kernel = functools.partial(
        _fused_window_kernel, page_size=page_size, scale=scale, quant=quant
    )
    scalars = [block_tables.astype(jnp.int32), cached_lens.astype(jnp.int32),
               total_lens]
    if quant:
        scalars += [k_scales.astype(jnp.float32), v_scales.astype(jnp.float32)]
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, group, s_w, hd), q_win.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*scalars, q_r, k_pages, v_pages)

    # [B, n_kv, group, S, hd] -> [B, S, n_q, hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, s_w, n_q, hd)


def fused_paged_attention(q, k_pages, v_pages, block_tables, cached_lens,
                          new_lens, k_scales=None, v_scales=None):
    """Drop-in for ``paged_attention_ref``/``pallas_paged.paged_attention``
    at the forward_paged seam: spec-verify windows (S = k+1), plain decode
    (S = 1), and quantized pools all hit the SAME kernel instead of the
    dispatcher's gather fallback.  Interpret mode off-TPU keeps CPU tests
    on the kernel's exact compute graph."""
    return fused_window_attention(
        q, k_pages, v_pages, block_tables, cached_lens, new_lens,
        k_scales, v_scales, interpret=jax.default_backend() != "tpu",
    )


def fused_packed_attention(
    q: jnp.ndarray,  # [T, n_q, hd] packed mixed-phase queries
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [R, max_pages]
    cached_lens: jnp.ndarray,  # [R]
    new_lens: jnp.ndarray,  # [R]
    seg_ids: jnp.ndarray,  # [T]; >= R marks padding tokens
    positions: jnp.ndarray,  # [T] absolute positions
    *,
    tq: int,
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Mixed-phase launch: the packed_prefill scatter re-pads the [T]
    buffer to the segment-major [R, tq] view — a prefill CHUNK and a
    decode/verify WINDOW are the same shape there (cached_lens base,
    new_lens valid tokens) — and one fused-kernel grid covers every
    segment regardless of phase or pool quantization."""
    t, n_q, hd = q.shape
    r = block_tables.shape[0]
    dest = _segment_scatter_indices(seg_ids, positions, cached_lens, tq)
    q_seg = (
        jnp.zeros((r * tq, n_q, hd), q.dtype)
        .at[dest].set(q, mode="drop")
        .reshape(r, tq, n_q, hd)
    )
    out_seg = fused_window_attention(
        q_seg, k_pages, v_pages, block_tables, cached_lens, new_lens,
        k_scales, v_scales, interpret=jax.default_backend() != "tpu",
    )
    # gather back to packed order; padding tokens read a clamped garbage
    # row (finite — never committed to KV, never projected to logits)
    flat = out_seg.reshape(r * tq, n_q, hd)
    return flat[jnp.clip(dest, 0, r * tq - 1)]
