"""Token sampling: greedy / temperature / top-k / top-p (nucleus) with
repetition penalty.

Covers the reference's client-side sampling surface (qwen_llm.py:107-114:
temperature 0.4, top_p 0.8, repetition_penalty 1.2, and the ingest client's
0.7/0.9) executed *inside* the engine on TPU — one fused jit per decode step
rather than vLLM's GPU sampler.

All functions are batch-first and jit-safe with static vocab shapes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _segment_logits(logits: jnp.ndarray, seg_pos=None) -> jnp.ndarray:
    """Accept the fused kernel's per-segment [B, S, V] logits layout
    directly: ``seg_pos`` ([B] int32) gathers each row's window position
    on device (None = position 0, the committed token of a verify/decode
    window).  Rank-2 logits pass through untouched — callers used to
    transpose-copy [B, S, V] windows host-side before sampling; the
    on-device take_along_axis fuses into the sampling program instead."""
    if logits.ndim == 2:
        return logits
    if seg_pos is None:
        return logits[:, 0]
    return jnp.take_along_axis(logits, seg_pos[:, None, None], axis=1)[:, 0]


def _exact_topk() -> bool:
    """SAMPLING_EXACT_TOPK=1 -> exact full-vocab candidate selection in
    sample_tokens_capped (read per trace, so flipping the env between
    engine constructions takes effect on the next compile)."""
    from githubrepostorag_tpu.config import _env_bool

    return _env_bool("SAMPLING_EXACT_TOPK", False)


def apply_repetition_penalty(
    logits: jnp.ndarray,  # [B, V] float32
    presence: jnp.ndarray,  # [B, V] bool — token appeared in prompt or output
    penalty: float | jnp.ndarray,
) -> jnp.ndarray:
    """HF/vLLM convention: divide positive logits by the penalty, multiply
    negative ones, for every token already seen."""
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(presence, penalized, logits)


def top_k_mask(logits: jnp.ndarray, k: jnp.ndarray | int) -> jnp.ndarray:
    """Keep the k highest logits per row.  ``k`` is a scalar or [B] array of
    int32; k <= 0 disables filtering for that row."""
    vocab = logits.shape[-1]
    k_arr = jnp.broadcast_to(jnp.asarray(k, jnp.int32), logits.shape[:-1])  # [B]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    idx = jnp.clip(k_arr - 1, 0, vocab - 1)[..., None]
    threshold = jnp.take_along_axis(sorted_desc, idx, axis=-1)  # [B, 1]
    filtered = jnp.where(logits < threshold, NEG_INF, logits)
    return jnp.where((k_arr <= 0)[..., None], logits, filtered)


def top_p_mask(logits: jnp.ndarray, p: jnp.ndarray | float) -> jnp.ndarray:
    """Nucleus filtering: mask tokens outside the smallest set with cumulative
    probability >= p.  p >= 1 disables."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumprobs = jnp.cumsum(probs, axis=-1)
    # keep tokens while cumulative prob of *previous* tokens < p; the top
    # token always survives (p <= 0 must degrade to near-greedy, not to
    # uniform sampling over a fully masked vocab)
    keep_sorted = (cumprobs - probs) < jnp.asarray(p)[..., None]
    keep_sorted = keep_sorted.at[..., 0].set(True)
    # threshold = smallest kept logit
    threshold = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < threshold, NEG_INF, logits)


def sample_tokens_capped(
    logits: jnp.ndarray,  # [B, V] float32, or [B, S, V] fused-window layout
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B] — 0 means greedy
    top_p: jnp.ndarray,  # [B] — 1.0 disables
    top_k: jnp.ndarray,  # [B] int32 — 0 disables
    repetition_penalty: jnp.ndarray,  # [B]
    presence: jnp.ndarray,  # [B, V] bool
    cap: int = 128,
    seg_pos: jnp.ndarray | None = None,  # [B] window position per row
    # (rank-3 logits only; None = position 0)
) -> jnp.ndarray:
    """Decode-loop sampler: identical semantics to ``sample_tokens`` except
    top-k/top-p operate within the ``cap`` highest logits.  The candidate
    set comes from one ``lax.approx_max_k`` (TPU-native; an exact
    ``lax.top_k`` over the 152k vocab measures ~1.6 ms/step standalone on
    v5e — comparable to the whole 0.5B forward — and costs ~15% of decode
    throughput in-burst) whose default aggregate_to_topk pass already
    returns the cap candidates EXACTLY sorted; recall_target=0.99 sets the
    internal bin oversampling.  A bin-collision miss (~(1-recall) per
    step) costs one step of that token's sampling mass — no correctness
    impact, greedy rows use the separate exact argmax below.
    Exact nucleus whenever it fits the cap, which holds for every sampling
    config in the system (reference clients use top_p 0.8/0.9 at
    temperature <= 0.7 — qwen_llm.py:107-114).

    SAMPLING_EXACT_TOPK=1 swaps the approximate candidate pull for an
    exact ``lax.top_k`` over the full vocab — the escape hatch for
    reproducibility-sensitive evals where the ~(1-recall)-per-step chance
    of a missing tail candidate matters more than the ~15%
    decode-throughput cost."""
    logits = _segment_logits(logits, seg_pos)
    logits = apply_repetition_penalty(logits, presence, repetition_penalty[:, None])
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    vocab = logits.shape[-1]
    cap = min(cap, vocab)
    if _exact_topk():
        vals, idx = jax.lax.top_k(scaled, cap)
        idx = idx.astype(jnp.int32)
    else:
        # approx_max_k's default aggregate_to_topk=True ENDS with an exact
        # sorted top-k over its oversampled candidate bins (the recall
        # knob controls the internal oversampling), so its output is
        # already what a second lax.top_k would produce — device profiling
        # showed that redundant second sort costing ~0.1 ms/decode step.
        # Pull exactly cap candidates: the in-burst aggregate sort scales
        # with the pull size (real-chip scan bench: pool=2*cap costs
        # ~0.17 ms/step more than pool=cap at bs8), and each true top-cap
        # candidate still lands in the pull with >= recall_target
        # probability.  SAMPLING_EXACT_TOPK=1 below remains the exactness
        # escape hatch.
        # recall_target stays 0.99: ADVICE r04 suggested 0.995 on the
        # theory that only pull size (not recall) costs time — MEASURED
        # false on the real chip (r05 A/B, 3-run medians on the 0.5B bs8
        # decode item: 3354 tok/s at 0.99 vs 3215 at 0.995, a 4.3% hit —
        # the recall knob widens approx_max_k's internal bins and that
        # reduction work is visible where sampling is a large step
        # fraction).  SAMPLING_EXACT_TOPK=1 remains the exactness hatch.
        vals, idx = jax.lax.approx_max_k(scaled, cap, recall_target=0.99)
        idx = idx.astype(jnp.int32)
    # top-k within the cap: positions >= k masked (k<=0 disables)
    ranks = jnp.arange(cap)[None, :]
    k_arr = top_k[:, None]
    vals = jnp.where((k_arr > 0) & (ranks >= k_arr), NEG_INF, vals)
    # nucleus within the cap (vals already sorted descending)
    probs = jax.nn.softmax(vals, axis=-1)
    keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p[:, None]
    keep = keep.at[:, 0].set(True)
    vals = jnp.where(keep, vals, NEG_INF)
    choice = jax.random.categorical(rng, vals, axis=-1)  # [B] index into cap
    sampled = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    return jnp.where(temperature <= 0.0, greedy, sampled)


def sample_tokens_nofilter(
    logits: jnp.ndarray,  # [B, V] float32, or [B, S, V] fused-window layout
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B] — 0 means greedy
    repetition_penalty: jnp.ndarray,  # [B]
    presence: jnp.ndarray,  # [B, V] bool
    seg_pos: jnp.ndarray | None = None,  # [B] window position per row
) -> jnp.ndarray:
    """Sampling fast path for rows with top_p >= 1 and top_k <= 0 (the
    default API sampling config): ``jax.random.categorical`` over the full
    vocab is exactly Gumbel-argmax — one fused reduce, no approx_max_k
    candidate pull and no sort.  The candidate sort costs ~0.23 ms per
    decode step at bs8 on v5e (device trace: ``sort.9``), and grows with
    the row count; the engine selects this variant per burst from its
    host-side sampling mirrors (serving/engine.py _decode_step).

    Distribution contract: the engine's sampling support is "the top-cap
    candidates" (sample_tokens_capped); this variant WIDENS that to the
    exact full vocab when the whole batch qualifies.  A non-filtering row
    batched with a filtering one therefore samples from the top-cap
    support instead — the delta is the tail mass beyond the top 128
    logits, negligible at practical temperatures, and batch composition
    already shifts per-row draws (rows index a shared step key), so no
    cross-composition reproducibility is lost that ever existed."""
    logits = _segment_logits(logits, seg_pos)
    logits = apply_repetition_penalty(logits, presence, repetition_penalty[:, None])
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


@partial(jax.jit, static_argnames=())
def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float32 (last-position logits)
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B] — 0 means greedy
    top_p: jnp.ndarray,  # [B] — 1.0 disables
    top_k: jnp.ndarray,  # [B] int32 — 0 disables
    repetition_penalty: jnp.ndarray,  # [B] — 1.0 disables
    presence: jnp.ndarray,  # [B, V] bool
) -> jnp.ndarray:
    """Per-request sampling params, one fused kernel.  Returns [B] int32."""
    logits = apply_repetition_penalty(logits, presence, repetition_penalty[:, None])
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    filtered = top_p_mask(top_k_mask(scaled, top_k), top_p)
    sampled = jax.random.categorical(rng, filtered, axis=-1).astype(jnp.int32)

    return jnp.where(temperature <= 0.0, greedy, sampled)
