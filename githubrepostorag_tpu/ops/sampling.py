"""Token sampling: greedy / temperature / top-k / top-p (nucleus) with
repetition penalty.

Covers the reference's client-side sampling surface (qwen_llm.py:107-114:
temperature 0.4, top_p 0.8, repetition_penalty 1.2, and the ingest client's
0.7/0.9) executed *inside* the engine on TPU — one fused jit per decode step
rather than vLLM's GPU sampler.

All functions are batch-first and jit-safe with static vocab shapes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def apply_repetition_penalty(
    logits: jnp.ndarray,  # [B, V] float32
    presence: jnp.ndarray,  # [B, V] bool — token appeared in prompt or output
    penalty: float | jnp.ndarray,
) -> jnp.ndarray:
    """HF/vLLM convention: divide positive logits by the penalty, multiply
    negative ones, for every token already seen."""
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(presence, penalized, logits)


def top_k_mask(logits: jnp.ndarray, k: jnp.ndarray | int) -> jnp.ndarray:
    """Keep the k highest logits per row.  ``k`` is a scalar or [B] array of
    int32; k <= 0 disables filtering for that row."""
    vocab = logits.shape[-1]
    k_arr = jnp.broadcast_to(jnp.asarray(k, jnp.int32), logits.shape[:-1])  # [B]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    idx = jnp.clip(k_arr - 1, 0, vocab - 1)[..., None]
    threshold = jnp.take_along_axis(sorted_desc, idx, axis=-1)  # [B, 1]
    filtered = jnp.where(logits < threshold, NEG_INF, logits)
    return jnp.where((k_arr <= 0)[..., None], logits, filtered)


def top_p_mask(logits: jnp.ndarray, p: jnp.ndarray | float) -> jnp.ndarray:
    """Nucleus filtering: mask tokens outside the smallest set with cumulative
    probability >= p.  p >= 1 disables."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumprobs = jnp.cumsum(probs, axis=-1)
    # keep tokens while cumulative prob of *previous* tokens < p; the top
    # token always survives (p <= 0 must degrade to near-greedy, not to
    # uniform sampling over a fully masked vocab)
    keep_sorted = (cumprobs - probs) < jnp.asarray(p)[..., None]
    keep_sorted = keep_sorted.at[..., 0].set(True)
    # threshold = smallest kept logit
    threshold = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < threshold, NEG_INF, logits)


@partial(jax.jit, static_argnames=())
def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float32 (last-position logits)
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B] — 0 means greedy
    top_p: jnp.ndarray,  # [B] — 1.0 disables
    top_k: jnp.ndarray,  # [B] int32 — 0 disables
    repetition_penalty: jnp.ndarray,  # [B] — 1.0 disables
    presence: jnp.ndarray,  # [B, V] bool
) -> jnp.ndarray:
    """Per-request sampling params, one fused kernel.  Returns [B] int32."""
    logits = apply_repetition_penalty(logits, presence, repetition_penalty[:, None])
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    filtered = top_p_mask(top_k_mask(scaled, top_k), top_p)
    sampled = jax.random.categorical(rng, filtered, axis=-1).astype(jnp.int32)

    return jnp.where(temperature <= 0.0, greedy, sampled)
