"""TPU compute ops: norms, rotary embeddings, attention (dense + paged
Pallas), sampling.  Everything here is jit-safe (static shapes, no Python
control flow on traced values) and bfloat16-friendly."""

from githubrepostorag_tpu.ops.norms import rms_norm
from githubrepostorag_tpu.ops.rope import apply_rope, rope_cos_sin

__all__ = ["rms_norm", "apply_rope", "rope_cos_sin"]
