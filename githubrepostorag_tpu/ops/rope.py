"""Rotary position embeddings (rotate-half convention, Llama/Qwen2 family).

cos/sin are computed in float32 from integer positions so decode steps at
position 30k+ keep full precision, then applied in the activation dtype.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(positions: jnp.ndarray, head_dim: int, theta: float = 10000.0):
    """positions [B, S] (int32) -> cos, sin each [B, S, head_dim]."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, S, hd/2]
    angles = jnp.concatenate([angles, angles], axis=-1)  # [B, S, hd]
    return jnp.cos(angles), jnp.sin(angles)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rope(q: jnp.ndarray, k: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray):
    """q [B, S, n_q, hd], k [B, S, n_kv, hd]; cos/sin [B, S, hd]."""
    cos = cos[:, :, None, :].astype(q.dtype)
    sin = sin[:, :, None, :].astype(q.dtype)
    q_out = q * cos + _rotate_half(q) * sin
    k_out = k * cos + _rotate_half(k) * sin
    return q_out, k_out
