"""Paged attention over the page-pool KV cache.

Two implementations with one contract:
  - ``paged_attention_ref`` — gather the sequence's pages into a contiguous
    [B, S_max] view and run dense attention.  Always correct, materializes
    the gathered KV in HBM; used on CPU tests and as the Pallas kernel's
    oracle.
  - ``paged_attention`` (ops/pallas_paged.py) — the TPU kernel: walks the
    block table page by page in VMEM with an online-softmax accumulator, so
    nothing is materialized.  Falls back to the reference path off-TPU.

Contract (both): q for ONE new-token step per row plus optional chunk width:
  q            [B, S, n_q, hd]  — new queries (right-padded per row)
  k_pages      [n_kv, P, page_size, hd] — this layer's pool
  v_pages      [n_kv, P, page_size, hd]
  block_tables [B, max_pages]   int32 — page ids per row
  cached_lens  [B] int32        — tokens already in cache BEFORE this step
  new_lens     [B] int32        — valid new tokens this step (<= S)
Returns [B, S, n_q, hd].  Rows attend to their cache prefix plus the causal
part of the new chunk; padded queries/kv are masked.
"""

from __future__ import annotations

import jax.numpy as jnp

from githubrepostorag_tpu.ops.attention import dense_attention


def gather_kv(k_pages, v_pages, block_tables, k_scales=None, v_scales=None,
              dtype=None):
    """[n_kv, P, ps, hd] + [B, max_pages] -> [B, max_pages*ps, n_kv, hd].

    With ``k_scales``/``v_scales`` ([n_kv, P] per-PAGE dequant scales,
    kv_quant pools — kv_cache.quantize_kv_paged) the gathered quantized
    pages dequantize to ``dtype`` (default bf16) on the way out.  uint8
    pools are nibble-packed int4 (kv_cache.pack_int4): the gathered bytes
    unpack to the full head width before the scale multiply, so this stays
    the bit-exact oracle for the fused kernel's in-register dequant."""
    b, max_pages = block_tables.shape
    n_kv, _, ps, hd_store = k_pages.shape

    def gather(pages, scales):
        g = pages[:, block_tables]  # [n_kv, B, max_pages, ps, hd_store]
        g = jnp.moveaxis(g, 0, 3)  # [B, max_pages, ps, n_kv, hd_store]
        if pages.dtype == jnp.uint8:
            from githubrepostorag_tpu.serving.kv_cache import unpack_int4

            g = unpack_int4(g)  # [..., hd_store] uint8 -> [..., hd] int8
        g = g.reshape(b, max_pages * ps, n_kv, g.shape[-1])
        if scales is None:
            return g
        s = jnp.moveaxis(scales[:, block_tables], 0, 2)  # [B, mp, n_kv]
        s = jnp.repeat(s, ps, axis=1)  # page scale -> its ps token rows
        return (g.astype(jnp.float32) * s[..., None]).astype(dtype or jnp.bfloat16)

    return gather(k_pages, k_scales), gather(v_pages, v_scales)


def paged_attention_ref(q, k_pages, v_pages, block_tables, cached_lens, new_lens,
                        k_scales=None, v_scales=None):
    k, v = gather_kv(k_pages, v_pages, block_tables, k_scales, v_scales,
                     dtype=q.dtype)
    # The new tokens are already scattered into the pages before attention,
    # so the valid kv length is cached + new.
    return dense_attention(
        q,
        k,
        v,
        causal=True,
        q_offset=cached_lens,
        kv_lengths=cached_lens + new_lens,
    )
