"""RMSNorm (the Qwen2/Llama family normalization).

Computed in float32 regardless of input dtype — the variance accumulation
underflows in bfloat16 — then cast back before the weight multiply,
matching HF's Qwen2RMSNorm numerics so logits-parity tests against the
reference model hold.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return normed.astype(orig_dtype) * weight
