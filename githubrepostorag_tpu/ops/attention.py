"""Dense grouped-query attention (the reference path the Pallas paged kernel
is validated against, and the prefill path of the serving engine).

GQA is computed with a grouped einsum — Q heads are reshaped to
[n_kv, group] so K/V are never materialized repeated across the group, which
matters on TPU where HBM bandwidth is the bottleneck.
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = -1e30


def dense_attention(
    q: jnp.ndarray,  # [B, Sq, n_q, hd]
    k: jnp.ndarray,  # [B, Sk, n_kv, hd]
    v: jnp.ndarray,  # [B, Sk, n_kv, hd]
    *,
    causal: bool = True,
    q_offset: jnp.ndarray | int = 0,
    kv_lengths: jnp.ndarray | None = None,  # [B] valid kv length per seq
    kv_valid: jnp.ndarray | None = None,  # [B, Sk] bool — arbitrary validity
) -> jnp.ndarray:
    """Scaled-dot-product attention with causal masking and GQA.

    ``q_offset`` is the absolute position of q's first token within the kv
    sequence (decode: Sk-1 for a single new token; chunked prefill: the chunk
    start).  ``kv_lengths`` masks right-padded kv entries per batch row;
    ``kv_valid`` masks arbitrary kv entries (the decode burst's
    pool-prefix + staged-tail layout, where validity isn't a prefix).
    Returns [B, Sq, n_q, hd] in q.dtype; softmax in float32.
    """
    b, sq, n_q, hd = q.shape
    _, sk, n_kv, _ = k.shape
    group = n_q // n_kv
    scale = 1.0 / (hd ** 0.5)

    qg = q.reshape(b, sq, n_kv, group, hd)
    # [B, n_kv, g, Sq, Sk]
    scores = jnp.einsum("bsngh,btnh->bngst", qg.astype(jnp.float32), k.astype(jnp.float32))
    scores = scores * scale

    kv_pos = jnp.arange(sk)
    mask = jnp.zeros((b, 1, 1, sq, sk), dtype=bool)
    if causal:
        q_pos = jnp.arange(sq) + jnp.asarray(q_offset).reshape(-1, 1)  # [B or 1, Sq]
        causal_mask = kv_pos[None, None, :] > q_pos[:, :, None]  # [B or 1, Sq, Sk]
        mask = mask | causal_mask[:, None, None, :, :]
    if kv_lengths is not None:
        pad_mask = kv_pos[None, :] >= kv_lengths[:, None]  # [B, Sk]
        mask = mask | pad_mask[:, None, None, None, :]
    if kv_valid is not None:
        mask = mask | (~kv_valid)[:, None, None, None, :]
    scores = jnp.where(mask, NEG_INF, scores)

    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bngst,btnh->bsngh", probs, v.astype(jnp.float32))
    return out.reshape(b, sq, n_q, hd).astype(q.dtype)
