"""Pallas TPU paged-attention decode kernel (flash-decoding over the block
table).

In-tree replacement for the PagedAttention CUDA kernel vLLM brings to the
reference deployment (helm/templates/qwen-deployment.yaml).  One grid step
processes one (sequence, kv-head, page) triple: the page's K/V slab is
DMA'd into VMEM by the Pallas pipeline (double-buffered automatically via
the BlockSpec index map, which reads the *scalar-prefetched* block table),
scores for the kv-head's query group hit the MXU, and an online-softmax
accumulator in VMEM scratch carries (m, l, acc) across the page walk.
Nothing is ever materialized in HBM — the gather-based reference path
(ops/paged_attention.py) exists only as the correctness oracle.

Contract matches paged_attention_ref for the decode shape S == 1:
  q            [B, 1, n_q, hd]
  k_pages      [n_kv, P, page_size, hd]   (one layer's pool)
  v_pages      [n_kv, P, page_size, hd]
  block_tables [B, max_pages] int32
  cached_lens  [B] int32  (tokens in cache BEFORE this step)
  new_lens     [B] int32  (1 for active rows, 0 for padding rows)
Returns [B, 1, n_q, hd] in q.dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# JAX renamed TPUCompilerParams -> CompilerParams; support both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def _decode_kernel(
    # scalar prefetch
    block_tables_ref,  # [B, max_pages] SMEM
    total_lens_ref,  # [B] SMEM
    # blocks
    q_ref,  # [1, 1, group, hd] VMEM
    k_ref,  # [1, 1, page_size, hd] VMEM (one page, one kv head)
    v_ref,  # [1, 1, page_size, hd] VMEM
    out_ref,  # [1, 1, group, hd] VMEM
    # scratch
    m_ref,  # [group, 128] f32
    l_ref,  # [group, 128] f32
    acc_ref,  # [group, hd] f32
    *,
    page_size: int,
    scale: float,
):
    bi = pl.program_id(0)
    pi = pl.program_id(2)
    num_pi = pl.num_programs(2)

    @pl.when(pi == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    total = total_lens_ref[bi]  # valid kv length for this row
    page_start = pi * page_size

    @pl.when(page_start < total)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)  # [group, hd]
        k = k_ref[0, 0].astype(jnp.float32)  # [page_size, hd]
        v = v_ref[0, 0].astype(jnp.float32)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [group, page_size]
        kv_pos = page_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kv_pos < total, s, NEG_INF)

        m_prev = m_ref[:, :1]  # [group, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [group, page_size]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:, :1] = m_new
        l_ref[:, :1] = l_new

    @pl.when(pi == num_pi - 1)
    def _():
        # padding rows never hit the accumulate branch; guard the 0/0
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = (acc_ref[...] / safe_l).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention_decode(
    q: jnp.ndarray,  # [B, 1, n_q, hd]
    k_pages: jnp.ndarray,  # [n_kv, P, page_size, hd]
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_pages]
    cached_lens: jnp.ndarray,  # [B]
    new_lens: jnp.ndarray,  # [B]
    interpret: bool = False,
) -> jnp.ndarray:
    b, s, n_q, hd = q.shape
    assert s == 1, "pallas kernel is the decode path (S == 1)"
    n_kv, num_pages, page_size, _ = k_pages.shape
    group = n_q // n_kv
    max_pages = block_tables.shape[1]
    scale = 1.0 / (hd ** 0.5)

    total_lens = (cached_lens + new_lens).astype(jnp.int32)
    q_r = q.reshape(b, n_kv, group, hd)

    grid = (b, n_kv, max_pages)

    def q_map(bi, hi, pi, bt, tl):
        return (bi, hi, 0, 0)

    def kv_map(bi, hi, pi, bt, tl):
        # Clamp the walk to allocated pages: beyond the row's length the
        # kernel skips compute, so any valid page id works — reuse page 0.
        page = jax.lax.select(pi * page_size < tl[bi], bt[bi, pi], 0)
        return (hi, page, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, group, hd), q_map),
            pl.BlockSpec((1, 1, page_size, hd), kv_map),
            pl.BlockSpec((1, 1, page_size, hd), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, group, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, hd), jnp.float32),
        ],
    )

    kernel = functools.partial(_decode_kernel, page_size=page_size, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, group, hd), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), total_lens, q_r, k_pages, v_pages)

    return out.reshape(b, 1, n_q, hd)


def _decode_staged_kernel(
    *refs,
    page_size: int,
    scale: float,
    layered: bool = False,
    kv_quant: bool = False,
):
    """Decode-burst attention: online softmax over [pool-prefix pages |
    staged tail].  Grid (B, max_pages + 1): the first max_pages steps walk
    the row's block table for ALL kv heads at once (skipping pages past
    ``pool_lens``); the final step folds in the burst's staged K/V
    (positions < ``staged_len``) and writes the normalized output.  One
    grid step per (row, page) — not per (row, head, page) — keeps the
    kernel's fixed per-step cost off the decode critical path.

    Refs, in order: scalar prefetch [block_tables (B, max_pages) SMEM,
    pool_lens (B), staged_len (1), + layer (1) when ``layered``, + k/v
    per-PAGE scales (n_kv, P) f32 when ``kv_quant``], blocks
    [q (1, n_kv, group, hd) VMEM, k/v (one pool page, every kv head —
    leading extra 1 for the layer axis when ``layered``), staged k/v
    (1, n_kv, n_steps, hd)], out (1, n_kv, group, hd), scratch [m, l
    (n_kv, group, 128) f32, acc (n_kv, group, hd) f32].  ``kv_quant``:
    pool tiles are int8; each page's scale is read per kv head from the
    SMEM scalar channel (zero extra operand DMAs — per-token scale tiles
    measured 5-18x slower, r04) and dequant happens here in VMEM, right
    before the dots."""
    n_scalars = (4 if layered else 3) + (2 if kv_quant else 0)
    scalar_refs = refs[:n_scalars]
    block_tables_ref, pool_lens_ref, staged_len_ref = scalar_refs[:3]
    blocks = refs[n_scalars : n_scalars + 5]
    q_ref, k_ref, v_ref, sk_ref, sv_ref = blocks
    out_ref, m_ref, l_ref, acc_ref = refs[n_scalars + 5 :]
    if layered:
        raw_k = lambda: k_ref[0, :, 0]  # [n_kv, page_size, hd]
        raw_v = lambda: v_ref[0, :, 0]
    else:
        raw_k = lambda: k_ref[:, 0]
        raw_v = lambda: v_ref[:, 0]
    bi = pl.program_id(0)
    pi = pl.program_id(1)
    num_pi = pl.num_programs(1)
    if kv_quant:
        # per-PAGE scales ride the SCALAR-PREFETCH channel ([n_kv, P] f32
        # in SMEM, already layer-sliced by the wrapper) and are read as
        # per-head scalars — the r03 per-token scale TILES added two tiny
        # operand DMAs to every (row, page) grid step and measured 5-18x
        # slower than bf16 pools; int8 pages with SMEM scales run at bf16
        # speed + halved KV HBM (r04 isolation)
        ks_ref, vs_ref = scalar_refs[-2:]
        n_kv_heads = k_ref.shape[1] if layered else k_ref.shape[0]
        page = block_tables_ref[bi, jnp.minimum(pi, num_pi - 2)]

        def dequant(raw, ref):
            # per-head scalar-from-SMEM x [ps, hd] plane, restacked on the
            # leading axis (a [n_kv] vector reshaped to [n_kv,1,1] is an
            # unsupported Mosaic shape cast; scalar broadcasts are free)
            x = raw().astype(jnp.float32)
            return jnp.stack([x[h] * ref[h, page] for h in range(n_kv_heads)])

        k_page = lambda: dequant(raw_k, ks_ref)
        v_page = lambda: dequant(raw_v, vs_ref)
    else:
        k_page, v_page = raw_k, raw_v

    @pl.when(pi == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    total = pool_lens_ref[bi]
    page_start = pi * page_size

    # batched-over-heads dot: [n_kv, g, hd] x [n_kv, T, hd] -> [n_kv, g, T]
    bdot = lambda a, b: jax.lax.dot_general(
        a, b, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )
    # [n_kv, g, T] x [n_kv, T, hd] -> [n_kv, g, hd]
    pdot = lambda p, v: jax.lax.dot_general(
        p, v, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )

    def accumulate(s, vals):
        """Online-softmax update: s [n_kv, g, T] over vals [n_kv, T, hd]."""
        m_prev = m_ref[:, :, :1]
        l_prev = l_ref[:, :, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[:, :, :1] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + pdot(p, vals)
        m_ref[:, :, :1] = m_new

    @pl.when((pi < num_pi - 1) & (page_start < total))
    def _():
        q = q_ref[0].astype(jnp.float32)  # [n_kv, group, hd]
        k = k_page().astype(jnp.float32)  # [n_kv, page_size, hd]
        s = bdot(q, k) * scale  # [n_kv, group, page_size]
        kv_pos = page_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(kv_pos < total, s, NEG_INF)
        accumulate(s, v_page().astype(jnp.float32))

    @pl.when(pi == num_pi - 1)
    def _():
        q = q_ref[0].astype(jnp.float32)
        sk = sk_ref[0].astype(jnp.float32)  # [n_kv, n_steps, hd]
        s = bdot(q, sk) * scale  # [n_kv, group, n_steps]
        idx = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(idx < staged_len_ref[0], s, NEG_INF)
        accumulate(s, sv_ref[0].astype(jnp.float32))

        # staged_len >= 1 always, so l > 0 for every row incl. padding rows
        l = l_ref[:, :, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0] = (acc_ref[...] / safe_l).astype(out_ref.dtype)


def paged_attention_decode_staged(
    q: jnp.ndarray,  # [B, 1, n_q, hd]
    k_pages: jnp.ndarray,  # [n_kv, P, ps, hd] or [L, n_kv, P, ps, hd] pool
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_pages]
    pool_lens: jnp.ndarray,  # [B] — valid pool-prefix tokens per row
    staged_k: jnp.ndarray,  # [B, n_kv, n_steps, hd] — burst staging buffer
    staged_v: jnp.ndarray,
    staged_len: jnp.ndarray,  # [1] int32 — staged entries valid this step
    layer: jnp.ndarray | None = None,  # [] / [1] int32, REQUIRED for rank-5
    k_scales: jnp.ndarray | None = None,  # per-PAGE dequant scales (int8
    v_scales: jnp.ndarray | None = None,  # pools): [(L,) n_kv, P] f32
    interpret: bool = False,
) -> jnp.ndarray:
    """Burst-decode attention over [pool prefix | staged tail] without ever
    materializing the gathered KV in HBM (replaces gather_kv+dense in
    serving/decode_burst.py).  Not jitted — always called inside the burst's
    compiled program.

    Rank-5 pools + ``layer``: the burst's layer loop passes the WHOLE
    [L, n_kv, P, ps, hd] pool and the current layer index as a prefetched
    scalar — the BlockSpec index map addresses (layer, head, page)
    directly, so no per-layer pool slice is ever materialized.  Device
    profiling showed the sliced form costing ~0.5 ms/step at 0.5B/bs8
    (2 x 4 MB x 24 layers of dynamic-slice copy traffic per decode step).

    ``k_scales``/``v_scales`` mark int8 (kv_quant) pools: page tiles
    arrive int8 and dequantize in VMEM right before the dots with their
    per-PAGE scale read from the scalar-prefetch SMEM channel — KV HBM
    reads halve at zero extra operand DMAs (per-token scale tiles
    measured 5-18x slower, r04); the staged tail stays full precision."""
    b, s, n_q, hd = q.shape
    assert s == 1, "staged kernel is the decode path (S == 1)"
    layered = k_pages.ndim == 5
    kv_quant = k_scales is not None
    if layered:
        assert layer is not None, "rank-5 pools need the layer index"
        n_kv, num_pages, page_size, _ = k_pages.shape[1:]
    else:
        n_kv, num_pages, page_size, _ = k_pages.shape
    group = n_q // n_kv
    max_pages = block_tables.shape[1]
    scale = 1.0 / (hd ** 0.5)
    q_r = q.reshape(b, n_kv, group, hd)

    grid = (b, max_pages + 1)

    def q_map(bi, pi, *refs):
        return (bi, 0, 0, 0)

    def clamp_page(bi, pi, bt, pool):
        # Clamp the walk to allocated pages; the staged grid step and pages
        # past the row's prefix skip compute, so any valid page id works.
        pp = jnp.minimum(pi, max_pages - 1)
        return jax.lax.select(
            (pi < max_pages) & (pi * page_size < pool[bi]), bt[bi, pp], 0
        )

    if layered:
        def kv_map(bi, pi, bt, pool, sl, *rest):
            return (rest[0][0], 0, clamp_page(bi, pi, bt, pool), 0, 0)

        kv_block = (1, n_kv, 1, page_size, hd)
    else:
        def kv_map(bi, pi, bt, pool, sl, *rest):
            return (0, clamp_page(bi, pi, bt, pool), 0, 0)

        kv_block = (n_kv, 1, page_size, hd)

    def staged_map(bi, pi, *refs):
        return (bi, 0, 0, 0)

    n_steps = staged_k.shape[2]
    scalars = [
        block_tables.astype(jnp.int32),
        pool_lens.astype(jnp.int32),
        staged_len.astype(jnp.int32),
    ]
    if layered:
        scalars.append(jnp.reshape(layer, (1,)).astype(jnp.int32))
    if kv_quant:
        # per-page scales [n_kv, P] join the SCALAR-PREFETCH channel (SMEM,
        # like the block tables): zero extra per-grid-step operand DMAs.
        # Layer-sliced here — a [n_kv, P] f32 slice is ~KBs, not a pool copy
        ks, vs = k_scales, v_scales
        if layered:
            li = jnp.reshape(layer, ()).astype(jnp.int32)
            ks = jax.lax.dynamic_index_in_dim(ks, li, 0, keepdims=False)
            vs = jax.lax.dynamic_index_in_dim(vs, li, 0, keepdims=False)
        scalars += [ks.astype(jnp.float32), vs.astype(jnp.float32)]
    in_specs = [
        pl.BlockSpec((1, n_kv, group, hd), q_map),
        pl.BlockSpec(kv_block, kv_map),
        pl.BlockSpec(kv_block, kv_map),
        pl.BlockSpec((1, n_kv, n_steps, hd), staged_map),
        pl.BlockSpec((1, n_kv, n_steps, hd), staged_map),
    ]
    operands = [q_r, k_pages, v_pages, staged_k, staged_v]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, n_kv, group, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((n_kv, group, 128), jnp.float32),
            pltpu.VMEM((n_kv, group, 128), jnp.float32),
            pltpu.VMEM((n_kv, group, hd), jnp.float32),
        ],
    )

    kernel = functools.partial(
        _decode_staged_kernel, page_size=page_size, scale=scale,
        layered=layered, kv_quant=kv_quant,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, group, hd), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*scalars, *operands)

    return out.reshape(b, 1, n_q, hd)


def paged_attention(q, k_pages, v_pages, block_tables, cached_lens, new_lens):
    """Dispatcher with the paged_attention_ref contract: Pallas for decode
    steps, gather+dense for prefill chunks (S > 1)."""
    from githubrepostorag_tpu.ops.paged_attention import paged_attention_ref

    if q.shape[1] == 1:
        interpret = jax.default_backend() != "tpu"
        return paged_attention_decode(
            q, k_pages, v_pages, block_tables, cached_lens, new_lens, interpret=interpret
        )
    return paged_attention_ref(q, k_pages, v_pages, block_tables, cached_lens, new_lens)
