"""Segment-ID packed prefill attention over the page-pool KV cache.

The padded prefill dispatch ([row_bucket, width] with every row padded to
the widest pending chunk) burns compute on padding whenever a wave is
heterogeneous — short uncached suffixes after prefix-cache hits, tail
chunks, mixed prompt lengths.  The packed path flattens every prefilling
row's next chunk into ONE fixed-size [budget] token buffer with per-token
segment IDs, so dense-layer FLOPs (projections/MLP — the bulk of prefill
compute) scale with real tokens instead of rows x max-chunk.

Attention itself still needs per-segment causal structure, so the op
internally re-pads the packed queries to a segment-major [R, tq] view
(scatter by ``seg_ids * tq + in_chunk_index``; tq = the static per-segment
chunk cap) and masks with each segment's cached/new lengths:

  - XLA reference path: gather the block-table pages to a contiguous view
    and run ``dense_attention`` — exactly the padded path's oracle, so
    parity with ``paged_attention_ref`` is structural.
  - Pallas path: a flash-prefill kernel that walks the block table page by
    page in VMEM with an online-softmax accumulator, computing the causal
    mask from the scalar-prefetched cached/total lengths.  Nothing is
    materialized in HBM — at 1k-2k-token prompts the per-layer
    [R, max_pages*ps, n_kv, hd] gather is the dominant HBM cost of the
    reference path.

Contract:
  q            [T, n_q, hd]    — packed new-token queries (T = token budget)
  k_pages      [n_kv, P, page_size, hd] — this layer's pool (post-commit:
               the packed chunk's K/V are already scattered in)
  v_pages      [n_kv, P, page_size, hd]
  block_tables [R, max_pages] int32 — page ids per segment
  cached_lens  [R] int32 — tokens in cache BEFORE this chunk, per segment
  new_lens     [R] int32 — valid new tokens this chunk, per segment
  seg_ids      [T] int32 — owning segment per packed token; >= R marks
               padding tokens (they drop out of the segment view)
  positions    [T] int32 — absolute sequence position per packed token
               (token t sits at in-chunk index positions[t] -
               cached_lens[seg_ids[t]], always < tq)
Returns [T, n_q, hd] in q.dtype.  Padding tokens get finite garbage —
their K/V never committed (slot -1) and their logits are never read.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from githubrepostorag_tpu.ops.attention import dense_attention
from githubrepostorag_tpu.ops.paged_attention import gather_kv

NEG_INF = -1e30

# JAX renamed TPUCompilerParams -> CompilerParams; support both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def _segment_scatter_indices(seg_ids, positions, cached_lens, tq):
    """Destination row in the segment-major [R*tq] view for every packed
    token.  Padding tokens (seg >= R) map to the out-of-range sentinel
    R*tq, which a mode="drop" scatter discards (JAX scatter *wraps*
    negative indices, so the sentinel must be explicit and positive)."""
    r = cached_lens.shape[0]
    cached_ext = jnp.concatenate(
        [cached_lens.astype(jnp.int32), jnp.zeros((1,), jnp.int32)]
    )
    seg_c = jnp.minimum(seg_ids, r)
    in_chunk = positions - cached_ext[seg_c]
    return jnp.where(seg_ids >= r, r * tq, seg_c * tq + in_chunk)


def _packed_prefill_kernel(
    # scalar prefetch
    block_tables_ref,  # [R, max_pages] SMEM
    cached_lens_ref,  # [R] SMEM
    total_lens_ref,  # [R] SMEM
    # blocks
    q_ref,  # [1, 1, group, tq, hd] VMEM (one segment, one kv head)
    k_ref,  # [1, 1, page_size, hd] VMEM (one page, one kv head)
    v_ref,  # [1, 1, page_size, hd] VMEM
    out_ref,  # [1, 1, group, tq, hd] VMEM
    # scratch
    m_ref,  # [group, tq, 128] f32
    l_ref,  # [group, tq, 128] f32
    acc_ref,  # [group, tq, hd] f32
    *,
    page_size: int,
    scale: float,
):
    bi = pl.program_id(0)
    pi = pl.program_id(2)
    num_pi = pl.num_programs(2)

    @pl.when(pi == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    cached = cached_lens_ref[bi]  # chunk start == each q row's base position
    total = total_lens_ref[bi]  # valid kv length for this segment
    page_start = pi * page_size

    @pl.when(page_start < total)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)  # [group, tq, hd]
        k = k_ref[0, 0].astype(jnp.float32)  # [page_size, hd]
        v = v_ref[0, 0].astype(jnp.float32)

        s = jax.lax.dot_general(
            q, k, (((2,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [group, tq, page_size]
        # causal within the segment: q row ti sits at absolute position
        # cached + ti; kv beyond the segment's valid length is padding
        kv_pos = page_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        q_pos = cached + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where((kv_pos <= q_pos) & (kv_pos < total), s, NEG_INF)

        m_prev = m_ref[:, :, :1]  # [group, tq, 1]
        l_prev = l_ref[:, :, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [group, tq, page_size]
        l_ref[:, :, :1] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((2,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:, :, :1] = m_new

    @pl.when(pi == num_pi - 1)
    def _():
        # bucket-padding segments (total == 0) never hit the accumulate
        # branch; guard the 0/0
        l = l_ref[:, :, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = (acc_ref[...] / safe_l).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def packed_prefill_attention_seg(
    q_seg: jnp.ndarray,  # [R, tq, n_q, hd] segment-major queries
    k_pages: jnp.ndarray,  # [n_kv, P, page_size, hd]
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [R, max_pages]
    cached_lens: jnp.ndarray,  # [R]
    new_lens: jnp.ndarray,  # [R]
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas flash-prefill over the segment-major view: grid
    (R, n_kv, max_pages), one page's K/V slab in VMEM per step, online
    softmax across the page walk.  Matches ``dense_attention`` over the
    gathered pages (the reference path below) bit-for-bit in structure."""
    r, tq, n_q, hd = q_seg.shape
    n_kv, num_pages, page_size, _ = k_pages.shape
    group = n_q // n_kv
    max_pages = block_tables.shape[1]
    scale = 1.0 / (hd ** 0.5)

    total_lens = (cached_lens + new_lens).astype(jnp.int32)
    # [R, tq, n_kv, group, hd] -> [R, n_kv, group, tq, hd]: one kv head's
    # whole query group rides each grid step's MXU dots
    q_r = q_seg.reshape(r, tq, n_kv, group, hd).transpose(0, 2, 3, 1, 4)

    grid = (r, n_kv, max_pages)

    def q_map(bi, hi, pi, bt, cl, tl):
        return (bi, hi, 0, 0, 0)

    def kv_map(bi, hi, pi, bt, cl, tl):
        # Clamp the walk to allocated pages: beyond the segment's length
        # the kernel skips compute, so any valid page id works — page 0.
        page = jax.lax.select(pi * page_size < tl[bi], bt[bi, pi], 0)
        return (hi, page, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, group, tq, hd), q_map),
            pl.BlockSpec((1, 1, page_size, hd), kv_map),
            pl.BlockSpec((1, 1, page_size, hd), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, group, tq, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((group, tq, 128), jnp.float32),
            pltpu.VMEM((group, tq, 128), jnp.float32),
            pltpu.VMEM((group, tq, hd), jnp.float32),
        ],
    )

    kernel = functools.partial(
        _packed_prefill_kernel, page_size=page_size, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((r, n_kv, group, tq, hd), q_seg.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), cached_lens.astype(jnp.int32),
      total_lens, q_r, k_pages, v_pages)

    # [R, n_kv, group, tq, hd] -> [R, tq, n_q, hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(r, tq, n_q, hd)


def packed_prefill_attention(
    q: jnp.ndarray,  # [T, n_q, hd] packed queries
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [R, max_pages]
    cached_lens: jnp.ndarray,  # [R]
    new_lens: jnp.ndarray,  # [R]
    seg_ids: jnp.ndarray,  # [T]
    positions: jnp.ndarray,  # [T]
    *,
    tq: int,  # static per-segment chunk cap (min(prefill_chunk, budget))
    use_pallas: bool = False,
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Packed-buffer entry point (see module docstring for the contract).

    Scatters the packed queries into the segment-major [R, tq] view, runs
    segment-masked attention there (Pallas when ``use_pallas``: the seg
    kernel for full-precision pools, the fused window kernel
    (ops/fused_decode.py) for quantized pools — int8/int4 pages
    dequantize in-register instead of taking the materialized gather
    path), and gathers the outputs back to packed order."""
    t, n_q, hd = q.shape
    r = block_tables.shape[0]
    quant = k_scales is not None
    if use_pallas and quant:
        from githubrepostorag_tpu.ops.fused_decode import fused_packed_attention

        return fused_packed_attention(
            q, k_pages, v_pages, block_tables, cached_lens, new_lens,
            seg_ids, positions, tq=tq, k_scales=k_scales, v_scales=v_scales,
        )
    dest = _segment_scatter_indices(seg_ids, positions, cached_lens, tq)
    q_seg = (
        jnp.zeros((r * tq, n_q, hd), q.dtype)
        .at[dest].set(q, mode="drop")
        .reshape(r, tq, n_q, hd)
    )
    if use_pallas and not quant:
        interpret = jax.default_backend() != "tpu"
        out_seg = packed_prefill_attention_seg(
            q_seg, k_pages, v_pages, block_tables, cached_lens, new_lens,
            interpret=interpret,
        )
    else:
        k, v = gather_kv(k_pages, v_pages, block_tables, k_scales, v_scales,
                         dtype=q.dtype)
        out_seg = dense_attention(
            q_seg, k, v,
            causal=True,
            q_offset=cached_lens,
            kv_lengths=cached_lens + new_lens,
        )
    # gather back to packed order; padding tokens read a clamped garbage
    # row (finite — never committed to KV, never projected to logits)
    flat = out_seg.reshape(r * tq, n_q, hd)
    return flat[jnp.clip(dest, 0, r * tq - 1)]


def ring_segment_layout(lens: list[int], width: int, rb: int):
    """Host-side layout of a segment-packed RING buffer: whole prompts back
    to back (the ring path always runs from position 0, so unlike the
    chunked contract above there are no cached prefixes — in-segment index
    IS the RoPE position).  Returns numpy arrays sized for the compiled
    ring program:

      seg       [width] int32 — owning segment per token; rb (the fixed
                segment-row bucket) marks padding
      positions [width] int32 — restarting at 0 per segment
      logits_at [rb]    int32 — each segment's last-token index into the
                flat buffer; rows past len(lens) point at 0 (ignored)
      starts    [len(lens)] int32 — each segment's first-token offset

    Shared by the engine's packed dispatch and its tests/bench so the
    buffer layout can never fork between them."""
    import numpy as np

    assert sum(lens) <= width and len(lens) <= rb
    seg = np.full((width,), rb, dtype=np.int32)
    positions = np.zeros((width,), dtype=np.int32)
    logits_at = np.zeros((rb,), dtype=np.int32)
    starts = np.zeros((len(lens),), dtype=np.int32)
    off = 0
    for i, n in enumerate(lens):
        seg[off : off + n] = i
        positions[off : off + n] = np.arange(n)
        logits_at[i] = off + n - 1
        starts[i] = off
        off += n
    return seg, positions, logits_at, starts
