"""Request/response DTOs shared by the API, worker, and agent.

Parity with rag_shared/models.py:6-14 in the reference, with the schema drift
it had fixed: the reference's QueryRequest carried ``top_k``/``repo_name``
while the worker read ``force_level``/``namespace`` from the raw dict
(worker.py:101-107).  Here every field the pipeline actually consumes is
declared.
"""

from __future__ import annotations

from typing import Any, Optional

from pydantic import BaseModel, Field


class QueryRequest(BaseModel):
    query: str
    top_k: Optional[int] = 5
    repo_name: Optional[str] = None
    namespace: Optional[str] = None
    force_level: Optional[str] = None  # catalog|repo|module|file|chunk
    # wall-clock budget for the whole job; clamped to JOB_TIMEOUT_SECONDS
    # server-side and propagated API -> worker -> agent -> engine
    deadline_ms: Optional[int] = None
    # SLO priority class (None -> PRIORITY_DEFAULT_CLASS).  Unknown strings
    # are just new classes; propagated API -> worker -> agent -> engine,
    # where it drives per-class admission, headroom, and preemption
    priority: Optional[str] = None


class RAGResponse(BaseModel):
    answer: str
    sources: Optional[list[dict[str, Any]]] = None


class IngestRequest(BaseModel):
    components: list[str] = Field(default_factory=list)
    namespace: str = "default"
    branch: Optional[str] = None
