"""Weight-only int8 quantization for the decoder.

Fills the role AWQ fills in the reference deployment (vLLM serves
Qwen2.5-Coder-7B-Instruct-AWQ on an 8 GB GPU — helm/values.yaml:67): a 7B
bf16 checkpoint (~15.2 GB) does not fit a 16 GB v5e chip next to its KV
pools, but int8 weights (~7.6 GB) do.  Decode is HBM-bandwidth-bound, so
halving weight bytes is also the main single-chip speed lever.

Scheme: per-output-channel symmetric int8 —
    scale[o] = max_i |W[i, o]| / 127        (bf16 scales)
    W_q[i, o] = round(W[i, o] / scale[o])   (int8)
Quantized tensors are ``QuantizedLinear(q, s)`` pytree nodes; matmuls go
through :func:`qmatmul`, which dequantizes inside the XLA program — the
convert+scale fuses into the dot's operand read on TPU (measured ~590 GB/s
effective weight bandwidth for 7B decode, i.e. no materialized bf16 copy),
so no hand-written dequant kernel is needed.

The embedding table quantizes too (per-ROW scales — ``quantize_embedding``):
a tied-weight model reads it in full every decode step for logits, so at
0.5B it is ~27 % of per-step weight traffic.  Token lookups go through
``embedding_lookup`` (gather int8 rows, scale per row).  Norms and biases
stay bf16.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedLinear(NamedTuple):
    """Weight-only int8 projection: ``q`` int8 [.., in, out], ``s`` bf16
    per OUTPUT channel [.., out]."""

    q: jnp.ndarray
    s: jnp.ndarray


class QuantizedEmbedding(NamedTuple):
    """Weight-only int8 embedding table: ``q`` int8 [V, d], ``s`` bf16 per
    vocab ROW [V].  A distinct type from QuantizedLinear because the scale
    axis differs — generic linear consumers (qmatmul/dequantize) must not
    silently apply row scales as column scales."""

    q: jnp.ndarray
    s: jnp.ndarray


class QuantizedLinear4(NamedTuple):
    """Weight-only 4-bit projection (the AWQ-class scheme the reference
    actually deploys — vLLM serves Qwen2.5-Coder-7B-Instruct-AWQ,
    /root/reference/helm/values.yaml:67).  Group-wise ASYMMETRIC uint4:

        w[i, o] ≈ q[i, o] * s[g(i), o] - zs[g(i), o]

    with g(i) = i // group_size over the INPUT axis — matching AWQ's
    group-128/64 geometry (scales+zeros per input group per output channel).

    ``q`` packs two nibbles per byte plane-wise WITHIN each group: for
    group g of size gsz, byte row j holds original rows (g*gsz + j) in the
    low nibble and (g*gsz + j + gsz/2) in the high nibble.  Unpacking is
    two shifts + one concat on the in-group axis — no interleave/transpose
    — so XLA fuses the dequant into the consuming dot's operand stream
    like the int8 path.  Packing within groups (not across the whole input
    axis) keeps row-parallel TP shards self-contained: any shard boundary
    that lands on a group boundary owns whole groups of bytes AND their
    scales, so GSPMD never has to redistribute the dequantized weight.

    Fields: ``q`` uint8 [.., in/2, out]; ``s`` bf16 [.., in/group, out];
    ``zs`` bf16 [.., in/group, out] with dequant ``w = q*s - zs``
    (zs = -group_min; storing the product form makes dequant a fused
    multiply-subtract)."""

    q: jnp.ndarray
    s: jnp.ndarray
    zs: jnp.ndarray


def _quantize_symmetric(w, axis: int):
    """Shared symmetric-int8 recipe: reduce |w| over ``axis``, scale to
    127, round/clip, bf16 scales with the reduced axis squeezed out.

    Computed HOST-side in numpy: quantizing a 7B tree with eager device ops
    would transiently materialize ~15 GB of f32 on the 16 GB chip this
    feature exists to fit — only the int8 weights and bf16 scales ever
    reach the device."""
    import ml_dtypes
    import numpy as np

    w_np = np.asarray(w, dtype=np.float32)  # pulls device arrays to host
    amax = np.max(np.abs(w_np), axis=axis, keepdims=True)
    scale = np.maximum(amax / 127.0, 1e-8)
    q = np.clip(np.round(w_np / scale), -127, 127).astype(np.int8)
    s = np.squeeze(scale, axis=axis).astype(ml_dtypes.bfloat16)
    return jnp.asarray(q), jnp.asarray(s)


def quantize_weight(w) -> QuantizedLinear:
    """Per-output-channel symmetric int8.  ``w`` is [in, out] or stacked
    [L, in, out]; the input (reduction) axis is -2, so scales are [out] /
    [L, out]."""
    q, s = _quantize_symmetric(w, axis=-2)
    return QuantizedLinear(q=q, s=s)


def dequant_weight(w, dtype) -> jnp.ndarray:
    """Compute-dtype view of a maybe-quantized linear weight.  THE one
    definition of the int8/int4->dtype expression — every consumer
    (qmatmul, the MoE expert einsums, dequantize) routes through here so a
    scheme change cannot silently miss a path.  XLA fuses the
    convert+scale into the consuming dot's operand stream on TPU; no bf16
    copy is materialized for the common shapes."""
    if isinstance(w, QuantizedLinear):
        return w.q.astype(dtype) * w.s.astype(dtype)[..., None, :]
    if isinstance(w, QuantizedLinear4):
        lead, out = w.q.shape[:-2], w.q.shape[-1]
        n_g = w.s.shape[-2]
        in_half = w.q.shape[-2]  # in/2 packed byte rows
        half_g = in_half // n_g  # gsz/2 byte rows per group
        pg = w.q.reshape(*lead, n_g, half_g, out)
        lo = (pg & jnp.uint8(0xF)).astype(dtype)
        hi = (pg >> jnp.uint8(4)).astype(dtype)
        grouped = jnp.concatenate([lo, hi], axis=-2)  # [.., n_g, gsz, out]
        wf = (
            grouped * w.s[..., :, None, :].astype(dtype)
            - w.zs[..., :, None, :].astype(dtype)
        )
        return wf.reshape(*lead, 2 * in_half, out)
    return w


def dequantize(t, dtype=jnp.bfloat16) -> jnp.ndarray:
    """Full-precision reconstruction (f32 math, then cast) for tests."""
    if isinstance(t, QuantizedLinear4):
        return dequant_weight(
            QuantizedLinear4(
                q=t.q, s=t.s.astype(jnp.float32), zs=t.zs.astype(jnp.float32)
            ),
            jnp.float32,
        ).astype(dtype)
    return dequant_weight(
        QuantizedLinear(q=t.q, s=t.s.astype(jnp.float32)), jnp.float32
    ).astype(dtype)


def quantize_weight4(w, group_size: int = 64) -> QuantizedLinear4:
    """Group-wise asymmetric uint4 (AWQ-class).  ``w`` is [in, out] or
    stacked [.., in, out]; groups of ``group_size`` run along the input
    axis.  64 (not AWQ's usual 128) is the default because every Qwen2
    in-dimension splits into 64-token groups that stay whole under tp<=8
    row-parallel sharding.  Host-side numpy like _quantize_symmetric (a 7B
    tree must never materialize in f32 on the device being quantized for)."""
    import ml_dtypes
    import numpy as np

    w_np = np.asarray(w, dtype=np.float32)
    in_dim, out = w_np.shape[-2], w_np.shape[-1]
    if group_size % 2 or in_dim % group_size:
        raise ValueError(
            f"input dim {in_dim} must be divisible by the (even) group_size "
            f"{group_size} for in-group nibble plane packing"
        )
    lead = w_np.shape[:-2]
    n_g, half = in_dim // group_size, group_size // 2
    grouped = w_np.reshape(*lead, n_g, group_size, out)
    mx = grouped.max(axis=-2, keepdims=True)
    mn = grouped.min(axis=-2, keepdims=True)
    scale = np.maximum((mx - mn) / 15.0, 1e-8)
    # w ≈ q*scale + mn, i.e. zs = -mn.  Unlike AWQ's nibble-stored zeros,
    # zs is bf16, so no [0,15] clamp: one-sided groups (all-positive mn>0)
    # keep their full range instead of saturating at nibble 15.
    q = np.clip(np.round((grouped - mn) / scale), 0, 15).astype(np.uint8)
    packed = (q[..., :half, :] | (q[..., half:, :] << 4)).reshape(
        *lead, in_dim // 2, out
    )
    s = np.squeeze(scale, axis=-2).astype(ml_dtypes.bfloat16)
    zs = np.squeeze(-mn, axis=-2).astype(ml_dtypes.bfloat16)
    return QuantizedLinear4(q=jnp.asarray(packed), s=jnp.asarray(s), zs=jnp.asarray(zs))


def q4_matmul(x: jnp.ndarray, w: "QuantizedLinear4", preferred=None) -> jnp.ndarray:
    """``x @ dequant(w)`` as TWO dots plus a zero-point correction, never
    materializing the unpacked weight:

        y = x_lo @ (lo(q)*s) + x_hi @ (hi(q)*s) - (Σ_j x)[g] @ zs[g]

    where lo/hi are the in-group nibble planes and x splits the same way.
    The nibble mask/shift and the group-scale multiply are ELEMENTWISE on
    a dot operand — XLA fuses them into the operand stream exactly like
    the int8 convert+scale.  The concat form (dequant_weight) does not
    reliably fuse: measured 0.21 ms vs 0.06 ms per [32,3584]x[3584,18944]
    matmul on v5e (int8: 0.49 ms) — this formulation is what makes int4
    HALVE the decode weight-read time instead of tripling it.

    ``w`` leaves must be unstacked ([in/2, out]); stacked layers arrive
    here sliced by the layer scan.  ``preferred``: accumulation dtype for
    the dots (float32 for logits)."""
    lead = x.shape[:-1]
    in_dim = x.shape[-1]
    n_g = w.s.shape[-2]
    out = w.q.shape[-1]
    gsz = in_dim // n_g
    half = gsz // 2
    dt = x.dtype
    pg = w.q.reshape(n_g, half, out)
    s = w.s[:, None, :].astype(dt)
    lo = (pg & jnp.uint8(0xF)).astype(dt) * s
    hi = (pg >> jnp.uint8(4)).astype(dt) * s
    xg = x.reshape(*lead, n_g, gsz)
    x_lo, x_hi = xg[..., :half], xg[..., half:]
    kw = {} if preferred is None else {"preferred_element_type": preferred}
    y = (
        jnp.einsum("...gj,gjo->...o", x_lo, lo, **kw)
        + jnp.einsum("...gj,gjo->...o", x_hi, hi, **kw)
        - jnp.einsum("...g,go->...o", xg.sum(axis=-1), w.zs.astype(dt), **kw)
    )
    return y


class Layered4(NamedTuple):
    """A per-layer VIEW into stacked int4 weights: the full [L, in/2, out]
    arrays plus the current layer index.  The layer loops of the decode
    burst and the paged forward build these instead of letting the scan
    slice quantized leaves — the Pallas GEMM then indexes (layer, tile)
    directly and no per-layer weight copy is ever materialized (the same
    discipline as the rank-5 KV pools)."""

    q: jnp.ndarray  # [L, in/2, out] uint8
    s: jnp.ndarray  # [L, n_g, out] bf16
    zs: jnp.ndarray  # [L, n_g, out] bf16
    layer: jnp.ndarray  # scalar int32
    # W4A8 routing hint: None = auto (decode-sized batches take the MXU
    # int8 path), False = force exact bf16-dequant — the prefill/verify
    # paths pin False so an engine whose prefill_chunk is decode-sized
    # never silently relaxes the prompt-processing accuracy contract
    w4a8: bool | None = None


class Layered4XLA(NamedTuple):
    """Layered4's XLA-route twin: same fields, but ``qmatmul`` lowers it
    through the two-dot einsum formulation instead of the Pallas kernel.
    Used when the weights are GSPMD-sharded (TP meshes): a pallas_call is
    an opaque custom call with no partitioning rule, so GSPMD would have
    to all-gather the sharded weight stacks to feed it — the einsum path
    partitions normally."""

    q: jnp.ndarray
    s: jnp.ndarray
    zs: jnp.ndarray
    layer: jnp.ndarray


def _use_pallas_int4() -> bool:
    return jax.default_backend() == "tpu"


def q4_dispatch(x, q, s, zs, layer=None, out_dtype=None, kernel: bool = True,
                w4a8: bool | None = None):
    """THE int4 matmul router (every consumer — qmatmul, _logits — goes
    through here): Pallas GEMM on TPU when ``kernel`` (W4A8 MXU-int8 route
    for decode-sized batches, exact bf16-dequant otherwise — see
    ``int4_matmul``), else the two-dot XLA formulation."""
    if kernel and _use_pallas_int4():
        from githubrepostorag_tpu.ops.pallas_int4 import int4_matmul

        return int4_matmul(x, q, s, zs, layer=layer, out_dtype=out_dtype,
                           w4a8=w4a8)
    if layer is not None:
        sl = lambda a: jax.lax.dynamic_index_in_dim(a, layer, 0, keepdims=False)
        q, s, zs = sl(q), sl(s), sl(zs)
    preferred = out_dtype if out_dtype is not None and out_dtype != x.dtype else None
    y = q4_matmul(x, QuantizedLinear4(q, s, zs), preferred=preferred)
    return y if out_dtype is None else y.astype(out_dtype)


def _split_q4(layers: dict) -> tuple[dict, dict]:
    """Partition a layer-param dict into (scan-sliceable leaves, stacked
    int4 stacks).  Layer loops scan the first and view the second through
    ``Layered4`` at each index — see ``qmatmul``."""
    q4 = {k: v for k, v in layers.items() if isinstance(v, QuantizedLinear4)}
    rest = {k: v for k, v in layers.items() if k not in q4}
    return rest, q4


def _with_layered_q4(p: dict, q4_stacks: dict, layer, kernel: bool = True,
                     w4a8: bool | None = None) -> dict:
    """Per-layer param dict = sliced leaves + Layered4 views at ``layer``.
    ``kernel=False`` (TP-sharded weights) builds the XLA-route twin —
    see Layered4XLA.  ``w4a8`` is the routing hint carried into each view
    (decode burst: auto; prefill/verify: False)."""
    if not q4_stacks:
        return p
    out = dict(p)
    for k, v in q4_stacks.items():
        if kernel:
            out[k] = Layered4(q=v.q, s=v.s, zs=v.zs, layer=layer, w4a8=w4a8)
        else:
            out[k] = Layered4XLA(q=v.q, s=v.s, zs=v.zs, layer=layer)
    return out


def qmatmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` where ``w`` is a plain array, QuantizedLinear (int8),
    QuantizedLinear4 (int4), or Layered4 (stacked int4 + layer index).

    int8 dequant fuses into the dot's operand read under XLA.  int4 does
    NOT (the unpack chain materializes — see ops/pallas_int4.py), so on
    TPU int4 routes to the Pallas in-VMEM-dequant GEMM; elsewhere to the
    two-dot XLA formulation (q4_matmul), which is also the kernel's
    correctness oracle."""
    if isinstance(w, Layered4):
        return q4_dispatch(x, w.q, w.s, w.zs, layer=w.layer, w4a8=w.w4a8)
    if isinstance(w, Layered4XLA):
        return q4_dispatch(x, w.q, w.s, w.zs, layer=w.layer, kernel=False)
    if isinstance(w, QuantizedLinear4):
        return q4_dispatch(x, w.q, w.s, w.zs)
    return x @ dequant_weight(w, x.dtype)


def quantize_embedding(w) -> QuantizedEmbedding:
    """Per-ROW symmetric int8 for the embedding table [V, d]: each vocab row
    is one channel, so the tied-weight logits contraction over d dequantizes
    per output logit, and the token-lookup path is ``q[ids] * s[ids]``."""
    q, s = _quantize_symmetric(w, axis=-1)
    return QuantizedEmbedding(q=q, s=s)


def embedding_lookup(embed, ids: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    """Token embedding gather for plain or int8 tables."""
    if isinstance(embed, QuantizedEmbedding):
        rows = jnp.take(embed.q, ids, axis=0).astype(dtype)
        return rows * jnp.take(embed.s, ids, axis=0)[..., None].astype(dtype)
    return jnp.take(embed, ids, axis=0)


def _concat_linears(ws, biases=None):
    """Concatenate same-input linear leaves along the OUTPUT axis — valid
    for plain arrays and both quantized schemes, because every per-output
    quantity (int8 q columns + per-channel s; int4 packed columns +
    per-group s/zs) concatenates on its last axis while the input-axis
    structure (rows, nibble plane packing, group boundaries) is untouched."""
    w0 = ws[0]
    if isinstance(w0, QuantizedLinear):
        w = QuantizedLinear(
            q=jnp.concatenate([x.q for x in ws], axis=-1),
            s=jnp.concatenate([x.s for x in ws], axis=-1),
        )
    elif isinstance(w0, QuantizedLinear4):
        w = QuantizedLinear4(
            q=jnp.concatenate([x.q for x in ws], axis=-1),
            s=jnp.concatenate([x.s for x in ws], axis=-1),
            zs=jnp.concatenate([x.zs for x in ws], axis=-1),
        )
    else:
        w = jnp.concatenate(ws, axis=-1)
    if biases is None:
        return w
    return w, jnp.concatenate(biases, axis=-1)


def fuse_projections(params: dict, in_place: bool = False) -> dict:
    """Single-chip serving layout transform: fuse wq|wk|wv -> wqkv and
    wg|wu -> wgu so each decode step runs 4 projection matmuls per layer
    instead of 7.  Device profiling (round 4) showed ~60 us of fixed
    per-matmul cost at 7B decode shapes — the three sub-10 MB projections
    (wk/wv at 0.9 MB int4) were pure overhead; fusing also widens the
    quantized-GEMM tiles.  The model block detects the fused keys
    (qwen2._block) and splits activations after the matmul, which is a
    free lane slice.  NOT applied under a TP mesh: a column-sharded fused
    weight would put the q|k|v split boundaries inside shards and force a
    resharding gather after every matmul — the Megatron answer is a
    per-shard interleaved layout, deliberately not replicated here; the
    mesh path keeps per-projection leaves and GSPMD specs.

    ``in_place=True`` mutates ``params["layers"]``, popping each
    per-projection leaf before its replacement concat materializes — on a
    SOLELY-OWNED device-resident 7B tree the transient is one fused stack
    (<= 4 GB), not a full second tree (load_qwen2 uses this).  The default
    copies the dicts so a caller-shared tree is never altered (the Engine
    wraps trees it does not own); a big tree fused this way transiently
    holds both layouts — prefer building big trees fused from the start
    (init_params_quantized(fuse=True) / load_qwen2(fuse=True), after
    which this is a no-op).  MoE layers pass through untouched."""
    if not in_place:
        params = dict(params, layers=dict(params["layers"]))
    layers = params["layers"]
    if "wq" in layers:
        layers["wqkv"], layers["bqkv"] = _concat_linears(
            [layers.pop("wq"), layers.pop("wk"), layers.pop("wv")],
            [layers.pop("bq"), layers.pop("bk"), layers.pop("bv")],
        )
    if "wg" in layers:
        layers["wgu"] = _concat_linears([layers.pop("wg"), layers.pop("wu")])
    return params


def quantize_qwen2_params(
    params: dict, embeddings: bool = True, bits: int = 8, group_size: int = 64
) -> dict:
    """Quantize every linear projection of a Qwen2(-MoE) param tree
    (attention wq/wk/wv/wo, the dense MLP or the expert+shared-expert
    stacks, lm_head when present, and — by default — the embedding table,
    which a tied-weight model reads IN FULL every decode step for logits);
    norms, biases, the MoE router, and the shared-expert gate stay bf16.

    ``bits=4`` switches projections to the AWQ-class group-wise uint4
    scheme (quantize_weight4); the embedding table stays per-row int8
    either way — AWQ itself keeps embeddings full precision, and a 4-bit
    table would put its larger error on every token AND every logit."""
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    qw = (
        quantize_weight
        if bits == 8
        else lambda w: quantize_weight4(w, group_size=group_size)
    )
    out = dict(params)
    layers = dict(params["layers"])
    # Quantize every projection leaf PRESENT, covering all four layouts:
    # dense/MoE x unfused/fused (fuse_projections renames wq|wk|wv -> wqkv
    # and wg|wu -> wgu; a fused-at-init tree must quantize without being
    # un-fused first).  MoE experts + shared expert quantize with stacked
    # per-expert scales (the leading dims pass through both schemes); the
    # router and the [d, 1] shared gate stay full precision — they are
    # tiny and routing decisions are the precision-sensitive part of a
    # sparse model.  Norms and biases are never in this list.
    matched = 0
    for name in ("wq", "wk", "wv", "wqkv", "wo", "wg", "wu", "wgu", "wd",
                 "e_wg", "e_wu", "e_wd", "s_wg", "s_wu", "s_wd"):
        if name in layers:
            layers[name] = qw(layers[name])
            matched += 1
    if matched == 0:
        # A renamed/foreign tree must fail loudly: every known layout has
        # at least one projection leaf, and returning the tree untouched
        # would silently serve FULL-PRECISION weights under
        # quantizeWeights:"int8" (no error, just 2x the HBM and none of
        # the speedup — the failure only shows up in a memory profile)
        raise ValueError(
            "quantize_qwen2_params: no known projection leaf found in "
            f"params['layers'] (keys: {sorted(layers)}); the tree would "
            "pass through at full precision"
        )
    out["layers"] = layers
    if "lm_head" in params:
        out["lm_head"] = qw(params["lm_head"])
    if embeddings:
        out["embed"] = quantize_embedding(params["embed"])
    return out


@functools.partial(jax.jit, static_argnames=("shape", "kind"))
def _devrand(shape: tuple, salt: jnp.ndarray, kind: str) -> jnp.ndarray:
    """Uniform-ish random leaf ON DEVICE via a Knuth-hashed iota — a pure
    elementwise chain XLA fuses straight into the FINAL dtype, so a
    multi-GB random leaf costs one device-side write (in the narrow output
    type — the u32 intermediate must stay inside this jit or a 7B-scale
    leaf transiently materializes 4x its bytes and OOMs the chip) and ZERO
    host->device transfer.  The host-numpy path this replaces cost the
    bench ~20 min of tunnel transfer for the 7B int8 tree (and minutes of
    single-thread RNG); bench throughput is weight-value-independent, so
    hash quality only needs to defeat trivial value patterns.

    kinds: "u8" uniform uint8; "i8" uniform int8 (bitcast); "bf16"
    centered floats with std ~ 0.02."""
    n = 1
    for s_ in shape:
        n *= s_
    i = jax.lax.iota(jnp.uint32, n)
    h = i * jnp.uint32(2654435761) + salt
    h = h ^ (h >> 16)
    h = h * jnp.uint32(2246822519)
    h = (h ^ (h >> 13)).reshape(shape)
    if kind == "u8":
        return (h & jnp.uint32(0xFF)).astype(jnp.uint8)
    if kind == "i8":
        # clamp -128 -> -127: real checkpoints clip symmetric int8 to
        # +-127, and the documented "uniform int8 std ~73" scale
        # derivation assumes that range (ADVICE r04)
        return jnp.maximum(
            jax.lax.bitcast_convert_type(
                (h & jnp.uint32(0xFF)).astype(jnp.uint8), jnp.int8
            ),
            jnp.int8(-127),
        )
    assert kind == "bf16", kind
    # uniform [0, 2^32) -> centered, std ~ 0.02 (uniform std = range/sqrt(12))
    return ((h.astype(jnp.float32) - 2147483648.0) * (0.02 / 1.24e9)).astype(
        jnp.bfloat16
    )


def init_params_quantized(cfg, seed: int = 0, bits: int = 8,
                          group_size: int = 64, fuse: bool = False) -> dict:
    """Random quantized Qwen2 params (int8 or AWQ-class int4), generated
    leaf by leaf ON DEVICE (_devrand): a 7B bf16 tree cannot be
    materialized on a 16 GB chip just to quantize it, and building the
    quantized tree host-side costs the bench ~20 min of remote-TPU tunnel
    transfer.  Real checkpoints stream through quantize_weight /
    quantize_weight4 shard by shard in hf_loader.  Bench/test use:
    throughput is weight-value-independent."""
    if getattr(cfg, "num_experts", 0):
        raise NotImplementedError(
            "random quantized MoE init is not implemented (this helper exists "
            "for dense-geometry benches); real MoE checkpoints quantize "
            "through load_qwen2(..., quantize=True)"
        )
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    salt_box = [jnp.uint32(seed * 40503 + 12345)]

    def noise(shape, kind):
        salt_box[0] = salt_box[0] * jnp.uint32(747796405) + jnp.uint32(1)
        return _devrand(tuple(shape), salt_box[0], kind)

    d, nq, nkv, hd, inter, L, v = (
        cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
        cfg.intermediate_size, cfg.num_layers, cfg.vocab_size,
    )

    def bf16(*shape):
        return noise(shape, "bf16")

    def qlin8(*shape):
        q = noise(shape, "i8")
        # scale so dequantized std ~ 0.02 (uniform int8 std ~ 73)
        s = jnp.full(shape[:-2] + shape[-1:], 0.02 / 73.0, dtype=jnp.bfloat16)
        return QuantizedLinear(q=q, s=s)

    def qlin4(*shape):
        in_dim, out = shape[-2], shape[-1]
        if group_size % 2 or in_dim % group_size:
            raise ValueError(
                f"input dim {in_dim} must be divisible by the (even) "
                f"group_size {group_size} (same contract as quantize_weight4)"
            )
        packed = noise(shape[:-2] + (in_dim // 2, out), "u8")
        sshape = shape[:-2] + (in_dim // group_size, out)
        # uniform uint4 std ~ 4.6; center with zs = 7.5*s
        s = jnp.full(sshape, 0.02 / 4.6, dtype=jnp.bfloat16)
        zs = jnp.full(sshape, 7.5 * 0.02 / 4.6, dtype=jnp.bfloat16)
        return QuantizedLinear4(q=packed, s=s, zs=zs)

    qlin = qlin8 if bits == 8 else qlin4

    layers = {
        "ln1": jnp.ones((L, d), dtype=jnp.bfloat16),
        "ln2": jnp.ones((L, d), dtype=jnp.bfloat16),
        "wo": qlin(L, nq * hd, d),
        "wd": qlin(L, inter, d),
    }
    if fuse:
        # generate the fused single-chip serving layout DIRECTLY (random
        # weights): fusing a resident 7B device tree with jnp.concatenate
        # would transiently double weight HBM — see fuse_projections
        layers.update({
            "wqkv": qlin(L, d, (nq + 2 * nkv) * hd),
            "bqkv": jnp.zeros((L, (nq + 2 * nkv) * hd), dtype=jnp.bfloat16),
            "wgu": qlin(L, d, 2 * inter),
        })
    else:
        layers.update({
            "wq": qlin(L, d, nq * hd),
            "bq": jnp.zeros((L, nq * hd), dtype=jnp.bfloat16),
            "wk": qlin(L, d, nkv * hd),
            "bk": jnp.zeros((L, nkv * hd), dtype=jnp.bfloat16),
            "wv": qlin(L, d, nkv * hd),
            "bv": jnp.zeros((L, nkv * hd), dtype=jnp.bfloat16),
            "wg": qlin(L, d, inter),
            "wu": qlin(L, d, inter),
        })
    embed_q = noise((v, d), "i8")
    embed_s = jnp.full((v,), 0.02 / 73.0, dtype=jnp.bfloat16)
    params = {"embed": QuantizedEmbedding(q=embed_q, s=embed_s), "layers": layers,
              "norm": jnp.ones((d,), dtype=jnp.bfloat16)}
    if not cfg.tie_word_embeddings:
        params["lm_head"] = qlin(d, v)
    return params


def params_nbytes(params) -> int:
    return sum(
        leaf.nbytes for leaf in jax.tree.leaves(params) if hasattr(leaf, "nbytes")
    )
