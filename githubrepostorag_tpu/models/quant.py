"""Weight-only int8 quantization for the decoder.

Fills the role AWQ fills in the reference deployment (vLLM serves
Qwen2.5-Coder-7B-Instruct-AWQ on an 8 GB GPU — helm/values.yaml:67): a 7B
bf16 checkpoint (~15.2 GB) does not fit a 16 GB v5e chip next to its KV
pools, but int8 weights (~7.6 GB) do.  Decode is HBM-bandwidth-bound, so
halving weight bytes is also the main single-chip speed lever.

Scheme: per-output-channel symmetric int8 —
    scale[o] = max_i |W[i, o]| / 127        (bf16 scales)
    W_q[i, o] = round(W[i, o] / scale[o])   (int8)
Quantized tensors are ``QuantizedLinear(q, s)`` pytree nodes; matmuls go
through :func:`qmatmul`, which dequantizes inside the XLA program — the
convert+scale fuses into the dot's operand read on TPU (measured ~590 GB/s
effective weight bandwidth for 7B decode, i.e. no materialized bf16 copy),
so no hand-written dequant kernel is needed.

The embedding table quantizes too (per-ROW scales — ``quantize_embedding``):
a tied-weight model reads it in full every decode step for logits, so at
0.5B it is ~27 % of per-step weight traffic.  Token lookups go through
``embedding_lookup`` (gather int8 rows, scale per row).  Norms and biases
stay bf16.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedLinear(NamedTuple):
    """Weight-only int8 projection: ``q`` int8 [.., in, out], ``s`` bf16
    per OUTPUT channel [.., out]."""

    q: jnp.ndarray
    s: jnp.ndarray


class QuantizedEmbedding(NamedTuple):
    """Weight-only int8 embedding table: ``q`` int8 [V, d], ``s`` bf16 per
    vocab ROW [V].  A distinct type from QuantizedLinear because the scale
    axis differs — generic linear consumers (qmatmul/dequantize) must not
    silently apply row scales as column scales."""

    q: jnp.ndarray
    s: jnp.ndarray


def _quantize_symmetric(w, axis: int):
    """Shared symmetric-int8 recipe: reduce |w| over ``axis``, scale to
    127, round/clip, bf16 scales with the reduced axis squeezed out.

    Computed HOST-side in numpy: quantizing a 7B tree with eager device ops
    would transiently materialize ~15 GB of f32 on the 16 GB chip this
    feature exists to fit — only the int8 weights and bf16 scales ever
    reach the device."""
    import ml_dtypes
    import numpy as np

    w_np = np.asarray(w, dtype=np.float32)  # pulls device arrays to host
    amax = np.max(np.abs(w_np), axis=axis, keepdims=True)
    scale = np.maximum(amax / 127.0, 1e-8)
    q = np.clip(np.round(w_np / scale), -127, 127).astype(np.int8)
    s = np.squeeze(scale, axis=axis).astype(ml_dtypes.bfloat16)
    return jnp.asarray(q), jnp.asarray(s)


def quantize_weight(w) -> QuantizedLinear:
    """Per-output-channel symmetric int8.  ``w`` is [in, out] or stacked
    [L, in, out]; the input (reduction) axis is -2, so scales are [out] /
    [L, out]."""
    q, s = _quantize_symmetric(w, axis=-2)
    return QuantizedLinear(q=q, s=s)


def dequant_weight(w, dtype) -> jnp.ndarray:
    """Compute-dtype view of a maybe-quantized linear weight.  THE one
    definition of the int8->dtype expression (per-output-channel scales) —
    every consumer (qmatmul, the MoE expert einsums, dequantize) routes
    through here so a scheme change cannot silently miss a path.  XLA
    fuses the convert+scale into the consuming dot's operand stream on
    TPU; no bf16 copy is materialized for the common shapes."""
    if isinstance(w, QuantizedLinear):
        return w.q.astype(dtype) * w.s.astype(dtype)[..., None, :]
    return w


def dequantize(t: QuantizedLinear, dtype=jnp.bfloat16) -> jnp.ndarray:
    return dequant_weight(
        QuantizedLinear(q=t.q, s=t.s.astype(jnp.float32)), jnp.float32
    ).astype(dtype)


def qmatmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` where ``w`` is a plain array or a QuantizedLinear (int8
    contraction with int32 accumulation is not supported for mixed
    bf16/int8 operands on all backends, so the weight dequantizes at use —
    see dequant_weight)."""
    return x @ dequant_weight(w, x.dtype)


def quantize_embedding(w) -> QuantizedEmbedding:
    """Per-ROW symmetric int8 for the embedding table [V, d]: each vocab row
    is one channel, so the tied-weight logits contraction over d dequantizes
    per output logit, and the token-lookup path is ``q[ids] * s[ids]``."""
    q, s = _quantize_symmetric(w, axis=-1)
    return QuantizedEmbedding(q=q, s=s)


def embedding_lookup(embed, ids: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    """Token embedding gather for plain or int8 tables."""
    if isinstance(embed, QuantizedEmbedding):
        rows = jnp.take(embed.q, ids, axis=0).astype(dtype)
        return rows * jnp.take(embed.s, ids, axis=0)[..., None].astype(dtype)
    return jnp.take(embed, ids, axis=0)


def quantize_qwen2_params(params: dict, embeddings: bool = True) -> dict:
    """Quantize every linear projection of a Qwen2(-MoE) param tree
    (attention wq/wk/wv/wo, the dense MLP or the expert+shared-expert
    stacks, lm_head when present, and — by default — the embedding table,
    which a tied-weight model reads IN FULL every decode step for logits);
    norms, biases, the MoE router, and the shared-expert gate stay bf16."""
    out = dict(params)
    layers = dict(params["layers"])
    if "router" in layers:
        # MoE: experts + shared expert quantize with stacked per-expert
        # scales ([L, E, ff] — _quantize_symmetric reduces axis -2 whatever
        # the leading dims).  The router and the [d, 1] shared gate stay
        # full precision: they are tiny and routing decisions are the
        # precision-sensitive part of a sparse model.
        mlp_names = ("e_wg", "e_wu", "e_wd", "s_wg", "s_wu", "s_wd")
    else:
        mlp_names = ("wg", "wu", "wd")
    for name in ("wq", "wk", "wv", "wo") + mlp_names:
        layers[name] = quantize_weight(layers[name])
    out["layers"] = layers
    if "lm_head" in params:
        out["lm_head"] = quantize_weight(params["lm_head"])
    if embeddings:
        out["embed"] = quantize_embedding(params["embed"])
    return out


def init_params_quantized(cfg, seed: int = 0) -> dict:
    """Random int8-quantized Qwen2 params, built HOST-side leaf by leaf (a
    7B bf16 tree cannot be materialized on a 16 GB chip just to quantize
    it; real checkpoints stream through quantize_weight shard by shard in
    hf_loader).  Bench/test use: throughput is weight-value-independent."""
    import ml_dtypes
    import numpy as np

    if getattr(cfg, "num_experts", 0):
        raise NotImplementedError(
            "random int8 MoE init is not implemented (this helper exists for "
            "dense-geometry benches); real MoE checkpoints quantize through "
            "load_qwen2(..., quantize=True)"
        )
    rng = np.random.default_rng(seed)
    d, nq, nkv, hd, inter, L, v = (
        cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
        cfg.intermediate_size, cfg.num_layers, cfg.vocab_size,
    )

    def bf16(*shape):
        return jnp.asarray(
            (rng.standard_normal(shape) * 0.02).astype(ml_dtypes.bfloat16)
        )

    def qlin(*shape):
        q = jnp.asarray(rng.integers(-127, 128, shape, dtype=np.int8))
        # scale so dequantized std ~ 0.02 (uniform int8 std ~ 73)
        s = jnp.full(shape[:-2] + shape[-1:], 0.02 / 73.0, dtype=jnp.bfloat16)
        return QuantizedLinear(q=q, s=s)

    layers = {
        "ln1": jnp.ones((L, d), dtype=jnp.bfloat16),
        "ln2": jnp.ones((L, d), dtype=jnp.bfloat16),
        "wq": qlin(L, d, nq * hd),
        "bq": jnp.zeros((L, nq * hd), dtype=jnp.bfloat16),
        "wk": qlin(L, d, nkv * hd),
        "bk": jnp.zeros((L, nkv * hd), dtype=jnp.bfloat16),
        "wv": qlin(L, d, nkv * hd),
        "bv": jnp.zeros((L, nkv * hd), dtype=jnp.bfloat16),
        "wo": qlin(L, nq * hd, d),
        "wg": qlin(L, d, inter),
        "wu": qlin(L, d, inter),
        "wd": qlin(L, inter, d),
    }
    embed_q = jnp.asarray(rng.integers(-127, 128, (v, d), dtype=np.int8))
    embed_s = jnp.full((v,), 0.02 / 73.0, dtype=jnp.bfloat16)
    params = {"embed": QuantizedEmbedding(q=embed_q, s=embed_s), "layers": layers,
              "norm": jnp.ones((d,), dtype=jnp.bfloat16)}
    if not cfg.tie_word_embeddings:
        params["lm_head"] = qlin(d, v)
    return params


def params_nbytes(params) -> int:
    return sum(
        leaf.nbytes for leaf in jax.tree.leaves(params) if hasattr(leaf, "nbytes")
    )
