"""BERT-class text encoder in pure functional JAX (e5-small-v2 geometry:
12 layers, hidden 384, 12 heads, GELU FFN 1536, learned positions,
post-layer-norm blocks, mean pooling + L2 normalization).

Replaces the reference's CPU-torch ``HuggingFaceEmbeddings`` encoder
(instantiated at graph_rag_retrievers.py:53, vector_write_service.py:117,
ingest_controller.py:376, cassandra_service.py:127 — all torch 2.3 CPU per
environment-worker.yaml:9) with a TPU path: big batches ride the MXU during
ingest (pjit data-parallel over the mesh), single queries take a small
padded bucket for low latency at retrieval time.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 384
    intermediate_size: int = 1536
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12

    @classmethod
    def e5_small(cls) -> "BertConfig":
        """intfloat/e5-small-v2 geometry — also BAAI/bge-small-en-v1.5's
        (BASELINE eval config #2): both are 12-layer/384-hidden BERTs, and
        real checkpoints load through JaxBertTextEncoder.from_pretrained,
        which reads the geometry from config.json (embedding.py applies e5
        query/passage prefixes only when the model name says e5)."""
        return cls()

    @classmethod
    def tiny(cls) -> "BertConfig":
        return cls(
            vocab_size=256, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=4, max_position_embeddings=64,
        )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def _layer_norm(x, weight, bias, eps):
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
    return ((xf - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight + bias


def init_params(cfg: BertConfig, key: jax.Array, dtype=jnp.float32) -> dict:
    d, inter, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    keys = jax.random.split(key, 12)

    def norm(k, *shape):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * 0.02).astype(dtype)

    layers = {
        "wq": norm(keys[0], L, d, d), "bq": jnp.zeros((L, d), dtype),
        "wk": norm(keys[1], L, d, d), "bk": jnp.zeros((L, d), dtype),
        "wv": norm(keys[2], L, d, d), "bv": jnp.zeros((L, d), dtype),
        "wo": norm(keys[3], L, d, d), "bo": jnp.zeros((L, d), dtype),
        "ln_attn_w": jnp.ones((L, d), dtype), "ln_attn_b": jnp.zeros((L, d), dtype),
        "w1": norm(keys[4], L, d, inter), "b1": jnp.zeros((L, inter), dtype),
        "w2": norm(keys[5], L, inter, d), "b2": jnp.zeros((L, d), dtype),
        "ln_ffn_w": jnp.ones((L, d), dtype), "ln_ffn_b": jnp.zeros((L, d), dtype),
    }
    return {
        "word_embeddings": norm(keys[6], cfg.vocab_size, d),
        "position_embeddings": norm(keys[7], cfg.max_position_embeddings, d),
        "token_type_embeddings": norm(keys[8], cfg.type_vocab_size, d),
        "ln_embed_w": jnp.ones((d,), dtype),
        "ln_embed_b": jnp.zeros((d,), dtype),
        "layers": layers,
    }


@partial(jax.jit, static_argnames=("cfg",))
def forward(
    params: dict,
    cfg: BertConfig,
    input_ids: jnp.ndarray,  # [B, S] int32
    attention_mask: jnp.ndarray,  # [B, S] 1 = real token
) -> jnp.ndarray:
    """Token-level hidden states [B, S, D]."""
    b, s = input_ids.shape
    nh, hd = cfg.num_heads, cfg.head_dim

    pos_ids = jnp.arange(s)[None, :]
    h = (
        jnp.take(params["word_embeddings"], input_ids, axis=0)
        + params["position_embeddings"][pos_ids]
        + params["token_type_embeddings"][0][None, None, :]
    )
    h = _layer_norm(h, params["ln_embed_w"], params["ln_embed_b"], cfg.layer_norm_eps)

    # additive mask [B, 1, 1, S]
    neg = jnp.asarray(-1e30, h.dtype)
    attn_bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, neg)

    def body(h, p):
        q = (h @ p["wq"] + p["bq"]).reshape(b, s, nh, hd)
        k = (h @ p["wk"] + p["bk"]).reshape(b, s, nh, hd)
        v = (h @ p["wv"] + p["bv"]).reshape(b, s, nh, hd)
        scores = jnp.einsum("bsnh,btnh->bnst", q, k).astype(jnp.float32)
        scores = scores / (hd ** 0.5) + attn_bias.astype(jnp.float32)
        probs = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
        ctx = jnp.einsum("bnst,btnh->bsnh", probs, v).reshape(b, s, nh * hd)
        attn_out = ctx @ p["wo"] + p["bo"]
        h = _layer_norm(h + attn_out, p["ln_attn_w"], p["ln_attn_b"], cfg.layer_norm_eps)
        ffn = jax.nn.gelu(h @ p["w1"] + p["b1"], approximate=False) @ p["w2"] + p["b2"]
        h = _layer_norm(h + ffn, p["ln_ffn_w"], p["ln_ffn_b"], cfg.layer_norm_eps)
        return h, None

    h, _ = jax.lax.scan(body, h, params["layers"])
    return h


@partial(jax.jit, static_argnames=("cfg",))
def embed(
    params: dict,
    cfg: BertConfig,
    input_ids: jnp.ndarray,
    attention_mask: jnp.ndarray,
) -> jnp.ndarray:
    """Sentence embeddings: masked mean pooling + L2 norm -> [B, D] float32
    (the e5 family's pooling; sentence-transformers' default mean pooling)."""
    h = forward(params, cfg, input_ids, attention_mask).astype(jnp.float32)
    mask = attention_mask[..., None].astype(jnp.float32)
    pooled = (h * mask).sum(axis=1) / jnp.maximum(mask.sum(axis=1), 1e-9)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12)


def params_from_hf_state_dict(state_dict: dict, cfg: BertConfig, dtype=np.float32) -> dict:
    """Convert a HF BertModel state dict (bert.* or bare) to our pytree."""

    def _np(t):
        if isinstance(t, np.ndarray):
            return t
        return t.detach().to("cpu").float().numpy()

    sd = {}
    for k, v in state_dict.items():
        sd[k.removeprefix("bert.")] = v
    L = cfg.num_layers

    def get(name):
        return _np(sd[name])

    def lin(fmt):  # HF [out, in] -> [in, out], stacked
        return np.stack([get(fmt.format(i)).T for i in range(L)]).astype(dtype)

    def vec(fmt):
        return np.stack([get(fmt.format(i)) for i in range(L)]).astype(dtype)

    pre = "encoder.layer.{}."
    layers = {
        "wq": lin(pre + "attention.self.query.weight"), "bq": vec(pre + "attention.self.query.bias"),
        "wk": lin(pre + "attention.self.key.weight"), "bk": vec(pre + "attention.self.key.bias"),
        "wv": lin(pre + "attention.self.value.weight"), "bv": vec(pre + "attention.self.value.bias"),
        "wo": lin(pre + "attention.output.dense.weight"), "bo": vec(pre + "attention.output.dense.bias"),
        "ln_attn_w": vec(pre + "attention.output.LayerNorm.weight"),
        "ln_attn_b": vec(pre + "attention.output.LayerNorm.bias"),
        "w1": lin(pre + "intermediate.dense.weight"), "b1": vec(pre + "intermediate.dense.bias"),
        "w2": lin(pre + "output.dense.weight"), "b2": vec(pre + "output.dense.bias"),
        "ln_ffn_w": vec(pre + "output.LayerNorm.weight"),
        "ln_ffn_b": vec(pre + "output.LayerNorm.bias"),
    }
    return {
        "word_embeddings": get("embeddings.word_embeddings.weight").astype(dtype),
        "position_embeddings": get("embeddings.position_embeddings.weight").astype(dtype),
        "token_type_embeddings": get("embeddings.token_type_embeddings.weight").astype(dtype),
        "ln_embed_w": get("embeddings.LayerNorm.weight").astype(dtype),
        "ln_embed_b": get("embeddings.LayerNorm.bias").astype(dtype),
        "layers": layers,
    }
