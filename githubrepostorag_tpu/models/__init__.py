"""Model definitions: the Qwen2-family decoder (serving + training) and the
BERT-class embedding encoder.  Pure-functional JAX — parameters are pytrees
of arrays with layers stacked on a leading axis so the layer loop is a
single ``lax.scan`` (one compile per shape, not per layer) and pjit sharding
rules apply uniformly across layers."""

from githubrepostorag_tpu.models.qwen2 import Qwen2Config, forward, init_params

__all__ = ["Qwen2Config", "forward", "init_params"]
