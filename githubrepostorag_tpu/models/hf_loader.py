"""Load HuggingFace Qwen2 checkpoints into the stacked-params pytree.

Two entry points:
  - ``params_from_state_dict`` — from an in-memory state dict (numpy/torch
    tensors); used by parity tests against ``transformers`` models.
  - ``load_qwen2`` — from a local checkpoint directory (config.json +
    safetensors shards).  No network access: weights must already be on
    disk (MODEL_WEIGHTS_PATH).

HF stores linear weights [out, in]; this framework stores [in, out] so the
forward pass is ``x @ w``.  Per-layer tensors are stacked on a leading L
axis for the lax.scan layer loop.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from githubrepostorag_tpu.models.qwen2 import Qwen2Config


def _np(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    # torch tensor (parity tests) without importing torch here
    return t.detach().to("cpu").float().numpy()


def config_from_hf(hf_cfg: dict) -> Qwen2Config:
    num_heads = hf_cfg["num_attention_heads"]
    moe: dict = {}
    if hf_cfg.get("num_experts", 0):  # Qwen2MoeConfig (model_type qwen2_moe)
        if hf_cfg.get("decoder_sparse_step", 1) != 1 or hf_cfg.get("mlp_only_layers"):
            # lax.scan over stacked layers needs a uniform block structure
            raise ValueError(
                "only uniformly-sparse Qwen2-MoE checkpoints are supported "
                "(decoder_sparse_step=1, no mlp_only_layers)"
            )
        moe = dict(
            num_experts=hf_cfg["num_experts"],
            num_experts_per_tok=hf_cfg["num_experts_per_tok"],
            moe_intermediate_size=hf_cfg["moe_intermediate_size"],
            shared_expert_intermediate_size=hf_cfg["shared_expert_intermediate_size"],
            norm_topk_prob=hf_cfg.get("norm_topk_prob", False),
            # serving default: bounded-capacity dispatch.  The exact no-drop
            # mode (capacity_factor=0) builds [T, E, T] dispatch tensors —
            # parity-test scale only; override via dataclasses.replace
            capacity_factor=2.0,
        )
    return Qwen2Config(
        vocab_size=hf_cfg["vocab_size"],
        hidden_size=hf_cfg["hidden_size"],
        intermediate_size=hf_cfg["intermediate_size"],
        num_layers=hf_cfg["num_hidden_layers"],
        num_heads=num_heads,
        num_kv_heads=hf_cfg.get("num_key_value_heads", num_heads),
        head_dim=hf_cfg.get("head_dim") or hf_cfg["hidden_size"] // num_heads,
        rope_theta=hf_cfg.get("rope_theta", 1_000_000.0),  # HF Qwen2Config default
        rms_norm_eps=hf_cfg.get("rms_norm_eps", 1e-6),
        tie_word_embeddings=hf_cfg.get("tie_word_embeddings", False),
        max_position_embeddings=hf_cfg.get("max_position_embeddings", 32768),
        **moe,
    )


def params_from_state_dict(state_dict: dict, cfg: Qwen2Config, dtype=np.float32) -> dict:
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    L = cfg.num_layers

    def get(name: str) -> np.ndarray:
        return _np(sd[name])

    def stack_linear(fmt: str) -> np.ndarray:
        # HF [out, in] -> ours [in, out], stacked [L, in, out]
        return np.stack([get(fmt.format(i)).T for i in range(L)]).astype(dtype)

    def stack_vec(fmt: str) -> np.ndarray:
        return np.stack([get(fmt.format(i)) for i in range(L)]).astype(dtype)

    layers = {
        "ln1": stack_vec("layers.{}.input_layernorm.weight"),
        "ln2": stack_vec("layers.{}.post_attention_layernorm.weight"),
        "wq": stack_linear("layers.{}.self_attn.q_proj.weight"),
        "bq": stack_vec("layers.{}.self_attn.q_proj.bias"),
        "wk": stack_linear("layers.{}.self_attn.k_proj.weight"),
        "bk": stack_vec("layers.{}.self_attn.k_proj.bias"),
        "wv": stack_linear("layers.{}.self_attn.v_proj.weight"),
        "bv": stack_vec("layers.{}.self_attn.v_proj.bias"),
        "wo": stack_linear("layers.{}.self_attn.o_proj.weight"),
    }
    if cfg.num_experts > 0:  # Qwen2-MoE sparse MLP (models/moe.py keys)
        E = cfg.num_experts

        def stack_experts(fmt: str) -> np.ndarray:
            # [L, E, in, out] from HF's per-expert [out, in] linears
            return np.stack([
                np.stack([get(fmt.format(i, e)).T for e in range(E)])
                for i in range(L)
            ]).astype(dtype)

        layers.update({
            "router": stack_linear("layers.{}.mlp.gate.weight"),
            "e_wg": stack_experts("layers.{}.mlp.experts.{}.gate_proj.weight"),
            "e_wu": stack_experts("layers.{}.mlp.experts.{}.up_proj.weight"),
            "e_wd": stack_experts("layers.{}.mlp.experts.{}.down_proj.weight"),
            "s_wg": stack_linear("layers.{}.mlp.shared_expert.gate_proj.weight"),
            "s_wu": stack_linear("layers.{}.mlp.shared_expert.up_proj.weight"),
            "s_wd": stack_linear("layers.{}.mlp.shared_expert.down_proj.weight"),
            "s_gate": stack_linear("layers.{}.mlp.shared_expert_gate.weight"),
        })
    else:
        layers.update({
            "wg": stack_linear("layers.{}.mlp.gate_proj.weight"),
            "wu": stack_linear("layers.{}.mlp.up_proj.weight"),
            "wd": stack_linear("layers.{}.mlp.down_proj.weight"),
        })
    params = {
        "embed": get("embed_tokens.weight").astype(dtype),
        "layers": layers,
        "norm": get("norm.weight").astype(dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = _np(sd["lm_head.weight"]).T.astype(dtype)
    return params


def load_qwen2(
    checkpoint_dir: str, dtype=np.float32, quantize: bool = False
) -> tuple[dict, Qwen2Config]:
    """Load config.json + *.safetensors from a local directory.

    ``quantize=True`` converts every linear projection AND the embedding
    table to weight-only int8 (models/quant.py) host-side before device
    placement — the path that
    fits Qwen2-7B on a single 16 GB chip (the AWQ-equivalent of the
    reference's Qwen2.5-Coder-7B-Instruct-AWQ deployment, values.yaml:67).
    """
    from safetensors import safe_open  # ships with transformers' deps

    root = Path(checkpoint_dir)
    hf_cfg = json.loads((root / "config.json").read_text())
    cfg = config_from_hf(hf_cfg)

    state: dict[str, np.ndarray] = {}
    for shard in sorted(root.glob("*.safetensors")):
        with safe_open(str(shard), framework="np") as f:
            for key in f.keys():
                state[key] = f.get_tensor(key)
    params = params_from_state_dict(state, cfg, dtype=dtype)
    if quantize:
        from githubrepostorag_tpu.models.quant import quantize_qwen2_params

        params = quantize_qwen2_params(params)
    return params, cfg
