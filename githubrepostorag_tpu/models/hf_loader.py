"""Load HuggingFace Qwen2 checkpoints into the stacked-params pytree.

Two entry points:
  - ``params_from_state_dict`` — from an in-memory state dict (numpy/torch
    tensors); used by parity tests against ``transformers`` models.
  - ``load_qwen2`` — from a local checkpoint directory (config.json +
    safetensors shards).  No network access: weights must already be on
    disk (MODEL_WEIGHTS_PATH).

HF stores linear weights [out, in]; this framework stores [in, out] so the
forward pass is ``x @ w``.  Per-layer tensors are stacked on a leading L
axis for the lax.scan layer loop.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from githubrepostorag_tpu.models.qwen2 import Qwen2Config


def _np(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    # torch tensor (parity tests) without importing torch here
    return t.detach().to("cpu").float().numpy()


def config_from_hf(hf_cfg: dict, moe_capacity_factor: float = 2.0) -> Qwen2Config:
    """Pure parser: HF config dict -> Qwen2Config.  ``moe_capacity_factor``
    is caller-supplied (the serving entrypoint threads
    Settings.moe_capacity_factor through load_qwen2) so parsing the same
    config.json never depends on process env."""
    num_heads = hf_cfg["num_attention_heads"]
    moe: dict = {}
    if hf_cfg.get("num_experts", 0):  # Qwen2MoeConfig (model_type qwen2_moe)
        if hf_cfg.get("decoder_sparse_step", 1) != 1 or hf_cfg.get("mlp_only_layers"):
            # lax.scan over stacked layers needs a uniform block structure
            raise ValueError(
                "only uniformly-sparse Qwen2-MoE checkpoints are supported "
                "(decoder_sparse_step=1, no mlp_only_layers)"
            )
        moe = dict(
            num_experts=hf_cfg["num_experts"],
            num_experts_per_tok=hf_cfg["num_experts_per_tok"],
            moe_intermediate_size=hf_cfg["moe_intermediate_size"],
            shared_expert_intermediate_size=hf_cfg["shared_expert_intermediate_size"],
            norm_topk_prob=hf_cfg.get("norm_topk_prob", False),
            # bounded-capacity dispatch (MOE_DROP_STATS=1 counts drops).
            # The exact no-drop mode (factor 0) builds [T, E, T] dispatch
            # tensors — parity-test scale only.
            capacity_factor=moe_capacity_factor,
        )
    return Qwen2Config(
        vocab_size=hf_cfg["vocab_size"],
        hidden_size=hf_cfg["hidden_size"],
        intermediate_size=hf_cfg["intermediate_size"],
        num_layers=hf_cfg["num_hidden_layers"],
        num_heads=num_heads,
        num_kv_heads=hf_cfg.get("num_key_value_heads", num_heads),
        head_dim=hf_cfg.get("head_dim") or hf_cfg["hidden_size"] // num_heads,
        rope_theta=hf_cfg.get("rope_theta", 1_000_000.0),  # HF Qwen2Config default
        rms_norm_eps=hf_cfg.get("rms_norm_eps", 1e-6),
        tie_word_embeddings=hf_cfg.get("tie_word_embeddings", False),
        max_position_embeddings=hf_cfg.get("max_position_embeddings", 32768),
        **moe,
    )


def params_from_state_dict(state_dict: dict, cfg: Qwen2Config, dtype=np.float32) -> dict:
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    L = cfg.num_layers

    def get(name: str) -> np.ndarray:
        return _np(sd[name])

    def stack_linear(fmt: str) -> np.ndarray:
        # HF [out, in] -> ours [in, out], stacked [L, in, out]
        return np.stack([get(fmt.format(i)).T for i in range(L)]).astype(dtype)

    def stack_vec(fmt: str) -> np.ndarray:
        return np.stack([get(fmt.format(i)) for i in range(L)]).astype(dtype)

    layers = {
        "ln1": stack_vec("layers.{}.input_layernorm.weight"),
        "ln2": stack_vec("layers.{}.post_attention_layernorm.weight"),
        "wq": stack_linear("layers.{}.self_attn.q_proj.weight"),
        "bq": stack_vec("layers.{}.self_attn.q_proj.bias"),
        "wk": stack_linear("layers.{}.self_attn.k_proj.weight"),
        "bk": stack_vec("layers.{}.self_attn.k_proj.bias"),
        "wv": stack_linear("layers.{}.self_attn.v_proj.weight"),
        "bv": stack_vec("layers.{}.self_attn.v_proj.bias"),
        "wo": stack_linear("layers.{}.self_attn.o_proj.weight"),
    }
    if cfg.num_experts > 0:  # Qwen2-MoE sparse MLP (models/moe.py keys)
        E = cfg.num_experts

        def stack_experts(fmt: str) -> np.ndarray:
            # [L, E, in, out] from HF's per-expert [out, in] linears
            return np.stack([
                np.stack([get(fmt.format(i, e)).T for e in range(E)])
                for i in range(L)
            ]).astype(dtype)

        layers.update({
            "router": stack_linear("layers.{}.mlp.gate.weight"),
            "e_wg": stack_experts("layers.{}.mlp.experts.{}.gate_proj.weight"),
            "e_wu": stack_experts("layers.{}.mlp.experts.{}.up_proj.weight"),
            "e_wd": stack_experts("layers.{}.mlp.experts.{}.down_proj.weight"),
            "s_wg": stack_linear("layers.{}.mlp.shared_expert.gate_proj.weight"),
            "s_wu": stack_linear("layers.{}.mlp.shared_expert.up_proj.weight"),
            "s_wd": stack_linear("layers.{}.mlp.shared_expert.down_proj.weight"),
            "s_gate": stack_linear("layers.{}.mlp.shared_expert_gate.weight"),
        })
    else:
        layers.update({
            "wg": stack_linear("layers.{}.mlp.gate_proj.weight"),
            "wu": stack_linear("layers.{}.mlp.up_proj.weight"),
            "wd": stack_linear("layers.{}.mlp.down_proj.weight"),
        })
    params = {
        "embed": get("embed_tokens.weight").astype(dtype),
        "layers": layers,
        "norm": get("norm.weight").astype(dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = _np(sd["lm_head.weight"]).T.astype(dtype)
    return params


def load_qwen2(
    checkpoint_dir: str,
    dtype=np.float32,
    quantize: bool | int = False,
    moe_capacity_factor: float = 2.0,
    fuse: bool = False,
) -> tuple[dict, Qwen2Config]:
    """Load config.json + *.safetensors from a local directory.

    ``quantize`` converts every linear projection AND the embedding table
    to weight-only quantized form (models/quant.py) host-side before
    device placement: ``True``/``8`` = per-channel int8, ``4`` = AWQ-class
    group-wise uint4 — the path that fits Qwen2-7B on a single 16 GB chip
    (matching the reference's Qwen2.5-Coder-7B-Instruct-AWQ deployment,
    values.yaml:67).  Checkpoints that are ALREADY AWQ-quantized
    (quant_config.quant_method == "awq" in config.json, qweight/qzeros/
    scales tensors) are detected and repacked via
    ``awq_params_from_state_dict`` — the uint4 codes transfer exactly (no
    dequant/requant round trip); scales round fp16->bf16.
    """
    from safetensors import safe_open  # ships with transformers' deps

    root = Path(checkpoint_dir)
    hf_cfg = json.loads((root / "config.json").read_text())
    cfg = config_from_hf(hf_cfg, moe_capacity_factor=moe_capacity_factor)

    state: dict[str, np.ndarray] = {}
    for shard in sorted(root.glob("*.safetensors")):
        with safe_open(str(shard), framework="np") as f:
            for key in f.keys():
                state[key] = f.get_tensor(key)
    if quantize not in (False, True, 4, 8):
        raise ValueError(f"quantize must be False/True/8/4, got {quantize!r}")
    if (hf_cfg.get("quantization_config") or {}).get("quant_method") == "awq":
        if quantize in (True, 8):
            import logging

            logging.getLogger(__name__).warning(
                "checkpoint %s is natively 4-bit AWQ; ignoring the int8 "
                "quantize request and repacking the AWQ codes", checkpoint_dir
            )
        params = awq_params_from_state_dict(state, cfg, hf_cfg, dtype=dtype)
    else:
        params = params_from_state_dict(state, cfg, dtype=dtype)
        if quantize:
            from githubrepostorag_tpu.models.quant import quantize_qwen2_params

            params = quantize_qwen2_params(params, bits=4 if quantize == 4 else 8)
    if fuse:
        # single-chip serving layout (quant.fuse_projections): fuse at load
        # time, while the tree is the only thing on the device, rather than
        # at Engine construction next to freshly allocated KV pools
        from githubrepostorag_tpu.models.quant import fuse_projections

        params = fuse_projections(params, in_place=True)  # solely owned here
    return params, cfg


# ---- AWQ checkpoint repacking -------------------------------------------

AWQ_NIBBLE_ORDER = (0, 2, 4, 6, 1, 3, 5, 7)  # AutoAWQ's GEMM packing order


def _awq_unpack(packed: np.ndarray) -> np.ndarray:
    """Unpack AutoAWQ int32 nibble-packed tensors along the LAST axis:
    [r, c/8] int32 -> [r, c] uint8 (values 0..15).  AWQ packs 8 columns
    per int32 in the interleaved order ``AWQ_NIBBLE_ORDER`` (see
    AutoAWQ awq/utils/packing_utils.py — behavioral contract only)."""
    r, c8 = packed.shape
    out = np.empty((r, c8 * 8), dtype=np.uint8)
    u = packed.view(np.uint32) if packed.dtype == np.int32 else packed.astype(np.uint32)
    for pos, col in enumerate(AWQ_NIBBLE_ORDER):
        out[:, col::8] = ((u >> np.uint32(4 * pos)) & np.uint32(0xF)).astype(np.uint8)
    return out


def awq_linear_to_quantized4(
    qweight: np.ndarray,  # int32 [in, out/8]
    qzeros: np.ndarray,  # int32 [in/group, out/8]
    scales: np.ndarray,  # f16/f32 [in/group, out]
):
    """Repack one AutoAWQ GEMM-format linear into the in-tree
    ``QuantizedLinear4`` layout.  AWQ dequant is ``(q - z) * s``; ours is
    ``q * s - zs`` with ``zs = z * s``.  The uint4 codes transfer exactly;
    s and zs are stored bf16 (AWQ ships fp16 scales), so repacked dequant
    matches the AWQ reference to bf16 rounding of the scales (~2^-8
    relative) — not bit-exact."""
    import jax.numpy as jnp
    import ml_dtypes

    from githubrepostorag_tpu.models.quant import QuantizedLinear4

    q = _awq_unpack(qweight)  # [in, out] uint8
    z = _awq_unpack(qzeros).astype(np.float32)  # [in/group, out]
    s = scales.astype(np.float32)
    in_dim, out = q.shape
    n_g = s.shape[0]
    group = in_dim // n_g
    if group % 2 or in_dim % group:
        raise ValueError(f"AWQ group size {group} not even over in dim {in_dim}")
    # in-group plane packing (see QuantizedLinear4): low nibble = first
    # half of each group's rows, high nibble = second half
    qg = q.reshape(n_g, group, out)
    packed = (qg[:, : group // 2, :] | (qg[:, group // 2 :, :] << 4)).reshape(
        in_dim // 2, out
    )
    return QuantizedLinear4(
        q=jnp.asarray(packed),
        s=jnp.asarray(s.astype(ml_dtypes.bfloat16)),
        zs=jnp.asarray((z * s).astype(ml_dtypes.bfloat16)),
    )


def awq_params_from_state_dict(
    state_dict: dict, cfg: Qwen2Config, hf_cfg: dict, dtype=np.float32
) -> dict:
    """Build the stacked-params pytree from an AWQ checkpoint's
    qweight/qzeros/scales tensors (projections) + full-precision
    embedding/norm tensors.  The embedding table re-quantizes to the
    in-tree per-row int8 (AWQ keeps it fp16; int8 per-row is this
    framework's standard table format and adds <0.4% RMS error).
    ``dtype`` sets the unquantized leaves (norms/biases) and thereby the
    activation dtype (qwen2._embed_dtype) — pass bf16 for serving."""
    from githubrepostorag_tpu.models.quant import quantize_embedding

    if cfg.num_experts > 0:
        raise NotImplementedError(
            "AWQ repacking covers dense Qwen2 checkpoints; AWQ MoE exports "
            "are not supported (quantize a bf16 MoE checkpoint instead)"
        )
    qc = hf_cfg.get("quantization_config") or {}
    if qc.get("bits", 4) != 4 or qc.get("version", "gemm").lower() != "gemm":
        raise ValueError(
            f"only 4-bit GEMM-format AWQ checkpoints are supported, got "
            f"bits={qc.get('bits')} version={qc.get('version')}"
        )
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    L = cfg.num_layers

    def stack_awq(prefix_fmt: str):
        import jax
        import jax.numpy as jnp

        per_layer = [
            awq_linear_to_quantized4(
                _np_int(sd[prefix_fmt.format(i) + ".qweight"]),
                _np_int(sd[prefix_fmt.format(i) + ".qzeros"]),
                _np(sd[prefix_fmt.format(i) + ".scales"]),
            )
            for i in range(L)
        ]
        # stack each field (q/s/zs) on a new leading L axis
        return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_layer)

    def stack_vec(fmt: str) -> np.ndarray:
        return np.stack([_np(sd[fmt.format(i)]) for i in range(L)]).astype(dtype)

    layers = {
        "ln1": stack_vec("layers.{}.input_layernorm.weight"),
        "ln2": stack_vec("layers.{}.post_attention_layernorm.weight"),
        "wq": stack_awq("layers.{}.self_attn.q_proj"),
        "bq": stack_vec("layers.{}.self_attn.q_proj.bias"),
        "wk": stack_awq("layers.{}.self_attn.k_proj"),
        "bk": stack_vec("layers.{}.self_attn.k_proj.bias"),
        "wv": stack_awq("layers.{}.self_attn.v_proj"),
        "bv": stack_vec("layers.{}.self_attn.v_proj.bias"),
        "wo": stack_awq("layers.{}.self_attn.o_proj"),
        "wg": stack_awq("layers.{}.mlp.gate_proj"),
        "wu": stack_awq("layers.{}.mlp.up_proj"),
        "wd": stack_awq("layers.{}.mlp.down_proj"),
    }
    params = {
        "embed": quantize_embedding(_np(sd["embed_tokens.weight"])),
        "layers": layers,
        "norm": _np(sd["norm.weight"]).astype(dtype),
    }
    if not cfg.tie_word_embeddings:
        lm = sd.get("lm_head.weight")
        if lm is not None:  # AWQ keeps lm_head fp16; re-quantize to int8
            from githubrepostorag_tpu.models.quant import quantize_weight

            params["lm_head"] = quantize_weight(_np(lm).T)
        else:  # some AWQ exports quantize lm_head too
            params["lm_head"] = awq_linear_to_quantized4(
                _np_int(sd["lm_head.qweight"]),
                _np_int(sd["lm_head.qzeros"]),
                _np(sd["lm_head.scales"]),
            )
    return params


def _np_int(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    return t.detach().to("cpu").numpy()
