"""Sparse Mixture-of-Experts MLP (Qwen2-MoE family) with expert-parallel
sharding over the ``ep`` mesh axis.

The TPU formulation: no scatters, no per-expert Python — routing becomes
dense one-hot dispatch/combine tensors and the expert FFN is ONE batched
einsum over stacked expert weights [E, ...] (GShard/Switch style).  With
the expert axis of the weights sharded P("ep", ...), GSPMD turns the
dispatch/combine einsums into the all-to-alls of classic expert
parallelism — no hand-written collectives, same recipe as the rest of the
mesh fabric (SURVEY.md §2.3: the mesh was designed so EP "can slot in";
this fills the slot).

Math matches HF ``Qwen2MoeSparseMoeBlock`` (softmax router in float32,
top-k, optional top-k renorm, plus an always-on shared expert scaled by a
sigmoid gate), so HF-parity tests hold token-exact when capacity is
no-drop.  Capacity: ``cfg.capacity_factor == 0`` gives exact no-drop
dispatch (capacity = T; dispatch tensors are [T, E, T] — parity/test
scale); real serving sets a factor so capacity = ceil(K*T/E * factor) and
overflow tokens simply lose that expert's contribution (standard
token-dropping semantics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.quant import dequant_weight, qmatmul

# Host-side routing-drop accumulator (ADVICE r02: bounded-capacity dispatch
# silently loses expert contributions under router imbalance — make the
# drop rate observable).  MOE_DROP_STATS=1 enables a per-layer
# jax.debug.callback that adds (assignments, dropped) here and to the
# Prometheus counters; off by default because the callback forces a
# host round trip per MoE layer.
DROP_STATS = {"assignments": 0, "dropped": 0}


def _drop_stats_enabled() -> bool:
    from githubrepostorag_tpu.config import _env_bool

    return _env_bool("MOE_DROP_STATS", False)


def _record_drops(assignments, dropped) -> None:
    DROP_STATS["assignments"] += int(assignments)
    DROP_STATS["dropped"] += int(dropped)
    try:
        from githubrepostorag_tpu.metrics import MOE_ASSIGNMENTS, MOE_DROPPED

        MOE_ASSIGNMENTS.inc(int(assignments))
        MOE_DROPPED.inc(int(dropped))
    except Exception:  # pragma: no cover - metrics registry optional in tools
        pass


def moe_mlp(cfg, p: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Sparse MoE MLP over normed hidden states ``x`` [B, S, d].

    ``p`` keys: ``router`` [d, E]; ``e_wg``/``e_wu`` [E, d, ff_e],
    ``e_wd`` [E, ff_e, d]; ``s_wg``/``s_wu`` [d, ff_s], ``s_wd`` [ff_s, d];
    ``s_gate`` [d, 1].
    """
    b, s, d = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    T = b * s
    xf = x.reshape(T, d)

    # --- router: float32 softmax over experts, top-k (HF parity) ----------
    logits = jnp.einsum("td,de->te", xf.astype(jnp.float32), p["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]
    top_p, top_i = jax.lax.top_k(probs, K)  # [T, K]
    if cfg.norm_topk_prob:
        top_p = top_p / jnp.maximum(top_p.sum(axis=-1, keepdims=True), 1e-20)

    # --- dispatch/combine tensors (one-hot + in-expert position) ----------
    if cfg.capacity_factor > 0:
        C = max(1, int(-(-K * T * cfg.capacity_factor // E)))
    else:
        C = T  # no-drop: an expert can at most receive every token once
    oh = jax.nn.one_hot(top_i, E, dtype=jnp.float32)  # [T, K, E]
    oh_flat = oh.reshape(T * K, E)
    # arrival order: token-major, then k — position of each assignment in
    # its expert's queue decides who fits under the capacity
    pos = jnp.cumsum(oh_flat, axis=0) - oh_flat
    slot = (pos * oh_flat).sum(-1)  # [T*K] this assignment's queue position
    keep = slot < C
    if cfg.capacity_factor > 0 and _drop_stats_enabled():
        jax.debug.callback(
            _record_drops, jnp.asarray(T * K), (~(slot < C)).sum()
        )
    slot_oh = (jax.nn.one_hot(slot, C, dtype=jnp.float32) * keep[:, None]).reshape(T, K, C)
    # contract k inside the einsums: a materialized [T, K, E, C] would be
    # K times the memory of the [T, E, C] tensors actually needed
    dispatch = jnp.einsum("tke,tkc->tec", oh, slot_oh)  # [T, E, C] 0/1
    combine = jnp.einsum("tke,tkc,tk->tec", oh, slot_oh, top_p)

    # --- expert FFN: one batched einsum per projection --------------------
    cdt = x.dtype
    xs = jnp.einsum("td,tec->ecd", xf, dispatch.astype(cdt))  # [E, C, d]
    h1 = jnp.einsum("ecd,edf->ecf", xs, dequant_weight(p["e_wg"], cdt))
    h2 = jnp.einsum("ecd,edf->ecf", xs, dequant_weight(p["e_wu"], cdt))
    ys = jnp.einsum(
        "ecf,efd->ecd", jax.nn.silu(h1) * h2, dequant_weight(p["e_wd"], cdt)
    )
    y = jnp.einsum("ecd,tec->td", ys, combine.astype(cdt))

    # --- always-on shared expert with sigmoid gate ------------------------
    sh = jax.nn.silu(qmatmul(xf, p["s_wg"])) * qmatmul(xf, p["s_wu"])
    sh = qmatmul(sh, p["s_wd"]) * jax.nn.sigmoid(qmatmul(xf, p["s_gate"]))
    return (y + sh).reshape(b, s, d)


def init_moe_layer_params(cfg, key: jax.Array, dtype=jnp.float32) -> dict:
    """Random init of ONE stack of MoE-MLP layer params ([L, ...] leaves),
    merged into the attention params by qwen2.init_params."""
    L, d = cfg.num_layers, cfg.hidden_size
    E, ffe, ffs = cfg.num_experts, cfg.moe_intermediate_size, cfg.shared_expert_intermediate_size
    ks = jax.random.split(key, 8)
    norm = lambda k, *shape: (
        jax.random.normal(k, shape, dtype=jnp.float32) * 0.02
    ).astype(dtype)
    return {
        "router": norm(ks[0], L, d, E),
        "e_wg": norm(ks[1], L, E, d, ffe),
        "e_wu": norm(ks[2], L, E, d, ffe),
        "e_wd": norm(ks[3], L, E, ffe, d),
        "s_wg": norm(ks[4], L, d, ffs),
        "s_wu": norm(ks[5], L, d, ffs),
        "s_wd": norm(ks[6], L, ffs, d),
        "s_gate": norm(ks[7], L, d, 1),
    }


# EP sharding lives with every other layout decision in
# parallel/sharding.py::qwen2_param_specs (expert axes P(None, "ep", ...)),
# so Engine(mesh=...) and init_train_state shard MoE trees the same way
# they shard dense ones.
