"""Qwen2-family decoder in pure functional JAX.

Replaces the reference's out-of-tree vLLM serving model
(helm/templates/qwen-deployment.yaml:21-33 — image vllm/vllm-openai serving
Qwen2.5-Coder-7B-Instruct-AWQ) with an in-tree implementation designed for
TPU: bfloat16 activations on the MXU, stacked-layer params scanned with
``lax.scan``, grouped-query attention without materialized KV repetition,
and a cache interface the paged serving engine plugs into.

Architecture (matches HF ``Qwen2ForCausalLM``): token embedding, N blocks of
[RMSNorm -> GQA attention with QKV bias + RoPE -> residual, RMSNorm ->
SwiGLU MLP -> residual], final RMSNorm, (optionally tied) LM head.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.quant import (
    QuantizedEmbedding,
    QuantizedLinear,
    QuantizedLinear4,
    _split_q4,
    _with_layered_q4,
    dequant_weight,
    embedding_lookup,
    qmatmul,
)
from githubrepostorag_tpu.ops.attention import dense_attention
from githubrepostorag_tpu.ops.norms import rms_norm
from githubrepostorag_tpu.ops.rope import apply_rope, rope_cos_sin


@dataclass(frozen=True)
class Qwen2Config:
    vocab_size: int = 151936
    hidden_size: int = 896
    intermediate_size: int = 4864
    num_layers: int = 24
    num_heads: int = 14
    num_kv_heads: int = 2
    head_dim: int = 64
    rope_theta: float = 1_000_000.0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = True
    max_position_embeddings: int = 32768
    # ---- MoE (Qwen2-MoE family: Qwen1.5-MoE-A2.7B / Qwen2-57B-A14B) ------
    # num_experts 0 = dense; >0 switches every layer's MLP to the sparse
    # block (router top-k experts + always-on shared expert), models/moe.py
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    shared_expert_intermediate_size: int = 0
    norm_topk_prob: bool = False
    # expert capacity = ceil(K*T/E * factor); 0.0 = exact no-drop dispatch
    # (capacity T — HF-parity math, quadratic dispatch tensors: test scale)
    capacity_factor: float = 0.0

    # ---- presets (HF config.json values for the eval-config model family) --

    @classmethod
    def tiny(cls) -> "Qwen2Config":
        """Test-scale config (CI / parity tests)."""
        return cls(
            vocab_size=512,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            rope_theta=10_000.0,
            tie_word_embeddings=True,
            max_position_embeddings=512,
        )

    @classmethod
    def tiny_moe(cls) -> "Qwen2Config":
        """Test-scale MoE: 4 experts top-2 + shared expert."""
        return cls(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
            rope_theta=10_000.0, tie_word_embeddings=True,
            max_position_embeddings=512,
            num_experts=4, num_experts_per_tok=2, moe_intermediate_size=48,
            shared_expert_intermediate_size=96, norm_topk_prob=True,
        )

    @classmethod
    def qwen1_5_moe_a2_7b(cls) -> "Qwen2Config":
        """Qwen/Qwen1.5-MoE-A2.7B geometry (60 experts top-4 + shared)."""
        return cls(
            vocab_size=151936, hidden_size=2048, intermediate_size=5632,
            num_layers=24, num_heads=16, num_kv_heads=16, head_dim=128,
            tie_word_embeddings=False,
            num_experts=60, num_experts_per_tok=4, moe_intermediate_size=1408,
            shared_expert_intermediate_size=5632, norm_topk_prob=False,
            capacity_factor=2.0,
        )

    @classmethod
    def qwen2_0_5b(cls) -> "Qwen2Config":
        return cls(
            hidden_size=896, intermediate_size=4864, num_layers=24,
            num_heads=14, num_kv_heads=2, head_dim=64, tie_word_embeddings=True,
        )

    @classmethod
    def qwen2_1_5b(cls) -> "Qwen2Config":
        return cls(
            hidden_size=1536, intermediate_size=8960, num_layers=28,
            num_heads=12, num_kv_heads=2, head_dim=128, tie_word_embeddings=True,
        )

    @classmethod
    def qwen2_7b(cls) -> "Qwen2Config":
        return cls(
            hidden_size=3584, intermediate_size=18944, num_layers=28,
            num_heads=28, num_kv_heads=4, head_dim=128, tie_word_embeddings=False,
            vocab_size=152064,
        )


def init_params(cfg: Qwen2Config, key: jax.Array, dtype=jnp.float32) -> dict:
    """Random init (normal 0.02, the HF default) with stacked layer leaves."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, nq, nkv, hd, inter, L = (
        cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
        cfg.intermediate_size, cfg.num_layers,
    )

    def norm(key, *shape):
        return (jax.random.normal(key, shape, dtype=jnp.float32) * 0.02).astype(dtype)

    keys = jax.random.split(k_layers, 10)
    layers = {
        "ln1": jnp.ones((L, d), dtype=dtype),
        "ln2": jnp.ones((L, d), dtype=dtype),
        "wq": norm(keys[0], L, d, nq * hd),
        "bq": jnp.zeros((L, nq * hd), dtype=dtype),
        "wk": norm(keys[1], L, d, nkv * hd),
        "bk": jnp.zeros((L, nkv * hd), dtype=dtype),
        "wv": norm(keys[2], L, d, nkv * hd),
        "bv": jnp.zeros((L, nkv * hd), dtype=dtype),
        "wo": norm(keys[3], L, nq * hd, d),
    }
    if cfg.num_experts > 0:
        from githubrepostorag_tpu.models.moe import init_moe_layer_params

        layers.update(init_moe_layer_params(cfg, keys[9], dtype=dtype))
    else:
        layers.update({
            "wg": norm(keys[4], L, d, inter),
            "wu": norm(keys[5], L, d, inter),
            "wd": norm(keys[6], L, inter, d),
        })
    params = {
        "embed": norm(k_embed, cfg.vocab_size, d),
        "layers": layers,
        "norm": jnp.ones((d,), dtype=dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = norm(k_head, d, cfg.vocab_size)
    return params


def _block(cfg: Qwen2Config, h, p, cos, sin, attend, reduce=None):
    """One transformer block.  ``attend(q, k, v) -> (attn_out, cache_info)``
    commits this step's K/V into whatever cache representation the caller
    uses (dense slab, page pool, or nothing) and returns the attention
    output.  Both the dense and paged forward paths share this body, so
    projection/RoPE/MLP changes cannot drift between them.

    ``reduce``: applied to the two row-parallel products (wo and wd) before
    the residual add.  Callers running this body INSIDE a shard_map with
    tensor-parallel weight shards (training/pipeline.py's tp-in-stage)
    pass ``lambda x: lax.psum(x, "tp")`` and a cfg whose head counts are
    the LOCAL per-shard counts; annotation-driven (GSPMD) callers leave it
    None — the compiler inserts the same psums from the param shardings."""
    b, s, d = h.shape
    nq, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    if reduce is None:
        reduce = lambda x: x

    hn = rms_norm(h, p["ln1"], cfg.rms_norm_eps)
    if "wqkv" in p:  # fused single-chip serving layout (quant.fuse_projections)
        qkv = qmatmul(hn, p["wqkv"]) + p["bqkv"]
        q, k, v = jnp.split(qkv, [nq * hd, (nq + nkv) * hd], axis=-1)
        q = q.reshape(b, s, nq, hd)
        k = k.reshape(b, s, nkv, hd)
        v = v.reshape(b, s, nkv, hd)
    else:
        q = (qmatmul(hn, p["wq"]) + p["bq"]).reshape(b, s, nq, hd)
        k = (qmatmul(hn, p["wk"]) + p["bk"]).reshape(b, s, nkv, hd)
        v = (qmatmul(hn, p["wv"]) + p["bv"]).reshape(b, s, nkv, hd)
    q, k = apply_rope(q, k, cos, sin)

    attn, cache_info = attend(q, k, v)
    h = h + reduce(qmatmul(attn.reshape(b, s, nq * hd), p["wo"]))

    hn = rms_norm(h, p["ln2"], cfg.rms_norm_eps)
    if "router" in p:  # sparse MoE MLP (Qwen2-MoE family, models/moe.py)
        from githubrepostorag_tpu.models.moe import moe_mlp

        h = h + moe_mlp(cfg, p, hn)
    elif "wgu" in p:  # fused gate|up (quant.fuse_projections)
        g, u = jnp.split(qmatmul(hn, p["wgu"]), 2, axis=-1)
        h = h + reduce(qmatmul(jax.nn.silu(g) * u, p["wd"]))
    else:
        h = h + reduce(
            qmatmul(jax.nn.silu(qmatmul(hn, p["wg"])) * qmatmul(hn, p["wu"]), p["wd"])
        )
    return h, cache_info


@partial(jax.jit, static_argnames=("cfg",))
def forward(
    params: dict,
    cfg: Qwen2Config,
    input_ids: jnp.ndarray,  # [B, S] int32
    positions: jnp.ndarray,  # [B, S] int32
    cache_k: jnp.ndarray | None = None,  # [L, B, S_cache, n_kv, hd]
    cache_v: jnp.ndarray | None = None,
    kv_lengths: jnp.ndarray | None = None,  # [B] tokens already cached
):
    """Full forward pass -> (logits [B, S, V] float32, (cache_k, cache_v)).

    Without a cache: plain causal attention over the input (training /
    scoring / parity tests).  With a cache: incremental prefill or decode —
    new K/V are written at each row's ``kv_lengths`` offset and attention
    covers the whole cache.

    Caller contract: ``kv_lengths + S`` must not exceed the cache's length
    axis.  ``dynamic_update_slice`` clamps out-of-range starts, which would
    silently corrupt the newest cache entries — the serving engine
    (serving/engine.py) enforces the bound before dispatch.
    """
    h = embedding_lookup(params["embed"], input_ids, dtype=_embed_dtype(params))
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)
    s = input_ids.shape[1]

    use_cache = cache_k is not None
    if use_cache:
        xs = (params["layers"], cache_k, cache_v)
    else:
        xs = (params["layers"],)

    def body(h, layer_xs):
        if use_cache:
            p, ck, cv = layer_xs

            def attend(q, k, v):
                # Commit new k/v at each row's current length, then attend
                # over the full cache with per-row validity masking.
                def write(cache, new, start):
                    return jax.lax.dynamic_update_slice(
                        cache, new.astype(cache.dtype), (start, 0, 0)
                    )

                new_ck = jax.vmap(write)(ck, k, kv_lengths)
                new_cv = jax.vmap(write)(cv, v, kv_lengths)
                attn = dense_attention(
                    q, new_ck, new_cv,
                    causal=True,
                    q_offset=kv_lengths,
                    kv_lengths=kv_lengths + s,
                )
                return attn, (new_ck, new_cv)

            h, cache_info = _block(cfg, h, p, cos, sin, attend)
            return h, cache_info

        (p,) = layer_xs
        h, _ = _block(
            cfg, h, p, cos, sin,
            lambda q, k, v: (dense_attention(q, k, v, causal=True, q_offset=0), None),
        )
        return h, None

    h, cache_out = jax.lax.scan(body, h, xs)
    h = rms_norm(h, params["norm"], cfg.rms_norm_eps)
    logits = _logits(params, h)

    if use_cache:
        new_k, new_v = cache_out
        return logits, (new_k, new_v)
    return logits, None


def forward_with_attend(
    params: dict,
    cfg: Qwen2Config,
    input_ids: jnp.ndarray,  # [B, S] int32
    positions: jnp.ndarray,  # [B, S] int32
    attend_fn=None,
    remat: bool = False,
) -> jnp.ndarray:
    """Cache-free forward with a pluggable attention implementation.

    ``attend_fn(q, k, v) -> out`` defaults to causal dense attention; the
    training path passes ``parallel.make_ring_attend(...)`` so the sequence
    axis can live sharded over the ``sp`` mesh axis.  ``remat`` checkpoints
    each scanned layer, so backward holds one layer's activations at a time
    (peak HBM O(S) instead of O(S·L)).  Not jitted — callers jit (the train
    step jits the whole loss+grad program).  Returns logits [B, S, V] f32.
    """
    if attend_fn is None:
        attend_fn = lambda q, k, v: dense_attention(q, k, v, causal=True, q_offset=0)

    h = embedding_lookup(params["embed"], input_ids, dtype=_embed_dtype(params))
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)

    def body(h, layer_xs):
        (p,) = layer_xs
        h, _ = _block(cfg, h, p, cos, sin, lambda q, k, v: (attend_fn(q, k, v), None))
        return h, None

    if remat:
        body = jax.checkpoint(body)
    h, _ = jax.lax.scan(body, h, (params["layers"],))
    h = rms_norm(h, params["norm"], cfg.rms_norm_eps)
    return _logits(params, h)


def _embed_dtype(params: dict):
    """Activation dtype for the param tree — taken from the final norm
    vector, which is always a plain array (embed may be int8, and its bf16
    scales must not force bf16 activations on an f32 test tree)."""
    return params["norm"].dtype


def _logits(params: dict, h: jnp.ndarray, int4_kernel: bool = True,
            w4a8: bool | None = None) -> jnp.ndarray:
    """Final projection -> float32 logits (tied embedding or separate
    lm_head).  Operands stay in their stored dtype (bf16 on the MXU) with
    float32 accumulation via preferred_element_type — an explicit astype
    would materialize a second full-vocab matrix every decode step."""
    lm_head = params.get("lm_head")
    if lm_head is None:
        embed = params["embed"]
        if isinstance(embed, QuantizedEmbedding):
            # int8 tied embedding: dequant fuses into the contraction; the
            # per-row scales apply to the OUTPUT logits
            logits = jnp.einsum(
                "bsd,vd->bsv", h, embed.q.astype(h.dtype),
                preferred_element_type=jnp.float32,
            )
            return logits * embed.s.astype(jnp.float32)[None, None, :]
        return jnp.einsum(
            "bsd,vd->bsv", h, embed, preferred_element_type=jnp.float32
        )
    if isinstance(lm_head, QuantizedLinear4):
        # XLA materializes the int4 unpack (~1 GB bf16 head per step) —
        # q4_dispatch routes to the Pallas in-VMEM-dequant GEMM on TPU
        # (two-dot XLA formulation elsewhere / under TP sharding)
        from githubrepostorag_tpu.models.quant import q4_dispatch

        return q4_dispatch(h, lm_head.q, lm_head.s, lm_head.zs,
                           out_dtype=jnp.float32, kernel=int4_kernel,
                           w4a8=w4a8)
    if isinstance(lm_head, QuantizedLinear):
        # dequantized per use; the convert+scale fuses into the dot
        wd = dequant_weight(lm_head, h.dtype)
        return jnp.einsum("bsd,dv->bsv", h, wd, preferred_element_type=jnp.float32)
    return jnp.einsum(
        "bsd,dv->bsv", h, lm_head, preferred_element_type=jnp.float32
    )


def make_dense_cache(cfg: Qwen2Config, batch: int, max_len: int, dtype=jnp.bfloat16):
    """Allocate a contiguous per-layer KV cache [L, B, max_len, n_kv, hd]."""
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype=dtype), jnp.zeros(shape, dtype=dtype)


@partial(
    jax.jit, static_argnames=("cfg", "use_pallas", "int4_kernel"),
    donate_argnums=(4, 5),
)
def forward_paged(
    params: dict,
    cfg: Qwen2Config,
    input_ids: jnp.ndarray,  # [B, S] int32, right-padded per row
    positions: jnp.ndarray,  # [B, S] int32 absolute positions
    k_pages: jnp.ndarray,  # [L, n_kv, P, page_size, hd] (donated)
    v_pages: jnp.ndarray,  # (donated)
    slot_mapping: jnp.ndarray,  # [B, S] int32 flat pool slots, -1 for padding
    block_tables: jnp.ndarray,  # [B, max_pages] int32
    cached_lens: jnp.ndarray,  # [B] tokens already in cache before this step
    new_lens: jnp.ndarray,  # [B] valid new tokens this step
    use_pallas: bool = False,
    logits_at: jnp.ndarray | None = None,  # [B] per-row position, see below
    k_scales: jnp.ndarray | None = None,  # [L, n_kv, P] f32 (per-page) —
    v_scales: jnp.ndarray | None = None,  # int8 (kv_quant) pool scales
    int4_kernel: bool = True,  # False under TP-sharded int4 weights
    # (pallas_call has no GSPMD partitioning rule — see quant.Layered4XLA)
):
    """Prefill-chunk or decode step over the paged KV cache.

    New K/V are scattered into the page pools at ``slot_mapping`` (padding
    slots are -1 and dropped), then attention runs over each row's block
    table.  Returns (logits, k_pages, v_pages[, k_scales, v_scales]) — the
    pools are donated so XLA updates them in place (scale pools are small
    enough that their copy is noise).

    ``k_scales``/``v_scales`` mark int8 kv_quant pools: new K/V quantize
    per PAGE at the scatter (kv_cache.quantize_kv_paged: the first write
    to a page fixes its scale, appends reuse it and clip) and attention
    runs the gather path with dequant — prefill/verify chunks are
    compute-dominated, so the materialized gather costs little here; the
    decode hot path (decode_burst) reads int8 pages directly in its
    Pallas kernel.

    ``logits_at``: per-row chunk index at which to project logits, returning
    [B, 1, V].  Without it logits cover every position ([B, S, V] float32 —
    at prefill width x batch x vocab that is GBs of HBM; the serving engine
    only ever needs each prompt's last position, vLLM's
    "last-token-only logits" optimization).
    """
    return forward_paged_impl(
        params, cfg, input_ids, positions, k_pages, v_pages,
        slot_mapping, block_tables, cached_lens, new_lens, use_pallas,
        logits_at=logits_at, k_scales=k_scales, v_scales=v_scales,
        int4_kernel=int4_kernel,
    )


def forward_paged_impl(
    params: dict,
    cfg: Qwen2Config,
    input_ids: jnp.ndarray,
    positions: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    slot_mapping: jnp.ndarray,
    block_tables: jnp.ndarray,
    cached_lens: jnp.ndarray,
    new_lens: jnp.ndarray,
    use_pallas: bool = False,
    logits_at: jnp.ndarray | None = None,
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
    int4_kernel: bool = True,
):
    """Unjitted body of ``forward_paged`` so larger fused programs (the
    multi-step decode burst in serving/decode_burst.py) can inline it inside
    their own scan without nested-jit donation clashes."""
    from githubrepostorag_tpu.ops.paged_attention import paged_attention_ref

    quant = k_scales is not None
    if use_pallas:
        # ONE kernel for every window shape and pool precision: spec
        # verify (S = k+1), plain decode (S = 1), fp/int8/int4 pages all
        # run ops/fused_decode's flash window kernel — the old dispatcher
        # routed S > 1 and quantized pools to the materialized gather_kv
        # fallback, a full [B, mp*ps, n_kv, hd] HBM copy per layer.
        from githubrepostorag_tpu.ops.fused_decode import (
            fused_paged_attention as attn_fn,
        )
    else:
        attn_fn = paged_attention_ref

    b, s = input_ids.shape
    nkv, hd = cfg.num_kv_heads, cfg.head_dim
    num_pages, page_size = k_pages.shape[2], k_pages.shape[3]
    total_slots = num_pages * page_size

    h = embedding_lookup(params["embed"], input_ids, dtype=_embed_dtype(params))
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)
    # Padding slots arrive as -1; JAX scatter *wraps* negative indices (it
    # only drops indices >= size), so map them to an out-of-range positive
    # sentinel that mode="drop" actually drops.
    flat_slots = slot_mapping.reshape(-1)  # [B*S]
    flat_slots = jnp.where(flat_slots < 0, total_slots, flat_slots)

    scan_layers, q4_stacks = _split_q4(params["layers"])

    def body(carry, layer_xs):
        h, li = carry
        if quant:
            p, kp, vp, ks, vs = layer_xs
        else:
            p, kp, vp = layer_xs
            ks = vs = None
        # prefill / spec-verify chunks pin w4a8=False: prompt processing
        # keeps the exact bf16-dequant contract even when the chunk is
        # decode-sized (the auto gate must never catch a prefill batch)
        p = _with_layered_q4(p, q4_stacks, li, kernel=int4_kernel, w4a8=False)

        def attend(q, k, v):
            from githubrepostorag_tpu.serving.kv_cache import commit_paged

            k_t = k.reshape(-1, nkv, hd).swapaxes(0, 1)  # [n_kv, B*S, hd]
            v_t = v.reshape(-1, nkv, hd).swapaxes(0, 1)
            # commit_paged is THE shared pool-commit rule (cast for bf16
            # pools; per-page first-write scales for int8 — same semantics
            # as the burst and ring-prefill commits)
            new_kp, new_ks = commit_paged(
                kp, k_t, flat_slots, ks if quant else None, page_size
            )
            new_vp, new_vs = commit_paged(
                vp, v_t, flat_slots, vs if quant else None, page_size
            )
            if quant:
                attn = attn_fn(q, new_kp, new_vp, block_tables, cached_lens,
                               new_lens, new_ks, new_vs)
                return attn, (new_kp, new_vp, new_ks, new_vs)
            attn = attn_fn(q, new_kp, new_vp, block_tables, cached_lens, new_lens)
            return attn, (new_kp, new_vp)

        h, cache = _block(cfg, h, p, cos, sin, attend)
        return (h, li + 1), cache

    if quant:
        xs = (scan_layers, k_pages, v_pages, k_scales, v_scales)
        (h, _), (k_pages, v_pages, k_scales, v_scales) = jax.lax.scan(
            body, (h, 0), xs
        )
    else:
        (h, _), (k_pages, v_pages) = jax.lax.scan(
            body, (h, 0), (scan_layers, k_pages, v_pages)
        )
    h = rms_norm(h, params["norm"], cfg.rms_norm_eps)
    if logits_at is not None:
        h = jnp.take_along_axis(h, logits_at[:, None, None], axis=1)  # [B, 1, d]
    # w4a8=False: prefill/spec-verify logits keep the exact bf16-dequant
    # contract, like the projections above (the prompt's first sampled
    # token and every verify accept/reject come from these)
    logits = _logits(params, h, int4_kernel=int4_kernel, w4a8=False)
    if quant:
        return logits, k_pages, v_pages, k_scales, v_scales
    return logits, k_pages, v_pages


@partial(
    jax.jit, static_argnames=("cfg", "tq", "use_pallas", "int4_kernel"),
    donate_argnums=(4, 5),
)
def forward_paged_packed(
    params: dict,
    cfg: Qwen2Config,
    input_ids: jnp.ndarray,  # [1, T] int32 packed token buffer (T = budget)
    positions: jnp.ndarray,  # [1, T] int32 absolute positions per token
    k_pages: jnp.ndarray,  # [L, n_kv, P, page_size, hd] (donated)
    v_pages: jnp.ndarray,  # (donated)
    slot_mapping: jnp.ndarray,  # [T] int32 flat pool slots, -1 for padding
    block_tables: jnp.ndarray,  # [R, max_pages] int32 per SEGMENT
    cached_lens: jnp.ndarray,  # [R] tokens in cache before this chunk
    new_lens: jnp.ndarray,  # [R] valid new tokens this chunk
    seg_ids: jnp.ndarray,  # [T] int32 owning segment; >= R marks padding
    logits_at: jnp.ndarray,  # [R] packed-buffer index of each segment's
    # last token (the generalized per-segment logits_at)
    tq: int,  # static per-segment chunk cap — min(prefill_chunk, budget)
    use_pallas: bool = False,
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
    int4_kernel: bool = True,
):
    """Token-budget packed prefill step over the paged KV cache.

    The padded ``forward_paged`` prefill runs [row_bucket, width] with every
    row padded to the widest pending chunk; this variant runs ONE flat
    [1, budget] buffer holding every prefilling row's next chunk back to
    back, so embedding/projection/MLP FLOPs — the bulk of prefill compute —
    scale with real tokens.  Attention runs the segment-masked path
    (ops/packed_prefill.py): per-token ``seg_ids`` map tokens to block
    tables / cached lengths, causal structure is per segment.

    New K/V are scattered into the page pools at ``slot_mapping`` exactly
    like forward_paged (padding slots -1 drop).  Returns
    (logits [R, 1, V], k_pages, v_pages[, k_scales, v_scales]) — logits
    are per SEGMENT at each segment's last packed position, so the engine's
    [row-bucket] sampling program is unchanged."""
    return forward_paged_packed_impl(
        params, cfg, input_ids, positions, k_pages, v_pages, slot_mapping,
        block_tables, cached_lens, new_lens, seg_ids, logits_at, tq,
        use_pallas, k_scales=k_scales, v_scales=v_scales,
        int4_kernel=int4_kernel,
    )


def forward_paged_packed_impl(
    params: dict,
    cfg: Qwen2Config,
    input_ids: jnp.ndarray,
    positions: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    slot_mapping: jnp.ndarray,
    block_tables: jnp.ndarray,
    cached_lens: jnp.ndarray,
    new_lens: jnp.ndarray,
    seg_ids: jnp.ndarray,
    logits_at: jnp.ndarray,
    tq: int,
    use_pallas: bool = False,
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
    int4_kernel: bool = True,
):
    """Unjitted body of ``forward_paged_packed`` so larger fused programs
    (serving/fused_step.py's one-dispatch prefill+decode step) can inline
    the packed phase without nested-jit donation clashes — the same split
    as forward_paged/forward_paged_impl."""
    from githubrepostorag_tpu.ops.packed_prefill import packed_prefill_attention

    quant = k_scales is not None
    nkv, hd = cfg.num_kv_heads, cfg.head_dim
    num_pages, page_size = k_pages.shape[2], k_pages.shape[3]
    total_slots = num_pages * page_size

    h = embedding_lookup(params["embed"], input_ids, dtype=_embed_dtype(params))
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)
    flat_slots = slot_mapping.reshape(-1)  # [T]
    flat_slots = jnp.where(flat_slots < 0, total_slots, flat_slots)
    pos_flat = positions.reshape(-1)

    scan_layers, q4_stacks = _split_q4(params["layers"])

    def body(carry, layer_xs):
        h, li = carry
        if quant:
            p, kp, vp, ks, vs = layer_xs
        else:
            p, kp, vp = layer_xs
            ks = vs = None
        # same w4a8=False pin as forward_paged: prompt processing keeps the
        # exact bf16-dequant contract regardless of the packed buffer size
        p = _with_layered_q4(p, q4_stacks, li, kernel=int4_kernel, w4a8=False)

        def attend(q, k, v):
            from githubrepostorag_tpu.serving.kv_cache import commit_paged

            k_t = k.reshape(-1, nkv, hd).swapaxes(0, 1)  # [n_kv, T, hd]
            v_t = v.reshape(-1, nkv, hd).swapaxes(0, 1)
            new_kp, new_ks = commit_paged(
                kp, k_t, flat_slots, ks if quant else None, page_size
            )
            new_vp, new_vs = commit_paged(
                vp, v_t, flat_slots, vs if quant else None, page_size
            )
            attn = packed_prefill_attention(
                q[0], new_kp, new_vp, block_tables, cached_lens, new_lens,
                seg_ids, pos_flat, tq=tq, use_pallas=use_pallas,
                k_scales=new_ks if quant else None,
                v_scales=new_vs if quant else None,
            )[None]  # [1, T, n_q, hd]
            if quant:
                return attn, (new_kp, new_vp, new_ks, new_vs)
            return attn, (new_kp, new_vp)

        h, cache = _block(cfg, h, p, cos, sin, attend)
        return (h, li + 1), cache

    if quant:
        xs = (scan_layers, k_pages, v_pages, k_scales, v_scales)
        (h, _), (k_pages, v_pages, k_scales, v_scales) = jax.lax.scan(
            body, (h, 0), xs
        )
    else:
        (h, _), (k_pages, v_pages) = jax.lax.scan(
            body, (h, 0), (scan_layers, k_pages, v_pages)
        )
    h = rms_norm(h, params["norm"], cfg.rms_norm_eps)
    # per-segment last-token hidden states: [1, T, d] -> [R, 1, d]
    h = h[0, logits_at][:, None, :]
    logits = _logits(params, h, int4_kernel=int4_kernel, w4a8=False)
    if quant:
        return logits, k_pages, v_pages, k_scales, v_scales
    return logits, k_pages, v_pages
