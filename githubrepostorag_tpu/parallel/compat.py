"""Version-compat shims for the distributed fabric.

``shard_map`` moved twice across the jax versions this tree supports:
``jax.experimental.shard_map.shard_map`` (<= 0.4.x, replication checking
via ``check_rep``) became ``jax.shard_map`` (>= 0.6, ``check_vma``).
Callers write against the new spelling once, here, instead of each
guessing — same shape as the ``pltpu.CompilerParams`` shim in
ops/packed_prefill.py.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` when it exists, else the experimental spelling
    with ``check_vma`` mapped onto the old ``check_rep`` kwarg."""
    new = getattr(jax, "shard_map", None)
    if new is not None:
        try:
            return new(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_vma=check_vma)
        except TypeError:  # a middle version: new location, old kwarg
            return new(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_rep=check_vma)
    from jax.experimental.shard_map import shard_map as old

    return old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)
