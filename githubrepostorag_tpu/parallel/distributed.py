"""Multi-host runtime initialization: jax.distributed over ICI/DCN.

The reference's model-level comm story is NCCL-inside-vLLM, unused at its
single-GPU scale (SURVEY.md §2.3 / §5.8).  The TPU-native equivalent is
jax.distributed: every host process joins one runtime, after which
``jax.devices()`` is the GLOBAL device list and the same
``make_mesh``/``pjit`` code paths scale from one chip to a multi-host pod
— collectives ride ICI within a slice and DCN across slices, routed by
XLA, with zero NCCL/MPI in-tree.

Env contract (standard jax.distributed variables, also set by GKE/TPU-VM
launchers):
  JAX_COORDINATOR_ADDRESS  host:port of process 0   (required to opt in)
  JAX_NUM_PROCESSES        total host processes
  JAX_PROCESS_ID           this process's index
On TPU pods jax can infer all three from the TPU metadata server, so
``maybe_initialize_distributed()`` also honors plain
``JAX_DISTRIBUTED=auto``.
"""

from __future__ import annotations

import os

from githubrepostorag_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_initialized = False


def maybe_initialize_distributed() -> bool:
    """Join the multi-host runtime when configured; no-op otherwise.

    Returns True when this process is part of a multi-host runtime.  Safe
    to call from every entry point (server, worker, ingest, trainer) —
    initialization happens at most once per process.
    """
    global _initialized
    if _initialized:
        return True

    import jax

    coordinator = os.environ.get("JAX_COORDINATOR_ADDRESS")
    auto = os.environ.get("JAX_DISTRIBUTED", "").lower() == "auto"
    if not coordinator and not auto:
        return False

    kwargs: dict = {}
    if coordinator:
        kwargs["coordinator_address"] = coordinator
        num = os.environ.get("JAX_NUM_PROCESSES")
        pid = os.environ.get("JAX_PROCESS_ID")
        if num is not None:
            kwargs["num_processes"] = int(num)
        if pid is not None:
            kwargs["process_id"] = int(pid)
    jax.distributed.initialize(**kwargs)
    _initialized = True
    logger.info(
        "jax.distributed up: process %d/%d, %d global devices (%d local)",
        jax.process_index(), jax.process_count(),
        jax.device_count(), jax.local_device_count(),
    )
    return True
