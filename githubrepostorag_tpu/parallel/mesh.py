"""Device-mesh construction over the five logical parallelism axes.

One mesh shape serves the whole framework: serving shards the decoder with
``tp``, ingest batch-embedding uses ``dp``, long-context training/scoring
spreads the sequence over ``sp`` (ring attention), and ``pp``/``ep`` are
reserved axes (size 1 until a pipeline schedule / MoE family lands) so
PartitionSpecs never need re-plumbing when they do.

The reference has nothing to mirror here (single GPU, TP=1 — SURVEY.md
§2.3); the design follows the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert collectives over ICI.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

# Order matters: earlier axes vary slowest over the device list.  ICI
# neighbours come from trailing axes, so put the bandwidth-hungry axes
# (tp, sp — per-layer collectives) last and the coarse-grained ones
# (dp — gradient/batch reductions only) first.
AXIS_NAMES = ("dp", "pp", "tp", "sp", "ep")


@dataclass(frozen=True)
class MeshPlan:
    dp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.tp * self.sp * self.ep

    def shape(self) -> dict[str, int]:
        return {"dp": self.dp, "pp": self.pp, "tp": self.tp, "sp": self.sp, "ep": self.ep}


def make_mesh(plan: MeshPlan | None = None, devices=None, **axes: int) -> Mesh:
    """Build a ``jax.sharding.Mesh`` for ``plan`` (or keyword axis sizes).

    ``make_mesh(tp=4, dp=2)`` -> 8-device mesh with axes
    (dp=2, pp=1, tp=4, sp=1, ep=1).  The axis-size product must equal the
    number of devices used.
    """
    if plan is None:
        plan = MeshPlan(**axes)
    elif axes:
        raise TypeError("pass either a MeshPlan or keyword axis sizes, not both")
    devices = list(jax.devices()) if devices is None else list(devices)
    if plan.n_devices > len(devices):
        raise ValueError(
            f"mesh plan {plan.shape()} needs {plan.n_devices} devices, "
            f"only {len(devices)} available"
        )
    devices = devices[: plan.n_devices]
    grid = np.asarray(devices).reshape(plan.dp, plan.pp, plan.tp, plan.sp, plan.ep)
    return Mesh(grid, AXIS_NAMES)


def plan_for_devices(
    n: int,
    *,
    num_heads: int | None = None,
    num_kv_heads: int | None = None,
    role: str = "serve",
) -> MeshPlan:
    """Factor ``n`` devices into a sensible default plan.

    serve: all-TP (latency — every chip works on every token), capped at the
    largest power-of-two divisor of ``num_heads`` (and kv heads if given, so
    the attention shard_map specs divide cleanly); leftover devices become dp.
    train: balance dp × tp × sp so batch, heads, and sequence all shard.
    ingest: all-DP (throughput — independent batch rows).
    """
    if n < 1:
        raise ValueError("need at least one device")

    def tp_for(n: int) -> int:
        # largest power of two that divides the device count AND every given
        # head count — never strands devices, never splits a head
        tp = _pow2_floor(n)
        heads = [h for h in (num_heads, num_kv_heads) if h is not None]
        while tp > 1 and not (n % tp == 0 and all(h % tp == 0 for h in heads)):
            tp //= 2
        return tp

    if role == "ingest":
        return MeshPlan(dp=n)
    if role == "serve":
        tp = tp_for(n)
        return MeshPlan(dp=n // tp, tp=tp)
    if role == "train":
        # peel off tp first (bounded by heads), then split the rest between
        # dp and sp as evenly as powers of two allow
        tp = tp_for(n)
        rest = n // tp
        sp = _pow2_floor(int(rest**0.5))
        while rest % sp != 0:
            sp //= 2
        return MeshPlan(dp=rest // sp, tp=tp, sp=sp)
    raise ValueError(f"unknown role {role!r}")


def plan_from_string(spec: str) -> MeshPlan:
    """Parse the MESH_SHAPE env format: ``"dp:2,tp:4"`` (axes omitted are
    size 1).  The operator's explicit override of ``plan_for_devices``."""
    axes: dict[str, int] = {}
    for part in spec.replace(" ", "").split(","):
        if not part:
            continue
        name, _, size = part.partition(":")
        if name not in AXIS_NAMES or not size.isdigit() or int(size) < 1:
            raise ValueError(
                f"bad MESH_SHAPE entry {part!r}: want axis:size with axis in {AXIS_NAMES}"
            )
        if name in axes:  # "tp:4,tp:2" is a typo, not a request for tp=2
            raise ValueError(f"bad MESH_SHAPE: axis {name!r} given twice")
        axes[name] = int(size)
    return MeshPlan(**axes)


def _pow2_floor(x: int) -> int:
    p = 1
    while p * 2 <= x:
        p *= 2
    return p
