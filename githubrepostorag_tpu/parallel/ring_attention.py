"""Ring attention: causal GQA attention with the sequence axis sharded over
the ``sp`` mesh axis.

Long context the TPU way: each device keeps its contiguous sequence shard of
Q resident and streams the K/V shards around the ring — step ``s`` folds the
block owned by device ``(i - s) mod n`` into an online (streaming) softmax
while ``lax.ppermute`` rotates the K/V blocks one hop over ICI.  Peak memory
per device is O(S/n) for activations and one K/V block in flight; no device
ever materialises the full [S, S] score matrix or the full K/V.

The reference *avoids* long context instead of scaling it (max-model-len
11712 + truncation cascade — SURVEY.md §5.7); this module is what makes
long-context a capability rather than a cap.

``ring_attention`` is the shard_map-local body (pure jnp + ppermute);
``make_ring_attend`` wraps it for global [B, S, H, D] arrays on a mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,  # [B, S_loc, n_q, hd]  this device's query shard
    k: jnp.ndarray,  # [B, S_loc, n_kv, hd] this device's K shard
    v: jnp.ndarray,  # [B, S_loc, n_kv, hd]
    seg: jnp.ndarray | None = None,  # [B, S_loc] per-token segment ids
    *,
    axis_name: str,
    axis_size: int,
    causal: bool = True,
) -> jnp.ndarray:
    """shard_map-local ring attention body.  Sequence shards are contiguous:
    device ``i`` owns global positions [i*S_loc, (i+1)*S_loc).  Returns the
    local attention output [B, S_loc, n_q, hd] in q.dtype; softmax runs in
    float32 (MXU-friendly bf16 inputs, f32 accumulation).

    ``seg`` packs many sequences into one ring pass: tokens attend only
    within their own segment id (and causally, when ``causal``).  The kv-side
    segment shard rotates around the ring with its K/V block, so every step
    masks the held block against the resident queries' ids.  Padding tokens
    carry a sentinel id out of the live range; their rows are garbage and the
    caller never samples them.
    """
    b, sq, n_q, hd = q.shape
    n_kv = k.shape[2]
    group = n_q // n_kv
    scale = 1.0 / (hd**0.5)

    my = lax.axis_index(axis_name)
    q_pos = my * sq + jnp.arange(sq)  # [Sq] global positions of local queries
    qg = q.reshape(b, sq, n_kv, group, hd).astype(jnp.float32)

    # online-softmax state, laid out [B, n_kv, g, Sq(, hd)] like ops.attention
    m = jnp.full((b, n_kv, group, sq), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b, n_kv, group, sq), dtype=jnp.float32)
    acc = jnp.zeros((b, n_kv, group, sq, hd), dtype=jnp.float32)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    k_blk, v_blk = k, v
    kv_seg_blk = seg  # rotates with its K/V block
    for step in range(axis_size):  # static unroll; axis_size is mesh-known
        owner = (my - step) % axis_size  # whose block we hold this step
        kv_pos = owner * sq + jnp.arange(sq)  # [Sk] global positions

        scores = (
            jnp.einsum("bsngh,btnh->bngst", qg, k_blk.astype(jnp.float32)) * scale
        )  # [B, n_kv, g, Sq, Sk]
        invalid = None  # [B or 1, Sq, Sk]
        if causal:
            invalid = (kv_pos[None, :] > q_pos[:, None])[None]
        if seg is not None:
            cross = seg[:, :, None] != kv_seg_blk[:, None, :]  # [B, Sq, Sk]
            invalid = cross if invalid is None else invalid | cross
        if invalid is not None:
            scores = jnp.where(invalid[:, None, None], NEG_INF, scores)

        new_m = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - new_m)  # rescale of previous accumulation
        p = jnp.exp(scores - new_m[..., None])
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bngst,btnh->bngsh", p, v_blk.astype(jnp.float32)
        )
        m = new_m

        if step < axis_size - 1:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
            if kv_seg_blk is not None:
                kv_seg_blk = lax.ppermute(kv_seg_blk, axis_name, perm)

    # with causal masking alone every query sees at least itself (step 0
    # covers the local diagonal) so l > 0; under segment masking a row can be
    # fully masked (no kv token shares its id), so guard the divide — the
    # where is bit-identical to the plain divide wherever l > 0
    l = jnp.where(l == 0.0, 1.0, l)
    out = acc / l[..., None]  # [B, n_kv, g, Sq, hd]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, sq, n_q, hd)
    return out.astype(q.dtype)


def make_ring_attend(
    mesh: Mesh,
    *,
    num_heads: int,
    num_kv_heads: int,
    axis_name: str = "sp",
    batch_axis: str = "dp",
    head_axis: str = "tp",
    causal: bool = True,
    segmented: bool = False,
):
    """Build ``attend(q, k, v)`` over *global* [B, S, H, hd] arrays: sequence
    sharded over ``sp``, batch over ``dp``, and heads over ``tp`` when tp
    divides both the Q- and KV-head counts (GQA: otherwise heads stay
    replicated inside the ring so local grouping matches global grouping).

    ``segmented=True`` returns ``attend(q, k, v, seg)`` instead, where ``seg``
    is [B, S] per-token segment ids sharded like the sequence: many packed
    sequences share one ring pass, masked to their own segments.
    """
    n = mesh.shape[axis_name]
    tp = mesh.shape.get(head_axis, 1)
    shard_heads = tp > 1 and num_heads % tp == 0 and num_kv_heads % tp == 0
    h_ax = head_axis if shard_heads else None
    b_ax = batch_axis if mesh.shape.get(batch_axis, 1) > 1 else None

    spec = P(b_ax, axis_name, h_ax, None)
    body = partial(ring_attention, axis_name=axis_name, axis_size=n, causal=causal)

    if n == 1 and not segmented:
        # degenerate ring: still honour the head/batch layout, skip ppermute
        from githubrepostorag_tpu.ops.attention import dense_attention

        return lambda q, k, v: dense_attention(q, k, v, causal=causal, q_offset=0)

    from githubrepostorag_tpu.parallel.compat import shard_map

    if segmented:
        seg_spec = P(b_ax, axis_name)
        return shard_map(
            body,
            mesh=mesh,
            in_specs=(spec, spec, spec, seg_spec),
            out_specs=spec,
            check_vma=False,
        )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
