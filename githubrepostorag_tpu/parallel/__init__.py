"""Mesh / sharding / collectives — the model-level distributed fabric.

The reference has no model-level parallelism at all (vLLM runs TP=1 on a
single GPU, no ``--tensor-parallel-size`` in helm/templates/qwen-deployment.yaml:23-33;
NCCL is present only transitively and unused — SURVEY.md §2.3).  The
TPU-native build makes the mesh a first-class subsystem instead:

  mesh.py           -- one ``jax.sharding.Mesh`` over the logical axes
                       (dp, pp, tp, sp, ep); factorisation helpers.
  sharding.py       -- PartitionSpec rules for the Qwen2 decoder and the
                       BERT encoder params (Megatron-style column/row TP),
                       with divisibility-checked fallback to replication,
                       plus ``shard_params`` / batch-sharding helpers.
  ring_attention.py -- sequence-parallel causal GQA attention: the sequence
                       axis lives sharded over ``sp``; K/V blocks rotate
                       around the ring via ``lax.ppermute`` while each step
                       folds one block into an online (streaming) softmax.

All collectives are either emitted by XLA/GSPMD from the sharding
annotations (TP psum/all-gather around the row/column-parallel matmuls) or
written once as ``ppermute`` inside ``shard_map`` (the ring).  Nothing here
speaks NCCL/MPI — ICI/DCN routing is the compiler's job.

PP and EP exist as mesh axes (size 1 by default) so pipeline/expert layouts
can slot in without re-plumbing callers; Qwen2-7B on a v5e-8 fits with TP
alone (SURVEY.md §2.3), so no pipeline schedule is implemented yet.
"""

from githubrepostorag_tpu.parallel.distributed import maybe_initialize_distributed
from githubrepostorag_tpu.parallel.mesh import (
    AXIS_NAMES,
    MeshPlan,
    make_mesh,
    plan_for_devices,
    plan_from_string,
)
from githubrepostorag_tpu.parallel.ring_attention import make_ring_attend, ring_attention
from githubrepostorag_tpu.parallel.sharding import (
    batch_spec,
    encoder_param_specs,
    qwen2_param_specs,
    shard_params,
)

__all__ = [
    "AXIS_NAMES",
    "maybe_initialize_distributed",
    "MeshPlan",
    "make_mesh",
    "plan_for_devices",
    "plan_from_string",
    "qwen2_param_specs",
    "encoder_param_specs",
    "shard_params",
    "batch_spec",
    "ring_attention",
    "make_ring_attend",
]
