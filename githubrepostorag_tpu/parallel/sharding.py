"""PartitionSpec rules: how each param of the Qwen2 decoder / BERT encoder
lays out over the mesh, plus helpers to apply them.

Megatron-style tensor parallelism expressed purely as sharding annotations
(GSPMD inserts the collectives):

  - attention: wq/bq column-parallel over heads, wo row-parallel (psum after
    the output projection); wk/wv shard only when tp divides the KV-head
    count — Qwen2's GQA has 2-4 KV heads, so at tp > n_kv they stay
    replicated (they are the small projections; this is the standard GQA
    trade, not a fallback of convenience).
  - MLP: wg/wu column-parallel over the intermediate dim, wd row-parallel.
  - embedding vocab-parallel; untied lm_head vocab-parallel on its output.
  - norms and other vectors replicated.

Every rule is divisibility-checked against the actual mesh: a dimension that
doesn't divide evenly is replicated rather than producing a GSPMD error, so
the same code serves tp=1 tests and tp=8 pods.

The reference ships nothing comparable (TP=1, SURVEY.md §2.3).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from githubrepostorag_tpu.models.qwen2 import Qwen2Config


def _axis(mesh: Mesh, name: str, dim: int) -> str | None:
    """Use mesh axis ``name`` for a dimension of size ``dim`` iff it divides."""
    size = mesh.shape.get(name, 1)
    return name if size > 1 and dim % size == 0 else None


def qwen2_param_specs(cfg: Qwen2Config, mesh: Mesh, params: dict | None = None) -> dict:
    """PartitionSpec pytree matching ``models.qwen2.init_params`` structure.

    When ``params`` is given and carries int8 ``QuantizedLinear`` leaves
    (models/quant.py), each projection's spec becomes a matching
    QuantizedLinear of specs — ``q`` sharded like the weight, ``s`` (per
    output channel) sharded like the weight's output axis — so TP serving
    composes with weight-only quantization."""
    nq, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    inter, d, v = cfg.intermediate_size, cfg.hidden_size, cfg.vocab_size

    # shard the fused head dim only when tp divides the head *count*, so the
    # [.., n, hd] reshape inside the block propagates without resharding
    q_tp = _axis(mesh, "tp", nq) and _axis(mesh, "tp", nq * hd)
    kv_tp = _axis(mesh, "tp", nkv) and _axis(mesh, "tp", nkv * hd)
    mlp_tp = _axis(mesh, "tp", inter)
    vocab_tp = _axis(mesh, "tp", v)

    layers = {
        "ln1": P(None, None),
        "ln2": P(None, None),
        "wq": P(None, None, q_tp),
        "bq": P(None, q_tp),
        "wk": P(None, None, kv_tp),
        "bk": P(None, kv_tp),
        "wv": P(None, None, kv_tp),
        "bv": P(None, kv_tp),
        "wo": P(None, q_tp, None),
    }
    if cfg.num_experts > 0:
        # MoE MLP: expert axis over ep (models/moe.py — GSPMD turns the
        # dispatch/combine einsums into expert-parallel all-to-alls);
        # router/shared-expert replicated
        ep = _axis(mesh, "ep", cfg.num_experts)
        layers.update({
            "router": P(None, None, None),
            "e_wg": P(None, ep, None, None),
            "e_wu": P(None, ep, None, None),
            "e_wd": P(None, ep, None, None),
            "s_wg": P(None, None, None),
            "s_wu": P(None, None, None),
            "s_wd": P(None, None, None),
            "s_gate": P(None, None, None),
        })
    else:
        layers.update({
            "wg": P(None, None, mlp_tp),
            "wu": P(None, None, mlp_tp),
            "wd": P(None, mlp_tp, None),
        })
    specs = {
        "embed": P(vocab_tp, None),
        "layers": layers,
        "norm": P(None),
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, vocab_tp)

    if params is not None:
        from githubrepostorag_tpu.models.quant import QuantizedLinear, QuantizedLinear4

        def adapt(spec: P) -> QuantizedLinear:
            # q shards like the weight; s is per-output-channel -> shard
            # like the weight's trailing axis (leading stacked axes kept)
            return QuantizedLinear(q=spec, s=P(*spec[:-2], spec[-1]))

        def adapt4(spec: P) -> QuantizedLinear4:
            # int4: q is [.., in/2, out] plane-packed and s/zs are
            # [.., in/group, out] — all three share the weight's rank and
            # axis meaning, so the weight's spec applies verbatim (GSPMD
            # pads if an axis size doesn't divide the smaller dims)
            return QuantizedLinear4(q=spec, s=spec, zs=spec)

        for name, leaf in params["layers"].items():
            if isinstance(leaf, QuantizedLinear):
                specs["layers"][name] = adapt(specs["layers"][name])
            elif isinstance(leaf, QuantizedLinear4):
                specs["layers"][name] = adapt4(specs["layers"][name])
        if isinstance(params.get("lm_head"), QuantizedLinear):
            specs["lm_head"] = adapt(specs["lm_head"])
        elif isinstance(params.get("lm_head"), QuantizedLinear4):
            specs["lm_head"] = adapt4(specs["lm_head"])
        from githubrepostorag_tpu.models.quant import QuantizedEmbedding

        if isinstance(params["embed"], QuantizedEmbedding):
            # embed scales are per vocab ROW: shard like the leading axis
            specs["embed"] = QuantizedEmbedding(
                q=specs["embed"], s=P(specs["embed"][0])
            )
    return specs


def encoder_param_specs(params, mesh: Mesh) -> dict:
    """The e5-small-class encoder is ~33M params — replicate everywhere and
    scale by sharding the *batch* over dp (see ``batch_spec``)."""
    del mesh
    return jax.tree.map(lambda _: P(), params)


def batch_spec(*, seq_parallel: bool = False) -> P:
    """Sharding for [B, S] token batches: batch over dp, sequence over sp
    when ring attention is in play."""
    return P("dp", "sp" if seq_parallel else None)


def shard_params(params, mesh: Mesh, specs) -> dict:
    """Place a param pytree onto the mesh per ``specs`` (a PartitionSpec
    pytree of the same structure)."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)
