"""Supervision wrappers over the events layer.

``ResilientBus`` decorates any ``ProgressBus`` with the delivery guarantees
the worker needs under partial failure:

  - every ``emit`` retries through a jittered ``RetryPolicy`` behind the
    shared ``bus`` circuit breaker;
  - terminal events (``final`` / ``error``) get a deeper retry budget than
    progress chatter — a lost ``turn`` is cosmetic, a lost ``final`` strands
    every SSE client and poller on that job;
  - an emit that exhausts its retries (or hits an open breaker) is DROPPED,
    but never silently: rag_bus_emit_drops_total counts it by event kind and
    the log carries the job id.  emit never raises into the job path.

The bus stream side (re-subscribe on connection loss) lives in the Redis
bus itself — a generator can't be usefully wrapped from out here without
buffering semantics the memory hub already provides.
"""

from __future__ import annotations

from typing import Any, AsyncIterator

from githubrepostorag_tpu.events.base import ProgressBus
from githubrepostorag_tpu.metrics import EVENT_EMIT_DROPS
from githubrepostorag_tpu.resilience.policy import RetryPolicy, get_breaker
from githubrepostorag_tpu.utils.logging import get_logger

logger = get_logger(__name__)

TERMINAL_EVENTS = ("final", "error")


class ResilientBus(ProgressBus):
    """Retrying, breaker-guarded, never-raising decorator for emit."""

    def __init__(
        self,
        inner: ProgressBus,
        policy: RetryPolicy | None = None,
        terminal_policy: RetryPolicy | None = None,
    ) -> None:
        self._inner = inner
        self._policy = policy or RetryPolicy.from_settings()
        self._terminal_policy = terminal_policy or RetryPolicy.from_settings(
            max_attempts=max(6, self._policy.max_attempts)
        )
        self._breaker = get_breaker("bus")

    async def emit(self, job_id: str, event: str, data: dict[str, Any]) -> None:
        # the breaker observes the whole retried emit as ONE dependency
        # call: a blip absorbed by a retry is a success, not a failure
        if not self._breaker.allow():
            EVENT_EMIT_DROPS.labels(event=event).inc()
            logger.warning("bus breaker open: dropped %r for job %s", event, job_id)
            return
        policy = self._terminal_policy if event in TERMINAL_EVENTS else self._policy
        try:
            await policy.call(self._inner.emit, job_id, event, data)
        except Exception as exc:  # noqa: BLE001 - emit must not kill the job
            self._breaker.record_failure()
            EVENT_EMIT_DROPS.labels(event=event).inc()
            logger.warning(
                "emit %r for job %s dropped after %d attempts: %s",
                event, job_id, policy.max_attempts, exc,
            )
        else:
            self._breaker.record_success()

    def stream(self, job_id: str) -> AsyncIterator[str]:
        return self._inner.stream(job_id)

    async def close(self) -> None:
        await self._inner.close()
