"""Deterministic fault injection for chaos tests and staging soak.

A fault spec rides the ``FAULTS`` env var::

    FAULTS="redis.send:drop@3;cql.exchange:error@0.5;llm.complete:delay=2"

Grammar per ``;``-separated entry: ``site:action[@param]`` where

  - ``site`` is a seam name wired into the I/O layers (``redis.send``,
    ``redis.recv``, ``cql.exchange``, ``llm.complete``, ``bus.emit``)
  - ``action`` is ``drop`` (the operation is lost — connection seams close
    the socket and raise, the bus seam raises so the supervised emit path
    retries and counts), ``error`` (raise ``InjectedFault``, a
    ``ConnectionError`` subclass so every reconnect path treats it as a
    dead dependency), or ``delay=SECONDS`` (sleep, then proceed)
  - ``@param`` selects WHICH calls fire: an integer N >= 1 means
    deterministically every Nth call at that site (``drop@3`` = calls
    3, 6, 9, ...); a float in (0, 1) is a seeded per-call probability
    (``error@0.5``); ``window=N:M`` fires only on calls N..M inclusive,
    1-based (``delay=2@window=5:8`` = calls 5, 6, 7, 8; ``error@window=40:``
    is open-ended from call 40) so a chaos script can express "healthy,
    then dies, then recovers" at one site; omitted means every call.

Probabilities draw from ``random.Random(FAULTS_SEED ^ crc32(site))`` — the
builtin ``hash()`` is salted per process and would unseed the chaos suite.
When ``FAULTS`` is unset the seams cost one attribute load and a falsy
check; no parsing, no locks, no metrics.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from random import Random

from githubrepostorag_tpu.config import get_settings
from githubrepostorag_tpu.metrics import FAULTS_INJECTED
from githubrepostorag_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class InjectedFault(ConnectionError):
    """An injected dependency failure.  Subclasses ConnectionError (itself
    an OSError) so the production reconnect/replay/retry paths exercise
    their real branches instead of a parallel test-only codepath."""


class FaultSpecError(ValueError):
    """Malformed FAULTS spec — raised at parse, never mid-traffic."""


@dataclass
class _Fault:
    site: str
    action: str  # "drop" | "error" | "delay"
    every: int | None = None  # fire every Nth call
    probability: float | None = None  # seeded per-call probability
    window_lo: int | None = None  # fire only on calls N..M (1-based, inclusive)
    window_hi: int | None = None  # None = open-ended
    delay_s: float = 0.0
    calls: int = 0
    fired: int = 0
    _rng: Random = field(default_factory=Random, repr=False)

    def should_fire(self) -> bool:
        self.calls += 1
        if self.window_lo is not None:
            if self.calls < self.window_lo:
                return False
            return self.window_hi is None or self.calls <= self.window_hi
        if self.every is not None:
            return self.calls % self.every == 0
        if self.probability is not None:
            return self._rng.random() < self.probability
        return True


def _parse_entry(entry: str, seed: int) -> _Fault:
    entry = entry.strip()
    site, sep, action_spec = entry.partition(":")
    if not sep or not site or not action_spec:
        raise FaultSpecError(f"FAULTS entry {entry!r}: expected 'site:action[@param]'")
    action_spec, _, param = action_spec.partition("@")
    action, _, value = action_spec.partition("=")
    if action not in ("drop", "error", "delay"):
        raise FaultSpecError(f"FAULTS entry {entry!r}: unknown action {action!r}")
    fault = _Fault(site=site.strip(), action=action)
    fault._rng = Random(seed ^ zlib.crc32(fault.site.encode()))
    if action == "delay":
        try:
            fault.delay_s = float(value)
        except ValueError:
            raise FaultSpecError(f"FAULTS entry {entry!r}: delay needs '=seconds'") from None
    elif value:
        raise FaultSpecError(f"FAULTS entry {entry!r}: only delay takes '=value'")
    if param.startswith("window="):
        lo_s, sep2, hi_s = param[len("window="):].partition(":")
        if not sep2:
            raise FaultSpecError(
                f"FAULTS entry {entry!r}: window needs 'N:M' (M empty = open-ended)"
            )
        try:
            fault.window_lo = int(lo_s)
            fault.window_hi = int(hi_s) if hi_s else None
        except ValueError:
            raise FaultSpecError(
                f"FAULTS entry {entry!r}: window bounds must be integers"
            ) from None
        if fault.window_lo < 1 or (
            fault.window_hi is not None and fault.window_hi < fault.window_lo
        ):
            raise FaultSpecError(
                f"FAULTS entry {entry!r}: window needs 1 <= N <= M"
            )
    elif param:
        try:
            num = float(param)
        except ValueError:
            raise FaultSpecError(f"FAULTS entry {entry!r}: bad param {param!r}") from None
        if num >= 1:
            if num != int(num):
                raise FaultSpecError(
                    f"FAULTS entry {entry!r}: every-Nth param must be an integer"
                )
            fault.every = int(num)
        elif 0 < num < 1:
            fault.probability = num
        else:
            raise FaultSpecError(f"FAULTS entry {entry!r}: param must be >0")
    return fault


class FaultRegistry:
    """Parsed faults grouped by site.  One instance per process, rebuilt
    when tests reload settings (conftest calls ``reset_faults``)."""

    def __init__(self, faults: list[_Fault]) -> None:
        self.by_site: dict[str, list[_Fault]] = {}
        for f in faults:
            self.by_site.setdefault(f.site, []).append(f)
        self._lock = threading.Lock()
        # timestamped injection ring for the /debug/timeline exporter —
        # prometheus keeps the totals, this keeps the WHEN
        self._events: deque[tuple[float, str, str]] = deque(maxlen=256)

    @classmethod
    def from_env(cls) -> "FaultRegistry":
        s = get_settings()
        spec = s.faults.strip()
        if not spec:
            return cls([])
        faults = [_parse_entry(e, s.faults_seed) for e in spec.split(";") if e.strip()]
        if faults:
            logger.warning("FAULT INJECTION ACTIVE: %s", spec)
        return cls(faults)

    def decide(self, site: str) -> tuple[str | None, float]:
        """-> (action or None, delay_s).  Counters advance under a lock so
        every-Nth cadence stays exact across threads."""
        entries = self.by_site.get(site)
        if not entries:
            return None, 0.0
        with self._lock:
            for fault in entries:
                if fault.should_fire():
                    fault.fired += 1
                    FAULTS_INJECTED.labels(site=site, action=fault.action).inc()
                    self._events.append(
                        (time.monotonic(), site, fault.action))
                    return fault.action, fault.delay_s
        return None, 0.0

    def events(self, t_min: float = 0.0) -> list[tuple[float, str, str]]:
        """Injections fired at or after ``t_min`` as (monotonic_t, site,
        action) — the timeline's fault-instant source."""
        with self._lock:
            return [e for e in self._events if e[0] >= t_min]

    def stats(self) -> dict[str, list[dict]]:
        with self._lock:
            return {
                site: [
                    {"action": f.action, "calls": f.calls, "fired": f.fired}
                    for f in entries
                ]
                for site, entries in self.by_site.items()
            }


_registry: FaultRegistry | None = None
_registry_lock = threading.Lock()


def get_registry() -> FaultRegistry:
    global _registry
    reg = _registry
    if reg is None:
        with _registry_lock:
            reg = _registry
            if reg is None:
                reg = _registry = FaultRegistry.from_env()
    return reg


def reset_faults() -> None:
    """Force a re-parse of FAULTS on next use (test isolation)."""
    global _registry
    with _registry_lock:
        _registry = None


def active() -> bool:
    return bool(get_registry().by_site)


def fire_sync(site: str) -> bool:
    """Fault seam for synchronous code (CQL store, LLM backends).

    Returns True when a ``drop`` fired — the caller owns drop semantics
    (close a socket, skip a publish).  ``error`` raises ``InjectedFault``;
    ``delay`` sleeps then returns False.  Zero-cost when FAULTS is unset.
    """
    reg = get_registry()
    if not reg.by_site:
        return False
    action, delay_s = reg.decide(site)
    if action is None:
        return False
    if action == "delay":
        time.sleep(delay_s)
        return False
    if action == "error":
        raise InjectedFault(f"injected error at {site}")
    return True  # drop


async def fire_async(site: str) -> bool:
    """Async twin of ``fire_sync`` for seams on the event loop (RESP
    client, progress bus).  Delays use asyncio.sleep — a blocking sleep
    here would stall every SSE stream and dequeue in the process (the
    exact ASY001 bug tpulint flags)."""
    import asyncio

    reg = get_registry()
    if not reg.by_site:
        return False
    action, delay_s = reg.decide(site)
    if action is None:
        return False
    if action == "delay":
        await asyncio.sleep(delay_s)
        return False
    if action == "error":
        raise InjectedFault(f"injected error at {site}")
    return True  # drop
