"""Resilience primitives: retry, circuit breaking, and deadline budgets.

The north star serves millions of users, where transient dependency failure
is the steady state, not the exception ([vllm-pagedattention]'s argument
applied to the control plane: throughput dies to stalls and orphaned work,
not FLOPs).  Three primitives, each process-cheap and asyncio-safe:

  - ``RetryPolicy`` — jittered exponential backoff (full jitter, seeded for
    deterministic tests).  ``delay_for`` is the schedule, ``call`` the async
    driver; sync callers iterate ``delays()`` themselves and sleep however
    their context allows (never ``time.sleep`` inside ``async def`` —
    tpulint ASY001 exists because that one bug froze the reference's loop).
  - ``CircuitBreaker`` — per-dependency closed/open/half-open with counted
    state transitions, registered in a process-wide registry so /health can
    report every breaker and go 503 while one is open.
  - ``Deadline`` — a wall-budget object threaded API -> queue -> worker ->
    agent -> LLM -> engine.  Crossing a process boundary uses ``to_wire``
    (budget + epoch stamp; monotonic clocks don't travel), inside a process
    it rides a thread-local scope so the LLM protocol signature stays
    unchanged.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Iterator

from githubrepostorag_tpu.config import get_settings
from githubrepostorag_tpu.metrics import BREAKER_TRANSITIONS
from githubrepostorag_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class DeadlineExceeded(Exception):
    """The request's wall budget ran out (checked between agent stages and
    at LLM submission; the engine reaps its own rows at step boundaries)."""


class CircuitOpen(ConnectionError):
    """Raised when a call is refused because the dependency's breaker is
    open.  Subclasses ConnectionError so callers that already treat
    connection failures as retryable/degradable handle it for free."""


# --------------------------------------------------------------------- retry


@dataclass
class RetryPolicy:
    """Jittered exponential backoff: delay(n) = uniform(d/2, d) with
    d = min(cap, base * 2**n) (AWS full-jitter, halved floor so retries
    never synchronize across workers).  ``seed`` pins the jitter stream for
    deterministic tests; production leaves it None."""

    max_attempts: int = 4
    base: float = 0.05
    cap: float = 2.0
    seed: int | None = None
    _rng: random.Random = field(init=False, repr=False)

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)

    @classmethod
    def from_settings(cls, **overrides: Any) -> "RetryPolicy":
        s = get_settings()
        kw: dict[str, Any] = dict(
            max_attempts=s.retry_max_attempts,
            base=s.retry_base_seconds,
            cap=s.retry_cap_seconds,
        )
        kw.update(overrides)
        return cls(**kw)

    def delay_for(self, attempt: int) -> float:
        d = min(self.cap, self.base * (2 ** max(0, attempt)))
        return self._rng.uniform(d / 2, d)

    def delays(self) -> Iterator[float]:
        """The backoff schedule between attempts (max_attempts - 1 gaps)."""
        for attempt in range(max(0, self.max_attempts - 1)):
            yield self.delay_for(attempt)

    async def call(
        self,
        fn: Callable[..., Awaitable[Any]],
        *args: Any,
        retry_on: tuple[type[BaseException], ...] = (ConnectionError, OSError),
        **kwargs: Any,
    ) -> Any:
        """Await ``fn`` up to ``max_attempts`` times, sleeping the jittered
        schedule between failures.  The final failure propagates."""
        import asyncio

        last: BaseException | None = None
        for attempt in range(self.max_attempts):
            try:
                return await fn(*args, **kwargs)
            except retry_on as exc:
                last = exc
                if attempt + 1 >= self.max_attempts:
                    break
                delay = self.delay_for(attempt)
                logger.debug("retry %d/%d after %s: sleeping %.3fs",
                             attempt + 1, self.max_attempts, exc, delay)
                await asyncio.sleep(delay)
        assert last is not None
        raise last


# ------------------------------------------------------------------ breaker

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-dependency circuit breaker.

    closed -> open after ``failure_threshold`` consecutive failures; open
    refuses calls (``CircuitOpen``) for ``reset_seconds``, then one probe is
    allowed (half-open); probe success closes, probe failure re-opens.
    Every state transition is counted (``snapshot()``) and exported
    (rag_breaker_transitions_total) so /health and dashboards see flapping,
    not just the current state.  Thread-safe: the agent runs in executor
    threads while the bus lives on the loop.
    """

    def __init__(
        self,
        name: str,
        failure_threshold: int | None = None,
        reset_seconds: float | None = None,
    ) -> None:
        s = get_settings()
        self.name = name
        self.failure_threshold = failure_threshold or s.breaker_failure_threshold
        self.reset_seconds = (
            s.breaker_reset_seconds if reset_seconds is None else reset_seconds
        )
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self.transitions: dict[str, int] = {}

    # -- state machine (all under the lock) --

    def _transition(self, to_state: str) -> None:
        if to_state == self._state:
            return
        self._state = to_state
        self.transitions[to_state] = self.transitions.get(to_state, 0) + 1
        BREAKER_TRANSITIONS.labels(dep=self.name, to_state=to_state).inc()
        logger.info("breaker %s -> %s", self.name, to_state)

    def allow(self) -> bool:
        """True if a call may proceed now.  In half-open, only the single
        probe call is admitted until it reports success/failure."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if time.monotonic() - self._opened_at >= self.reset_seconds:
                    self._transition(HALF_OPEN)
                    self._probing = True
                    return True
                return False
            # half-open: one in-flight probe at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probing = False
            if self._state == HALF_OPEN:
                self._opened_at = time.monotonic()
                self._transition(OPEN)
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._opened_at = time.monotonic()
                self._transition(OPEN)

    def _end_probe(self) -> None:
        with self._lock:
            self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "transitions": dict(self.transitions),
            }

    async def call(
        self,
        fn: Callable[..., Awaitable[Any]],
        *args: Any,
        failure_on: tuple[type[BaseException], ...] = (ConnectionError, OSError),
        **kwargs: Any,
    ) -> Any:
        if not self.allow():
            raise CircuitOpen(f"circuit {self.name!r} is open")
        try:
            result = await fn(*args, **kwargs)
        except failure_on:
            self.record_failure()
            raise
        except Exception:
            # non-connection errors are the dependency answering, not dying
            self._end_probe()
            raise
        self.record_success()
        return result


_BREAKERS: dict[str, CircuitBreaker] = {}
_BREAKERS_LOCK = threading.Lock()


def get_breaker(name: str, **kwargs: Any) -> CircuitBreaker:
    """Process-wide breaker registry, one breaker per dependency name."""
    with _BREAKERS_LOCK:
        breaker = _BREAKERS.get(name)
        if breaker is None:
            breaker = CircuitBreaker(name, **kwargs)
            _BREAKERS[name] = breaker
        return breaker


def breaker_states() -> dict[str, dict[str, Any]]:
    with _BREAKERS_LOCK:
        return {name: b.snapshot() for name, b in _BREAKERS.items()}


def reset_breakers() -> None:
    """Drop every registered breaker (test isolation)."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()


# ----------------------------------------------------------------- deadline


class Deadline:
    """A wall-clock budget.  Created once at admission (API), then threaded
    with the job; each layer spends from the same budget instead of stacking
    independent timeouts that can sum past what the client will wait."""

    __slots__ = ("_expires_at",)

    def __init__(self, budget_s: float) -> None:
        self._expires_at = time.monotonic() + max(0.0, budget_s)

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self._expires_at

    def remaining(self) -> float:
        return max(0.0, self._expires_at - time.monotonic())

    def monotonic_deadline(self) -> float:
        """Absolute time.monotonic() timestamp — same-process only (the
        engine compares it against its own clock at step boundaries)."""
        return self._expires_at

    def to_wire(self) -> dict[str, float]:
        """Serialize for a queue hop.  Monotonic clocks don't cross process
        boundaries, so the wire form is remaining budget + an epoch stamp;
        the receiver subtracts its own queue-transit time from the budget."""
        return {"budget_ms": int(self.remaining() * 1000), "t0": time.time()}

    @classmethod
    def from_wire(cls, wire: dict[str, float]) -> "Deadline":
        budget_s = float(wire.get("budget_ms", 0)) / 1000.0
        transit = max(0.0, time.time() - float(wire.get("t0", time.time())))  # tpulint: disable=OBS001 -- cross-process transit needs the wall clock; monotonic bases differ per host and the max(0,...) clamp absorbs skew
        return cls(budget_s - transit)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Deadline(remaining={self.remaining():.3f}s)"


_SCOPE = threading.local()


@contextlib.contextmanager
def deadline_scope(deadline: Deadline | None):
    """Bind ``deadline`` to the current thread for the duration.  The agent
    sets this around a run; LLM backends read it via ``current_deadline()``
    so the ``LLM`` protocol signature stays unchanged.  Thread-local, not a
    contextvar: the agent and its LLM calls share one executor thread, and
    the engine's driver thread must NOT inherit it."""
    prev = getattr(_SCOPE, "deadline", None)
    _SCOPE.deadline = deadline
    try:
        yield deadline
    finally:
        _SCOPE.deadline = prev


def current_deadline() -> Deadline | None:
    return getattr(_SCOPE, "deadline", None)


@contextlib.contextmanager
def priority_scope(klass: str | None):
    """Bind the job's SLO priority class to the current thread, same shape
    and rationale as ``deadline_scope``: the worker sets it around a run,
    LLM backends read it via ``current_priority()`` and stamp it on engine
    requests — the ``LLM`` protocol signature stays unchanged."""
    prev = getattr(_SCOPE, "priority", None)
    _SCOPE.priority = klass
    try:
        yield klass
    finally:
        _SCOPE.priority = prev


def current_priority() -> str | None:
    return getattr(_SCOPE, "priority", None)
