"""Resilience layer: retry/breaker/deadline policies, deterministic fault
injection, and supervision wrappers (see policy.py / faults.py /
supervise.py module docs)."""

from githubrepostorag_tpu.resilience.policy import (
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    breaker_states,
    current_deadline,
    deadline_scope,
    get_breaker,
    reset_breakers,
)
from githubrepostorag_tpu.resilience.faults import (
    FaultSpecError,
    InjectedFault,
    fire_async,
    fire_sync,
    reset_faults,
)
from githubrepostorag_tpu.resilience.supervise import ResilientBus
from githubrepostorag_tpu.resilience.admission import (
    admission_hint,
    clear_hint_provider,
    set_hint_provider,
    should_shed,
)

__all__ = [
    "CircuitBreaker",
    "admission_hint",
    "clear_hint_provider",
    "set_hint_provider",
    "should_shed",
    "CircuitOpen",
    "Deadline",
    "DeadlineExceeded",
    "FaultSpecError",
    "InjectedFault",
    "ResilientBus",
    "RetryPolicy",
    "breaker_states",
    "current_deadline",
    "deadline_scope",
    "fire_async",
    "fire_sync",
    "get_breaker",
    "reset_breakers",
    "reset_faults",
]
