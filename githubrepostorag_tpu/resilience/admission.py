"""Per-class admission decisions consulted by the API's load-shedding check.

The SLO plane (obs/slo.py) registers two callables here at construction:
the legacy fleet-wide ``admission_hint`` and the per-class
``decision_table``.  This module deliberately holds only callables so
``resilience`` never imports ``obs`` (no import cycle) and works unchanged
when no plane exists (standalone workers, unit tests): the default
decision is "accept".

Decisions form the graceful-degradation ladder, least to most drastic:

    "accept"   all SLOs ok for the class
    "throttle" the protected class is in warn — batch admission tightens
               (headroom doubles engine-side) but requests still queue
    "preempt"  the protected class is critical — the engine is parking
               batch-class victims to the KV host tier; batch intake
               continues but expect queueing
    "shed"     the class's own error budget is burning critically AND
               preemption has no victims left to reclaim — reject with
               429 now, before the queue does it slower

Failure is open by design — a broken SLO plane must never take the API
down with it — but no longer silent: every fail-open is logged and counted
(``rag_admission_failopen_total``) so a dead provider shows up on a
dashboard instead of masquerading as a healthy fleet.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

from githubrepostorag_tpu.metrics import ADMISSION_FAILOPEN

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_provider: Callable[[], str] | None = None
_table_provider: Callable[[], dict] | None = None

ACCEPT, THROTTLE, PREEMPT, SHED = "accept", "throttle", "preempt", "shed"
_DECISIONS = (ACCEPT, THROTTLE, PREEMPT, SHED)


def set_hint_provider(fn: Callable[[], str]) -> None:
    global _provider
    with _lock:
        _provider = fn


def clear_hint_provider() -> None:
    global _provider
    with _lock:
        _provider = None


def set_table_provider(fn: Callable[[], dict]) -> None:
    """Register the per-class decision-table callable (the SLO plane's
    ``decision_table``)."""
    global _table_provider
    with _lock:
        _table_provider = fn


def clear_table_provider() -> None:
    global _table_provider
    with _lock:
        _table_provider = None


def _failopen(what: str, exc: Exception | None = None) -> None:
    ADMISSION_FAILOPEN.inc()
    if exc is not None:
        logger.warning("admission %s failed open: %r", what, exc)
    else:
        logger.warning("admission %s failed open: invalid value", what)


def admission_hint() -> str:
    """Legacy fleet-wide hint (worst state across every class); failure-open
    with logging + counting."""
    with _lock:
        fn = _provider
    if fn is None:
        return ACCEPT
    try:
        hint = fn()
    except Exception as exc:  # noqa: BLE001 - hint is advisory, never fatal
        _failopen("hint provider", exc)
        return ACCEPT
    if hint not in (ACCEPT, THROTTLE, SHED):
        _failopen("hint provider")
        return ACCEPT
    return hint


def admission_table() -> dict[str, str]:
    """Current per-class decision table ({} when no plane is registered).
    A raising or garbage-returning provider fails open to {} — logged and
    counted, never fatal."""
    from githubrepostorag_tpu.resilience.faults import InjectedFault, get_registry

    with _lock:
        fn = _table_provider
    if fn is None:
        return {}
    try:
        # fault seam: FAULTS="admission.decide:error" proves the fail-open
        # path under chaos load (tests/test_chaos.py).  Inlined rather than
        # fire_sync() because admission runs on the event loop — a delay
        # action degrades to an immediate error instead of a blocking sleep.
        reg = get_registry()
        if reg.by_site and reg.decide("admission.decide")[0] is not None:
            raise InjectedFault("injected fault at admission.decide")
        table = fn()
    except Exception as exc:  # noqa: BLE001 - advisory, never fatal
        _failopen("table provider", exc)
        return {}
    if not isinstance(table, dict):
        _failopen("table provider")
        return {}
    out: dict[str, str] = {}
    for klass, decision in table.items():
        if decision in _DECISIONS:
            out[str(klass)] = decision
        else:
            _failopen("table provider")
    return out


def admission_decision(klass: str | None = None) -> str:
    """Decision for one priority class.  Unknown classes inherit the
    legacy fleet-wide hint so a brand-new label is still protected by the
    old worst-state behavior rather than silently accepted."""
    table = admission_table()
    if klass is not None and klass in table:
        return table[klass]
    hint = admission_hint()
    return hint if hint in _DECISIONS else ACCEPT


def should_shed(klass: str | None = None) -> bool:
    return admission_decision(klass) == SHED
