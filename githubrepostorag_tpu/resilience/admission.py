"""Admission hint consulted by the API's load-shedding check.

The SLO plane (obs/slo.py) registers its ``admission_hint`` callable here
at construction; this module deliberately holds only that callable so
``resilience`` never imports ``obs`` (no import cycle) and works unchanged
when no plane exists (standalone workers, unit tests): the default hint is
"accept".

Hints: "accept" (all SLOs ok) | "throttle" (warn: burn rates elevated on
both windows) | "shed" (critical: the error budget is burning at a rate
that exhausts it within hours — reject load now, before the queue does).
"""

from __future__ import annotations

import threading
from typing import Callable

_lock = threading.Lock()
_provider: Callable[[], str] | None = None

ACCEPT, THROTTLE, SHED = "accept", "throttle", "shed"


def set_hint_provider(fn: Callable[[], str]) -> None:
    global _provider
    with _lock:
        _provider = fn


def clear_hint_provider() -> None:
    global _provider
    with _lock:
        _provider = None


def admission_hint() -> str:
    """Current fleet admission hint; failure-open (a broken or absent SLO
    plane must never take the API down with it)."""
    with _lock:
        fn = _provider
    if fn is None:
        return ACCEPT
    try:
        hint = fn()
    except Exception:  # noqa: BLE001 - hint is advisory, never fatal
        return ACCEPT
    return hint if hint in (ACCEPT, THROTTLE, SHED) else ACCEPT


def should_shed() -> bool:
    return admission_hint() == SHED
