"""Streaming ingest -> live device index (PR 13): mutation-log ordering,
watermarks and durable replay; the apply loop draining into the device
index while queries run; background hole-reclaim compaction; and
versioned snapshot/restore.

The acceptance bars from the ISSUE are pinned here:

* concurrent apply-vs-query: every result a query thread observes equals
  some exact PREFIX of the mutation stream (watermark-bounded
  consistency), with ZERO live XLA compiles under sustained mutation —
  ``compile_guard`` over both the search and mutation program counters;
* churn: tombstoned holes return to ~0 via in-place compaction with the
  ``full_syncs`` counter unmoved (no whole-table re-put on the hot path)
  and the capacity bucket never growing;
* snapshot -> restore: the replica is score- and tie-order-IDENTICAL
  (exact float equality, not just allclose) and replays only the log
  suffix past the snapshot watermark.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from githubrepostorag_tpu.ingest.stream import (
    DELETE,
    UPSERT,
    MutationLog,
    StreamSink,
    apply_ops,
    watch_local,
)
from githubrepostorag_tpu.metrics import (
    INDEX_FULL_SYNCS,
    INDEX_HOLES,
    INDEX_WATERMARK,
    counter_value,
)
from githubrepostorag_tpu.retrieval import (
    DeviceIndexedStore,
    LiveIndexApplier,
    LiveIndexedStore,
    get_live_applier,
    live_index_payload,
    register_live_applier,
    load_snapshot,
    restore_replica,
    save_snapshot,
)
from githubrepostorag_tpu.retrieval.live_index import TOTAL_SCOPE
from githubrepostorag_tpu.store.base import Doc
from githubrepostorag_tpu.store.memory import MemoryVectorStore
from tests.helpers.compile_guard import compile_guard

DIM = 16


def _mk_docs(rng, n, prefix="d", dim=DIM):
    return [
        Doc(f"{prefix}{i:04d}", f"text {i}",
            {"namespace": "default", "repo": f"repo{i % 3}"},
            rng.normal(size=dim).astype(np.float32))
        for i in range(n)
    ]


def _ids(hits):
    return [h.doc.doc_id for h in hits]


def _scores(hits):
    return [h.score for h in hits]


# ------------------------------------------------------------- mutation log


def test_log_assigns_one_total_order_and_per_table_watermarks():
    log = MutationLog()
    rng = np.random.default_rng(0)
    s1 = log.append_upsert("a", _mk_docs(rng, 3, prefix="a"))
    s2 = log.append_upsert("b", _mk_docs(rng, 2, prefix="b"))
    s3 = log.append_delete("a", ["a0000"])
    # ONE total order across tables: seqs are strictly monotonic
    assert (s1, s2, s3) == (3, 5, 6)
    wm = log.watermark()
    assert wm["seq"] == 6
    assert wm["tables"] == {"a": 6, "b": 5}
    ops = log.read_since(0)
    assert [op.seq for op in ops] == [1, 2, 3, 4, 5, 6]
    assert [op.kind for op in ops] == [UPSERT] * 5 + [DELETE]
    assert [op.seq for op in log.read_since(4)] == [5, 6]
    assert [op.seq for op in log.read_since(2, limit=2)] == [3, 4]
    assert log.read_since(6) == []


def test_log_durable_replay_trim_and_bit_exact_vectors(tmp_path):
    path = str(tmp_path / "wal" / "mutation_log.jsonl")
    rng = np.random.default_rng(1)
    docs = _mk_docs(rng, 4)
    log = MutationLog(path=path)
    log.append_upsert("t", docs)
    log.append_delete("t", [docs[0].doc_id])
    wm = log.watermark()
    log.close()
    # a restarted replica replays the file and lands on the same watermark
    replayed = MutationLog(path=path)
    assert replayed.watermark() == wm
    ops = replayed.read_since(0)
    assert len(ops) == 5
    for op, d in zip(ops[:4], docs):
        # float32 -> repr -> float32 must round-trip BIT-exactly, or
        # replayed scores drift from the original's
        assert op.vector.dtype == np.float32
        np.testing.assert_array_equal(
            op.vector, np.asarray(d.vector, dtype=np.float32))
    # trim drops the memory tail; older cursors fall back to the file
    assert replayed.trim(3) == 3
    assert [op.seq for op in replayed.read_since(0)] == [1, 2, 3, 4, 5]
    assert [op.seq for op in replayed.read_since(3)] == [4, 5]
    replayed.close()
    # memory-only logs refuse to trim: the tail is their only replay source
    mem = MutationLog()
    mem.append_upsert("t", docs[:1])
    assert mem.trim(1) == 0
    assert len(mem.read_since(0)) == 1


class _RecordingStore(MemoryVectorStore):
    def __init__(self):
        super().__init__()
        self.calls = []

    def upsert(self, table, docs):
        self.calls.append(("upsert", table, len(docs)))
        return super().upsert(table, docs)

    def delete(self, table, doc_ids):
        doc_ids = list(doc_ids)
        self.calls.append(("delete", table, len(doc_ids)))
        return super().delete(table, doc_ids)


def test_stream_sink_routes_writes_and_apply_ops_batches_runs():
    rng = np.random.default_rng(2)
    log = MutationLog()
    sink = StreamSink(log)
    docs_t, docs_u = _mk_docs(rng, 3), _mk_docs(rng, 2, prefix="u")
    assert sink.upsert("t", docs_t) == 3
    assert sink.upsert("u", docs_u) == 2
    assert sink.delete("t", [d.doc_id for d in docs_t[:2]]) == 2
    sink.save()  # durable already; must be a no-op, not an error
    # apply batches each maximal same-(kind, table) run into ONE store call
    rec = _RecordingStore()
    apply_ops(rec, log.read_since(0))
    assert rec.calls == [("upsert", "t", 3), ("upsert", "u", 2),
                         ("delete", "t", 2)]
    direct = MemoryVectorStore()
    direct.upsert("t", docs_t)
    direct.upsert("u", docs_u)
    direct.delete("t", [d.doc_id for d in docs_t[:2]])
    for table in ("t", "u"):
        assert rec.count(table) == direct.count(table)
        q = rng.normal(size=DIM).astype(np.float32)
        assert _ids(rec.search(table, q, 5)) == _ids(direct.search(table, q, 5))


# ------------------------------------------------------------------ applier


def test_applier_thread_drains_and_publishes_watermarks():
    log = MutationLog()
    store = MemoryVectorStore()
    # long idle interval: shutdown latency below proves poke() releases
    # the park point instead of waiting the interval out
    applier = LiveIndexApplier(log, store, apply_batch=4,
                               compact_interval_s=30.0).start()
    try:
        rng = np.random.default_rng(3)
        log.append_upsert("t", _mk_docs(rng, 10))
        assert applier.flush(timeout=10)
        assert store.count("t") == 10
        assert applier.applied_seq() == log.watermark()["seq"] == 10
        p = applier.payload()
        assert p["enabled"] is True
        assert p["lag_ops"] == 0 and p["ops_applied"] == 10
        assert p["watermark"]["scopes"]["t"] == {
            "appended": 10, "applied": 10, "lag": 0}
        assert counter_value(
            INDEX_WATERMARK, scope=TOTAL_SCOPE, kind="applied") == 10
        assert counter_value(
            INDEX_WATERMARK, scope="t", kind="appended") == 10
        t0 = time.monotonic()
    finally:
        applier.stop()
    assert time.monotonic() - t0 < 5.0


def test_applier_start_seq_skips_the_pre_watermark_prefix():
    log = MutationLog()
    rng = np.random.default_rng(4)
    log.append_upsert("t", _mk_docs(rng, 5))
    store = MemoryVectorStore()
    applier = LiveIndexApplier(log, store, start_seq=3)
    assert applier.drain() == 2  # ops 4 and 5 only
    assert store.count("t") == 2


def test_concurrent_queries_see_only_stream_prefixes_with_zero_compiles():
    """Randomized interleavings: producer appends churn ops while two
    query threads hammer the device index.  Every observed result must
    equal some exact op-prefix of the stream (the store lock serializes
    each apply run against searches), and the whole run — applies,
    background compactions, queries — adds ZERO XLA programs."""
    rng = np.random.default_rng(5)
    seed_docs = _mk_docs(rng, 40)
    # churn plan over the SEED id set only (capacity bucket never grows):
    # vector updates, deletes, and re-upserts of deleted ids
    live = {d.doc_id for d in seed_docs}
    dead: set[str] = set()
    plan: list[tuple[str, str, np.ndarray | None]] = []
    for step in range(60):
        roll = rng.random()
        if roll < 0.3 and len(live) > 30:
            did = sorted(live)[int(rng.integers(len(live)))]
            live.discard(did)
            dead.add(did)
            plan.append((DELETE, did, None))
        else:
            if dead and roll < 0.6:
                did = sorted(dead)[int(rng.integers(len(dead)))]
                dead.discard(did)
            else:
                did = sorted(live)[int(rng.integers(len(live)))]
            live.add(did)
            plan.append((UPSERT, did,
                         rng.normal(size=DIM).astype(np.float32)))

    inner = MemoryVectorStore()
    dev = DeviceIndexedStore(inner, k_bucket=16, max_wave=8)
    dev.upsert("t", seed_docs)
    dev.warmup()

    # reference prefix states: top-k ids after every op, host-store truth
    queries = [rng.normal(size=DIM).astype(np.float32) for _ in range(3)]
    ref = MemoryVectorStore()
    ref.upsert("t", seed_docs)
    allowed = [{tuple(_ids(ref.search("t", q, 5)))} for q in queries]
    for kind, did, vec in plan:
        if kind == DELETE:
            ref.delete("t", [did])
        else:
            ref.upsert("t", [Doc(did, f"u {did}", {"repo": "repo0"}, vec)])
        for i, q in enumerate(queries):
            allowed[i].add(tuple(_ids(ref.search("t", q, 5))))

    log = MutationLog()
    applier = LiveIndexApplier(log, dev, apply_batch=6,
                               compact_interval_s=0.05,
                               compact_min_holes=8,
                               compact_max_hole_fraction=0.2)
    observed: list[set[tuple]] = [set() for _ in queries]
    stop = threading.Event()
    errors: list[BaseException] = []

    def query_loop():
        n = 0
        try:
            while not stop.is_set():
                i = n % len(queries)
                observed[i].add(tuple(_ids(dev.search("t", queries[i], 5))))
                n += 1
        except BaseException as exc:  # noqa: BLE001 - surface in main thread
            errors.append(exc)

    with compile_guard(dev.search_program_cache_size,
                       label="live apply-vs-query search"), \
         compile_guard(dev.mutation_program_cache_size,
                       label="live apply-vs-query mutation"):
        applier.start()
        try:
            threads = [threading.Thread(target=query_loop) for _ in range(2)]
            for t in threads:
                t.start()
            for kind, did, vec in plan:  # randomized producer pacing
                if kind == DELETE:
                    log.append_delete("t", [did])
                else:
                    log.append_upsert(
                        "t", [Doc(did, f"u {did}", {"repo": "repo0"}, vec)])
                if rng.random() < 0.3:
                    time.sleep(0.001)
            assert applier.flush(timeout=30)
            stop.set()
            for t in threads:
                t.join()
        finally:
            stop.set()
            applier.stop()
    assert not errors, errors
    for i, q in enumerate(queries):
        assert observed[i], "query thread never completed a search"
        rogue = observed[i] - allowed[i]
        assert not rogue, f"query {i} observed non-prefix states: {rogue}"
        # fully-applied stream: device equals the host reference exactly
        assert _ids(dev.search("t", q, 5)) == _ids(ref.search("t", q, 5))
        np.testing.assert_allclose(
            _scores(dev.search("t", q, 5)), _scores(ref.search("t", q, 5)),
            atol=1e-5)


# --------------------------------------------------------------- compaction


def test_churn_reclaims_holes_in_place_without_full_sync():
    rng = np.random.default_rng(6)
    inner = MemoryVectorStore()
    dev = DeviceIndexedStore(inner, k_bucket=16, max_wave=8)
    docs = _mk_docs(rng, 50)
    dev.upsert("t", docs)
    dev.warmup()
    h0 = dev.health()["device_index"]["t"]
    full_syncs0 = h0["full_syncs"]
    metric_full0 = counter_value(INDEX_FULL_SYNCS, table="t")
    log = MutationLog()
    applier = LiveIndexApplier(log, dev, apply_batch=7, compact_min_holes=4,
                               compact_max_hole_fraction=0.2)
    ref = MemoryVectorStore()
    ref.upsert("t", docs)
    q = rng.normal(size=DIM).astype(np.float32)
    with compile_guard(dev.search_program_cache_size, label="churn search"), \
         compile_guard(dev.mutation_program_cache_size,
                       label="churn mutation"):
        for cycle in range(30):
            did = f"d{int(rng.integers(50)):04d}"
            log.append_delete("t", [did])
            doc = Doc(did, f"cycle {cycle}", {"repo": f"repo{cycle % 3}"},
                      rng.normal(size=DIM).astype(np.float32))
            log.append_upsert("t", [doc])
            ref.delete("t", [did])
            ref.upsert("t", [doc])
            applier.drain()
            if cycle % 5 == 0:
                assert _ids(dev.search("t", q, 8)) == _ids(ref.search("t", q, 8))
    h1 = dev.health()["device_index"]["t"]
    assert h1["capacity"] == h0["capacity"] == 64  # churn never grew the bucket
    assert h1["holes"] < applier.compact_min_holes  # gauge back to ~0
    assert h1["compactions"] > 0
    # counter-asserted: NO whole-table re-put on the hot path
    assert h1["full_syncs"] == full_syncs0
    assert counter_value(INDEX_FULL_SYNCS, table="t") == metric_full0
    assert counter_value(INDEX_HOLES, table="t") == h1["holes"]
    assert applier.payload()["compaction"]["reclaimed_rows"] > 0
    # score and tie-order parity survived row remapping
    for _ in range(3):
        qq = rng.normal(size=DIM).astype(np.float32)
        assert _ids(dev.search("t", qq, 10)) == _ids(ref.search("t", qq, 10))
        np.testing.assert_allclose(
            _scores(dev.search("t", qq, 10)), _scores(ref.search("t", qq, 10)),
            atol=1e-5)


# ------------------------------------------------------- snapshot / restore


def test_snapshot_restore_identical_with_suffix_only_replay(tmp_path):
    rng = np.random.default_rng(7)
    log = MutationLog()
    inner = MemoryVectorStore()
    dev = DeviceIndexedStore(inner, k_bucket=16, max_wave=8)
    applier = LiveIndexApplier(log, dev, apply_batch=16)
    docs = _mk_docs(rng, 45)
    v = rng.normal(size=DIM).astype(np.float32)
    ties = [Doc(f"tie{i}", "same", {"repo": "repo0"}, v.copy())
            for i in range(4)]
    log.append_upsert("t", docs)
    log.append_upsert("t", ties)
    log.append_delete("t", ["d0004", "d0010"])
    applier.drain()
    dev.warmup()

    snap = str(tmp_path / "snap")
    manifest = save_snapshot(dev, snap, watermark=applier.applied_seq())
    assert manifest["version"] == 1
    assert manifest["watermark"]["seq"] == applier.applied_seq()
    (entry,) = manifest["tables"]
    assert entry["name"] == "t" and entry["count"] == 47  # 45 + 4 - 2
    assert entry["capacity"] == 64 and entry["dim"] == DIM

    # ops PAST the snapshot watermark — the only thing restore may replay
    log.append_upsert("t", [Doc("d0004", "back", {"repo": "repo1"},
                                rng.normal(size=DIM).astype(np.float32))])
    log.append_delete("t", ["tie3"])
    applier.drain()

    replica = DeviceIndexedStore(MemoryVectorStore(), k_bucket=16, max_wave=8)
    out = restore_replica(snap, replica, log=log)
    assert out["replayed"] == 2  # the suffix, nothing earlier
    assert replica.count("t") == dev.count("t")
    # reserve() pre-sized the replica straight to the recorded bucket
    assert (replica.health()["device_index"]["t"]["capacity"]
            == dev.health()["device_index"]["t"]["capacity"])
    queries = [rng.normal(size=DIM).astype(np.float32) for _ in range(4)] + [v]
    for q in queries:
        for flt in (None, {"repo": "repo0"}):
            a = dev.search("t", q, 8, filter=flt)
            b = replica.search("t", q, 8, filter=flt)
            # identical raw bits in, identical program: scores must match
            # EXACTLY, and ties (tie0..tie2) must break in the same order
            assert _ids(a) == _ids(b)
            assert _scores(a) == _scores(b)


def test_snapshot_version_gate_refuses_mismatch(tmp_path):
    store = MemoryVectorStore()
    store.upsert("t", _mk_docs(np.random.default_rng(8), 3))
    snap = str(tmp_path / "snap")
    manifest = save_snapshot(store, snap, watermark=3)
    assert manifest["watermark"] == {"seq": 3, "tables": {}}
    mpath = os.path.join(snap, "manifest.json")
    with open(mpath, encoding="utf-8") as fh:
        doc = json.load(fh)
    doc["version"] = 99
    with open(mpath, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    with pytest.raises(ValueError, match="version"):
        load_snapshot(snap, MemoryVectorStore())


# --------------------------------------------------- store front / registry


def test_live_indexed_store_front_and_registry_payload():
    log = MutationLog()
    store = MemoryVectorStore()
    applier = LiveIndexApplier(log, store)
    front = LiveIndexedStore(store, log, applier)
    rng = np.random.default_rng(9)
    docs = _mk_docs(rng, 6)
    assert front.upsert("t", docs) == 6  # producer returns immediately
    assert front.count("t") == 0  # reads trail the log until the apply runs
    applier.flush()  # threadless flush drains inline
    assert front.count("t") == 6
    assert front.tables() == ["t"]
    q = rng.normal(size=DIM).astype(np.float32)
    assert _ids(front.search("t", q, 3)) == _ids(store.search("t", q, 3))
    assert front.delete("t", [docs[0].doc_id]) == 1
    applier.flush()
    assert front.get("t", docs[0].doc_id) is None
    h = front.health()
    assert h["live_index"]["enabled"] is True
    assert h["live_index"]["lag_ops"] == 0
    # /debug/index registry: explicit disabled marker without an applier
    assert live_index_payload() == {"enabled": False}
    register_live_applier(applier)
    try:
        assert get_live_applier() is applier
        assert (live_index_payload()["watermark"]["applied"]
                == applier.applied_seq())
    finally:
        register_live_applier(None)


async def test_debug_index_endpoint_renders_registry_payload():
    from githubrepostorag_tpu.api.app import RagApi
    from githubrepostorag_tpu.serving.openai_api import OpenAIServer

    # the handlers only consult the registry — no engine/bus wiring needed
    server = OpenAIServer.__new__(OpenAIServer)
    api = RagApi.__new__(RagApi)
    for handler in (server.debug_index, api.debug_index):
        assert json.loads((await handler(None)).body) == {"enabled": False}
    applier = LiveIndexApplier(MutationLog(), MemoryVectorStore())
    register_live_applier(applier)
    try:
        for handler in (server.debug_index, api.debug_index):
            body = json.loads((await handler(None)).body)
            assert body["enabled"] is True
            assert "watermark" in body and "compaction" in body
    finally:
        register_live_applier(None)


def test_factory_builds_live_front_when_enabled(monkeypatch, tmp_path):
    from githubrepostorag_tpu.config import reload_settings
    from githubrepostorag_tpu.store.factory import get_store, reset_store

    monkeypatch.setenv("STORE_BACKEND", "memory")
    monkeypatch.setenv("LIVE_INDEX", "on")
    monkeypatch.setenv("LIVE_INDEX_LOG_PATH", str(tmp_path / "mlog.jsonl"))
    reload_settings()
    reset_store()
    try:
        store = get_store()
        assert isinstance(store, LiveIndexedStore)
        assert get_live_applier() is store.applier
        rng = np.random.default_rng(10)
        store.upsert("t", _mk_docs(rng, 4))
        assert store.applier.flush(timeout=10)
        assert store.count("t") == 4
        assert (tmp_path / "mlog.jsonl").exists()  # producer writes durable
        thread = store.applier._thread
        assert thread is not None and thread.is_alive()
        reset_store()  # must stop the drain thread and clear the registry
        assert get_live_applier() is None
        assert not thread.is_alive()
    finally:
        monkeypatch.delenv("STORE_BACKEND", raising=False)
        monkeypatch.delenv("LIVE_INDEX", raising=False)
        monkeypatch.delenv("LIVE_INDEX_LOG_PATH", raising=False)
        reload_settings()
        reset_store()


# ------------------------------------------------------------------- watch


def test_watch_local_fires_on_fingerprint_change(tmp_path):
    (tmp_path / "a.py").write_text("x = 1\n")
    events = []

    def on_change():
        events.append(len(events))
        if len(events) == 1:
            # mutate the tree between polls: the next poll must fire
            (tmp_path / "b.py").write_text("y = 2\n")
        elif len(events) == 2:
            # hidden files are not fingerprinted: no third fire
            (tmp_path / ".hidden").write_text("z\n")

    fired = watch_local(str(tmp_path), on_change, interval_s=0.01,
                        max_polls=5)
    assert fired == 2  # the initial index + the visible change
    assert events == [0, 1]
    # a pre-set stop event short-circuits before the first poll
    ev = threading.Event()
    ev.set()
    assert watch_local(str(tmp_path), on_change, interval_s=0.01,
                       stop=ev) == 0
