"""In-tree byte-level BPE (C++ core + Python front) vs the HuggingFace
``tokenizers`` library as ground truth: a ByteLevel BPE trained on a small
corpus, saved as tokenizer.json, loaded by both — ids must match exactly on
a battery of unicode-heavy inputs, and the native C++ merge loop must agree
with the pure-Python fallback.
"""

import pytest

tokenizers = pytest.importorskip("tokenizers")

from githubrepostorag_tpu.serving.bpe_native import NativeBPETokenizer  # noqa: E402
from githubrepostorag_tpu.serving.tokenizer import StreamingDetokenizer  # noqa: E402

CORPUS = [
    "def forward(self, x): return self.proj(x) + self.bias",
    "The quick brown fox jumps over the lazy dog. THE QUICK BROWN FOX!",
    "import numpy as np\nimport jax.numpy as jnp\n\n# comment line",
    "Cassandra vector store with SAI cosine index, batch size 128.",
    "don't we'll they've it's I'm you're he'd",
    "naïve café résumé — em-dash…ellipsis",
    "数字 123 和 456.789 与单词混合",
    "for i in range(100):\n    print(f\"{i:03d}\")\r\n\ttabbed",
    "emoji 🚀🔥 and symbols €£¥ ©®™",
    "   leading spaces and   multiple   gaps   ",
]

SPECIALS = ["<|endoftext|>", "<|im_start|>", "<|im_end|>"]

BATTERY = [
    "hello world",
    "def f(x): return x + 1  # increment",
    "don't stop",
    "multi\nline\n\ntext with\ttabs",
    "unicode: naïve café 数字 🚀",
    "numbers 42 and 3.14159 mixed with words",
    "",
    " ",
    "   spaced   out   ",
    "ALLCAPS lowercase MiXeD",
    "a",
    "🚀",
    "price: €99.99 (discount!)",
]


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    from tokenizers.implementations import ByteLevelBPETokenizer

    tok = ByteLevelBPETokenizer()
    tok.train_from_iterator(
        CORPUS * 4, vocab_size=600, min_frequency=1, special_tokens=SPECIALS
    )
    path = tmp_path_factory.mktemp("bpe") / "tokenizer.json"
    tok.save(str(path))
    hf = tokenizers.Tokenizer.from_file(str(path))
    return path, hf


@pytest.fixture(scope="module")
def native(trained):
    path, _ = trained
    return NativeBPETokenizer(path)


def test_native_backend_built(native):
    # the C++ library builds in this image (g++ present); if this fails the
    # fallback still works but the native core is what's under test
    assert native.backend == "native"


def test_encode_matches_hf_exactly(trained, native):
    _, hf = trained
    for text in BATTERY:
        assert native.encode(text) == hf.encode(text).ids, repr(text)


def test_encode_with_special_tokens(trained, native):
    _, hf = trained
    text = "<|im_start|>user\nhello world<|im_end|>\n<|im_start|>assistant\n"
    assert native.encode(text) == hf.encode(text).ids
    assert native.specials["<|im_end|>"] == native.eos_token_id


def test_python_fallback_matches_native(trained, native):
    path, _ = trained
    py = NativeBPETokenizer(path, use_native=False)
    assert py.backend == "python"
    for text in BATTERY:
        assert py.encode(text) == native.encode(text), repr(text)


def test_decode_roundtrip(trained, native):
    _, hf = trained
    for text in BATTERY:
        ids = native.encode(text)
        assert native.decode(ids) == hf.decode(ids, skip_special_tokens=True), repr(text)


def test_chat_template_and_streaming_detokenize(native):
    msgs = [{"role": "user", "content": "hi 🚀"}]
    ids = native.encode_chat(msgs)
    assert native.specials["<|im_start|>"] in ids
    # StreamingDetokenizer over the native tokenizer never emits half a
    # codepoint and reconstructs the prompt text (minus specials)
    sd = StreamingDetokenizer(native)
    out = "".join(sd.push(i) for i in ids) + sd.flush()
    assert out == native.decode(ids)
    assert "🚀" in out


def test_make_tokenizer_prefers_native(trained, tmp_path):
    import shutil

    from githubrepostorag_tpu.serving.tokenizer import make_tokenizer

    path, _ = trained
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    shutil.copy(path, ckpt / "tokenizer.json")
    tok = make_tokenizer(str(ckpt), backend="native")
    assert type(tok).__name__ == "NativeBPETokenizer"
    assert tok.encode("hello world")


def test_ignore_merges_and_nfc_normalizer_parity(trained, tmp_path):
    """Real checkpoints (Qwen2, Llama-3 family) set model.ignore_merges and
    a unicode normalizer; both must match HF exactly."""
    import json

    path, _ = trained
    spec = json.loads(path.read_text())
    spec["model"]["ignore_merges"] = True
    spec["normalizer"] = {"type": "NFC"}
    mod = tmp_path / "tokenizer.json"
    mod.write_text(json.dumps(spec))
    hf = tokenizers.Tokenizer.from_file(str(mod))
    ours = NativeBPETokenizer(mod)
    battery = BATTERY + [
        "café naïve",  # NFD input the normalizer must compose
        "the quick brown fox",  # words that are whole vocab entries
    ]
    for text in battery:
        assert ours.encode(text) == hf.encode(text).ids, repr(text)


def test_unsupported_normalizer_rejected(trained, tmp_path):
    import json

    path, _ = trained
    spec = json.loads(path.read_text())
    spec["normalizer"] = {"type": "Replace", "pattern": {"String": "x"}, "content": "y"}
    mod = tmp_path / "tokenizer.json"
    mod.write_text(json.dumps(spec))
    with pytest.raises(ValueError, match="unsupported normalizer"):
        NativeBPETokenizer(mod)


def test_non_special_added_token_survives_decode(trained, tmp_path):
    import json

    path, _ = trained
    spec = json.loads(path.read_text())
    new_id = max(spec["model"]["vocab"].values()) + 1
    spec.setdefault("added_tokens", []).append({
        "id": new_id, "content": "JAXTPU", "special": False,
        "single_word": False, "lstrip": False, "rstrip": False,
        "normalized": False,
    })
    mod = tmp_path / "tokenizer.json"
    mod.write_text(json.dumps(spec))
    hf = tokenizers.Tokenizer.from_file(str(mod))
    ours = NativeBPETokenizer(mod)
    text = "run JAXTPU fast"
    ids = ours.encode(text)
    assert ids == hf.encode(text).ids
    assert new_id in ids
    # HF skip_special_tokens keeps non-special added tokens; so must we
    assert ours.decode(ids) == hf.decode(ids, skip_special_tokens=True)
    assert "JAXTPU" in ours.decode(ids)


def test_eos_from_tokenizer_config(trained, tmp_path):
    import json
    import shutil

    path, _ = trained
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    shutil.copy(path, ckpt / "tokenizer.json")
    (ckpt / "tokenizer_config.json").write_text(
        json.dumps({"eos_token": "<|endoftext|>"})
    )
    tok = NativeBPETokenizer(ckpt / "tokenizer.json")
    assert tok.eos_token_id == tok.specials["<|endoftext|>"]


def test_eos_refused_when_undeterminable(trained, tmp_path):
    """No config and no recognizable eos special: refuse rather than guess a
    stop token (make_tokenizer then falls back to transformers)."""
    import json

    path, _ = trained
    spec = json.loads(path.read_text())
    for t in spec.get("added_tokens", []):
        t["content"] = t["content"].replace("<|", "[").replace("|>", "]")
    vocab = spec["model"]["vocab"]
    for k in list(vocab):
        if k.startswith("<|"):
            vocab[k.replace("<|", "[").replace("|>", "]")] = vocab.pop(k)
    mod = tmp_path / "tokenizer.json"
    mod.write_text(json.dumps(spec))
    with pytest.raises(ValueError, match="eos"):
        NativeBPETokenizer(mod)


def test_default_system_from_chat_template(trained, tmp_path):
    """from_checkpoint extracts the checkpoint's default system prompt from
    a Qwen2-style chat_template and injects it when chats carry no system
    turn — matching what transformers' template rendering would do."""
    import json
    import shutil

    path, _ = trained
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    shutil.copy(path, ckpt / "tokenizer.json")
    template = (
        "{%- if messages[0]['role'] == 'system' %}"
        "{{- '<|im_start|>system\\n' + messages[0]['content'] + '<|im_end|>\\n' }}"
        "{%- else %}"
        "{{- '<|im_start|>system\\nYou are a helpful assistant.<|im_end|>\\n' }}"
        "{%- endif %}"
    )
    (ckpt / "tokenizer_config.json").write_text(json.dumps(
        {"eos_token": "<|im_end|>", "chat_template": template}
    ))
    tok = NativeBPETokenizer.from_checkpoint(ckpt)
    assert tok.default_system == "You are a helpful assistant."
    rendered = tok.apply_chat_template([{"role": "user", "content": "hi"}])
    assert rendered.startswith("<|im_start|>system\nYou are a helpful assistant.")
    # explicit system turn wins
    rendered = tok.apply_chat_template(
        [{"role": "system", "content": "be terse"}, {"role": "user", "content": "hi"}]
    )
    assert "You are a helpful" not in rendered and "be terse" in rendered


def test_unrecognizable_chat_template_rejected(trained, tmp_path):
    import json
    import shutil

    path, _ = trained
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    shutil.copy(path, ckpt / "tokenizer.json")
    (ckpt / "tokenizer_config.json").write_text(json.dumps(
        {"chat_template": "{% for m in messages %}[{{m.role}}]{{m.content}}{% endfor %}"}
    ))
    with pytest.raises(ValueError, match="template"):
        NativeBPETokenizer.from_checkpoint(ckpt)


def test_add_prefix_space_rejected(trained, tmp_path):
    """RoBERTa-style add_prefix_space changes every first-word id; we don't
    implement it, so the loader must refuse (-> transformers fallback)."""
    import json

    path, _ = trained
    spec = json.loads(path.read_text())
    spec["pre_tokenizer"] = {"type": "ByteLevel", "add_prefix_space": True,
                             "trim_offsets": True, "use_regex": True}
    mod = tmp_path / "tokenizer.json"
    mod.write_text(json.dumps(spec))
    with pytest.raises(ValueError, match="add_prefix_space"):
        NativeBPETokenizer(mod)


def test_unknown_pretokenizer_rejected(trained, tmp_path):
    import json

    path, _ = trained
    spec = json.loads(path.read_text())
    spec["pre_tokenizer"] = {"type": "Whitespace"}
    mod = tmp_path / "tokenizer.json"
    mod.write_text(json.dumps(spec))
    with pytest.raises(ValueError, match="pre_tokenizer"):
        NativeBPETokenizer(mod)


def test_long_input_stability(trained, native):
    _, hf = trained
    text = " ".join(CORPUS) * 8
    ids = native.encode(text)
    assert ids == hf.encode(text).ids
    assert native.decode(ids) == hf.decode(ids, skip_special_tokens=True)
