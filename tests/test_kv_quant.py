"""Int8 KV cache (kv_quant pools): per-token symmetric quantization,
engine output parity against full-precision KV, prefix-cache composition,
and the staged Pallas kernel's in-VMEM dequant (interpret mode).

VERDICT r02 #5: int8 KV halves cache reads at long context and doubles
effective page capacity under the 64-stream config (the KV-fit reasoning
behind the reference's --max-model-len 11712, values.yaml:74).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
from githubrepostorag_tpu.serving import Engine, SamplingParams
from githubrepostorag_tpu.serving.kv_cache import make_page_pools, quantize_kv


@pytest.fixture(scope="module")
def tiny():
    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    return cfg, params


def _engine(params, cfg, **kw):
    defaults = dict(max_num_seqs=2, num_pages=32, page_size=4, max_seq_len=64,
                    kv_dtype=jnp.float32, decode_burst=8)
    defaults.update(kw)
    return Engine(params, cfg, **defaults)


def test_quantize_kv_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 2.0, (3, 17, 64)), dtype=jnp.float32)
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (3, 17)
    back = q.astype(jnp.float32) * s[..., None]
    err = np.abs(np.asarray(back) - np.asarray(x))
    # per-token symmetric: error <= scale/2 = amax/254 per vector
    bound = np.abs(np.asarray(x)).max(axis=-1, keepdims=True) / 254 + 1e-6
    assert (err <= bound).all()


def test_quantize_kv_zero_vector_safe():
    q, s = quantize_kv(jnp.zeros((2, 8)))
    assert np.asarray(q).max() == 0 and (np.asarray(s) > 0).all()


def test_quant_pools_shapes_and_bytes():
    cfg = Qwen2Config.tiny()
    full = make_page_pools(cfg, 16, 8)
    quant = make_page_pools(cfg, 16, 8, quant=True)
    assert quant.k.dtype == jnp.int8
    assert quant.ks.shape == quant.k.shape[:-2] and quant.ks.dtype == jnp.float32
    payload = quant.k.nbytes + quant.ks.nbytes
    assert payload < 0.55 * full.k.nbytes  # int8 + per-page scales vs bf16


def test_engine_kv_quant_tracks_full_precision(tiny):
    """Greedy decode over int8 KV must track the full-precision engine:
    same first tokens, and token-for-token equality over a short horizon
    (tiny scale, per-token scales — the quantization error is far below
    typical logit gaps)."""
    cfg, params = tiny
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7]]
    sp = SamplingParams(max_tokens=12, temperature=0.0, stop_token_ids=())
    ref = [r.output_tokens for r in _engine(params, cfg).generate(prompts, sp)]
    got = [r.output_tokens
           for r in _engine(params, cfg, kv_quant=True).generate(prompts, sp)]
    for r, g in zip(ref, got):
        assert r[:6] == g[:6], (r, g)  # short horizon: identical
        # full horizon: allow a late near-tie flip, not divergence
        assert sum(a != b for a, b in zip(r, g)) <= 2, (r, g)


def test_engine_kv_quant_tracks_full_precision_at_page_128(tiny):
    """The production default page size (config.py KV_PAGE_SIZE=128)
    widens the first-write scale window: up to 127 decode appends into a
    page reuse the scale its OPENING write fixed (quantize_kv_paged),
    clipping any later outlier — the accuracy case the r05 throughput
    probes never measured.  Greedy decode must track the bf16 engine
    deep into a page full of first-write-scaled appends."""
    cfg, params = tiny
    geom = dict(num_pages=4, page_size=128, max_seq_len=256)
    sp = SamplingParams(max_tokens=100, temperature=0.0, stop_token_ids=())
    prompts = [[1, 2, 3, 4, 5]]
    ref = _engine(params, cfg, **geom).generate(prompts, sp)[0].output_tokens
    got = _engine(params, cfg, kv_quant=True, **geom).generate(prompts, sp)[0].output_tokens
    # first divergence (tiny random weights have near-tie logit gaps, so a
    # single late flip cascades — count faithful PREFIX length, not flips)
    first_diff = next((i for i, (a, b) in enumerate(zip(ref, got)) if a != b),
                      len(ref))
    assert first_diff >= 32, (first_diff, ref, got)


def test_kv_quant_composes_with_prefix_cache(tiny):
    """A warm request resuming from int8 cached pages must produce the
    cold request's tokens — the page content is the quantized
    representation either way."""
    cfg, params = tiny
    eng = _engine(params, cfg, kv_quant=True, prefix_caching=True)
    prefix = list(range(1, 17))  # 4 full pages
    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
    cold = eng.generate([prefix + [20, 21]], sp)[0].output_tokens
    warm = eng.generate([prefix + [20, 21]], sp)[0].output_tokens
    assert eng._allocator.hit_tokens > 0
    assert warm == cold


@pytest.mark.parametrize("burst_iters", [0, 3])
def test_kv_quant_spec_decode_runs(tiny, burst_iters):
    """Spec mode verifies drafts through forward_paged's quantized path —
    both host-dispatched (burst_iters=0) and the fused on-device burst
    (its quant branch threads the scale pools through the scan carry)."""
    cfg, params = tiny
    zero_layers = jax.tree.map(jnp.zeros_like, params["layers"])
    rep_params = dict(params, layers=zero_layers)  # repeater: drafts accept
    eng = _engine(rep_params, cfg, kv_quant=True, spec_ngram_k=4,
                  spec_burst_iters=burst_iters)
    sp = SamplingParams(max_tokens=16, temperature=0.0, stop_token_ids=())
    res = eng.generate([[5, 6, 7, 8]], sp)[0]
    assert len(res.output_tokens) == 16
    assert eng.spec_accepted > 0  # the repeating tail drafted + accepted


def test_kv_quant_composes_with_sp_ring_prefill(tiny):
    """Round-4: the ring commit quantizes per page (long_prefill.py), so
    kv_quant + sp no longer rejects at construction — a long prompt rides
    the ring path onto int8 pools and decodes.  Cross-path token parity
    lives in tests/test_long_prefill.py."""
    cfg, params = tiny
    from githubrepostorag_tpu.parallel import MeshPlan, make_mesh

    eng = _engine(params, cfg, kv_quant=True, mesh=make_mesh(MeshPlan(sp=2)),
                  sp_prefill_threshold=32)
    sp = SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=())
    res = eng.generate([list(range(1, 41))], sp)[0]  # 40 >= threshold
    assert eng.sp_prefills == 1
    assert len(res.output_tokens) == 6


def test_staged_kernel_int8_matches_dequant_reference(tiny):
    """The Pallas staged kernel's in-VMEM dequant (interpret mode) must
    match attention over the explicitly dequantized pool."""
    from githubrepostorag_tpu.ops.attention import dense_attention
    from githubrepostorag_tpu.ops.pallas_paged import paged_attention_decode_staged

    rng = np.random.default_rng(1)
    L, B, n_kv, group, hd, P, ps, n_steps = 3, 2, 2, 2, 16, 8, 4, 4
    q = jnp.asarray(rng.normal(size=(B, 1, n_kv * group, hd)), dtype=jnp.float32)
    kf = rng.normal(size=(L, n_kv, P, ps, hd)).astype(np.float32)
    vf = rng.normal(size=(L, n_kv, P, ps, hd)).astype(np.float32)
    def quant_per_page(x):  # [L, n_kv, P, ps, hd] -> int8 + [L, n_kv, P]
        s = np.maximum(np.abs(x).max(axis=(-2, -1)) / 127.0, 1e-8)
        q = np.clip(np.round(x / s[..., None, None]), -127, 127).astype(np.int8)
        return jnp.asarray(q), jnp.asarray(s.astype(np.float32))

    kq, ks = quant_per_page(kf)
    vq, vs = quant_per_page(vf)
    bt = jnp.asarray(rng.permutation(P)[: B * 3].reshape(B, 3), dtype=jnp.int32)
    pool_lens = jnp.asarray([9, 5], dtype=jnp.int32)
    sk = jnp.asarray(rng.normal(size=(B, n_kv, n_steps, hd)), dtype=jnp.float32)
    sv = jnp.asarray(rng.normal(size=(B, n_kv, n_steps, hd)), dtype=jnp.float32)
    sl = jnp.asarray([2], dtype=jnp.int32)
    li = jnp.asarray([1], dtype=jnp.int32)

    got = paged_attention_decode_staged(
        q, kq, vq, bt, pool_lens, sk, sv, sl, li, ks, vs, interpret=True
    )

    # reference: dequantize layer 1's pages, gather, dense attention
    kd = np.asarray(kq, dtype=np.float32) * np.asarray(ks)[..., None, None]
    vd = np.asarray(vq, dtype=np.float32) * np.asarray(vs)[..., None, None]
    outs = []
    for b in range(B):
        pages = np.asarray(bt)[b]
        k_seq = kd[1][:, pages].reshape(n_kv, -1, hd)  # [n_kv, 3*ps, hd]
        v_seq = vd[1][:, pages].reshape(n_kv, -1, hd)
        k_all = np.concatenate([k_seq, np.asarray(sk)[b]], axis=1)
        v_all = np.concatenate([v_seq, np.asarray(sv)[b]], axis=1)
        n_pool = int(pool_lens[b])
        valid = np.zeros((k_all.shape[1],), dtype=bool)
        valid[:n_pool] = True
        valid[3 * ps : 3 * ps + int(sl[0])] = True
        out = dense_attention(
            q[b : b + 1],
            jnp.asarray(k_all.transpose(1, 0, 2))[None],
            jnp.asarray(v_all.transpose(1, 0, 2))[None],
            causal=False,
            kv_valid=jnp.asarray(valid)[None],
        )
        outs.append(np.asarray(out)[0])
    ref = np.stack(outs)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-5)


def test_quantize_kv_paged_first_write_then_append():
    """Per-page semantics: a page's scale is fixed by the write containing
    its slot 0 (with headroom); a later append to the same page reuses the
    stored scale and clips rather than rescaling; dropped slots (sentinel)
    touch nothing."""
    from githubrepostorag_tpu.serving.kv_cache import (
        KV_SCALE_HEADROOM,
        quantize_kv_paged,
    )

    ps, p, hd = 4, 8, 16
    rng = np.random.default_rng(2)
    scales = jnp.zeros((2, p), jnp.float32)  # [n_kv, P], never written

    # first write: page 3 slots 12..13 (opens at slot 0 of page 3)
    vals1 = jnp.asarray(rng.normal(0, 1.0, (2, 2, hd)), jnp.float32)
    slots1 = jnp.asarray([12, 13], jnp.int32)
    q1, scales = quantize_kv_paged(vals1, slots1, scales, ps)
    s3 = np.asarray(scales)[:, 3]
    expect = np.abs(np.asarray(vals1)).max(axis=(1, 2)) * KV_SCALE_HEADROOM / 127
    np.testing.assert_allclose(s3, expect, rtol=1e-5)
    assert (np.asarray(scales)[:, :3] == 0).all()

    # append slots 14..15: same page, larger values -> clip, scale UNCHANGED
    vals2 = jnp.asarray(rng.normal(0, 10.0, (2, 2, hd)), jnp.float32)
    slots2 = jnp.asarray([14, 15], jnp.int32)
    q2, scales2 = quantize_kv_paged(vals2, slots2, scales, ps)
    np.testing.assert_allclose(np.asarray(scales2)[:, 3], s3, rtol=0)
    assert np.abs(np.asarray(q2)).max() == 127  # clipped, not rescaled

    # dropped sentinel slots leave scales untouched
    q3, scales3 = quantize_kv_paged(vals1, jnp.asarray([-1, p * ps], jnp.int32),
                                    scales2, ps)
    np.testing.assert_array_equal(np.asarray(scales3), np.asarray(scales2))

    # roundtrip error within a freshly-scaled page is bounded by scale/2
    back = np.asarray(q1, np.float32) * s3[:, None, None]
    err = np.abs(back - np.asarray(vals1))
    assert (err <= s3[:, None, None] / 2 + 1e-6).all()


def test_kv_quant_engine_with_tp_mesh(tiny):
    """kv_quant composes with a TP mesh: rank-3 scale pools shard with
    their kv-head axis (regression: the device_put spec kept 4 axes after
    the per-page migration and crashed Engine init)."""
    from githubrepostorag_tpu.parallel import MeshPlan, make_mesh

    cfg, params = tiny
    eng = _engine(params, cfg, kv_quant=True, mesh=make_mesh(MeshPlan(tp=2)))
    sp = SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=())
    ref = _engine(params, cfg, kv_quant=True).generate([[1, 2, 3, 4]], sp)
    got = eng.generate([[1, 2, 3, 4]], sp)
    assert got[0].output_tokens == ref[0].output_tokens
