"""Pallas paged-attention decode kernel vs the gather+dense oracle
(interpret mode on CPU; the same kernel runs compiled on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.ops.paged_attention import paged_attention_ref
from githubrepostorag_tpu.ops.pallas_paged import paged_attention_decode


def _case(seed, b, n_q, n_kv, hd, ps, num_pages, max_pages, lens):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(b, 1, n_q, hd)).astype(np.float32)
    k_pages = rng.normal(size=(n_kv, num_pages, ps, hd)).astype(np.float32)
    v_pages = rng.normal(size=(n_kv, num_pages, ps, hd)).astype(np.float32)
    # distinct random pages per row
    perm = rng.permutation(num_pages)
    block_tables = np.zeros((b, max_pages), dtype=np.int32)
    taken = 0
    for row in range(b):
        need = -(-int(lens[row]) // ps) if lens[row] else 0
        block_tables[row, :need] = perm[taken : taken + need]
        taken += need
    cached = np.asarray([max(l - 1, 0) for l in lens], dtype=np.int32)
    new = np.asarray([1 if l else 0 for l in lens], dtype=np.int32)
    return (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(block_tables), jnp.asarray(cached), jnp.asarray(new))


@pytest.mark.parametrize("lens", [[13], [16], [1]])
def test_single_row_matches_ref(lens):
    args = _case(0, 1, 4, 2, 32, 8, 16, 4, lens)
    ref = paged_attention_ref(*args)
    out = paged_attention_decode(*args, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ragged_batch_with_padding_rows():
    # rows with different lengths, including an inactive row (len 0)
    args = _case(1, 4, 8, 2, 64, 16, 32, 4, [50, 7, 0, 33])
    ref = paged_attention_ref(*args)
    out = paged_attention_decode(*args, interpret=True)
    active = np.asarray([0, 1, 3])
    np.testing.assert_allclose(
        np.asarray(out)[active], np.asarray(ref)[active], atol=1e-5, rtol=1e-5
    )
    assert bool(jnp.isfinite(out).all())  # padding row must not NaN


def test_gqa_group_of_seven():
    # Qwen2-7B geometry: 28 q heads over 4 kv heads (group 7)
    args = _case(2, 2, 28, 4, 64, 16, 24, 6, [80, 42])
    ref = paged_attention_ref(*args)
    out = paged_attention_decode(*args, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_engine_with_pallas_path_matches_hf():
    transformers = pytest.importorskip("transformers")
    import torch
    from githubrepostorag_tpu.models.hf_loader import config_from_hf, params_from_state_dict
    from githubrepostorag_tpu.serving import Engine, SamplingParams

    hf_cfg = transformers.Qwen2Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg.to_dict())
    params = params_from_state_dict(model.state_dict(), cfg)

    prompt = np.random.default_rng(3).integers(0, 512, size=21).tolist()
    eng = Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=8,
                 max_seq_len=64, prefill_chunk=32, kv_dtype=jnp.float32,
                 use_pallas=True)
    res = eng.generate([prompt], SamplingParams(temperature=0.0, max_tokens=6))[0]
    with torch.no_grad():
        ref = model.generate(torch.tensor([prompt]), max_new_tokens=6, do_sample=False,
                             pad_token_id=0, eos_token_id=None)
    assert res.output_tokens == ref[0, len(prompt):].tolist()
