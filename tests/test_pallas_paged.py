"""Pallas paged-attention decode kernel vs the gather+dense oracle
(interpret mode on CPU; the same kernel runs compiled on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.ops.paged_attention import paged_attention_ref
from githubrepostorag_tpu.ops.pallas_paged import paged_attention_decode


def _case(seed, b, n_q, n_kv, hd, ps, num_pages, max_pages, lens):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(b, 1, n_q, hd)).astype(np.float32)
    k_pages = rng.normal(size=(n_kv, num_pages, ps, hd)).astype(np.float32)
    v_pages = rng.normal(size=(n_kv, num_pages, ps, hd)).astype(np.float32)
    # distinct random pages per row
    perm = rng.permutation(num_pages)
    block_tables = np.zeros((b, max_pages), dtype=np.int32)
    taken = 0
    for row in range(b):
        need = -(-int(lens[row]) // ps) if lens[row] else 0
        block_tables[row, :need] = perm[taken : taken + need]
        taken += need
    cached = np.asarray([max(l - 1, 0) for l in lens], dtype=np.int32)
    new = np.asarray([1 if l else 0 for l in lens], dtype=np.int32)
    return (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(block_tables), jnp.asarray(cached), jnp.asarray(new))


@pytest.mark.parametrize("lens", [[13], [16], [1]])
def test_single_row_matches_ref(lens):
    args = _case(0, 1, 4, 2, 32, 8, 16, 4, lens)
    ref = paged_attention_ref(*args)
    out = paged_attention_decode(*args, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ragged_batch_with_padding_rows():
    # rows with different lengths, including an inactive row (len 0)
    args = _case(1, 4, 8, 2, 64, 16, 32, 4, [50, 7, 0, 33])
    ref = paged_attention_ref(*args)
    out = paged_attention_decode(*args, interpret=True)
    active = np.asarray([0, 1, 3])
    np.testing.assert_allclose(
        np.asarray(out)[active], np.asarray(ref)[active], atol=1e-5, rtol=1e-5
    )
    assert bool(jnp.isfinite(out).all())  # padding row must not NaN


def test_gqa_group_of_seven():
    # Qwen2-7B geometry: 28 q heads over 4 kv heads (group 7)
    args = _case(2, 2, 28, 4, 64, 16, 24, 6, [80, 42])
    ref = paged_attention_ref(*args)
    out = paged_attention_decode(*args, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_engine_with_pallas_path_matches_hf():
    transformers = pytest.importorskip("transformers")
    import torch
    from githubrepostorag_tpu.models.hf_loader import config_from_hf, params_from_state_dict
    from githubrepostorag_tpu.serving import Engine, SamplingParams

    hf_cfg = transformers.Qwen2Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg.to_dict())
    params = params_from_state_dict(model.state_dict(), cfg)

    prompt = np.random.default_rng(3).integers(0, 512, size=21).tolist()
    eng = Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=8,
                 max_seq_len=64, prefill_chunk=32, kv_dtype=jnp.float32,
                 use_pallas=True)
    res = eng.generate([prompt], SamplingParams(temperature=0.0, max_tokens=6))[0]
    with torch.no_grad():
        ref = model.generate(torch.tensor([prompt]), max_new_tokens=6, do_sample=False,
                             pad_token_id=0, eos_token_id=None)
    assert res.output_tokens == ref[0, len(prompt):].tolist()


# ------------------------------------------------- staged burst kernel ----


def _staged_case(seed, b, n_q, n_kv, hd, ps, num_pages, max_pages, pool_lens,
                 n_steps, staged_len):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(b, 1, n_q, hd)).astype(np.float32)
    k_pages = rng.normal(size=(n_kv, num_pages, ps, hd)).astype(np.float32)
    v_pages = rng.normal(size=(n_kv, num_pages, ps, hd)).astype(np.float32)
    staged_k = rng.normal(size=(b, n_kv, n_steps, hd)).astype(np.float32)
    staged_v = rng.normal(size=(b, n_kv, n_steps, hd)).astype(np.float32)
    perm = rng.permutation(num_pages)
    block_tables = np.zeros((b, max_pages), dtype=np.int32)
    taken = 0
    for row in range(b):
        need = -(-int(pool_lens[row]) // ps) if pool_lens[row] else 0
        block_tables[row, :need] = perm[taken : taken + need]
        taken += need
    return (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(block_tables), jnp.asarray(pool_lens, dtype=jnp.int32),
            jnp.asarray(staged_k), jnp.asarray(staged_v),
            jnp.asarray([staged_len], dtype=jnp.int32))


def _staged_oracle(q, k_pages, v_pages, block_tables, pool_lens, staged_k,
                   staged_v, staged_len):
    """gather pool + concat staged tail + masked dense attention — the same
    math the decode burst's CPU path runs."""
    from githubrepostorag_tpu.ops.attention import dense_attention
    from githubrepostorag_tpu.ops.paged_attention import gather_kv

    b = q.shape[0]
    n_steps = staged_k.shape[2]
    pool_k, pool_v = gather_kv(k_pages, v_pages, block_tables)
    pool_valid = jnp.arange(pool_k.shape[1])[None, :] < pool_lens[:, None]
    staged_valid = jnp.broadcast_to(
        (jnp.arange(n_steps) < staged_len[0])[None, :], (b, n_steps)
    )
    k_all = jnp.concatenate([pool_k, staged_k.swapaxes(1, 2)], axis=1)
    v_all = jnp.concatenate([pool_v, staged_v.swapaxes(1, 2)], axis=1)
    valid = jnp.concatenate([pool_valid, staged_valid], axis=1)
    return dense_attention(q, k_all, v_all, causal=False, kv_valid=valid)


@pytest.mark.parametrize("pool_lens,staged_len", [
    ([50, 7, 0, 33], 3),   # ragged pools incl. empty, mid-burst
    ([0, 0, 0, 0], 1),     # burst step 0 right after prefill-free start
    ([64, 64, 64, 64], 8), # full pools, full staged tail
])
def test_staged_kernel_matches_oracle(pool_lens, staged_len):
    from githubrepostorag_tpu.ops.pallas_paged import paged_attention_decode_staged

    args = _staged_case(0, 4, 8, 2, 64, 16, 32, 4, pool_lens, 8, staged_len)
    ref = _staged_oracle(*args)
    out = paged_attention_decode_staged(*args, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_staged_kernel_gqa_group_seven():
    from githubrepostorag_tpu.ops.pallas_paged import paged_attention_decode_staged

    args = _staged_case(3, 2, 28, 4, 64, 16, 24, 6, [80, 42], 16, 11)
    ref = _staged_oracle(*args)
    out = paged_attention_decode_staged(*args, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_burst_pallas_matches_gather_path():
    """decode_burst(use_pallas=True) must be token-identical to the gather
    oracle path on the same inputs (greedy, so no sampling noise)."""
    from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
    from githubrepostorag_tpu.serving.decode_burst import decode_burst
    from githubrepostorag_tpu.serving.kv_cache import make_page_pools

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(7))
    b, num_pages, page_size, n_steps = 2, 16, 4, 6
    max_pages = 8

    rng = np.random.default_rng(0)
    seq_lens = np.asarray([5, 3], dtype=np.int32)
    bt = np.zeros((b, max_pages), dtype=np.int32)
    bt[0] = np.arange(8); bt[1] = np.arange(8, 16)
    last = np.asarray([4, 7], dtype=np.int32)

    outs = {}
    for use_pallas in (False, True):
        pools = make_page_pools(cfg, num_pages, page_size, dtype=jnp.float32)
        # identical pool contents for both paths
        rng2 = np.random.default_rng(42)
        k_init = jnp.asarray(rng2.standard_normal(pools.k.shape), dtype=jnp.float32)
        v_init = jnp.asarray(rng2.standard_normal(pools.v.shape), dtype=jnp.float32)
        toks, valid, k_out, v_out, _, out_lens = decode_burst(
            params, cfg,
            jnp.asarray(last), jnp.asarray(seq_lens),
            k_init, v_init,
            jnp.zeros((b, cfg.vocab_size), dtype=bool),
            jnp.ones((b,), dtype=bool),
            jnp.full((b,), 30, dtype=jnp.int32),
            jnp.asarray(bt), jax.random.PRNGKey(5),
            jnp.zeros((b,)), jnp.ones((b,)), jnp.zeros((b,), jnp.int32),
            jnp.ones((b,)),
            n_steps=n_steps, use_pallas=use_pallas,
        )
        outs[use_pallas] = (np.asarray(toks), np.asarray(valid),
                            np.asarray(k_out), np.asarray(v_out),
                            np.asarray(out_lens))

    np.testing.assert_array_equal(outs[False][0], outs[True][0])  # tokens
    np.testing.assert_array_equal(outs[False][1], outs[True][1])  # valid
    np.testing.assert_allclose(outs[False][2], outs[True][2], atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(outs[False][3], outs[True][3], atol=1e-5, rtol=1e-5)
    np.testing.assert_array_equal(outs[False][4], outs[True][4])
