"""Observability stack: trace context propagation (wire + contextvar),
the flight recorder's bounds and payloads, MeteredLLM span/status/token
accounting, /metrics label cardinality, the XLA compile watchdog, and the
full-stack connected-trace path API -> worker -> agent -> engine."""

import asyncio
import json
import subprocess
import sys
from pathlib import Path

import pytest

from githubrepostorag_tpu.metrics import (
    DECODE_TOKENS,
    FAULTS_INJECTED,
    HTTP_REQUESTS,
    LLM_CALLS,
    XLA_COMPILES,
    MeteredLLM,
    counter_value,
)
from githubrepostorag_tpu.obs import (
    NOOP_SPAN,
    FlightRecorder,
    get_recorder,
    reset_recorder,
    root_span,
    span,
)
from githubrepostorag_tpu.obs.trace import Span, TraceContext, current_context, trace_scope
from githubrepostorag_tpu.resilience.policy import Deadline

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture
def sampled(monkeypatch):
    """Force-sample every new root and start from an empty recorder."""
    monkeypatch.setenv("TRACE_SAMPLE", "1")
    yield reset_recorder()
    reset_recorder()


# ------------------------------------------------------------------- wire --


def test_traceparent_header_round_trip():
    ctx = TraceContext("ab" * 16, "cd" * 8, flags=1)
    back = TraceContext.from_header(ctx.to_header())
    assert back is not None
    assert (back.trace_id, back.span_id, back.flags) == (ctx.trace_id, ctx.span_id, 1)

    unsampled = TraceContext("ef" * 16, "01" * 8, flags=0)
    back = TraceContext.from_header(unsampled.to_header())
    assert back is not None and not back.sampled

    for junk in (None, "", "garbage", "00-zz-xx-01", "01-" + "a" * 32 + "-" + "b" * 16 + "-01"):
        assert TraceContext.from_header(junk) is None


def test_trace_rides_the_envelope_next_to_deadline():
    """The queue hop carries kwargs["trace"] beside kwargs["deadline"];
    both survive a JSON round trip (the Redis envelope is JSON)."""
    ctx = TraceContext("12" * 16, "34" * 8, flags=1)
    kwargs = {"deadline": Deadline(5.0).to_wire(), "trace": ctx.to_wire()}
    kwargs = json.loads(json.dumps(kwargs))  # the actual wire transform

    back = TraceContext.from_wire(kwargs.get("trace"))
    assert back is not None and back.trace_id == ctx.trace_id and back.sampled
    deadline = Deadline.from_wire(kwargs["deadline"])
    assert 3.0 < deadline.remaining() <= 5.0


def test_old_envelope_without_trace_key_still_parses():
    """Envelopes enqueued by a pre-tracing deployment have no trace field;
    from_wire must answer None for every malformed shape, never raise."""
    old = json.loads(json.dumps({"deadline": Deadline(2.0).to_wire()}))
    assert TraceContext.from_wire(old.get("trace")) is None
    assert Deadline.from_wire(old["deadline"]).remaining() > 0
    for junk in (None, 42, [], {"traceparent": 7}, {"other": "x"}):
        assert TraceContext.from_wire(junk) is None


# ------------------------------------------------------------ span scopes --


def test_span_without_scope_is_the_shared_noop(monkeypatch):
    monkeypatch.delenv("TRACE_SAMPLE", raising=False)
    with span("anything") as sp:
        assert sp is NOOP_SPAN
    with span("nested") as outer:
        with span("inner") as inner:
            assert outer is inner is NOOP_SPAN


def test_trace_sample_zero_records_nothing(monkeypatch):
    monkeypatch.setenv("TRACE_SAMPLE", "0")
    rec = reset_recorder()
    try:
        with root_span("http POST /rag/jobs") as sp:
            assert sp is NOOP_SPAN
            assert sp.context is None  # -> create_job sends trace=None
            with span("agent.run") as child:
                assert child is NOOP_SPAN
        assert rec.trace_ids() == []
    finally:
        reset_recorder()


def test_root_span_continues_wire_context_and_children_nest(sampled):
    wire = TraceContext("fe" * 16, "dc" * 8, flags=1).to_wire()
    with root_span("worker.job", wire=wire) as sp:
        assert sp.trace_id == "fe" * 16
        assert sp.parent_id == "dc" * 8
        with span("agent.run") as child:
            assert child.parent_id == sp.span_id
            assert current_context().span_id == child.span_id
    payload = sampled.trace_payload("fe" * 16)
    assert {s["name"] for s in payload["spans"]} == {"worker.job", "agent.run"}


def test_span_error_status_on_exception(sampled):
    with pytest.raises(ValueError):
        with root_span("worker.job"):
            with span("agent.plan"):
                raise ValueError("nope")
    tid = sampled.trace_ids()[0]
    by_name = {s["name"]: s for s in sampled.trace_payload(tid)["spans"]}
    assert by_name["agent.plan"]["status"] == "error: ValueError"
    assert by_name["worker.job"]["status"] == "error: ValueError"


# --------------------------------------------------------------- recorder --


def _finished_span(name, trace_id, dur=0.01):
    sp = Span(name, TraceContext(trace_id, "", 1))
    sp.end = sp.start + dur
    return sp


def test_recorder_evicts_oldest_trace_and_counts_drops():
    rec = FlightRecorder(max_traces=2, max_spans_per_trace=8)
    for i in range(4):
        rec.record(_finished_span("s", f"{i:032x}"))
    assert rec.trace_ids() == [f"{2:032x}", f"{3:032x}"]
    payload = rec.summaries_payload()
    assert payload["dropped_traces"] == 2
    assert payload["trace_count"] == 2
    assert rec.trace_payload(f"{0:032x}") is None  # evicted


def test_recorder_caps_spans_per_trace():
    rec = FlightRecorder(max_traces=4, max_spans_per_trace=3)
    tid = "aa" * 16
    for _ in range(5):
        rec.record(_finished_span("s", tid))
    payload = rec.trace_payload(tid)
    assert payload["span_count"] == 3
    assert payload["dropped_spans"] == 2


def test_recorder_bounded_memory_under_concurrent_writers():
    # the bounded-memory contract must hold while threaded producers race
    # the ring: trace count never exceeds max_traces, per-trace spans never
    # exceed max_spans_per_trace, and every record() is accounted for as
    # either a stored span, a dropped span, or part of an evicted trace
    import threading

    max_traces, max_spans = 8, 4
    writers, spans_each = 6, 200
    rec = FlightRecorder(max_traces=max_traces, max_spans_per_trace=max_spans)
    start = threading.Barrier(writers)

    def produce(widx):
        start.wait()
        for i in range(spans_each):
            # writers collide on shared trace ids (cap path) and mint
            # fresh ones (eviction path) in the same interleaving
            tid = f"{(widx * spans_each + i) % (max_traces * 3):032x}"
            rec.record(_finished_span("s", tid))

    threads = [threading.Thread(target=produce, args=(w,)) for w in range(writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    payload = rec.summaries_payload()
    assert payload["trace_count"] <= max_traces
    assert len(rec.trace_ids()) == payload["trace_count"]
    stored = dropped_spans = 0
    for tid in rec.trace_ids():
        tp = rec.trace_payload(tid)
        if tp is None:
            continue  # evicted between the two reads
        assert tp["span_count"] <= max_spans
        stored += tp["span_count"]
        dropped_spans += tp["dropped_spans"]
    assert stored <= max_traces * max_spans
    # no record() vanished silently: with 3*max_traces trace ids cycling,
    # evictions and span drops must both have fired under the race
    assert payload["dropped_traces"] > 0
    assert dropped_spans + stored > 0


def test_recorder_drop_counters_are_exact_single_trace_race():
    # all writers hammer ONE trace id: no evictions possible, so stored +
    # dropped must equal exactly the number of record() calls
    import threading

    max_spans = 16
    writers, spans_each = 8, 100
    rec = FlightRecorder(max_traces=2, max_spans_per_trace=max_spans)
    tid = "cc" * 16
    start = threading.Barrier(writers)

    def produce():
        start.wait()
        for _ in range(spans_each):
            rec.record(_finished_span("s", tid))

    threads = [threading.Thread(target=produce) for _ in range(writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    tp = rec.trace_payload(tid)
    assert tp["span_count"] == max_spans
    assert tp["dropped_spans"] == writers * spans_each - max_spans
    assert rec.summaries_payload()["dropped_traces"] == 0


def test_phase_summary_maps_and_sums_span_names():
    rec = FlightRecorder(max_traces=4, max_spans_per_trace=16)
    tid = "bb" * 16
    rec.record(_finished_span("engine.queue_wait", tid, dur=0.5))
    rec.record(_finished_span("engine.prefill", tid, dur=1.0))
    rec.record(_finished_span("engine.decode", tid, dur=2.0))
    rec.record(_finished_span("agent.retrieve", tid, dur=0.25))
    rec.record(_finished_span("agent.retrieve", tid, dur=0.25))  # second wave sums
    rec.record(_finished_span("worker.job", tid, dur=9.0))  # not a phase
    phases = rec.phase_summary(tid)
    assert phases == {"queue": 0.5, "prefill": 1.0, "decode": 2.0, "retrieve": 0.5}


def test_debug_traces_schema_matches_committed_golden():
    import os

    proc = subprocess.run(
        [sys.executable, "scripts/check_traces_schema.py"],
        cwd=REPO, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------- counter_value --


def test_counter_value_reads_each_multi_label_series():
    base_drop = counter_value(FAULTS_INJECTED, site="obs.test", action="drop")
    base_err = counter_value(FAULTS_INJECTED, site="obs.test", action="error")
    FAULTS_INJECTED.labels(site="obs.test", action="drop").inc()
    FAULTS_INJECTED.labels(site="obs.test", action="drop").inc()
    FAULTS_INJECTED.labels(site="obs.test", action="error").inc()
    assert counter_value(FAULTS_INJECTED, site="obs.test", action="drop") == base_drop + 2
    assert counter_value(FAULTS_INJECTED, site="obs.test", action="error") == base_err + 1
    assert counter_value(FAULTS_INJECTED, site="obs.test", action="never") == 0.0


# -------------------------------------------------------------- MeteredLLM --


class _ScriptedStream:
    """Inner LLM whose stream behavior is programmable per test."""

    def __init__(self, deltas=(), raises=None):
        self.deltas = list(deltas)
        self.raises = raises

    def stream_complete(self, prompt, **kw):
        for d in self.deltas:
            yield d
        if self.raises is not None:
            raise self.raises


def _llm_counts():
    return {s: counter_value(LLM_CALLS, status=s)
            for s in ("ok", "error", "cancelled")}


def test_metered_stream_counts_tokens_and_ok(sampled):
    before, tok_before = _llm_counts(), counter_value(DECODE_TOKENS)
    llm = MeteredLLM(_ScriptedStream(deltas=["a", "b", "c"]))
    with root_span("worker.job"):
        assert list(llm.stream_complete("q")) == ["a", "b", "c"]
    after = _llm_counts()
    assert after["ok"] == before["ok"] + 1
    assert after["error"] == before["error"]
    assert counter_value(DECODE_TOKENS) == tok_before + 3
    tid = sampled.trace_ids()[0]
    stream = next(s for s in sampled.trace_payload(tid)["spans"]
                  if s["name"] == "llm.stream")
    assert stream["status"] == "ok" and stream["attrs"]["deltas"] == 3


def test_metered_stream_error_delta_is_not_ok(sampled):
    """Regression: stream_complete used to label every call status="ok"
    even when the backend yielded its errors-as-text sentinel."""
    before = _llm_counts()
    llm = MeteredLLM(_ScriptedStream(deltas=["Error: backend down"]))
    with root_span("worker.job"):
        list(llm.stream_complete("q"))
    after = _llm_counts()
    assert after["error"] == before["error"] + 1
    assert after["ok"] == before["ok"]
    tid = sampled.trace_ids()[0]
    stream = next(s for s in sampled.trace_payload(tid)["spans"]
                  if s["name"] == "llm.stream")
    assert stream["status"].startswith("error")


def test_metered_stream_raise_is_not_ok():
    before = _llm_counts()
    llm = MeteredLLM(_ScriptedStream(deltas=["a"], raises=RuntimeError("boom")))
    with pytest.raises(RuntimeError):
        list(llm.stream_complete("q"))
    after = _llm_counts()
    assert after["error"] == before["error"] + 1
    assert after["ok"] == before["ok"]


def test_metered_stream_early_close_counts_cancelled():
    before = _llm_counts()
    llm = MeteredLLM(_ScriptedStream(deltas=["a", "b", "c"]))
    gen = llm.stream_complete("q")
    assert next(gen) == "a"
    gen.close()
    after = _llm_counts()
    assert after["cancelled"] == before["cancelled"] + 1
    assert after["ok"] == before["ok"]


# ----------------------------------------------------- compile watchdog ---


def test_compile_watchdog_detects_a_genuine_recompile():
    import jax
    import jax.numpy as jnp

    from githubrepostorag_tpu.obs.engine_profile import CompileWatchdog

    f = jax.jit(lambda x: x + 1)
    f(jnp.zeros((2,), jnp.float32))
    dog = CompileWatchdog(jits=[("test.f", f)])
    assert dog.sample() == 0  # warm shape, no new programs
    f(jnp.zeros((2,), jnp.float32))
    assert dog.sample() == 0  # cache hit is not a compile
    f(jnp.zeros((3,), jnp.float32))  # fresh shape -> real XLA compile
    assert dog.sample() == 1
    assert dog.sample() == 0  # delta, not level


def test_discover_jits_finds_the_serving_programs():
    from githubrepostorag_tpu.obs.engine_profile import discover_jits

    jits = discover_jits()
    assert jits, "no jitted callables found in the serving/model modules"
    assert all(callable(obj._cache_size) for _, obj in jits)


# ------------------------------------------------- full stack over a bus ---

AGENT_SCRIPT = {
    r"Pick the retrieval scope": '{"scope": "chunk", "filters": {}}',
    r"Assess whether the retrieved": '{"coverage": 0.9, "needs_more": false}',
    r"senior engineer": "Jobs are created via POST /rag/jobs [1].",
}


def _tiny_llm(max_num_seqs=2, num_pages=128):
    import jax
    import jax.numpy as jnp

    from githubrepostorag_tpu.llm import InProcessLLM
    from githubrepostorag_tpu.models import Qwen2Config, init_params
    from githubrepostorag_tpu.serving import Engine
    from githubrepostorag_tpu.serving.async_engine import AsyncEngine
    from githubrepostorag_tpu.serving.tokenizer import ByteTokenizer

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = Engine(params, cfg, max_num_seqs=max_num_seqs, num_pages=num_pages,
                 page_size=8, max_seq_len=256, prefill_chunk=64,
                 kv_dtype=jnp.float32)
    return InProcessLLM(AsyncEngine(eng), ByteTokenizer(),
                        default_max_tokens=8, default_temperature=0.0,
                        context_window=128)


class _HybridLLM:
    """Scripted plan/judge via FakeLLM; the synthesis prompt (the only one
    matching "senior engineer") runs through the real in-process engine so
    the trace reaches genuine prefill/decode spans."""

    def __init__(self, fake, real):
        self.fake, self.real = fake, real

    def _pick(self, prompt):
        return self.real if "senior engineer" in prompt else self.fake

    def complete(self, prompt, **kw):
        return self._pick(prompt).complete(prompt, **kw)

    def stream_complete(self, prompt, **kw):
        return self._pick(prompt).stream_complete(prompt, **kw)


def _stack(llm):
    from githubrepostorag_tpu.agent import GraphAgent
    from githubrepostorag_tpu.api.app import RagApi
    from githubrepostorag_tpu.embedding import HashingTextEncoder
    from githubrepostorag_tpu.events import MemoryBus, MemoryCancelFlags, MemoryJobQueue
    from githubrepostorag_tpu.retrieval import RetrieverFactory
    from githubrepostorag_tpu.store import Doc, MemoryVectorStore
    from githubrepostorag_tpu.worker import RagWorker

    store, enc = MemoryVectorStore(), HashingTextEncoder()
    texts = [
        ("c1", "async def create_job(request): enqueue and return job id",
         {"repo": "api", "module": "app", "file_path": "app/jobs.py"}),
        ("c2", "class RagWorker: consumes jobs and emits progress events",
         {"repo": "api", "module": "worker", "file_path": "worker/worker.py"}),
    ]
    store.upsert("embeddings", [
        Doc(d, t, {"namespace": "default", "scope": "chunk", **m}, enc.encode([t])[0])
        for d, t, m in texts
    ])
    agent = GraphAgent(llm, RetrieverFactory(store, enc), namespace="default")
    bus = MemoryBus(ping_interval=0.05)
    flags, queue = MemoryCancelFlags(), MemoryJobQueue()
    worker = RagWorker(agent, bus, flags, queue, max_jobs=2, job_timeout=120)
    return RagApi(bus, flags, queue), worker


async def _collect_events(session, base, job_id, timeout=120):
    import aiohttp

    events = []
    async with session.get(f"{base}/rag/jobs/{job_id}/events",
                           timeout=aiohttp.ClientTimeout(total=timeout)) as resp:
        async for raw in resp.content:
            line = raw.decode().strip()
            if line.startswith("data: "):
                events.append(json.loads(line[6:]))
                if events[-1]["event"] == "final":
                    break
    return events


async def test_one_connected_trace_api_to_engine_decode(sampled):
    """The acceptance trace: root API span -> worker continuation -> agent
    phase spans -> engine prefill/decode spans, all one trace_id, the full
    tree retrievable from /debug/traces/{trace_id}, and the compact phase
    summary on the terminal SSE event."""
    import aiohttp

    from githubrepostorag_tpu.llm import FakeLLM

    real = _tiny_llm()
    real.complete("warm the engine compile cache")  # compiles outside the job
    api, worker = _stack(_HybridLLM(FakeLLM(script=AGENT_SCRIPT), real))
    reset_recorder()  # drop the warmup call's trace noise
    port = await api.start(host="127.0.0.1", port=0)
    worker_task = asyncio.create_task(worker.run_forever())
    try:
        async with aiohttp.ClientSession() as session:
            base = f"http://127.0.0.1:{port}"
            resp = await session.post(f"{base}/rag/jobs",
                                      json={"query": "how are jobs created?"})
            body = await resp.json()
            trace_id = body["trace_id"]
            assert len(trace_id) == 32

            events = await _collect_events(session, base, body["job_id"])
            final = events[-1]["data"]
            assert final["trace_id"] == trace_id
            for phase in ("plan", "retrieve", "judge", "synthesize",
                          "prefill", "decode"):
                assert phase in final["phases"], (phase, final["phases"])
                assert final["phases"][phase] >= 0.0

            # worker.job finishes just after the final event; poll briefly
            payload, by_name = {}, {}
            for _ in range(50):
                detail = await session.get(f"{base}/debug/traces/{trace_id}")
                assert detail.status == 200
                payload = await detail.json()
                by_name = {s["name"]: s for s in payload["spans"]}
                if "worker.job" in by_name:
                    break
                await asyncio.sleep(0.05)
            for name in ("http POST /rag/jobs", "worker.job", "agent.run",
                         "agent.plan", "agent.retrieve", "agent.judge",
                         "agent.synthesize", "llm.generate",
                         "engine.queue_wait", "engine.prefill", "engine.decode"):
                assert name in by_name, f"missing span {name}: {sorted(by_name)}"

            # parent links form ONE connected tree rooted at the API span
            root = by_name["http POST /rag/jobs"]
            assert root["parent_id"] is None
            assert by_name["worker.job"]["parent_id"] == root["span_id"]
            assert by_name["agent.run"]["parent_id"] == by_name["worker.job"]["span_id"]
            assert (by_name["agent.synthesize"]["parent_id"]
                    == by_name["agent.run"]["span_id"])
            assert (by_name["llm.generate"]["parent_id"]
                    == by_name["agent.synthesize"]["span_id"])
            for eng_span in ("engine.queue_wait", "engine.prefill", "engine.decode"):
                assert (by_name[eng_span]["parent_id"]
                        == by_name["llm.generate"]["span_id"])

            # the index lists the trace under its API root
            summary = await (await session.get(f"{base}/debug/traces")).json()
            row = next(t for t in summary["traces"] if t["trace_id"] == trace_id)
            assert row["root"] == "http POST /rag/jobs"
            assert row["span_count"] == len(payload["spans"])

            missing = await session.get(f"{base}/debug/traces/{'0' * 32}")
            assert missing.status == 404
    finally:
        worker.stop()
        worker_task.cancel()
        await api.stop()
        real.close()


async def test_post_warmup_recompile_fires_watchdog(sampled):
    """A fresh XLA compile observed during live stepping must increment
    rag_xla_compiles_total and stamp an xla_compile event on the in-flight
    request's span."""
    import jax
    import jax.numpy as jnp

    from githubrepostorag_tpu.obs.engine_profile import CompileWatchdog

    f = jax.jit(lambda x: x * 2)
    f(jnp.zeros((2,), jnp.float32))  # pre-warm shape A
    llm = _tiny_llm()
    # watch our sentinel jit: its recompile below is a genuine XLA compile,
    # observed by the real per-step sampling on the engine driver thread
    llm.engine.profiler.watchdog = CompileWatchdog(jits=[("test.sentinel", f)])
    try:
        llm.complete("warm")  # AsyncEngine.start() -> profiler.mark_warm()
        before = counter_value(XLA_COMPILES)

        f(jnp.zeros((5,), jnp.float32))  # the post-warmup recompile
        with trace_scope(TraceContext(f"{7:032x}", "", 1)):
            out = llm.complete("probe request")
        assert isinstance(out, str)

        assert counter_value(XLA_COMPILES) == before + 1
        payload = get_recorder().trace_payload(f"{7:032x}")
        assert payload is not None
        gen = next(s for s in payload["spans"] if s["name"] == "llm.generate")
        compile_events = [e for e in gen["events"] if e["name"] == "xla_compile"]
        assert compile_events and compile_events[0]["new_programs"] == 1
    finally:
        llm.close()


# --------------------------------------------------- /metrics cardinality --


async def test_metrics_path_labels_use_route_templates():
    """A scrape must see ONE path label per route regardless of how many
    job ids traffic minted — raw ids in labels are a cardinality leak."""
    import aiohttp

    from githubrepostorag_tpu.api.app import RagApi
    from githubrepostorag_tpu.events import MemoryBus, MemoryCancelFlags, MemoryJobQueue

    api = RagApi(MemoryBus(ping_interval=0.05), MemoryCancelFlags(), MemoryJobQueue())
    port = await api.start(host="127.0.0.1", port=0)
    try:
        async with aiohttp.ClientSession() as session:
            base = f"http://127.0.0.1:{port}"
            for i in range(12):
                r = await session.get(f"{base}/rag/jobs/{i:032x}/result")
                assert r.status == 404  # unknown job; the route still matched
                c = await session.post(f"{base}/rag/jobs/{i:032x}/cancel")
                assert c.status == 200
        result_paths = {
            s.labels["path"]
            for s in HTTP_REQUESTS.collect()[0].samples
            if not s.name.endswith("_created") and "result" in s.labels.get("path", "")
        }
        assert result_paths == {"/rag/jobs/{job_id}/result"}
        cancel_paths = {
            s.labels["path"]
            for s in HTTP_REQUESTS.collect()[0].samples
            if not s.name.endswith("_created") and "cancel" in s.labels.get("path", "")
        }
        assert cancel_paths == {"/rag/jobs/{job_id}/cancel"}
    finally:
        await api.stop()
