"""Scoped graph retrievers: ANN seeding, metadata-edge traversal, ranking."""

import numpy as np

from githubrepostorag_tpu.embedding import HashingTextEncoder
from githubrepostorag_tpu.retrieval import RetrieverFactory
from githubrepostorag_tpu.retrieval.retrievers import SCOPE_SPECS, ScopeRetriever
from githubrepostorag_tpu.store import Doc, MemoryVectorStore


def _seed(store, encoder):
    chunks = [
        ("c1", "def create_job(): enqueue rag job", {"repo": "svc", "module": "api", "file_path": "api/jobs.py"}),
        ("c2", "def cancel_job(): set cancel flag", {"repo": "svc", "module": "api", "file_path": "api/jobs.py"}),
        ("c3", "class ProgressBus: redis pubsub events", {"repo": "svc", "module": "bus", "file_path": "bus/bus.py"}),
        ("c4", "helm values for cassandra statefulset", {"repo": "infra", "module": "helm", "file_path": "helm/values.yaml"}),
    ]
    docs = []
    for did, text, meta in chunks:
        meta = {"namespace": "default", **meta}
        vec = encoder.encode([text])[0]
        docs.append(Doc(did, text, meta, vec))
    store.upsert("embeddings", docs)


def test_ann_seed_plus_edge_traversal_pulls_same_file_chunks():
    from githubrepostorag_tpu.retrieval.retrievers import ScopeSpec

    store, enc = MemoryVectorStore(), HashingTextEncoder()
    _seed(store, enc)
    # start_k=1 so only c1 can seed; c2 must arrive via the file_path edge
    spec = ScopeSpec("chunk", k=10, start_k=1, adjacent_k=8, max_depth=2,
                     edges=("file_path", "module"))
    r = ScopeRetriever(store, enc, "chunk", spec=spec)
    docs = r.retrieve("how do I create a job?", {"namespace": "default"})
    ids = [d.doc_id for d in docs]
    assert ids[0] == "c1"  # best ANN match first
    assert "c2" in ids  # same file_path edge pulled the sibling chunk
    # seed is depth 0, edge-reached sibling has depth > 0
    by_id = {d.doc_id: d for d in docs}
    assert by_id["c1"].depth == 0
    assert by_id["c2"].depth >= 1


def test_filters_restrict_traversal():
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    _seed(store, enc)
    r = ScopeRetriever(store, enc, "chunk")
    docs = r.retrieve("cassandra helm values", {"namespace": "default", "repo": "svc"})
    assert all(d.metadata["repo"] == "svc" for d in docs)


def test_k_cap_respected():
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    many = [
        Doc(f"d{i}", f"function number {i} does work", {"namespace": "default", "repo": "r", "module": "m", "file_path": "f.py"},
            enc.encode([f"function number {i} does work"])[0])
        for i in range(30)
    ]
    store.upsert("embeddings", many)
    r = ScopeRetriever(store, enc, "chunk")
    docs = r.retrieve("function work", {"namespace": "default"})
    assert len(docs) <= SCOPE_SPECS["chunk"].k


def test_factory_caches_and_validates():
    import pytest

    store, enc = MemoryVectorStore(), HashingTextEncoder()
    f = RetrieverFactory(store, enc)
    assert f.for_scope("repo") is f.for_scope("repo")
    with pytest.raises(KeyError):
        f.for_scope("nonsense")


def test_empty_store_returns_empty():
    f = RetrieverFactory(MemoryVectorStore(), HashingTextEncoder())
    assert f.retrieve("chunk", "anything") == []


def test_mmr_prefers_diverse_over_redundant():
    """MMR selection (the reference's richer GraphRetrieverFactory design,
    dead there, live here): given near-duplicate top hits, the second pick
    must be the diverse document, not the duplicate."""
    from githubrepostorag_tpu.retrieval.retrievers import RetrievedDoc, mmr_select

    a = np.asarray([1.0, 0.0], dtype=np.float32)
    a_dup = np.asarray([0.999, 0.045], dtype=np.float32)
    a_dup /= np.linalg.norm(a_dup)
    b = np.asarray([0.0, 1.0], dtype=np.float32)
    docs = [
        RetrievedDoc("a", "", {}, 0.95),
        RetrievedDoc("a_dup", "", {}, 0.94),
        RetrievedDoc("b", "", {}, 0.60),
    ]
    vectors = {"a": a, "a_dup": a_dup, "b": b}
    picked = [d.doc_id for d in mmr_select(docs, vectors, k=2, lam=0.4)]
    assert picked == ["a", "b"]
    # pure relevance would have picked the duplicate
    ranked = [d.doc_id for d in sorted(docs, key=lambda d: d.score, reverse=True)][:2]
    assert ranked == ["a", "a_dup"]


def test_mmr_scope_retriever_end_to_end():
    from githubrepostorag_tpu.retrieval.retrievers import SCOPE_SPECS, ScopeRetriever

    assert SCOPE_SPECS["chunk"].mmr_lambda == 0.3  # reference lambdas
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    _seed(store, enc)
    r = ScopeRetriever(store, enc, "chunk")
    docs = r.retrieve("how do I create a job?", {"namespace": "default"})
    assert docs and docs[0].doc_id == "c1"  # top relevance still leads


def test_hashing_encoder_md5_cache_hits_and_parity():
    """The module-level md5->(index, sign) LRU must not change encodings,
    and repeated encodes of the same vocabulary must hit it."""
    from githubrepostorag_tpu.embedding import _hash_slot

    enc = HashingTextEncoder(dim=96)
    _hash_slot.cache_clear()
    first = enc.encode(["rebalance the kafka consumer group"] * 3)
    info = _hash_slot.cache_info()
    assert info.hits > 0  # texts 2 and 3 reuse text 1's tokens
    again = enc.encode(["rebalance the kafka consumer group"])
    assert _hash_slot.cache_info().misses == info.misses  # all cached now
    np.testing.assert_array_equal(first[0], again[0])
    # distinct dims hash to distinct slots (dim is part of the cache key)
    enc2 = HashingTextEncoder(dim=7)
    vec = enc2.encode(["rebalance"])[0]
    assert vec.shape == (7,)


def test_retrieve_many_batches_seed_search(monkeypatch):
    """retrieve_many must issue ONE batched seed search for the whole query
    set, not one search per query."""
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    _seed(store, enc)
    calls = {"batch": 0, "single": 0}
    orig_batch = store.search_batch
    orig_single = store.search

    def counting_batch(*a, **kw):
        calls["batch"] += 1
        return orig_batch(*a, **kw)

    def counting_single(*a, **kw):
        calls["single"] += 1
        return orig_single(*a, **kw)

    monkeypatch.setattr(store, "search_batch", counting_batch)
    monkeypatch.setattr(store, "search", counting_single)
    r = ScopeRetriever(store, enc, "chunk")
    r.retrieve_many(["create a job", "cancel a job", "redis pubsub"],
                    {"namespace": "default"})
    assert calls["batch"] == 1
    # the default search_batch loops search() internally; no EXTRA singles
    assert calls["single"] == 3
