"""Token-budget packed prefill (PR 2 tentpole): op-level parity against a
direct per-token oracle, engine packed-vs-padded greedy parity on the
heterogeneous traffic the packed path exists for (mixed lengths, mid-chunk
splits, prefix-cache resumes), and the compiled-shape discipline (warmup
predicts the packed program count exactly; live traffic adds zero)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.serving import Engine, SamplingParams
from tests.helpers.compile_guard import compile_guard


# --------------------------------------------------------------- op level


def _ref_packed_attention(q, k_pages, v_pages, block_tables, cached_lens,
                          new_lens, seg_ids, positions):
    """Per-token oracle: packed token t of segment s attends causally over
    that segment's first positions[t]+1 cached tokens, gathered page by
    page from the pool — no segment-major scatter, no masking tricks."""
    t_, n_q, hd = q.shape
    n_kv, _, ps, _ = k_pages.shape
    group = n_q // n_kv
    qf = np.asarray(q, np.float32)
    kf = np.asarray(k_pages, np.float32)
    vf = np.asarray(v_pages, np.float32)
    bt = np.asarray(block_tables)
    out = np.zeros((t_, n_q, hd), np.float32)
    for t in range(t_):
        s = int(seg_ids[t])
        if s >= bt.shape[0]:
            continue  # padding token — op output is unspecified garbage
        kv_len = int(positions[t]) + 1
        ks = np.stack([kf[:, bt[s, p // ps], p % ps] for p in range(kv_len)])
        vs = np.stack([vf[:, bt[s, p // ps], p % ps] for p in range(kv_len)])
        for h in range(n_q):
            scores = ks[:, h // group] @ qf[t, h] / np.sqrt(hd)
            w = np.exp(scores - scores.max())
            out[t, h] = (w / w.sum()) @ vs[:, h // group]
    return out


def _packed_case(seed=0):
    """3 live segments + 1 padding token in a 16-token budget: a mid-prompt
    chunk (cached 5, new 3), a fresh full chunk (cached 0, new 8 == tq),
    and a tail chunk deep into page 2 (cached 11, new 4)."""
    rng = np.random.default_rng(seed)
    n_kv, pages, ps, hd, group = 2, 8, 8, 16, 2
    r, tq = 3, 8
    k_pages = jnp.asarray(rng.normal(0, 1, (n_kv, pages, ps, hd)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(0, 1, (n_kv, pages, ps, hd)), jnp.float32)
    block_tables = jnp.asarray([[0, 1], [2, 3], [4, 5]], jnp.int32)
    cached = jnp.asarray([5, 0, 11], jnp.int32)
    new = jnp.asarray([3, 8, 4], jnp.int32)
    seg_ids, positions = [], []
    for s in range(r):
        for i in range(int(new[s])):
            seg_ids.append(s)
            positions.append(int(cached[s]) + i)
    seg_ids.append(r)  # padding slot
    positions.append(0)
    q = jnp.asarray(rng.normal(0, 1, (len(seg_ids), n_kv * group, hd)),
                    jnp.float32)
    return (q, k_pages, v_pages, block_tables, cached, new,
            jnp.asarray(seg_ids, jnp.int32), jnp.asarray(positions, jnp.int32),
            tq)


@pytest.mark.parametrize("use_pallas", [False, True])
def test_packed_prefill_attention_matches_oracle(use_pallas):
    from githubrepostorag_tpu.ops.packed_prefill import packed_prefill_attention

    (q, kp, vp, bt, cached, new, seg, pos, tq) = _packed_case()
    out = packed_prefill_attention(q, kp, vp, bt, cached, new, seg, pos,
                                   tq=tq, use_pallas=use_pallas)
    ref = _ref_packed_attention(q, kp, vp, bt, cached, new, seg, pos)
    live = np.asarray(seg) < bt.shape[0]
    np.testing.assert_allclose(np.asarray(out)[live], ref[live],
                               rtol=2e-5, atol=2e-5)
    assert np.isfinite(np.asarray(out)).all()  # padding rows: finite garbage


def test_packed_prefill_attention_quant_pages_match_oracle():
    """kv_quant pools route through the gather path with per-page dequant
    (even under use_pallas) — parity is against the oracle over the
    DEQUANTIZED pages."""
    from githubrepostorag_tpu.ops.packed_prefill import packed_prefill_attention

    def quantize(pages):  # per-page symmetric int8, [n_kv, P] scales
        scales = jnp.maximum(jnp.max(jnp.abs(pages), axis=(2, 3)) / 127.0, 1e-8)
        return (jnp.round(pages / scales[:, :, None, None]).astype(jnp.int8),
                scales)

    (q, kp, vp, bt, cached, new, seg, pos, tq) = _packed_case(seed=3)
    kq, ks = quantize(kp)
    vq, vs = quantize(vp)
    out = packed_prefill_attention(q, kq, vq, bt, cached, new, seg, pos,
                                   tq=tq, use_pallas=True,  # quant forces XLA
                                   k_scales=ks, v_scales=vs)
    kdq = kq.astype(jnp.float32) * ks[:, :, None, None]
    vdq = vq.astype(jnp.float32) * vs[:, :, None, None]
    ref = _ref_packed_attention(q, kdq, vdq, bt, cached, new, seg, pos)
    live = np.asarray(seg) < bt.shape[0]
    np.testing.assert_allclose(np.asarray(out)[live], ref[live],
                               rtol=2e-5, atol=2e-5)


# ------------------------------------------------- engine parity (vs HF)

transformers = pytest.importorskip("transformers")
import torch  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    from githubrepostorag_tpu.models.hf_loader import (
        config_from_hf,
        params_from_state_dict,
    )

    hf_cfg = transformers.Qwen2Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg.to_dict())
    params = params_from_state_dict(model.state_dict(), cfg)
    return model, params, cfg


def _make_engine(params, cfg, **kw):
    defaults = dict(
        max_num_seqs=4, num_pages=64, page_size=8, max_seq_len=128,
        prefill_chunk=32, kv_dtype=jnp.float32,
    )
    defaults.update(kw)
    return Engine(params, cfg, **defaults)


def _hf_greedy(model, prompt, n):
    ids = torch.tensor([prompt])
    with torch.no_grad():
        out = model.generate(
            ids, max_new_tokens=n, do_sample=False,
            pad_token_id=0, eos_token_id=None, use_cache=True,
        )
    return out[0, len(prompt):].tolist()


@pytest.mark.parametrize("use_pallas", [False, True])
def test_packed_prefill_matches_padded_and_hf(tiny, use_pallas):
    """Greedy tokens must be IDENTICAL to the padded engine and to HF on a
    wave the packed path actually reshapes: mixed lengths, a budget (48)
    smaller than the pending work (splits chunks mid-way), 5 prompts
    through 4 rows (continuous-batching admission)."""
    model, params, cfg = tiny
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, cfg.vocab_size, size=n).tolist()
               for n in (5, 16, 17, 70, 33)]
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    padded = _make_engine(params, cfg, prefill_widths=2)
    packed = _make_engine(params, cfg, prefill_token_budget=48,
                          use_pallas=use_pallas)
    got_padded = [r.output_tokens for r in padded.generate(prompts, sp)]
    got_packed = [r.output_tokens for r in packed.generate(prompts, sp)]
    assert got_packed == got_padded
    for prompt, toks in zip(prompts, got_packed):
        assert toks == _hf_greedy(model, prompt, 8)
    assert packed.packed_prefill_tokens == sum(len(p) for p in prompts)
    assert packed.packed_prefill_padding > 0  # heterogeneous wave padded some


def test_packed_prefill_prefix_cache_resume_matches_hf(tiny):
    """Prefix-cache hits hand the packed scheduler short uncached suffixes
    with nonzero cached_lens — the heterogeneity the budget packs around.
    A warm repeat and a shared-prefix variant must both match HF."""
    model, params, cfg = tiny
    rng = np.random.default_rng(9)
    prefix = rng.integers(0, cfg.vocab_size, size=40).tolist()  # 5 full pages
    tails = [rng.integers(0, cfg.vocab_size, size=n).tolist() for n in (3, 9)]
    eng = _make_engine(params, cfg, prefill_token_budget=48)
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    cold = eng.generate([prefix + tails[0]], sp)[0]
    hits0 = eng._allocator.hit_tokens
    warm = eng.generate([prefix + t for t in tails], sp)
    assert eng._allocator.hit_tokens > hits0  # the resume path actually ran
    assert cold.output_tokens == _hf_greedy(model, prefix + tails[0], 8)
    for tail, res in zip(tails, warm):
        assert res.output_tokens == _hf_greedy(model, prefix + tail, 8)


def test_packed_kv_quant_matches_padded_kv_quant(tiny):
    """int8 KV pages quantize identically under both dispatch modes (same
    commit path), so greedy tokens stay identical packed vs padded."""
    _, params, cfg = tiny
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, cfg.vocab_size, size=n).tolist()
               for n in (5, 17, 33)]
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    padded = _make_engine(params, cfg, kv_quant=True)
    packed = _make_engine(params, cfg, kv_quant=True, prefill_token_budget=48)
    assert ([r.output_tokens for r in packed.generate(prompts, sp)]
            == [r.output_tokens for r in padded.generate(prompts, sp)])


# ------------------------------------------------ compiled-shape discipline


def test_packed_warmup_compiles_exact_shape_set(tiny):
    """warmup() must compile exactly one forward_paged_packed program per
    packed_prefill_buckets() entry, and live traffic (mixed lengths,
    admission churn, prefix-cache resumes) must add ZERO — the packed
    path's whole point is collapsing the (row bucket x width) shape zoo."""
    from githubrepostorag_tpu.models.qwen2 import forward_paged_packed

    _, params, cfg = tiny
    # budget 40 (not the 48 other tests use): forward_paged_packed is a
    # module-global jit, so a shared buffer shape would arrive pre-compiled
    # and break the exact-count assertion below
    eng = _make_engine(params, cfg, prefill_token_budget=40)
    assert eng.packed_prefill_buckets() == [1, 2, 4]
    with compile_guard(forward_paged_packed._cache_size,
                       expect=len(eng.packed_prefill_buckets()),
                       label="packed warmup"):
        eng.warmup()
    rng = np.random.default_rng(13)
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    prompts = [rng.integers(0, cfg.vocab_size, size=n).tolist()
               for n in (5, 16, 17, 70, 33)]
    with compile_guard(forward_paged_packed._cache_size,
                       label="mixed packed traffic"):
        eng.generate(prompts, sp)
        eng.generate(prompts, sp)  # warm repeat: prefix-cache resume traffic
    # the collapse claim: packed shapes never exceed the padded engine's
    # (row bucket x width bucket) grid for the same geometry
    padded = _make_engine(params, cfg, prefill_widths=2)
    row_buckets = {min(b, padded.max_num_seqs)
                   for b in (1, 2, 4, 8) if b <= padded.max_num_seqs}
    padded_shapes = len(row_buckets) * len(padded.prefill_width_buckets)
    assert len(eng.packed_prefill_buckets()) <= padded_shapes
