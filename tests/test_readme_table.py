"""README perf-table drift gate (VERDICT r04 next #2).

The r03 and r04 rounds both shipped a README whose perf table disagreed
with the driver-visible evidence.  scripts/readme_perf_table.py now renders
a driver column (latest BENCH_r0N.json tail) next to the builder column
(BENCH_SUMMARY.json); this test regenerates that block from the committed
artifacts and FAILS CI when README.md's block differs — hand-edits and
stale tables can't reach a release.
"""

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "scripts"))

import readme_perf_table as rpt  # noqa: E402


def test_readme_matches_committed_bench_artifacts():
    text = (ROOT / "README.md").read_text()
    i = text.index(rpt.START)
    j = text.index(rpt.END) + len(rpt.END)
    committed = text[i:j]
    regenerated = rpt.render()
    assert committed == regenerated, (
        "README.md perf table drifted from the committed bench artifacts; "
        "run: python scripts/readme_perf_table.py"
    )


def test_driver_summary_parses_from_latest_round_artifact():
    name, summary = rpt.load_driver_summary()
    assert name.startswith("BENCH_r")
    # the flagship decode metric must be driver-visible
    assert any(k.startswith("decode_tok_s_per_chip_qwen2-7b") for k in summary)


def test_driver_summary_survives_front_truncated_tail(tmp_path):
    """The driver keeps only the last ~2000 chars — the summary line may be
    cut at the FRONT; per-metric recovery must still work."""
    (tmp_path / "BENCH_r09.json").write_text(
        '{"tail": "...cut...95.727,\\"x_a\\":80.3}}\\n{\\"metric\\": '
        '\\"decode_tok_s_per_chip_qwen2-7b_int8_bs32\\", \\"value\\": 2191.0}", '
        '"rc": 0}'
    )
    # no bench_summary key survived the cut -> falls through to no summary
    name, summary = rpt.load_driver_summary(tmp_path)
    assert (name, summary) == ("", {})

    (tmp_path / "BENCH_r10.json").write_text(
        '{"tail": "{\\"bench_summary\\":{\\"a_metric\\":1.5,'
        '\\"b_metric\\":2191.055}}\\n{\\"metric\\": \\"a\\"}", "rc": 0}'
    )
    name, summary = rpt.load_driver_summary(tmp_path)
    assert name == "BENCH_r10.json"
    assert summary == {"a_metric": 1.5, "b_metric": 2191.055}
