"""README perf-table drift gate (VERDICT r04 next #2).

The r03 and r04 rounds both shipped a README whose perf table disagreed
with the driver-visible evidence.  scripts/readme_perf_table.py now renders
a driver column (latest BENCH_r0N.json tail) next to the builder column
(BENCH_SUMMARY.json); this test regenerates that block from the committed
artifacts and FAILS CI when README.md's block differs — hand-edits and
stale tables can't reach a release.
"""

import pathlib
import re
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "scripts"))

import readme_perf_table as rpt  # noqa: E402


def _tracked_bench_artifacts() -> list[str]:
    """COMMITTED driver artifacts, via ``git ls-files`` — a local untracked
    BENCH_r*.json (e.g. a builder's scratch copy of a driver tail) must not
    shift the "newest two" window versus CI, which tests the committed
    tree.  Falls back to the filesystem glob when git is unavailable
    (tarball checkouts)."""
    try:
        out = subprocess.run(
            ["git", "ls-files", "BENCH_r*.json"],
            cwd=ROOT, capture_output=True, text=True, check=True,
        ).stdout.split()
    except (OSError, subprocess.CalledProcessError):
        out = []
    if not out:
        out = [p.name for p in ROOT.glob("BENCH_r[0-9]*.json")]
    return [pathlib.PurePath(p).name for p in out
            if re.fullmatch(r"BENCH_r[0-9]+\.json", pathlib.PurePath(p).name)]


def test_readme_matches_committed_bench_artifacts():
    """Regeneration is PINNED to the driver artifact named in the README's
    own column header: the round driver drops a NEWER BENCH_r0N.json at
    round end (after the README was committed), and the gate must catch
    hand-edits/stale tables without failing on that expected newer file —
    the next round's first regeneration picks it up."""
    text = (ROOT / "README.md").read_text()
    i = text.index(rpt.START)
    j = text.index(rpt.END) + len(rpt.END)
    committed = text[i:j]
    pin = rpt.committed_driver_name(committed)  # parse the BLOCK, not the
    # whole README — prose elsewhere could echo a header line
    regenerated = rpt.render(driver_name=pin)
    assert committed == regenerated, (
        "README.md perf table drifted from the committed bench artifacts; "
        "run: python scripts/readme_perf_table.py"
    )
    # the pin tolerance is ONE round of driver lag, not arbitrary
    # staleness: the pinned artifact must be the newest or second-newest
    # committed BENCH_r0N.json (the newest appears when the round driver
    # runs after README was committed)
    recent = sorted(_tracked_bench_artifacts(), reverse=True)[:2]
    # "" (a committed no-driver header) is only legitimate before any
    # driver artifact exists at all
    assert pin in recent or (pin == "" and not recent), (
        f"README's driver column pins {pin!r} but the newest artifacts are "
        f"{recent} — regenerate: python scripts/readme_perf_table.py"
    )


def test_driver_summary_parses_from_latest_round_artifact():
    name, summary = rpt.load_driver_summary()
    assert name.startswith("BENCH_r")
    # the flagship decode metric must be driver-visible
    assert any(k.startswith("decode_tok_s_per_chip_qwen2-7b") for k in summary)


def test_driver_summary_survives_front_truncated_tail(tmp_path):
    """The driver keeps only the last ~2000 chars — the summary line may be
    cut at the FRONT, even past the "bench_summary" key itself (r05 was);
    per-metric recovery must still work."""
    (tmp_path / "BENCH_r09.json").write_text(
        '{"tail": "...cut...95.727,\\"x_a\\":80.3}}\\n{\\"metric\\": '
        '\\"decode_tok_s_per_chip_qwen2-7b_int8_bs32\\", \\"value\\": 2191.0}", '
        '"rc": 0}'
    )
    # the key itself was cut, but the first line still closes the summary
    # object: its surviving compact pairs are recovered (the spaced emit
    # lines after the newline never parse as pairs)
    name, summary = rpt.load_driver_summary(tmp_path)
    assert name == "BENCH_r09.json"
    assert summary == {"x_a": 80.3}

    # a tail whose first line never closes a summary object stays no-driver
    (tmp_path / "BENCH_r09.json").write_text(
        '{"tail": "some log line\\n{\\"metric\\": \\"a\\", \\"value\\": 1.0}", '
        '"rc": 0}'
    )
    name, summary = rpt.load_driver_summary(tmp_path)
    assert (name, summary) == ("", {})

    (tmp_path / "BENCH_r10.json").write_text(
        '{"tail": "{\\"bench_summary\\":{\\"a_metric\\":1.5,'
        '\\"b_metric\\":2191.055}}\\n{\\"metric\\": \\"a\\"}", "rc": 0}'
    )
    name, summary = rpt.load_driver_summary(tmp_path)
    assert name == "BENCH_r10.json"
    assert summary == {"a_metric": 1.5, "b_metric": 2191.055}
