"""Donation contract on the KV migrate path.

``scatter_pages`` is jitted with ``donate_argnums=(0, 1, 4, 5)``: the
device pools (and quantization scales) handed in are *donated* — XLA may
reuse their buffers for the outputs, so the caller must rebind from the
returned tuple and never touch the originals again.  The whole-program
linter (SPD002) proves every call site in the tree follows that contract
statically; this test pins it dynamically, so a future edit that drops
the rebinding (``_, _, _, _ = scatter_pages(...)``) fails a behavioral
test as well as the lint gate.

On CPU donation is allowed to be a no-op (the runtime may keep the input
buffer alive), so the deletion probe is opportunistic: we only assert
that *if* the runtime did consume the input, reading it raises — and
that the returned pools are correct either way.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from githubrepostorag_tpu.ops.page_migration import gather_pages, scatter_pages


def _pools(seed=11):
    L, n_kv, P, ps, hd, nb = 2, 2, 6, 4, 8, 4
    rng = np.random.default_rng(seed)
    k0 = jnp.asarray(rng.standard_normal((L, n_kv, P, ps, hd)), jnp.float32)
    v0 = jnp.asarray(rng.standard_normal((L, n_kv, P, ps, hd)), jnp.float32)
    pk = jnp.asarray(rng.standard_normal((L, n_kv, nb, ps, hd)), jnp.float32)
    pv = jnp.asarray(rng.standard_normal((L, n_kv, nb, ps, hd)), jnp.float32)
    idx = jnp.asarray(np.array([4, 1, -1, -1], np.int32))
    return k0, v0, pk, pv, idx


def test_scatter_pages_rebinding_contract_carries_the_burst():
    """The migrate path must rebind the pools from scatter_pages' return
    value: the returned arrays — not the donated inputs — are the ones
    that carry the fault-in burst."""
    k0, v0, pk, pv, idx = _pools()
    k_ref, v_ref = np.asarray(k0), np.asarray(v0)

    k1, v1, _, _ = scatter_pages(k0, v0, idx, pk, v_vals=pv)

    # the rebound pools carry the burst at the real rows...
    np.testing.assert_array_equal(np.asarray(k1[:, :, 4]), np.asarray(pk[:, :, 0]))
    np.testing.assert_array_equal(np.asarray(v1[:, :, 4]), np.asarray(pv[:, :, 0]))
    np.testing.assert_array_equal(np.asarray(k1[:, :, 1]), np.asarray(pk[:, :, 1]))
    # ...and every untouched page survives the buffer reuse intact
    for p in [0, 2, 3, 5]:
        np.testing.assert_array_equal(np.asarray(k1[:, :, p]), k_ref[:, :, p])
        np.testing.assert_array_equal(np.asarray(v1[:, :, p]), v_ref[:, :, p])


def test_scatter_pages_donated_inputs_are_dead_after_the_call():
    """If the runtime honored the donation, the input pools are deleted
    and any read raises — exactly the hazard SPD002 flags statically.
    Donation may legally be a no-op (CPU often keeps the buffer), so a
    still-live input only has to still hold its pre-call contents."""
    k0, v0, pk, pv, idx = _pools(seed=12)
    k_ref = np.asarray(k0)

    k1, v1, _, _ = scatter_pages(k0, v0, idx, pk, v_vals=pv)
    jax.block_until_ready((k1, v1))

    for donated in (k0, v0):
        if donated.is_deleted():
            with pytest.raises(RuntimeError):
                np.asarray(donated)
    if not k0.is_deleted():
        # no-op donation: the original is untouched, the burst only
        # exists in the rebound result
        np.testing.assert_array_equal(np.asarray(k0), k_ref)
        assert not np.array_equal(np.asarray(k1[:, :, 4]), k_ref[:, :, 4])


def test_gather_pages_does_not_consume_its_inputs():
    """gather_pages is jitted WITHOUT donate_argnums: the pools stay
    live and readable after the call — the read side of a migration
    burst must not invalidate the resident pools."""
    k0, v0, pk, pv, idx = _pools(seed=13)
    k1, v1, _, _ = scatter_pages(k0.copy(), v0.copy(), idx, pk, v_vals=pv)

    gk, gv, _, _ = gather_pages(k1, v1, idx)
    jax.block_until_ready((gk, gv))

    assert not k1.is_deleted() and not v1.is_deleted()
    # real rows round-trip, and the pools are still readable afterwards
    np.testing.assert_array_equal(np.asarray(gk[:, :, 0]), np.asarray(pk[:, :, 0]))
    np.testing.assert_array_equal(np.asarray(gv[:, :, 0]), np.asarray(pv[:, :, 0]))
    np.testing.assert_array_equal(np.asarray(k1[:, :, 4]), np.asarray(pk[:, :, 0]))
