"""Int8 weight-only quantization: roundtrip error, forward parity, engine."""

import numpy as np

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.quant import (
    QuantizedLinear,
    dequantize,
    qmatmul,
    quantize_qwen2_params,
    quantize_weight,
)
from githubrepostorag_tpu.models.qwen2 import Qwen2Config, forward, init_params


def test_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(0, 0.02, (64, 128)), dtype=jnp.float32)
    qt = quantize_weight(w)
    assert qt.q.dtype == jnp.int8 and qt.q.shape == (64, 128)
    assert qt.s.shape == (128,)
    err = np.abs(np.asarray(dequantize(qt, jnp.float32)) - np.asarray(w))
    # per element: scale/2 from int8 rounding + up to ~scale/4 from the
    # bf16 storage of the scale itself (127 * 2^-9)
    assert err.max() <= float(np.asarray(qt.s, dtype=np.float32).max()) * 0.8


def test_quantize_stacked_layers_shapes():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(0, 0.02, (3, 16, 32)), dtype=jnp.float32)
    qt = quantize_weight(w)
    assert qt.q.shape == (3, 16, 32) and qt.s.shape == (3, 32)
    deq = dequantize(qt, jnp.float32)
    np.testing.assert_allclose(np.asarray(deq), np.asarray(w), atol=2e-3)


def test_qmatmul_matches_dequant_matmul():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 64)), dtype=jnp.float32)
    w = jnp.asarray(rng.normal(0, 0.02, (64, 128)), dtype=jnp.float32)
    qt = quantize_weight(w)
    np.testing.assert_allclose(
        np.asarray(qmatmul(x, qt)), np.asarray(x @ dequantize(qt, jnp.float32)),
        rtol=1e-5, atol=1e-5,
    )


def test_quantized_forward_tracks_bf16_logits():
    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qparams = quantize_qwen2_params(params)
    ids = jnp.asarray(np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 16)),
                      dtype=jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (2, 16))
    ref, _ = forward(params, cfg, ids, pos)
    out, _ = forward(qparams, cfg, ids, pos)
    a = np.asarray(ref).reshape(-1).astype(np.float64)
    b = np.asarray(out).reshape(-1).astype(np.float64)
    corr = np.dot(a - a.mean(), b - b.mean()) / (np.std(a) * np.std(b) * a.size)
    assert corr > 0.999, corr  # int8 tracks fp closely at init scale


def test_engine_runs_with_quantized_params():
    from githubrepostorag_tpu.serving import Engine, SamplingParams

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(7), dtype=jnp.float32)
    qparams = quantize_qwen2_params(params)
    eng = Engine(qparams, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                 max_seq_len=64, kv_dtype=jnp.float32, decode_burst=8)
    res = eng.generate([[1, 2, 3, 4, 5]],
                       SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=()))[0]
    assert len(res.output_tokens) == 8
    assert res.finish_reason == "length"


def test_tp2_engine_with_quantized_params_token_identical():
    """Weight-only int8 composes with TP sharding: the quantized specs tree
    mirrors the QuantizedLinear structure, and tp=2 greedy decode matches
    the single-device quantized engine."""
    from githubrepostorag_tpu.parallel import MeshPlan, make_mesh
    from githubrepostorag_tpu.serving import Engine, SamplingParams

    cfg = Qwen2Config.tiny()
    qparams = quantize_qwen2_params(init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32))

    def run(mesh):
        eng = Engine(qparams, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                     max_seq_len=64, kv_dtype=jnp.float32, decode_burst=8,
                     mesh=mesh)
        sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
        return [r.output_tokens for r in eng.generate([[1, 2, 3], [6, 5, 4]], sp)]

    assert run(make_mesh(MeshPlan(tp=2))) == run(None)


def test_quantize_rejects_tree_with_no_known_projection_leaf():
    """A renamed/foreign params tree must fail loudly: silently returning
    it unquantized serves full-precision weights under an int8 config —
    no error, 2x the HBM, and the miss only shows in a memory profile."""
    import pytest

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(3))
    params["layers"] = {f"foreign_{k}": v for k, v in params["layers"].items()}
    with pytest.raises(ValueError, match="no known projection leaf"):
        quantize_qwen2_params(params)
