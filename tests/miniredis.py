"""A tiny in-process RESP2 server implementing just the commands the
framework's Redis layer uses (SET/GET/DEL/EX, PUBLISH/SUBSCRIBE,
LPUSH/BRPOP, AUTH/SELECT).  Lets the RedisBus/RedisJobQueue path be tested
end-to-end over a real TCP socket without a Redis binary in the image."""

from __future__ import annotations

import asyncio
import time
from collections import defaultdict, deque


class MiniRedis:
    def __init__(self) -> None:
        self.kv: dict[str, tuple[str, float | None]] = {}
        self.lists: dict[str, deque[str]] = defaultdict(deque)
        self.subscribers: dict[str, list[asyncio.StreamWriter]] = defaultdict(list)
        self.server: asyncio.AbstractServer | None = None
        self.port: int | None = None

    async def start(self) -> int:
        self.server = await asyncio.start_server(self._handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self.server:
            self.server.close()
            await self.server.wait_closed()

    async def _read_command(self, reader: asyncio.StreamReader) -> list[str] | None:
        line = await reader.readline()
        if not line:
            return None
        assert line[:1] == b"*", line
        n = int(line[1:-2])
        args = []
        for _ in range(n):
            hdr = await reader.readline()
            assert hdr[:1] == b"$"
            length = int(hdr[1:-2])
            data = await reader.readexactly(length + 2)
            args.append(data[:-2].decode("utf-8"))
        return args

    @staticmethod
    def _simple(s: str) -> bytes:
        return f"+{s}\r\n".encode()

    @staticmethod
    def _bulk(s: str | None) -> bytes:
        if s is None:
            return b"$-1\r\n"
        b = s.encode("utf-8")
        return b"$%d\r\n%s\r\n" % (len(b), b)

    @staticmethod
    def _int(i: int) -> bytes:
        return f":{i}\r\n".encode()

    @classmethod
    def _array(cls, items: list) -> bytes:
        out = [b"*%d\r\n" % len(items)]
        for it in items:
            if isinstance(it, int):
                out.append(cls._int(it))
            else:
                out.append(cls._bulk(it))
        return b"".join(out)

    def _get(self, key: str) -> str | None:
        entry = self.kv.get(key)
        if entry is None:
            return None
        val, expiry = entry
        if expiry is not None and time.monotonic() > expiry:
            del self.kv[key]
            return None
        return val

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                args = await self._read_command(reader)
                if args is None:
                    return
                cmd = args[0].upper()
                if cmd in ("AUTH", "SELECT"):
                    writer.write(self._simple("OK"))
                elif cmd == "SET":
                    expiry = None
                    if len(args) >= 5 and args[3].upper() == "EX":
                        expiry = time.monotonic() + float(args[4])
                    self.kv[args[1]] = (args[2], expiry)
                    writer.write(self._simple("OK"))
                elif cmd == "GET":
                    writer.write(self._bulk(self._get(args[1])))
                elif cmd == "DEL":
                    existed = int(args[1] in self.kv)
                    self.kv.pop(args[1], None)
                    writer.write(self._int(existed))
                elif cmd == "PUBLISH":
                    channel, message = args[1], args[2]
                    receivers = self.subscribers.get(channel, [])
                    for w in list(receivers):
                        try:
                            w.write(self._array(["message", channel, message]))
                            await w.drain()
                        except (ConnectionError, OSError):
                            receivers.remove(w)
                    writer.write(self._int(len(receivers)))
                elif cmd == "SUBSCRIBE":
                    self.subscribers[args[1]].append(writer)
                    writer.write(self._array(["subscribe", args[1], 1]))
                elif cmd == "LPUSH":
                    self.lists[args[1]].appendleft(args[2])
                    writer.write(self._int(len(self.lists[args[1]])))
                elif cmd == "LLEN":
                    writer.write(self._int(len(self.lists.get(args[1], ()))))
                elif cmd == "BRPOP":
                    key, timeout = args[1], float(args[2])
                    deadline = time.monotonic() + (timeout or 1e9)
                    popped = None
                    while time.monotonic() < deadline:
                        if self.lists.get(key):
                            popped = self.lists[key].pop()
                            break
                        await asyncio.sleep(0.01)
                    writer.write(self._array([key, popped]) if popped is not None else b"*-1\r\n")
                else:
                    writer.write(f"-ERR unknown command '{cmd}'\r\n".encode())
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass
        finally:
            for subs in self.subscribers.values():
                if writer in subs:
                    subs.remove(writer)
            writer.close()
