"""CassandraVectorStore over the REAL wire: the in-tree CQL v4 client
(store/cql.py) against minicassandra, a TCP server speaking the native
protocol — STARTUP/auth handshake, DDL, PREPARE/EXECUTE binary binding,
ANN search with cosine scoring, filters, gets, counts, deletes.

Closes VERDICT r02 missing #3: the r02 wire path was validated against a
fake *session object*; here every byte crosses a socket in the same
framing a Cassandra 5 node expects (reference counterpart:
ingest/src/app/services/cassandra_service.py:93-197).
"""

from __future__ import annotations

import numpy as np
import pytest

from githubrepostorag_tpu.store.base import Doc
from githubrepostorag_tpu.store.cassandra import CassandraVectorStore
from githubrepostorag_tpu.store.cql import CQLError, CQLSession

from tests.minicassandra import MiniCassandra

DIM = 8


@pytest.fixture()
def server():
    srv = MiniCassandra()
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture()
def store(server):
    return CassandraVectorStore(
        hosts=["127.0.0.1"], port=server.port, keyspace="ks", embed_dim=DIM
    )


def _vec(seed: int) -> np.ndarray:
    return np.random.default_rng(seed).normal(size=DIM).astype(np.float32)


def _docs(n: int, **meta) -> list[Doc]:
    return [
        Doc(f"doc-{i}", f"body {i}", {"kind": "chunk", **meta}, _vec(i))
        for i in range(n)
    ]


def test_auth_handshake_and_health(server, store):
    assert store.health()["status"] == "UP"
    # the server demanded PasswordAuthenticator and the client satisfied it
    assert any(q.startswith("CREATE KEYSPACE") for q in server.queries)


def test_bad_credentials_rejected(server):
    with pytest.raises(CQLError, match="Bad credentials"):
        CQLSession("127.0.0.1", server.port, username="x", password="nope")


def test_upsert_is_prepared_and_idempotent(server, store):
    docs = _docs(3)
    assert store.upsert("chunks", docs) == 3
    assert store.upsert("chunks", docs) == 3  # keyed by row_id
    assert store.count("chunks") == 3
    assert any(q.startswith("PREPARE INSERT INTO ks.chunks") for q in server.queries)
    # prepared statement reused: exactly one PREPARE for six row writes
    assert sum(q.startswith("PREPARE") for q in server.queries) == 1


def test_vector_roundtrip_exact(store):
    """The VECTOR<FLOAT, n> custom marshal survives the wire bit-exact in
    both directions (EXECUTE bind -> storage -> rows decode)."""
    v = _vec(42)
    store.upsert("chunks", [Doc("d", "t", {}, v)])
    got = store.get("chunks", "d")
    np.testing.assert_array_equal(got.vector, v)


def test_ann_search_orders_by_cosine(store):
    store.upsert("chunks", _docs(8))
    q = _vec(3)  # identical to doc-3's vector -> top hit, score 1.0
    hits = store.search("chunks", q, k=3)
    assert [h.doc.doc_id for h in hits][0] == "doc-3"
    assert hits[0].score == pytest.approx(1.0, abs=1e-5)
    assert len(hits) == 3
    assert hits[0].score >= hits[1].score >= hits[2].score


def test_search_with_metadata_filter(store):
    store.upsert("chunks", _docs(4, repo="a"))
    store.upsert("chunks", [Doc("other", "x", {"kind": "chunk", "repo": "b"}, _vec(9))])
    hits = store.search("chunks", _vec(9), k=10, filter={"repo": "b"})
    assert [h.doc.doc_id for h in hits] == ["other"]


def test_find_by_metadata_and_entries_fallback(store):
    """Shredded keys get the entry form first ('topics:kafka'='1'); rows
    written before shredding match the plain-equality second variant."""
    store.upsert("files", [Doc("f1", "x", {"topics": "kafka"}, _vec(1))])
    docs = store.find_by_metadata("files", {"topics": "kafka"})
    assert [d.doc_id for d in docs] == ["f1"]


def test_get_missing_returns_none(store):
    store.upsert("chunks", _docs(1))
    assert store.get("chunks", "nope") is None
    assert store.get("chunks", "doc-0").text == "body 0"


def test_delete_returns_rows_actually_removed(store):
    store.upsert("chunks", _docs(2))
    assert store.delete("chunks", ["doc-0", "ghost"]) == 1
    assert store.count("chunks") == 1


def test_tables_lists_created_tables(store):
    store.upsert("chunks", _docs(1))
    store.upsert("files", _docs(1))
    assert store.tables() == ["chunks", "files"]


def test_quote_escaping_survives_the_wire(store):
    """Single quotes in ids/metadata must round-trip through both the
    client-side literal interpolation (simple SELECT/DELETE) and the
    binary EXECUTE path (INSERT)."""
    tricky = "it's a 'quoted' id"
    store.upsert("chunks", [Doc(tricky, "o'body", {"k": "v'al"}, _vec(5))])
    got = store.get("chunks", tricky)
    assert got is not None and got.text == "o'body" and got.metadata["k"] == "v'al"
    assert store.delete("chunks", [tricky]) == 1


def test_reconnect_after_connection_drop(store):
    """A dead TCP connection must not brick the store: the session
    reconnects (full STARTUP/auth handshake) and replays the request —
    the DataStax driver behavior a long-lived serving pod relies on."""
    store.upsert("chunks", _docs(1))
    store._session._sock.close()  # simulate server restart / LB reap
    assert store.count("chunks") == 1  # simple statement path reconnects
    store._session._sock.close()
    assert store.upsert("chunks", _docs(2)) == 2  # prepared EXECUTE path too
    assert store.health()["status"] == "UP"


class _KillableCassandra(MiniCassandra):
    """MiniCassandra that dies mid-exchange: when a QUERY containing
    ``kill_on`` arrives it records the query, then closes the connection
    WITHOUT replying — the client is left waiting on a half-done exchange,
    exactly what a node crash between request and response looks like."""

    def __init__(self) -> None:
        super().__init__()
        self.kill_on: str | None = None

    def _run(self, cql: str):
        if self.kill_on and self.kill_on in cql:
            self.kill_on = None  # one-shot: the replayed request succeeds
            raise ConnectionError("server killed mid-exchange")
        return super()._run(cql)


@pytest.fixture()
def killable():
    srv = _KillableCassandra()
    srv.start()
    yield srv
    srv.stop()


def test_idempotent_request_is_replayed_after_mid_exchange_death(killable):
    """The server reads the full request then dies before replying — an
    ambiguous failure.  Idempotent statements (everything this store
    issues) reconnect and replay transparently: the server must see the
    statement TWICE and the caller sees one clean result."""
    sess = CQLSession("127.0.0.1", killable.port)
    killable.kill_on = "release_version"
    rs = sess.execute("SELECT release_version FROM system.local")
    assert rs.one().release_version == "5.0-mini"
    seen = [q for q in killable.queries if "release_version" in q]
    assert len(seen) == 2  # original attempt + the replay


def test_non_idempotent_request_is_not_replayed(killable):
    """idempotent=False gates the replay: after the ambiguous failure the
    error propagates (the statement may have applied server-side), the
    server saw it exactly once, and the reconnected session stays usable."""
    sess = CQLSession("127.0.0.1", killable.port)
    killable.kill_on = "USE ks_counter"
    with pytest.raises((CQLError, OSError)):
        sess.execute("USE ks_counter", idempotent=False)
    seen = [q for q in killable.queries if "ks_counter" in q]
    assert len(seen) == 1  # never replayed
    # the session already reconnected: next statement works first try
    rs = sess.execute("SELECT release_version FROM system.local")
    assert rs.one().release_version == "5.0-mini"


def test_injected_cql_fault_exercises_the_replay_path(killable, monkeypatch):
    """The cql.exchange fault seam rides the same reconnect/replay branches
    as a real dead socket: with every exchange erroring once per 2 calls,
    idempotent traffic still completes."""
    from githubrepostorag_tpu.config import reload_settings
    from githubrepostorag_tpu.resilience.faults import get_registry, reset_faults

    sess = CQLSession("127.0.0.1", killable.port)  # handshake pre-faults
    monkeypatch.setenv("FAULTS", "cql.exchange:error@2")
    reload_settings()
    reset_faults()
    for _ in range(4):  # calls 2, 4, ... fault then replay
        rs = sess.execute("SELECT release_version FROM system.local")
        assert rs.one().release_version == "5.0-mini"
    stats = get_registry().stats()
    assert sum(e["fired"] for e in stats["cql.exchange"]) >= 2


def test_unicode_text_roundtrip(store):
    store.upsert("chunks", [Doc("u", "héllo 世界 🚀", {"λ": "µ"}, _vec(6))])
    got = store.get("chunks", "u")
    assert got.text == "héllo 世界 🚀"
    assert got.metadata == {"λ": "µ"}


def test_interpolate_is_quote_aware():
    """%s inside a '...' CQL string literal is NOT a placeholder, literal
    % never raises, '' stays inside the literal, and placeholder/param
    count mismatches raise instead of corrupting the statement."""
    import numpy as np
    import pytest

    from githubrepostorag_tpu.store.cql import cql_literal, interpolate

    assert (
        interpolate("UPDATE t SET note = '50%savings' WHERE row_id = %s", ("r1",))
        == "UPDATE t SET note = '50%savings' WHERE row_id = 'r1'"
    )
    assert (
        interpolate("x = %s AND y = '%s it''s %s' AND z = %s", (1, 2))
        == "x = 1 AND y = '%s it''s %s' AND z = 2"
    )
    with pytest.raises(ValueError):
        interpolate("SELECT * FROM t WHERE v LIKE '%sql%'", ("extra",))
    with pytest.raises(ValueError):
        interpolate("a %s %s", ("one",))
    with pytest.raises(ValueError):  # empty params must not skip validation
        interpolate("DELETE FROM t WHERE row_id = %s", [])
    assert interpolate("SELECT * FROM t", None) == "SELECT * FROM t"
    # numpy scalars render as plain CQL numbers (numpy-2.x repr is not CQL)
    assert cql_literal(np.float64(1.5)) == "1.5"
    assert cql_literal([np.float64(1.5), np.int32(2)]) == "[1.5, 2]"
    assert cql_literal(["a", "b'c"]) == "['a', 'b''c']"
