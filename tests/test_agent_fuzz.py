"""Agent-loop fuzz against HOSTILE LLM outputs (VERDICT r04 next #6).

The reference's JSON-robustness fallbacks are load-bearing for answer
quality (agent_graph.py:226-228,346-355 parse-fail stage-down; SURVEY §7
"hardest parts" #5).  test_agent.py proves each fallback branch in
isolation; this file drives hundreds of randomized FULL ``GraphAgent.run``
calls where every LLM call returns adversarial text — malformed JSON,
truncated JSON, wrong types, unknown/pluralized/hostile filter keys,
up-the-ladder scope suggestions, empty strings, ``Error:`` strings, think
tags, control bytes — and asserts the run-level invariants:

  1. every run terminates with an AgentResult (bounded by max_iters);
  2. the answer is always a string and sources are well-formed dicts;
  3. filters never gain keys outside the canonical metadata vocabulary
     (an unknown key would zero every later retrieval);
  4. the retrieval scope only ever moves DOWN the ladder.
"""

from __future__ import annotations

import random

from githubrepostorag_tpu.agent import GraphAgent
from githubrepostorag_tpu.agent.graph import SYNTH_MAX_BLOCKS
from githubrepostorag_tpu.config import get_settings
from githubrepostorag_tpu.embedding import HashingTextEncoder
from githubrepostorag_tpu.retrieval import RetrieverFactory
from githubrepostorag_tpu.retrieval.retrievers import SCOPE_LADDER
from githubrepostorag_tpu.store import Doc, MemoryVectorStore

CANONICAL_FILTER_KEYS = {"namespace", "repo", "module", "file_path", "topics", "scope"}

# Adversarial completions: every shape of LLM misbehavior the reference's
# fallbacks exist for, plus a few it never considered.
HOSTILE_OUTPUTS = [
    "",
    "   \n\t  ",
    "not json at all, just prose about the question",
    '{"scope": "galaxy", "filters": {"planet": "mars"}}',  # unknown scope+key
    '{"scope": "catalog"',  # truncated mid-object
    '{"coverage": "very high", "needs_more": "yes please"}',  # wrong types
    '{"stage_down": "catalog"}',  # UP the ladder — must be refused
    '{"suggest_filters": {"repos": ["r1", "r2"], "unknown_key": "x", "topicss": 3}}',
    "[1, 2, 3]",
    '"just a quoted string"',
    "null",
    "Error: model overloaded, please retry",  # errors-as-text contract
    '{"coverage": 0.9, "needs_more": false} trailing garbage after the JSON',
    '<think>let me think about this...</think>{"coverage": 0.1, "needs_more": true}',
    '{"coverage": NaN, "needs_more": true}',
    "\x00\x01 binary junk \x7f",
    '{"rewrite": 42, "needs_more": true}',  # rewrite wrong type
    "{}",
    '{"scope": "chunk", "filters": {"repo": null, "module": ["m1"], "file_path": {}}}',
    '{"coverage": -7.5, "needs_more": true, "stage_down": "file"}',
    "ok",  # too short for a rewrite
    '{"needs_more": true, "rewrite": ""}',
    '```json\n{"coverage": 0.5, "needs_more": true}\n```',  # fenced
    '{"suggest_filters": {"scope": "delete everything", "namespace": "evil"}}',
]


class HostileLLM:
    """Returns a seeded-random hostile completion for EVERY call."""

    def __init__(self, seed: int) -> None:
        self.rng = random.Random(seed)
        self.calls = 0

    def complete(self, prompt, *, system=None, max_tokens=None, temperature=None) -> str:
        self.calls += 1
        out = self.rng.choice(HOSTILE_OUTPUTS)
        if self.rng.random() < 0.2:  # random truncation of whatever it was
            out = out[: self.rng.randint(0, max(len(out) - 1, 0))]
        return out

    def stream_complete(self, prompt, *, system=None, max_tokens=None,
                        temperature=None, on_text=None):
        text = self.complete(prompt)
        for piece in (text[i:i + 7] for i in range(0, len(text), 7)) if text else [""]:
            if on_text:
                on_text(piece)
            yield piece


def _populated_factory() -> RetrieverFactory:
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    tables = get_settings().scope_tables
    fixtures = {
        "catalog": [("cat1", "catalog of repositories in namespace default", {})],
        "repo": [("r1", "repo one: a message broker in java", {"repo": "broker"}),
                 ("r2", "repo two: cassandra client library", {"repo": "cassclient"})],
        "module": [("m1", "module consumer handles message consumption",
                    {"repo": "broker", "module": "consumer"})],
        "file": [("f1", "file Consumer.java implements the consumer loop",
                  {"repo": "broker", "module": "consumer", "file_path": "Consumer.java"})],
        "chunk": [("c1", "class Consumer { void poll() { /* reconnect retry */ } }",
                   {"repo": "broker", "module": "consumer", "file_path": "Consumer.java"}),
                  ("c2", "def reconnect(): backoff and retry the session",
                   {"repo": "cassclient", "module": "net", "file_path": "net/session.py"}),
                  ("c3", "cache configuration yaml for the api tier",
                   {"repo": "broker", "module": "config", "file_path": "config/cache.yaml"})],
    }
    for scope, rows in fixtures.items():
        store.upsert(tables[scope], [
            Doc(d, t, {"namespace": "default", "scope": scope, **m}, enc.encode([t])[0])
            for d, t, m in rows
        ])
    return RetrieverFactory(store, enc)


QUERIES = [
    "how does the consumer reconnect after a timeout exception?",  # codey
    "tell me about the projects in this workspace",  # overview
    "repo: broker how is caching configured",  # repo hint
    "what is in repository cassclient",
    "",  # empty query
    "x" * 500,  # absurdly long query
]


def _ladder_idx(scope: str) -> int:
    return SCOPE_LADDER.index(scope) if scope in SCOPE_LADDER else -1


def test_agent_fuzz_hostile_llm_full_runs():
    factory = _populated_factory()
    empty_factory = RetrieverFactory(MemoryVectorStore(), HashingTextEncoder())
    rng = random.Random(0xC0FFEE)

    for trial in range(250):
        llm = HostileLLM(seed=trial)
        agent = GraphAgent(
            llm,
            factory if rng.random() < 0.8 else empty_factory,
            max_iters=rng.choice([1, 2, 3, 4]),
            namespace="default" if rng.random() < 0.7 else None,
        )
        force = rng.choice([None, None, "bogus_level", *SCOPE_LADDER])
        tokens: list[str] = []
        result = agent.run(
            rng.choice(QUERIES),
            force_level=force,
            top_k=rng.choice([None, 1, 3, 50, -2]),
            token_cb=tokens.append if rng.random() < 0.5 else None,
        )

        # 1. terminated with a well-formed result
        assert isinstance(result.answer, str)
        assert isinstance(result.sources, list)
        assert len(result.sources) <= SYNTH_MAX_BLOCKS
        for s in result.sources:
            assert {"id", "doc_id", "repo", "module", "file_path",
                    "scope", "score", "text"} <= set(s)

        turns = result.debug.get("turns", [])
        judges = [t for t in turns if t["stage"] == "judge"]
        assert len(judges) <= agent.max_iters + 1

        # 3. filters never gain non-canonical keys (hostile suggest_filters)
        for t in turns:
            for key in t.get("filters", {}):
                assert key in CANONICAL_FILTER_KEYS, (trial, key, t)

        # 4. scope only ever moves down the ladder (ignore the synthesize
        # last-resort chunk probe, which doesn't change the run's scope)
        scopes = [t["scope"] for t in turns
                  if t["stage"] in ("plan", "retrieve") and not t.get("last_resort")]
        assert scopes, turns
        assert all(s in SCOPE_LADDER for s in scopes)
        idxs = [_ladder_idx(s) for s in scopes]
        assert idxs == sorted(idxs), (trial, scopes)


def test_agent_fuzz_cancellation_still_clean():
    """should_stop firing at a random stage raises RunCancelled (never a
    stuck loop, never a partial-state crash)."""
    import pytest

    from githubrepostorag_tpu.agent import RunCancelled

    factory = _populated_factory()
    for trial in range(30):
        # a single-iteration run probes should_stop exactly 5 times (before
        # plan, retrieve, judge, rewrite, synthesize) — fire within that
        fire_after = trial % 5
        calls = {"n": 0}

        def should_stop() -> bool:
            calls["n"] += 1
            return calls["n"] > fire_after

        agent = GraphAgent(HostileLLM(seed=trial), factory, max_iters=3)
        with pytest.raises(RunCancelled):
            agent.run("how does the consumer reconnect?", should_stop=should_stop)
