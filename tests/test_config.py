"""Config: reference env-var names resolve into the unified Settings."""

from githubrepostorag_tpu.config import Settings, get_settings, reload_settings


def test_defaults_match_reference():
    s = Settings()
    assert s.max_rag_attempts == 3
    assert s.min_source_nodes == 1
    assert s.router_top_k == 5
    assert s.embed_dim == 384
    assert s.qwen_max_output == 4096
    assert s.sse_ping_seconds == 15
    assert s.context_window == 11712
    assert s.embeddings_table_chunk == "embeddings"
    assert s.embeddings_table_catalog == "embeddings_catalog"
    assert s.prefill_token_budget == 0  # default: padded prefill dispatch


def test_env_overrides(monkeypatch):
    monkeypatch.setenv("MAX_RAG_ATTEMPTS", "7")
    monkeypatch.setenv("EMBEDDINGS_TABLE", "alt_embeddings")
    monkeypatch.setenv("DEV_MODE", "true")
    monkeypatch.setenv("PREFILL_WIDTHS", "2")
    monkeypatch.setenv("PREFILL_TOKEN_BUDGET", "2048")
    s = reload_settings()
    assert s.max_rag_attempts == 7
    assert s.embeddings_table_chunk == "alt_embeddings"
    assert s.dev_force_standalone is True
    assert s.prefill_widths == 2
    assert s.prefill_token_budget == 2048


def test_scope_tables_cover_all_five_levels():
    tables = get_settings().scope_tables
    assert set(tables) == {"catalog", "repo", "module", "file", "chunk"}


def test_bad_env_int_falls_back(monkeypatch):
    monkeypatch.setenv("ROUTER_TOP_K", "not-a-number")
    s = reload_settings()
    assert s.router_top_k == 5


def test_quantize_weights_values(monkeypatch):
    from githubrepostorag_tpu.config import reload_settings

    for raw, want in [("int4", 4), ("int8", 8), ("true", 8), ("4", 4),
                      ("", 0), ("false", 0)]:
        monkeypatch.setenv("QUANTIZE_WEIGHTS", raw)
        assert reload_settings().quantize_weights == want, raw


def test_quantize_weights_typo_raises(monkeypatch):
    import pytest

    from githubrepostorag_tpu.config import reload_settings

    monkeypatch.setenv("QUANTIZE_WEIGHTS", "in8")
    with pytest.raises(ValueError, match="QUANTIZE_WEIGHTS"):
        reload_settings()
    monkeypatch.setenv("QUANTIZE_WEIGHTS", "int8")
    reload_settings()


def test_moe_capacity_factor_env(monkeypatch):
    from githubrepostorag_tpu.config import reload_settings

    monkeypatch.setenv("MOE_CAPACITY_FACTOR", "1.25")
    assert reload_settings().moe_capacity_factor == 1.25
    monkeypatch.delenv("MOE_CAPACITY_FACTOR")
    assert reload_settings().moe_capacity_factor == 2.0
