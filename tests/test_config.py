"""Config: reference env-var names resolve into the unified Settings."""

from githubrepostorag_tpu.config import Settings, get_settings, reload_settings


def test_defaults_match_reference():
    s = Settings()
    assert s.max_rag_attempts == 3
    assert s.min_source_nodes == 1
    assert s.router_top_k == 5
    assert s.embed_dim == 384
    assert s.qwen_max_output == 4096
    assert s.sse_ping_seconds == 15
    assert s.context_window == 11712
    assert s.embeddings_table_chunk == "embeddings"
    assert s.embeddings_table_catalog == "embeddings_catalog"


def test_env_overrides(monkeypatch):
    monkeypatch.setenv("MAX_RAG_ATTEMPTS", "7")
    monkeypatch.setenv("EMBEDDINGS_TABLE", "alt_embeddings")
    monkeypatch.setenv("DEV_MODE", "true")
    s = reload_settings()
    assert s.max_rag_attempts == 7
    assert s.embeddings_table_chunk == "alt_embeddings"
    assert s.dev_force_standalone is True


def test_scope_tables_cover_all_five_levels():
    tables = get_settings().scope_tables
    assert set(tables) == {"catalog", "repo", "module", "file", "chunk"}


def test_bad_env_int_falls_back(monkeypatch):
    monkeypatch.setenv("ROUTER_TOP_K", "not-a-number")
    s = reload_settings()
    assert s.router_top_k == 5
