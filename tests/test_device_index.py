"""Device-resident retrieval index: exact parity with the numpy store,
warmup/bucket compile contract, coalesced waves, and fallback accounting.

The parity bar is the ISSUE-3 acceptance criterion: on randomized corpora
the device index must return IDENTICAL top-k ids to MemoryVectorStore
(scores within fp32 tolerance), including metadata filters (shredded
keys), empty tables, k > corpus size, deletions, and re-upserts — and the
jitted search-program count must not move under live traffic after
``warmup()`` (the PR-2 ``_cache_size`` house style).
"""

import threading

import numpy as np
import pytest

from githubrepostorag_tpu.embedding import HashingTextEncoder
from githubrepostorag_tpu.metrics import DEVICE_INDEX_SEARCHES
from githubrepostorag_tpu.parallel import MeshPlan, make_mesh
from githubrepostorag_tpu.retrieval import (
    DeviceIndexedStore,
    RetrievalCoalescer,
    RetrieverFactory,
)
from githubrepostorag_tpu.store.base import Doc
from githubrepostorag_tpu.store.memory import MemoryVectorStore
from tests.helpers.compile_guard import compile_guard

DIM = 24


def _mk_docs(rng, n, dim=DIM, vectorless_every=0):
    docs = []
    for i in range(n):
        vec = None
        if not vectorless_every or (i % vectorless_every):
            vec = rng.normal(size=dim).astype(np.float32)
        meta = {
            "namespace": "default",
            "repo": f"repo{i % 3}",
            "module": f"mod{i % 5}",
            "topics": f"t{i % 2}",
            f"topics:t{i % 2}": "1",  # shredded entry, as ingest writes it
        }
        docs.append(Doc(f"d{i:04d}", f"text {i}", meta, vec))
    return docs


def _ids(hits):
    return [h.doc.doc_id for h in hits]


def _scores(hits):
    return [h.score for h in hits]


def _assert_parity(inner, dev, table, queries, ks, filters):
    for q in queries:
        for k in ks:
            for flt in filters:
                host = inner.search(table, q, k, filter=flt)
                devh = dev.search(table, q, k, filter=flt)
                assert _ids(host) == _ids(devh), (k, flt)
                assert np.allclose(_scores(host), _scores(devh), atol=1e-5)


@pytest.mark.parametrize("n_docs", [1, 7, 50, 130])
def test_randomized_corpus_parity(n_docs):
    rng = np.random.default_rng(n_docs)
    inner = MemoryVectorStore()
    inner.upsert("t", _mk_docs(rng, n_docs, vectorless_every=9))
    dev = DeviceIndexedStore(inner, k_bucket=16, max_wave=8)
    queries = [rng.normal(size=DIM).astype(np.float32) for _ in range(4)]
    queries.append(np.zeros(DIM, dtype=np.float32))  # zero-norm -> no hits
    _assert_parity(
        inner, dev, "t", queries, ks=[1, 3, 16],
        filters=[None, {"repo": "repo1"}, {"topics": "t0"},
                 {"repo": "repo0", "topics": "t1"}, {"repo": "nope"}],
    )


def test_parity_k_exceeds_corpus_and_k_bucket():
    rng = np.random.default_rng(3)
    inner = MemoryVectorStore()
    inner.upsert("t", _mk_docs(rng, 10))
    dev = DeviceIndexedStore(inner, k_bucket=8)
    q = rng.normal(size=DIM).astype(np.float32)
    # k > corpus within the bucket: every row comes back, same order
    assert _ids(dev.search("t", q, 8)) == _ids(inner.search("t", q, 8))
    # k > k_bucket: host fallback, still exact parity and counted
    before = DEVICE_INDEX_SEARCHES.labels(path="fallback")._value.get()
    assert _ids(dev.search("t", q, 50)) == _ids(inner.search("t", q, 50))
    assert DEVICE_INDEX_SEARCHES.labels(path="fallback")._value.get() == before + 1


def test_empty_and_unknown_tables():
    inner = MemoryVectorStore()
    dev = DeviceIndexedStore(inner)
    q = np.ones(DIM, dtype=np.float32)
    assert dev.search("missing", q, 5) == []
    inner.upsert("t", [Doc("v", "no vector yet", {"repo": "r"}, None)])
    dev2 = DeviceIndexedStore(inner)
    assert dev2.search("t", q, 5) == inner.search("t", q, 5) == []


def test_tie_order_matches_host_canonical_order():
    """Duplicate vectors: both paths order ties by insertion row — the
    memory store's stable (-score, row) partial sort and lax.top_k's
    lower-index preference agree."""
    rng = np.random.default_rng(7)
    inner = MemoryVectorStore()
    v = rng.normal(size=DIM).astype(np.float32)
    docs = [Doc(f"tie{i}", "same", {}, v.copy()) for i in range(6)]
    docs += _mk_docs(rng, 5)
    inner.upsert("t", docs)
    dev = DeviceIndexedStore(inner)
    expect = [f"tie{i}" for i in range(4)]
    assert _ids(inner.search("t", v, 4)) == expect
    assert _ids(dev.search("t", v, 4)) == expect


def test_incremental_upsert_delete_reupsert_parity():
    rng = np.random.default_rng(11)
    inner = MemoryVectorStore()
    dev = DeviceIndexedStore(inner, min_capacity=4)
    q = rng.normal(size=DIM).astype(np.float32)
    # grow one doc at a time across several capacity buckets
    for i, doc in enumerate(_mk_docs(rng, 40)):
        dev.upsert("t", [doc])
        if i % 13 == 0:
            assert _ids(dev.search("t", q, 10)) == _ids(inner.search("t", q, 10))
    dev.delete("t", ["d0003", "d0010"])
    assert _ids(dev.search("t", q, 10)) == _ids(inner.search("t", q, 10))
    # re-upsert an existing id with a new vector: same row, same tie order
    dev.upsert("t", [Doc("d0005", "updated", {"repo": "repo9"}, q.copy())])
    host, devh = inner.search("t", q, 5), dev.search("t", q, 5)
    assert _ids(host) == _ids(devh) and _ids(devh)[0] == "d0005"
    # metadata filter now matches the updated row
    assert _ids(dev.search("t", q, 5, filter={"repo": "repo9"})) == ["d0005"]


def test_wraps_preexisting_inner_rows():
    """Wrapping a store that already holds rows (persistence reload) seeds
    the mirror from the inner store."""
    rng = np.random.default_rng(13)
    inner = MemoryVectorStore()
    inner.upsert("t", _mk_docs(rng, 20))
    dev = DeviceIndexedStore(inner)
    q = rng.normal(size=DIM).astype(np.float32)
    assert _ids(dev.search("t", q, 6)) == _ids(inner.search("t", q, 6))


@pytest.mark.parametrize("plan", [MeshPlan(dp=8), MeshPlan(dp=2)])
def test_sharded_parity_over_dp_mesh(plan):
    """The dp-sharded program (local top-k -> all-gather -> merge) returns
    the same ids/scores/tie-order as the host store on the virtual mesh."""
    rng = np.random.default_rng(17)
    inner = MemoryVectorStore()
    docs = _mk_docs(rng, 60)
    v = rng.normal(size=DIM).astype(np.float32)
    docs += [Doc(f"tie{i}", "same", {}, v.copy()) for i in range(5)]
    inner.upsert("t", docs)
    dev = DeviceIndexedStore(inner, mesh=make_mesh(plan), k_bucket=16)
    queries = [rng.normal(size=DIM).astype(np.float32) for _ in range(3)] + [v]
    _assert_parity(inner, dev, "t", queries, ks=[1, 5, 16],
                   filters=[None, {"repo": "repo2"}])


def test_warmup_compiles_exact_bucket_set_and_traffic_adds_zero():
    """House style from PR 2: warmup's compile count is exactly the bucket
    set (query buckets 1..max_wave for the one capacity bucket), and mixed
    live traffic afterwards adds ZERO programs."""
    rng = np.random.default_rng(19)
    inner = MemoryVectorStore()
    inner.upsert("t", _mk_docs(rng, 50))
    dev = DeviceIndexedStore(inner, k_bucket=16, max_wave=16)
    assert dev.search_program_cache_size() == 0
    # query buckets 1, 2, 4, 8, 16 x one capacity bucket
    with compile_guard(dev.search_program_cache_size, expect=5,
                       label="device-index warmup"):
        dev.warmup()
    with compile_guard(dev.search_program_cache_size,
                       label="mixed search traffic"):
        # live traffic: every query count 1..16, filters on and off, k varied
        for nq in range(1, 17):
            qs = rng.normal(size=(nq, DIM)).astype(np.float32)
            dev.search_batch("t", qs, 1 + nq % 16)
            dev.search_batch("t", qs, 4, [{"repo": "repo1"}] * nq)
        # upserts that stay inside the capacity bucket also add zero programs
        dev.upsert("t", [Doc("late", "late doc", {}, rng.normal(size=DIM).astype(np.float32))])
        dev.search("t", rng.normal(size=DIM).astype(np.float32), 3)


def test_delete_reupsert_churn_reuses_holes_without_growing():
    """PR-13 hole reuse: at capacity, delete->re-upsert churn compacts
    tombstoned holes in place instead of growing the bucket — capacity
    pins, full_syncs stays put, zero new programs (the repack gather and
    the dirty-row scatter are both warmed), and score/tie-order parity
    holds through every row remap."""
    rng = np.random.default_rng(29)
    inner = MemoryVectorStore()
    dev = DeviceIndexedStore(inner, k_bucket=16, max_wave=8)
    dev.upsert("t", _mk_docs(rng, 50))
    dev.warmup()
    h0 = dev.health()["device_index"]["t"]
    assert h0["capacity"] == 64
    with compile_guard(dev.search_program_cache_size, label="churn search"), \
         compile_guard(dev.mutation_program_cache_size,
                       label="churn mutation"):
        for cycle in range(40):
            did = f"d{int(rng.integers(50)):04d}"
            dev.delete("t", [did])
            dev.upsert("t", [Doc(did, f"cycle {cycle}", {"repo": "repo0"},
                                 rng.normal(size=DIM).astype(np.float32))])
            if cycle % 7 == 0:
                q = rng.normal(size=DIM).astype(np.float32)
                host, devh = inner.search("t", q, 10), dev.search("t", q, 10)
                assert _ids(host) == _ids(devh)
                assert np.allclose(_scores(host), _scores(devh), atol=1e-5)
    h1 = dev.health()["device_index"]["t"]
    assert h1["capacity"] == 64          # holes reused, bucket never grew
    assert h1["compactions"] > 0
    assert h1["full_syncs"] == h0["full_syncs"]  # no whole-table re-put
    # operator-facing compact() drains the remaining holes completely
    dev.compact("t")
    assert dev.health()["device_index"]["t"]["holes"] == 0
    # ties still break by insertion order after rows were remapped
    v = rng.normal(size=DIM).astype(np.float32)
    dev.upsert("t", [Doc(f"tie{i}", "same", {}, v.copy()) for i in range(3)])
    expect = ["tie0", "tie1", "tie2"]
    assert _ids(inner.search("t", v, 3)) == expect
    assert _ids(dev.search("t", v, 3)) == expect


def test_device_path_counted():
    rng = np.random.default_rng(23)
    inner = MemoryVectorStore()
    inner.upsert("t", _mk_docs(rng, 10))
    dev = DeviceIndexedStore(inner)
    before = DEVICE_INDEX_SEARCHES.labels(path="device")._value.get()
    dev.search_batch("t", rng.normal(size=(3, DIM)).astype(np.float32), 2)
    assert DEVICE_INDEX_SEARCHES.labels(path="device")._value.get() == before + 3


# --------------------------------------------------------------- coalescer


def _seed_corpus(store, enc, n=24):
    texts = [f"alpha beta {i} gamma delta" for i in range(n)]
    store.upsert("embeddings", [
        Doc(f"c{i}", t, {"namespace": "default", "file_path": f"f{i % 4}",
                         "module": f"m{i % 2}"},
            enc.encode([t])[0])
        for i, t in enumerate(texts)
    ])


def test_coalescer_matches_direct_path_under_concurrency():
    enc = HashingTextEncoder(dim=64)
    store = MemoryVectorStore()
    _seed_corpus(store, enc)
    co = RetrievalCoalescer(store, enc, max_wave=8)
    results = {}

    def caller(i):
        _, hits = co.search_text("embeddings", f"alpha beta {i}", 3)
        results[i] = _ids(hits)

    threads = [threading.Thread(target=caller, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(16):
        direct = store.search(
            "embeddings", enc.encode([f"alpha beta {i}"], kind="query")[0], 3)
        assert results[i] == _ids(direct)


def test_coalescer_propagates_errors_and_recovers():
    class Boom:
        dim = 8

        def __init__(self):
            self.fail = True

        def encode(self, texts, kind="passage"):
            if self.fail:
                raise RuntimeError("encoder down")
            return np.ones((len(texts), 8), dtype=np.float32)

    enc = Boom()
    store = MemoryVectorStore()
    co = RetrievalCoalescer(store, enc, max_wave=4)
    with pytest.raises(RuntimeError, match="encoder down"):
        co.search_text("embeddings", "q", 3)
    enc.fail = False  # the drain thread must survive a failed wave
    qvec, hits = co.search_text("embeddings", "q", 3)
    assert hits == [] and qvec.shape == (8,)


def test_retrieve_many_equals_sequential_retrieve():
    """Batched fan-out must not change results: retrieve_many over a set of
    queries returns exactly what per-query retrieve() returns."""
    enc = HashingTextEncoder(dim=64)
    store = MemoryVectorStore()
    _seed_corpus(store, enc)
    direct = RetrieverFactory(store, enc, coalescer=False)
    assert direct.coalescer is None
    coalesced = RetrieverFactory(store, enc)
    assert coalesced.coalescer is not None
    queries = [f"alpha beta {i}" for i in (1, 5, 9)]
    flt = {"namespace": "default"}
    for scope in ("chunk", "file"):
        seq = [direct.for_scope(scope).retrieve(q, flt) for q in queries]
        # rebuild retriever so the per-call edge cache starts cold
        batched = coalesced.for_scope(scope).retrieve_many(queries, flt)
        for a, b in zip(seq, batched):
            assert [d.doc_id for d in a] == [d.doc_id for d in b]
            assert [d.depth for d in a] == [d.depth for d in b]
            np.testing.assert_allclose(
                [d.score for d in a], [d.score for d in b], atol=1e-5)


def test_retriever_factory_respects_coalesce_knob(monkeypatch):
    from githubrepostorag_tpu.config import reload_settings

    monkeypatch.setenv("RETRIEVAL_COALESCE", "0")
    reload_settings()
    enc = HashingTextEncoder(dim=32)
    f = RetrieverFactory(MemoryVectorStore(), enc)
    assert f.coalescer is None
    monkeypatch.delenv("RETRIEVAL_COALESCE")
    reload_settings()
    f2 = RetrieverFactory(MemoryVectorStore(), enc)
    assert f2.coalescer is not None


def test_device_store_through_full_retriever_stack():
    """End-to-end: coalescer over a DeviceIndexedStore — one wave drives the
    batched device search; hierarchy results equal the pure-host stack."""
    enc = HashingTextEncoder(dim=64)
    host = MemoryVectorStore()
    _seed_corpus(host, enc)
    dev = DeviceIndexedStore(host, k_bucket=16, max_wave=8)
    f_host = RetrieverFactory(host, enc)
    f_dev = RetrieverFactory(dev, enc)
    queries = [f"alpha beta {i}" for i in (2, 6, 11)]
    flt = {"namespace": "default"}
    host_out = f_host.for_scope("chunk").retrieve_many(queries, flt)
    dev_out = f_dev.for_scope("chunk").retrieve_many(queries, flt)
    for a, b in zip(host_out, dev_out):
        assert [d.doc_id for d in a] == [d.doc_id for d in b]
