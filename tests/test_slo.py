"""Fleet SLO plane (obs/ledger.py + obs/slo.py): token-ledger bucket
classification and limiter attribution, SRE multi-window burn-rate state
machine, per-replica metric federation under dp=2, the FAULTS-driven chaos
path (deadline storm -> ok -> critical -> ok with counted transitions),
and the API observing the admission hint on its shedding path."""

from __future__ import annotations

import asyncio
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.config import reload_settings
from githubrepostorag_tpu.metrics import DECODE_TOKENS, JOBS_SHED, counter_value
from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
from githubrepostorag_tpu.obs.ledger import (
    BUCKETS,
    SNAPSHOT_FIELDS,
    TokenLedger,
    flops_per_token,
)
from githubrepostorag_tpu.obs.slo import (
    CRITICAL,
    OK,
    WARN,
    SLOMonitor,
    get_slo_plane,
)
from githubrepostorag_tpu.parallel import MeshPlan
from githubrepostorag_tpu.resilience import admission_hint, should_shed
from githubrepostorag_tpu.resilience.admission import (
    clear_hint_provider,
    set_hint_provider,
)
from githubrepostorag_tpu.resilience.faults import reset_faults
from githubrepostorag_tpu.resilience.policy import Deadline, deadline_scope
from githubrepostorag_tpu.serving import Engine, SamplingParams
from githubrepostorag_tpu.serving.multi_engine import MultiAsyncEngine, dp_submeshes


def _snap(**kw) -> dict[str, float]:
    """A cumulative engine snapshot with every field defaulted to zero."""
    base = {f: 0.0 for f in SNAPSHOT_FIELDS}
    base.update(kw)
    return base


# ------------------------------------------------------------ token ledger


def test_ledger_bucket_classification_and_goodput():
    led = TokenLedger("t0", window_s=60.0)
    led.on_step(_snap(prefill_tokens=20, prefill_seconds_total=0.3),
                100.0, 100.4)
    led.on_step(_snap(prefill_tokens=20, prefill_seconds_total=0.3,
                      committed_tokens=8, decode_seconds_total=0.55),
                100.5, 100.8)  # 0.1s gap after the previous step_end
    snap = led.snapshot(now=100.8)
    assert snap["steps"] == 2
    assert snap["bucket_seconds"]["prefill"] == pytest.approx(0.3)
    assert snap["bucket_seconds"]["decode"] == pytest.approx(0.55)
    assert snap["bucket_seconds"]["sched_stall"] == pytest.approx(0.1)
    assert snap["bucket_seconds"]["compile"] == 0.0
    assert snap["tokens"]["committed"] == 8
    assert snap["tokens"]["prefill"] == 20
    # elapsed = now - first step_end = 0.4s -> 8 committed / 0.4
    assert snap["goodput_tok_s"] == pytest.approx(20.0)
    assert set(snap["bucket_seconds"]) == set(BUCKETS)


def test_ledger_compile_bucket_is_unaccounted_step_time():
    led = TokenLedger("t1", window_s=60.0)
    # a fresh XLA compile: 2.0s wall but only 0.2s of measured phase time
    led.on_step(_snap(prefill_seconds_total=0.2), 10.0, 12.0, compiles=1)
    snap = led.snapshot(now=12.0)
    assert snap["bucket_seconds"]["compile"] == pytest.approx(1.8)
    assert snap["limiter"] == "compile"


def test_ledger_limiter_hbm_pages_when_admission_blocked():
    led = TokenLedger("t2", window_s=60.0)
    led.on_step(_snap(decode_seconds_total=0.1, admission_blocked_steps=1),
                10.0, 10.1)
    led.on_step(_snap(decode_seconds_total=0.2, admission_blocked_steps=2),
                10.1, 10.2)
    assert led.snapshot(now=10.2)["limiter"] == "hbm_pages"


def test_ledger_limiter_swap_wait_when_migration_dominates():
    led = TokenLedger("t3", window_s=60.0)
    led.on_step(_snap(decode_seconds_total=0.4, migration_seconds_total=0.6),
                10.0, 11.0)
    assert led.snapshot(now=11.0)["limiter"] == "swap_wait"


def test_ledger_limiter_stall_when_gaps_dominate():
    led = TokenLedger("t4", window_s=60.0)
    led.on_step(_snap(decode_seconds_total=0.1), 100.0, 100.1)
    led.on_step(_snap(decode_seconds_total=0.2), 101.0, 101.1)  # 0.9s gap
    assert led.snapshot(now=101.1)["limiter"] == "stall"


def test_ledger_idle_gap_is_not_a_scheduler_stall():
    led = TokenLedger("t5", window_s=60.0)
    led.on_step(_snap(decode_seconds_total=0.1), 20.0, 20.1)
    led.idle(now=20.5)  # driver went idle between requests
    led.on_step(_snap(decode_seconds_total=0.2), 21.0, 21.1)
    assert led.snapshot(now=21.1)["bucket_seconds"]["sched_stall"] == 0.0


def test_ledger_window_prunes_and_goodput_decays_to_zero():
    led = TokenLedger("t6", window_s=1.0)
    led.on_step(_snap(committed_tokens=8, decode_seconds_total=0.2),
                10.0, 10.2)
    assert led.snapshot(now=10.4)["goodput_tok_s"] > 0
    stale = led.snapshot(now=12.0)  # the only step fell out of the window
    assert stale["steps"] == 0
    assert stale["goodput_tok_s"] == 0.0
    assert stale["limiter"] == "none"


def test_ledger_wasted_token_accounting():
    led = TokenLedger("t7", window_s=60.0)
    led.on_step(_snap(committed_tokens=6, reaped_tokens=2,
                      spec_proposed=10, spec_accepted=6,
                      spec_verify_seconds_total=0.2),
                10.0, 10.3)
    tokens = led.snapshot(now=10.3)["tokens"]
    assert tokens["spec_rejected"] == 4
    assert tokens["deadline_reaped"] == 2
    # wasted = (4 rejected + 2 reaped) / (6 committed + 6 wasted)
    assert tokens["wasted_fraction"] == pytest.approx(0.5)


def test_ledger_mfu_from_flops_per_token():
    led = TokenLedger("t8", flops_per_tok=1e9, peak_flops=1e12, window_s=60.0)
    led.on_step(_snap(prefill_tokens=10, prefill_seconds_total=0.4), 0.0, 0.5)
    led.on_step(_snap(prefill_tokens=10, prefill_seconds_total=0.4,
                      committed_tokens=10, decode_seconds_total=0.4),
                0.5, 1.0)
    snap = led.snapshot(now=1.0)
    # 20 tokens x 1e9 flops over 0.5s x 1e12 peak = 4% MFU
    assert snap["mfu"] == pytest.approx(0.04)
    assert snap["goodput_tok_s"] == pytest.approx(20.0)


def test_flops_per_token_estimate_is_parameter_scaled():
    cfg = Qwen2Config.tiny()
    fpt = flops_per_token(cfg)
    assert fpt > 2.0 * cfg.vocab_size * cfg.hidden_size  # at least the lm head
    assert fpt < 1e12  # sane for a tiny config


# ----------------------------------------------------- burn-rate monitor


def test_monitor_trips_critical_then_recovers(monkeypatch):
    monkeypatch.setenv("SLO_WINDOWS", "1,5")
    reload_settings()
    mon = SLOMonitor("m0")
    t0 = 1000.0
    for i in range(5):
        mon.observe(deadline_missed=True, now=t0 + 0.1 * i)
    # burn = (5/5 miss) / 0.05 budget = 20 >= 14.4 on BOTH windows
    assert mon.worst_state() == CRITICAL
    counts = mon.transition_counts()
    assert counts[("deadline_miss", "interactive", "critical")] == 1

    # the bad burst ages out of the long window; good traffic replaces it
    for i in range(3):
        mon.observe(deadline_missed=False, now=t0 + 10.0 + 0.1 * i)
    assert mon.worst_state() == OK
    counts = mon.transition_counts()
    assert counts[("deadline_miss", "interactive", "ok")] == 1

    payload = mon.payload(now=t0 + 10.5)
    assert payload["replica"] == "m0"
    assert payload["state"] == "ok"
    assert payload["transitions"] == 2
    row = next(r for r in payload["objectives"]
               if r["objective"] == "deadline_miss")
    assert [b["window_s"] for b in row["burn"]] == [1.0, 5.0]
    assert all(b["rate"] == 0.0 for b in row["burn"])


def test_monitor_warn_between_thresholds(monkeypatch):
    monkeypatch.setenv("SLO_WINDOWS", "1,5")
    reload_settings()
    mon = SLOMonitor("m1")
    t0 = 2000.0
    # 5/10 missed -> burn = 0.5 / 0.05 = 10: past warn (6), short of 14.4
    for i in range(10):
        mon.observe(deadline_missed=(i % 2 == 0), now=t0 + 0.05 * i)
    assert mon.worst_state() == WARN
    plane = get_slo_plane()
    plane.register("m1", monitor=mon)
    assert plane.admission_hint() == "throttle"
    assert admission_hint() == "throttle"
    assert not should_shed()


def test_monitor_requires_both_windows_to_alert(monkeypatch):
    """The long window filters blips: a short bad burst trips the 1s window
    but not the 5s one, so the state machine must stay ok."""
    monkeypatch.setenv("SLO_WINDOWS", "1,5")
    reload_settings()
    mon = SLOMonitor("m2")
    t0 = 3000.0
    for i in range(6):
        mon.observe(deadline_missed=False, now=t0 + 0.05 * i)
    for i in range(2):  # blip: short window is 100% bad, long is 2/8
        mon.observe(deadline_missed=True, now=t0 + 2.0 + 0.05 * i)
    assert mon.worst_state() == OK
    assert mon.transition_counts() == {}


def test_monitor_ttft_and_tpot_objectives(monkeypatch):
    monkeypatch.setenv("SLO_WINDOWS", "1,5")
    monkeypatch.setenv("SLO_TPOT_MS", "100")
    reload_settings()
    mon = SLOMonitor("m3")
    t0 = 4000.0
    for i in range(5):
        mon.observe("batch", ttft_s=0.01, tpot_s=0.5, now=t0 + 0.05 * i)
    payload = mon.payload(now=t0 + 0.3)
    by_name = {r["objective"]: r for r in payload["objectives"]
               if r["klass"] == "batch"}
    assert by_name["tpot"]["state"] == "critical"  # 100% over 100ms budget 5%
    assert by_name["ttft_p99"]["state"] == "ok"
    assert by_name["tpot"]["events"] == 5 and by_name["tpot"]["bad"] == 5


def test_monitor_longctx_class_has_relaxed_thresholds(monkeypatch):
    """A 5s TTFT is a hard interactive miss but comfortably inside the
    longctx objectives — same monitor, per-class threshold override."""
    monkeypatch.setenv("SLO_WINDOWS", "1,5")
    monkeypatch.setenv("SLO_TTFT_P99_MS", "1000")
    monkeypatch.setenv("SLO_LONGCTX_TTFT_P99_MS", "45000")
    reload_settings()
    mon = SLOMonitor("m4")
    t0 = 5000.0
    for i in range(5):
        mon.observe("interactive", ttft_s=5.0, now=t0 + 0.05 * i)
        mon.observe("longctx", ttft_s=5.0, now=t0 + 0.05 * i)
    payload = mon.payload(now=t0 + 0.3)
    rows = {(r["objective"], r["klass"]): r for r in payload["objectives"]}
    assert rows[("ttft_p99", "interactive")]["state"] == "critical"
    assert rows[("ttft_p99", "longctx")]["state"] == "ok"
    assert rows[("ttft_p99", "longctx")]["bad"] == 0


def test_slo_payload_config_includes_longctx_thresholds():
    plane = get_slo_plane()
    cfg = plane.slo_payload()["config"]
    assert cfg["longctx_ttft_p50_ms"] > cfg["ttft_p50_ms"]
    assert cfg["longctx_ttft_p99_ms"] > cfg["ttft_p99_ms"]
    assert cfg["longctx_tpot_ms"] >= cfg["tpot_ms"]


# ------------------------------------------------------------ SLO plane


def test_plane_fleet_payload_federates_ledger_and_monitor():
    plane = get_slo_plane()
    led = TokenLedger("p0", window_s=60.0)
    now = time.monotonic()  # fleet_payload snapshots at real monotonic time
    led.on_step(_snap(committed_tokens=10, decode_seconds_total=0.2,
                      reaped_tokens=1), now - 0.5, now)
    mon = SLOMonitor("p0")
    mon.observe(deadline_missed=False)
    plane.register("p0", ledger=led, monitor=mon,
                   stats=lambda: {"num_running": 0})

    slo = plane.slo_payload()
    assert slo["admission_hint"] == "accept"
    assert set(slo["config"]) >= {"windows_s", "burn_warn", "burn_critical",
                                  "ttft_p99_ms", "deadline_miss_budget"}
    assert [r["replica"] for r in slo["replicas"]] == ["p0"]

    fleet = plane.fleet_payload()
    assert fleet["fleet"]["replicas"] == 1
    assert fleet["fleet"]["committed_tokens"] == 10
    assert fleet["fleet"]["wasted_tokens"] == 1
    rep = fleet["replicas"][0]
    assert rep["ledger"]["tokens"]["committed"] == 10
    assert rep["slo"]["state"] == "ok"
    assert rep["stats"] == {"num_running": 0}

    plane.unregister("p0")
    assert plane.fleet_payload()["fleet"]["replicas"] == 0


def test_admission_hint_is_failure_open():
    assert admission_hint() == "accept"  # no provider registered
    set_hint_provider(lambda: 1 / 0)
    try:
        assert admission_hint() == "accept"  # broken plane never blocks
    finally:
        clear_hint_provider()
    set_hint_provider(lambda: "bogus")
    try:
        assert admission_hint() == "accept"  # unknown hints are ignored
    finally:
        clear_hint_provider()


# ------------------------------------------- dp=2 metrics federation


@pytest.fixture(scope="module")
def tiny():
    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    return cfg, params


def _engine(params, cfg, mesh=None):
    return Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                  max_seq_len=64, kv_dtype=jnp.float32, decode_burst=8,
                  mesh=mesh)


def _prompts(n):
    rng = np.random.default_rng(11)
    return [rng.integers(0, 512, 6 + i).tolist() for i in range(n)]


async def test_dp2_replica_series_distinct_and_summed(tiny):
    """Regression for the replica-aliasing bug: with dp=2 every engine
    driver used to write the same unlabeled series; now r0/r1 must be
    distinct AND sum to the true total."""
    cfg, params = tiny
    meshes, _ = dp_submeshes(MeshPlan(tp=2, dp=2))
    multi = MultiAsyncEngine([_engine(params, cfg, mesh=m) for m in meshes])
    base = {r: counter_value(DECODE_TOKENS, replica=r) for r in ("r0", "r1")}
    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
    try:
        results = await asyncio.gather(
            *(multi.generate(p, sp) for p in _prompts(4)))
    finally:
        await multi.stop()
    total = sum(len(r.output_tokens) for r in results)
    assert total == 32
    delta = {r: counter_value(DECODE_TOKENS, replica=r) - base[r]
             for r in ("r0", "r1")}
    assert delta["r0"] > 0 and delta["r1"] > 0  # distinct per-replica series
    assert delta["r0"] + delta["r1"] == total  # no double count, no aliasing

    fleet = multi.fleet()
    assert fleet["fleet"]["replicas"] == 2
    assert [r["replica"] for r in fleet["replicas"]] == ["r0", "r1"]
    committed = sum(r["ledger"]["tokens"]["committed"]
                    for r in fleet["replicas"])
    assert committed == total
    for rep in fleet["replicas"]:
        assert rep["slo"]["replica"] == rep["replica"]
        assert "free_pages" in rep["stats"]


# ------------------------------------------------------------ chaos path


def _build_llm(replica: str):
    from githubrepostorag_tpu.llm import InProcessLLM
    from githubrepostorag_tpu.serving.async_engine import AsyncEngine
    from githubrepostorag_tpu.serving.tokenizer import ByteTokenizer

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = Engine(params, cfg, max_num_seqs=2, num_pages=128, page_size=8,
                 max_seq_len=256, prefill_chunk=64, kv_dtype=jnp.float32)
    ae = AsyncEngine(eng, replica=replica)
    return InProcessLLM(ae, ByteTokenizer(), default_max_tokens=8,
                        default_temperature=0.0, context_window=128), ae


def test_chaos_deadline_storm_trips_critical_then_recovers(monkeypatch):
    """End-to-end chaos drill: a FAULTS-injected llm.complete delay burns
    most of each request's deadline budget, the engine reaps the rows, the
    deadline-miss burn rate trips ok->critical, the admission hint flips to
    shed, and clearing the fault recovers critical->ok — with every
    transition counted."""
    # tight windows so the drill runs in seconds; park the latency
    # objectives so only the (deterministic) deadline-miss one can trip
    monkeypatch.setenv("SLO_WINDOWS", "0.5,2")
    monkeypatch.setenv("SLO_TTFT_P50_MS", "60000")
    monkeypatch.setenv("SLO_TTFT_P99_MS", "60000")
    monkeypatch.setenv("SLO_TPOT_MS", "60000")
    reload_settings()
    llm, ae = _build_llm("chaos0")
    try:
        llm.complete("warm the engine compile cache")  # no faults yet
        assert ae.slo.worst_state() == OK

        monkeypatch.setenv("FAULTS", "llm.complete:delay=0.45")
        reload_settings()
        reset_faults()
        for _ in range(4):
            with deadline_scope(Deadline(0.51)):
                # the fault eats 0.45s of the 0.51s budget before submission;
                # 200 tokens cannot decode in ~60ms -> the engine reaps the
                # row at a step boundary (finish_reason="deadline")
                out = llm.complete("deadline storm request", max_tokens=200)
            assert "reaped" in out
        ae.slo.maybe_refresh(force=True)  # don't race the 0.25s rate limit
        assert ae.slo.worst_state() == CRITICAL
        counts = ae.slo.transition_counts()
        assert counts.get(("deadline_miss", "interactive", "critical"), 0) >= 1
        # the hint the API's shedding path consults
        assert admission_hint() == "shed"
        assert should_shed()

        monkeypatch.setenv("FAULTS", "")
        reload_settings()
        reset_faults()
        deadline = time.monotonic() + 20.0
        while ae.slo.worst_state() != OK and time.monotonic() < deadline:
            assert "Error" not in llm.complete("healthy traffic", max_tokens=4)
            time.sleep(0.05)
        assert ae.slo.worst_state() == OK
        counts = ae.slo.transition_counts()
        assert counts.get(("deadline_miss", "interactive", "ok"), 0) >= 1
        assert admission_hint() == "accept"
        assert not should_shed()
    finally:
        llm.close()


# ------------------------------------------------- API shedding path


async def test_api_sheds_jobs_while_hint_is_shed():
    from tests.test_api_worker import _with_service

    class _CriticalMonitor:
        def worst_state(self):
            return CRITICAL

    plane = get_slo_plane()
    plane.register("storm", monitor=_CriticalMonitor())
    shed_before = counter_value(JOBS_SHED)

    async def body(session, base, api, worker):
        resp = await session.post(f"{base}/rag/jobs", json={"query": "q"})
        assert resp.status == 429
        payload = await resp.json()
        assert "SLO" in payload["error"]
        assert resp.headers.get("Retry-After") == "1"
        # burn recovers -> hint back to accept -> admission resumes
        plane.unregister("storm")
        resp2 = await session.post(
            f"{base}/rag/jobs", json={"query": "how are jobs created?"})
        assert resp2.status != 429
        assert "job_id" in await resp2.json()

    await _with_service(body)
    assert counter_value(JOBS_SHED) == shed_before + 1
