"""Qwen2-MoE model family (models/moe.py): HF logits/generation parity,
expert-parallel sharding parity on the CPU mesh, capacity-drop semantics,
and the full serving engine over a MoE checkpoint.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.qwen2 import (
    Qwen2Config,
    forward_with_attend,
    init_params,
)
from githubrepostorag_tpu.parallel import MeshPlan, make_mesh
from githubrepostorag_tpu.serving import Engine, SamplingParams

transformers = pytest.importorskip("transformers")
import torch  # noqa: E402


@pytest.fixture(scope="module")
def tiny_moe():
    from githubrepostorag_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    hf_cfg = transformers.Qwen2MoeConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, attention_dropout=0.0,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=48,
        shared_expert_intermediate_size=96, norm_topk_prob=True,
        decoder_sparse_step=1, mlp_only_layers=[],
        output_router_logits=False,
    )
    import dataclasses

    torch.manual_seed(0)
    model = transformers.Qwen2MoeForCausalLM(hf_cfg).eval()
    # exact no-drop dispatch for HF parity (serving default is bounded)
    cfg = dataclasses.replace(config_from_hf(hf_cfg.to_dict()), capacity_factor=0.0)
    params = params_from_state_dict(model.state_dict(), cfg)
    return model, params, cfg


def test_config_from_hf_maps_moe_fields(tiny_moe):
    from githubrepostorag_tpu.models.hf_loader import config_from_hf

    _, _, cfg = tiny_moe
    assert cfg.num_experts == 4
    assert cfg.num_experts_per_tok == 2
    assert cfg.moe_intermediate_size == 48
    assert cfg.shared_expert_intermediate_size == 96
    assert cfg.norm_topk_prob is True
    assert cfg.capacity_factor == 0.0  # fixture overrode it for parity
    # the LOAD default is bounded capacity: no-drop dispatch is quadratic
    loaded = config_from_hf(transformers.Qwen2MoeConfig(num_experts=4).to_dict())
    assert loaded.capacity_factor == 2.0


def test_nonuniform_sparsity_rejected():
    from githubrepostorag_tpu.models.hf_loader import config_from_hf

    hf = transformers.Qwen2MoeConfig(num_experts=4, mlp_only_layers=[0]).to_dict()
    with pytest.raises(ValueError, match="uniform"):
        config_from_hf(hf)


def test_forward_logits_match_hf(tiny_moe):
    model, params, cfg = tiny_moe
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (2, 17), dtype=np.int32)
    with torch.no_grad():
        ref = model(torch.tensor(ids)).logits.numpy()
    pos = np.broadcast_to(np.arange(17, dtype=np.int32), (2, 17))
    got = np.asarray(
        forward_with_attend(params, cfg, jnp.asarray(ids), jnp.asarray(pos))
    )
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_engine_greedy_matches_hf_generate(tiny_moe):
    """The MoE family serves through the same paged engine: greedy decode
    must equal HF generate token-for-token."""
    model, params, cfg = tiny_moe
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, 21).tolist()
    eng = Engine(params, cfg, max_num_seqs=2, num_pages=64, page_size=8,
                 max_seq_len=128, prefill_chunk=32, kv_dtype=jnp.float32,
                 decode_burst=4)
    got = eng.generate(
        [prompt], SamplingParams(max_tokens=12, temperature=0.0, stop_token_ids=())
    )[0].output_tokens
    with torch.no_grad():
        hf = model.generate(torch.tensor([prompt]), max_new_tokens=12,
                            do_sample=False, pad_token_id=0, eos_token_id=None,
                            use_cache=True)
    assert got == hf[0, len(prompt):].tolist()


def test_ep_sharded_forward_matches_single_device(tiny_moe):
    """Expert weights sharded over ep=4 via the standard param specs: same
    logits as replicated."""
    from githubrepostorag_tpu.parallel.sharding import qwen2_param_specs, shard_params

    _, params, cfg = tiny_moe
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16), dtype=np.int32))
    pos = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (2, 16))
    ref = np.asarray(forward_with_attend(params, cfg, ids, pos))

    mesh = make_mesh(MeshPlan(ep=4))
    sharded = shard_params(params, mesh, qwen2_param_specs(cfg, mesh, params))
    for name in ("e_wg", "e_wu", "e_wd"):
        assert "ep" in str(sharded["layers"][name].sharding.spec)
    got = np.asarray(forward_with_attend(sharded, cfg, ids, pos))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_ep_sharded_engine_token_identical(tiny_moe):
    """The paged engine with an ep=4 mesh (expert weights sharded through
    Engine's own shard_params path) decodes the same greedy tokens as the
    unsharded engine.  Two prompt seeds guard against a reordered-psum
    near-tie argmax flip (a numerics artifact, not a sharding bug)."""
    _, params, cfg = tiny_moe
    sp = SamplingParams(max_tokens=10, temperature=0.0, stop_token_ids=())

    def run(mesh, prompt):
        eng = Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=8,
                     max_seq_len=64, prefill_chunk=32, kv_dtype=jnp.float32,
                     decode_burst=4, mesh=mesh)
        return eng.generate([prompt], sp)[0].output_tokens

    for seed in (6, 11):
        prompt = np.random.default_rng(seed).integers(0, cfg.vocab_size, 19).tolist()
        if run(make_mesh(MeshPlan(ep=4)), prompt) == run(None, prompt):
            break
    else:
        raise AssertionError("ep-sharded engine decode diverged on 2 seeds")


def test_capacity_drops_are_bounded_not_catastrophic():
    """With a finite capacity factor, overflow tokens lose expert
    contributions but the shared expert keeps outputs finite and close."""
    cfg_exact = Qwen2Config.tiny_moe()
    cfg_cap = Qwen2Config(**{**cfg_exact.__dict__, "capacity_factor": 1.5})
    params = init_params(cfg_exact, jax.random.PRNGKey(0))
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, cfg_exact.vocab_size, (2, 32), dtype=np.int32))
    pos = jnp.broadcast_to(jnp.arange(32, dtype=jnp.int32), (2, 32))
    exact = np.asarray(forward_with_attend(params, cfg_exact, ids, pos))
    capped = np.asarray(forward_with_attend(params, cfg_cap, ids, pos))
    assert np.all(np.isfinite(capped))
    # most tokens fit under capacity, so most logits agree with no-drop
    frac_same = np.mean(np.abs(capped - exact) < 1e-4)
    assert frac_same > 0.5, f"only {frac_same:.0%} of logits survived capacity"


def test_moe_int8_quantization(tiny_moe):
    """Weight-only int8 MoE: experts/shared-expert carry stacked per-expert
    scales, router and gate stay full precision, and logits track the bf16
    model within quantization tolerance (greedy engine output included)."""
    from githubrepostorag_tpu.models.quant import (
        QuantizedLinear,
        quantize_qwen2_params,
    )

    _, params, cfg = tiny_moe
    qp = quantize_qwen2_params(params)
    layers = qp["layers"]
    assert isinstance(layers["e_wg"], QuantizedLinear)
    assert layers["e_wg"].q.dtype == jnp.int8
    # scales: [L, E, ff] — per expert, per output channel
    assert layers["e_wg"].s.shape == layers["e_wg"].q.shape[:2] + (
        layers["e_wg"].q.shape[-1],
    )
    assert not isinstance(layers["router"], QuantizedLinear)
    assert not isinstance(layers["s_gate"], QuantizedLinear)

    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 16), dtype=np.int32))
    pos = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (1, 16))
    full = np.asarray(forward_with_attend(params, cfg, ids, pos))
    quant = np.asarray(forward_with_attend(qp, cfg, ids, pos))
    # int8 error bound, not exactness — relative to the logit scale
    assert np.abs(quant - full).max() / (np.abs(full).max() + 1e-6) < 0.15

    prompt = rng.integers(0, cfg.vocab_size, 15).tolist()
    eng = Engine(qp, cfg, max_num_seqs=2, num_pages=32, page_size=8,
                 max_seq_len=64, prefill_chunk=32, kv_dtype=jnp.float32,
                 decode_burst=4)
    res = eng.generate(
        [prompt], SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
    )[0]
    assert len(res.output_tokens) == 8


def test_moe_random_int8_init_still_guarded(tiny_moe):
    from githubrepostorag_tpu.models.quant import init_params_quantized

    _, _, cfg = tiny_moe
    with pytest.raises(NotImplementedError, match="load_qwen2"):
        init_params_quantized(cfg)


def test_moe_sharded_train_step(tiny_moe):
    """The REAL sharded train step (training/step.py) accepts MoE params on
    an ep mesh: loss finite, expert weights actually update."""
    import optax

    from githubrepostorag_tpu.training import init_train_state, make_train_step

    _, _, cfg = tiny_moe
    mesh = make_mesh(MeshPlan(ep=4))
    opt = optax.sgd(1e-2)
    step, _ = make_train_step(cfg, mesh, opt, remat=False)
    state = init_train_state(cfg, mesh, jax.random.PRNGKey(1), opt)
    before = np.asarray(state.params["layers"]["e_wg"])
    rng = np.random.default_rng(4)
    ids = rng.integers(0, cfg.vocab_size, (2, 16), dtype=np.int32)
    batch = {
        "input_ids": jnp.asarray(ids),
        "targets": jnp.asarray(np.roll(ids, -1, 1)),
        "mask": jnp.ones((2, 16), dtype=jnp.int32),
    }
    params, _, loss = step(state.params, state.opt_state, batch)
    assert np.isfinite(float(loss))
    after = np.asarray(params["layers"]["e_wg"])
    assert np.abs(after - before).sum() > 0, "expert weights did not update"


def test_moe_drop_stats_counter(tiny_moe, monkeypatch):
    """MOE_DROP_STATS=1 makes bounded-capacity dispatch observable: a
    router forced to send every token to one expert under a tight capacity
    must report drops (ADVICE r02 — silent contribution loss)."""
    import dataclasses

    from githubrepostorag_tpu.models import moe

    _, params, cfg = tiny_moe
    cfg = dataclasses.replace(cfg, capacity_factor=0.5)
    # all tokens to expert 0: bias the router column hard
    lay = dict(params["layers"])
    router = np.asarray(lay["router"]).copy()
    router[:, :, 0] += 100.0
    lay["router"] = jnp.asarray(router)
    monkeypatch.setenv("MOE_DROP_STATS", "1")
    moe.DROP_STATS["assignments"] = moe.DROP_STATS["dropped"] = 0
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8, cfg.hidden_size)),
                    dtype=jnp.float32)
    p0 = jax.tree.map(lambda l: l[0], lay)
    jax.block_until_ready(moe.moe_mlp(cfg, p0, x))
    assert moe.DROP_STATS["assignments"] == 2 * 8 * cfg.num_experts_per_tok
    assert moe.DROP_STATS["dropped"] > 0

    # disabled -> no callback, counters untouched
    monkeypatch.delenv("MOE_DROP_STATS")
    moe.DROP_STATS["assignments"] = moe.DROP_STATS["dropped"] = 0
    jax.block_until_ready(moe.moe_mlp(cfg, p0, x))
    assert moe.DROP_STATS == {"assignments": 0, "dropped": 0}
