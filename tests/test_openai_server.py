"""OpenAI-compatible server end-to-end over a real TCP socket: chat
completions (stream + non-stream), completions, stop strings, health,
64-way concurrency shape, and the InProcessLLM client."""

import asyncio
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models import Qwen2Config, init_params
from githubrepostorag_tpu.serving import Engine, SamplingParams
from githubrepostorag_tpu.serving.async_engine import AsyncEngine
from githubrepostorag_tpu.serving.openai_api import OpenAIServer
from githubrepostorag_tpu.serving.tokenizer import ByteTokenizer, StreamingDetokenizer


def _build_server(max_num_seqs=4, num_pages=256, max_seq_len=256):
    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = Engine(
        params, cfg, max_num_seqs=max_num_seqs, num_pages=num_pages, page_size=8,
        max_seq_len=max_seq_len, prefill_chunk=64, kv_dtype=jnp.float32,
    )
    tok = ByteTokenizer()
    return OpenAIServer(AsyncEngine(eng), tok, model_name="tiny-test")


async def _with_server(fn, **kw):
    import aiohttp

    server = _build_server(**kw)
    port = await server.start(host="127.0.0.1", port=0)
    try:
        async with aiohttp.ClientSession() as session:
            await fn(session, f"http://127.0.0.1:{port}")
    finally:
        await server.stop()


async def test_chat_completion_roundtrip():
    async def body(session, base):
        resp = await session.post(
            f"{base}/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 8,
                "temperature": 0,
            },
        )
        assert resp.status == 200
        data = await resp.json()
        assert data["object"] == "chat.completion"
        assert data["choices"][0]["finish_reason"] in ("stop", "length")
        assert data["usage"]["completion_tokens"] > 0
        assert isinstance(data["choices"][0]["message"]["content"], str)

    await _with_server(body)


async def test_chat_completion_streaming():
    async def body(session, base):
        resp = await session.post(
            f"{base}/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "stream please"}],
                "max_tokens": 8,
                "temperature": 0,
                "stream": True,
            },
        )
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        chunks, done = [], False
        async for raw in resp.content:
            line = raw.decode().strip()
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                done = True
                break
            chunks.append(json.loads(payload))
        assert done
        assert chunks[0]["object"] == "chat.completion.chunk"
        assert chunks[-1]["choices"][0]["finish_reason"] in ("stop", "length")
        # deltas concatenate to some text
        text = "".join(c["choices"][0]["delta"].get("content", "") for c in chunks)
        assert isinstance(text, str)

    await _with_server(body)


async def test_completions_endpoint_and_models_and_health():
    async def body(session, base):
        resp = await session.post(
            f"{base}/v1/completions",
            json={"prompt": "abc", "max_tokens": 4, "temperature": 0},
        )
        data = await resp.json()
        assert data["object"] == "text_completion"

        models = await (await session.get(f"{base}/v1/models")).json()
        assert models["data"][0]["id"] == "tiny-test"

        health = await (await session.get(f"{base}/health")).json()
        assert health["status"] == "ok"
        assert "free_pages" in health

    await _with_server(body)


async def test_malformed_request_400():
    async def body(session, base):
        resp = await session.post(f"{base}/v1/chat/completions", data=b"not json")
        assert resp.status == 400
        err = await resp.json()
        assert "error" in err

        resp2 = await session.post(f"{base}/v1/chat/completions", json={"nope": 1})
        assert resp2.status == 400

    await _with_server(body)


async def test_concurrent_streams():
    """BASELINE config #5 shape: many concurrent SSE streams sharing the
    continuous batch (scaled down for CPU)."""

    async def body(session, base):
        async def one(i):
            resp = await session.post(
                f"{base}/v1/chat/completions",
                json={
                    "messages": [{"role": "user", "content": f"req {i}"}],
                    "max_tokens": 6,
                    "temperature": 0.5,
                    "stream": True,
                },
            )
            n_done = 0
            async for raw in resp.content:
                line = raw.decode().strip()
                if line == "data: [DONE]":
                    n_done += 1
            return n_done

        results = await asyncio.gather(*(one(i) for i in range(8)))
        assert all(r == 1 for r in results)

    await _with_server(body, max_num_seqs=4)  # more streams than batch slots


def test_streaming_detokenizer_utf8_boundaries():
    tok = ByteTokenizer()
    detok = StreamingDetokenizer(tok)
    text = "héllo 世界"
    out = ""
    for b in text.encode("utf-8"):
        out += detok.push(b)
    out += detok.flush()
    assert out == text


def test_inprocess_llm_client():
    from githubrepostorag_tpu.llm import InProcessLLM

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = Engine(params, cfg, max_num_seqs=2, num_pages=128, page_size=8,
                 max_seq_len=256, prefill_chunk=64, kv_dtype=jnp.float32)
    llm = InProcessLLM(AsyncEngine(eng), ByteTokenizer(),
                       default_max_tokens=8, default_temperature=0.0)
    out = llm.complete("What does this repo do?")
    assert isinstance(out, str)
    deltas = list(llm.stream_complete("stream this", max_tokens=6))
    assert "".join(deltas) is not None


def test_fake_llm_scripting():
    from githubrepostorag_tpu.llm import FakeLLM

    llm = FakeLLM(script={
        r"plan the scope": '{"scope": "repo", "filters": {}}',
        r"respond with only the number": "I think the answer is 3.",
    })
    assert llm.complete("Please plan the scope for this query") == '{"scope": "repo", "filters": {}}'
    # selector prompts go through the choice cascade
    assert llm.complete("Pick one. respond with only the number") == "3"
    assert llm.calls[0]["prompt"].startswith("Please plan")


async def test_multi_turn_chat_reuses_prefix_cache():
    """Turn 2 of a conversation carries turn 1's rendered history verbatim,
    so its prefill resumes from turn 1's cached KV pages — the RAG/chat
    cost model the prefix cache exists for, proven at the API layer."""
    server = _build_server()

    async def body(session, base):
        history = [{"role": "user", "content": "tell me about pages " * 4}]
        r1 = await session.post(f"{base}/v1/chat/completions", json={
            "messages": history, "max_tokens": 8, "temperature": 0,
        })
        assert r1.status == 200
        reply = (await r1.json())["choices"][0]["message"]["content"]
        hits_before = server.engine.engine._allocator.hit_tokens
        history += [
            {"role": "assistant", "content": reply},
            {"role": "user", "content": "go on"},
        ]
        r2 = await session.post(f"{base}/v1/chat/completions", json={
            "messages": history, "max_tokens": 8, "temperature": 0,
        })
        assert r2.status == 200
        hits = server.engine.engine._allocator.hit_tokens - hits_before
        # turn 1's prompt renders to 98 byte-tokens -> its 12 full 8-token
        # pages come back from the cache on turn 2
        assert hits >= 96, f"only {hits} tokens reused across turns"

    import aiohttp

    port = await server.start(host="127.0.0.1", port=0)
    try:
        async with aiohttp.ClientSession() as session:
            await body(session, f"http://127.0.0.1:{port}")
    finally:
        await server.stop()


def _write_awq_checkpoint(root) -> None:
    """A freshly generated tiny AWQ-layout checkpoint on disk: config.json
    with quantization_config.quant_method=awq + one safetensors shard whose
    projections are AutoAWQ GEMM-packed qweight/qzeros/scales (the layout
    Qwen2.5-Coder-7B-Instruct-AWQ ships — reference values.yaml:67)."""
    from githubrepostorag_tpu.models.hf_loader import AWQ_NIBBLE_ORDER

    rng = np.random.default_rng(3)
    group = 16

    def awq_pack(u4: np.ndarray) -> np.ndarray:
        r, c = u4.shape
        out = np.zeros((r, c // 8), dtype=np.uint32)
        for pos, col in enumerate(AWQ_NIBBLE_ORDER):
            out |= u4[:, col::8].astype(np.uint32) << np.uint32(4 * pos)
        return out.view(np.int32)

    def awq_linear(in_dim: int, out_dim: int) -> dict[str, np.ndarray]:
        q = rng.integers(0, 16, (in_dim, out_dim), dtype=np.uint8)
        z = rng.integers(0, 16, (in_dim // group, out_dim), dtype=np.uint8)
        s = (rng.random((in_dim // group, out_dim), dtype=np.float32) * 0.05
             + 0.005).astype(np.float16)
        return {"qweight": awq_pack(q), "qzeros": awq_pack(z), "scales": s}

    cfg = Qwen2Config.tiny()
    h, q_out = cfg.hidden_size, cfg.num_heads * cfg.head_dim
    kv_out, inter = cfg.num_kv_heads * cfg.head_dim, cfg.intermediate_size
    state: dict[str, np.ndarray] = {
        "model.embed_tokens.weight":
            (rng.standard_normal((cfg.vocab_size, h)) * 0.02).astype(np.float16),
        "model.norm.weight": np.ones(h, dtype=np.float16),
    }
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}"
        state[f"{p}.input_layernorm.weight"] = np.ones(h, dtype=np.float16)
        state[f"{p}.post_attention_layernorm.weight"] = np.ones(h, dtype=np.float16)
        for name, dims in (("self_attn.q_proj", (h, q_out)),
                           ("self_attn.k_proj", (h, kv_out)),
                           ("self_attn.v_proj", (h, kv_out)),
                           ("self_attn.o_proj", (q_out, h)),
                           ("mlp.gate_proj", (h, inter)),
                           ("mlp.up_proj", (h, inter)),
                           ("mlp.down_proj", (inter, h))):
            for suffix, tensor in awq_linear(*dims).items():
                state[f"{p}.{name}.{suffix}"] = tensor
        for bname, dim in (("q_proj", q_out), ("k_proj", kv_out), ("v_proj", kv_out)):
            state[f"{p}.self_attn.{bname}.bias"] = (
                rng.standard_normal(dim) * 0.01).astype(np.float16)

    from safetensors.numpy import save_file

    save_file(state, str(root / "model.safetensors"))
    (root / "config.json").write_text(json.dumps({
        "model_type": "qwen2",
        "vocab_size": cfg.vocab_size,
        "hidden_size": h,
        "intermediate_size": inter,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.head_dim,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": 1e-6,
        "tie_word_embeddings": True,
        "max_position_embeddings": cfg.max_position_embeddings,
        "torch_dtype": "float16",
        "quantization_config": {
            "quant_method": "awq", "bits": 4, "version": "gemm",
            "group_size": group, "zero_point": True,
        },
    }))


async def test_awq_checkpoint_end_to_end(tmp_path):
    """VERDICT r04 next #10: keep the real-weight path warm.  Round-trips a
    freshly generated AWQ-layout checkpoint through hf_loader (AWQ
    detection -> nibble repack -> QuantizedLinear4 stacks -> fused serving
    layout) and the OpenAI server — the moment a real AWQ checkpoint ever
    lands on a host, the same load_qwen2 + serve path runs it."""
    import aiohttp

    from githubrepostorag_tpu.models.hf_loader import load_qwen2
    from githubrepostorag_tpu.models.quant import QuantizedLinear4

    _write_awq_checkpoint(tmp_path)
    params, cfg = load_qwen2(str(tmp_path), dtype=np.float32, fuse=True)
    assert cfg.vocab_size == Qwen2Config.tiny().vocab_size
    # the projections really are the in-tree int4 form (not dequantized)
    assert isinstance(params["layers"]["wo"], QuantizedLinear4)

    eng = Engine(params, cfg, max_num_seqs=2, num_pages=64, page_size=8,
                 max_seq_len=128, prefill_chunk=32, kv_dtype=jnp.float32)
    server = OpenAIServer(AsyncEngine(eng), ByteTokenizer(), model_name="tiny-awq")
    port = await server.start(host="127.0.0.1", port=0)
    try:
        async with aiohttp.ClientSession() as session:
            resp = await session.post(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "hello awq"}],
                      "max_tokens": 8, "temperature": 0},
            )
            assert resp.status == 200
            data = await resp.json()
            assert data["usage"]["completion_tokens"] > 0
            assert isinstance(data["choices"][0]["message"]["content"], str)
    finally:
        await server.stop()


async def test_async_engine_stop_joins_driver_off_loop():
    """Regression: stop() must not freeze the event loop while joining the
    driver — a cold compile can hold a step for seconds, and an inline
    join() would stall every coroutine in the process for the duration."""
    import threading
    import time

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=8,
                 max_seq_len=64, kv_dtype=jnp.float32)
    ae = AsyncEngine(eng)
    await ae.start()

    real = ae._thread
    join_threads = []

    class SlowJoin:
        """Stands in for a driver stuck mid-step: join() blocks 0.3s."""

        def join(self, timeout=None):
            join_threads.append(threading.current_thread())
            time.sleep(0.3)
            real.join(timeout)

    ae._thread = SlowJoin()

    ticks = 0

    async def heartbeat():
        nonlocal ticks
        while True:
            await asyncio.sleep(0.01)
            ticks += 1

    hb = asyncio.create_task(heartbeat())
    await asyncio.sleep(0)  # let the heartbeat get scheduled
    before = ticks
    await ae.stop()
    progressed = ticks - before
    hb.cancel()

    assert join_threads and join_threads[0] is not threading.current_thread()
    assert progressed >= 2  # loop kept serving coroutines during the join
    assert not real.is_alive()
