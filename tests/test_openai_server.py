"""OpenAI-compatible server end-to-end over a real TCP socket: chat
completions (stream + non-stream), completions, stop strings, health,
64-way concurrency shape, and the InProcessLLM client."""

import asyncio
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models import Qwen2Config, init_params
from githubrepostorag_tpu.serving import Engine, SamplingParams
from githubrepostorag_tpu.serving.async_engine import AsyncEngine
from githubrepostorag_tpu.serving.openai_api import OpenAIServer
from githubrepostorag_tpu.serving.tokenizer import ByteTokenizer, StreamingDetokenizer


def _build_server(max_num_seqs=4, num_pages=256, max_seq_len=256):
    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = Engine(
        params, cfg, max_num_seqs=max_num_seqs, num_pages=num_pages, page_size=8,
        max_seq_len=max_seq_len, prefill_chunk=64, kv_dtype=jnp.float32,
    )
    tok = ByteTokenizer()
    return OpenAIServer(AsyncEngine(eng), tok, model_name="tiny-test")


async def _with_server(fn, **kw):
    import aiohttp

    server = _build_server(**kw)
    port = await server.start(host="127.0.0.1", port=0)
    try:
        async with aiohttp.ClientSession() as session:
            await fn(session, f"http://127.0.0.1:{port}")
    finally:
        await server.stop()


async def test_chat_completion_roundtrip():
    async def body(session, base):
        resp = await session.post(
            f"{base}/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 8,
                "temperature": 0,
            },
        )
        assert resp.status == 200
        data = await resp.json()
        assert data["object"] == "chat.completion"
        assert data["choices"][0]["finish_reason"] in ("stop", "length")
        assert data["usage"]["completion_tokens"] > 0
        assert isinstance(data["choices"][0]["message"]["content"], str)

    await _with_server(body)


async def test_chat_completion_streaming():
    async def body(session, base):
        resp = await session.post(
            f"{base}/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "stream please"}],
                "max_tokens": 8,
                "temperature": 0,
                "stream": True,
            },
        )
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        chunks, done = [], False
        async for raw in resp.content:
            line = raw.decode().strip()
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                done = True
                break
            chunks.append(json.loads(payload))
        assert done
        assert chunks[0]["object"] == "chat.completion.chunk"
        assert chunks[-1]["choices"][0]["finish_reason"] in ("stop", "length")
        # deltas concatenate to some text
        text = "".join(c["choices"][0]["delta"].get("content", "") for c in chunks)
        assert isinstance(text, str)

    await _with_server(body)


async def test_completions_endpoint_and_models_and_health():
    async def body(session, base):
        resp = await session.post(
            f"{base}/v1/completions",
            json={"prompt": "abc", "max_tokens": 4, "temperature": 0},
        )
        data = await resp.json()
        assert data["object"] == "text_completion"

        models = await (await session.get(f"{base}/v1/models")).json()
        assert models["data"][0]["id"] == "tiny-test"

        health = await (await session.get(f"{base}/health")).json()
        assert health["status"] == "ok"
        assert "free_pages" in health

    await _with_server(body)


async def test_malformed_request_400():
    async def body(session, base):
        resp = await session.post(f"{base}/v1/chat/completions", data=b"not json")
        assert resp.status == 400
        err = await resp.json()
        assert "error" in err

        resp2 = await session.post(f"{base}/v1/chat/completions", json={"nope": 1})
        assert resp2.status == 400

    await _with_server(body)


async def test_concurrent_streams():
    """BASELINE config #5 shape: many concurrent SSE streams sharing the
    continuous batch (scaled down for CPU)."""

    async def body(session, base):
        async def one(i):
            resp = await session.post(
                f"{base}/v1/chat/completions",
                json={
                    "messages": [{"role": "user", "content": f"req {i}"}],
                    "max_tokens": 6,
                    "temperature": 0.5,
                    "stream": True,
                },
            )
            n_done = 0
            async for raw in resp.content:
                line = raw.decode().strip()
                if line == "data: [DONE]":
                    n_done += 1
            return n_done

        results = await asyncio.gather(*(one(i) for i in range(8)))
        assert all(r == 1 for r in results)

    await _with_server(body, max_num_seqs=4)  # more streams than batch slots


def test_streaming_detokenizer_utf8_boundaries():
    tok = ByteTokenizer()
    detok = StreamingDetokenizer(tok)
    text = "héllo 世界"
    out = ""
    for b in text.encode("utf-8"):
        out += detok.push(b)
    out += detok.flush()
    assert out == text


def test_inprocess_llm_client():
    from githubrepostorag_tpu.llm import InProcessLLM

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = Engine(params, cfg, max_num_seqs=2, num_pages=128, page_size=8,
                 max_seq_len=256, prefill_chunk=64, kv_dtype=jnp.float32)
    llm = InProcessLLM(AsyncEngine(eng), ByteTokenizer(),
                       default_max_tokens=8, default_temperature=0.0)
    out = llm.complete("What does this repo do?")
    assert isinstance(out, str)
    deltas = list(llm.stream_complete("stream this", max_tokens=6))
    assert "".join(deltas) is not None


def test_fake_llm_scripting():
    from githubrepostorag_tpu.llm import FakeLLM

    llm = FakeLLM(script={
        r"plan the scope": '{"scope": "repo", "filters": {}}',
        r"respond with only the number": "I think the answer is 3.",
    })
    assert llm.complete("Please plan the scope for this query") == '{"scope": "repo", "filters": {}}'
    # selector prompts go through the choice cascade
    assert llm.complete("Pick one. respond with only the number") == "3"
    assert llm.calls[0]["prompt"].startswith("Please plan")


async def test_multi_turn_chat_reuses_prefix_cache():
    """Turn 2 of a conversation carries turn 1's rendered history verbatim,
    so its prefill resumes from turn 1's cached KV pages — the RAG/chat
    cost model the prefix cache exists for, proven at the API layer."""
    server = _build_server()

    async def body(session, base):
        history = [{"role": "user", "content": "tell me about pages " * 4}]
        r1 = await session.post(f"{base}/v1/chat/completions", json={
            "messages": history, "max_tokens": 8, "temperature": 0,
        })
        assert r1.status == 200
        reply = (await r1.json())["choices"][0]["message"]["content"]
        hits_before = server.engine.engine._allocator.hit_tokens
        history += [
            {"role": "assistant", "content": reply},
            {"role": "user", "content": "go on"},
        ]
        r2 = await session.post(f"{base}/v1/chat/completions", json={
            "messages": history, "max_tokens": 8, "temperature": 0,
        })
        assert r2.status == 200
        hits = server.engine.engine._allocator.hit_tokens - hits_before
        # turn 1's prompt renders to 98 byte-tokens -> its 12 full 8-token
        # pages come back from the cache on turn 2
        assert hits >= 96, f"only {hits} tokens reused across turns"

    import aiohttp

    port = await server.start(host="127.0.0.1", port=0)
    try:
        async with aiohttp.ClientSession() as session:
            await body(session, f"http://127.0.0.1:{port}")
    finally:
        await server.stop()
