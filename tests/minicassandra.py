"""A tiny in-process Cassandra speaking the CQL native protocol v4 over a
real TCP socket — the miniredis pattern (tests/miniredis.py) applied to the
vector store: STARTUP/AUTHENTICATE/AUTH_RESPONSE, QUERY, PREPARE/EXECUTE
with binary-bound values, and RESULT rows with typed columns (varchar,
bigint, float, map<text,text>, and Cassandra 5's VECTOR<FLOAT, n> custom
marshal).  Interprets just the CQL the store issues: keyspace/table/index
DDL, prepared INSERT upserts, ANN search with ``similarity_cosine``
scoring + metadata entry filters, metadata lookups, point gets, COUNT,
DELETE, and the system tables the health probe and ``tables()`` read.

This is what lets tests/test_cql_wire.py run CassandraVectorStore's REAL
wire path (githubrepostorag_tpu/store/cql.py) end-to-end in CI — closing
VERDICT r02 missing #3 (the r02 store was CQL-shape-tested against a fake
session object only; no test spoke the actual protocol).
"""

from __future__ import annotations

import hashlib
import re
import struct
import threading
import socketserver

import numpy as np

from githubrepostorag_tpu.store import cql as W  # wire helpers (shared codec)

_VEC_CLS = "org.apache.cassandra.db.marshal.VectorType"


def _vector_type(dim: int):
    return ("vector", dim)


def _type_option(t) -> bytes:
    """Encode one type descriptor as a wire [option]."""
    if t[0] == "vector":
        cls = f"{_VEC_CLS}(org.apache.cassandra.db.marshal.FloatType, {t[1]})"
        return struct.pack(">H", W.TYPE_CUSTOM) + W._string(cls)
    if t[0] == "map":
        return struct.pack(">H", W.TYPE_MAP) + _type_option(t[1]) + _type_option(t[2])
    return struct.pack(">H", t[0])


class MiniCassandra:
    """In-memory tables: {name: {row_id: {body_blob, vector, metadata_s}}}."""

    def __init__(self, username: str = "cassandra", password: str = "cassandra") -> None:
        self.tables: dict[str, dict[str, dict]] = {}
        self.dims: dict[str, int] = {}
        self.keyspaces: set[str] = set()
        self.prepared: dict[bytes, str] = {}
        self.auth = (username, password)
        self.queries: list[str] = []  # every CQL text seen, for assertions
        self._server: socketserver.ThreadingTCPServer | None = None
        self.port: int | None = None

    # ---- lifecycle ----

    def start(self) -> int:
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one client connection
                try:
                    outer._serve(self.request)
                except (ConnectionError, OSError):
                    pass

        self._server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self.port

    def stop(self) -> None:
        if self._server:
            self._server.shutdown()
            self._server.server_close()

    # ---- framing ----

    def _serve(self, sock) -> None:
        authed = False
        while True:
            header = _recv_exact(sock, 9)
            if header is None:
                return
            _v, _f, stream, op, length = struct.unpack(">BBhBi", header)
            body = _recv_exact(sock, length) if length else b""
            if body is None:
                return
            if op == W.OP_STARTUP:
                _send(sock, stream, W.OP_AUTHENTICATE,
                      W._string("org.apache.cassandra.auth.PasswordAuthenticator"))
            elif op == W.OP_AUTH_RESPONSE:
                buf = W._Buf(body)
                token = buf.bytes_() or b""
                parts = token.split(b"\x00")
                if parts[-2:] == [self.auth[0].encode(), self.auth[1].encode()]:
                    authed = True
                    _send(sock, stream, W.OP_AUTH_SUCCESS, W._bytes(None))
                else:
                    _send_error(sock, stream, 0x0100, "Bad credentials")
            elif not authed:
                _send_error(sock, stream, 0x0100, "Not authenticated")
            elif op == W.OP_QUERY:
                buf = W._Buf(body)
                cql = buf.long_string()
                self.queries.append(cql)
                try:
                    _send_result(sock, stream, self._run(cql))
                except _Unsupported as exc:
                    _send_error(sock, stream, 0x2000, str(exc))
            elif op == W.OP_PREPARE:
                buf = W._Buf(body)
                cql = buf.long_string()
                self.queries.append("PREPARE " + cql)
                _send(sock, stream, W.OP_RESULT, self._prepare(cql))
            elif op == W.OP_EXECUTE:
                buf = W._Buf(body)
                qid = buf.short_bytes()
                buf.u16()  # consistency
                flags = buf.u8()
                values = []
                if flags & 0x01:
                    n = buf.u16()
                    values = [buf.bytes_() for _ in range(n)]
                try:
                    _send_result(sock, stream, self._execute(qid, values))
                except _Unsupported as exc:
                    _send_error(sock, stream, 0x2000, str(exc))
            else:
                _send_error(sock, stream, 0x000A, f"opcode 0x{op:02X} unsupported")

    # ---- CQL interpretation ----

    def _prepare(self, cql: str) -> bytes:
        m = re.match(
            r"INSERT INTO (\w+)\.(\w+) \(row_id, body_blob, vector, metadata_s\)"
            r" VALUES \(\?, \?, \?, \?\)",
            cql,
        )
        if not m:
            raise _Unsupported(f"cannot prepare: {cql}")
        table = m.group(2)
        qid = hashlib.md5(cql.encode()).digest()
        self.prepared[qid] = table
        dim = self.dims.get(table, 384)
        types = [
            (W.TYPE_VARCHAR,), (W.TYPE_VARCHAR,), _vector_type(dim),
            ("map", (W.TYPE_VARCHAR,), (W.TYPE_VARCHAR,)),
        ]
        names = ["row_id", "body_blob", "vector", "metadata_s"]
        meta = struct.pack(">iii", 0x0001, len(types), 1) + struct.pack(">H", 0)
        meta += W._string("ks") + W._string(table)
        for name, t in zip(names, types):
            meta += W._string(name) + _type_option(t)
        result_meta = struct.pack(">ii", 0x0004, 0)  # no_metadata, 0 cols
        return (
            struct.pack(">i", W.RESULT_PREPARED)
            + struct.pack(">H", len(qid)) + qid
            + meta + result_meta
        )

    def _execute(self, qid: bytes, values: list[bytes | None]):
        table = self.prepared.get(qid)
        if table is None:
            raise _Unsupported("unknown prepared id")
        dim = self.dims.get(table, 384)
        row_id = W.decode_value((W.TYPE_VARCHAR,), values[0])
        body = W.decode_value((W.TYPE_VARCHAR,), values[1])
        vec = W.decode_value(_vector_type(dim), values[2])
        meta = W.decode_value(("map", (W.TYPE_VARCHAR,), (W.TYPE_VARCHAR,)), values[3])
        self.tables.setdefault(table, {})[row_id] = {
            "row_id": row_id, "body_blob": body, "vector": vec,
            "metadata_s": meta or {},
        }
        return ("void",)

    def _run(self, cql: str):
        cql = cql.strip()
        if m := re.match(r"CREATE KEYSPACE IF NOT EXISTS (\w+)", cql):
            self.keyspaces.add(m.group(1))
            return ("void",)
        if m := re.match(
            r"CREATE TABLE IF NOT EXISTS \w+\.(\w+) .*VECTOR<FLOAT, (\d+)>", cql
        ):
            self.tables.setdefault(m.group(1), {})
            self.dims[m.group(1)] = int(m.group(2))
            return ("void",)
        if cql.startswith("CREATE CUSTOM INDEX"):
            return ("void",)
        if re.match(r"SELECT release_version FROM system\.local", cql):
            return ("rows", ["release_version"], [(W.TYPE_VARCHAR,)], [["5.0-mini"]])
        if m := re.match(
            r"SELECT table_name FROM system_schema\.tables WHERE keyspace_name = '(\w+)'",
            cql,
        ):
            rows = [[t] for t in sorted(self.tables)]
            return ("rows", ["table_name"], [(W.TYPE_VARCHAR,)], rows)
        if m := re.match(r"SELECT COUNT\(\*\) AS n FROM \w+\.(\w+)", cql):
            n = len(self.tables.get(m.group(1), {}))
            return ("rows", ["n"], [(W.TYPE_BIGINT,)], [[n]])
        if m := re.match(r"DELETE FROM \w+\.(\w+) WHERE row_id = '((?:[^']|'')*)'", cql):
            self.tables.get(m.group(1), {}).pop(_unesc(m.group(2)), None)
            return ("void",)
        if m := re.match(
            r"SELECT row_id FROM \w+\.(\w+) WHERE row_id = '((?:[^']|'')*)'", cql
        ):
            row = self.tables.get(m.group(1), {}).get(_unesc(m.group(2)))
            rows = [[row["row_id"]]] if row else []
            return ("rows", ["row_id"], [(W.TYPE_VARCHAR,)], rows)
        if "ORDER BY vector ANN OF" in cql:
            return self._ann(cql)
        if m := re.match(
            r"SELECT row_id, body_blob, metadata_s, vector FROM \w+\.(\w+) "
            r"WHERE row_id = '((?:[^']|'')*)'",
            cql,
        ):
            row = self.tables.get(m.group(1), {}).get(_unesc(m.group(2)))
            return self._doc_rows(m.group(1), [row] if row else [])
        if m := re.match(
            r"SELECT row_id, body_blob, metadata_s, vector FROM \w+\.(\w+)\s*"
            r"(?:WHERE (.*?))? LIMIT (\d+)$",
            cql,
        ):
            rows = self._filtered(m.group(1), m.group(2))
            return self._doc_rows(m.group(1), rows[: int(m.group(3))])
        raise _Unsupported(f"cannot interpret: {cql}")

    def _filtered(self, table: str, where: str | None) -> list[dict]:
        rows = list(self.tables.get(table, {}).values())
        for key, val in _where_pairs(where):
            rows = [r for r in rows if r["metadata_s"].get(key) == val]
        return rows

    def _ann(self, cql: str):
        m = re.match(
            r"SELECT row_id, body_blob, metadata_s, vector, "
            r"similarity_cosine\(vector, (\[[^\]]*\])\) AS score "
            r"FROM \w+\.(\w+)(?: WHERE (.*?))? ORDER BY vector ANN OF "
            r"(\[[^\]]*\]) LIMIT (\d+)$",
            cql,
        )
        if not m:
            raise _Unsupported(f"cannot parse ANN query: {cql}")
        qv = np.asarray(eval(m.group(1)), dtype=np.float32)  # noqa: S307 - literal list
        table, where, limit = m.group(2), m.group(3), int(m.group(5))
        rows = self._filtered(table, where)
        scored = []
        for r in rows:
            v = r["vector"]
            denom = float(np.linalg.norm(qv) * np.linalg.norm(v)) or 1e-9
            # Cassandra similarity_cosine maps cosine to [0, 1]
            score = (1.0 + float(np.dot(qv, v)) / denom) / 2.0
            scored.append((score, r))
        scored.sort(key=lambda sr: -sr[0])
        dim = self.dims.get(table, 384)
        names = ["row_id", "body_blob", "metadata_s", "vector", "score"]
        types = [
            (W.TYPE_VARCHAR,), (W.TYPE_VARCHAR,),
            ("map", (W.TYPE_VARCHAR,), (W.TYPE_VARCHAR,)),
            _vector_type(dim), (W.TYPE_FLOAT,),
        ]
        out = [
            [r["row_id"], r["body_blob"], r["metadata_s"], r["vector"], s]
            for s, r in scored[:limit]
        ]
        return ("rows", names, types, out)

    def _doc_rows(self, table: str, rows: list[dict]):
        dim = self.dims.get(table, 384)
        names = ["row_id", "body_blob", "metadata_s", "vector"]
        types = [
            (W.TYPE_VARCHAR,), (W.TYPE_VARCHAR,),
            ("map", (W.TYPE_VARCHAR,), (W.TYPE_VARCHAR,)),
            _vector_type(dim),
        ]
        out = [[r["row_id"], r["body_blob"], r["metadata_s"], r["vector"]] for r in rows]
        return ("rows", names, types, out)


class _Unsupported(Exception):
    pass


def _unesc(s: str) -> str:
    return s.replace("''", "'")


def _where_pairs(where: str | None) -> list[tuple[str, str]]:
    if not where:
        return []
    pairs = []
    for m in re.finditer(
        r"metadata_s\['((?:[^']|'')*)'\] = '((?:[^']|'')*)'", where
    ):
        pairs.append((_unesc(m.group(1)), _unesc(m.group(2))))
    return pairs


# ---- response encoding ---------------------------------------------------


def _recv_exact(sock, n: int) -> bytes | None:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            return None
        out += chunk
    return out


def _send(sock, stream: int, opcode: int, body: bytes) -> None:
    sock.sendall(
        struct.pack(">BBhBi", W.VERSION_RESP, 0, stream, opcode, len(body)) + body
    )


def _send_error(sock, stream: int, code: int, msg: str) -> None:
    _send(sock, stream, W.OP_ERROR, struct.pack(">i", code) + W._string(msg))


def _send_result(sock, stream: int, result) -> None:
    if result[0] == "void":
        _send(sock, stream, W.OP_RESULT, struct.pack(">i", W.RESULT_VOID))
        return
    _kind, names, types, rows = result
    body = struct.pack(">i", W.RESULT_ROWS)
    body += struct.pack(">ii", 0x0001, len(names))  # global_tables_spec
    body += W._string("ks") + W._string("t")
    for name, t in zip(names, types):
        body += W._string(name) + _type_option(t)
    body += struct.pack(">i", len(rows))
    for row in rows:
        for t, v in zip(types, row):
            body += W._bytes(W.encode_value(t, v))
    _send(sock, stream, W.OP_RESULT, body)
