"""dp-grouped multi-engine serving (serving/multi_engine.py): disjoint
submeshes, token-identical outputs vs a single engine, least-loaded
routing, cancel, and the OpenAI server surface over replicas.

Covers VERDICT r02 next-step #9 (the deferred round-2 idea): one server
process running MESH_SHAPE=tp:2,dp:2-style replica groups on the virtual
8-device CPU mesh.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
from githubrepostorag_tpu.parallel import MeshPlan
from githubrepostorag_tpu.serving import Engine, SamplingParams
from githubrepostorag_tpu.serving.multi_engine import MultiAsyncEngine, dp_submeshes


@pytest.fixture(scope="module")
def tiny():
    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    return cfg, params


def _engine(params, cfg, mesh=None):
    return Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                  max_seq_len=64, kv_dtype=jnp.float32, decode_burst=8,
                  mesh=mesh)


def _prompts(n):
    rng = np.random.default_rng(11)
    return [rng.integers(0, 512, 6 + i).tolist() for i in range(n)]


def test_dp_submeshes_disjoint_devices():
    meshes, groups = dp_submeshes(MeshPlan(tp=2, dp=2))
    assert len(meshes) == 2 and len(groups) == 2
    flat = [d.id for g in groups for d in g]
    assert len(flat) == len(set(flat)) == 4  # disjoint, 2 devices each
    for m in meshes:
        assert dict(m.shape)["tp"] == 2 and dict(m.shape)["dp"] == 1


def test_dp_submeshes_single_device_groups():
    """Pure-dp groups still get real 1-device meshes so each replica's
    params/pools land on ITS device, not the process default device."""
    meshes, groups = dp_submeshes(MeshPlan(dp=4))
    assert all(len(g) == 1 for g in groups)
    mesh_devices = [m.devices.reshape(-1)[0].id for m in meshes]
    assert len(set(mesh_devices)) == 4  # four distinct devices
    assert mesh_devices == [g[0].id for g in groups]


def test_dp_submeshes_rejects_oversubscription():
    with pytest.raises(ValueError, match="devices"):
        dp_submeshes(MeshPlan(tp=8, dp=2))  # 16 > 8 virtual devices


async def test_multi_engine_token_identical_and_balanced(tiny):
    cfg, params = tiny
    prompts = _prompts(4)
    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
    expected = [
        r.output_tokens for r in _engine(params, cfg).generate(prompts, sp)
    ]

    meshes, _ = dp_submeshes(MeshPlan(tp=2, dp=2))
    multi = MultiAsyncEngine([_engine(params, cfg, mesh=m) for m in meshes])
    try:
        import asyncio

        results = await asyncio.gather(
            *(multi.generate(p, sp) for p in prompts)
        )
        assert [r.output_tokens for r in results] == expected
        stats = multi.stats()
        assert stats["replicas"] == 2
        assert stats["requests_admitted"] == 4
        # 4 concurrent requests over 2 replicas of max_num_seqs=2: least-
        # loaded admission must have routed work to BOTH replicas
        admitted = [s["requests_admitted"] for s in stats["per_replica"]]
        assert all(a > 0 for a in admitted), admitted
    finally:
        await multi.stop()


async def test_multi_engine_cancel_routes_to_owner(tiny):
    cfg, params = tiny
    sp = SamplingParams(max_tokens=50, temperature=0.0, stop_token_ids=())
    meshes, _ = dp_submeshes(MeshPlan(dp=2))
    multi = MultiAsyncEngine([_engine(params, cfg, mesh=m) for m in meshes])
    try:
        got_tokens = 0
        async for event in multi.stream(_prompts(1)[0], sp, request_id="kill-me"):
            if event.type == "token":
                got_tokens += 1
                await multi.cancel("kill-me")
            if event.type == "final":
                assert event.result.finish_reason == "cancelled"
                break
        assert got_tokens >= 1
    finally:
        await multi.stop()


async def test_openai_server_over_replicas(tiny):
    """The OpenAI surface works unchanged over MultiAsyncEngine (the
    duck-type contract __main__.py relies on for MESH_SHAPE dp>1)."""
    import asyncio
    import json
    import urllib.request

    from githubrepostorag_tpu.serving.openai_api import OpenAIServer
    from githubrepostorag_tpu.serving.tokenizer import ByteTokenizer

    cfg, params = tiny
    meshes, _ = dp_submeshes(MeshPlan(dp=2))
    multi = MultiAsyncEngine([_engine(params, cfg, mesh=m) for m in meshes])
    server = OpenAIServer(multi, ByteTokenizer(), model_name="tiny-dp")
    port = await server.start(host="127.0.0.1", port=0)
    loop = asyncio.get_running_loop()

    def post(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/chat/completions",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read().decode())

    body = {"model": "tiny-dp", "max_tokens": 4, "temperature": 0,
            "messages": [{"role": "user", "content": "hi"}]}
    out1, out2 = await asyncio.gather(
        loop.run_in_executor(None, post, body),
        loop.run_in_executor(None, post, body),
    )
    assert out1["usage"]["completion_tokens"] == 4
    # same prompt, greedy, replicated weights -> identical replies from
    # whichever replica served each request
    assert out1["choices"][0]["message"]["content"] == \
        out2["choices"][0]["message"]["content"]
    await server.stop()


def test_stats_merge_sums_counters_and_means_rates():
    """Merge-rule regression: counters SUM across replicas, but rate/ratio/
    utilization-suffixed keys merge by MEAN — two replicas at 0.8
    acceptance are at 0.8, not 1.6."""

    class Stub:
        def __init__(self, s):
            self._s = s

        def stats(self):
            return self._s

    multi = MultiAsyncEngine.__new__(MultiAsyncEngine)
    multi._engines = [
        Stub({"requests_admitted": 3, "spec_acceptance_rate": 0.8,
              "kv_utilization": 0.5, "spec_fallbacks": 1}),
        Stub({"requests_admitted": 1, "spec_acceptance_rate": 0.4,
              "kv_utilization": 0.1, "spec_fallbacks": 0}),
    ]
    merged = MultiAsyncEngine.stats(multi)
    assert merged["requests_admitted"] == 4  # counter: summed
    assert merged["spec_acceptance_rate"] == pytest.approx(0.6)  # rate: mean
    assert merged["kv_utilization"] == pytest.approx(0.3)
    assert merged["spec_fallbacks"] == 1  # plain counter, still summed
    assert merged["replicas"] == 2


async def test_multi_engine_propagates_deadline(tiny):
    """Regression: stream()/generate() must accept and forward deadline_s.
    Before the fix the facade lacked the keyword, so llm.py's always-passed
    deadline_s= raised TypeError under dp>1 (swallowed into an error
    completion) and deadline reaping never engaged on replica groups."""
    import time

    cfg, params = tiny
    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
    meshes, _ = dp_submeshes(MeshPlan(dp=2))
    multi = MultiAsyncEngine([_engine(params, cfg, mesh=m) for m in meshes])
    try:
        ok = await multi.generate(_prompts(1)[0], sp,
                                  deadline_s=time.monotonic() + 60.0)
        assert ok.finish_reason in ("length", "stop")
        assert len(ok.output_tokens) == 8

        reaped = await multi.generate(_prompts(1)[0], sp,
                                      deadline_s=time.monotonic() - 0.001)
        assert reaped.finish_reason == "deadline"
    finally:
        await multi.stop()
