"""Randomized scheduling fuzz over the engine's combined features.

The engine now composes continuous batching, co-dispatched mixed
prefill+decode, pipelined bursts, prefix caching, cancellation, and
(optionally) speculative decoding.  This test drives hundreds of random
scheduling decisions — admissions with shared/unshared prompts at random
times, cancels, varied lengths — against engines in several configurations
and checks the global invariants after every episode:

  - every request finishes with a sane reason,
  - every greedy request's output is byte-identical to a solo run of the
    same prompt on a fresh engine (scheduling must never change tokens),
  - the allocator ends balanced (free_count == num_pages, nothing leaked),
  - the engine ends idle (no stuck rows/waves/chains).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from githubrepostorag_tpu.serving import Engine, SamplingParams

transformers = pytest.importorskip("transformers")
import torch  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    from githubrepostorag_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    hf_cfg = transformers.Qwen2Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg.to_dict())
    params = params_from_state_dict(model.state_dict(), cfg)
    return params, cfg


CONFIGS = [
    dict(),  # bursts + prefix caching (defaults)
    dict(prefix_caching=False),
    dict(spec_ngram_k=3),
    dict(decode_burst=1),  # per-token stepping
    dict(prefill_widths=3),  # width-bucketed prefill dispatches
]


@pytest.mark.parametrize(
    "extra", CONFIGS, ids=["default", "nocache", "spec", "burst1", "widths"]
)
def test_random_schedule_episode(tiny, extra):
    params, cfg = tiny
    import zlib

    # deterministic per-config seed: a failing episode must replay exactly
    rng = np.random.default_rng(zlib.crc32(repr(sorted(extra.items())).encode()))

    def make():
        return Engine(params, cfg, max_num_seqs=4, num_pages=48, page_size=8,
                      max_seq_len=128, prefill_chunk=16, kv_dtype=jnp.float32,
                      decode_burst=extra.get("decode_burst", 4), **{
                          k: v for k, v in extra.items() if k != "decode_burst"
                      })

    # a small pool of prompts, some sharing prefixes (prefix-cache traffic)
    base = rng.integers(0, cfg.vocab_size, 40).tolist()
    prompts = [
        base[:24],
        base[:24] + rng.integers(0, cfg.vocab_size, 9).tolist(),
        rng.integers(0, cfg.vocab_size, 37).tolist(),
        [7, 8, 9, 10] * 7,  # loops: speculative-friendly
        rng.integers(0, cfg.vocab_size, 5).tolist(),
    ]
    solo_cache: dict[tuple[int, int], list[int]] = {}

    def solo(pi: int, max_tokens: int) -> list[int]:
        key = (pi, max_tokens)
        if key not in solo_cache:
            solo_cache[key] = make().generate(
                [prompts[pi]],
                SamplingParams(max_tokens=max_tokens, temperature=0.0,
                               stop_token_ids=()),
            )[0].output_tokens
        return solo_cache[key]

    eng = make()
    episode = []  # (request_id, prompt_idx, max_tokens, cancelled)
    live: dict[str, tuple[int, int]] = {}
    done: dict[str, object] = {}
    steps = 0
    while steps < 400 and (eng.has_work() or len(episode) < 14):
        action = rng.random()
        if len(episode) < 14 and (action < 0.35 or not eng.has_work()):
            pi = int(rng.integers(0, len(prompts)))
            mt = int(rng.integers(3, 14))
            rid = eng.add_request(
                prompts[pi],
                SamplingParams(max_tokens=mt, temperature=0.0, stop_token_ids=()),
            )
            episode.append([rid, pi, mt, False])
            live[rid] = (pi, mt)
        elif action < 0.40 and live:
            rid = list(live)[int(rng.integers(0, len(live)))]
            eng.cancel(rid)
            for e in episode:
                if e[0] == rid:
                    e[3] = True
        for res in eng.step():
            done[res.request_id] = res
            live.pop(res.request_id, None)
        steps += 1
    assert not eng.has_work(), "engine stuck with work after 400 steps"

    for rid, pi, mt, cancelled in episode:
        res = done[rid]
        if cancelled and res.finish_reason == "cancelled":
            continue  # a cancel that landed before completion
        assert res.finish_reason == "length", (rid, res.finish_reason)
        assert res.output_tokens == solo(pi, mt), (
            f"{rid} (prompt {pi}, max_tokens {mt}) diverged from its solo run"
        )

    # nothing leaked: allocator balanced, no stranded state
    assert eng._allocator.free_count == eng._allocator.num_pages
    assert not eng._row_req and not eng._waiting
    assert eng._chain is None and not eng._pending_first and not eng._deferred


@pytest.mark.parametrize("extra", [
    dict(spec_ngram_k=3),  # speculative path
    dict(prefill_widths=3),  # plain bursts: the mixed top_p traffic flips
    # the filter_sampling burst variant between bursts, over width-bucketed
    # prefill dispatches
], ids=["spec", "burst-widths"])
def test_random_schedule_sampled_invariants(tiny, extra):
    """Sampled traffic (temperature > 0, top-p, penalties) under random
    scheduling: outputs are seed-dependent, so only the structural
    invariants are asserted — everything finishes, lengths are sane, and
    nothing leaks."""
    params, cfg = tiny
    rng = np.random.default_rng(99)
    eng = Engine(params, cfg, max_num_seqs=4, num_pages=48, page_size=8,
                 max_seq_len=128, prefill_chunk=16, kv_dtype=jnp.float32,
                 decode_burst=4, **extra)
    want: dict[str, int] = {}
    done: dict[str, object] = {}
    steps = 0
    while steps < 400 and (eng.has_work() or len(want) < 12):
        if len(want) < 12 and (rng.random() < 0.4 or not eng.has_work()):
            mt = int(rng.integers(3, 12))
            rid = eng.add_request(
                rng.integers(0, cfg.vocab_size, int(rng.integers(4, 40))).tolist(),
                SamplingParams(
                    max_tokens=mt,
                    temperature=float(rng.choice([0.0, 0.7, 1.1])),
                    top_p=float(rng.choice([0.8, 0.95, 1.0])),
                    repetition_penalty=float(rng.choice([1.0, 1.2])),
                    stop_token_ids=(),
                ),
            )
            want[rid] = mt
        for res in eng.step():
            done[res.request_id] = res
        steps += 1
    assert not eng.has_work()
    for rid, mt in want.items():
        res = done[rid]
        assert res.finish_reason == "length"
        assert len(res.output_tokens) == mt
        assert all(0 <= t < cfg.vocab_size for t in res.output_tokens)
    assert eng._allocator.free_count == eng._allocator.num_pages
    assert eng._chain is None and not eng._pending_first and not eng._deferred
