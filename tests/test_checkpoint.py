"""Orbax sharded checkpoint round-trip on the virtual CPU mesh."""

import numpy as np

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
from githubrepostorag_tpu.parallel import MeshPlan, make_mesh, qwen2_param_specs, shard_params
from githubrepostorag_tpu.training.checkpoint import load_checkpoint, save_checkpoint


def test_sharded_params_roundtrip_with_shardings(tmp_path):
    cfg = Qwen2Config.tiny()
    mesh = make_mesh(MeshPlan(dp=2, tp=2, sp=2))
    params = shard_params(
        init_params(cfg, jax.random.PRNGKey(0)), mesh, qwen2_param_specs(cfg, mesh)
    )
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)

    restored = load_checkpoint(path, template=params)
    ref_leaves = jax.tree.leaves(params)
    new_leaves = jax.tree.leaves(restored)
    assert len(ref_leaves) == len(new_leaves)
    for a, b in zip(ref_leaves, new_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert b.sharding == a.sharding  # placement survives the round trip


def test_restore_without_template(tmp_path):
    tree = {"w": jnp.arange(8, dtype=jnp.float32), "step": jnp.asarray(3)}
    path = str(tmp_path / "plain")
    save_checkpoint(path, tree)
    out = load_checkpoint(path)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.arange(8, dtype=np.float32))
    assert int(out["step"]) == 3
