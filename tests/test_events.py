"""Event bus: wire-format parity with the reference's Redis pub/sub bus
(rag_shared/bus.py) plus the replay-buffer improvement and the job queue."""

import asyncio
import json

import pytest

from githubrepostorag_tpu.events import (
    MemoryBus,
    MemoryCancelFlags,
    MemoryJobQueue,
    PING_FRAME,
)


async def _collect(bus, job_id, n_frames, timeout=5.0):
    out = []

    async def consume():
        async for frame in bus.stream(job_id):
            out.append(frame)
            if len([f for f in out if f.startswith("data:")]) >= n_frames:
                return

    await asyncio.wait_for(consume(), timeout)
    return out


async def test_emit_then_stream_sees_replayed_event():
    bus = MemoryBus(ping_interval=0.05)
    await bus.emit("j1", "started", {"job_id": "j1"})
    frames = await _collect(bus, "j1", 1)
    datas = [f for f in frames if f.startswith("data:")]
    payload = json.loads(datas[0][len("data: "):].strip())
    assert payload == {"event": "started", "data": {"job_id": "j1"}}


async def test_live_emit_reaches_subscriber():
    bus = MemoryBus(ping_interval=0.05)

    async def emitter():
        await asyncio.sleep(0.05)
        await bus.emit("j2", "final", {"answer": "42"})

    task = asyncio.create_task(emitter())
    frames = await _collect(bus, "j2", 1)
    await task
    assert any('"final"' in f for f in frames)


async def test_ping_frames_flow_when_idle():
    bus = MemoryBus(ping_interval=0.01)
    gen = bus.stream("j3")
    frame = await asyncio.wait_for(gen.__anext__(), 1.0)
    assert frame == PING_FRAME
    await gen.aclose()


async def test_sse_frame_format():
    bus = MemoryBus(ping_interval=0.05)
    await bus.emit("j4", "turn", {"stage": "retrieve"})
    frames = await _collect(bus, "j4", 1)
    data = [f for f in frames if f.startswith("data:")][0]
    assert data.endswith("\n\n")


async def test_cancel_flags_roundtrip():
    flags = MemoryCancelFlags()
    assert not await flags.is_cancelled("jx")
    await flags.cancel("jx")
    assert await flags.is_cancelled("jx")
    assert not await flags.is_cancelled("other")


async def test_job_queue_fifo_and_results():
    q = MemoryJobQueue()
    j1 = await q.enqueue_job("run_rag_job", "j-1", {"query": "q"}, _job_id="j-1")
    await q.enqueue_job("run_rag_job", "j-2", {"query": "r"}, _job_id="j-2")
    assert j1.job_id == "j-1"
    first = await q.dequeue()
    second = await q.dequeue()
    assert first.job_id == "j-1" and second.job_id == "j-2"
    assert first.function == "run_rag_job"
    await q.set_result("j-1", {"answer": "a"})
    assert await q.get_result("j-1") == {"answer": "a"}
    assert await q.get_result("missing") is None


async def test_multiple_subscribers_both_receive():
    bus = MemoryBus(ping_interval=0.05)
    r1 = asyncio.create_task(_collect(bus, "j5", 1))
    r2 = asyncio.create_task(_collect(bus, "j5", 1))
    await asyncio.sleep(0.05)
    await bus.emit("j5", "iteration", {"n": 1})
    f1, f2 = await asyncio.gather(r1, r2)
    assert any("iteration" in f for f in f1)
    assert any("iteration" in f for f in f2)
