"""Chaos suite for the resilience layer: the fault-injection registry, the
retry/breaker/deadline primitives, and full enqueue -> agent -> SSE jobs
driven through MemoryEvents and miniredis under injected faults.  The
invariants under test are the tentpole's acceptance bar: every job reaches a
terminal event, nothing hangs past its deadline, and a deadline-reaped
engine request returns every KV page it held."""

from __future__ import annotations

import asyncio
import json
import time

import pytest

from githubrepostorag_tpu.agent import GraphAgent
from githubrepostorag_tpu.config import reload_settings
from githubrepostorag_tpu.embedding import HashingTextEncoder
from githubrepostorag_tpu.events import MemoryBus, MemoryCancelFlags, MemoryJobQueue
from githubrepostorag_tpu.events.base import ProgressBus, channel_for
from githubrepostorag_tpu.llm import FakeLLM
from githubrepostorag_tpu.metrics import (
    BUS_RECONNECTS,
    CTRL_ACTIONS,
    EVENT_EMIT_DROPS,
    FAULTS_INJECTED,
    JOBS_SHED,
    WORKER_DEQUEUE_ERRORS,
    counter_value,
)
from githubrepostorag_tpu.resilience.faults import (
    FaultSpecError,
    InjectedFault,
    _parse_entry,
    active,
    fire_sync,
    get_registry,
    reset_faults,
)
from githubrepostorag_tpu.resilience.policy import (
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    breaker_states,
    current_deadline,
    deadline_scope,
    get_breaker,
)
from githubrepostorag_tpu.resilience.supervise import ResilientBus
from githubrepostorag_tpu.retrieval import RetrieverFactory
from githubrepostorag_tpu.store import Doc, MemoryVectorStore
from githubrepostorag_tpu.worker import RagWorker

from tests.test_api_worker import AGENT_SCRIPT, _collect_events, _with_service


def _enable(monkeypatch, spec: str, seed: int = 0, **env: str) -> None:
    """Point FAULTS at ``spec`` and rebuild the registry from env."""
    monkeypatch.setenv("FAULTS", spec)
    monkeypatch.setenv("FAULTS_SEED", str(seed))
    for key, value in env.items():
        monkeypatch.setenv(key, value)
    reload_settings()
    reset_faults()


# ------------------------------------------------------------ fault registry


def test_fault_spec_parses_sites_actions_and_params(monkeypatch):
    _enable(monkeypatch, "redis.send:drop@3;cql.exchange:error@0.5;llm.complete:delay=2")
    reg = get_registry()
    assert set(reg.by_site) == {"redis.send", "cql.exchange", "llm.complete"}
    assert reg.by_site["redis.send"][0].action == "drop"
    assert reg.by_site["redis.send"][0].every == 3
    assert reg.by_site["cql.exchange"][0].probability == 0.5
    assert reg.by_site["llm.complete"][0].delay_s == 2.0
    assert active()


def test_drop_every_nth_is_deterministic(monkeypatch):
    _enable(monkeypatch, "x.site:drop@3")
    fired = [fire_sync("x.site") for _ in range(9)]
    assert fired == [False, False, True, False, False, True, False, False, True]
    assert counter_value(FAULTS_INJECTED, site="x.site", action="drop") >= 3


def test_probability_faults_are_seeded(monkeypatch):
    def pattern() -> list[bool]:
        out = []
        for _ in range(40):
            try:
                fire_sync("y.site")
                out.append(False)
            except InjectedFault:
                out.append(True)
        return out

    _enable(monkeypatch, "y.site:error@0.5", seed=123)
    first = pattern()
    reset_faults()  # re-parse: same seed must reproduce the same draws
    assert pattern() == first
    assert any(first) and not all(first)  # p=0.5 over 40 calls


def test_malformed_specs_raise_at_parse():
    for bad in ("nosite", "x:frobnicate", "x:delay", "x:drop@0", "x:drop@1.5",
                "x:drop=3", ":drop", "x:"):
        with pytest.raises(FaultSpecError):
            _parse_entry(bad, seed=0)


def test_window_fault_fires_only_inside_the_window(monkeypatch):
    """``@window=N:M`` scripts "healthy, then dies, then recovers" at one
    site: calls 3..5 fire, everything before and after passes clean."""
    _enable(monkeypatch, "w.site:drop@window=3:5")
    fired = [fire_sync("w.site") for _ in range(7)]
    assert fired == [False, False, True, True, True, False, False]
    assert counter_value(FAULTS_INJECTED, site="w.site", action="drop") >= 3


def test_open_ended_window_kills_permanently(monkeypatch):
    """``@window=N:`` (no upper bound) models a replica that dies at call
    N and never comes back — the controller chaos e2e's kill switch."""
    _enable(monkeypatch, "w.site:error@window=2:")
    assert fire_sync("w.site") is False
    for _ in range(3):
        with pytest.raises(InjectedFault):
            fire_sync("w.site")


def test_window_composes_with_delay_value(monkeypatch):
    _enable(monkeypatch, "w.site:delay=0.05@window=2:2")
    t0 = time.monotonic()
    assert fire_sync("w.site") is False  # call 1: outside, no sleep
    assert time.monotonic() - t0 < 0.04
    t0 = time.monotonic()
    assert fire_sync("w.site") is False  # call 2: delay fires, then proceeds
    assert time.monotonic() - t0 >= 0.04
    assert fire_sync("w.site") is False  # call 3: outside again


def test_window_parse_errors():
    for bad in ("x:drop@window=", "x:drop@window=3", "x:drop@window=0:2",
                "x:drop@window=5:3", "x:drop@window=a:b",
                "x:drop@window=1.5:2"):
        with pytest.raises(FaultSpecError):
            _parse_entry(bad, seed=0)


def test_unset_faults_is_inert():
    assert not active()
    assert fire_sync("redis.send") is False
    assert get_registry().by_site == {}


def test_delay_fault_sleeps(monkeypatch):
    _enable(monkeypatch, "z.site:delay=0.05")
    t0 = time.monotonic()
    assert fire_sync("z.site") is False  # delay proceeds after sleeping
    assert time.monotonic() - t0 >= 0.04


# -------------------------------------------------------------- retry policy


def test_retry_delays_are_bounded_full_jitter():
    policy = RetryPolicy(max_attempts=5, base=0.1, cap=1.0, seed=7)
    for attempt in range(6):
        d = min(1.0, 0.1 * 2 ** attempt)
        delay = policy.delay_for(attempt)
        assert d / 2 <= delay <= d
    # seeded stream reproduces
    a = list(RetryPolicy(max_attempts=4, base=0.1, seed=1).delays())
    b = list(RetryPolicy(max_attempts=4, base=0.1, seed=1).delays())
    assert a == b and len(a) == 3


async def test_retry_call_retries_connection_errors_then_succeeds():
    calls = []

    async def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("blip")
        return 7

    policy = RetryPolicy(max_attempts=4, base=0.001, seed=0)
    assert await policy.call(flaky) == 7
    assert len(calls) == 3


async def test_retry_call_exhausts_and_propagates():
    async def dead():
        raise ConnectionError("hard down")

    policy = RetryPolicy(max_attempts=3, base=0.001, seed=0)
    with pytest.raises(ConnectionError, match="hard down"):
        await policy.call(dead)


async def test_retry_call_does_not_retry_non_connection_errors():
    calls = []

    async def broken():
        calls.append(1)
        raise ValueError("logic bug, not an outage")

    with pytest.raises(ValueError):
        await RetryPolicy(max_attempts=4, base=0.001).call(broken)
    assert len(calls) == 1


# ------------------------------------------------------------ circuit breaker


def test_breaker_opens_half_opens_and_closes():
    b = CircuitBreaker("dep", failure_threshold=3, reset_seconds=0.1)
    assert b.allow() and b.state == "closed"
    for _ in range(3):
        b.record_failure()
    assert b.state == "open"
    assert not b.allow()  # refused while open
    time.sleep(0.12)
    assert b.allow()  # reset window elapsed: the single half-open probe
    assert b.state == "half_open"
    assert not b.allow()  # second concurrent probe refused
    b.record_success()
    assert b.state == "closed" and b.allow()
    snap = b.snapshot()
    assert snap["transitions"] == {"open": 1, "half_open": 1, "closed": 1}


def test_breaker_probe_failure_reopens():
    b = CircuitBreaker("dep2", failure_threshold=1, reset_seconds=0.05)
    b.record_failure()
    assert b.state == "open"
    time.sleep(0.06)
    assert b.allow()
    b.record_failure()  # probe failed: straight back to open
    assert b.state == "open"
    assert b.snapshot()["transitions"]["open"] == 2


def test_breaker_registry_reports_states():
    b = get_breaker("llm.http", failure_threshold=1)
    assert get_breaker("llm.http") is b
    b.record_failure()
    states = breaker_states()
    assert states["llm.http"]["state"] == "open"


# ------------------------------------------------------------------ deadline


def test_deadline_budget_and_expiry():
    d = Deadline(0.05)
    assert not d.expired and 0 < d.remaining() <= 0.05
    time.sleep(0.06)
    assert d.expired and d.remaining() == 0.0


def test_deadline_wire_roundtrip_preserves_budget():
    d = Deadline(5.0)
    d2 = Deadline.from_wire(d.to_wire())
    assert abs(d2.remaining() - d.remaining()) < 0.1


def test_deadline_scope_is_thread_local():
    assert current_deadline() is None
    d = Deadline(1.0)
    with deadline_scope(d):
        assert current_deadline() is d
        with deadline_scope(None):
            assert current_deadline() is None
        assert current_deadline() is d
    assert current_deadline() is None


# --------------------------------------------------------------- supervised bus


class _FlakyInner(ProgressBus):
    """Fails the first ``fail_n`` emits with ConnectionError, then records."""

    def __init__(self, fail_n: int) -> None:
        self.fail_n = fail_n
        self.calls = 0
        self.delivered: list[tuple[str, str]] = []

    async def emit(self, job_id, event, data):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise ConnectionError("bus blip")
        self.delivered.append((job_id, event))

    async def stream(self, job_id):  # pragma: no cover - unused
        yield ""

    async def close(self):
        pass


async def test_resilient_bus_absorbs_transient_failures(monkeypatch):
    monkeypatch.setenv("RETRY_BASE_SECONDS", "0.005")
    reload_settings()
    inner = _FlakyInner(fail_n=2)
    before = counter_value(EVENT_EMIT_DROPS, event="turn")
    await ResilientBus(inner).emit("j", "turn", {})
    assert inner.delivered == [("j", "turn")]
    assert counter_value(EVENT_EMIT_DROPS, event="turn") == before


async def test_resilient_bus_terminal_events_get_deeper_budget(monkeypatch):
    monkeypatch.setenv("RETRY_BASE_SECONDS", "0.005")
    reload_settings()
    # 5 failures: past the default 4-attempt progress budget, inside the
    # >= 6-attempt terminal budget
    dropped = _FlakyInner(fail_n=5)
    before = counter_value(EVENT_EMIT_DROPS, event="turn")
    await ResilientBus(dropped).emit("j", "turn", {})
    assert dropped.delivered == []  # progress chatter: dropped, counted
    assert counter_value(EVENT_EMIT_DROPS, event="turn") == before + 1

    delivered = _FlakyInner(fail_n=5)
    await ResilientBus(delivered).emit("j", "final", {"answer": "x"})
    assert delivered.delivered == [("j", "final")]  # terminal: survives


async def test_resilient_bus_open_breaker_sheds_without_calling_inner():
    get_breaker("bus", failure_threshold=1).record_failure()  # force open
    inner = _FlakyInner(fail_n=0)
    before = counter_value(EVENT_EMIT_DROPS, event="iteration")
    await ResilientBus(inner).emit("j", "iteration", {})
    assert inner.calls == 0  # fast-path drop: dependency never touched
    assert counter_value(EVENT_EMIT_DROPS, event="iteration") == before + 1


# ------------------------------------------------- worker dequeue supervision


class _FlakyQueue(MemoryJobQueue):
    def __init__(self, fail_n: int) -> None:
        super().__init__()
        self.failures_left = fail_n

    async def dequeue(self):
        if self.failures_left > 0:
            self.failures_left -= 1
            raise ConnectionError("injected dequeue failure")
        return await super().dequeue()


def _agent() -> GraphAgent:
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    text = "async def create_job(request): enqueue and return job id"
    store.upsert("embeddings", [Doc(
        "c1", text,
        {"namespace": "default", "scope": "chunk", "repo": "api",
         "module": "app", "file_path": "app/jobs.py"},
        enc.encode([text])[0],
    )])
    return GraphAgent(FakeLLM(script=AGENT_SCRIPT), RetrieverFactory(store, enc),
                      namespace="default")


async def test_worker_survives_flaky_dequeue(monkeypatch):
    """Satellite 1 regression: a queue.dequeue() raise used to kill
    run_forever silently — jobs then queued forever with live SSE clients
    attached.  Now: counted, backed off, survived."""
    monkeypatch.setenv("RETRY_BASE_SECONDS", "0.005")
    reload_settings()
    queue = _FlakyQueue(fail_n=3)
    worker = RagWorker(_agent(), MemoryBus(), MemoryCancelFlags(), queue,
                       max_jobs=2, job_timeout=10)
    before = counter_value(WORKER_DEQUEUE_ERRORS)
    task = asyncio.create_task(worker.run_forever())
    try:
        await queue.enqueue_job("run_rag_job", "fj", {"query": "q"}, _job_id="fj")
        result = None
        for _ in range(400):
            result = await queue.get_result("fj")
            if result is not None:
                break
            await asyncio.sleep(0.025)
        assert result is not None and result.get("answer")
        assert counter_value(WORKER_DEQUEUE_ERRORS) - before == 3
    finally:
        worker.stop()
        task.cancel()


# ----------------------------------------------------- end-to-end: memory hub


async def test_memory_stack_chaos_every_job_reaches_final(monkeypatch):
    """Full enqueue -> agent -> SSE with every 3rd bus emit failing and the
    LLM lagging: the supervised emit path must absorb the faults so every
    job still delivers its complete, correct event sequence."""
    _enable(monkeypatch, "bus.emit:drop@3;llm.complete:delay=0.01",
            RETRY_BASE_SECONDS="0.005")

    async def body(session, base, api, worker):
        ids = []
        for i in range(3):
            resp = await session.post(f"{base}/rag/jobs",
                                      json={"query": f"how are jobs created? v{i}"})
            assert resp.status == 200
            ids.append((await resp.json())["job_id"])
        results = await asyncio.wait_for(
            asyncio.gather(*(_collect_events(session, base, j) for j in ids)),
            timeout=30,
        )
        for events in results:
            # progress chatter may be legitimately dropped (counted) under
            # sustained faults; the guarantee is the terminal event and a
            # correct answer, not a complete transcript
            assert events[-1]["event"] == "final"
            assert events[-1]["data"]["answer"]
        stats = get_registry().stats()
        assert sum(e["fired"] for e in stats["bus.emit"]) >= 1
        assert sum(e["fired"] for e in stats["llm.complete"]) >= 1

    await _with_service(body)


async def test_deadline_ms_expires_job_to_terminal_error(monkeypatch):
    """deadline_ms travels API -> queue -> worker -> agent: a budget the slow
    LLM cannot meet must surface as a terminal error+final pair well before
    the 30s job timeout, never a hang."""

    class SlowLLM(FakeLLM):
        def complete(self, prompt, **kw):
            time.sleep(0.25)
            return super().complete(prompt, **kw)

    slow = SlowLLM(script={
        r"Pick the retrieval scope": '{"scope": "chunk", "filters": {}}',
        r"Assess whether the retrieved": '{"coverage": 0.2, "needs_more": true}',
        r"Rephrase": "retry query",
        r"alternative search": '["alt"]',
        r"senior engineer": "too late to matter",
    })

    async def body(session, base, api, worker):
        t0 = time.monotonic()
        resp = await session.post(f"{base}/rag/jobs",
                                  json={"query": "slow question", "deadline_ms": 400})
        assert resp.status == 200
        job_id = (await resp.json())["job_id"]
        events = await asyncio.wait_for(
            _collect_events(session, base, job_id), timeout=15)
        elapsed = time.monotonic() - t0
        # the error frame is terminal for SSE clients (the stream closes on
        # it); the paired empty final still reaches pollers via the bus
        assert events[-1]["event"] == "error"
        assert "deadline" in events[-1]["data"]["error"]
        assert elapsed < 10  # budget + slack, nowhere near job_timeout

    await _with_service(slow_llm=slow, fn=body)


async def test_invalid_deadline_ms_rejected():
    async def body(session, base, api, worker):
        resp = await session.post(f"{base}/rag/jobs",
                                  json={"query": "q", "deadline_ms": -5})
        assert resp.status == 400
        assert "deadline_ms" in (await resp.json())["error"]

    await _with_service(body)


async def test_full_queue_sheds_with_429_and_retry_after(monkeypatch):
    monkeypatch.setenv("JOB_QUEUE_MAX_DEPTH", "0")
    reload_settings()

    async def body(session, base, api, worker):
        before = counter_value(JOBS_SHED)
        resp = await session.post(f"{base}/rag/jobs", json={"query": "q"})
        assert resp.status == 429
        assert "Retry-After" in resp.headers
        assert int(resp.headers["Retry-After"]) >= 1
        assert "full" in (await resp.json())["error"]
        assert counter_value(JOBS_SHED) - before == 1

    await _with_service(body)


# ---------------------------------------------------------------- SSE hygiene


class _StalledBus(ProgressBus):
    """Says nothing for a while, then one final frame — an agent thinking."""

    async def emit(self, job_id, event, data):  # pragma: no cover - unused
        pass

    async def stream(self, job_id):
        await asyncio.sleep(0.25)
        yield 'data: {"event": "final", "data": {"answer": "late"}}\n\n'

    async def close(self):
        pass


class _DyingBus(ProgressBus):
    """One frame, then a non-connection failure inside the stream."""

    async def emit(self, job_id, event, data):  # pragma: no cover - unused
        pass

    async def stream(self, job_id):
        yield 'data: {"event": "started", "data": {}}\n\n'
        raise RuntimeError("decode exploded")

    async def close(self):
        pass


async def _raw_sse(bus, heartbeat_env: str) -> bytes:
    import aiohttp

    from githubrepostorag_tpu.api.app import RagApi

    api = RagApi(bus, MemoryCancelFlags(), MemoryJobQueue())
    port = await api.start(host="127.0.0.1", port=0)
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{port}/rag/jobs/j1/events",
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                return await resp.content.read()
    finally:
        await api.stop()


async def test_sse_heartbeats_flow_while_bus_is_silent(monkeypatch):
    monkeypatch.setenv("SSE_HEARTBEAT_SECONDS", "0.05")
    reload_settings()
    raw = await _raw_sse(_StalledBus(), "0.05")
    assert raw.count(b": heartbeat\n\n") >= 2  # 0.25s gap / 0.05s beat
    assert b'"event": "final"' in raw


async def test_sse_bus_failure_sends_error_frame_and_closes(monkeypatch):
    monkeypatch.setenv("SSE_HEARTBEAT_SECONDS", "5")
    reload_settings()
    raw = await _raw_sse(_DyingBus(), "5")
    assert b'"event": "started"' in raw
    assert b"event stream failed" in raw  # the error frame, then EOF
    assert raw.rstrip().endswith(b"}")


# -------------------------------------------------------------------- health


async def test_health_503_while_a_breaker_is_open():
    async def body(session, base, api, worker):
        healthy = await session.get(f"{base}/health")
        assert healthy.status == 200
        payload = await healthy.json()
        res = payload["components"]["resilience"]
        assert res["status"] == "UP"
        assert "queue_depth" in res["details"]
        assert isinstance(res["details"]["jobs_in_flight"], int)

        b = get_breaker("llm.http", failure_threshold=2, reset_seconds=60)
        b.record_failure()
        b.record_failure()
        resp = await session.get(f"{base}/health")
        assert resp.status == 503
        payload = await resp.json()
        assert payload["status"] == "DOWN"
        res = payload["components"]["resilience"]
        assert res["status"] == "DOWN"
        assert res["details"]["breakers"]["llm.http"]["state"] == "open"

    await _with_service(body)


# -------------------------------------------------- end-to-end: redis (mini)


async def test_redis_stream_reconnects_after_connection_loss(monkeypatch):
    """Reconnect-with-backoff supervision: killing the server side of the
    SUBSCRIBE connection must re-subscribe (counted) and resume delivery."""
    from githubrepostorag_tpu.events.redis import RedisBus
    from tests.miniredis import MiniRedis

    monkeypatch.setenv("RETRY_BASE_SECONDS", "0.01")
    reload_settings()
    server = MiniRedis()
    port = await server.start()
    bus = RedisBus(f"redis://127.0.0.1:{port}/0", ping_interval=0.1)
    channel = channel_for("jr")
    frames: list[str] = []
    done = asyncio.Event()

    async def subscriber():
        async for f in bus.stream("jr"):
            if f.startswith("data:"):
                frames.append(f)
                if len(frames) >= 2:
                    done.set()
                    return

    task = asyncio.create_task(subscriber())
    try:
        for _ in range(300):
            if server.subscribers.get(channel):
                break
            await asyncio.sleep(0.01)
        await bus.emit("jr", "turn", {"n": 1})
        for _ in range(300):
            if frames:
                break
            await asyncio.sleep(0.01)
        assert frames, "first event never arrived"

        before = counter_value(BUS_RECONNECTS)
        for w in list(server.subscribers.get(channel, [])):
            w.close()  # server-side kill: LB reap / redis restart
        server.subscribers[channel].clear()
        for _ in range(500):  # wait for the re-subscribe to land
            if server.subscribers.get(channel):
                break
            await asyncio.sleep(0.01)
        assert server.subscribers.get(channel), "client never re-subscribed"
        assert counter_value(BUS_RECONNECTS) - before >= 1

        await bus.emit("jr", "final", {"answer": "hi"})
        await asyncio.wait_for(done.wait(), timeout=5)
        assert '"final"' in frames[-1]
    finally:
        task.cancel()
        await bus.close()
        await server.stop()


async def test_redis_stack_chaos_job_reaches_terminal(monkeypatch):
    """The miniredis leg of the tentpole chaos bar: with every 5th RESP send
    dropped (dequeue, publish, flag polls, result writes all share the seam)
    a job must still reach a terminal event — degraded is fine, hung is not."""
    from githubrepostorag_tpu.events.redis import RedisBus, RedisCancelFlags, RedisJobQueue
    from tests.miniredis import MiniRedis

    _enable(monkeypatch, "redis.send:drop@5", seed=3, RETRY_BASE_SECONDS="0.01")
    server = MiniRedis()
    port = await server.start()
    url = f"redis://127.0.0.1:{port}/0"
    bus = RedisBus(url, ping_interval=0.1)
    worker = RagWorker(_agent(), bus, RedisCancelFlags(url), RedisJobQueue(url),
                       max_jobs=2, job_timeout=10)
    queue = RedisJobQueue(url)  # test's own handle, separate connections
    channel = channel_for("cj")
    events: list[dict] = []
    terminal = asyncio.Event()

    async def subscriber():
        async for f in bus.stream("cj"):
            if f.startswith("data:"):
                events.append(json.loads(f[len("data:"):].strip()))
                if events[-1]["event"] == "final":
                    terminal.set()
                    return

    sub = asyncio.create_task(subscriber())
    wtask = asyncio.create_task(worker.run_forever())
    try:
        for _ in range(500):
            if server.subscribers.get(channel):
                break
            await asyncio.sleep(0.01)
        deadline_wire = Deadline(8.0).to_wire()
        for _ in range(8):  # the LPUSH itself may ride into a drop
            try:
                await queue.enqueue_job("run_rag_job", "cj",
                                        {"query": "how are jobs created?"},
                                        _job_id="cj", deadline=deadline_wire)
                break
            except (ConnectionError, OSError):
                await asyncio.sleep(0.02)
        await asyncio.wait_for(terminal.wait(), timeout=20)
        assert events[-1]["event"] == "final"
        stats = get_registry().stats()
        assert sum(e["fired"] for e in stats["redis.send"]) >= 1
    finally:
        worker.stop()
        sub.cancel()
        wtask.cancel()
        await bus.close()
        await server.stop()


# ------------------------------------------------- engine deadline reaping


@pytest.fixture(scope="module")
def tiny_model():
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")
    from githubrepostorag_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    hf_cfg = transformers.Qwen2Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg.to_dict())
    params = params_from_state_dict(model.state_dict(), cfg)
    return params, cfg


def test_engine_deadline_reap_recycles_every_page(tiny_model):
    """The page-accounting half of the tentpole acceptance bar: a request
    whose deadline lapses mid-generation is reaped at a step boundary with
    finish_reason 'deadline' and ALL of its KV pages back in the pool."""
    import jax.numpy as jnp

    from githubrepostorag_tpu.serving import Engine, SamplingParams

    params, cfg = tiny_model
    eng = Engine(params, cfg, max_num_seqs=4, num_pages=64, page_size=8,
                 max_seq_len=128, prefill_chunk=32, kv_dtype=jnp.float32)
    assert eng._allocator.free_count == eng._allocator.num_pages
    sp = SamplingParams(temperature=0.0, max_tokens=100, stop_token_ids=())
    rid = eng.add_request([1, 2, 3, 4], sp, deadline_s=time.monotonic() + 0.2)
    done = []
    while eng.has_work():
        done.extend(eng.step())
        time.sleep(0.01)  # 100 decode steps cannot beat a 0.2s budget
    assert [r.request_id for r in done] == [rid]
    assert done[0].finish_reason == "deadline"
    assert len(done[0].output_tokens) < 100  # genuinely cut short
    assert eng._allocator.free_count == eng._allocator.num_pages  # pages recycled
    assert eng.deadline_reaps == 1

    # a generous deadline must never be reaped: same engine, normal finish
    res = None
    rid2 = eng.add_request([5, 6, 7], SamplingParams(
        temperature=0.0, max_tokens=5, stop_token_ids=()),
        deadline_s=time.monotonic() + 300.0)
    while eng.has_work():
        for r in eng.step():
            res = r
    assert res is not None and res.request_id == rid2
    assert res.finish_reason == "length" and len(res.output_tokens) == 5
    assert eng._allocator.free_count == eng._allocator.num_pages
    assert eng.deadline_reaps == 1


def test_agent_raises_deadline_exceeded_at_stage_boundary():
    agent = _agent()
    with pytest.raises(DeadlineExceeded):
        agent.run("how are jobs created?", deadline=Deadline(0.0))


# --------------------------------------------- fleet drain under injection


async def test_replica_death_during_drain_still_resolves(tiny_model, monkeypatch):
    """FAULTS kills the replica mid-drain (``fleet.drain:error``): drain
    must still resolve — corpse force-stopped, lifecycle 'drained', the
    breaker debited — and the surviving replica keeps serving."""
    import jax.numpy as jnp

    from githubrepostorag_tpu.serving import Engine, SamplingParams
    from githubrepostorag_tpu.serving.multi_engine import MultiAsyncEngine

    params, cfg = tiny_model
    multi = MultiAsyncEngine([
        Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=8,
               max_seq_len=64, kv_dtype=jnp.float32)
        for _ in range(2)
    ])
    sp = SamplingParams(temperature=0.0, max_tokens=4, stop_token_ids=())
    try:
        ok = await multi.generate([1, 2, 3, 4], sp)
        assert ok.finish_reason in ("length", "stop")

        _enable(monkeypatch, "fleet.drain:error")
        before = counter_value(FAULTS_INJECTED, site="fleet.drain",
                               action="error")
        out = await multi.drain("r0")
        assert out["lifecycle"] == "drained"
        assert "fault" in out and "fleet.drain" in out["fault"]
        assert counter_value(FAULTS_INJECTED, site="fleet.drain",
                             action="error") == before + 1
        assert get_breaker("replica-r0").snapshot()["consecutive_failures"] >= 1

        # the fleet routes around the corpse without timing out against it
        monkeypatch.setenv("FAULTS", "")
        reload_settings()
        reset_faults()
        res = await multi.generate([5, 6, 7, 8], sp)
        assert res.finish_reason in ("length", "stop")
        stats = multi.router_stats()["per_replica"]
        assert stats["r0"]["lifecycle"] == "drained"
        assert stats["r1"]["routed"] >= 1  # survivor took the traffic
    finally:
        await multi.stop()


async def test_decode_replica_death_mid_handoff_finishes_fused(
        tiny_model, monkeypatch):
    """FAULTS kills the KV transfer mid-handoff (``disagg.transfer:error``
    — where a dead decode peer or a downed link surfaces): the request
    must still finish, token-identical, fused on the prefill replica that
    already holds its prefix, with the fallback accounted and the decode
    replica's breaker debited."""
    import jax.numpy as jnp

    from githubrepostorag_tpu.serving import Engine, SamplingParams
    from githubrepostorag_tpu.serving.multi_engine import MultiAsyncEngine

    params, cfg = tiny_model

    def _eng():
        return Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                      max_seq_len=64, kv_dtype=jnp.float32,
                      kv_tier="on", kv_host_pool_pages=32)

    prompt = list(range(40, 58))  # 4 full shippable pages at page_size=4
    sp = SamplingParams(temperature=0.0, max_tokens=6, stop_token_ids=())
    expected = _eng().generate([prompt], sp)[0].output_tokens

    monkeypatch.setenv("DISAGG", "on")
    monkeypatch.setenv("DISAGG_PREFILL_REPLICAS", "1")
    _enable(monkeypatch, "disagg.transfer:error")  # reloads settings too
    multi = MultiAsyncEngine([_eng(), _eng()])
    assert multi.disagg_stats()["enabled"]
    try:
        before = counter_value(FAULTS_INJECTED, site="disagg.transfer",
                               action="error")
        res = await multi.generate(prompt, sp)
        assert res.output_tokens == expected  # fused fallback, same tokens
        assert counter_value(FAULTS_INJECTED, site="disagg.transfer",
                             action="error") == before + 1
        ds = multi.disagg_stats()
        assert ds["handoffs"] == 0
        assert ds["fallbacks"]["transfer_error"] == 1
        assert ds["pages_shipped"] == 0  # the wire died before any landing
        # the decode peer ate the blame, not the prefill replica
        assert get_breaker("replica-r1").snapshot()["consecutive_failures"] >= 1
        assert get_breaker("replica-r0").snapshot()["consecutive_failures"] == 0

        # with the fault cleared the very next request hands off cleanly
        monkeypatch.setenv("FAULTS", "")
        reload_settings()
        reset_faults()
        res = await multi.generate(prompt, sp)
        assert res.output_tokens == expected
        assert multi.disagg_stats()["handoffs"] == 1
    finally:
        await multi.stop()


# -------------------------------------------- preemption under saturation


async def test_disagg_decode_preempt_falls_back_fused(tiny_model, monkeypatch):
    """The decode replica parks the handed-off request before its first
    token: the router must cancel it there and finish fused on the prefill
    replica that still holds the prefix — token-identical, with the
    fallback accounted under 'preempted'."""
    import jax.numpy as jnp

    from githubrepostorag_tpu.serving import Engine, SamplingParams
    from githubrepostorag_tpu.serving.async_engine import StreamEvent
    from githubrepostorag_tpu.serving.multi_engine import MultiAsyncEngine

    params, cfg = tiny_model

    def _eng():
        return Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                      max_seq_len=64, kv_dtype=jnp.float32,
                      kv_tier="on", kv_host_pool_pages=32, preempt="on")

    prompt = list(range(40, 58))  # 4 full shippable pages at page_size=4
    sp = SamplingParams(temperature=0.0, max_tokens=6, stop_token_ids=())
    expected = _eng().generate([prompt], sp)[0].output_tokens

    monkeypatch.setenv("DISAGG", "on")
    monkeypatch.setenv("DISAGG_PREFILL_REPLICAS", "1")
    reload_settings()
    multi = MultiAsyncEngine([_eng(), _eng()])
    assert multi.disagg_stats()["enabled"]

    # the park lands on the decode replica before any token flows — the
    # engine's preempt pass emits it at a step boundary; here the trigger
    # is simulated at the stream seam so the ordering is deterministic
    orig = multi._stream_on
    state = {"parked": False}

    async def parked_decode(target, granted, prompt_ids, sampling, rid,
                            deadline_s, priority):
        if target.role == "decode" and not state["parked"]:
            state["parked"] = True
            yield StreamEvent(type="parked")
            return
        async for event in orig(target, granted, prompt_ids, sampling, rid,
                                deadline_s, priority):
            yield event

    monkeypatch.setattr(multi, "_stream_on", parked_decode)
    try:
        res = await multi.generate(prompt, sp, priority="batch")
        assert res.output_tokens == expected  # fused fallback, same tokens
        ds = multi.disagg_stats()
        assert ds["handoffs"] == 1  # pages DID ship before the park
        assert ds["fallbacks"]["preempted"] == 1
        assert state["parked"]
    finally:
        await multi.stop()


def test_saturating_load_interactive_ttft_recovers_batch_finishes(
        tiny_model, monkeypatch):
    """FAULTS kills the SLO decision table (``admission.decide:error``)
    while batch traffic holds the whole KV pool: admission fails OPEN
    (counted) so batch is not shed at the API rung — and the engine's
    preemption ladder alone still bounds interactive TTFT.  Every batch
    request finishes with its full token budget (parks shrink max_tokens
    by tokens already produced, so nothing is lost or recomputed) and
    every interactive arrival gets its first token within a few steps."""
    import jax.numpy as jnp

    from githubrepostorag_tpu.metrics import ADMISSION_FAILOPEN
    from githubrepostorag_tpu.resilience import admission
    from githubrepostorag_tpu.serving import Engine, SamplingParams

    params, cfg = tiny_model
    _enable(monkeypatch, "admission.decide:error")
    admission.set_table_provider(
        lambda: {"batch": admission.SHED, "interactive": admission.ACCEPT})
    try:
        before = counter_value(ADMISSION_FAILOPEN)
        # the dead table fails open: batch traffic reaches the engine
        assert admission.should_shed("batch") is False
        assert counter_value(ADMISSION_FAILOPEN) == before + 1
        assert counter_value(FAULTS_INJECTED, site="admission.decide",
                             action="error") >= 1

        greedy = dict(temperature=0.0, stop_token_ids=())
        sp_batch = SamplingParams(max_tokens=24, **greedy)
        sp_hot = SamplingParams(max_tokens=4, **greedy)
        batch_prompts = [list(range(1, 9)), list(range(21, 29))]
        hot_prompts = [list(range(40 + 20 * i, 48 + 20 * i))
                       for i in range(3)]

        ref_eng = Engine(params, cfg, max_num_seqs=2, num_pages=64,
                         page_size=4, max_seq_len=64, kv_dtype=jnp.float32)
        ref_batch = [ref_eng.generate([p], sp_batch)[0].output_tokens
                     for p in batch_prompts]

        # 2 batch rows x (8 prompt + 24 budget) = 16 pages: the whole pool
        eng = Engine(params, cfg, max_num_seqs=2, num_pages=16, page_size=4,
                     max_seq_len=64, kv_dtype=jnp.float32, decode_burst=4,
                     kv_tier="on", kv_host_pool_pages=64, preempt="on")
        step_no = [0]
        first_token_step: dict[str, int] = {}

        def on_token(rid: str, _tok: int) -> None:
            first_token_step.setdefault(rid, step_no[0])

        results = []

        def step():
            step_no[0] += 1
            results.extend(eng.step())

        batch_rids = [eng.add_request(p, sp_batch, priority="batch",
                                      on_token=on_token)
                      for p in batch_prompts]
        for _ in range(3):
            step()

        ttft_steps = []
        for hp in hot_prompts:  # interactive arrivals against a full pool
            submitted_at = step_no[0]
            rid = eng.add_request(hp, sp_hot, on_token=on_token)
            guard = 0
            while rid not in {r.request_id for r in results}:
                step()
                guard += 1
                assert guard < 40, "interactive request starved"
            ttft_steps.append(first_token_step[rid] - submitted_at)

        guard = 0
        while eng.has_work():
            step()
            guard += 1
            assert guard < 200, "batch never finished after preemption"
        eng.flush_kv_migrations()

        # the first wave hit a full pool and had to park a victim; later
        # waves may find the pool already drained — that's the ladder
        # working (admit beats preempt when capacity exists)
        assert eng.preemptions >= 1
        assert eng.preempt_resumes == eng.preemptions
        # interactive p99 == max over the wave: first token within a few
        # steps of arrival even though batch held every page
        assert max(ttft_steps) <= 3, ttft_steps
        by_id = {r.request_id: r for r in results}
        for rid, want in zip(batch_rids, ref_batch):
            res = by_id[rid]
            assert res.finish_reason == "length"  # finished, not died
            assert res.output_tokens == want  # token-identical across parks
        assert eng.resume_recomputed_prompt_tokens == 0
        assert eng._allocator.free_count == eng._allocator.num_pages
    finally:
        admission.clear_table_provider()


async def test_controller_chaos_killed_replica_recovers_via_spare(
        tiny_model, monkeypatch, tmp_path):
    """The PR's acceptance bar, end to end: FAULTS kills r0's driver at a
    scripted step (``fleet.step.r0:error@window=3:``) while the fleet is
    under load; the real FleetController must sense the dead driver, fence
    the victim (its in-flight requests fail with the standard error frame,
    never hang), restore the latest index snapshot, activate the warm
    spare, and retire the corpse — after which goodput recovers.  Zero
    requests are lost except the victim's in-flight ones."""
    import jax.numpy as jnp

    from githubrepostorag_tpu.retrieval.snapshot import (
        restore_for_activation,
        save_snapshot,
    )
    from githubrepostorag_tpu.serving import Engine, SamplingParams
    from githubrepostorag_tpu.serving.controller import FleetController
    from githubrepostorag_tpu.serving.multi_engine import MultiAsyncEngine
    from githubrepostorag_tpu.store import MemoryVectorStore

    params, cfg = tiny_model

    def _eng():
        return Engine(params, cfg, max_num_seqs=4, num_pages=32, page_size=8,
                      max_seq_len=64, kv_dtype=jnp.float32)

    # a snapshot for the spare to warm up from (the controller's restore
    # hook records its invocation and restores into a fresh store)
    source = MemoryVectorStore()
    enc = HashingTextEncoder()
    text = "def handler(req): route and serve"
    source.upsert("embeddings", [Doc(
        "d1", text, {"namespace": "default", "scope": "chunk"},
        enc.encode([text])[0])])
    save_snapshot(source, str(tmp_path / "snap-001"), watermark=7)

    restored_into = MemoryVectorStore()
    restore_calls: list[dict] = []

    def restore():
        out = restore_for_activation(str(tmp_path), restored_into)
        restore_calls.append(out)
        return out

    # r0 dies on its 3rd driver iteration — mid-generation of whatever it
    # holds; open-ended window so a restarted driver would die again
    # liveness timeout sits ABOVE the CPU backend's first-step compile
    # stall (several seconds holding the driver lock): this test's trigger
    # is genuine thread death ("dead"), not a heartbeat age ("wedged")
    _enable(monkeypatch, "fleet.step.r0:error@window=3:",
            CTRL_TICK_S="0.05", CTRL_HYSTERESIS_TICKS="2",
            CTRL_COOLDOWN_S="0.1", CTRL_LIVENESS_TIMEOUT_S="30",
            CTRL_MAX_ACTIONS="4", CTRL_ACTION_WINDOW_S="60")
    multi = MultiAsyncEngine([_eng(), _eng(), _eng()], spares=1)
    assert multi.spare_replicas() == ["r2"]
    ctrl = FleetController(multi, restore=restore)
    await ctrl.start()
    sp = SamplingParams(temperature=0.0, max_tokens=12, stop_token_ids=())
    prompts = [[1 + i, 2 + i, 3 + i, 4 + i] for i in range(8)]
    try:
        # wave 1: r0 dies under this load.  Every request must resolve —
        # the victim's in-flight ones with an error frame, the rest clean.
        wave1 = await asyncio.wait_for(
            asyncio.gather(*(multi.generate(p, sp) for p in prompts)),
            timeout=120)
        assert len(wave1) == 8
        errors = [r for r in wave1 if r.finish_reason == "error"]
        clean = [r for r in wave1 if r.finish_reason != "error"]
        assert errors, "the killed replica held no in-flight work"
        assert all("fenced by fleet controller" in r.error for r in errors)
        assert all(r.finish_reason in ("length", "stop") for r in clean)

        # the controller converges: spare active, corpse retired
        for _ in range(400):
            if (multi._by_id["r2"].lifecycle == "active"
                    and multi._by_id["r0"].lifecycle == "drained"):
                break
            await asyncio.sleep(0.025)
        assert multi._by_id["r2"].lifecycle == "active"
        assert multi._by_id["r2"].driver_alive()
        assert multi._by_id["r0"].lifecycle == "drained"
        assert not multi._by_id["r0"].driver_alive()
        assert multi._by_id["r0"].driver_error  # the injected kill, recorded

        # the spare warmed up from the snapshot, not cold
        assert restore_calls and restore_calls[0]["replayed"] == 0
        assert restore_calls[0]["manifest"]["watermark"]["seq"] == 7
        assert restored_into.find_by_metadata("embeddings", {}, limit=10)

        # the action was justified and published: ledger window + burn
        # state + liveness ride the log entry and /debug/fleet
        section = multi.fleet()["controller"]
        fo = [e for e in section["log"] if e["action"] == "failover"
              and e["status"] == "dispatched"]
        assert fo, section["log"]
        just = fo[0]["justification"]
        assert just["liveness"]["thread_alive"] is False
        assert just["ledger"]["window_s"] > 0
        assert just["burn"]["state"] in ("ok", "warn", "critical")
        assert fo[0]["reason"] == "dead"
        assert counter_value(
            CTRL_ACTIONS, action="failover", reason=fo[0]["reason"]) >= 1

        # wave 2: goodput recovers on r1 + the activated spare
        wave2 = await asyncio.wait_for(
            asyncio.gather(*(multi.generate(p, sp) for p in prompts[:4])),
            timeout=120)
        assert all(r.finish_reason in ("length", "stop") for r in wave2)
        per = multi.router_stats()["per_replica"]
        assert per["r2"]["routed"] >= 1  # the spare is genuinely serving
        assert per["r0"]["lifecycle"] == "drained"
    finally:
        ctrl.stop()
        await multi.stop()


def test_admission_decide_fault_injection_fails_open_and_counts(monkeypatch):
    """FAULTS="admission.decide:error" proves the decision-table seam:
    every consult fails open to accept, each one logged + counted."""
    from githubrepostorag_tpu.metrics import ADMISSION_FAILOPEN
    from githubrepostorag_tpu.resilience import admission

    _enable(monkeypatch, "admission.decide:error")
    admission.set_table_provider(lambda: {"interactive": admission.SHED})
    try:
        before_open = counter_value(ADMISSION_FAILOPEN)
        before_inj = counter_value(FAULTS_INJECTED, site="admission.decide",
                                   action="error")
        assert admission.admission_table() == {}
        assert admission.admission_decision("interactive") == admission.ACCEPT
        assert not admission.should_shed("interactive")
        assert counter_value(ADMISSION_FAILOPEN) == before_open + 3
        assert counter_value(FAULTS_INJECTED, site="admission.decide",
                             action="error") == before_inj + 3
    finally:
        admission.clear_table_provider()
