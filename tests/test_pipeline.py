"""Pipeline-parallel training (training/pipeline.py): the GPipe schedule
over the pp mesh axis must produce the SAME loss and the SAME updated
params as the plain (non-pipelined) train step — pipelining is a schedule,
not a model change.  Runs on the virtual 8-device CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
from githubrepostorag_tpu.parallel import MeshPlan, make_mesh
from githubrepostorag_tpu.training import init_train_state, make_train_step
from githubrepostorag_tpu.training.pipeline import (
    init_pp_train_state,
    make_pp_train_step,
    merge_layers_from_pp,
    split_layers_for_pp,
)


def _batch(cfg, b, s, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, (b, s), dtype=np.int32)
    return {
        "input_ids": jnp.asarray(ids),
        "targets": jnp.asarray(np.roll(ids, -1, axis=1)),
        "mask": jnp.ones((b, s), dtype=jnp.int32),
    }


def _ref_step(cfg, batch, optimizer):
    """Non-pipelined single-device reference: same loss + update."""
    mesh = make_mesh(MeshPlan())  # 1 device
    step, _ = make_train_step(cfg, mesh, optimizer, remat=False)
    state = init_train_state(cfg, mesh, jax.random.PRNGKey(0), optimizer)
    params, _, loss = step(state.params, state.opt_state, batch)
    return state, params, loss


@pytest.mark.parametrize("pp,microbatches", [(2, 2), (2, 4), (4, 4)])
def test_pp_loss_and_update_match_reference(pp, microbatches):
    cfg = Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=4, num_heads=4, num_kv_heads=2, head_dim=8,
        rope_theta=10000.0, tie_word_embeddings=True,
    )
    optimizer = optax.sgd(1e-2)  # deterministic update, no moment noise
    batch = _batch(cfg, b=4, s=16)
    _, ref_params, ref_loss = _ref_step(cfg, batch, optimizer)

    mesh = make_mesh(MeshPlan(pp=pp))
    step, _ = make_pp_train_step(
        cfg, mesh, optimizer, num_microbatches=microbatches, remat=False
    )
    state = init_pp_train_state(cfg, mesh, jax.random.PRNGKey(0), optimizer)
    params, _, loss = step(state.params, state.opt_state, batch)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    merged = merge_layers_from_pp(params)
    flat_ref = jax.tree_util.tree_leaves_with_path(ref_params)
    flat_got = {jax.tree_util.keystr(k): v
                for k, v in jax.tree_util.tree_leaves_with_path(merged)}
    for key, ref_leaf in flat_ref:
        got = flat_got[jax.tree_util.keystr(key)]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref_leaf), rtol=3e-4, atol=3e-5,
            err_msg=f"param {jax.tree_util.keystr(key)} diverged under pp",
        )


def test_pp_with_remat_matches():
    cfg = Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
        rope_theta=10000.0, tie_word_embeddings=True,
    )
    optimizer = optax.sgd(1e-2)
    batch = _batch(cfg, b=4, s=16, seed=1)
    _, _, ref_loss = _ref_step(cfg, batch, optimizer)

    mesh = make_mesh(MeshPlan(pp=2))
    step, _ = make_pp_train_step(cfg, mesh, optimizer, num_microbatches=2, remat=True)
    state = init_pp_train_state(cfg, mesh, jax.random.PRNGKey(0), optimizer)
    _, _, loss = step(state.params, state.opt_state, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)


def test_pp_composes_with_dp():
    """pp=2 x dp=2: batch shards over dp inside each pipeline stage."""
    cfg = Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
        rope_theta=10000.0, tie_word_embeddings=True,
    )
    optimizer = optax.sgd(1e-2)
    batch = _batch(cfg, b=8, s=16, seed=2)
    _, _, ref_loss = _ref_step(cfg, batch, optimizer)

    mesh = make_mesh(MeshPlan(dp=2, pp=2))
    step, _ = make_pp_train_step(cfg, mesh, optimizer, num_microbatches=2, remat=False)
    state = init_pp_train_state(cfg, mesh, jax.random.PRNGKey(0), optimizer)
    _, _, loss = step(state.params, state.opt_state, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)


def test_split_merge_roundtrip():
    cfg = Qwen2Config(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_layers=4, num_heads=2, num_kv_heads=2, head_dim=8,
        rope_theta=10000.0, tie_word_embeddings=True,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    split = split_layers_for_pp(params, 2)
    back = merge_layers_from_pp(split)
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(back),
    ):
        assert jax.tree_util.keystr(ka) == jax.tree_util.keystr(kb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="divide"):
        split_layers_for_pp(params, 3)


def test_pp_composes_with_tp():
    """pp=2 x tp=2: Megatron column/row weight shards inside each stage,
    explicit psum after wo/wd — loss and updated params must match the
    unpipelined unsharded reference (VERDICT r02 #10: pp>1 combined with
    the other axes, not in isolation)."""
    cfg = Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=4, num_heads=4, num_kv_heads=2, head_dim=8,
        rope_theta=10000.0, tie_word_embeddings=True,
    )
    optimizer = optax.sgd(1e-2)
    batch = _batch(cfg, b=4, s=16, seed=3)
    _, ref_params, ref_loss = _ref_step(cfg, batch, optimizer)

    mesh = make_mesh(MeshPlan(pp=2, tp=2))
    step, _ = make_pp_train_step(cfg, mesh, optimizer, num_microbatches=2, remat=False)
    state = init_pp_train_state(cfg, mesh, jax.random.PRNGKey(0), optimizer)
    params, _, loss = step(state.params, state.opt_state, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)

    merged = merge_layers_from_pp(params)
    flat_got = {jax.tree_util.keystr(k): v
                for k, v in jax.tree_util.tree_leaves_with_path(merged)}
    for key, ref_leaf in jax.tree_util.tree_leaves_with_path(ref_params):
        got = flat_got[jax.tree_util.keystr(key)]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref_leaf), rtol=3e-4, atol=3e-5,
            err_msg=f"param {jax.tree_util.keystr(key)} diverged under pp x tp",
        )


def test_pp_composes_with_dp_and_tp():
    """The full dp=2 x pp=2 x tp=2 cube on 8 virtual devices — the
    combined-axes shape the driver dryrun asserts."""
    cfg = Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
        rope_theta=10000.0, tie_word_embeddings=True,
    )
    optimizer = optax.sgd(1e-2)
    batch = _batch(cfg, b=8, s=16, seed=4)
    _, _, ref_loss = _ref_step(cfg, batch, optimizer)

    mesh = make_mesh(MeshPlan(dp=2, pp=2, tp=2))
    step, _ = make_pp_train_step(cfg, mesh, optimizer, num_microbatches=2, remat=False)
    state = init_pp_train_state(cfg, mesh, jax.random.PRNGKey(0), optimizer)
    _, _, loss = step(state.params, state.opt_state, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)


def test_pp_tp_rejects_indivisible_heads():
    cfg = Qwen2Config(
        vocab_size=64, hidden_size=24, intermediate_size=48,
        num_layers=2, num_heads=3, num_kv_heads=1, head_dim=8,
        rope_theta=10000.0, tie_word_embeddings=True,
    )
    mesh = make_mesh(MeshPlan(pp=2, tp=2))
    with pytest.raises(ValueError, match="must divide"):
        make_pp_train_step(cfg, mesh, optax.sgd(1e-2), num_microbatches=2)
