"""Prefix-affinity fleet router (serving/multi_engine.py): chain-hash
affinity, SLO-weighted fallback, breaker integration, the pending-admission
staleness fix, and the drain / warm-spare replica lifecycle."""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
from githubrepostorag_tpu.obs.ledger import SNAPSHOT_FIELDS
from githubrepostorag_tpu.resilience.policy import get_breaker
from githubrepostorag_tpu.serving import Engine, SamplingParams
from githubrepostorag_tpu.serving.chain_hash import chain_hashes
from githubrepostorag_tpu.serving.kv_cache import page_hashes
from githubrepostorag_tpu.serving.multi_engine import MultiAsyncEngine
from githubrepostorag_tpu.serving.routing import (
    AFFINITY_LOAD_SLACK,
    ReplicaDigest,
    score_prefix,
    weighted_load,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    return cfg, params


def _engine(params, cfg, **kw):
    kw.setdefault("max_num_seqs", 2)
    kw.setdefault("num_pages", 32)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_seq_len", 64)
    return Engine(params, cfg, kv_dtype=jnp.float32, decode_burst=8, **kw)


def _prompts(n, seed=11):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 512, 12 + i).tolist() for i in range(n)]


# -------------------------------------------------------------- chain hash --


def test_chain_hash_is_the_allocator_identity():
    """Router and allocator must agree on page identity by construction:
    kv_cache.page_hashes IS chain_hash.chain_hashes."""
    toks = list(range(23))
    assert page_hashes(toks, 4) == chain_hashes(toks, 4)
    # one hash per FULL page; the partial trailing page gets none
    assert len(chain_hashes(toks, 4)) == 5
    # chained, not per-page: a different prefix changes every later hash
    other = chain_hashes([99] + toks[1:], 4)
    assert all(a != b for a, b in zip(chain_hashes(toks, 4), other))


def test_score_prefix_stops_at_first_unservable_page():
    h = chain_hashes(list(range(20)), 4)  # 5 pages
    res, hst, score = score_prefix(h, frozenset(h[:2]), frozenset(h[2:3]))
    assert (res, hst) == (2, 1)
    assert score == pytest.approx(2.6)
    # page 1 missing kills the run even though pages 2-4 are resident
    res, hst, _ = score_prefix(h, frozenset([h[0]] + h[2:]), frozenset())
    assert (res, hst) == (1, 0)


def test_weighted_load_penalizes_paging_limiters():
    assert weighted_load(2.0, "none") == 2.0
    assert weighted_load(2.0, "hbm_pages") > weighted_load(5.0, "none")
    assert weighted_load(0.0, "swap_wait") > weighted_load(3.0, "stall")


def test_replica_digest_snapshot_is_immutable_view():
    d = ReplicaDigest("r0")
    d.publish(frozenset([b"a"]), frozenset([b"b"]), 0.001)
    res, hst = d.snapshot()
    assert res == {b"a"} and hst == {b"b"}
    p = d.payload()
    assert p["resident_pages"] == 1 and p["builds"] == 1


# ----------------------------------------------------------------- routing --


def test_affinity_routes_to_longest_prefix_run(tiny):
    cfg, params = tiny
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)],
                             policy="affinity")
    prompt = list(range(100, 124))
    h = chain_hashes(prompt, 4)
    # r1 holds a longer resident run than r0
    multi._by_id["r0"].digest.publish(frozenset(h[:2]), frozenset())
    multi._by_id["r1"].digest.publish(frozenset(h[:5]), frozenset())
    target, granted = multi._pick(prompt)
    assert target.replica == "r1" and granted
    assert multi.router_stats()["decisions"]["affinity_hit"] == 1
    # host-tier pages extend the run but weigh less than resident ones
    multi._by_id["r0"].digest.publish(frozenset(h[:4]), frozenset(h[4:6]))
    target, _ = multi._pick(prompt)
    assert target.replica == "r0"  # 4 + 2*0.6 = 5.2 beats 5.0
    per = multi.router_stats()["per_replica"]
    assert per["r0"]["matched_resident_pages"] == 4
    assert per["r0"]["matched_host_pages"] == 2
    assert per["r0"]["prefix_hit_rate"] == 1.0


def test_affinity_yields_to_load_when_hit_replica_saturated(tiny):
    """A prefix hit is not a license to pile a whole burst onto one
    replica: past AFFINITY_LOAD_SLACK extra requests the router falls back
    to the weighted ranking (and counts a miss, not a hit)."""
    cfg, params = tiny
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)],
                             policy="affinity")
    prompt = list(range(300, 324))
    h = chain_hashes(prompt, 4)
    multi._by_id["r0"].digest.publish(frozenset(h), frozenset())
    # within the slack the hit replica keeps winning despite deeper queues
    multi._pending["r0"] = int(AFFINITY_LOAD_SLACK)
    target, _ = multi._pick(prompt)
    assert target.replica == "r0"
    assert multi.router_stats()["decisions"]["affinity_hit"] == 1
    # one past the slack: yield to the idle peer, counted as a miss
    multi._pending["r0"] = int(AFFINITY_LOAD_SLACK) + 1
    target, _ = multi._pick(prompt)
    assert target.replica == "r1"
    d = multi.router_stats()["decisions"]
    assert d["affinity_hit"] == 1 and d["affinity_miss"] == 1


def test_no_prefix_hit_falls_back_and_counts_miss(tiny):
    cfg, params = tiny
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)],
                             policy="affinity")
    multi._pick(list(range(200, 220)))  # empty digests everywhere
    d = multi.router_stats()["decisions"]
    assert d["affinity_miss"] == 1 and d["affinity_hit"] == 0


def test_pick_staleness_burst_spreads_over_replicas(tiny):
    """Regression (ISSUE 11 satellite): a burst of picks whose admissions
    have not landed yet must not all target the same 'idle' replica — the
    load snapshot counts picked-but-unadmitted requests."""
    cfg, params = tiny
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)],
                             policy="least_loaded")
    picks = []
    for p in _prompts(6):
        target, _ = multi._pick(p)
        # what stream() does between _pick and the engine admission
        multi._pending[target.replica] += 1
        picks.append(target.replica)
    assert set(picks) == {"r0", "r1"}, picks
    counts = {r: picks.count(r) for r in set(picks)}
    assert max(counts.values()) - min(counts.values()) <= 1, counts


def test_breaker_open_replica_is_skipped(tiny):
    cfg, params = tiny
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)],
                             policy="affinity")
    prompt = list(range(300, 320))
    h = chain_hashes(prompt, 4)
    multi._by_id["r0"].digest.publish(frozenset(h), frozenset())
    br0 = get_breaker("replica-r0")
    for _ in range(br0.failure_threshold):
        br0.record_failure()
    assert br0.state == "open"
    target, granted = multi._pick(prompt)
    assert target.replica == "r1" and granted
    assert multi.router_stats()["decisions"]["skipped_breaker_open"] == 1
    # every breaker refusing fails open to the best-ranked replica
    br1 = get_breaker("replica-r1")
    for _ in range(br1.failure_threshold):
        br1.record_failure()
    target, granted = multi._pick(prompt)
    assert target.replica == "r0" and not granted


def test_limiter_weighted_fallback_skips_paging_bound_replica(tiny):
    cfg, params = tiny
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)],
                             policy="least_loaded")
    # drive r0's ledger into hbm_pages attribution: most steps blocked
    led = multi._by_id["r0"].ledger
    snap = {f: 0.0 for f in SNAPSHOT_FIELDS}
    import time
    now = time.monotonic()
    for i in range(4):
        snap["admission_blocked_steps"] += 1
        snap["decode_seconds_total"] += 0.01
        led.on_step(dict(snap), now - 1.0 + i * 0.1, now - 0.95 + i * 0.1)
    assert led.current_limiter() == "hbm_pages"
    target, _ = multi._pick(list(range(400, 420)))
    assert target.replica == "r1"
    assert multi.router_stats()["decisions"]["skipped_limiter"] == 1


async def test_routed_traffic_token_identical_with_counters(tiny):
    """End-to-end: mixed routed traffic produces the same tokens as a
    single engine, and the decision counters ride stats()/fleet()."""
    cfg, params = tiny
    prompts = _prompts(4)
    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
    expected = [
        r.output_tokens for r in _engine(params, cfg).generate(prompts, sp)
    ]
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)])
    try:
        results = await asyncio.gather(*(multi.generate(p, sp) for p in prompts))
        assert [r.output_tokens for r in results] == expected
        router = multi.stats()["router"]
        assert set(router["decisions"]) == {
            "affinity_hit", "affinity_miss",
            "skipped_breaker_open", "skipped_limiter"}
        assert sum(v["routed"] for v in router["per_replica"].values()) == 4
        assert all(0.0 <= v["prefix_hit_rate"] <= 1.0
                   for v in router["per_replica"].values())
        fleet = multi.fleet()
        assert fleet["router"]["decisions"] == router["decisions"]
        assert all(r["digest"] is not None for r in fleet["replicas"])
    finally:
        await multi.stop()


# --------------------------------------------------------------- lifecycle --


async def test_drain_with_in_flight_completes_token_identically(tiny):
    cfg, params = tiny
    prompt = _prompts(1)[0]
    sp = SamplingParams(max_tokens=16, temperature=0.0, stop_token_ids=())
    expected = _engine(params, cfg).generate([prompt], sp)[0].output_tokens

    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)])
    try:
        tokens = []
        drain_task = None
        final = None
        async for event in multi.stream(prompt, sp, request_id="drain-me"):
            if event.type == "token":
                tokens.append(event.token_id)
                if drain_task is None:
                    victim = multi._route["drain-me"].replica
                    drain_task = asyncio.create_task(multi.drain(victim))
            else:
                final = event.result
        out = await drain_task
        assert out["lifecycle"] == "drained"
        assert final.output_tokens == expected == tokens
        assert multi._by_id[out["replica"]].lifecycle == "drained"
    finally:
        await multi.stop()


async def test_drained_replica_admits_nothing(tiny):
    cfg, params = tiny
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)])
    try:
        await multi.drain("r0")
        before = multi._by_id["r0"].engine.requests_admitted
        await asyncio.gather(*(multi.generate(p, sp) for p in _prompts(4)))
        assert multi._by_id["r0"].engine.requests_admitted == before
        assert multi.router_stats()["per_replica"]["r0"]["routed"] == 0
        assert multi.router_stats()["per_replica"]["r1"]["routed"] == 4
    finally:
        await multi.stop()


async def test_drain_writes_cached_pages_back_to_host_tier(tiny):
    cfg, params = tiny
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())
    multi = MultiAsyncEngine(
        [_engine(params, cfg, kv_tier="on", kv_host_pool_pages=32)
         for _ in range(2)])
    try:
        await asyncio.gather(*(multi.generate(p, sp) for p in _prompts(4)))
        victim = max(multi._engines,
                     key=lambda ae: ae.engine.requests_admitted).replica
        alloc = multi._by_id[victim].engine._allocator
        assert len(alloc._lru) > 0  # parked prefix pages to write back
        await multi.drain(victim)
        assert alloc.host_pages > 0
        assert alloc.writebacks > 0
    finally:
        await multi.stop()


async def test_warm_spare_activation_restores_capacity(tiny):
    cfg, params = tiny
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)],
                             spares=1)
    try:
        assert multi._by_id["r1"].lifecycle == "spare"
        await asyncio.gather(*(multi.generate(p, sp) for p in _prompts(3)))
        assert multi.router_stats()["per_replica"]["r1"]["routed"] == 0

        await multi.drain("r0")
        with pytest.raises(RuntimeError, match="no active replicas"):
            await multi.generate(_prompts(1)[0], sp)

        out = await multi.activate("r1")
        assert out["lifecycle"] == "active"
        r = await multi.generate(_prompts(1)[0], sp)
        assert r.finish_reason in ("length", "stop")
        assert multi.router_stats()["per_replica"]["r1"]["routed"] == 1
    finally:
        await multi.stop()


async def test_double_drain_joins_one_operation(tiny):
    """Idempotence: two concurrent drains of the same replica share ONE
    task — same result object, no interleaved second writeback."""
    cfg, params = tiny
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)])
    try:
        multi._pending["r0"] = 1  # holds _in_flight > 0: drain spins
        t1 = asyncio.create_task(multi.drain("r0"))
        t2 = asyncio.create_task(multi.drain("r0"))
        await asyncio.sleep(0.05)
        assert not t1.done() and not t2.done()
        assert multi._by_id["r0"].lifecycle == "draining"
        multi._pending["r0"] = 0
        r1, r2 = await asyncio.gather(t1, t2)
        assert r1 is r2  # the same operation's result, not a re-run
        assert r1["lifecycle"] == "drained" and r1["waited"] >= 1
    finally:
        await multi.stop()


async def test_double_activate_joins_one_operation(tiny):
    cfg, params = tiny
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)],
                             spares=1)
    try:
        t1 = asyncio.create_task(multi.activate("r1"))
        t2 = asyncio.create_task(multi.activate("r1"))
        r1, r2 = await asyncio.gather(t1, t2)
        assert r1 is r2 and r1["lifecycle"] == "active"
        assert multi._by_id["r1"].lifecycle == "active"
    finally:
        await multi.stop()


async def test_drain_then_activate_race_serializes(tiny):
    """An activate issued while a drain is in flight must queue behind it
    (never interleave with the writeback), then run — final state is a
    clean re-activation, and the replica still serves."""
    cfg, params = tiny
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)])
    try:
        multi._pending["r0"] = 1
        d = asyncio.create_task(multi.drain("r0"))
        await asyncio.sleep(0.02)
        a = asyncio.create_task(multi.activate("r0"))
        await asyncio.sleep(0.05)
        assert not a.done()  # queued behind the running drain
        multi._pending["r0"] = 0
        out_d, out_a = await asyncio.gather(d, a)
        assert out_d["lifecycle"] == "drained"
        assert out_a["lifecycle"] == "active"
        assert multi._by_id["r0"].lifecycle == "active"
        r = await multi.generate(_prompts(1)[0], sp)
        assert r.finish_reason in ("length", "stop")
    finally:
        await multi.stop()


async def test_stats_deadline_yields_stale_row_for_wedged_driver(tiny, monkeypatch):
    """Satellite regression: fleet stats() used to block on a wedged
    replica's driver lock (held for the whole injected delay).  Now the
    per-replica collection runs under a Deadline and a blocked replica
    yields its cached row + ``stale_since`` instead of hanging /debug."""
    import time

    from tests.test_chaos import _enable

    cfg, params = tiny
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)])
    try:
        fresh = multi.stats()  # populate the stats cache for both replicas
        assert all("stale_since" not in row for row in fresh["per_replica"])
        # wedge r1's driver: each iteration sleeps 0.8s HOLDING the lock
        _enable(monkeypatch, "fleet.step.r1:delay=0.8",
                CTRL_STATS_TIMEOUT_S="0.1")
        await multi._by_id["r1"].start()
        stale = None
        for _ in range(50):
            t0 = time.monotonic()
            snap = multi.stats()
            assert time.monotonic() - t0 < 0.75  # never a full wedge-wait
            row = snap["per_replica"][1]
            if "stale_since" in row:
                stale = row
                break
        assert stale is not None, "wedged replica never reported stale"
        assert stale["stale_since"] >= 0.0
        assert stale["role"] == "fused"  # cached content, not an empty row
        # the healthy replica's row stays live alongside the stale one
        assert "stale_since" not in snap["per_replica"][0]
    finally:
        monkeypatch.setenv("FAULTS", "")
        from githubrepostorag_tpu.config import reload_settings
        from githubrepostorag_tpu.resilience.faults import reset_faults
        reload_settings()
        reset_faults()
        await multi.stop()


async def test_fleet_lifecycle_endpoints(tiny):
    """POST /debug/fleet/drain + /activate drive the lifecycle over HTTP
    and /debug/fleet renders router + lifecycle state."""
    import json
    import urllib.request

    from githubrepostorag_tpu.serving.openai_api import OpenAIServer
    from githubrepostorag_tpu.serving.tokenizer import ByteTokenizer

    cfg, params = tiny
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(2)])
    server = OpenAIServer(multi, ByteTokenizer(), model_name="tiny-fleet")
    port = await server.start(host="127.0.0.1", port=0)
    loop = asyncio.get_running_loop()

    def call(path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"},
            method="POST" if body is not None else "GET",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read().decode())

    out = await loop.run_in_executor(
        None, call, "/debug/fleet/drain", {"replica": "r1"})
    assert out == {"replica": "r1", "lifecycle": "drained", "waited": 0}
    fleet = await loop.run_in_executor(None, call, "/debug/fleet")
    assert fleet["router"]["per_replica"]["r1"]["lifecycle"] == "drained"
    out = await loop.run_in_executor(
        None, call, "/debug/fleet/activate", {"replica": "r1"})
    assert out["lifecycle"] == "active"
    await server.stop()
