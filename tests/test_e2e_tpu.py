"""BASELINE eval config #1 at REAL scale: ingest this repository, then
answer a RAG query where the synthesis LLM is the in-tree Qwen2-0.5B
engine running on the actual TPU (random weights — the loop, streaming,
and latency are what's under test; answer text is weight-dependent).

Marked ``integration``: requires a TPU device and ~2 min of compiles.
Run: ``TPU_TESTS=1 pytest -m integration tests/test_e2e_tpu.py``
(the conftest forces the CPU backend unless TPU_TESTS=1).
"""

from pathlib import Path

import pytest

import jax

pytestmark = [pytest.mark.integration, pytest.mark.tpu]


@pytest.mark.skipif(jax.default_backend() != "tpu", reason="needs a real TPU chip")
def test_config1_e2e_on_tpu(tmp_path, monkeypatch):
    import jax.numpy as jnp

    from githubrepostorag_tpu.agent import GraphAgent
    from githubrepostorag_tpu.embedding import HashingTextEncoder
    from githubrepostorag_tpu.ingest.controller import ingest_component
    from githubrepostorag_tpu.ingest.sources import LocalRepoReader
    from githubrepostorag_tpu.llm import FakeLLM, InProcessLLM
    from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
    from githubrepostorag_tpu.retrieval import RetrieverFactory
    from githubrepostorag_tpu.serving.async_engine import AsyncEngine
    from githubrepostorag_tpu.serving.engine import Engine
    from githubrepostorag_tpu.serving.tokenizer import ByteTokenizer
    from githubrepostorag_tpu.store import MemoryVectorStore

    monkeypatch.setenv("DATA_DIR", str(tmp_path))
    from githubrepostorag_tpu.config import reload_settings

    reload_settings()

    # --- ingest this repo (extractors scripted: ingest-side LLM quality is
    # not what this test measures; the TPU engine is the QUERY-side LLM)
    root = Path(__file__).resolve().parent.parent
    docs = LocalRepoReader(str(root / "githubrepostorag_tpu")).load()[:30]
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    ingest_component(
        "self", docs=docs, store=store, encoder=enc,
        llm=FakeLLM(script={r".": "summary, title, keywords"}),
    )
    assert store.count("embeddings") > 10

    # --- real TPU decoder behind the sync LLM protocol
    cfg = Qwen2Config.qwen2_0_5b()
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    eng = Engine(params, cfg, max_num_seqs=4, num_pages=32, page_size=256,
                 max_seq_len=2048, prefill_chunk=512, use_pallas=True,
                 decode_burst=32, prefill_widths=2)  # width-bucketed
    # prefill on real hardware: the agent's mixed prompt lengths hit both
    # dispatch widths
    llm = InProcessLLM(AsyncEngine(eng), ByteTokenizer(),
                   default_max_tokens=48, context_window=2048)

    deltas: list[str] = []
    stream_calls: list[str] = []
    orig_stream = llm.stream_complete

    def counting_stream(prompt, **kw):
        stream_calls.append(prompt)
        yield from orig_stream(prompt, **kw)

    llm.stream_complete = counting_stream
    agent = GraphAgent(llm, RetrieverFactory(store, enc), namespace="default",
                       max_iters=1)
    result = agent.run(
        "how does the serving engine schedule prefill and decode?",
        token_cb=deltas.append,
    )
    # the full loop ran: retrieval found real chunks of this repo, the TPU
    # decoder generated (and streamed) the synthesis, sources are attributed
    assert result.sources, result.debug
    assert all(s["doc_id"] and s["scope"] for s in result.sources)
    assert result.debug["final_ctx_blocks"] >= 1
    assert isinstance(result.answer, str)
    # synthesis really streamed through the TPU engine (ByteTokenizer drops
    # non-byte ids from a random model, so deltas/answer may be empty text)
    assert stream_calls, "synthesize never hit the engine's streaming path"
