"""tpulint's own test suite: every rule has a firing positive fixture and a
silent negative fixture, suppressions need justifications, the JSON reporter
keeps its schema, and the production tree itself stays lint-clean."""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.tpulint.core import (  # noqa: E402
    RULE_NO_JUSTIFICATION,
    RULE_PARSE_ERROR,
    RULE_STALE_SUPPRESSION,
    RULE_UNKNOWN_RULE,
    analyze_file,
    analyze_source,
    iter_py_files,
    run_paths,
)
from tools.tpulint.reporters import (  # noqa: E402
    render_json,
    render_rule_list,
    render_sarif,
    render_text,
)
from tools.tpulint.rules import RULES  # noqa: E402

FIXTURES = REPO / "tests" / "lint_fixtures"
WPA_FIXTURES = FIXTURES / "wpa"
SHP_FIXTURES = FIXTURES / "shp"
SPD_FIXTURES = FIXTURES / "spd"
RULE_IDS = ["TPU001", "TPU002", "TPU003", "TPU004", "TPU005", "TPU006",
            "TPU007", "ASY001", "ASY002", "OBS001", "OBS002", "OBS003"]
WPA_RULE_IDS = ["WPA001", "WPA002", "WPA003", "WPA004"]
SHP_RULE_IDS = ["SHP001", "SHP002", "SHP003", "SHP004"]
SPD_RULE_IDS = ["SPD001", "SPD002", "SPD003", "SPD004", "SPD005"]
ALL_RULE_IDS = RULE_IDS + WPA_RULE_IDS + SHP_RULE_IDS + SPD_RULE_IDS


# ------------------------------------------------------------------ registry

def test_registry_has_the_documented_rule_set():
    assert sorted(RULES) == sorted(ALL_RULE_IDS)


def test_list_rules_mentions_every_id():
    listing = render_rule_list()
    for rule_id in ALL_RULE_IDS:
        assert rule_id in listing


# ------------------------------------------------------------ fixture corpus

@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_positive_fixture_fires(rule_id):
    findings = analyze_file(FIXTURES / f"{rule_id.lower()}_pos.py")
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire on its positive fixture"
    assert all(not f.suppressed for f in hits)


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_negative_fixture_is_silent(rule_id):
    findings = analyze_file(FIXTURES / f"{rule_id.lower()}_neg.py")
    assert [f for f in findings if f.rule == rule_id] == []


def test_negative_fixtures_are_fully_clean():
    # negatives must not trip OTHER rules either, or the corpus is confusing
    for neg in sorted(FIXTURES.glob("*_neg.py")):
        findings = analyze_file(neg)
        assert findings == [], f"{neg.name}: {[f.rule for f in findings]}"


def test_obs002_suppressed_fixture_is_silenced_with_justification():
    # the pushgateway pattern (ephemeral per-push registry) is the one
    # sanctioned in-function construction; it rides on a justified disable
    findings = analyze_file(FIXTURES / "obs002_sup.py")
    hits = [f for f in findings if f.rule == "OBS002"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)


def test_obs003_suppressed_fixture_is_silenced_with_justification():
    # a genuinely bounded "id-shaped" label set (fixed tenant roster) is the
    # sanctioned exception; it rides on a justified disable
    findings = analyze_file(FIXTURES / "obs003_sup.py")
    hits = [f for f in findings if f.rule == "OBS003"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)


def test_asy001_fires_on_blocking_sleep_in_async_retry_helper():
    # the resilience-layer hazard: jittered-backoff helpers must use
    # asyncio.sleep — a time.sleep between retries parks every coroutine
    findings = analyze_file(FIXTURES / "asy001_pos.py")
    hits = [f for f in findings if f.rule == "ASY001" and f.line > 13]
    assert hits, "ASY001 missed the blocking backoff inside retry_with_backoff"
    assert all(not f.suppressed for f in hits)


# -------------------------------------------- whole-program fixture corpus
#
# Each WPA fixture is a multi-file mini-project: the hazard is only visible
# when the analyzer resolves imports / class attributes / thread spawns
# across module boundaries, so these run through run_paths (which includes
# the program pass), not analyze_file.

@pytest.mark.parametrize("rule_id", WPA_RULE_IDS)
def test_wpa_positive_fixture_fires(rule_id):
    findings, _ = run_paths([WPA_FIXTURES / f"{rule_id.lower()}_pos"])
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire on its positive fixture package"
    assert all(not f.suppressed for f in hits)


@pytest.mark.parametrize("rule_id", WPA_RULE_IDS)
def test_wpa_negative_fixture_is_silent(rule_id):
    findings, _ = run_paths([WPA_FIXTURES / f"{rule_id.lower()}_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


@pytest.mark.parametrize("rule_id", WPA_RULE_IDS)
def test_wpa_suppressed_fixture_is_silenced_with_justification(rule_id):
    findings, _ = run_paths([WPA_FIXTURES / f"{rule_id.lower()}_sup"])
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    # a used suppression must not be swept as stale
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


# The SHP (shapeflow) fixtures follow the WPA convention: each rule has a
# pos/neg/sup mini-package, and the SHP001 positive is deliberately
# cross-module — the source is in serving.py, the sink in shapes.py, so
# only the interprocedural taint pass can connect them.

@pytest.mark.parametrize("rule_id", SHP_RULE_IDS)
def test_shp_positive_fixture_fires(rule_id):
    findings, _ = run_paths([SHP_FIXTURES / f"{rule_id.lower()}_pos"])
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire on its positive fixture package"
    assert all(not f.suppressed for f in hits)
    assert [f.rule for f in findings] == [rule_id] * len(hits)


@pytest.mark.parametrize("rule_id", SHP_RULE_IDS)
def test_shp_negative_fixture_is_silent(rule_id):
    findings, _ = run_paths([SHP_FIXTURES / f"{rule_id.lower()}_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


@pytest.mark.parametrize("rule_id", SHP_RULE_IDS)
def test_shp_suppressed_fixture_is_silenced_with_justification(rule_id):
    findings, _ = run_paths([SHP_FIXTURES / f"{rule_id.lower()}_sup"])
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


def test_shp001_message_carries_cross_module_taint_chain():
    """Every SHP001 must ship its witness: the source step, each hop, and
    the sink, with file:line anchors — here spanning two modules."""
    findings, _ = run_paths([SHP_FIXTURES / "shp001_pos"])
    (hit,) = [f for f in findings if f.rule == "SHP001"]
    assert hit.taint_chain and len(hit.taint_chain) >= 3
    assert "Taint:" in hit.message
    assert "len(requests)" in hit.taint_chain[0]
    assert "serving.py" in hit.taint_chain[0]  # source module
    assert "shapes.py" in hit.taint_chain[-1]  # sink module
    for step in hit.taint_chain:
        assert ":" in step and "[" in step  # every step carries file:line


# The live-index compactor extends the SHP001 alphabet: the repack gather
# vector must be sized by the CAPACITY bucket, not by the live-row count
# that survives a tombstone sweep (retrieval/device_index.py sizes the
# source vector at t.capacity for exactly this reason — one repack program
# per capacity rung, any survivor count).

def test_shp001_compact_positive_catches_survivor_sized_repack():
    findings, _ = run_paths([SHP_FIXTURES / "shp001_compact_pos"])
    hits = [f for f in findings if f.rule == "SHP001" and not f.suppressed]
    assert hits, "survivor-count-sized repack vector escaped the taint pass"
    (hit,) = hits
    assert "len(docs)" in hit.taint_chain[0]
    assert "compactor.py" in hit.taint_chain[0]  # source module
    assert "repack.py" in hit.taint_chain[-1]  # sink module


def test_shp001_compact_negative_is_silent():
    findings, _ = run_paths([SHP_FIXTURES / "shp001_compact_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


def test_shp001_compact_suppressed_is_silenced_with_justification():
    findings, _ = run_paths([SHP_FIXTURES / "shp001_compact_sup"])
    hits = [f for f in findings if f.rule == "SHP001"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


# Segment-packed ring prefill extends both SHP alphabets: the [1, width]
# ring buffer must be sized by the SP_RING_BUCKETS ladder, not by the raw
# token count of whichever long prompts packed into the wave
# (serving/engine.py routes every packed pass through _ring_width /
# sp_ring_bucket_ladder for exactly this reason — one compiled ring
# program per ladder entry, any wave composition), and a class dispatching
# ring passes at ladder widths must precompile them in warmup.

def test_shp001_ring_positive_catches_wave_sized_buffer():
    findings, _ = run_paths([SHP_FIXTURES / "shp001_ring_pos"])
    hits = [f for f in findings if f.rule == "SHP001" and not f.suppressed]
    assert hits, "wave-token-sized ring buffer escaped the taint pass"
    (hit,) = hits
    assert "len(tokens)" in hit.taint_chain[0]
    assert "scheduler.py" in hit.taint_chain[0]  # source module
    assert "pack.py" in hit.taint_chain[-1]  # sink module


def test_shp001_ring_negative_is_silent():
    findings, _ = run_paths([SHP_FIXTURES / "shp001_ring_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


def test_shp001_ring_suppressed_is_silenced_with_justification():
    findings, _ = run_paths([SHP_FIXTURES / "shp001_ring_sup"])
    hits = [f for f in findings if f.rule == "SHP001"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


def test_shp002_ring_positive_flags_unwarmed_ring_ladder():
    findings, _ = run_paths([SHP_FIXTURES / "shp002_ring_pos"])
    hits = [f for f in findings if f.rule == "SHP002" and not f.suppressed]
    assert any("RingPrefillServer" in f.message for f in hits), (
        "ring class with no warmup escaped SHP002")


def test_shp002_ring_negative_is_silent():
    findings, _ = run_paths([SHP_FIXTURES / "shp002_ring_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


def test_shp002_ring_suppressed_is_silenced_with_justification():
    findings, _ = run_paths([SHP_FIXTURES / "shp002_ring_sup"])
    hits = [f for f in findings if f.rule == "SHP002"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


# The fused decode step extends both SHP alphabets once more: the
# spec-verify window of the fused kernel grid must be the STATIC k+1 the
# engine compiled (short drafts pad — ops/fused_decode.py scores a fixed
# [rows, S] window per bucket), never the live draft length, and a class
# dispatching the fused burst at row buckets must precompile the whole
# (bucket, has_prefill, filter) variant set in warmup — exactly what
# serving/engine.py's fused warmup ladder exists for.

def test_shp001_fused_positive_catches_draft_sized_window():
    findings, _ = run_paths([SHP_FIXTURES / "shp001_fused_pos"])
    hits = [f for f in findings if f.rule == "SHP001" and not f.suppressed]
    assert hits, "draft-length-sized fused window escaped the taint pass"
    (hit,) = hits
    assert "len(draft_tokens)" in hit.taint_chain[0]
    assert "burst.py" in hit.taint_chain[0]  # source module
    assert "grid.py" in hit.taint_chain[-1]  # sink module


def test_shp001_fused_negative_is_silent():
    findings, _ = run_paths([SHP_FIXTURES / "shp001_fused_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


def test_shp001_fused_suppressed_is_silenced_with_justification():
    findings, _ = run_paths([SHP_FIXTURES / "shp001_fused_sup"])
    hits = [f for f in findings if f.rule == "SHP001"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


def test_shp002_fused_positive_flags_unwarmed_fused_ladder():
    findings, _ = run_paths([SHP_FIXTURES / "shp002_fused_pos"])
    hits = [f for f in findings if f.rule == "SHP002" and not f.suppressed]
    assert any("FusedStepEngine" in f.message for f in hits), (
        "fused-step class with no warmup escaped SHP002")


def test_shp002_fused_negative_is_silent():
    findings, _ = run_paths([SHP_FIXTURES / "shp002_fused_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


def test_shp002_fused_suppressed_is_silenced_with_justification():
    findings, _ = run_paths([SHP_FIXTURES / "shp002_fused_sup"])
    hits = [f for f in findings if f.rule == "SHP002"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


# The SPD (spmdflow) fixtures follow the same convention: each rule has a
# pos/neg/sup mini-package.  The SPD001 positive splits the mesh
# construction and the bad collective across modules; the SPD002 positive
# routes one donation through a helper so the witness must chain the hop.

@pytest.mark.parametrize("rule_id", SPD_RULE_IDS)
def test_spd_positive_fixture_fires(rule_id):
    findings, _ = run_paths([SPD_FIXTURES / f"{rule_id.lower()}_pos"])
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire on its positive fixture package"
    assert all(not f.suppressed for f in hits)
    assert [f.rule for f in findings] == [rule_id] * len(hits)


@pytest.mark.parametrize("rule_id", SPD_RULE_IDS)
def test_spd_negative_fixture_is_silent(rule_id):
    findings, _ = run_paths([SPD_FIXTURES / f"{rule_id.lower()}_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


@pytest.mark.parametrize("rule_id", SPD_RULE_IDS)
def test_spd_suppressed_fixture_is_silenced_with_justification(rule_id):
    findings, _ = run_paths([SPD_FIXTURES / f"{rule_id.lower()}_sup"])
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


def test_spd001_witness_names_the_unbound_axis_and_known_axes():
    findings, _ = run_paths([SPD_FIXTURES / "spd001_pos"])
    (hit,) = [f for f in findings if f.rule == "SPD001"]
    assert "'pp'" in hit.message and "dp" in hit.message and "tp" in hit.message
    assert hit.taint_chain
    assert "psum" in hit.taint_chain[-1]
    assert "collect.py" in hit.taint_chain[-1]


def test_spd002_witness_chains_the_helper_hop():
    """The drive->_flush->jit donation must carry every hop: the helper
    that consumed the parameter, the jitted callee that donated it, and
    the stale read, each with a file:line anchor."""
    findings, _ = run_paths([SPD_FIXTURES / "spd002_pos"])
    hits = [f for f in findings if f.rule == "SPD002"]
    assert len(hits) == 2
    chained = [f for f in hits if any("_flush" in s for s in (f.taint_chain or []))]
    (via_helper,) = chained
    assert len(via_helper.taint_chain) >= 3
    assert "update_pool" in " ".join(via_helper.taint_chain)
    assert "read again" in via_helper.taint_chain[-1]
    for step in via_helper.taint_chain:
        assert ":" in step and "[" in step  # every step carries file:line


def test_spd_rules_have_stale_suppression_sweep_and_unknown_exit(tmp_path):
    """LNT002 covers SPD directives: a justified disable that matches no
    SPD finding is swept; a misspelled SPD id is LNT001."""
    (tmp_path / "mod.py").write_text(
        "def fine(pool):\n"
        "    # tpulint: disable=SPD002 -- historical; the donation moved behind a rebind\n"
        "    return pool\n"
    )
    findings, _ = run_paths([tmp_path])
    assert [f.rule for f in findings] == [RULE_STALE_SUPPRESSION]
    (tmp_path / "mod.py").write_text(
        "def fine(pool):\n"
        "    # tpulint: disable=SPD999 -- no such rule\n"
        "    return pool\n"
    )
    findings, _ = run_paths([tmp_path])
    assert RULE_UNKNOWN_RULE in {f.rule for f in findings}


def test_cli_unknown_spd_suppression_exits_3(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text(
        "def fine(pool):\n"
        "    # tpulint: disable=SPD999 -- misspelled id\n"
        "    return pool\n"
    )
    assert _run_cli(str(target)).returncode == 3


def test_spd_baseline_roundtrip(tmp_path):
    """--write-baseline fingerprints SPD findings like every other rule,
    and the baselined run exits clean."""
    baseline = tmp_path / "baseline.json"
    target = "tests/lint_fixtures/spd/spd001_pos"
    assert _run_cli(target).returncode == 1
    assert _run_cli(target, "--write-baseline", str(baseline)).returncode == 0
    payload = json.loads(baseline.read_text())
    assert any(fp.startswith("SPD001::") for fp in payload["fingerprints"])
    proc = _run_cli(target, "--baseline", str(baseline), "--format", "json")
    assert proc.returncode == 0
    out = json.loads(proc.stdout)
    assert out["stats"]["baselined"] > 0


# ------------------------------------------------------- planted regressions
# Mutation tests against the REAL tree: re-introduce the two classes of bug
# the shapeflow pass exists to catch, and prove it catches them.

def _mutated_tree(tmp_path, relpath: str, needle: str, replacement: str) -> Path:
    src_root = REPO / "githubrepostorag_tpu"
    dst = tmp_path / "githubrepostorag_tpu"
    shutil.copytree(src_root, dst, ignore=shutil.ignore_patterns("__pycache__"))
    target = dst / relpath
    text = target.read_text()
    assert needle in text, f"mutation needle vanished from {relpath}"
    target.write_text(text.replace(needle, replacement, 1))
    return dst


def test_planted_engine_debucketing_is_caught_as_shp001(tmp_path):
    """Strip the bucket barrier from the spec-burst row sizing: the
    request-derived batch size then reaches the dispatch shapes raw, and
    SHP001 must fire with a full witness chain."""
    dst = _mutated_tree(
        tmp_path, "serving/engine.py",
        "rb = _bucket(len(running), self.max_num_seqs, minimum=1)",
        "rb = len(running)")
    findings, _ = run_paths([dst])
    hits = [f for f in findings if f.rule == "SHP001" and not f.suppressed]
    assert hits, "debucketed engine row sizing escaped the taint pass"
    assert all(f.taint_chain for f in hits)
    assert any("len(running)" in f.taint_chain[0] for f in hits)


def test_planted_encoder_warmup_removal_is_caught_as_shp002(tmp_path):
    """Rename the encoder's warmup: the class then runs its bucketed
    embed dispatches with no warmup routine — the exact in-tree bug this
    pass found — and SHP002 must flag the class."""
    dst = _mutated_tree(
        tmp_path, "embedding.py",
        "def warmup(self) -> int:",
        "def _prime_ladder(self) -> int:")
    findings, _ = run_paths([dst])
    hits = [f for f in findings if f.rule == "SHP002" and not f.suppressed]
    assert any("JaxBertTextEncoder" in f.message for f in hits), (
        "warmup removal on JaxBertTextEncoder escaped SHP002")


def test_planted_pipeline_dropped_tp_reduce_is_caught_as_spd003(tmp_path):
    """Drop the Megatron row-parallel psum from the pp training body: the
    tp-partitioned layer inputs then leave the shard_map with no reduction
    over tp under a replicated out_specs, and SPD003 must fire with the
    in_specs -> no-reduction -> out_specs witness."""
    dst = _mutated_tree(
        tmp_path, "training/pipeline.py",
        'reduce = (lambda x: lax.psum(x, "tp")) if tp > 1 else None',
        "reduce = None")
    findings, _ = run_paths([dst])
    hits = [f for f in findings if f.rule == "SPD003" and not f.suppressed]
    assert hits, "dropped tp reduce in pp_loss escaped the SPMD pass"
    (hit,) = hits
    assert "'tp'" in hit.message
    assert hit.taint_chain and len(hit.taint_chain) >= 3
    assert "in_specs" in hit.taint_chain[0]
    assert "pp_loss" in hit.taint_chain[1]
    assert "out_specs" in hit.taint_chain[-1]


def test_planted_ring_perm_without_modulo_is_caught_as_spd004(tmp_path):
    """Strip the % axis_size wrap from the ring-attention rotation: the
    last rank's destination falls off the ring, and SPD004 must anchor the
    finding at each ppermute with the perm-build step in the witness."""
    dst = _mutated_tree(
        tmp_path, "parallel/ring_attention.py",
        "perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]",
        "perm = [(j, j + 1) for j in range(axis_size)]")
    findings, _ = run_paths([dst])
    hits = [f for f in findings if f.rule == "SPD004" and not f.suppressed]
    assert hits, "unwrapped ring perm escaped the SPMD pass"
    assert all("% axis_size" in f.message for f in hits)
    for f in hits:
        assert f.taint_chain and "perm built here" in f.taint_chain[0]
        assert "ring_attention.py:67" in f.taint_chain[0]


def test_planted_donated_page_reread_is_caught_as_spd002(tmp_path):
    """Stop rebinding the scatter_pages result on the migrate path: the
    donated device page pools are then re-read on the next loop pass, and
    SPD002 must carry the donate-site -> stale-read witness."""
    dst = _mutated_tree(
        tmp_path, "serving/engine.py",
        "self._dk_pages, self._dv_pages, _, _ = scatter_pages(",
        "_, _, _, _ = scatter_pages(")
    findings, _ = run_paths([dst])
    hits = [f for f in findings if f.rule == "SPD002" and not f.suppressed]
    assert hits, "donated page-pool re-read escaped the SPMD pass"
    assert any("self._dk_pages" in f.message for f in hits)
    for f in hits:
        assert f.taint_chain
        assert "scatter_pages" in f.taint_chain[0]
        assert "donate position" in f.taint_chain[0]
        assert "read again" in f.taint_chain[-1]


def test_wpa004_positive_catches_both_leak_and_double_free():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_pos"])
    messages = [f.message for f in findings if f.rule == "WPA004"]
    assert any("leak" in m for m in messages), messages
    assert any("double-free" in m for m in messages), messages


# KV tiering extends the WPA004 alphabet: evict()/fault_in() move pages
# between the device and host tiers WITHOUT changing ownership, so the
# checker must (a) not treat a tier move as a release — parking pages on
# the host and dropping the handle is still a leak — and (b) flag a tier
# move applied to a handle whose pages were already released.

def test_wpa004_tier_positive_catches_use_after_release_and_leak():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_tier_pos"])
    messages = [f.message for f in findings if f.rule == "WPA004"]
    assert any("use-after-release" in m for m in messages), messages
    # evict() must NOT count as a release: the parked handle still leaks
    assert any("leak" in m for m in messages), messages


def test_wpa004_tier_negative_is_silent():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_tier_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


def test_wpa004_tier_suppressed_is_silenced_with_justification():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_tier_sup"])
    hits = [f for f in findings if f.rule == "WPA004"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


# Preemption extends the WPA004 alphabet once more: park() surrenders a
# victim's pages to the host tier but keeps the handle accountable — it
# must later be resumed (ownership returns) or released (deadline reap).
# Dropping a parked handle strands host-tier pages forever; parking or
# resuming a released handle is a use-after-release.

def test_wpa004_park_positive_catches_leak_and_use_after_release():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_park_pos"])
    messages = [f.message for f in findings if f.rule == "WPA004"]
    assert any("parked page leak" in m for m in messages), messages
    assert any("use-after-release" in m for m in messages), messages


def test_wpa004_park_negative_is_silent():
    # both legal closes: park -> resume -> release, and park -> release
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_park_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


def test_wpa004_park_suppressed_is_silenced_with_justification():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_park_sup"])
    hits = [f for f in findings if f.rule == "WPA004"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


# int4 KV pages sharpen the WPA004 reap path: both nibble planes of an
# int4 pool live in ONE set of page handles (serving/kv_cache.py packs
# k's halves into the same uint8 page), so a reap sweep that frees "per
# plane" double-frees, and clearing the per-page scale table without
# releasing the pages strands them forever.

def test_wpa004_reap_positive_catches_per_plane_double_free_and_leak():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_reap_pos"])
    messages = [f.message for f in findings if f.rule == "WPA004"]
    assert any("double-free" in m for m in messages), messages
    assert any("leak" in m for m in messages), messages


def test_wpa004_reap_negative_is_silent():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_reap_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


def test_wpa004_reap_suppressed_is_silenced_with_justification():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_reap_sup"])
    hits = [f for f in findings if f.rule == "WPA004"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


# Disaggregated serving extends the WPA004 alphabet again: export_pages()
# puts a handle in flight toward a peer pool and import_pages() lands it.
# The checker must prove every export reaches exactly one import or a
# release — dangling exports, double-imports, and transfers of released
# handles all fire; the clean handoff (and the abandon path) stay silent.

def test_wpa004_xfer_positive_catches_all_three_shapes():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_xfer_pos"])
    messages = [f.message for f in findings if f.rule == "WPA004"]
    assert any("dangling export" in m for m in messages), messages
    assert any("double-import" in m for m in messages), messages
    assert any("use-after-release" in m for m in messages), messages


def test_wpa004_xfer_negative_is_silent():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_xfer_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


def test_wpa004_xfer_suppressed_is_silenced_with_justification():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_xfer_sup"])
    hits = [f for f in findings if f.rule == "WPA004"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


# The fleet router reads per-replica chain digests the driver thread
# updates every step (serving/routing.py).  These fixtures pin the exact
# cross-domain shape: an event-loop pick path consuming a driver-written
# digest attribute must go through a lock (or a justified atomic swap).

def test_wpa002_router_digest_read_without_lock_fires():
    findings, _ = run_paths([WPA_FIXTURES / "wpa002_router_pos"])
    hits = [f for f in findings if f.rule == "WPA002" and not f.suppressed]
    assert hits, "lock-free cross-domain digest read escaped WPA002"
    assert any("resident" in f.message for f in hits), \
        [f.message for f in hits]


def test_wpa002_router_locked_digest_swap_is_silent():
    findings, _ = run_paths([WPA_FIXTURES / "wpa002_router_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


def test_wpa002_router_suppressed_swap_needs_justification():
    findings, _ = run_paths([WPA_FIXTURES / "wpa002_router_sup"])
    hits = [f for f in findings if f.rule == "WPA002"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)


def test_domain_annotation_seeds_inference(tmp_path):
    # `# tpulint: domain=event_loop` pins a sync helper to the loop even
    # with no call edge proving it — the annotation is the seed
    (tmp_path / "mod.py").write_text(
        "import time\n\n\n"
        "# tpulint: domain=event_loop\n"
        "def helper():\n"
        "    time.sleep(1)\n"
    )
    findings, _ = run_paths([tmp_path])
    assert [f.rule for f in findings] == ["WPA001"]


def test_tpu003_fires_on_unbucketed_search_fixture():
    # the hazard retrieval/device_index.py's bucket contract exists to
    # prevent: corpus/query counts flowing straight into jitted shapes
    findings = analyze_file(FIXTURES / "tpu003_search_unbucketed_pos.py")
    hits = [f for f in findings if f.rule == "TPU003"]
    assert len(hits) >= 2  # traced shape AND len()-into-jit both caught
    assert all(not f.suppressed for f in hits)
    assert [f.rule for f in findings] == ["TPU003"] * len(findings)


# -------------------------------------------------------------- suppressions

def test_justified_suppression_silences_and_records_reason():
    findings = analyze_file(FIXTURES / "suppress_ok.py")
    assert findings, "fixture should produce (suppressed) findings"
    assert all(f.suppressed for f in findings)
    assert all(f.justification for f in findings)


def test_suppression_without_justification_keeps_finding_and_adds_lnt000():
    findings = analyze_file(FIXTURES / "suppress_nojust.py")
    rules = {f.rule for f in findings}
    assert RULE_NO_JUSTIFICATION in rules
    asy = [f for f in findings if f.rule == "ASY001"]
    assert asy and not asy[0].suppressed


def test_unknown_rule_in_suppression_is_reported():
    findings = analyze_file(FIXTURES / "suppress_unknown.py")
    assert RULE_UNKNOWN_RULE in {f.rule for f in findings}


def test_stale_suppression_is_swept(tmp_path):
    # a justified directive matching zero findings is dead weight that
    # would silently swallow the next real finding on that line
    (tmp_path / "mod.py").write_text(
        "import time\n\n\n"
        "def fine():\n"
        "    # tpulint: disable=ASY001 -- historical; the async wrapper was removed\n"
        "    return time.monotonic()\n"
    )
    findings, _ = run_paths([tmp_path])
    assert [f.rule for f in findings] == [RULE_STALE_SUPPRESSION]
    assert not findings[0].suppressed


def test_used_suppression_is_not_swept():
    findings, _ = run_paths([FIXTURES / "suppress_ok.py"])
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


def test_directive_inside_string_literal_is_ignored():
    src = 'MSG = "# tpulint: disable=ASY001 -- not a real comment"\n'
    assert analyze_source(src, "s.py") == []


def test_parse_error_becomes_a_finding_not_a_crash():
    findings = analyze_source("def broken(:\n", "broken.py")
    assert [f.rule for f in findings] == [RULE_PARSE_ERROR]


# ----------------------------------------------------------------- reporters

def test_json_reporter_schema():
    findings, stats = run_paths([FIXTURES / "asy001_pos.py"])
    payload = json.loads(render_json(findings, stats))
    assert payload["version"] == 4
    assert set(payload["stats"]) == {"files", "findings", "unsuppressed",
                                     "suppressed", "baselined",
                                     "pass_seconds"}
    assert payload["stats"]["files"] == 1
    assert payload["stats"]["unsuppressed"] == len(payload["findings"]) > 0
    for entry in payload["findings"]:
        assert set(entry) == {"path", "line", "col", "rule", "message",
                              "suppressed", "justification", "qualname",
                              "baselined", "witness"}
        assert entry["rule"] in RULE_IDS
        assert entry["qualname"]  # every finding is attributed to a scope
    assert set(payload["rules"]) == set(ALL_RULE_IDS)


def test_json_stats_report_per_pass_wall_time():
    """v4 surfaces where the lint budget goes: one graph build shared by
    the wpa/shapeflow/spmdflow passes, each timed separately."""
    findings, stats = run_paths([SPD_FIXTURES / "spd001_pos"])
    seconds = stats["pass_seconds"]
    assert set(seconds) == {"graph_build", "per_file", "wpa",
                            "shapeflow", "spmdflow"}
    assert all(isinstance(v, float) and v >= 0.0 for v in seconds.values())


def test_json_reporter_carries_witness_for_shp001_and_spd002():
    findings, stats = run_paths([SHP_FIXTURES / "shp001_pos"])
    payload = json.loads(render_json(findings, stats))
    (entry,) = [e for e in payload["findings"] if e["rule"] == "SHP001"]
    assert isinstance(entry["witness"], list) and len(entry["witness"]) >= 3
    findings, stats = run_paths([SPD_FIXTURES / "spd002_pos"])
    payload = json.loads(render_json(findings, stats))
    entries = [e for e in payload["findings"] if e["rule"] == "SPD002"]
    assert entries and all(isinstance(e["witness"], list) for e in entries)


def test_sarif_reporter_schema():
    """The SARIF output must be structurally valid 2.1.0: versioned, one
    run, every result tied to a registered rule with a physical location,
    and suppressed findings carried as SARIF suppressions (not dropped)."""
    findings, stats = run_paths([SHP_FIXTURES / "shp001_pos",
                                 SHP_FIXTURES / "shp003_sup"])
    payload = json.loads(render_sarif(findings, stats))
    assert payload["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in payload["$schema"]
    (run,) = payload["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "tpulint"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == sorted(rule_ids) and set(rule_ids) == set(ALL_RULE_IDS)
    for rule in driver["rules"]:
        assert rule["shortDescription"]["text"]
        assert rule["fullDescription"]["text"]
    assert run["results"], "expected results for the positive fixtures"
    for result in run["results"]:
        assert result["ruleId"] in ALL_RULE_IDS
        assert driver["rules"][result["ruleIndex"]]["id"] == result["ruleId"]
        loc = result["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1
    by_rule = {r["ruleId"]: r for r in run["results"]}
    # SHP001's witness rides in the message text
    assert "witness chain:" in by_rule["SHP001"]["message"]["text"]
    assert "suppressions" not in by_rule["SHP001"]
    sup = by_rule["SHP003"]["suppressions"][0]
    assert sup["kind"] == "inSource" and sup["justification"]
    assert run["properties"]["stats"]["suppressed"] == 1


def test_ci_artifact_schema_gate(tmp_path):
    """The exact gate scripts/ci.sh runs over artifacts/tpulint.{json,sarif}:
    generate both artifacts from a fixture package, pass them through
    scripts/check_tpulint_schema.py, and prove the checker rejects drift."""
    findings, stats = run_paths([SPD_FIXTURES / "spd002_pos"])
    json_path = tmp_path / "tpulint.json"
    sarif_path = tmp_path / "tpulint.sarif"
    json_path.write_text(render_json(findings, stats))
    sarif_path.write_text(render_sarif(findings, stats))
    proc = subprocess.run(
        [sys.executable, "scripts/check_tpulint_schema.py",
         str(json_path), str(sarif_path)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout
    # drift in the pinned version must fail the gate
    payload = json.loads(json_path.read_text())
    payload["version"] = 3
    json_path.write_text(json.dumps(payload))
    proc = subprocess.run(
        [sys.executable, "scripts/check_tpulint_schema.py",
         str(json_path), str(sarif_path)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "version" in proc.stderr


def test_text_reporter_lists_location_and_rule():
    findings, stats = run_paths([FIXTURES / "tpu001_pos.py"])
    text = render_text(findings, stats)
    assert "tpu001_pos.py" in text and "TPU001" in text
    assert "finding(s)" in text.splitlines()[-1]


# ----------------------------------------------------------------- discovery

def test_iter_py_files_exclude():
    all_files = list(iter_py_files([FIXTURES]))
    assert any(p.name == "tpu001_pos.py" for p in all_files)
    none = list(iter_py_files([FIXTURES], excludes=["lint_fixtures"]))
    assert none == []


# ----------------------------------------------------------------------- CLI

def _run_cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "tools.tpulint", *args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_cli_exit_codes():
    assert _run_cli("tests/lint_fixtures/tpu001_pos.py").returncode == 1
    assert _run_cli("tests/lint_fixtures/tpu001_neg.py").returncode == 0
    assert _run_cli().returncode == 2  # no paths


def test_cli_json_output_parses():
    proc = _run_cli("tests/lint_fixtures/tpu006_pos.py", "--format", "json")
    payload = json.loads(proc.stdout)
    assert payload["findings"][0]["rule"] == "TPU006"


def test_cli_unknown_suppression_rule_gets_its_own_exit_code():
    # a misspelled rule id silences nothing; exit 3 makes CI fail loudly
    # instead of quietly un-suppressing
    assert _run_cli("tests/lint_fixtures/suppress_unknown.py").returncode == 3


def test_cli_sarif_output_parses():
    proc = _run_cli("tests/lint_fixtures/tpu006_pos.py", "--format", "sarif")
    payload = json.loads(proc.stdout)
    assert payload["version"] == "2.1.0"
    assert payload["runs"][0]["results"][0]["ruleId"] == "TPU006"


# ----------------------------------------------------------------- diff mode

def test_diff_closure_follows_reverse_dependencies():
    """A changed util must pull in its (transitive) importers — they are
    where a cross-module regression would surface — but not the modules it
    merely imports."""
    from tools.tpulint import diffmode

    entries = [
        ("pkg/__init__.py", ""),
        ("pkg/util.py", "def bucket(n):\n    return n\n"),
        ("pkg/engine.py", "from pkg.util import bucket\n"),
        ("pkg/api.py", "from pkg.engine import run\n"),
        ("pkg/other.py", "VALUE = 1\n"),
    ]
    real = diffmode.changed_files
    diffmode.changed_files = lambda ref: {"pkg/util.py"}
    try:
        closure = diffmode.diff_closure(entries, "HEAD")
    finally:
        diffmode.changed_files = real
    assert closure == {"pkg/util.py", "pkg/engine.py", "pkg/api.py"}


def test_diff_mode_scopes_findings_to_the_closure(monkeypatch):
    """Whole-program analysis still sees every file (no fabricated or lost
    cross-module facts), but only closure files report findings: changing
    the taint SOURCE module reports nothing (the sink file is out of
    scope), while changing the SINK module reports the cross-module
    SHP001."""
    from tools.tpulint import diffmode

    pkg = SHP_FIXTURES / "shp001_pos"
    serving = str(pkg / "serving.py").replace("\\", "/")
    shapes = str(pkg / "shapes.py").replace("\\", "/")

    monkeypatch.setattr(diffmode, "changed_files", lambda ref: {serving})
    findings, stats = run_paths([pkg], diff_base="HEAD")
    assert stats["diff_selected"] == 1
    assert findings == []  # the SHP001 anchors in shapes.py, out of scope

    monkeypatch.setattr(diffmode, "changed_files", lambda ref: {shapes})
    findings, stats = run_paths([pkg], diff_base="HEAD")
    # shapes.py changed; serving.py imports it, so both are in scope
    assert stats["diff_selected"] == 2
    assert [f.rule for f in findings] == ["SHP001"]


def test_cli_diff_with_bad_ref_is_a_usage_error():
    proc = _run_cli("tests/lint_fixtures/tpu001_neg.py",
                    "--diff", "no-such-ref-xyzzy")
    assert proc.returncode == 2
    assert "--diff" in proc.stderr


def test_cli_diff_reports_scope_in_stats():
    proc = _run_cli("tests/lint_fixtures/tpu001_neg.py", "--diff", "HEAD",
                    "--format", "json")
    assert proc.returncode in (0, 1)
    payload = json.loads(proc.stdout)
    assert isinstance(payload["stats"]["diff_selected"], int)


# ------------------------------------------------------------------ baseline

def test_baseline_roundtrip(tmp_path):
    baseline = tmp_path / "baseline.json"
    target = "tests/lint_fixtures/wpa/wpa001_pos"
    # without a baseline the positive fixture fails the run
    assert _run_cli(target).returncode == 1
    # write-baseline records the fingerprints and exits clean
    assert _run_cli(target, "--write-baseline", str(baseline)).returncode == 0
    payload = json.loads(baseline.read_text())
    assert payload["version"] == 1 and payload["fingerprints"]
    # rule+path+qualname, line-insensitive: no line numbers in fingerprints
    assert all(fp.count("::") == 2 for fp in payload["fingerprints"])
    # the same findings are now baselined and no longer fail CI
    proc = _run_cli(target, "--baseline", str(baseline), "--format", "json")
    assert proc.returncode == 0
    out = json.loads(proc.stdout)
    assert out["stats"]["baselined"] > 0
    assert all(f["baselined"] for f in out["findings"] if not f["suppressed"])
    # a NEW finding (different qualname) still fails against the old baseline
    assert _run_cli(target, "tests/lint_fixtures/tpu001_pos.py",
                    "--baseline", str(baseline)).returncode == 1


def test_committed_baseline_is_empty():
    """The acceptance bar: the tree carries justified suppressions, not
    baselined debt."""
    payload = json.loads((REPO / "tools" / "tpulint" / "baseline.json").read_text())
    assert payload == {"version": 1, "fingerprints": []}




# ---------------------------------------------------- the tree stays clean

@pytest.fixture(scope="module")
def tree_run():
    """One timed full-tree run (per-file + whole-program pass) shared by
    the self-check and the wall-time budget test."""
    import time as _time

    start = _time.monotonic()
    findings, stats = run_paths(
        [REPO / "githubrepostorag_tpu", REPO / "tests"],
        excludes=["tests/lint_fixtures"],
    )
    return findings, stats, _time.monotonic() - start


def test_production_tree_has_zero_unsuppressed_findings(tree_run):
    """The same gate `make lint` enforces, kept inside tier-1 so a finding
    fails CI even when only pytest runs — now including the WPA
    whole-program rules over githubrepostorag_tpu itself."""
    findings, stats, _ = tree_run
    unsuppressed = [f for f in findings if not f.suppressed]
    assert unsuppressed == [], [f"{f.location()} {f.rule} {f.message}" for f in unsuppressed]
    # and every suppression that does exist must carry a justification
    for f in findings:
        if f.suppressed:
            assert f.justification


def test_production_tree_exercises_the_wpa_pass(tree_run):
    """Guard against the whole-program pass silently skipping the tree:
    the engine's allocator discipline must keep it suppression-visible."""
    findings, _, _ = tree_run
    wpa_suppressed = [f for f in findings
                      if f.rule.startswith("WPA") and f.suppressed]
    assert wpa_suppressed, "expected justified WPA suppressions in-tree"


def test_lint_wall_time_budget(tree_run):
    """The whole-program pass must not rot CI: a full-tree run stays
    under 30 s (the `make lint` budget)."""
    _, _, elapsed = tree_run
    assert elapsed < 30.0, f"full-tree lint took {elapsed:.1f}s (budget 30s)"
