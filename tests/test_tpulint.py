"""tpulint's own test suite: every rule has a firing positive fixture and a
silent negative fixture, suppressions need justifications, the JSON reporter
keeps its schema, and the production tree itself stays lint-clean."""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.tpulint.core import (  # noqa: E402
    RULE_NO_JUSTIFICATION,
    RULE_PARSE_ERROR,
    RULE_UNKNOWN_RULE,
    analyze_file,
    analyze_source,
    iter_py_files,
    run_paths,
)
from tools.tpulint.reporters import render_json, render_rule_list, render_text  # noqa: E402
from tools.tpulint.rules import RULES  # noqa: E402

FIXTURES = REPO / "tests" / "lint_fixtures"
RULE_IDS = ["TPU001", "TPU002", "TPU003", "TPU004", "TPU005", "TPU006",
            "TPU007", "ASY001", "ASY002", "OBS001"]


# ------------------------------------------------------------------ registry

def test_registry_has_the_documented_rule_set():
    assert sorted(RULES) == sorted(RULE_IDS)


def test_list_rules_mentions_every_id():
    listing = render_rule_list()
    for rule_id in RULE_IDS:
        assert rule_id in listing


# ------------------------------------------------------------ fixture corpus

@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_positive_fixture_fires(rule_id):
    findings = analyze_file(FIXTURES / f"{rule_id.lower()}_pos.py")
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire on its positive fixture"
    assert all(not f.suppressed for f in hits)


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_negative_fixture_is_silent(rule_id):
    findings = analyze_file(FIXTURES / f"{rule_id.lower()}_neg.py")
    assert [f for f in findings if f.rule == rule_id] == []


def test_negative_fixtures_are_fully_clean():
    # negatives must not trip OTHER rules either, or the corpus is confusing
    for neg in sorted(FIXTURES.glob("*_neg.py")):
        findings = analyze_file(neg)
        assert findings == [], f"{neg.name}: {[f.rule for f in findings]}"


def test_asy001_fires_on_blocking_sleep_in_async_retry_helper():
    # the resilience-layer hazard: jittered-backoff helpers must use
    # asyncio.sleep — a time.sleep between retries parks every coroutine
    findings = analyze_file(FIXTURES / "asy001_pos.py")
    hits = [f for f in findings if f.rule == "ASY001" and f.line > 13]
    assert hits, "ASY001 missed the blocking backoff inside retry_with_backoff"
    assert all(not f.suppressed for f in hits)


def test_tpu003_fires_on_unbucketed_search_fixture():
    # the hazard retrieval/device_index.py's bucket contract exists to
    # prevent: corpus/query counts flowing straight into jitted shapes
    findings = analyze_file(FIXTURES / "tpu003_search_unbucketed_pos.py")
    hits = [f for f in findings if f.rule == "TPU003"]
    assert len(hits) >= 2  # traced shape AND len()-into-jit both caught
    assert all(not f.suppressed for f in hits)
    assert [f.rule for f in findings] == ["TPU003"] * len(findings)


# -------------------------------------------------------------- suppressions

def test_justified_suppression_silences_and_records_reason():
    findings = analyze_file(FIXTURES / "suppress_ok.py")
    assert findings, "fixture should produce (suppressed) findings"
    assert all(f.suppressed for f in findings)
    assert all(f.justification for f in findings)


def test_suppression_without_justification_keeps_finding_and_adds_lnt000():
    findings = analyze_file(FIXTURES / "suppress_nojust.py")
    rules = {f.rule for f in findings}
    assert RULE_NO_JUSTIFICATION in rules
    asy = [f for f in findings if f.rule == "ASY001"]
    assert asy and not asy[0].suppressed


def test_unknown_rule_in_suppression_is_reported():
    findings = analyze_file(FIXTURES / "suppress_unknown.py")
    assert RULE_UNKNOWN_RULE in {f.rule for f in findings}


def test_directive_inside_string_literal_is_ignored():
    src = 'MSG = "# tpulint: disable=ASY001 -- not a real comment"\n'
    assert analyze_source(src, "s.py") == []


def test_parse_error_becomes_a_finding_not_a_crash():
    findings = analyze_source("def broken(:\n", "broken.py")
    assert [f.rule for f in findings] == [RULE_PARSE_ERROR]


# ----------------------------------------------------------------- reporters

def test_json_reporter_schema():
    findings, stats = run_paths([FIXTURES / "asy001_pos.py"])
    payload = json.loads(render_json(findings, stats))
    assert payload["version"] == 1
    assert set(payload["stats"]) == {"files", "findings", "unsuppressed", "suppressed"}
    assert payload["stats"]["files"] == 1
    assert payload["stats"]["unsuppressed"] == len(payload["findings"]) > 0
    for entry in payload["findings"]:
        assert set(entry) == {"path", "line", "col", "rule", "message", "suppressed", "justification"}
        assert entry["rule"] in RULE_IDS
    assert set(payload["rules"]) == set(RULE_IDS)


def test_text_reporter_lists_location_and_rule():
    findings, stats = run_paths([FIXTURES / "tpu001_pos.py"])
    text = render_text(findings, stats)
    assert "tpu001_pos.py" in text and "TPU001" in text
    assert "finding(s)" in text.splitlines()[-1]


# ----------------------------------------------------------------- discovery

def test_iter_py_files_exclude():
    all_files = list(iter_py_files([FIXTURES]))
    assert any(p.name == "tpu001_pos.py" for p in all_files)
    none = list(iter_py_files([FIXTURES], excludes=["lint_fixtures"]))
    assert none == []


# ----------------------------------------------------------------------- CLI

def _run_cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "tools.tpulint", *args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_cli_exit_codes():
    assert _run_cli("tests/lint_fixtures/tpu001_pos.py").returncode == 1
    assert _run_cli("tests/lint_fixtures/tpu001_neg.py").returncode == 0
    assert _run_cli().returncode == 2  # no paths


def test_cli_json_output_parses():
    proc = _run_cli("tests/lint_fixtures/tpu006_pos.py", "--format", "json")
    payload = json.loads(proc.stdout)
    assert payload["findings"][0]["rule"] == "TPU006"


# ---------------------------------------------------- the tree stays clean

def test_production_tree_has_zero_unsuppressed_findings():
    """The same gate `make lint` enforces, kept inside tier-1 so a finding
    fails CI even when only pytest runs."""
    findings, stats = run_paths(
        [REPO / "githubrepostorag_tpu", REPO / "tests"],
        excludes=["tests/lint_fixtures"],
    )
    unsuppressed = [f for f in findings if not f.suppressed]
    assert unsuppressed == [], [f"{f.location()} {f.rule} {f.message}" for f in unsuppressed]
    # and every suppression that does exist must carry a justification
    for f in findings:
        if f.suppressed:
            assert f.justification
