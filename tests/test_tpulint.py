"""tpulint's own test suite: every rule has a firing positive fixture and a
silent negative fixture, suppressions need justifications, the JSON reporter
keeps its schema, and the production tree itself stays lint-clean."""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.tpulint.core import (  # noqa: E402
    RULE_NO_JUSTIFICATION,
    RULE_PARSE_ERROR,
    RULE_STALE_SUPPRESSION,
    RULE_UNKNOWN_RULE,
    analyze_file,
    analyze_source,
    iter_py_files,
    run_paths,
)
from tools.tpulint.reporters import render_json, render_rule_list, render_text  # noqa: E402
from tools.tpulint.rules import RULES  # noqa: E402

FIXTURES = REPO / "tests" / "lint_fixtures"
WPA_FIXTURES = FIXTURES / "wpa"
RULE_IDS = ["TPU001", "TPU002", "TPU003", "TPU004", "TPU005", "TPU006",
            "TPU007", "ASY001", "ASY002", "OBS001"]
WPA_RULE_IDS = ["WPA001", "WPA002", "WPA003", "WPA004"]


# ------------------------------------------------------------------ registry

def test_registry_has_the_documented_rule_set():
    assert sorted(RULES) == sorted(RULE_IDS + WPA_RULE_IDS)


def test_list_rules_mentions_every_id():
    listing = render_rule_list()
    for rule_id in RULE_IDS + WPA_RULE_IDS:
        assert rule_id in listing


# ------------------------------------------------------------ fixture corpus

@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_positive_fixture_fires(rule_id):
    findings = analyze_file(FIXTURES / f"{rule_id.lower()}_pos.py")
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire on its positive fixture"
    assert all(not f.suppressed for f in hits)


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_negative_fixture_is_silent(rule_id):
    findings = analyze_file(FIXTURES / f"{rule_id.lower()}_neg.py")
    assert [f for f in findings if f.rule == rule_id] == []


def test_negative_fixtures_are_fully_clean():
    # negatives must not trip OTHER rules either, or the corpus is confusing
    for neg in sorted(FIXTURES.glob("*_neg.py")):
        findings = analyze_file(neg)
        assert findings == [], f"{neg.name}: {[f.rule for f in findings]}"


def test_asy001_fires_on_blocking_sleep_in_async_retry_helper():
    # the resilience-layer hazard: jittered-backoff helpers must use
    # asyncio.sleep — a time.sleep between retries parks every coroutine
    findings = analyze_file(FIXTURES / "asy001_pos.py")
    hits = [f for f in findings if f.rule == "ASY001" and f.line > 13]
    assert hits, "ASY001 missed the blocking backoff inside retry_with_backoff"
    assert all(not f.suppressed for f in hits)


# -------------------------------------------- whole-program fixture corpus
#
# Each WPA fixture is a multi-file mini-project: the hazard is only visible
# when the analyzer resolves imports / class attributes / thread spawns
# across module boundaries, so these run through run_paths (which includes
# the program pass), not analyze_file.

@pytest.mark.parametrize("rule_id", WPA_RULE_IDS)
def test_wpa_positive_fixture_fires(rule_id):
    findings, _ = run_paths([WPA_FIXTURES / f"{rule_id.lower()}_pos"])
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire on its positive fixture package"
    assert all(not f.suppressed for f in hits)


@pytest.mark.parametrize("rule_id", WPA_RULE_IDS)
def test_wpa_negative_fixture_is_silent(rule_id):
    findings, _ = run_paths([WPA_FIXTURES / f"{rule_id.lower()}_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


@pytest.mark.parametrize("rule_id", WPA_RULE_IDS)
def test_wpa_suppressed_fixture_is_silenced_with_justification(rule_id):
    findings, _ = run_paths([WPA_FIXTURES / f"{rule_id.lower()}_sup"])
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    # a used suppression must not be swept as stale
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


def test_wpa004_positive_catches_both_leak_and_double_free():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_pos"])
    messages = [f.message for f in findings if f.rule == "WPA004"]
    assert any("leak" in m for m in messages), messages
    assert any("double-free" in m for m in messages), messages


# KV tiering extends the WPA004 alphabet: evict()/fault_in() move pages
# between the device and host tiers WITHOUT changing ownership, so the
# checker must (a) not treat a tier move as a release — parking pages on
# the host and dropping the handle is still a leak — and (b) flag a tier
# move applied to a handle whose pages were already released.

def test_wpa004_tier_positive_catches_use_after_release_and_leak():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_tier_pos"])
    messages = [f.message for f in findings if f.rule == "WPA004"]
    assert any("use-after-release" in m for m in messages), messages
    # evict() must NOT count as a release: the parked handle still leaks
    assert any("leak" in m for m in messages), messages


def test_wpa004_tier_negative_is_silent():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_tier_neg"])
    assert findings == [], [(f.rule, f.line, f.message) for f in findings]


def test_wpa004_tier_suppressed_is_silenced_with_justification():
    findings, _ = run_paths([WPA_FIXTURES / "wpa004_tier_sup"])
    hits = [f for f in findings if f.rule == "WPA004"]
    assert hits, "suppressed variant should still produce (suppressed) findings"
    assert all(f.suppressed and f.justification for f in hits)
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


def test_domain_annotation_seeds_inference(tmp_path):
    # `# tpulint: domain=event_loop` pins a sync helper to the loop even
    # with no call edge proving it — the annotation is the seed
    (tmp_path / "mod.py").write_text(
        "import time\n\n\n"
        "# tpulint: domain=event_loop\n"
        "def helper():\n"
        "    time.sleep(1)\n"
    )
    findings, _ = run_paths([tmp_path])
    assert [f.rule for f in findings] == ["WPA001"]


def test_tpu003_fires_on_unbucketed_search_fixture():
    # the hazard retrieval/device_index.py's bucket contract exists to
    # prevent: corpus/query counts flowing straight into jitted shapes
    findings = analyze_file(FIXTURES / "tpu003_search_unbucketed_pos.py")
    hits = [f for f in findings if f.rule == "TPU003"]
    assert len(hits) >= 2  # traced shape AND len()-into-jit both caught
    assert all(not f.suppressed for f in hits)
    assert [f.rule for f in findings] == ["TPU003"] * len(findings)


# -------------------------------------------------------------- suppressions

def test_justified_suppression_silences_and_records_reason():
    findings = analyze_file(FIXTURES / "suppress_ok.py")
    assert findings, "fixture should produce (suppressed) findings"
    assert all(f.suppressed for f in findings)
    assert all(f.justification for f in findings)


def test_suppression_without_justification_keeps_finding_and_adds_lnt000():
    findings = analyze_file(FIXTURES / "suppress_nojust.py")
    rules = {f.rule for f in findings}
    assert RULE_NO_JUSTIFICATION in rules
    asy = [f for f in findings if f.rule == "ASY001"]
    assert asy and not asy[0].suppressed


def test_unknown_rule_in_suppression_is_reported():
    findings = analyze_file(FIXTURES / "suppress_unknown.py")
    assert RULE_UNKNOWN_RULE in {f.rule for f in findings}


def test_stale_suppression_is_swept(tmp_path):
    # a justified directive matching zero findings is dead weight that
    # would silently swallow the next real finding on that line
    (tmp_path / "mod.py").write_text(
        "import time\n\n\n"
        "def fine():\n"
        "    # tpulint: disable=ASY001 -- historical; the async wrapper was removed\n"
        "    return time.monotonic()\n"
    )
    findings, _ = run_paths([tmp_path])
    assert [f.rule for f in findings] == [RULE_STALE_SUPPRESSION]
    assert not findings[0].suppressed


def test_used_suppression_is_not_swept():
    findings, _ = run_paths([FIXTURES / "suppress_ok.py"])
    assert RULE_STALE_SUPPRESSION not in {f.rule for f in findings}


def test_directive_inside_string_literal_is_ignored():
    src = 'MSG = "# tpulint: disable=ASY001 -- not a real comment"\n'
    assert analyze_source(src, "s.py") == []


def test_parse_error_becomes_a_finding_not_a_crash():
    findings = analyze_source("def broken(:\n", "broken.py")
    assert [f.rule for f in findings] == [RULE_PARSE_ERROR]


# ----------------------------------------------------------------- reporters

def test_json_reporter_schema():
    findings, stats = run_paths([FIXTURES / "asy001_pos.py"])
    payload = json.loads(render_json(findings, stats))
    assert payload["version"] == 2
    assert set(payload["stats"]) == {"files", "findings", "unsuppressed",
                                     "suppressed", "baselined"}
    assert payload["stats"]["files"] == 1
    assert payload["stats"]["unsuppressed"] == len(payload["findings"]) > 0
    for entry in payload["findings"]:
        assert set(entry) == {"path", "line", "col", "rule", "message",
                              "suppressed", "justification", "qualname",
                              "baselined"}
        assert entry["rule"] in RULE_IDS
        assert entry["qualname"]  # every finding is attributed to a scope
    assert set(payload["rules"]) == set(RULE_IDS + WPA_RULE_IDS)


def test_text_reporter_lists_location_and_rule():
    findings, stats = run_paths([FIXTURES / "tpu001_pos.py"])
    text = render_text(findings, stats)
    assert "tpu001_pos.py" in text and "TPU001" in text
    assert "finding(s)" in text.splitlines()[-1]


# ----------------------------------------------------------------- discovery

def test_iter_py_files_exclude():
    all_files = list(iter_py_files([FIXTURES]))
    assert any(p.name == "tpu001_pos.py" for p in all_files)
    none = list(iter_py_files([FIXTURES], excludes=["lint_fixtures"]))
    assert none == []


# ----------------------------------------------------------------------- CLI

def _run_cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "tools.tpulint", *args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_cli_exit_codes():
    assert _run_cli("tests/lint_fixtures/tpu001_pos.py").returncode == 1
    assert _run_cli("tests/lint_fixtures/tpu001_neg.py").returncode == 0
    assert _run_cli().returncode == 2  # no paths


def test_cli_json_output_parses():
    proc = _run_cli("tests/lint_fixtures/tpu006_pos.py", "--format", "json")
    payload = json.loads(proc.stdout)
    assert payload["findings"][0]["rule"] == "TPU006"


def test_cli_unknown_suppression_rule_gets_its_own_exit_code():
    # a misspelled rule id silences nothing; exit 3 makes CI fail loudly
    # instead of quietly un-suppressing
    assert _run_cli("tests/lint_fixtures/suppress_unknown.py").returncode == 3


# ------------------------------------------------------------------ baseline

def test_baseline_roundtrip(tmp_path):
    baseline = tmp_path / "baseline.json"
    target = "tests/lint_fixtures/wpa/wpa001_pos"
    # without a baseline the positive fixture fails the run
    assert _run_cli(target).returncode == 1
    # write-baseline records the fingerprints and exits clean
    assert _run_cli(target, "--write-baseline", str(baseline)).returncode == 0
    payload = json.loads(baseline.read_text())
    assert payload["version"] == 1 and payload["fingerprints"]
    # rule+path+qualname, line-insensitive: no line numbers in fingerprints
    assert all(fp.count("::") == 2 for fp in payload["fingerprints"])
    # the same findings are now baselined and no longer fail CI
    proc = _run_cli(target, "--baseline", str(baseline), "--format", "json")
    assert proc.returncode == 0
    out = json.loads(proc.stdout)
    assert out["stats"]["baselined"] > 0
    assert all(f["baselined"] for f in out["findings"] if not f["suppressed"])
    # a NEW finding (different qualname) still fails against the old baseline
    assert _run_cli(target, "tests/lint_fixtures/tpu001_pos.py",
                    "--baseline", str(baseline)).returncode == 1


def test_committed_baseline_is_empty():
    """The acceptance bar: the tree carries justified suppressions, not
    baselined debt."""
    payload = json.loads((REPO / "tools" / "tpulint" / "baseline.json").read_text())
    assert payload == {"version": 1, "fingerprints": []}




# ---------------------------------------------------- the tree stays clean

@pytest.fixture(scope="module")
def tree_run():
    """One timed full-tree run (per-file + whole-program pass) shared by
    the self-check and the wall-time budget test."""
    import time as _time

    start = _time.monotonic()
    findings, stats = run_paths(
        [REPO / "githubrepostorag_tpu", REPO / "tests"],
        excludes=["tests/lint_fixtures"],
    )
    return findings, stats, _time.monotonic() - start


def test_production_tree_has_zero_unsuppressed_findings(tree_run):
    """The same gate `make lint` enforces, kept inside tier-1 so a finding
    fails CI even when only pytest runs — now including the WPA
    whole-program rules over githubrepostorag_tpu itself."""
    findings, stats, _ = tree_run
    unsuppressed = [f for f in findings if not f.suppressed]
    assert unsuppressed == [], [f"{f.location()} {f.rule} {f.message}" for f in unsuppressed]
    # and every suppression that does exist must carry a justification
    for f in findings:
        if f.suppressed:
            assert f.justification


def test_production_tree_exercises_the_wpa_pass(tree_run):
    """Guard against the whole-program pass silently skipping the tree:
    the engine's allocator discipline must keep it suppression-visible."""
    findings, _, _ = tree_run
    wpa_suppressed = [f for f in findings
                      if f.rule.startswith("WPA") and f.suppressed]
    assert wpa_suppressed, "expected justified WPA suppressions in-tree"


def test_lint_wall_time_budget(tree_run):
    """The whole-program pass must not rot CI: a full-tree run stays
    under 30 s (the `make lint` budget)."""
    _, _, elapsed = tree_run
    assert elapsed < 30.0, f"full-tree lint took {elapsed:.1f}s (budget 30s)"
