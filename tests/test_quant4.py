"""Int4 (AWQ-class) weight-only quantization: roundtrip error, packing,
forward parity, engine integration, TP composition, AWQ repacking.

The reference's deployed model is 4-bit AWQ (vLLM serving
Qwen2.5-Coder-7B-Instruct-AWQ — /root/reference/helm/values.yaml:67);
models/quant.py::QuantizedLinear4 is the TPU-native equivalent: group-wise
asymmetric uint4, plane-packed two nibbles per byte, dequantized in VMEM by
the Pallas GEMM (ops/pallas_int4.py) on TPU and by the two-dot XLA
formulation (q4_matmul) elsewhere.
"""

import numpy as np

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.quant import (
    QuantizedLinear4,
    dequantize,
    init_params_quantized,
    qmatmul,
    quantize_qwen2_params,
    quantize_weight4,
)
from githubrepostorag_tpu.models.qwen2 import Qwen2Config, forward, init_params

G = 16  # group size that divides the tiny config's dims (real configs use 64)


def test_quantize4_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(0, 0.02, (64, 128)), dtype=jnp.float32)
    qt = quantize_weight4(w, group_size=G)
    assert qt.q.dtype == jnp.uint8 and qt.q.shape == (32, 128)
    assert qt.s.shape == (64 // G, 128) and qt.zs.shape == (64 // G, 128)
    err = np.abs(np.asarray(dequantize(qt, jnp.float32)) - np.asarray(w))
    # asymmetric int4: half a step = (max-min)/30 per group, plus bf16
    # storage error on s/zs
    max_step = float(np.asarray(qt.s, dtype=np.float32).max())
    assert err.max() <= max_step * 1.2, (err.max(), max_step)


def test_quantize4_preserves_group_extremes():
    """Asymmetric quantization maps each group's min to nibble 0 and max to
    nibble 15, so the extreme values survive the roundtrip (up to bf16
    storage of s/zs) — the property that distinguishes asymmetric from
    symmetric int4, which wastes half a nibble on one-sided groups."""
    rng = np.random.default_rng(1)
    w = rng.uniform(0.5, 1.5, (32, 8)).astype(np.float32)  # one-sided values
    qt = quantize_weight4(jnp.asarray(w), group_size=16)
    back = np.asarray(dequantize(qt, jnp.float32)).reshape(2, 16, 8)
    wg = w.reshape(2, 16, 8)
    np.testing.assert_allclose(back.max(1), wg.max(1), rtol=2e-2)
    np.testing.assert_allclose(back.min(1), wg.min(1), rtol=2e-2, atol=2e-2)


def test_quantize4_stacked_layers_shapes():
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(0, 0.02, (3, 32, 48)), dtype=jnp.float32)
    qt = quantize_weight4(w, group_size=G)
    assert qt.q.shape == (3, 16, 48) and qt.s.shape == (3, 2, 48)
    deq = dequantize(qt, jnp.float32)
    np.testing.assert_allclose(np.asarray(deq), np.asarray(w), atol=6e-3)


def test_quantize4_rejects_misaligned_dims():
    import pytest

    w = jnp.zeros((24, 8), dtype=jnp.float32)  # 24 % (2*16) != 0
    with pytest.raises(ValueError):
        quantize_weight4(w, group_size=16)


def test_qmatmul4_matches_dequant_matmul():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(4, 64)), dtype=jnp.float32)
    w = jnp.asarray(rng.normal(0, 0.02, (64, 128)), dtype=jnp.float32)
    qt = quantize_weight4(w, group_size=G)
    np.testing.assert_allclose(
        np.asarray(qmatmul(x, qt)), np.asarray(x @ dequantize(qt, jnp.float32)),
        rtol=2e-2, atol=2e-4,
    )


def test_quantized4_forward_tracks_bf16_logits():
    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qparams = quantize_qwen2_params(params, bits=4, group_size=G)
    assert isinstance(qparams["layers"]["wq"], QuantizedLinear4)
    ids = jnp.asarray(np.random.default_rng(4).integers(0, cfg.vocab_size, (2, 16)),
                      dtype=jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (2, 16))
    ref, _ = forward(params, cfg, ids, pos)
    out, _ = forward(qparams, cfg, ids, pos)
    a = np.asarray(ref).reshape(-1).astype(np.float64)
    b = np.asarray(out).reshape(-1).astype(np.float64)
    corr = np.dot(a - a.mean(), b - b.mean()) / (np.std(a) * np.std(b) * a.size)
    assert corr > 0.995, corr  # group-16 int4 tracks fp at init scale


def test_engine_runs_with_int4_params():
    from githubrepostorag_tpu.serving import Engine, SamplingParams

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(7), dtype=jnp.float32)
    qparams = quantize_qwen2_params(params, bits=4, group_size=G)
    eng = Engine(qparams, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                 max_seq_len=64, kv_dtype=jnp.float32, decode_burst=8)
    res = eng.generate([[1, 2, 3, 4, 5]],
                       SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=()))[0]
    assert len(res.output_tokens) == 8
    assert res.finish_reason == "length"


def test_tp2_engine_with_int4_params_token_identical():
    """Int4 composes with TP sharding exactly like int8: the specs tree
    mirrors QuantizedLinear4 (q/s/zs all shard with the weight's spec) and
    tp=2 greedy decode matches the single-device int4 engine."""
    from githubrepostorag_tpu.parallel import MeshPlan, make_mesh
    from githubrepostorag_tpu.serving import Engine, SamplingParams

    cfg = Qwen2Config.tiny()
    qparams = quantize_qwen2_params(
        init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32),
        bits=4, group_size=G,
    )

    def run(mesh):
        eng = Engine(qparams, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                     max_seq_len=64, kv_dtype=jnp.float32, decode_burst=8,
                     mesh=mesh)
        sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
        return [r.output_tokens for r in eng.generate([[1, 2, 3], [6, 5, 4]], sp)]

    assert run(make_mesh(MeshPlan(tp=2))) == run(None)


def test_init_params_quantized4_shapes():
    cfg = Qwen2Config.tiny()
    params = init_params_quantized(cfg, bits=4, group_size=G)
    wq = params["layers"]["wq"]
    assert isinstance(wq, QuantizedLinear4)
    L, d = cfg.num_layers, cfg.hidden_size
    assert wq.q.shape == (L, d // 2, cfg.num_heads * cfg.head_dim)
    assert wq.s.shape == (L, d // G, cfg.num_heads * cfg.head_dim)


def test_awq_unpack_known_word():
    """Pin the AutoAWQ GEMM nibble layout against a hand-packed word (not a
    round trip through the same constant): columns 0..7 with values 0..7
    pack — per AutoAWQ's order_map [0,2,4,6,1,3,5,7] — into nibbles
    (low..high) 0,2,4,6,1,3,5,7 = 0x75316420."""
    from githubrepostorag_tpu.models.hf_loader import _awq_unpack

    word = np.array([[0x75316420]], dtype=np.uint32).view(np.int32)
    got = _awq_unpack(word)
    np.testing.assert_array_equal(got, np.arange(8, dtype=np.uint8)[None, :])


def test_awq_repack_roundtrip():
    """Synthetic AutoAWQ GEMM-format tensors repack losslessly: build
    known uint4 q / zeros / scales, pack them the AWQ way (8 nibbles per
    int32, interleaved column order), repack via awq_linear_to_quantized4,
    and check dequant equals the direct (q - z) * s reference."""
    from githubrepostorag_tpu.models.hf_loader import (
        AWQ_NIBBLE_ORDER,
        awq_linear_to_quantized4,
    )

    rng = np.random.default_rng(6)
    in_dim, out, group = 32, 16, 8
    q = rng.integers(0, 16, (in_dim, out)).astype(np.uint8)
    z = rng.integers(0, 16, (in_dim // group, out)).astype(np.uint8)
    s = rng.uniform(0.01, 0.03, (in_dim // group, out)).astype(np.float32)

    def awq_pack(u4: np.ndarray) -> np.ndarray:
        r, c = u4.shape
        packed = np.zeros((r, c // 8), dtype=np.uint32)
        for pos, col in enumerate(AWQ_NIBBLE_ORDER):
            packed |= u4[:, col::8].astype(np.uint32) << np.uint32(4 * pos)
        return packed.view(np.int32)

    qt = awq_linear_to_quantized4(awq_pack(q), awq_pack(z), s)
    got = np.asarray(dequantize(qt, jnp.float32))
    ref = (q.astype(np.float32) - np.repeat(z, group, 0)) * np.repeat(s, group, 0)
    # s/zs stored bf16: tolerance is bf16 eps on the scale magnitudes
    np.testing.assert_allclose(got, ref, atol=2e-3)


def test_int4_halves_weight_bytes_vs_int8():
    """At real geometry (0.5B MLP projection, group 64) int4 weights+scales
    are ~56% of int8 weights+scales — the HBM-read halving the 7B decode
    bench banks on."""
    from githubrepostorag_tpu.models.quant import params_nbytes, quantize_weight

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(0, 0.02, (896, 4864)), dtype=jnp.float32)
    n8 = sum(leaf.nbytes for leaf in jax.tree.leaves(quantize_weight(w)._asdict()))
    n4 = sum(
        leaf.nbytes
        for leaf in jax.tree.leaves(quantize_weight4(w, group_size=64)._asdict())
    )
    assert n4 < 0.6 * n8, (n4, n8)

    cfg = Qwen2Config.tiny()
    assert params_nbytes(init_params_quantized(cfg, bits=4, group_size=G)) < \
        params_nbytes(init_params_quantized(cfg, bits=8))


def test_pallas_int4_matmul_matches_oracle():
    """The Pallas in-VMEM-dequant GEMM (interpret mode) must match the
    two-dot XLA oracle (q4_matmul) for both unstacked and stacked+layered
    weights, including padded row counts."""
    from githubrepostorag_tpu.models.quant import q4_matmul
    from githubrepostorag_tpu.ops.pallas_int4 import int4_matmul

    rng = np.random.default_rng(9)
    IN, OUT, L = 64, 48, 3
    w = jnp.asarray(rng.normal(0, 0.02, (L, IN, OUT)), dtype=jnp.float32)
    qt = quantize_weight4(w, group_size=G)
    for m in (1, 5, 8):
        x = jnp.asarray(rng.normal(size=(m, IN)), dtype=jnp.float32)
        for li in (0, 2):
            sl = lambda a: a[li]
            ref = q4_matmul(x, QuantizedLinear4(sl(qt.q), sl(qt.s), sl(qt.zs)))
            got_l = int4_matmul(x, qt.q, qt.s, qt.zs,
                                layer=jnp.asarray(li, dtype=jnp.int32),
                                interpret=True, w4a8=False)
            got_u = int4_matmul(x, sl(qt.q), sl(qt.s), sl(qt.zs),
                                interpret=True, w4a8=False)
            np.testing.assert_allclose(np.asarray(got_l), np.asarray(ref),
                                       rtol=2e-2, atol=1e-4)
            np.testing.assert_allclose(np.asarray(got_u), np.asarray(ref),
                                       rtol=2e-2, atol=1e-4)


def test_pallas_int4_matmul_3d_batch_and_f32_out():
    from githubrepostorag_tpu.models.quant import q4_matmul
    from githubrepostorag_tpu.ops.pallas_int4 import int4_matmul

    rng = np.random.default_rng(10)
    IN, OUT = 32, 64
    w = jnp.asarray(rng.normal(0, 0.02, (IN, OUT)), dtype=jnp.float32)
    qt = quantize_weight4(w, group_size=G)
    x = jnp.asarray(rng.normal(size=(2, 3, IN)), dtype=jnp.float32)
    ref = q4_matmul(x, qt, preferred=jnp.float32)
    got = int4_matmul(x, qt.q, qt.s, qt.zs, out_dtype=jnp.float32, interpret=True,
                      w4a8=False)
    assert got.shape == (2, 3, OUT) and got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-2, atol=1e-4)


def _w4a8_oracle(x, qt, group_size):
    """XLA re-statement of the W4A8 math (ops/pallas_int4.py::_w4a8_matmul):
    per-row symmetric int8 activations, int32 group dots against the nibble
    values, group scales applied to the f32 partials, zero-point via the
    group row-sums.  Bit-for-bit the kernel's quantization decisions, so the
    interpret-mode comparison is tight."""
    m, in_dim = x.shape
    n_g = qt.s.shape[-2]
    gsz = in_dim // n_g
    assert gsz == group_size
    xf = np.asarray(x, dtype=np.float32)
    amax = np.abs(xf).max(axis=-1, keepdims=True)
    sxn = amax / 127.0
    with np.errstate(invalid="ignore"):
        xq = np.where(amax > 0, np.round(xf * (127.0 / np.maximum(amax, 1e-30))), 0.0)
    xq = xq.astype(np.int32)
    # unpack nibbles to int values, grouped [n_g, gsz, out]
    half = gsz // 2
    pg = np.asarray(qt.q).reshape(n_g, half, -1).astype(np.int32)
    w_int = np.concatenate([pg & 0xF, pg >> 4], axis=1)  # [n_g, gsz, out]
    s = np.asarray(qt.s, dtype=np.float32)
    zs = np.asarray(qt.zs, dtype=np.float32)
    xg = xq.reshape(m, n_g, gsz)
    p = np.einsum("mgj,gjo->gmo", xg, w_int)  # int32 partials
    acc = np.einsum("gmo,go->mo", p.astype(np.float32), s)
    r = xg.sum(axis=-1).astype(np.float32)  # [m, n_g]
    return sxn * (acc - r @ zs)


def test_w4a8_matches_oracle_and_reference():
    """The W4A8 route (interpret mode) must match the numpy oracle tightly
    (same integer math) and the exact bf16-dequant reference within the
    activation-quant tolerance — the documented accuracy-contract change."""
    from githubrepostorag_tpu.models.quant import q4_matmul
    from githubrepostorag_tpu.ops.pallas_int4 import int4_matmul

    rng = np.random.default_rng(11)
    IN, OUT, L = 64, 48, 3
    w = jnp.asarray(rng.normal(0, 0.02, (L, IN, OUT)), dtype=jnp.float32)
    qt = quantize_weight4(w, group_size=G)
    for m in (1, 5, 8):
        x = jnp.asarray(rng.normal(size=(m, IN)), dtype=jnp.float32)
        for li in (0, 2):
            sl = lambda a: a[li]
            oracle = _w4a8_oracle(x, QuantizedLinear4(sl(qt.q), sl(qt.s), sl(qt.zs)), G)
            ref = q4_matmul(x, QuantizedLinear4(sl(qt.q), sl(qt.s), sl(qt.zs)))
            got_l = int4_matmul(x, qt.q, qt.s, qt.zs,
                                layer=jnp.asarray(li, dtype=jnp.int32),
                                interpret=True, w4a8=True)
            got_u = int4_matmul(x, sl(qt.q), sl(qt.s), sl(qt.zs),
                                interpret=True, w4a8=True)
            for got in (got_l, got_u):
                # oracle: same int math, bf16 scale storage is shared — only
                # f32 summation order differs
                np.testing.assert_allclose(np.asarray(got), oracle,
                                           rtol=1e-4, atol=1e-5)
                # reference: differs by the per-row int8 activation quant
                np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                           rtol=5e-2, atol=5e-3)


def test_w4a8_3d_batch_f32_out_and_zero_rows():
    from githubrepostorag_tpu.ops.pallas_int4 import int4_matmul

    rng = np.random.default_rng(12)
    IN, OUT = 32, 64
    w = jnp.asarray(rng.normal(0, 0.02, (IN, OUT)), dtype=jnp.float32)
    qt = quantize_weight4(w, group_size=G)
    x = jnp.asarray(rng.normal(size=(2, 3, IN)), dtype=jnp.float32)
    x = x.at[0, 1].set(0.0)  # an all-zero row must not divide by zero
    oracle = _w4a8_oracle(x.reshape(6, IN), qt, G).reshape(2, 3, OUT)
    got = int4_matmul(x, qt.q, qt.s, qt.zs, out_dtype=jnp.float32,
                      interpret=True, w4a8=True)
    assert got.shape == (2, 3, OUT) and got.dtype == jnp.float32
    assert np.isfinite(np.asarray(got)).all()
    np.testing.assert_allclose(np.asarray(got), oracle, rtol=1e-4, atol=1e-5)


def test_fused_projections_match_unfused():
    """quant.fuse_projections (the single-chip serving layout) must be a
    pure re-layout: forward logits match the per-projection tree for both
    bf16 and quantized leaves, and the engine auto-fuses mesh-less trees."""
    from githubrepostorag_tpu.models.quant import fuse_projections
    from githubrepostorag_tpu.models.qwen2 import forward as qwen_forward

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    rng = np.random.default_rng(13)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 9)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(9)[None], (2, 9))
    ref, _ = qwen_forward(params, cfg, ids, pos)
    import copy

    fused = fuse_projections(copy.copy({**params, "layers": dict(params["layers"])}))
    assert "wqkv" in fused["layers"] and "wq" not in fused["layers"]
    got, _ = qwen_forward(fused, cfg, ids, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)
    # idempotent (a second Engine wrapping the same tree must not re-concat)
    again = fuse_projections(fused)
    assert again["layers"]["wqkv"] is fused["layers"]["wqkv"]
    assert set(again["layers"]) == set(fused["layers"])

    qparams = quantize_qwen2_params(
        init_params(cfg, jax.random.PRNGKey(4), dtype=jnp.float32), bits=4,
        group_size=G,
    )
    refq, _ = qwen_forward(qparams, cfg, ids, pos)
    fusedq = fuse_projections({**qparams, "layers": dict(qparams["layers"])})
    gotq, _ = qwen_forward(fusedq, cfg, ids, pos)
    np.testing.assert_allclose(np.asarray(gotq), np.asarray(refq), rtol=2e-5,
                               atol=2e-5)


def test_init_params_quantized_fused_geometry():
    cfg = Qwen2Config.tiny()
    p = init_params_quantized(cfg, bits=4, group_size=G, fuse=True)
    L, d = cfg.num_layers, cfg.hidden_size
    qkv_out = (cfg.num_heads + 2 * cfg.num_kv_heads) * cfg.head_dim
    assert p["layers"]["wqkv"].q.shape == (L, d // 2, qkv_out)
    assert p["layers"]["wgu"].q.shape == (L, d // 2, 2 * cfg.intermediate_size)
    assert "wq" not in p["layers"] and "wg" not in p["layers"]
