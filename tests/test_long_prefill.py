"""Sequence-parallel (ring attention) long-context prefill in serving:
token parity with the chunked single-device path, pool-content parity, and
mixed long+short scheduling.  Runs on the virtual 8-device CPU mesh.

Reference contrast: the reference caps context (vLLM --max-model-len 11712,
SURVEY.md §5.7) — this path *scales* it over the sp mesh axis instead.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from githubrepostorag_tpu.parallel import MeshPlan, make_mesh
from githubrepostorag_tpu.serving import Engine, SamplingParams

transformers = pytest.importorskip("transformers")
import torch  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    from githubrepostorag_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    hf_cfg = transformers.Qwen2Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg.to_dict())
    params = params_from_state_dict(model.state_dict(), cfg)
    return model, params, cfg


def _engine(params, cfg, **kw):
    defaults = dict(
        max_num_seqs=4, num_pages=64, page_size=8, max_seq_len=256,
        prefill_chunk=32, kv_dtype=jnp.float32, decode_burst=4,
    )
    defaults.update(kw)
    return Engine(params, cfg, **defaults)


def _sp_engine(params, cfg, threshold=40, **kw):
    return _engine(
        params, cfg, mesh=make_mesh(MeshPlan(sp=2)),
        sp_prefill_threshold=threshold, **kw,
    )


def test_ring_prefill_token_parity_with_chunked(tiny):
    model, params, cfg = tiny
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, size=48).tolist()
    sp = SamplingParams(max_tokens=12, temperature=0.0, stop_token_ids=(),
                        repetition_penalty=1.2)

    expected = _engine(params, cfg).generate([prompt], sp)[0].output_tokens

    eng = _sp_engine(params, cfg)
    got = eng.generate([prompt], sp)[0].output_tokens
    assert eng.sp_prefills == 1, "prompt above threshold must ride the sp path"
    assert got == expected

    # HF ground truth too: the ring path must match the reference model
    ids = torch.tensor([prompt])
    with torch.no_grad():
        hf = model.generate(ids, max_new_tokens=12, do_sample=False,
                            pad_token_id=0, eos_token_id=None,
                            repetition_penalty=1.2, use_cache=True)
    assert got == hf[0, len(prompt):].tolist()


def test_ring_prefill_pool_contents_match_chunked(tiny):
    """The KV pages the ring path writes must equal the chunked path's —
    decode after a ring prefill reads the same cache bytes."""
    _, params, cfg = tiny
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, size=56).tolist()
    sp = SamplingParams(max_tokens=1, temperature=0.0, stop_token_ids=())

    eng_a = _engine(params, cfg)
    eng_b = _sp_engine(params, cfg)
    eng_a.generate([prompt], sp)
    eng_b.generate([prompt], sp)
    assert eng_b.sp_prefills == 1
    # same admission order -> same allocator decisions -> same block tables
    k_a, k_b = np.asarray(eng_a._k_pages), np.asarray(eng_b._k_pages)
    v_a, v_b = np.asarray(eng_a._v_pages), np.asarray(eng_b._v_pages)
    np.testing.assert_allclose(k_a, k_b, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(v_a, v_b, rtol=1e-5, atol=1e-5)


def test_ring_prefill_kv_quant_matches_chunked(tiny):
    """kv_quant composes with the ring path: the ring commit quantizes per
    page with the SAME first-write-fixes-the-scale rule as the chunked
    path (serving/kv_cache.quantize_kv_paged).  In this geometry every
    prefill chunk covers whole pages, so both paths fix identical scales
    — decoded tokens must match exactly, and the int8 page bytes within a
    quantization step: the paths are NOT bit-identical, because the
    chunked path's later chunks attend over already-quantized earlier
    pages (its K/V inherit that rounding) while the ring path computes
    the whole prompt full-precision before one quantized commit."""
    _, params, cfg = tiny
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, cfg.vocab_size, size=48).tolist()
    sp = SamplingParams(max_tokens=12, temperature=0.0, stop_token_ids=())

    eng_a = _engine(params, cfg, kv_quant=True)
    eng_b = _sp_engine(params, cfg, kv_quant=True)
    expected = eng_a.generate([prompt], sp)[0].output_tokens
    got = eng_b.generate([prompt], sp)[0].output_tokens
    assert eng_b.sp_prefills == 1, "prompt above threshold must ride the sp path"
    assert got == expected
    for a, b in ((eng_a._k_pages, eng_b._k_pages),
                 (eng_a._v_pages, eng_b._v_pages)):
        diff = np.abs(np.asarray(a, np.int32) - np.asarray(b, np.int32))
        assert diff.max() <= 2, f"pages diverged beyond rounding: {diff.max()}"
    np.testing.assert_allclose(
        np.asarray(eng_a._k_scales), np.asarray(eng_b._k_scales),
        rtol=2e-2, atol=1e-7,
    )


def test_short_prompts_stay_on_chunked_path(tiny):
    _, params, cfg = tiny
    prompt = list(range(1, 21))  # 20 tokens < threshold 40
    sp = SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=())
    eng = _sp_engine(params, cfg)
    expected = _engine(params, cfg).generate([prompt], sp)[0].output_tokens
    assert eng.generate([prompt], sp)[0].output_tokens == expected
    assert eng.sp_prefills == 0


def test_mixed_long_short_continuous_batching(tiny):
    """A long prompt admitted while a short stream decodes: both must match
    their solo runs and the long one must use the sp path."""
    _, params, cfg = tiny
    rng = np.random.default_rng(2)
    short = [1, 2, 3, 4]
    long_p = rng.integers(0, cfg.vocab_size, size=64).tolist()
    sp = SamplingParams(max_tokens=10, temperature=0.0, stop_token_ids=())

    solo_short = _engine(params, cfg).generate([short], sp)[0].output_tokens
    solo_long = _engine(params, cfg).generate([long_p], sp)[0].output_tokens

    eng = _sp_engine(params, cfg)
    r1 = eng.add_request(short, sp)
    for _ in range(2):
        eng.step()
    r2 = eng.add_request(long_p, sp)
    done = {}
    while eng.has_work():
        for res in eng.step():
            done[res.request_id] = res
    assert eng.sp_prefills == 1
    assert done[r1].output_tokens == solo_short
    assert done[r2].output_tokens == solo_long


def test_sp_prefill_registers_prefix_for_chunked_followers(tiny):
    """A ring-prefilled prompt publishes its pages: a later SHORT prompt
    sharing the prefix (below the sp threshold) resumes from the cache."""
    _, params, cfg = tiny
    rng = np.random.default_rng(3)
    prefix = rng.integers(0, cfg.vocab_size, size=24).tolist()
    long_p = prefix + rng.integers(0, cfg.vocab_size, size=24).tolist()  # 48
    short_p = prefix + [7, 8, 9]  # 27 tokens, chunked path
    sp = SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=())

    eng = _sp_engine(params, cfg, threshold=40)
    eng.generate([long_p], sp)
    assert eng.sp_prefills == 1
    expected = _engine(params, cfg).generate([short_p], sp)[0].output_tokens
    got = eng.generate([short_p], sp)[0].output_tokens
    assert got == expected
    assert eng._allocator.hit_tokens == 24  # 3 pages resumed from the cache


def test_warmup_precompiles_ring_prefill_buckets(tiny):
    """ADVICE r02: warmup() must run a throwaway above-threshold prompt per
    ring-prefill width bucket, so the first live long prompt never pays the
    ring program's XLA compile mid-request.  With threshold 40 and
    max_seq_len 256 the width buckets a prompt can hit are 64/128/256 ->
    three sp prefills during warmup."""
    _, params, cfg = tiny
    eng = _sp_engine(params, cfg, threshold=40)
    eng.warmup()
    assert eng.sp_prefills == 3
    # engine state is clean after warmup: a real request still works and
    # takes the sp path without growing the compile count
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())
    long_p = np.random.default_rng(5).integers(0, cfg.vocab_size, 64).tolist()
    expected = _engine(params, cfg).generate([long_p], sp)[0].output_tokens
    assert eng.generate([long_p], sp)[0].output_tokens == expected
    assert eng.sp_prefills == 4


def test_warmup_skips_ring_prefill_when_disabled(tiny):
    _, params, cfg = tiny
    eng = _engine(params, cfg)  # no sp axis, no threshold
    eng.warmup()
    assert eng.sp_prefills == 0


# ---- segment-packed ring passes (sp_ring_pack, the default) ---------------


def test_packed_ring_multi_segment_token_parity(tiny):
    """Three long prompts admitted together flatten into ONE segment-packed
    ring pass; every stream's tokens must match the one-sequence-per-pass
    ring path AND the chunked single-device path run solo."""
    _, params, cfg = tiny
    rng = np.random.default_rng(11)
    lens = (48, 64, 56)  # mixed lengths, all above threshold 40, sum 168
    prompts = [rng.integers(0, cfg.vocab_size, n).tolist() for n in lens]
    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())

    solo = [_engine(params, cfg).generate([p], sp)[0].output_tokens
            for p in prompts]

    packed = _sp_engine(params, cfg)
    got = [r.output_tokens for r in packed.generate(prompts, sp)]
    assert packed.sp_prefills == 1, "three segments must share one ring pass"
    assert packed.sp_ring_segments == 3
    assert got == solo

    seq = _sp_engine(params, cfg, sp_ring_pack=False)
    got_seq = [r.output_tokens for r in seq.generate(prompts, sp)]
    assert seq.sp_prefills == 3, "baseline must dispatch one pass per prompt"
    assert got_seq == solo


def test_packed_ring_pool_contents_match_seq(tiny):
    """The packed pass commits every segment's K/V to the same pages with
    the same bytes as one-sequence-per-pass ring prefill — same admission
    order, same allocator decisions, same cache content."""
    _, params, cfg = tiny
    rng = np.random.default_rng(12)
    prompts = [rng.integers(0, cfg.vocab_size, n).tolist() for n in (56, 48)]
    sp = SamplingParams(max_tokens=1, temperature=0.0, stop_token_ids=())

    a = _sp_engine(params, cfg)
    b = _sp_engine(params, cfg, sp_ring_pack=False)
    a.generate(prompts, sp)
    b.generate(prompts, sp)
    assert a.sp_prefills == 1 and b.sp_prefills == 2
    np.testing.assert_allclose(np.asarray(a._k_pages), np.asarray(b._k_pages),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(a._v_pages), np.asarray(b._v_pages),
                               rtol=1e-5, atol=1e-5)


def test_packed_ring_kv_quant_parity(tiny):
    """kv_quant composes with segment packing: both ring flavors compute
    the whole prompt full-precision and quantize once at commit with the
    same first-write-fixes-the-scale rule, so decoded tokens must match
    exactly and the int8 page bytes within rounding."""
    _, params, cfg = tiny
    rng = np.random.default_rng(13)
    prompts = [rng.integers(0, cfg.vocab_size, n).tolist() for n in (48, 64)]
    sp = SamplingParams(max_tokens=10, temperature=0.0, stop_token_ids=())

    a = _sp_engine(params, cfg, kv_quant=True)
    b = _sp_engine(params, cfg, kv_quant=True, sp_ring_pack=False)
    got_a = [r.output_tokens for r in a.generate(prompts, sp)]
    got_b = [r.output_tokens for r in b.generate(prompts, sp)]
    assert a.sp_prefills == 1
    assert got_a == got_b
    for pa, pb in ((a._k_pages, b._k_pages), (a._v_pages, b._v_pages)):
        diff = np.abs(np.asarray(pa, np.int32) - np.asarray(pb, np.int32))
        assert diff.max() <= 2, f"pages diverged beyond rounding: {diff.max()}"


def test_packed_ring_token_budget_splits_passes(tiny):
    """A wave over the widest ladder width front-packs FIFO: the pass stops
    at the first prompt that doesn't fit and the leftover rides the NEXT
    step's pass — nothing starves, tokens match the solo runs."""
    _, params, cfg = tiny
    rng = np.random.default_rng(14)
    lens = (120, 120, 112)  # 240 fits the 256-wide cap, the third doesn't
    prompts = [rng.integers(0, cfg.vocab_size, n).tolist() for n in lens]
    sp = SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=())

    solo = [_engine(params, cfg).generate([p], sp)[0].output_tokens
            for p in prompts]
    eng = _sp_engine(params, cfg)
    got = [r.output_tokens for r in eng.generate(prompts, sp)]
    assert eng.sp_prefills == 2, "240-token pass then the 112-token leftover"
    assert eng.sp_ring_segments == 3
    assert got == solo


def test_packed_ring_mixed_with_short_chunked_rows(tiny):
    """Long prompts pack into a ring pass while a short prompt in the SAME
    admission wave rides the chunked path; all match their solo runs."""
    _, params, cfg = tiny
    rng = np.random.default_rng(16)
    long_a = rng.integers(0, cfg.vocab_size, 48).tolist()
    long_b = rng.integers(0, cfg.vocab_size, 44).tolist()
    short = [3, 1, 4, 1, 5]
    sp = SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=())

    solo = [_engine(params, cfg).generate([p], sp)[0].output_tokens
            for p in (long_a, long_b, short)]
    eng = _sp_engine(params, cfg)
    got = [r.output_tokens for r in eng.generate([long_a, long_b, short], sp)]
    assert eng.sp_prefills == 1 and eng.sp_ring_segments == 2
    assert got == solo


def test_packed_ring_registers_prefix_for_chunked_followers(tiny):
    """Packed-ring segments publish their pages like the one-sequence path:
    a later short prompt sharing a packed segment's prefix resumes from
    the cache on the chunked path."""
    _, params, cfg = tiny
    rng = np.random.default_rng(15)
    prefix = rng.integers(0, cfg.vocab_size, 24).tolist()
    long_a = prefix + rng.integers(0, cfg.vocab_size, 24).tolist()  # 48
    long_b = rng.integers(0, cfg.vocab_size, 56).tolist()
    short = prefix + [5, 6]  # 26 tokens, chunked path
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())

    eng = _sp_engine(params, cfg)
    eng.generate([long_a, long_b], sp)
    assert eng.sp_prefills == 1 and eng.sp_ring_segments == 2
    expected = _engine(params, cfg).generate([short], sp)[0].output_tokens
    assert eng.generate([short], sp)[0].output_tokens == expected
    assert eng._allocator.hit_tokens == 24  # 3 pages resumed from the cache
