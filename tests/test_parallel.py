"""parallel/ + training/: mesh factorisation, TP sharding rules, ring
attention vs dense parity, and the sharded train step — all on the virtual
8-device CPU mesh (conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from githubrepostorag_tpu.models.qwen2 import Qwen2Config, forward_with_attend, init_params
from githubrepostorag_tpu.ops.attention import dense_attention
from githubrepostorag_tpu.parallel import (
    MeshPlan,
    make_mesh,
    make_ring_attend,
    plan_for_devices,
    qwen2_param_specs,
    shard_params,
)
from githubrepostorag_tpu.training import init_train_state, make_train_step


def _batch(cfg, b=4, s=32, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, (b, s), dtype=np.int32)
    return {
        "input_ids": jnp.asarray(ids),
        "targets": jnp.asarray(np.roll(ids, -1, axis=1)),
        "mask": jnp.ones((b, s), dtype=jnp.int32),
    }


# ----------------------------------------------------------------- mesh ----


def test_mesh_axes_and_size():
    mesh = make_mesh(MeshPlan(dp=2, tp=2, sp=2))
    assert mesh.axis_names == ("dp", "pp", "tp", "sp", "ep")
    assert mesh.shape["dp"] == mesh.shape["tp"] == mesh.shape["sp"] == 2
    assert mesh.shape["pp"] == mesh.shape["ep"] == 1


def test_plan_from_string():
    from githubrepostorag_tpu.parallel import plan_from_string

    assert plan_from_string("dp:2,tp:4") == MeshPlan(dp=2, tp=4)
    assert plan_from_string("tp:4, sp:2") == MeshPlan(tp=4, sp=2)
    assert plan_from_string("") == MeshPlan()
    import pytest

    with pytest.raises(ValueError, match="MESH_SHAPE"):
        plan_from_string("tp:0")
    with pytest.raises(ValueError, match="MESH_SHAPE"):
        plan_from_string("xx:2")
    with pytest.raises(ValueError, match="twice"):
        plan_from_string("tp:4,tp:2")


def test_plan_for_devices_respects_head_divisibility():
    # 14 q heads / 2 kv heads (Qwen2-0.5B): tp must fall back to 2
    plan = plan_for_devices(8, num_heads=14, num_kv_heads=2, role="serve")
    assert plan.tp == 2 and plan.n_devices == 8
    plan = plan_for_devices(8, num_heads=28, num_kv_heads=4, role="serve")
    assert plan.tp == 4 and plan.dp == 2
    assert plan_for_devices(8, role="ingest") == MeshPlan(dp=8)
    tr = plan_for_devices(8, num_heads=4, num_kv_heads=2, role="train")
    assert tr.n_devices == 8 and tr.tp > 1 and tr.sp > 1


def test_mesh_too_many_devices_raises():
    with pytest.raises(ValueError):
        make_mesh(MeshPlan(dp=16))


# ------------------------------------------------------------- sharding ----


def test_qwen2_specs_shard_what_divides():
    cfg = Qwen2Config.tiny()  # 4 q heads, 2 kv heads, inter 128, vocab 512
    mesh = make_mesh(MeshPlan(dp=2, tp=2, sp=2))
    specs = qwen2_param_specs(cfg, mesh)
    assert specs["layers"]["wq"] == P(None, None, "tp")
    assert specs["layers"]["wo"] == P(None, "tp", None)
    assert specs["layers"]["wk"] == P(None, None, "tp")  # tp=2 divides 2 kv heads
    assert specs["layers"]["wg"] == P(None, None, "tp")
    assert specs["embed"] == P("tp", None)

    # tp=4 > 2 kv heads: kv projections must replicate, q-side still shards
    mesh4 = make_mesh(MeshPlan(tp=4))
    specs4 = qwen2_param_specs(cfg, mesh4)
    assert specs4["layers"]["wk"] == P(None, None, None)
    assert specs4["layers"]["wq"] == P(None, None, "tp")


def test_sharded_forward_matches_single_device():
    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 16), dtype=np.int32))
    pos = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (2, 16))
    ref = forward_with_attend(params, cfg, ids, pos)

    mesh = make_mesh(MeshPlan(tp=2))
    sharded = shard_params(params, mesh, qwen2_param_specs(cfg, mesh))
    out = jax.jit(lambda p: forward_with_attend(p, cfg, ids, pos))(sharded)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


# ------------------------------------------------------- ring attention ----


@pytest.mark.parametrize("plan", [MeshPlan(sp=8), MeshPlan(dp=2, tp=2, sp=2)])
def test_ring_attention_matches_dense(plan):
    mesh = make_mesh(plan)
    b, s, nq, nkv, hd = 4, 64, 4, 2, 16
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(keys[0], (b, s, nq, hd))
    k = jax.random.normal(keys[1], (b, s, nkv, hd))
    v = jax.random.normal(keys[2], (b, s, nkv, hd))
    attend = make_ring_attend(mesh, num_heads=nq, num_kv_heads=nkv)
    out = jax.jit(attend)(q, k, v)
    ref = dense_attention(q, k, v, causal=True, q_offset=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_grads_match_dense():
    mesh = make_mesh(MeshPlan(sp=4))
    b, s, nq, nkv, hd = 2, 32, 4, 2, 8
    keys = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(keys[0], (b, s, nq, hd))
    k = jax.random.normal(keys[1], (b, s, nkv, hd))
    v = jax.random.normal(keys[2], (b, s, nkv, hd))
    attend = make_ring_attend(mesh, num_heads=nq, num_kv_heads=nkv)

    g_ring = jax.jit(jax.grad(lambda q: (attend(q, k, v) ** 2).sum()))(q)
    g_ref = jax.grad(lambda q: (dense_attention(q, k, v, causal=True, q_offset=0) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref), atol=1e-4)


# ------------------------------------------------------------- training ----


def test_train_step_loss_decreases_on_full_mesh():
    cfg = Qwen2Config.tiny()
    mesh = make_mesh(MeshPlan(dp=2, tp=2, sp=2))
    step, opt = make_train_step(cfg, mesh)
    state = init_train_state(cfg, mesh, jax.random.PRNGKey(0), opt)
    batch = _batch(cfg)
    params, opt_state = state.params, state.opt_state
    losses = []
    for _ in range(4):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_train_loss_identical_across_mesh_shapes():
    cfg = Qwen2Config.tiny()
    batch = _batch(cfg, seed=3)
    vals = []
    for plan in (MeshPlan(), MeshPlan(dp=2, tp=2, sp=2)):
        mesh = make_mesh(plan)
        step, opt = make_train_step(cfg, mesh)
        state = init_train_state(cfg, mesh, jax.random.PRNGKey(0), opt)
        _, _, loss = step(state.params, state.opt_state, batch)
        vals.append(float(loss))
    assert abs(vals[0] - vals[1]) < 1e-3


# ------------------------------------------------- TP-sharded serving ----


def _greedy_engine_tokens(params, cfg, mesh, use_pallas):
    from githubrepostorag_tpu.serving import Engine, SamplingParams

    eng = Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                 max_seq_len=64, prefill_chunk=32, kv_dtype=jnp.float32,
                 use_pallas=use_pallas, decode_burst=8, mesh=mesh)
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7]]
    sp = SamplingParams(max_tokens=10, temperature=0.0, stop_token_ids=())
    return [r.output_tokens for r in eng.generate(prompts, sp)]


@pytest.mark.parametrize("use_pallas", [False, True])
def test_tp2_sharded_decode_token_identical(use_pallas):
    """TP=2 sharded serving (params, KV pools, and — on the pallas path —
    the staged kernel inside a shard_map island) must produce exactly the
    single-device greedy tokens.  vLLM --tensor-parallel-size equivalent."""
    from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params

    cfg = Qwen2Config.tiny()  # 4 q heads / 2 kv heads -> tp=2 divides both
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    ref = _greedy_engine_tokens(params, cfg, None, use_pallas)
    mesh = make_mesh(MeshPlan(tp=2))
    out = _greedy_engine_tokens(params, cfg, mesh, use_pallas)
    assert out == ref


def test_serve_plan_caps_tp_by_kv_heads():
    plan = plan_for_devices(8, num_heads=4, num_kv_heads=2, role="serve")
    assert plan.tp == 2 and plan.dp == 4
