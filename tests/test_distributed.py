"""maybe_initialize_distributed: env contract + a real single-process
jax.distributed runtime (subprocess so the test process's backend stays
untouched)."""

import os
import subprocess
import sys


def test_noop_without_env(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_DISTRIBUTED", raising=False)
    from githubrepostorag_tpu.parallel import maybe_initialize_distributed

    assert maybe_initialize_distributed() is False


def test_single_process_runtime_initializes():
    import socket

    with socket.socket() as probe:  # grab a free port to avoid collisions
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
        JAX_NUM_PROCESSES="1",
        JAX_PROCESS_ID="0",
    )
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');\n"
        "from githubrepostorag_tpu.parallel import maybe_initialize_distributed\n"
        "assert maybe_initialize_distributed() is True\n"
        "assert maybe_initialize_distributed() is True  # idempotent\n"
        "assert jax.process_count() == 1\n"
        "print('DIST OK')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True, text=True,
        timeout=120, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DIST OK" in proc.stdout
