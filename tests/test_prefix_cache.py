"""Automatic prefix caching: page-aligned KV reuse across requests
(the in-tree analog of vLLM's --enable-prefix-caching; the reference's
engine is vLLM itself, helm/templates/qwen-deployment.yaml:21-33).

Covers: allocator refcount/LRU mechanics, hit accounting, token-identical
outputs vs an uncached engine (including repetition-penalty sampling, which
depends on presence marks for the *skipped* prefix), shared-prefix fan-out,
concurrent twins, and eviction under pool pressure.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from githubrepostorag_tpu.serving import Engine, SamplingParams
from githubrepostorag_tpu.serving.kv_cache import (
    OutOfPages,
    PrefixCachingAllocator,
    page_hashes,
)

transformers = pytest.importorskip("transformers")
import torch  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    from githubrepostorag_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    hf_cfg = transformers.Qwen2Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg.to_dict())
    params = params_from_state_dict(model.state_dict(), cfg)
    return model, params, cfg


def _engine(params, cfg, **kw):
    defaults = dict(
        max_num_seqs=4, num_pages=64, page_size=8, max_seq_len=128,
        prefill_chunk=32, kv_dtype=jnp.float32, decode_burst=4,
    )
    defaults.update(kw)
    return Engine(params, cfg, **defaults)


# ------------------------------------------------------------- allocator --


def test_page_hashes_chain_identity():
    ps = 4
    a = page_hashes([1, 2, 3, 4, 5, 6, 7, 8, 9], ps)
    b = page_hashes([1, 2, 3, 4, 5, 6, 7, 8], ps)
    assert len(a) == 2 and len(b) == 2
    assert a == b  # same full pages -> same chain
    # a different token in page 0 changes EVERY downstream hash
    c = page_hashes([9, 2, 3, 4, 5, 6, 7, 8], ps)
    assert c[0] != b[0] and c[1] != b[1]
    # same page-1 tokens under a different prefix do not collide
    assert len([1, 2, 3]) == 3 and page_hashes([1, 2, 3], ps) == []


def test_allocator_share_refcount_lru_evict():
    al = PrefixCachingAllocator(4)
    h = page_hashes(list(range(8)), 4)  # two pages
    pages = al.allocate(2)
    al.register(h[0], pages[0])
    al.register(h[1], pages[1])
    # second claimant shares, refcount 2
    shared = al.share(h)
    assert shared == pages
    al.release(pages)  # first owner leaves -> rc 1, still live
    assert al.free_count == 2
    al.release(pages)  # second leaves -> rc 0, parked in LRU (still cached)
    assert al.free_count == 4
    # a new match revives the parked pages
    again = al.share(h)
    assert again == pages
    al.release(again)
    # exhaust the pool: parked cached pages get evicted for fresh allocation
    fresh = al.allocate(4)
    assert sorted(fresh) == [0, 1, 2, 3]
    assert al.share(h) == []  # evicted -> no longer matchable
    with pytest.raises(OutOfPages):
        al.allocate(1)
    al.release(fresh)
    assert al.free_count == 4


def test_eviction_takes_chain_tail_first():
    """Chains match head-first, so eviction must consume them tail-first:
    after evicting one page of a parked 2-page chain, the head must still
    be shareable (parking in block-table order would strand the whole
    chain)."""
    al = PrefixCachingAllocator(2)
    h = page_hashes(list(range(8)), 4)
    pages = al.allocate(2)
    al.register(h[0], pages[0])
    al.register(h[1], pages[1])
    al.release(pages)
    assert al.allocate(1) == [pages[1]]  # tail evicted, head survives
    assert al.share(h) == [pages[0]]


def test_releasable_count_excludes_shared_pages():
    al = PrefixCachingAllocator(4)
    h = page_hashes(list(range(8)), 4)
    pages = al.allocate(2)
    al.register(h[0], pages[0])
    al.register(h[1], pages[1])
    other = al.share(h)  # rc 2 on both
    assert al.releasable_count(pages) == 0  # releasing us frees nothing
    al.release(other)
    assert al.releasable_count(pages) == 2


def test_can_admit_accounts_for_parked_matches():
    """Matched pages parked in the LRU must not double-count as allocatable
    free pages — sharing them removes them from the evictable set."""
    al = PrefixCachingAllocator(4)
    h = page_hashes(list(range(8)), 4)
    pages = al.allocate(2)
    al.register(h[0], pages[0])
    al.register(h[1], pages[1])
    al.release(pages)  # both parked in LRU; 2 pages on the free list
    assert al.can_admit(h, 4)  # share 2 parked + allocate 2 free: exact fit
    assert not al.can_admit(h, 5)  # would need 3 fresh, only 2 free remain
    assert al.can_admit([], 4)  # no sharing: all 4 are allocatable
    assert not al.can_admit([], 5) and al.can_admit([], 5, extra_free=1)


def test_allocator_register_first_writer_wins():
    al = PrefixCachingAllocator(4)
    h = page_hashes(list(range(4)), 4)
    a = al.allocate(1)
    b = al.allocate(1)
    al.register(h[0], a[0])
    al.register(h[0], b[0])  # concurrent twin: mapping keeps the first page
    assert al.share(h) == a
    al.release(a)  # the share's ref
    al.release(a)  # the owner's ref -> parked
    al.release(b)  # unregistered page goes straight to the free list
    assert al.free_count == 4


# ---------------------------------------------------------------- engine --


def test_repeat_prompt_hits_cache_and_matches_uncached(tiny):
    _, params, cfg = tiny
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, size=40).tolist()  # 5 full pages
    # repetition penalty active: outputs depend on presence marks for the
    # SKIPPED prefix — the regression this test pins down
    sp = SamplingParams(max_tokens=12, temperature=0.0, stop_token_ids=(),
                        repetition_penalty=1.3)

    ref = _engine(params, cfg, prefix_caching=False)
    expected = ref.generate([prompt], sp)[0].output_tokens

    eng = _engine(params, cfg)
    first = eng.generate([prompt], sp)[0].output_tokens
    assert eng._allocator.hit_tokens == 0
    second = eng.generate([prompt], sp)[0].output_tokens
    # (40-1)//8 = 4 pages = 32 tokens served from cache on the repeat
    assert eng._allocator.hit_tokens == 32
    assert first == expected
    assert second == expected


def test_shared_prefix_fanout_matches_uncached(tiny):
    """RAG shape: one long shared system/context prefix, different tails."""
    _, params, cfg = tiny
    rng = np.random.default_rng(1)
    prefix = rng.integers(0, cfg.vocab_size, size=24).tolist()  # 3 full pages
    tails = [rng.integers(0, cfg.vocab_size, size=n).tolist() for n in (5, 9, 13)]
    prompts = [prefix + t for t in tails]
    sp = SamplingParams(max_tokens=10, temperature=0.0, stop_token_ids=(),
                        repetition_penalty=1.2)

    ref = _engine(params, cfg, prefix_caching=False)
    expected = [r.output_tokens for r in ref.generate(prompts, sp)]

    eng = _engine(params, cfg)
    seed = eng.generate([prompts[0]], sp)[0].output_tokens
    assert seed == expected[0]
    rest = [r.output_tokens for r in eng.generate(prompts[1:], sp)]
    assert rest == expected[1:]
    # both followers reused the 3-page (24-token) prefix
    assert eng._allocator.hit_tokens == 48


def test_concurrent_identical_prompts_correct(tiny):
    """Twins admitted in the same wave: the second may or may not share
    (registration is chunk-granular) but outputs must be identical."""
    _, params, cfg = tiny
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, cfg.vocab_size, size=40).tolist()
    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
    eng = _engine(params, cfg)
    results = eng.generate([prompt, prompt], sp)
    assert results[0].output_tokens == results[1].output_tokens
    solo = _engine(params, cfg, prefix_caching=False).generate([prompt], sp)[0]
    assert results[0].output_tokens == solo.output_tokens


def test_cache_survives_page_pressure_and_accounting_balances(tiny):
    """Fill the pool with distinct prompts until eviction must happen, then
    re-run the first prompt; every request completes and the allocator ends
    balanced (free_count == num_pages)."""
    _, params, cfg = tiny
    eng = _engine(params, cfg, num_pages=16, max_num_seqs=2, max_seq_len=64)
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, cfg.vocab_size, size=24).tolist() for _ in range(6)]
    for p in prompts:
        eng.generate([p], sp)
    assert eng.generate([prompts[0]], sp)[0].output_tokens  # after eviction churn
    assert eng._allocator.free_count == eng._allocator.num_pages
    assert not eng.has_work()


async def test_prefix_hits_reach_prometheus(tiny):
    """The async driver exports the cumulative cache-hit stat as a counter
    on /metrics (observability parity: SURVEY.md §5.5)."""
    from githubrepostorag_tpu.metrics import render
    from githubrepostorag_tpu.serving.async_engine import AsyncEngine

    _, params, cfg = tiny
    rng = np.random.default_rng(9)
    prompt = rng.integers(0, cfg.vocab_size, 40).tolist()
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())
    aeng = AsyncEngine(_engine(params, cfg))
    await aeng.generate(prompt, sp)
    await aeng.generate(prompt, sp)  # repeat: 32 tokens from the cache
    await aeng.stop()
    text = render().decode()
    line = next(
        l for l in text.splitlines()
        if l.startswith("rag_prefix_cache_hit_tokens_total")
    )
    assert float(line.split()[-1]) >= 32.0, line


def test_cached_prefix_skips_prefill_compute(tiny):
    """The repeat run must dispatch fewer prefill chunks: its prefill starts
    at the cached boundary."""
    _, params, cfg = tiny
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, cfg.vocab_size, size=64).tolist()  # 8 pages
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())
    eng = _engine(params, cfg, prefill_chunk=16)  # 4 chunks uncached
    eng.generate([prompt], sp)
    req_id = eng.add_request(prompt, sp)
    req = eng._requests[req_id]
    eng.step()  # admission happens here
    # (64-1)//8 = 7 pages cached -> prefill starts at 56, one chunk left
    assert req.cached_tokens == 56
    while eng.has_work():
        eng.step()
    assert len(req.output) == 4
