"""Disaggregated prefill/decode serving (serving/disagg.py + the
MultiAsyncEngine handoff): role-assignment viability, fused-vs-disagg
token identity (including prefix-dedup repeat traffic, int8 KV, and spec
decode on the decode replica), the fused fallback when the transfer dies,
role-aware fleet stats merging, and the zero-live-recompile contract
across mixed handoff / dedup / short-prompt traffic.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.config import reload_settings
from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
from githubrepostorag_tpu.serving import Engine, SamplingParams
from githubrepostorag_tpu.serving.disagg import InProcessTransport, assign_roles
from githubrepostorag_tpu.serving.multi_engine import MultiAsyncEngine


@pytest.fixture(scope="module")
def tiny():
    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    return cfg, params


def _engine(params, cfg, **kw):
    defaults = dict(max_num_seqs=2, num_pages=32, page_size=4, max_seq_len=64,
                    kv_dtype=jnp.float32, decode_burst=8,
                    kv_tier="on", kv_host_pool_pages=64)
    defaults.update(kw)
    return Engine(params, cfg, **defaults)


def _fleet(monkeypatch, params, cfg, n=3, prefill=1, **kw):
    """A DISAGG=on fleet: env is set + settings reloaded BEFORE construction
    because assign_roles reads get_settings() at fleet-build time."""
    monkeypatch.setenv("DISAGG", "on")
    monkeypatch.setenv("DISAGG_PREFILL_REPLICAS", str(prefill))
    reload_settings()
    return MultiAsyncEngine([_engine(params, cfg, **kw) for _ in range(n)])


def _prompts(n, seed=11):
    rng = np.random.default_rng(seed)
    # 12+ tokens at page_size=4: every prompt has >=2 full shippable pages
    return [rng.integers(0, 512, 12 + i).tolist() for i in range(n)]


def _sp(max_tokens=8):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0,
                          stop_token_ids=())


# -------------------------------------------------------- role assignment --


def test_assign_roles_off_or_unviable_stays_fused(tiny, monkeypatch):
    cfg, params = tiny
    # DISAGG=off (the default): everything fused, disagg plane dark
    multi = MultiAsyncEngine([_engine(params, cfg) for _ in range(3)])
    assert multi.disagg_stats() == {
        "enabled": False, "prefill_replicas": [], "decode_replicas": [],
        "handoffs": 0, "pages_shipped": 0, "pages_deduped": 0,
        "fallbacks": {}, "transport": None,
    }
    assert all(ae.role == "fused" for ae in multi._engines)

    # DISAGG=on but only one replica: nothing to split
    solo = _fleet(monkeypatch, params, cfg, n=1)
    assert not solo.disagg_stats()["enabled"]
    assert solo._engines[0].role == "fused"

    # DISAGG=on but an untiered replica: the handoff has no host tier to
    # move pages through, so the whole fleet stays fused
    monkeypatch.setenv("DISAGG", "on")
    reload_settings()
    mixed = MultiAsyncEngine([_engine(params, cfg),
                              _engine(params, cfg, kv_tier="off")])
    assert not mixed.disagg_stats()["enabled"]
    assert all(ae.role == "fused" for ae in mixed._engines)


def test_assign_roles_splits_and_clamps(tiny, monkeypatch):
    cfg, params = tiny
    multi = _fleet(monkeypatch, params, cfg, n=3, prefill=1)
    ds = multi.disagg_stats()
    assert ds["enabled"]
    assert ds["prefill_replicas"] == ["r0"]
    assert ds["decode_replicas"] == ["r1", "r2"]
    assert ds["transport"]["kind"] == "in_process"

    # DISAGG_PREFILL_REPLICAS is clamped so >=1 decode replica remains
    greedy = _fleet(monkeypatch, params, cfg, n=3, prefill=5)
    ds = greedy.disagg_stats()
    assert ds["prefill_replicas"] == ["r0", "r1"]
    assert ds["decode_replicas"] == ["r2"]


def test_assign_roles_keeps_spares_fused(tiny, monkeypatch):
    cfg, params = tiny
    monkeypatch.setenv("DISAGG", "on")
    monkeypatch.setenv("DISAGG_PREFILL_REPLICAS", "1")
    reload_settings()
    engines = [_engine(params, cfg) for _ in range(3)]
    multi = MultiAsyncEngine(engines, spares=1)
    roles = {ae.replica: ae.role for ae in multi._engines}
    assert list(roles.values()).count("prefill") == 1
    assert list(roles.values()).count("decode") == 1
    # the warm spare is neither: it joins as a decoder only when activated
    spare = [ae for ae in multi._engines if ae.lifecycle != "active"]
    assert len(spare) == 1 and spare[0].role == "fused"


# -------------------------------------------------------------- parity -----


async def test_disagg_token_identical_to_fused(tiny, monkeypatch):
    """The acceptance bar: the same prompts through a disaggregated fleet
    produce exactly the tokens a fused engine produces, with real handoffs
    (pages shipped, decode replicas importing) behind them."""
    cfg, params = tiny
    prompts = _prompts(4)
    sp = _sp()
    expected = [r.output_tokens
                for r in _engine(params, cfg).generate(prompts, sp)]

    multi = _fleet(monkeypatch, params, cfg, n=3, prefill=1)
    try:
        results = await asyncio.gather(
            *[multi.generate(p, sp) for p in prompts])
        assert [r.output_tokens for r in results] == expected
        ds = multi.disagg_stats()
        assert ds["handoffs"] == len(prompts)
        assert ds["pages_shipped"] > 0
        assert ds["fallbacks"] == {}
        imported = sum(ae.engine.kv_pages_imported
                       for ae in multi._engines if ae.role == "decode")
        assert imported > 0
        exported = sum(ae.engine.kv_pages_exported
                       for ae in multi._engines if ae.role == "prefill")
        assert exported >= imported
    finally:
        await multi.stop()


async def test_disagg_repeat_traffic_dedups_the_wire(tiny, monkeypatch):
    """A decode replica already holding the prefix content-hash-deduped
    pays nothing: replaying the same prompts through a 1-prefill/1-decode
    fleet must dedup on the second pass instead of re-storing pages."""
    cfg, params = tiny
    prompts = _prompts(2, seed=5)
    sp = _sp()
    expected = [r.output_tokens
                for r in _engine(params, cfg).generate(prompts, sp)]

    multi = _fleet(monkeypatch, params, cfg, n=2, prefill=1)
    try:
        first = [await multi.generate(p, sp) for p in prompts]
        assert [r.output_tokens for r in first] == expected
        ds = multi.disagg_stats()
        shipped_1, deduped_1 = ds["pages_shipped"], ds["pages_deduped"]
        assert shipped_1 > 0

        second = [await multi.generate(p, sp) for p in prompts]
        assert [r.output_tokens for r in second] == expected
        ds = multi.disagg_stats()
        # with a single decode replica the replay lands where the pages
        # already live: the wire dedups instead of shipping again
        assert ds["pages_deduped"] > deduped_1
        assert ds["pages_shipped"] - shipped_1 < shipped_1
    finally:
        await multi.stop()


@pytest.mark.parametrize("extra", [
    pytest.param(dict(kv_quant=True), id="int8_kv"),
    pytest.param(dict(spec_ngram_k=3), id="spec_decode"),
])
async def test_disagg_parity_composes_with_quant_and_spec(
        tiny, monkeypatch, extra):
    """The handoff must compose with the KV features riding the same
    pools: int8 KV pages ship with their scales, and the decode replica
    spec-decodes against imported pages — token-identical either way."""
    cfg, params = tiny
    prompts = _prompts(3, seed=7)
    sp = _sp()
    expected = [r.output_tokens
                for r in _engine(params, cfg, **extra).generate(prompts, sp)]

    multi = _fleet(monkeypatch, params, cfg, n=3, prefill=1, **extra)
    try:
        results = await asyncio.gather(
            *[multi.generate(p, sp) for p in prompts])
        assert [r.output_tokens for r in results] == expected
        assert multi.disagg_stats()["handoffs"] == len(prompts)
    finally:
        await multi.stop()


async def test_short_prompt_skips_the_handoff(tiny, monkeypatch):
    """A prompt without a single full shippable page has nothing a peer
    could reuse: it goes straight to a decode replica, no handoff."""
    cfg, params = tiny
    sp = _sp(max_tokens=4)
    expected = _engine(params, cfg).generate([[1, 2, 3, 4]], sp)[0]

    multi = _fleet(monkeypatch, params, cfg, n=2, prefill=1)
    try:
        res = await multi.generate([1, 2, 3, 4], sp)
        assert res.output_tokens == expected.output_tokens
        ds = multi.disagg_stats()
        assert ds["handoffs"] == 0 and ds["fallbacks"] == {}
        # it decoded where the decoders live
        assert multi.router_stats()["per_replica"]["r1"]["routed"] == 1
    finally:
        await multi.stop()


# ------------------------------------------------------------- fallback ----


async def test_transfer_failure_finishes_fused(tiny, monkeypatch):
    """A dead wire mid-handoff must not surface to the caller: the request
    finishes fused on the prefill replica — token-identically — and the
    fallback is accounted."""
    cfg, params = tiny
    prompts = _prompts(2, seed=9)
    sp = _sp()
    expected = [r.output_tokens
                for r in _engine(params, cfg).generate(prompts, sp)]

    multi = _fleet(monkeypatch, params, cfg, n=2, prefill=1)

    async def dead_wire(src, dst, hashes):
        raise ConnectionError("wire down")

    monkeypatch.setattr(multi._transport, "transfer", dead_wire)
    try:
        results = [await multi.generate(p, sp) for p in prompts]
        assert [r.output_tokens for r in results] == expected
        ds = multi.disagg_stats()
        assert ds["handoffs"] == 0
        assert ds["fallbacks"]["transfer_error"] == len(prompts)
        # fused fallback ran on the prefill replica that holds the prefix
        assert multi.router_stats()["per_replica"]["r0"]["routed"] > 0
    finally:
        await multi.stop()


async def test_no_decode_replica_finishes_fused(tiny, monkeypatch):
    """Draining the only decode replica mid-flight leaves nowhere to ship
    to: requests finish fused on the prefill side instead of erroring."""
    cfg, params = tiny
    sp = _sp(max_tokens=4)
    prompt = _prompts(1, seed=13)[0]
    expected = _engine(params, cfg).generate([prompt], sp)[0]

    multi = _fleet(monkeypatch, params, cfg, n=2, prefill=1)
    try:
        await multi.drain("r1")
        res = await multi.generate(prompt, sp)
        assert res.output_tokens == expected.output_tokens
        assert multi.disagg_stats()["fallbacks"]["no_decode_replica"] == 1
    finally:
        await multi.stop()


# ------------------------------------------------------- role-aware stats --


def test_merge_rows_excludes_prefill_from_rate_means():
    """The fleet merge's mean_rows seam: a prefill-specialized replica's
    idle decode-side rates must not drag the fleet means, while counters
    still sum across every replica."""
    prefill_row = {"requests": 10, "acceptance_rate": 0.0, "role": "prefill"}
    decode_row = {"requests": 30, "acceptance_rate": 0.8, "role": "decode"}
    merged = MultiAsyncEngine._merge_rows([prefill_row, decode_row],
                                          mean_rows=[decode_row])
    assert merged["requests"] == 40  # counters: SUM over everyone
    assert merged["acceptance_rate"] == pytest.approx(0.8)  # mean: decode only
    # without the seam the prefill zero would halve the fleet rate
    naive = MultiAsyncEngine._merge_rows([prefill_row, decode_row])
    assert naive["acceptance_rate"] == pytest.approx(0.4)


async def test_fleet_stats_expose_roles_and_per_role(tiny, monkeypatch):
    cfg, params = tiny
    multi = _fleet(monkeypatch, params, cfg, n=3, prefill=1)
    try:
        await multi.generate(_prompts(1)[0], _sp(max_tokens=4))
        stats = multi.stats()
        by_replica = {ae.replica: s["role"] for ae, s in
                      zip(multi._engines, stats["per_replica"])}
        assert by_replica == {"r0": "prefill", "r1": "decode", "r2": "decode"}
        assert set(stats["per_role"]) == {"prefill", "decode"}
        # the per-role sub-aggregates split the fleet's admission counter
        assert (stats["per_role"]["prefill"]["requests_admitted"]
                + stats["per_role"]["decode"]["requests_admitted"]
                == stats["requests_admitted"])
        assert stats["router"]["disagg"]["enabled"]
    finally:
        await multi.stop()


# ------------------------------------------------------ compile discipline --


async def test_disagg_zero_live_compiles(tiny, monkeypatch):
    """Mixed handoff / dedup-replay / short-prompt traffic after warmup
    compiles ZERO new XLA programs: export gathers and import-side
    fault-in scatters ride the warmup-precompiled migrate buckets on both
    roles, and import itself touches only host dicts."""
    from tests.helpers.compile_guard import compile_guard, watchdog_counter

    cfg, params = tiny
    prompts = _prompts(2, seed=17)
    sp = _sp(max_tokens=4)

    multi = _fleet(monkeypatch, params, cfg, n=3, prefill=1)
    try:
        for ae in multi._engines:
            ae.engine.warmup()
        # prime outside the guard: first traffic starts the driver threads
        await multi.generate(prompts[0], sp)
        with compile_guard(watchdog_counter(), label="mixed disagg traffic"):
            await asyncio.gather(
                multi.generate(prompts[1], sp),   # fresh handoff
                multi.generate(prompts[0], sp),   # dedup replay
                multi.generate([1, 2, 3], sp),    # shippable=0: no handoff
            )
        assert multi.disagg_stats()["handoffs"] >= 2
    finally:
        await multi.stop()
