"""CassandraVectorStore CQL-shape tests against a fake session.

The reference shipped an audit INSERT that could never work (``?``
placeholders on an unprepared statement — ingest_controller.py:419-435,
failure swallowed); these tests pin the exact CQL text + parameter shapes
of every statement this store issues so that class of bug cannot ship.
Marked unit tests (no driver needed — the class is constructed without
__init__); live-infra coverage is the ``integration`` marker below.
"""

from __future__ import annotations

from types import SimpleNamespace

import numpy as np
import pytest

from githubrepostorag_tpu.store.cassandra import CassandraVectorStore
from githubrepostorag_tpu.store.base import Doc


class FakeResult:
    def __init__(self, rows):
        self._rows = rows

    def __iter__(self):
        return iter(self._rows)

    def one(self):
        return self._rows[0] if self._rows else None


class FakePrepared:
    def __init__(self, cql):
        self.cql = cql


class FakeSession:
    """Records every (cql, params) pair; scripted results by substring."""

    def __init__(self):
        self.calls: list[tuple[str, object]] = []
        self.results: list[tuple[str, list]] = []  # (cql substring, rows)

    def script(self, substring: str, rows: list) -> None:
        self.results.append((substring, rows))

    def prepare(self, cql: str) -> FakePrepared:
        self.calls.append(("PREPARE", cql))
        return FakePrepared(cql)

    def execute(self, cql, params=None):
        text = cql.cql if isinstance(cql, FakePrepared) else cql
        self.calls.append((text, params))
        for sub, rows in self.results:
            if sub in text:
                return FakeResult(rows)
        return FakeResult([])


def make_store(session=None) -> tuple[CassandraVectorStore, FakeSession]:
    session = session or FakeSession()
    store = CassandraVectorStore.__new__(CassandraVectorStore)
    store._session = session
    store._ks = "vector_store"
    store._dim = 4
    store._known_tables = set()
    store._insert_stmts = {}
    return store, session


def executed(session, substring):
    return [c for c in session.calls if substring in str(c[0])]


def test_ensure_table_issues_schema_and_sai_indexes():
    store, s = make_store()
    store.upsert("embeddings", [])
    ddl = [c[0] for c in s.calls]
    assert any("CREATE TABLE IF NOT EXISTS vector_store.embeddings" in d for d in ddl)
    table_ddl = next(d for d in ddl if "CREATE TABLE" in d)
    for col in ("row_id TEXT PRIMARY KEY", "body_blob TEXT",
                "vector VECTOR<FLOAT, 4>", "metadata_s MAP<TEXT, TEXT>"):
        assert col in table_ddl
    assert any("StorageAttachedIndex" in d and "(vector)" in d for d in ddl)
    assert any("entries(metadata_s)" in d for d in ddl)


def test_upsert_uses_prepared_statement_with_question_marks():
    """Prepared statements take '?' placeholders; simple statements take
    '%s' — mixing them is the reference's shipped bug class."""
    store, s = make_store()
    doc = Doc("id1", "hello", {"topics": "kafka"}, np.asarray([1, 2, 3, 4], dtype=np.float32))
    assert store.upsert("embeddings", [doc]) == 1
    prepare = next(c for c in s.calls if c[0] == "PREPARE")
    assert prepare[1].count("?") == 4 and "%s" not in prepare[1]
    exec_call = next(c for c in s.calls if isinstance(c[0], str) and c[0].startswith("INSERT"))
    cql, params = exec_call
    assert params == ("id1", "hello", [1.0, 2.0, 3.0, 4.0], {"topics": "kafka"})


def test_unprepared_statements_use_percent_s_never_question_marks():
    store, s = make_store()
    store.get("embeddings", "id1")
    store.count("embeddings")
    store.delete("embeddings", ["id1"])
    store.find_by_metadata("embeddings", {"repo": "svc"})
    for cql, params in s.calls:
        if isinstance(cql, str) and not cql.startswith(("CREATE", "PREPARE", "INSERT")):
            assert "?" not in cql, f"unprepared statement with '?': {cql}"


def test_search_ann_cql_shape_and_params():
    store, s = make_store()
    row = SimpleNamespace(row_id="r1", body_blob="text", metadata_s={"repo": "svc"}, score=0.9)
    s.script("ORDER BY vector ANN OF", [row])
    hits = store.search("embeddings", np.asarray([0.1, 0.2, 0.3, 0.4]), k=5,
                        filter={"repo": "svc"})
    cql, params = executed(s, "ANN OF")[0]
    assert "similarity_cosine(vector, %s)" in cql
    assert "WHERE metadata_s[%s] = %s" in cql
    assert cql.endswith("ORDER BY vector ANN OF %s LIMIT %s")
    # params: [vector, key, val, vector, k] — ANN OF needs the vector twice
    assert params[0] == params[-2] == pytest.approx([0.1, 0.2, 0.3, 0.4])
    assert params[1:3] == ["repo", "svc"] and params[-1] == 5
    assert [h.doc.doc_id for h in hits] == ["r1"] and hits[0].score == pytest.approx(0.9)


def test_search_shredded_topics_filter_uses_entry_form():
    store, s = make_store()
    row = SimpleNamespace(row_id="r1", body_blob="t", metadata_s={}, score=1.0)
    s.script("topics:kafka", [row])
    store.search("embeddings", np.asarray([0.0, 0.0, 0.0, 1.0]), k=3,
                 filter={"topics": "Kafka"})
    cql, params = executed(s, "ANN OF")[0]
    assert params[1:3] == ["topics:kafka", "1"]  # lowered, entry-form


def test_search_falls_back_to_plain_equality_for_preshred_rows():
    """Rows ingested before shredding carry only metadata_s['topics']='kafka';
    when the entry form matches nothing the store must retry with plain
    equality instead of silently returning zero rows."""
    store, s = make_store()
    old_row = SimpleNamespace(row_id="old", body_blob="t", metadata_s={"topics": "kafka"}, score=1.0)

    class TwoPhase(FakeSession):
        def execute(self, cql, params=None):
            text = cql.cql if isinstance(cql, FakePrepared) else cql
            self.calls.append((text, params))
            if "ANN OF" in text and params and "topics:kafka" in params:
                return FakeResult([])  # entry form: no pre-shred rows
            if "ANN OF" in text:
                return FakeResult([old_row])
            return FakeResult([])

    store, s = make_store(TwoPhase())
    hits = store.search("embeddings", np.asarray([0.0, 0.0, 0.0, 1.0]), k=3,
                        filter={"topics": "kafka"})
    assert [h.doc.doc_id for h in hits] == ["old"]
    ann_calls = executed(s, "ANN OF")
    assert len(ann_calls) == 2
    assert "topics:kafka" in ann_calls[0][1] and "kafka" in ann_calls[1][1]


def test_find_by_metadata_cql_and_fallback():
    store, s = make_store()
    row = SimpleNamespace(row_id="r2", body_blob="b", metadata_s={"module": "api"})
    s.script("WHERE metadata_s", [row])
    docs = store.find_by_metadata("embeddings", {"module": "api"}, limit=7)
    cql, params = executed(s, "WHERE metadata_s")[0]
    assert cql.startswith("SELECT row_id, body_blob, metadata_s, vector FROM vector_store.embeddings")
    assert params == ["module", "api", 7]
    assert [d.doc_id for d in docs] == ["r2"]


def test_delete_checks_existence_first():
    store, s = make_store()
    s.script("SELECT row_id FROM", [SimpleNamespace(row_id="a")])
    n = store.delete("embeddings", ["a"])
    assert n == 1
    kinds = [c[0] for c in s.calls if isinstance(c[0], str)]
    sel = next(i for i, c in enumerate(kinds) if c.startswith("SELECT row_id"))
    dele = next(i for i, c in enumerate(kinds) if c.startswith("DELETE"))
    assert sel < dele


def test_health_probe_is_lightweight():
    store, s = make_store()
    s.script("system.local", [SimpleNamespace(release_version="5.0")])
    s.script("system_schema.tables", [SimpleNamespace(table_name="embeddings")])
    health = store.health()
    assert health["status"] == "UP"
    assert not executed(s, "COUNT(*)")  # liveness must not full-scan


@pytest.mark.integration
def test_live_cassandra_roundtrip():  # pragma: no cover - needs a container
    """Run with ``pytest -m integration`` against a live Cassandra 5
    (CASSANDRA_HOSTS env); exercises real DDL + SAI + ANN."""
    import os

    hosts = os.environ.get("CASSANDRA_HOSTS")
    if not hosts:
        pytest.skip("CASSANDRA_HOSTS not set")
    store = CassandraVectorStore(hosts.split(","), embed_dim=4)
    vec = np.asarray([1.0, 0.0, 0.0, 0.0], dtype=np.float32)
    store.upsert("it_embeddings", [Doc("it1", "hello", {"topics": "kafka"}, vec)])
    hits = store.search("it_embeddings", vec, k=1)
    assert hits and hits[0].doc.doc_id == "it1"
