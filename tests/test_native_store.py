"""Native (C++ SIMD) vector store: builds the shared library and checks
score/rank parity with the numpy backend; falls back cleanly when the
toolchain is unavailable."""

import numpy as np
import pytest

from githubrepostorag_tpu.store import Doc, MemoryVectorStore
from githubrepostorag_tpu.store.native import NativeVectorStore, _get_lib


def _seed_store(store, n=200, d=32, seed=0):
    rng = np.random.default_rng(seed)
    vecs = rng.normal(size=(n, d)).astype(np.float32)
    docs = [
        Doc(f"d{i}", f"text {i}", {"repo": "r" + str(i % 3)}, vecs[i])
        for i in range(n)
    ]
    store.upsert("embeddings", docs)
    return rng.normal(size=d).astype(np.float32)


def test_native_matches_numpy_ranking():
    native = NativeVectorStore()
    ref = MemoryVectorStore()
    q = _seed_store(native)
    _seed_store(ref)
    nh = native.search("embeddings", q, k=10)
    rh = ref.search("embeddings", q, k=10)
    assert [h.doc.doc_id for h in nh] == [h.doc.doc_id for h in rh]
    for a, b in zip(nh, rh):
        assert a.score == pytest.approx(b.score, abs=1e-5)


def test_native_with_filter():
    native = NativeVectorStore()
    q = _seed_store(native)
    hits = native.search("embeddings", q, k=5, filter={"repo": "r1"})
    assert hits
    assert all(h.doc.metadata["repo"] == "r1" for h in hits)


def test_native_lib_builds_or_falls_back():
    # Either the C++ library built (preferred in this image: g++ present)
    # or the store transparently uses the numpy path.
    lib = _get_lib()
    store = NativeVectorStore()
    q = _seed_store(store, n=8)
    assert store.search("embeddings", q, k=3)
    if lib is None:
        pytest.skip("native toolchain unavailable; numpy fallback exercised")
