"""API + worker end-to-end over a real TCP socket: job lifecycle, SSE event
sequence, cancellation mid-run, health matrix, metrics exposure."""

import asyncio
import json

import numpy as np
import pytest

from githubrepostorag_tpu.agent import GraphAgent
from githubrepostorag_tpu.api.app import RagApi
from githubrepostorag_tpu.embedding import HashingTextEncoder
from githubrepostorag_tpu.events import MemoryBus, MemoryCancelFlags, MemoryJobQueue
from githubrepostorag_tpu.llm import FakeLLM
from githubrepostorag_tpu.retrieval import RetrieverFactory
from githubrepostorag_tpu.store import Doc, MemoryVectorStore
from githubrepostorag_tpu.worker import RagWorker

AGENT_SCRIPT = {
    r"Pick the retrieval scope": '{"scope": "chunk", "filters": {}}',
    r"Assess whether the retrieved": '{"coverage": 0.9, "needs_more": false}',
    r"senior engineer": "Jobs are created via POST /rag/jobs [1].",
}


def _stack(script=None, slow_llm=None):
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    texts = [
        ("c1", "async def create_job(request): enqueue and return job id",
         {"repo": "api", "module": "app", "file_path": "app/jobs.py"}),
        ("c2", "class RagWorker: consumes jobs and emits progress events",
         {"repo": "api", "module": "worker", "file_path": "worker/worker.py"}),
        ("c3", "def health_report(): aggregate store and llm probes",
         {"repo": "api", "module": "app", "file_path": "app/health.py"}),
    ]
    store.upsert("embeddings", [
        Doc(d, t, {"namespace": "default", "scope": "chunk", **m}, enc.encode([t])[0])
        for d, t, m in texts
    ])
    llm = slow_llm or FakeLLM(script=script or AGENT_SCRIPT)
    agent = GraphAgent(llm, RetrieverFactory(store, enc), namespace="default")
    bus = MemoryBus(ping_interval=0.05)
    flags, queue = MemoryCancelFlags(), MemoryJobQueue()
    worker = RagWorker(agent, bus, flags, queue, max_jobs=4, job_timeout=30)
    api = RagApi(bus, flags, queue)
    return api, worker


async def _with_service(fn, **kw):
    import aiohttp

    api, worker = _stack(**kw)
    port = await api.start(host="127.0.0.1", port=0)
    worker_task = asyncio.create_task(worker.run_forever())
    try:
        async with aiohttp.ClientSession() as session:
            await fn(session, f"http://127.0.0.1:{port}", api, worker)
    finally:
        worker.stop()
        worker_task.cancel()
        await api.stop()


async def _collect_events(session, base, job_id, timeout=15):
    events = []
    async with session.get(f"{base}/rag/jobs/{job_id}/events",
                           timeout=__import__("aiohttp").ClientTimeout(total=timeout)) as resp:
        async for raw in resp.content:
            line = raw.decode().strip()
            if line.startswith("data: "):
                events.append(json.loads(line[6:]))
                if events[-1]["event"] in ("final",):
                    break
    return events


async def test_job_lifecycle_and_event_sequence():
    async def body(session, base, api, worker):
        resp = await session.post(f"{base}/rag/jobs", json={"query": "how are jobs created?"})
        assert resp.status == 200
        job_id = (await resp.json())["job_id"]
        assert len(job_id) == 32  # uuid4 hex

        events = await _collect_events(session, base, job_id)
        kinds = [e["event"] for e in events]
        assert kinds[0] == "started"
        assert "iteration" in kinds
        assert "turn" in kinds  # agent breadcrumbs streamed
        assert "retrieval" in kinds
        assert kinds[-1] == "final"
        final = events[-1]["data"]
        assert "POST /rag/jobs" in final["answer"]
        assert final["sources"]
        retrieval = next(e for e in events if e["event"] == "retrieval")
        assert retrieval["data"]["sources_found"] >= 1
        assert retrieval["data"]["turns"]

        # kept result retrievable afterwards
        res = await session.get(f"{base}/rag/jobs/{job_id}/result")
        assert res.status == 200
        assert (await res.json())["answer"] == final["answer"]

    await _with_service(body)


async def test_cancel_mid_run():
    class SlowLLM(FakeLLM):
        def complete(self, prompt, **kw):
            import time

            time.sleep(0.3)
            return super().complete(prompt, **kw)

    slow = SlowLLM(script={
        r"Pick the retrieval scope": '{"scope": "chunk", "filters": {}}',
        r"Assess whether the retrieved": '{"coverage": 0.2, "needs_more": true}',
        r"Rephrase": "retry query",
        r"alternative search": '["alt"]',
        r"senior engineer": "should never get here",
    })

    async def body(session, base, api, worker):
        resp = await session.post(f"{base}/rag/jobs", json={"query": "slow question"})
        job_id = (await resp.json())["job_id"]
        await asyncio.sleep(0.5)  # let it get into the loop
        cancel = await session.post(f"{base}/rag/jobs/{job_id}/cancel")
        assert (await cancel.json())["cancelled"] is True
        events = await _collect_events(session, base, job_id)
        final = events[-1]
        assert final["event"] == "final"
        assert final["data"].get("cancelled") is True
        assert final["data"]["answer"] == ""

    await _with_service(slow_llm=slow, fn=body)


async def test_bad_request_400():
    async def body(session, base, api, worker):
        resp = await session.post(f"{base}/rag/jobs", data=b"nope")
        assert resp.status == 400

    await _with_service(body)


async def test_health_and_metrics():
    async def body(session, base, api, worker):
        health = await session.get(f"{base}/health")
        assert health.status == 200
        payload = await health.json()
        assert payload["status"] == "UP"
        assert payload["components"]["vectorStore"]["status"] == "UP"
        assert "uptime" in payload["components"]["system"]["details"]

        # generate some traffic then check metrics exposition
        await session.post(f"{base}/rag/jobs", json={"query": "q"})
        metrics = await (await session.get(f"{base}/metrics")).text()
        assert "rag_api_requests_total" in metrics
        assert "rag_jobs_total" in metrics

    await _with_service(body)


async def test_health_503_when_store_breaks(monkeypatch):
    async def body(session, base, api, worker):
        class BrokenStore:
            def health(self):
                return {"status": "DOWN", "error": "no contact points"}

        import githubrepostorag_tpu.store.factory as factory

        monkeypatch.setattr(factory, "_store", BrokenStore())
        resp = await session.get(f"{base}/health")
        assert resp.status == 503
        assert (await resp.json())["status"] == "DOWN"

    await _with_service(body)


async def test_static_ui_served():
    async def body(session, base, api, worker):
        resp = await session.get(f"{base}/static/index.html")
        assert resp.status == 200
        html = await resp.text()
        assert "EventSource" in html
        assert "/rag/jobs" in html
        root = await session.get(f"{base}/")
        assert root.status == 200  # redirect followed to the UI

    await _with_service(body)


async def test_concurrent_jobs():
    async def body(session, base, api, worker):
        ids = []
        for i in range(4):
            resp = await session.post(f"{base}/rag/jobs", json={"query": f"question {i}"})
            ids.append((await resp.json())["job_id"])
        results = await asyncio.gather(*(_collect_events(session, base, j) for j in ids))
        for events in results:
            assert events[-1]["event"] == "final"
            assert events[-1]["data"]["answer"]

    await _with_service(body)


def test_format_uptime():
    from githubrepostorag_tpu.api.health import format_uptime

    assert format_uptime(5) == "5s"
    assert format_uptime(3665) == "1h 1m 5s"
    assert format_uptime(90061) == "1d 1h 1m 1s"


async def test_tokens_stream_before_final_in_job_sse():
    """Real token streaming through the bus (reference faked it —
    qwen_llm.py:149-151): a job's SSE stream must carry incremental `token`
    events whose concatenation equals the `final` answer."""
    async def body(session, base, api, worker):
        resp = await session.post(f"{base}/rag/jobs", json={"query": "how are jobs created?"})
        job_id = (await resp.json())["job_id"]
        events = await _collect_events(session, base, job_id)
        kinds = [e["event"] for e in events]
        assert "token" in kinds
        assert kinds[-1] == "final"
        assert kinds.index("token") < kinds.index("final")
        streamed = "".join(e["data"]["text"] for e in events if e["event"] == "token")
        final = events[-1]["data"]["answer"]
        assert streamed.strip() == final
    await _with_service(body)


async def test_per_request_top_k_caps_retrieval():
    """QueryRequest.top_k reaches the retriever (the reference declared it,
    rag_shared/models.py:6-9, but its worker never read it): top_k=1 caps
    that job's sources at one doc; the same query without top_k surfaces
    all three fixture docs under settings ROUTER_TOP_K."""
    async def body(session, base, api, worker):
        resp = await session.post(f"{base}/rag/jobs", json={
            "query": "how are jobs created?", "top_k": 1, "force_level": "chunk"})
        job_id = (await resp.json())["job_id"]
        events = await _collect_events(session, base, job_id)
        final = events[-1]["data"]
        assert len(final["sources"]) == 1
        retrieval = next(e for e in events if e["event"] == "retrieval")
        assert retrieval["data"]["sources_found"] == 1

        resp = await session.post(f"{base}/rag/jobs", json={
            "query": "how are jobs created?", "force_level": "chunk"})
        job_id = (await resp.json())["job_id"]
        events = await _collect_events(session, base, job_id)
        assert len(events[-1]["data"]["sources"]) >= 2

    await _with_service(body)
