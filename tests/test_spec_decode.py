"""N-gram speculative decoding (serving/spec_decode.py + engine
spec_ngram_k): outputs must be token-identical to the burst path for every
sampling config — speculation is a scheduling change, not a model change —
and repetitive contexts must actually accept drafts.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from githubrepostorag_tpu.serving import Engine, SamplingParams
from githubrepostorag_tpu.serving.spec_decode import ngram_propose

transformers = pytest.importorskip("transformers")
import torch  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    from githubrepostorag_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    hf_cfg = transformers.Qwen2Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg.to_dict())
    params = params_from_state_dict(model.state_dict(), cfg)
    return model, params, cfg


def _engine(params, cfg, **kw):
    defaults = dict(
        max_num_seqs=4, num_pages=64, page_size=8, max_seq_len=256,
        prefill_chunk=32, kv_dtype=jnp.float32, decode_burst=4,
    )
    defaults.update(kw)
    return Engine(params, cfg, **defaults)


# ------------------------------------------------------------- proposals --


def test_ngram_propose_finds_repeats():
    toks = [1, 2, 3, 9, 9, 1, 2, 3]
    # suffix [1,2,3] occurred at 0; the continuation there was [9, 9, 1]
    assert ngram_propose(toks, 3) == [9, 9, 1]
    assert ngram_propose(toks, 1) == [9]
    assert ngram_propose([5, 6, 7], 4) == []  # nothing repeats
    assert ngram_propose([], 4) == []
    assert ngram_propose([1], 0) == []


def test_ngram_propose_prefers_longest_then_earliest():
    # [8,2] occurs twice earlier; the EARLIEST occurrence (index 0, vLLM
    # prompt-lookup order) wins — its continuation is [3], not the more
    # recent match's [5].  Earliest matters on repetitive text: the most
    # recent match sits just before the suffix and truncates the draft.
    toks = [8, 2, 3, 0, 8, 2, 5, 0, 8, 2]
    assert ngram_propose(toks, 1, max_ngram=2) == [3]
    # a longer matching suffix wins over a shorter, earlier one
    toks2 = [1, 2, 3, 4, 7, 3, 4, 9, 1, 2, 3, 4]
    # suffix [1,2,3,4] matched at 0 -> continuation [7]
    assert ngram_propose(toks2, 1) == [7]


def test_ngram_propose_repeat_run_drafts_full_k():
    # a pure repeat run (the spec bench's regime): earliest-match ordering
    # drafts k tokens; most-recent ordering would draft only 1
    toks = [4, 1, 7] + [9] * 12
    assert ngram_propose(toks, 6) == [9] * 6


# ----------------------------------------------------------------- engine --


def test_spec_greedy_token_identical_and_accepts(tiny):
    model, params, cfg = tiny
    # repetitive prompt: tiny random models loop quickly, and the prompt
    # itself gives the n-gram matcher material from step one
    prompt = [7, 8, 9, 10] * 8
    sp = SamplingParams(max_tokens=32, temperature=0.0, stop_token_ids=(),
                        repetition_penalty=1.0)
    plain = _engine(params, cfg).generate([prompt], sp)[0].output_tokens

    eng = _engine(params, cfg, spec_ngram_k=4)
    got = eng.generate([prompt], sp)[0].output_tokens
    assert got == plain
    assert eng.spec_proposed > 0
    assert eng.spec_accepted > 0, (
        f"no draft accepted over {eng.spec_proposed} proposed — speculation "
        "never pays off even on a looping sequence"
    )

    # HF ground truth for the same prompt
    with torch.no_grad():
        hf = model.generate(torch.tensor([prompt]), max_new_tokens=32,
                            do_sample=False, pad_token_id=0, eos_token_id=None,
                            use_cache=True)
    assert got == hf[0, len(prompt):].tolist()


def test_spec_matches_plain_on_mixed_batch(tiny):
    """Greedy, greedy+penalty, and sampled rows in one speculative batch:
    all must match the burst engine run with the same seed."""
    _, params, cfg = tiny
    rng = np.random.default_rng(5)
    prompts = [
        [1, 2, 3, 4] * 6,
        rng.integers(0, cfg.vocab_size, 24).tolist(),
        rng.integers(0, cfg.vocab_size, 17).tolist(),
    ]
    sps = [
        SamplingParams(max_tokens=16, temperature=0.0, stop_token_ids=()),
        SamplingParams(max_tokens=16, temperature=0.0, stop_token_ids=(),
                       repetition_penalty=1.3),
        SamplingParams(max_tokens=16, temperature=0.8, top_p=0.9,
                       stop_token_ids=()),
    ]
    plain = _engine(params, cfg, rng_seed=3)
    spec = _engine(params, cfg, rng_seed=3, spec_ngram_k=4)
    res_p = plain.generate(prompts, sps)
    res_s = spec.generate(prompts, sps)
    # deterministic rows must be identical across scheduling modes
    assert res_s[0].output_tokens == res_p[0].output_tokens
    assert res_s[1].output_tokens == res_p[1].output_tokens
    # the sampled row draws from a different rng call sequence; assert
    # validity, not equality
    assert len(res_s[2].output_tokens) == 16
    # penalty/sampled rows never proposed drafts
    solo = _engine(params, cfg, spec_ngram_k=4)
    solo.generate([prompts[1]], [sps[1]])
    assert solo.spec_proposed == 0


def test_spec_respects_stop_and_max_tokens(tiny):
    """A stop token inside an accepted draft run must end the request at the
    stop, and page accounting must balance."""
    _, params, cfg = tiny
    prompt = [3, 4, 5] * 8
    base = _engine(params, cfg)
    sp0 = SamplingParams(max_tokens=24, temperature=0.0, stop_token_ids=())
    ref = base.generate([prompt], sp0)[0].output_tokens
    stop = ref[5]  # force a stop mid-stream
    sp = SamplingParams(max_tokens=24, temperature=0.0, stop_token_ids=(stop,))
    expect = _engine(params, cfg).generate([prompt], sp)[0]

    eng = _engine(params, cfg, spec_ngram_k=4)
    got = eng.generate([prompt], sp)[0]
    assert got.output_tokens == expect.output_tokens
    assert got.finish_reason == expect.finish_reason == "stop"
    assert eng._allocator.free_count == eng._allocator.num_pages
    assert not eng.has_work()


def test_spec_with_prefix_cache_and_continuous_batching(tiny):
    """Speculation composes with the other engine features: a second
    request admitted mid-run shares the prefix cache and both outputs
    match the plain engine."""
    _, params, cfg = tiny
    p1 = [6, 7, 8, 9] * 8
    p2 = [6, 7, 8, 9] * 8 + [1, 2, 3]
    sp = SamplingParams(max_tokens=12, temperature=0.0, stop_token_ids=())
    plain = _engine(params, cfg)
    exp1 = plain.generate([p1], sp)[0].output_tokens
    exp2 = plain.generate([p2], sp)[0].output_tokens

    eng = _engine(params, cfg, spec_ngram_k=4)
    r1 = eng.add_request(p1, sp)
    for _ in range(3):
        eng.step()
    r2 = eng.add_request(p2, sp)
    done = {}
    while eng.has_work():
        for res in eng.step():
            done[res.request_id] = res
    assert done[r1].output_tokens == exp1
    assert done[r2].output_tokens == exp2
    assert eng._allocator.hit_tokens > 0  # p2 resumed from p1's pages


# ----------------------------------------------------- fused spec bursts --


def test_ngram_draft_device_matches_expectations():
    import jax.numpy as jnp

    from githubrepostorag_tpu.serving.spec_burst import ngram_draft_device

    hist = np.zeros((3, 16), dtype=np.int32)
    # row 0: bigram [1,2] recurs — earliest at 0, followers [3, 9]
    hist[0, :7] = [1, 2, 3, 9, 9, 1, 2]
    # row 1: no repeat
    hist[1, :5] = [5, 6, 7, 8, 9]
    # row 2: too short for a match (needs >= 4 tokens)
    hist[2, :3] = [4, 4, 4]
    draft, dlen = ngram_draft_device(jnp.asarray(hist),
                                     jnp.asarray([7, 5, 3], dtype=jnp.int32), 4)
    draft, dlen = np.asarray(draft), np.asarray(dlen)
    assert dlen.tolist() == [4, 0, 0]
    assert draft[0, :4].tolist() == [3, 9, 9, 1]


def test_spec_burst_token_identical_and_accepts(tiny):
    """The fused on-device spec burst must produce byte-identical greedy
    output to both the plain burst engine and the host-dispatched spec
    path, while actually accepting drafts on a looping sequence."""
    model, params, cfg = tiny
    prompt = [7, 8, 9, 10] * 8
    sp = SamplingParams(max_tokens=32, temperature=0.0, stop_token_ids=(),
                        repetition_penalty=1.0)
    plain = _engine(params, cfg).generate([prompt], sp)[0].output_tokens

    eng = _engine(params, cfg, spec_ngram_k=4, spec_burst_iters=4)
    got = eng.generate([prompt], sp)[0].output_tokens
    assert got == plain
    assert eng.spec_proposed > 0
    assert eng.spec_accepted > 0

    with torch.no_grad():
        hf = model.generate(torch.tensor([prompt]), max_new_tokens=32,
                            do_sample=False, pad_token_id=0, eos_token_id=None,
                            use_cache=True)
    assert got == hf[0, len(prompt):].tolist()


def test_spec_burst_batch_and_stop(tiny):
    """Multi-row fused spec bursts: random prompts (no drafts -> 1
    token/iteration) and looping prompts in one batch, stop tokens and
    max_tokens respected mid-burst."""
    _, params, cfg = tiny
    rng = np.random.default_rng(9)
    prompts = [
        [3, 4, 5] * 10,
        rng.integers(0, cfg.vocab_size, 21).tolist(),
    ]
    sp = SamplingParams(max_tokens=12, temperature=0.0, stop_token_ids=())
    plain = _engine(params, cfg)
    res_p = plain.generate(prompts, [sp, sp])
    spec = _engine(params, cfg, spec_ngram_k=4, spec_burst_iters=3)
    res_s = spec.generate(prompts, [sp, sp])
    for a, b in zip(res_s, res_p):
        assert a.output_tokens == b.output_tokens
        assert a.finish_reason == "length"

    # stop token: generation ends exactly where the plain engine ends
    tok_stop = res_p[0].output_tokens[4]
    sp_stop = SamplingParams(max_tokens=12, temperature=0.0,
                             stop_token_ids=(tok_stop,))
    stop_p = _engine(params, cfg).generate([prompts[0]], sp_stop)[0]
    stop_s = _engine(params, cfg, spec_ngram_k=4,
                     spec_burst_iters=3).generate([prompts[0]], sp_stop)[0]
    assert stop_s.output_tokens == stop_p.output_tokens
    assert stop_s.finish_reason == stop_p.finish_reason == "stop"


def test_spec_burst_falls_back_for_sampled_rows(tiny):
    """A sampled row in the batch drops the engine to the host spec path —
    outputs still match the plain engine for the deterministic row."""
    _, params, cfg = tiny
    prompts = [[5, 6, 7] * 8, [9, 1, 2] * 7]
    sps = [
        SamplingParams(max_tokens=10, temperature=0.0, stop_token_ids=()),
        SamplingParams(max_tokens=10, temperature=0.9, stop_token_ids=()),
    ]
    plain = _engine(params, cfg, rng_seed=11)
    spec = _engine(params, cfg, rng_seed=11, spec_ngram_k=4, spec_burst_iters=4)
    res_p = plain.generate(prompts, sps)
    res_s = spec.generate(prompts, sps)
    assert res_s[0].output_tokens == res_p[0].output_tokens


def test_rag_quoting_construction():
    """The bench's RAG-shaped spec workload (bench_spec_decode_rag): zero
    layers + an untied lm_head whose column o is embed row o-1 make greedy
    argmax narrate the token cycle t -> t+1, and a prompt of SHUFFLED
    consecutive cycle segments gives the bigram prompt-lookup drafter
    partial acceptance — accepts inside each chunk's span, mispredicts at
    chunk boundaries.  Guards the construction the driver-visible
    spec_rag_* metrics depend on."""
    import dataclasses

    import jax
    import numpy as np

    from githubrepostorag_tpu.models import Qwen2Config, init_params

    cfg = dataclasses.replace(Qwen2Config.tiny(), tie_word_embeddings=False)
    params = init_params(cfg, jax.random.PRNGKey(5))
    params = dict(params,
                  layers=jax.tree.map(jnp.zeros_like, params["layers"]),
                  lm_head=jnp.roll(params["embed"], 1, axis=0).T)

    span, n_chunks, s0 = 16, 4, 100
    rng = np.random.default_rng(17)
    chunk_list = [list(range(s0 + span * j, s0 + span * (j + 1)))
                  for j in range(n_chunks)]
    prompt = [t for j in rng.permutation(n_chunks) for t in chunk_list[j]] + [s0]

    sp = SamplingParams(max_tokens=40, temperature=0.0, stop_token_ids=())
    eng = Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=16,
                 max_seq_len=256, prefill_chunk=32, kv_dtype=jnp.float32,
                 spec_ngram_k=8, spec_burst_iters=8)
    out = eng.generate([prompt], sp)[0].output_tokens
    # the model narrates the cycle (the "answer quotes the chunks")
    assert out == list(range(s0 + 1, s0 + 41))
    # and the drafter's acceptance is PARTIAL: well above chance, below 1.0
    acceptance = eng.spec_accepted / max(eng.spec_proposed, 1)
    assert 0.3 < acceptance < 1.0, acceptance


# ----------------------------------------------- proposal parity + edges --


def _ngram_propose_reference(tokens, k, *, max_ngram=4, min_ngram=1):
    """The pre-optimization implementation, kept verbatim as the parity
    oracle: longest n first, earliest start wins, O(window * max_ngram)
    slice sweep."""
    from githubrepostorag_tpu.serving.spec_decode import SEARCH_WINDOW

    if k <= 0 or len(tokens) < min_ngram + 1:
        return []
    window = tokens[-SEARCH_WINDOW:]
    n_tok = len(window)
    for n in range(min(max_ngram, n_tok - 1), min_ngram - 1, -1):
        suffix = window[-n:]
        for s in range(n_tok - n):
            if window[s : s + n] == suffix:
                return window[s + n : s + n + k]
    return []


def test_ngram_propose_matches_reference_fuzz():
    """The indexed early-exit rewrite must be decision-identical to the
    slice-sweep reference on thousands of random cases (small alphabets
    force repeats; degenerate k/ngram bounds included)."""
    rng = np.random.default_rng(23)
    for trial in range(2000):
        alpha = int(rng.integers(2, 8))
        n = int(rng.integers(0, 40))
        toks = rng.integers(0, alpha, n).tolist()
        k = int(rng.integers(0, 6))
        max_n = int(rng.integers(1, 6))
        min_n = int(rng.integers(1, max_n + 1))
        got = ngram_propose(toks, k, max_ngram=max_n, min_ngram=min_n)
        want = _ngram_propose_reference(toks, k, max_ngram=max_n, min_ngram=min_n)
        assert got == want, (toks, k, max_n, min_n, got, want)


def test_spec_burst_kv_quant_round_trip_parity(tiny):
    """Int8 KV through the fused spec burst: the scan carries the scale
    pools alongside the quantized pages, and output must be token-identical
    to the PLAIN engine on the same int8 pools — quantization error is
    shared, scheduling must not add any."""
    _, params, cfg = tiny
    prompt = [7, 8, 9, 10] * 8
    sp = SamplingParams(max_tokens=24, temperature=0.0, stop_token_ids=())
    plain = _engine(params, cfg, kv_quant=True).generate([prompt], sp)[0]
    eng = _engine(params, cfg, kv_quant=True, spec_ngram_k=4, spec_burst_iters=3)
    got = eng.generate([prompt], sp)[0]
    assert got.output_tokens == plain.output_tokens
    assert eng.spec_proposed > 0 and eng.spec_accepted > 0
    assert eng._allocator.free_count == eng._allocator.num_pages


def test_spec_burst_draft_overflowing_row_limits(tiny):
    """A row near its KV budget: ``row_limits`` forces the draft length to
    clip mid-iteration (dlen = limit - len - 1) so the correction token
    always has a slot.  The request must end exactly where the plain
    engine ends, with pages balanced."""
    _, params, cfg = tiny
    # max_seq_len=32 -> row limit 31; the 20-token looping prompt leaves
    # 12 decode slots, so a k=4 draft must clip in the final iterations
    geom = dict(max_seq_len=32, page_size=4, num_pages=32)
    prompt = [5, 6, 7, 8] * 5
    sp = SamplingParams(max_tokens=20, temperature=0.0, stop_token_ids=())
    plain = _engine(params, cfg, **geom).generate([prompt], sp)[0]
    eng = _engine(params, cfg, spec_ngram_k=4, spec_burst_iters=4, **geom)
    got = eng.generate([prompt], sp)[0]
    assert got.output_tokens == plain.output_tokens
    assert got.finish_reason == plain.finish_reason == "length"
    assert eng.spec_accepted > 0  # the loop really drafted near the limit
    assert eng._allocator.free_count == eng._allocator.num_pages
    assert not eng.has_work()
