"""Agent loop semantics with a scripted FakeLLM over the in-memory stack:
planning fallbacks, retrieval expansion, judge stage-down ladder, rewrite
loop bounds, synthesis budgets, anti-conservative retry."""

import json

import pytest

from githubrepostorag_tpu.agent import GraphAgent
from githubrepostorag_tpu.embedding import HashingTextEncoder
from githubrepostorag_tpu.llm import FakeLLM
from githubrepostorag_tpu.retrieval import RetrieverFactory
from githubrepostorag_tpu.store import Doc, MemoryVectorStore

PLAN = r"Pick the retrieval scope"
JUDGE = r"Assess whether the retrieved"
EXPAND = r"alternative search queries"
REWRITE = r"Rephrase this question"
SYNTH = r"senior engineer"
ENCOURAGE = r"helpful engineer"


@pytest.fixture
def stack():
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    texts = {
        "chunk": [
            ("c1", "def ingest_component(repo): run the ingest pipeline stages",
             {"repo": "coderag", "module": "ingest", "file_path": "ingest/controller.py"}),
            ("c2", "def run_rag_job(ctx, job_id): drive the agent and emit events",
             {"repo": "coderag", "module": "worker", "file_path": "worker/worker.py"}),
            ("c3", "class GraphAgent: plan retrieve judge rewrite synthesize loop",
             {"repo": "coderag", "module": "worker", "file_path": "worker/agent.py"}),
        ],
        "repo": [
            ("r1", "coderag: a RAG system over github repositories with hierarchical index " + "x" * 2000,
             {"repo": "coderag"}),
        ],
    }
    for scope, rows in texts.items():
        table = {"chunk": "embeddings", "repo": "embeddings_repo"}[scope]
        docs = []
        for did, text, meta in rows:
            meta = {"namespace": "default", "scope": scope, **meta}
            docs.append(Doc(did, text, meta, enc.encode([text])[0]))
        store.upsert(table, docs)
    return store, enc


def _agent(stack, script, max_iters=3):
    store, enc = stack
    llm = FakeLLM(script=script, default="generic answer [1]")
    return GraphAgent(llm, RetrieverFactory(store, enc), max_iters=max_iters, namespace="default"), llm


def test_happy_path_single_iteration(stack):
    agent, llm = _agent(stack, {
        PLAN: '{"scope": "chunk", "filters": {}}',
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
        SYNTH: "The ingest pipeline runs via ingest_component [1].",
    })
    events = []
    res = agent.run("how does the ingest pipeline run?", progress_cb=events.append)
    assert "ingest_component" in res.answer
    assert res.sources and res.sources[0]["doc_id"].startswith("c")
    stages = [e["stage"] for e in events]
    assert stages[0] == "plan"
    assert "retrieve" in stages and "judge" in stages and "synthesize" in stages
    assert res.debug["final_scope"] == "chunk"


def test_plan_garbage_falls_back_to_heuristic(stack):
    # codey question -> chunk; overview question -> repo
    agent, _ = _agent(stack, {
        PLAN: "utter nonsense, no json here",
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
    })
    res = agent.run("why does this function throw an exception?")
    assert any(t["scope"] == "chunk" for t in res.debug["turns"] if t["stage"] == "plan")

    agent2, _ = _agent(stack, {
        PLAN: "still nonsense",
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
    })
    res2 = agent2.run("give me a summary of the architecture")
    assert any(t["scope"] == "repo" for t in res2.debug["turns"] if t["stage"] == "plan")


def test_judge_parse_failure_stages_down(stack):
    agent, _ = _agent(stack, {
        PLAN: '{"scope": "repo", "filters": {}}',
        JUDGE: "no json at all",
    }, max_iters=2)
    res = agent.run("what repositories exist?")
    judges = [t for t in res.debug["turns"] if t["stage"] == "judge"]
    assert judges[0]["decision"]["stage_down"] == "module"


def test_low_coverage_auto_stages_down_ladder(stack):
    coverages = iter(['{"coverage": 0.1, "needs_more": true}',
                      '{"coverage": 0.9, "needs_more": false}'])
    agent, _ = _agent(stack, {
        PLAN: '{"scope": "repo", "filters": {}}',
        JUDGE: lambda p: next(coverages),
        REWRITE: "sharper question about the ingest pipeline",
        EXPAND: '["alt one", "alt two"]',
    })
    res = agent.run("tell me about ingest")
    scopes = [t.get("scope") for t in res.debug["turns"] if t["stage"] == "retrieve"]
    assert scopes[0] == "repo"
    assert scopes[1] == "module"  # one rung down after coverage 0.1


def test_retry_loop_bounded_by_max_iters(stack):
    agent, llm = _agent(stack, {
        PLAN: '{"scope": "chunk", "filters": {}}',
        JUDGE: '{"coverage": 0.5, "needs_more": true}',  # always wants more
        REWRITE: "rewritten question about workers",
        EXPAND: '["expansion a", "expansion b"]',
    }, max_iters=3)
    res = agent.run("an unanswerable question")
    retrieves = [t for t in res.debug["turns"] if t["stage"] == "retrieve"]
    assert len(retrieves) == 3  # initial + 2 retries, then forced synthesis
    ends = [t for t in res.debug["turns"] if t.get("reason") == "max_iters"]
    assert ends


def test_semantic_expansion_fills_sparse_results(stack):
    agent, llm = _agent(stack, {
        PLAN: '{"scope": "chunk", "filters": {}}',
        EXPAND: '["agent loop class", "rag job worker"]',
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
    })
    # "???" has no word tokens -> zero embedding -> zero ANN hits, so the
    # semantic expansion path is the only way to fill results
    res = agent.run("???")
    expanded = [t for t in res.debug["turns"] if t["stage"] == "retrieve_expanded"]
    assert expanded, "expansion should have been attempted and recorded"
    assert expanded[0]["expanded_hits"] > expanded[0]["original_hits"]
    assert res.sources, "expanded docs should flow into synthesis"


def test_anti_conservative_retry(stack):
    agent, llm = _agent(stack, {
        PLAN: '{"scope": "chunk", "filters": {}}',
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
        ENCOURAGE: "Here are the projects: coderag does X [1].",
        SYNTH: "I don't have enough information to answer.",
    })
    res = agent.run("what does the worker do?")
    assert "coderag does X" in res.answer
    assert res.debug.get("synthesis_retry") == "overcame_conservative_answer"


def test_force_level_and_repo_hint(stack):
    agent, _ = _agent(stack, {
        PLAN: '{"scope": "chunk", "filters": {}}',
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
    })
    res = agent.run("summarize repo: coderag please", force_level="repo")
    plans = [t for t in res.debug["turns"] if t["stage"] == "plan"]
    assert plans[-1].get("forced") is True
    retrieves = [t for t in res.debug["turns"] if t["stage"] == "retrieve"]
    assert retrieves[0]["scope"] == "repo"
    assert retrieves[0]["filters"].get("repo") == "coderag"


def test_source_text_budget(stack):
    agent, _ = _agent(stack, {
        PLAN: '{"scope": "repo", "filters": {}}',
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
    })
    res = agent.run("describe the coderag repository")
    assert res.sources
    assert all(len(s["text"]) <= 1200 for s in res.sources)


def test_filter_list_values_normalized(stack):
    agent, _ = _agent(stack, {
        PLAN: '{"scope": "chunk", "filters": {"repos": ["coderag"]}}',
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
    })
    res = agent.run("how does the agent work?")
    # the depluralized filter is visible at plan time regardless of whether
    # the repo filter then routes the run to longctx or the RAG loop
    plans = [t for t in res.debug["turns"] if t["stage"] == "plan"]
    assert plans[0]["filters"].get("repo") == "coderag"


LONGCTX = r"read the ENTIRE"


def test_longctx_mode_whole_repo_answer(stack):
    # architecture question + repo pinned down -> one assembled-repo
    # completion, no retrieve/judge loop at all
    agent, llm = _agent(stack, {
        PLAN: '{"scope": "repo", "filters": {"repo": "coderag"}}',
        LONGCTX: "Ingest feeds the worker which drives the agent [worker/agent.py].",
    })
    res = agent.run("how do the components of coderag fit together?")
    assert res.debug.get("mode") == "longctx"
    assert "feeds the worker" in res.answer
    assert res.sources == [res.sources[0]] and res.sources[0]["doc_id"] == "repo:coderag"
    stages = [t["stage"] for t in res.debug["turns"]]
    assert "assemble" in stages and "retrieve" not in stages
    # the whole repo went into the one completion
    longctx_calls = [c for c in llm.calls if "### ingest/controller.py" in c["prompt"]]
    assert longctx_calls and "### worker/agent.py" in longctx_calls[0]["prompt"]


def test_longctx_skipped_for_codey_question(stack):
    # snippet-smelling questions keep chunk RAG even with a repo filter
    agent, _ = _agent(stack, {
        PLAN: '{"scope": "chunk", "filters": {"repo": "coderag"}}',
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
    })
    res = agent.run("how does this function throw an exception?")
    assert res.debug.get("mode") is None  # never entered longctx
    assert any(t["stage"] == "retrieve" for t in res.debug["turns"])


def test_longctx_over_budget_falls_back_to_rag(stack, monkeypatch):
    import githubrepostorag_tpu.retrieval as retrieval_pkg

    monkeypatch.setattr(retrieval_pkg, "longctx_token_budget", lambda: 10)
    agent, _ = _agent(stack, {
        PLAN: '{"scope": "repo", "filters": {"repo": "coderag"}}',
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
    })
    res = agent.run("what is the overall architecture here?")
    falls = [t for t in res.debug["turns"] if t["stage"] == "longctx_fallback"]
    assert falls and falls[0]["reason"] == "over_budget"
    assert any(t["stage"] == "retrieve" for t in res.debug["turns"])
    assert res.answer


def test_longctx_unknown_repo_falls_back(stack):
    agent, _ = _agent(stack, {
        PLAN: '{"scope": "repo", "filters": {"repo": "ghost"}}',
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
    })
    res = agent.run("walk me through the design")
    falls = [t for t in res.debug["turns"] if t["stage"] == "longctx_fallback"]
    assert falls and falls[0]["reason"] == "no_chunks"
    assert res.answer


def test_assemble_repo_orders_modules_and_files(stack):
    from githubrepostorag_tpu.retrieval import assemble_repo

    store, _ = stack
    asm = assemble_repo(store, "coderag", namespace="default")
    assert asm is not None and not asm.truncated
    assert asm.files == 3 and asm.chunks == 3
    assert asm.token_estimate > 0
    # ingest module sorts before worker; every file gets a header
    assert asm.text.index("### ingest/controller.py") < asm.text.index("### worker/agent.py")
    assert assemble_repo(store, "ghost") is None


def test_progress_callback_errors_do_not_kill_run(stack):
    agent, _ = _agent(stack, {
        PLAN: '{"scope": "chunk", "filters": {}}',
        JUDGE: '{"coverage": 0.9, "needs_more": false}',
    })

    def bad_cb(event):
        raise RuntimeError("boom")

    res = agent.run("how does ingest work?", progress_cb=bad_cb)
    assert res.answer
