"""The fused decode hot path: ops/fused_decode.py's single-launch window
kernel must be numerically indistinguishable from ``paged_attention_ref``
(its stated oracle) across row buckets, window widths, quant modes, and
block-table holes; int4 nibble pages must round-trip bit-exactly through
commit/gather/migration; and the engine-level fused step
(serving/fused_step.py) must stay greedy-token-IDENTICAL to the unfused
path while compiling ZERO new XLA programs after warmup.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
from githubrepostorag_tpu.ops.fused_decode import (
    fused_packed_attention,
    fused_window_attention,
)
from githubrepostorag_tpu.ops.paged_attention import gather_kv, paged_attention_ref
from githubrepostorag_tpu.ops.sampling import (
    sample_tokens_capped,
    sample_tokens_nofilter,
)
from githubrepostorag_tpu.serving import Engine, SamplingParams
from githubrepostorag_tpu.serving.kv_cache import (
    make_page_pools,
    pack_int4,
    quant_bits,
    quantize_kv_paged,
    unpack_int4,
)

# kernel-test geometry: 2 kv heads x group 2, 8-wide heads, 4-token pages;
# each row walks MP pages out of a P=32 pool through a shuffled block table
N_KV, GROUP, HD, PS, P, MP = 2, 2, 8, 4, 32, 4
N_Q = N_KV * GROUP


def _rand_pools(key, quant):
    """Random pools in the exact storage layout of each kv_quant mode:
    f32, int8 + per-page scales, or nibble-packed uint8 + scales."""
    kf, vf, k8, v8, k4, v4, ks, vs = jax.random.split(key, 8)
    if quant == 0:
        k = jax.random.normal(kf, (N_KV, P, PS, HD), jnp.float32)
        v = jax.random.normal(vf, (N_KV, P, PS, HD), jnp.float32)
        return k, v, None, None
    if quant == 8:
        shape = (N_KV, P, PS, HD)
        k = jax.random.randint(k8, shape, -127, 128).astype(jnp.int8)
        v = jax.random.randint(v8, shape, -127, 128).astype(jnp.int8)
    else:
        shape = (N_KV, P, PS, HD // 2)  # every byte pattern is a valid nibble pair
        k = jax.random.randint(k4, shape, 0, 256).astype(jnp.uint8)
        v = jax.random.randint(v4, shape, 0, 256).astype(jnp.uint8)
    k_s = jax.random.uniform(ks, (N_KV, P), jnp.float32, 0.02, 0.2)
    v_s = jax.random.uniform(vs, (N_KV, P), jnp.float32, 0.02, 0.2)
    return k, v, k_s, v_s


def _window_case(key, b, s_w, quant):
    kq, kb, kp = jax.random.split(key, 3)
    k, v, ks, vs = _rand_pools(kp, quant)
    # block tables with HOLES: rows own disjoint shuffled page sets, so a
    # kernel that walked pages in pool order would read the wrong tokens
    bt = jax.random.permutation(kb, P)[: b * MP].reshape(b, MP).astype(jnp.int32)
    q = jax.random.normal(kq, (b, s_w, N_Q, HD), jnp.float32)
    cached = jnp.asarray([(3 * i) % (MP * PS - s_w + 1) for i in range(b)], jnp.int32)
    new = jnp.full((b,), s_w, jnp.int32)
    return q, k, v, bt, cached, new, ks, vs


# ------------------------------------------------------- kernel vs oracle --


@pytest.mark.parametrize("quant", [0, 8, 4], ids=["fp", "int8", "int4"])
@pytest.mark.parametrize("s_w", [1, 5, 9])  # plain decode, k=4 verify, k=8
@pytest.mark.parametrize("b", [1, 3])
def test_fused_window_matches_paged_ref(quant, s_w, b):
    key = jax.random.PRNGKey(quant * 100 + s_w * 10 + b)
    q, k, v, bt, cached, new, ks, vs = _window_case(key, b, s_w, quant)
    got = fused_window_attention(q, k, v, bt, cached, new, ks, vs, interpret=True)
    ref = paged_attention_ref(q, k, v, bt, cached, new, ks, vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_fused_window_inactive_rows_are_finite_zero():
    """Bucket-padding rows (total length 0) must come out exactly zero —
    never NaN from an empty softmax — while live rows still match."""
    q, k, v, bt, cached, new, ks, vs = _window_case(jax.random.PRNGKey(0), 3, 5, 0)
    cached = cached.at[1].set(0)
    new = new.at[1].set(0)
    got = fused_window_attention(q, k, v, bt, cached, new, interpret=True)
    assert bool(jnp.all(jnp.isfinite(got)))
    assert np.array_equal(np.asarray(got[1]), np.zeros_like(got[1]))
    live = np.asarray([0, 2])
    ref = paged_attention_ref(q[live], k, v, bt[live], cached[live], new[live])
    np.testing.assert_allclose(np.asarray(got)[live], np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("quant", [0, 8, 4], ids=["fp", "int8", "int4"])
def test_fused_packed_mixed_phase_matches_windows(quant):
    """One segment grid over a mixed wave — a 6-token prefill chunk and a
    3-token spec-verify window — must equal the segment-major oracle at
    every packed token, with padding tokens ignored."""
    tq, r = 8, 2
    k, v, ks, vs = _rand_pools(jax.random.PRNGKey(21 + quant), quant)
    bt = jax.random.permutation(jax.random.PRNGKey(5), P)[: r * MP]
    bt = bt.reshape(r, MP).astype(jnp.int32)
    cached = jnp.asarray([0, 9], jnp.int32)
    new = jnp.asarray([6, 3], jnp.int32)
    q_pack = jax.random.normal(jax.random.PRNGKey(7), (12, N_Q, HD), jnp.float32)
    seg_ids = jnp.asarray([0] * 6 + [1] * 3 + [r] * 3, jnp.int32)  # >= r pads
    positions = jnp.asarray([0, 1, 2, 3, 4, 5, 9, 10, 11, 0, 0, 0], jnp.int32)

    got = fused_packed_attention(q_pack, k, v, bt, cached, new, seg_ids,
                                 positions, tq=tq, k_scales=ks, v_scales=vs)

    q_seg = (jnp.zeros((r, tq, N_Q, HD), jnp.float32)
             .at[0, :6].set(q_pack[:6]).at[1, :3].set(q_pack[6:9]))
    ref = paged_attention_ref(q_seg, k, v, bt, cached, new, ks, vs)
    np.testing.assert_allclose(np.asarray(got[:6]), np.asarray(ref[0, :6]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(got[6:9]), np.asarray(ref[1, :3]),
                               rtol=1e-4, atol=1e-4)


# ------------------------------------------------------ int4 page layout --


def test_int4_pack_unpack_roundtrip_exact():
    vals = jax.random.randint(jax.random.PRNGKey(2), (5, 7, HD), -8, 8)
    q = vals.astype(jnp.int8)
    packed = pack_int4(q)
    assert packed.dtype == jnp.uint8 and packed.shape == (5, 7, HD // 2)
    assert np.array_equal(np.asarray(unpack_int4(packed)), np.asarray(q))


def test_int4_commit_gather_roundtrip():
    """commit_paged on a uint8 pool quantizes at qmax=7, nibble-packs, and
    gather_kv (the oracle's input path) dequantizes the exact same values
    back out — quantize -> pack -> unpack -> scale is lossless."""
    from githubrepostorag_tpu.serving.kv_cache import commit_paged

    pools = jnp.zeros((N_KV, P, PS, HD // 2), jnp.uint8)
    scales = jnp.zeros((N_KV, P), jnp.float32)
    vals = jax.random.normal(jax.random.PRNGKey(9), (N_KV, 2 * PS, HD), jnp.float32)
    # open pages 3 and 5 at their first slots (fresh-scale detection)
    slots = jnp.concatenate([3 * PS + jnp.arange(PS), 5 * PS + jnp.arange(PS)])
    slots = slots.astype(jnp.int32)
    new_pools, new_scales = commit_paged(pools, vals, slots, scales, PS)
    assert new_pools.dtype == jnp.uint8 and new_pools.shape == pools.shape

    qv, exp_scales = quantize_kv_paged(vals, slots, scales, PS, qmax=7)
    np.testing.assert_allclose(np.asarray(new_scales), np.asarray(exp_scales))
    expected = (qv.astype(jnp.float32).reshape(N_KV, 2, PS, HD)
                * exp_scales[:, jnp.asarray([3, 5])][..., None, None])

    bt = jnp.asarray([[3, 5]], jnp.int32)
    gk, _ = gather_kv(new_pools, new_pools, bt, new_scales, new_scales,
                      dtype=jnp.float32)  # [1, 2*PS, N_KV, HD]
    got = jnp.moveaxis(gk[0].reshape(2, PS, N_KV, HD), 2, 0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-6, atol=1e-6)


def test_int4_migration_roundtrip_bit_exact():
    """gather_pages -> scatter_pages must reproduce the nibble-packed bytes
    and per-page scales EXACTLY (disagg and host-tier parking ship this
    layout; any re-encode would compound quantization error)."""
    from githubrepostorag_tpu.ops.page_migration import gather_pages, scatter_pages

    l = 2
    key = jax.random.PRNGKey(13)
    kk, kv, ks, vs = jax.random.split(key, 4)
    shape = (l, N_KV, P, PS, HD // 2)
    kp = jax.random.randint(kk, shape, 0, 256).astype(jnp.uint8)
    vp = jax.random.randint(kv, shape, 0, 256).astype(jnp.uint8)
    ksc = jax.random.uniform(ks, (l, N_KV, P), jnp.float32, 0.01, 0.5)
    vsc = jax.random.uniform(vs, (l, N_KV, P), jnp.float32, 0.01, 0.5)

    idx = jnp.asarray([5, 2, 9, -1], jnp.int32)  # -1 = padding, must drop
    gk, gv, gks, gvs = gather_pages(kp, vp, idx, ksc, vsc)
    dk, dv, dks, dvs = scatter_pages(
        jnp.zeros_like(kp), jnp.zeros_like(vp), idx, gk,
        jnp.zeros_like(ksc), jnp.zeros_like(vsc),
        v_vals=gv, ks_vals=gks, vs_vals=gvs,
    )
    live = np.asarray([5, 2, 9])
    assert dk.dtype == jnp.uint8
    assert np.array_equal(np.asarray(dk[:, :, live]), np.asarray(kp[:, :, live]))
    assert np.array_equal(np.asarray(dv[:, :, live]), np.asarray(vp[:, :, live]))
    np.testing.assert_array_equal(np.asarray(dks[:, :, live]),
                                  np.asarray(ksc[:, :, live]))
    np.testing.assert_array_equal(np.asarray(dvs[:, :, live]),
                                  np.asarray(vsc[:, :, live]))
    # the padding index wrote nowhere: everything outside the burst is 0
    mask = np.ones(P, bool)
    mask[live] = False
    assert not np.asarray(dk[:, :, mask]).any()
    assert not np.asarray(dks[:, :, mask]).any()


def test_int4_pages_at_equal_pool_bytes():
    """The sizing claim: at a fixed HBM byte budget, int4 pools admit
    >= 1.8x the pages of int8 pools (2x payload minus the shared per-page
    scale overhead)."""
    cfg = dataclasses.replace(Qwen2Config.tiny(), head_dim=128)
    n_pages, ps = 8, 16
    p8 = make_page_pools(cfg, n_pages, ps, quant=8)
    p4 = make_page_pools(cfg, n_pages, ps, quant=4)
    bytes8 = sum(a.nbytes for a in (p8.k, p8.v, p8.ks, p8.vs)) / n_pages
    bytes4 = sum(a.nbytes for a in (p4.k, p4.v, p4.ks, p4.vs)) / n_pages
    budget = bytes8 * 4096  # an int8 pool of 4096 pages
    assert (budget // bytes4) / 4096 >= 1.8


def test_quant_bits_knob():
    assert quant_bits(False) == 0 and quant_bits(None) == 0
    assert quant_bits(True) == 8 and quant_bits(8) == 8
    assert quant_bits(4) == 4
    assert quant_bits("int4") == 4 and quant_bits("int8") == 8
    assert quant_bits("off") == 0
    with pytest.raises(ValueError):
        quant_bits(3)


# --------------------------------------------- fused-layout sampling path --


def test_sampling_accepts_fused_segment_logits():
    """sample_tokens_capped/nofilter on the fused [B, S, V] layout with
    per-row seg_pos must equal the host-gathered [B, V] call bit-for-bit
    (same rng): the device-side take_along_axis replaces a host transpose."""
    b, s, v = 4, 3, 64
    logits3 = jax.random.normal(jax.random.PRNGKey(17), (b, s, v), jnp.float32)
    seg_pos = jnp.asarray([0, 2, 1, 0], jnp.int32)
    logits2 = jnp.take_along_axis(logits3, seg_pos[:, None, None], axis=1)[:, 0]
    temp = jnp.asarray([0.0, 0.9, 0.7, 0.0], jnp.float32)
    top_p = jnp.asarray([1.0, 0.9, 1.0, 1.0], jnp.float32)
    top_k = jnp.asarray([0, 8, 0, 0], jnp.int32)
    rep = jnp.asarray([1.0, 1.0, 1.2, 1.0], jnp.float32)
    presence = jax.random.bernoulli(jax.random.PRNGKey(18), 0.1, (b, v))
    rng = jax.random.PRNGKey(19)

    flat = sample_tokens_capped(logits2, rng, temp, top_p, top_k, rep,
                                presence, cap=32)
    fused = sample_tokens_capped(logits3, rng, temp, top_p, top_k, rep,
                                 presence, cap=32, seg_pos=seg_pos)
    assert np.asarray(flat).tolist() == np.asarray(fused).tolist()

    flat_nf = sample_tokens_nofilter(logits2, rng, temp, rep, presence)
    fused_nf = sample_tokens_nofilter(logits3, rng, temp, rep, presence,
                                      seg_pos=seg_pos)
    assert np.asarray(flat_nf).tolist() == np.asarray(fused_nf).tolist()

    # seg_pos=None means window position 0 (the committed token)
    at0 = sample_tokens_capped(logits3[:, 0], rng, temp, top_p, top_k, rep,
                               presence, cap=32)
    dflt = sample_tokens_capped(logits3, rng, temp, top_p, top_k, rep,
                                presence, cap=32)
    assert np.asarray(at0).tolist() == np.asarray(dflt).tolist()


# --------------------------------------------------- engine-level parity --


@pytest.fixture(scope="module")
def narrator():
    """Tiny model whose untied lm_head makes greedy output deterministic
    and prompt-dependent — the parity fixture the unfused path is held to."""
    cfg = Qwen2Config(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, head_dim=8,
                      intermediate_size=64, tie_word_embeddings=False)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    params["lm_head"] = jnp.roll(params["embed"], 1, axis=0).T
    return cfg, params


def _engine(params, cfg, **kw):
    defaults = dict(max_num_seqs=4, num_pages=64, page_size=8, max_seq_len=128,
                    prefill_chunk=16, prefill_token_budget=32,
                    spec_ngram_k=3, spec_burst_iters=2, decode_burst=4)
    defaults.update(kw)
    return Engine(dict(params), cfg, **defaults)


def test_fused_step_construction_gates(narrator):
    cfg, params = narrator
    with pytest.raises(ValueError, match="spec_ngram_k"):
        _engine(params, cfg, fused_step=True, spec_ngram_k=0)
    with pytest.raises(ValueError, match="spec_burst_iters"):
        _engine(params, cfg, fused_step=True, spec_burst_iters=0)
    with pytest.raises(ValueError, match="prefill_token_budget"):
        _engine(params, cfg, fused_step=True, prefill_token_budget=None)
    with pytest.raises(ValueError, match="SPEC_DRAFT_MODEL"):
        _engine(params, cfg, fused_step=True, draft_params=dict(params),
                draft_cfg=cfg)
    with pytest.raises(ValueError, match="prefill_priority"):
        _engine(params, cfg, fused_step=True, prefill_priority=True)


@pytest.mark.parametrize("kv_quant", [False, True, 4], ids=["fp", "int8", "int4"])
def test_fused_greedy_token_identical(narrator, kv_quant):
    """THE acceptance criterion: the fused single-dispatch step produces
    byte-identical greedy output to the unfused engine, in every kv_quant
    mode, and returns every page to the pool."""
    cfg, params = narrator
    prompts = [[3, 4, 5], [7, 8, 9, 10], [1, 2]]
    sp = SamplingParams(max_tokens=10, temperature=0.0, stop_token_ids=())
    ref = _engine(params, cfg, kv_quant=kv_quant).generate(prompts, sp)

    eng = _engine(params, cfg, fused_step=True, kv_quant=kv_quant)
    got = eng.generate(prompts, sp)
    for a, b in zip(got, ref):
        assert a.output_tokens == b.output_tokens
    assert eng.fused_steps_total > 0
    assert eng.step_dispatches_total >= eng.fused_steps_total
    assert eng._allocator.free_count == eng._allocator.num_pages
    assert not eng.has_work()


def test_fused_mixed_sampled_row_keeps_greedy_parity(narrator):
    """A sampled row riding the fused burst must not perturb its greedy
    neighbors (the unfused engine demotes such batches to plain decode;
    the fused step keeps speculation for the greedy rows instead)."""
    cfg, params = narrator
    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
    sampled = SamplingParams(max_tokens=8, temperature=0.9, top_p=0.9,
                             stop_token_ids=())
    # each prompt ends one token shy of re-creating its opening bigram:
    # the greedy first token (prev+1 under the narrator head) completes it,
    # so the n-gram drafter finds a match and proposes in the first burst
    greedy_prompts = [[3, 4, 9, 3], [7, 8, 2, 7]]
    ref = _engine(params, cfg).generate(greedy_prompts, sp)

    eng = _engine(params, cfg, fused_step=True)
    got = eng.generate(greedy_prompts + [[11, 12, 13]],
                       [sp, sp, sampled])
    assert got[0].output_tokens == ref[0].output_tokens
    assert got[1].output_tokens == ref[1].output_tokens
    assert len(got[2].output_tokens) == 8
    assert all(0 <= t < cfg.vocab_size for t in got[2].output_tokens)
    assert eng.spec_proposed > 0  # greedy rows kept speculating


def test_fused_joint_admission_defers_prefill_into_burst(narrator):
    """A request admitted while others decode rides the SAME dispatch: the
    packed wave is deferred into the next fused step, so dispatches stay
    1 per step (plus the initial prefill-only packed program)."""
    cfg, params = narrator
    sp = SamplingParams(max_tokens=12, temperature=0.0, stop_token_ids=())
    ref = _engine(params, cfg).generate([[3, 4, 5], [9, 10, 11, 12]], sp)

    eng = _engine(params, cfg, fused_step=True)
    eng.add_request([3, 4, 5], sp)
    first = eng.step()  # prefill-only packed dispatch
    eng.add_request([9, 10, 11, 12], sp)  # joins mid-flight -> deferred
    done = list(first)
    while eng.has_work():
        done.extend(eng.step())
    by_len = sorted(done, key=lambda r: len(r.prompt_tokens))
    assert by_len[0].output_tokens == ref[0].output_tokens
    assert by_len[1].output_tokens == ref[1].output_tokens
    # every step after the first prefill was a single fused dispatch
    assert eng.step_dispatches_total == eng.fused_steps_total + 1


def test_ledger_dispatch_attribution():
    """The obs ledger turns the engine's dispatch counters into the
    /debug/slo dispatch section and the dispatches-per-step gauge."""
    from githubrepostorag_tpu.obs.ledger import SNAPSHOT_FIELDS, TokenLedger

    now = time.monotonic()
    ledger = TokenLedger("r0", flops_per_tok=1e9, peak_flops=1e12)
    snap = {f: 0.0 for f in SNAPSHOT_FIELDS}
    ledger.on_step(dict(snap), now - 1.0, now - 0.8)
    snap.update(committed_tokens=5, fused_steps_total=3,
                step_dispatches_total=4)
    ledger.on_step(dict(snap), now - 0.7, now - 0.2)
    s = ledger.snapshot()
    assert s["dispatch"]["fused_steps"] == 3
    assert s["dispatch"]["dispatches"] == 4
    assert s["dispatch"]["dispatches_per_step"] == 2.0


# ------------------------------------------------------ compile discipline --


@pytest.mark.parametrize("kv_quant", [False, 4], ids=["fp", "int4"])
def test_fused_zero_recompiles_across_mixed_traffic(narrator, kv_quant):
    """After warmup, mixed fused traffic — both row buckets, a sampled row
    (filter variant), joint admission mid-decode (has_prefill variant) —
    compiles ZERO new XLA programs."""
    from tests.helpers.compile_guard import compile_guard, watchdog_counter

    cfg, params = narrator
    eng = _engine(params, cfg, fused_step=True, kv_quant=kv_quant)
    eng.warmup()

    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
    sampled = SamplingParams(max_tokens=6, temperature=0.8, top_p=0.9,
                             stop_token_ids=())
    with compile_guard(watchdog_counter(),
                       label=f"fused mixed traffic (kv_quant={kv_quant})"):
        eng.generate([[1, 2, 3]], sp)                        # bucket 1
        eng.generate([[4, 5, 6], [7, 8, 9]], sp)             # bucket 2
        eng.generate([[1, 2, 3], [4, 5, 6]], [sp, sampled])  # filter variant
        eng.add_request([5, 6, 7], sp)
        eng.step()
        eng.add_request([9, 10, 11], sp)  # deferred wave -> has_prefill
        while eng.has_work():
            eng.step()
    assert eng.fused_steps_total > 0
