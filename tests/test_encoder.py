"""BERT encoder parity vs transformers and embedding-service behavior."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.embedding import HashingTextEncoder, JaxBertTextEncoder
from githubrepostorag_tpu.models.encoder import (
    BertConfig,
    embed,
    forward,
    init_params,
    params_from_hf_state_dict,
)

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def tiny_bert():
    hf_cfg = transformers.BertConfig(
        vocab_size=256, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    torch.manual_seed(0)
    model = transformers.BertModel(hf_cfg).eval()
    cfg = BertConfig.tiny()
    params = params_from_hf_state_dict(model.state_dict(), cfg)
    return model, params, cfg


def test_hidden_states_match_hf(tiny_bert):
    model, params, cfg = tiny_bert
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, size=(2, 11))
    mask = np.ones((2, 11), dtype=np.int64)
    mask[1, 7:] = 0  # padded row
    with torch.no_grad():
        ref = model(torch.tensor(ids), attention_mask=torch.tensor(mask)).last_hidden_state.numpy()
    ours = forward(params, cfg, jnp.asarray(ids, jnp.int32), jnp.asarray(mask, jnp.int32))
    # padded positions may differ; compare only valid tokens
    np.testing.assert_allclose(np.asarray(ours)[0], ref[0], atol=2e-4, rtol=2e-3)
    np.testing.assert_allclose(np.asarray(ours)[1, :7], ref[1, :7], atol=2e-4, rtol=2e-3)


def test_embed_is_masked_mean_pool_normalized(tiny_bert):
    _, params, cfg = tiny_bert
    ids = jnp.asarray([[5, 6, 7, 0, 0]], jnp.int32)
    mask = jnp.asarray([[1, 1, 1, 0, 0]], jnp.int32)
    vec = embed(params, cfg, ids, mask)
    assert vec.shape == (1, cfg.hidden_size)
    assert np.linalg.norm(np.asarray(vec)[0]) == pytest.approx(1.0, abs=1e-5)
    # padding must not affect the embedding
    ids2 = jnp.asarray([[5, 6, 7, 9, 9]], jnp.int32)
    vec2 = embed(params, cfg, ids2, mask)
    np.testing.assert_allclose(np.asarray(vec), np.asarray(vec2), atol=1e-5)


def test_jax_text_encoder_batching(tiny_bert):
    _, params, cfg = tiny_bert

    class StubTokenizer:
        def __call__(self, texts, **kw):
            return {"input_ids": [[(ord(c) % 250) + 1 for c in t[:20]] for t in texts]}

    enc = JaxBertTextEncoder(params, cfg, StubTokenizer(), max_length=64,
                             batch_size=2, e5_prefixes=False)
    texts = ["alpha", "a much longer text about code", "b", "medium length text"]
    vecs = enc.encode(texts)
    assert vecs.shape == (4, cfg.hidden_size)
    # per-text determinism regardless of batch composition
    single = enc.encode([texts[2]])
    np.testing.assert_allclose(vecs[2], single[0], atol=1e-5)


def test_encoder_warmup_then_live_traffic_compiles_zero(tiny_bert):
    """Regression for the tpulint SHP002 finding on JaxBertTextEncoder:
    the encoder had no warmup, so its whole (rows x length) bucket ladder
    compiled under live ingest traffic.  warmup() must cover the ladder
    exactly, and mixed-length mixed-count encode() traffic afterwards must
    compile ZERO new XLA programs."""
    from tests.helpers.compile_guard import compile_guard

    _, params, cfg = tiny_bert

    class StubTokenizer:
        def __call__(self, texts, **kw):
            cap = kw.get("max_length", 64)
            return {"input_ids": [[(ord(c) % 250) + 1 for c in t[:cap]] for t in texts]}

    enc = JaxBertTextEncoder(params, cfg, StubTokenizer(), max_length=64,
                             batch_size=8, e5_prefixes=False)
    assert enc.length_buckets() == [16, 32, 64]
    assert enc.row_buckets() == [8]
    n = enc.warmup()
    assert n == len(enc.row_buckets()) * len(enc.length_buckets())
    texts = (["ab"] * 3                      # length bucket 16, partial batch
             + ["x" * 30] * 8                # length bucket 32, full batch
             + ["y" * 200] * 5)              # truncated -> length bucket 64
    with compile_guard(embed._cache_size, label="live encode traffic"):
        enc.encode(texts)
        enc.encode(["z"])  # single-text query-shaped call
    vecs = enc.encode(texts)
    assert vecs.shape == (len(texts), cfg.hidden_size)


def test_hashing_encoder_similarity_tracks_overlap():
    enc = HashingTextEncoder(dim=384)
    vecs = enc.encode([
        "def ingest_component(repo, namespace)",
        "the ingest_component function handles a repo",
        "completely unrelated text about weather patterns",
    ])
    assert vecs.shape == (3, 384)
    sim_related = float(vecs[0] @ vecs[1])
    sim_unrelated = float(vecs[0] @ vecs[2])
    assert sim_related > sim_unrelated
    assert np.linalg.norm(vecs, axis=1) == pytest.approx([1.0, 1.0, 1.0], abs=1e-5)


def test_hashing_encoder_deterministic():
    a = HashingTextEncoder(dim=384).encode(["some text"])
    b = HashingTextEncoder(dim=384).encode(["some text"])
    np.testing.assert_array_equal(a, b)


def test_get_encoder_falls_back_to_hashing(monkeypatch):
    from githubrepostorag_tpu import embedding

    embedding.set_encoder(None)
    monkeypatch.setenv("EMBED_MODEL", "/nonexistent/path")
    from githubrepostorag_tpu.config import reload_settings

    reload_settings()
    enc = embedding.get_encoder()
    assert isinstance(enc, HashingTextEncoder)
    embedding.set_encoder(None)


def test_dp_sharded_encoder_matches_single_device(tiny_bert):
    """Ingest batch embedding sharded over the dp mesh axis must produce the
    same vectors as the unsharded path (SURVEY.md §2.3 data-parallel row)."""
    from githubrepostorag_tpu.parallel import MeshPlan, make_mesh

    _, params, cfg = tiny_bert

    class StubTokenizer:
        def __call__(self, texts, **kw):
            return {"input_ids": [[(ord(c) % 250) + 1 for c in t[:20]] for t in texts]}

    texts = [f"document number {i} about things" for i in range(20)]
    base = JaxBertTextEncoder(params, cfg, StubTokenizer(), max_length=64,
                              batch_size=8, e5_prefixes=False)
    mesh = make_mesh(MeshPlan(dp=8))
    dp = JaxBertTextEncoder(params, cfg, StubTokenizer(), max_length=64,
                            batch_size=8, e5_prefixes=False, mesh=mesh)
    np.testing.assert_allclose(base.encode(texts), dp.encode(texts),
                               atol=1e-5, rtol=1e-5)
