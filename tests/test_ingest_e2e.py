"""End-to-end ingest -> query: ingest this repository itself with a scripted
LLM into the in-memory store, then answer a question through the agent
(SURVEY.md §7 step 4 / BASELINE config #1, CPU-scale)."""

import json
from pathlib import Path

import pytest

from githubrepostorag_tpu.agent import GraphAgent
from githubrepostorag_tpu.embedding import HashingTextEncoder
from githubrepostorag_tpu.ingest.controller import ingest_component, ingest_many
from githubrepostorag_tpu.ingest.sources import LocalRepoReader
from githubrepostorag_tpu.ingest.types import SourceDoc
from githubrepostorag_tpu.llm import FakeLLM
from githubrepostorag_tpu.retrieval import RetrieverFactory
from githubrepostorag_tpu.store import MemoryVectorStore

INGEST_SCRIPT = {
    r"Summarize": "Summarized section.",
    r"short descriptive title": "Section Title",
    r"technical keywords": "rag, tpu, jax",
    r"README a useful description": "GOOD",
    r"200-300 word technical summary": "File-level summary of the source file.",
    r"summary of this module": "Module-level summary.",
    r"comprehensive overview": "Repo overview: a TPU-native RAG framework.",
}


@pytest.fixture
def repo_docs():
    root = Path(__file__).resolve().parent.parent
    docs = LocalRepoReader(str(root / "githubrepostorag_tpu")).load()
    assert len(docs) > 20
    return docs[:40]  # keep the CPU test quick


def test_ingest_populates_all_five_scopes(repo_docs, tmp_path, monkeypatch):
    monkeypatch.setenv("DATA_DIR", str(tmp_path))
    from githubrepostorag_tpu.config import reload_settings

    reload_settings()
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    llm = FakeLLM(script=INGEST_SCRIPT)
    stages = []
    record = ingest_component(
        "githubrepostorag-tpu", docs=repo_docs, llm=llm, store=store, encoder=enc,
        on_stage=lambda s, t: stages.append(s),
    )
    assert record["written"]["chunk"] > 10
    assert record["written"]["file"] > 5
    assert record["written"]["module"] >= 1
    assert record["written"]["repo"] == 1
    assert record["written"]["catalog"] == 1
    assert set(record["timings"]) >= {
        "preprocess", "code_nodes", "catalog", "file_summaries",
        "module_summaries", "repo_summary", "vector_write",
    }
    assert stages[0] == "preprocess"

    # audit manifest written and parseable
    manifest = (tmp_path / "ingest_runs.jsonl").read_text().strip()
    assert json.loads(manifest)["repo"] == "githubrepostorag-tpu"

    # raw docs dumped for resume
    assert (tmp_path / "repos" / "githubrepostorag-tpu" / "raw_documents_main.json").exists()


def test_reingest_is_idempotent(repo_docs):
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    llm = FakeLLM(script=INGEST_SCRIPT)
    r1 = ingest_component("repo-a", docs=repo_docs, llm=llm, store=store, encoder=enc)
    counts_1 = {t: store.count(t) for t in store.tables()}
    ingest_component("repo-a", docs=repo_docs, llm=llm, store=store, encoder=enc)
    counts_2 = {t: store.count(t) for t in store.tables()}
    assert counts_1 == counts_2, "re-ingest must upsert, not duplicate"


def test_ingest_then_agent_answers(repo_docs):
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    ingest_llm = FakeLLM(script=INGEST_SCRIPT)
    ingest_component("coderag-tpu", docs=repo_docs, llm=ingest_llm, store=store, encoder=enc)

    agent_llm = FakeLLM(script={
        r"Pick the retrieval scope": '{"scope": "chunk", "filters": {}}',
        r"Assess whether the retrieved": '{"coverage": 0.9, "needs_more": false}',
        r"senior engineer": "The engine schedules paged decode steps [1][2].",
    })
    agent = GraphAgent(agent_llm, RetrieverFactory(store, enc), namespace="default")
    res = agent.run("how does the serving engine schedule decode steps?")
    assert res.sources, "agent must retrieve ingested chunks"
    assert "paged decode" in res.answer
    # sources carry real file paths from this repo
    assert any(s["file_path"].endswith(".py") for s in res.sources)


def test_ingest_many_writes_sentinel(tmp_path, monkeypatch):
    monkeypatch.setenv("DATA_DIR", str(tmp_path))
    from githubrepostorag_tpu.config import reload_settings

    reload_settings()
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    llm = FakeLLM(script=INGEST_SCRIPT)
    # inject docs by monkeypatching the loader so no network is touched
    docs = [SourceDoc("src/x.py", "def x():\n    return 1\n")]
    import githubrepostorag_tpu.ingest.controller as ctl

    monkeypatch.setattr(
        "githubrepostorag_tpu.ingest.sources.GithubService.load_repo_documents",
        lambda self, repo, branch=None: docs,
    )
    results = ingest_many(components=["one", "two"], llm=llm, store=store, encoder=enc)
    assert len(results) == 2
    assert all("error" not in r for r in results)
    sentinel = json.loads((tmp_path / ".ingest_complete").read_text())
    assert sentinel["repos"] == 2


def test_ingest_many_isolates_per_repo_failures(monkeypatch):
    store, enc = MemoryVectorStore(), HashingTextEncoder()
    llm = FakeLLM(script=INGEST_SCRIPT)

    def load(self, repo, branch=None):
        if repo == "bad":
            raise RuntimeError("clone exploded")
        return [SourceDoc("a.py", "def a():\n    pass\n")]

    monkeypatch.setattr(
        "githubrepostorag_tpu.ingest.sources.GithubService.load_repo_documents", load
    )
    results = ingest_many(components=["bad", "good"], llm=llm, store=store, encoder=enc)
    assert "error" in results[0]
    assert "error" not in results[1]


def test_cli_local_ingest(tmp_path, monkeypatch, capsys):
    src = tmp_path / "proj"
    src.mkdir()
    (src / "main.py").write_text("def main():\n    print('hello')\n")
    (src / "README.md").write_text("# Proj\nA thing that does things for people.")
    monkeypatch.setenv("DATA_DIR", str(tmp_path / "data"))
    monkeypatch.setenv("LLM_BACKEND", "fake")
    from githubrepostorag_tpu.config import reload_settings

    reload_settings()
    from githubrepostorag_tpu.ingest.__main__ import main

    rc = main(["--local", str(src), "--repo", "proj"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["repo"] == "proj"
    assert out["written"]["chunk"] >= 1
