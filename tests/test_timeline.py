"""Pod timeline & HBM observatory (obs/timeline.py, obs/hbm.py,
obs/continuous.py).

Pins the PR's acceptance bar at obs granularity: the merged Perfetto
export stays inside its time window with properly nested span slices and
adds zero live XLA compiles to the traffic it observes; the page
observatory's per-request page-second attribution agrees with the
allocator-side occupancy integral to within 1%; the continuous profiler
samples every Nth step into a bounded ring; fleet fences and FAULTS
injections land on the victim replica's track; and the flight recorder's
new meta block (eviction/drop counters + high-water marks) stays exact.
"""

from __future__ import annotations

import asyncio
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
from githubrepostorag_tpu.obs.continuous import (ContinuousProfiler,
                                                 register_profiler)
from githubrepostorag_tpu.obs.hbm import PageObservatory, get_hbm_plane
from githubrepostorag_tpu.obs.ledger import SNAPSHOT_FIELDS, TokenLedger
from githubrepostorag_tpu.obs.recorder import FlightRecorder
from githubrepostorag_tpu.obs.slo import SLOMonitor, get_slo_plane
from githubrepostorag_tpu.obs.timeline import (build_timeline, dump_timeline,
                                               set_fleet_events_provider)
from githubrepostorag_tpu.obs.trace import Span, TraceContext
from githubrepostorag_tpu.serving import Engine, SamplingParams

REPO = Path(__file__).resolve().parents[1]

GREEDY = dict(temperature=0.0, stop_token_ids=())


@pytest.fixture(scope="module")
def tiny():
    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32)
    return cfg, params


def _span(name, trace_id, start, end=None, parent=None):
    sp = Span(name, TraceContext(trace_id, parent, 1), start=start)
    sp.end = end
    return sp


def _prompts(n, seed=11):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 512, 6 + i).tolist() for i in range(n)]


def _register_ledger(replica, now, steps=1):
    """One replica with `steps` classified ledger steps ending near now."""
    ledger = TokenLedger(replica, flops_per_tok=1e9, peak_flops=1e12,
                         window_s=600.0)
    snap = {f: 0.0 for f in SNAPSHOT_FIELDS}
    for i in range(steps):
        snap["committed_tokens"] += 4.0
        snap["decode_seconds_total"] += 1e-3
        t0 = now - 0.1 * (steps - i)
        ledger.on_step(dict(snap), t0, t0 + 0.05)
    get_slo_plane().register(replica, ledger=ledger,
                             monitor=SLOMonitor(replica))
    return ledger


# ------------------------------------------------------- recorder meta --


def test_recorder_meta_block_counts_evictions_and_watermarks():
    rec = FlightRecorder(max_traces=2, max_spans_per_trace=3)
    t = time.monotonic()
    for i in range(4):
        for _ in range(5):  # 5 records into a 3-span cap
            rec.record(_span("s", f"{i:032x}", t, t + 0.01))
    meta = rec.summaries_payload()["meta"]
    assert meta["evicted_traces"] == 2
    assert meta["dropped_spans_total"] == 2 * 4
    assert meta["trace_watermark"] == 2
    assert meta["span_watermark"] == 3
    assert meta["trace_ring_utilization"] == 1.0
    assert meta["span_watermark_utilization"] == 1.0
    # clear() resets the marks with the counters — no stale peaks
    rec.clear()
    meta = rec.summaries_payload()["meta"]
    assert meta == {"evicted_traces": 0, "dropped_spans_total": 0,
                    "trace_watermark": 0, "span_watermark": 0,
                    "trace_ring_utilization": 0.0,
                    "span_watermark_utilization": 0.0}


# -------------------------------------------------- continuous profiler --


def test_profiler_samples_every_nth_step_into_a_bounded_ring():
    prof = ContinuousProfiler("rp", sample_every=4, ring=8)
    base = time.monotonic()
    rec = {"decode": 1e-3, "wall": 2e-3, "committed": 4.0}
    for i in range(64):
        prof.on_step(base + i * 0.01, rec, queue=(2, 1, 0), pool=(10, 3))
    samples = prof.samples()
    assert len(samples) == 8  # ring bound, not 64/4
    seqs = [s["seq"] for s in samples]
    assert seqs == list(range(seqs[0], seqs[0] + 32, 4))  # every 4th step
    assert samples[-1]["seq"] == 64
    assert samples[0] == {"t": samples[0]["t"], "seq": seqs[0],
                          "running": 2, "waiting": 1, "parked": 0,
                          "free_pages": 10, "host_pages": 3,
                          "prefill": 0.0, "decode": 1e-3, "spec_verify": 0.0,
                          "kv_migration": 0.0, "kv_transfer": 0.0,
                          "sched_stall": 0.0, "compile": 0.0,
                          "committed": 4.0, "wall": 2e-3, "compiles": 0.0}
    cut = samples[4]["t"]
    assert [s["t"] for s in prof.samples(cut)] == [s["t"] for s in samples[4:]]
    payload = prof.payload()
    assert payload["steps_seen"] == 64
    assert payload["captured"] == 16
    assert payload["retained"] == 8
    assert payload["evicted"] == 8


def test_profiler_sample_every_zero_disables_capture():
    prof = ContinuousProfiler("rz", sample_every=0, ring=8)
    for i in range(16):
        prof.on_step(time.monotonic(), {"wall": 1e-3})
    assert prof.samples() == []
    assert prof.payload()["steps_seen"] == 16


# --------------------------------------------------- hbm observatory ----


def test_hbm_attribution_agrees_with_occupancy_integral(tiny):
    """The acceptance bar: per-request page-second attribution (engine
    hold/release seams) must sum to the allocator-side occupancy integral
    (claims seams) within 1% — same pages, two independent accountings."""
    cfg, params = tiny
    eng = Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                 max_seq_len=64, kv_dtype=jnp.float32, decode_burst=4)
    obs = PageObservatory("ra")
    eng.attach_page_observer(obs)
    sp = SamplingParams(max_tokens=8, **GREEDY)
    for wave in range(3):
        eng.generate(_prompts(4, seed=20 + wave), sp)
    now = time.monotonic()
    occ = obs.occupancy_integral(now)
    attr = obs.attributed_page_seconds(now)
    assert occ > 0.0
    assert abs(occ - attr) <= 0.01 * occ, \
        f"attribution {attr} vs occupancy integral {occ} off by >1%"
    a = obs.payload(now)["attribution"]
    assert a["finished_requests"] == 12
    assert a["live_requests"] == 0
    assert a["by_priority"]  # every request charged to a priority class
    assert sum(p["requests"] for p in a["by_priority"].values()) == 12


def test_hbm_plane_pod_payload_and_justification(tiny):
    cfg, params = tiny
    eng = Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                 max_seq_len=64, kv_dtype=jnp.float32, decode_burst=4)
    obs = PageObservatory("rb")
    eng.attach_page_observer(obs)
    obs.attach_pool_view(lambda: {"num_pages": 32,
                                  "free": eng._allocator.free_count})
    get_hbm_plane().register("rb", obs)
    eng.generate(_prompts(3, seed=30), SamplingParams(max_tokens=4, **GREEDY))
    now = time.monotonic()
    pod = get_hbm_plane().payload(now)
    assert pod["replica_count"] == 1
    rep = pod["replicas"]["rb"]
    assert rep["pool"]["held_claims"] == 0  # everything recycled
    assert rep["pool"]["held_peak"] > 0
    assert pod["totals"]["occupancy_integral_page_s"] > 0
    just = get_hbm_plane().justification("rb", now)
    assert just is not None and just["held_peak"] == rep["pool"]["held_peak"]
    assert get_hbm_plane().justification("missing", now) is None


# ------------------------------------------------------ timeline export --


async def test_timeline_under_live_traffic_window_nesting_zero_compiles(
        tiny, monkeypatch):
    from tests.helpers.compile_guard import compile_guard, watchdog_counter

    from githubrepostorag_tpu.config import reload_settings
    from githubrepostorag_tpu.serving.async_engine import AsyncEngine

    monkeypatch.setenv("PROFILE_SAMPLE_EVERY", "1")  # sample every step
    reload_settings()
    cfg, params = tiny
    eng = Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                 max_seq_len=64, kv_dtype=jnp.float32, decode_burst=8)
    eng.warmup()
    ae = AsyncEngine(eng, replica="rt")
    sp = SamplingParams(max_tokens=8, **GREEDY)
    t_start = time.monotonic()
    try:
        await asyncio.gather(*(ae.generate(p, sp)
                               for p in _prompts(3, seed=40)))
        with compile_guard(watchdog_counter(), label="live traffic"):
            await asyncio.gather(*(ae.generate(p, sp)
                                   for p in _prompts(3, seed=41)))
            # a sampled request-span tree riding the same window
            root = _span("api.request", "ef" * 16, time.monotonic())
            child = Span("engine.decode",
                         TraceContext("ef" * 16, root.span_id, 1),
                         start=time.monotonic())
            child.finish()
            root.finish()
            now = time.monotonic()
            tl = build_timeline(window_s=now - t_start + 1.0, now=now)
    finally:
        await ae.stop()

    md = tl["metadata"]
    assert md["replicas"] == ["rt"]
    src = md["sources"]
    assert src["spans"] >= 2 and src["steps"] > 0 and src["samples"] > 0
    now_us = int(round(now * 1e6))
    t_min_us = int(round((now - md["window_s"]) * 1e6))
    events = [e for e in tl["traceEvents"] if e["ph"] != "M"]
    assert events, "no events from live traffic"
    for e in events:
        assert e["ts"] <= now_us + 1
        # slices may START before the window as long as they reach into it;
        # instants and counters must sit inside it
        end = e["ts"] + e.get("dur", 0)
        assert end >= t_min_us - 1, f"event fully outside window: {e}"
        if e["ph"] in ("i", "C"):
            assert e["ts"] >= t_min_us - 1

    # span slices nest: every child lies within its parent's extent
    spans = {e["args"]["span_id"]: e for e in events
             if e.get("cat") == "span"}
    nested = 0
    for e in spans.values():
        parent = spans.get(e["args"]["parent_id"] or "")
        if parent is None:
            continue
        nested += 1
        assert parent["ts"] <= e["ts"] + 1
        assert (e["ts"] + e["dur"]) <= (parent["ts"] + parent["dur"]) + 2
    assert nested >= 1, "no nested span pair exported"


def test_timeline_fence_and_controller_land_on_their_tracks():
    now = time.monotonic()
    _register_ledger("r0", now)
    _register_ledger("r1", now)
    set_fleet_events_provider(lambda: [
        {"t": now - 0.2, "kind": "router.pick", "replica": "r1",
         "decision": "least_loaded"},
        {"t": now - 0.1, "kind": "fleet.fence", "replica": "r0",
         "failed": 2, "failed_requests": ["req-1", "req-2"]},
    ])
    get_slo_plane().set_controller_info(lambda: {"log": [{
        "t": now - 0.05, "replica": "r0", "action": "failover",
        "reason": "dead", "status": "dispatched",
        "justification": {"liveness": {"thread_alive": False}},
    }]})
    tl = build_timeline(window_s=60.0, now=now)
    events = [e for e in tl["traceEvents"] if e["ph"] != "M"]
    # sorted replicas: r0 -> pid 10, r1 -> pid 11
    fenced = [e for e in events if e.get("cat") == "fence"]
    assert sorted(e["args"]["request_id"] for e in fenced) == ["req-1", "req-2"]
    assert all(e["pid"] == 10 and e["tid"] == 3 for e in fenced), \
        "fenced-request instants must land on the VICTIM replica's track"
    ctrl = [e for e in events if e.get("cat") == "controller"]
    assert len(ctrl) == 1 and ctrl[0]["name"] == "ctrl.failover"
    assert ctrl[0]["pid"] == 3
    assert ctrl[0]["args"]["justification"]["liveness"]["thread_alive"] is False
    picks = [e for e in events if e.get("cat") == "fleet"
             and e["name"] == "router.pick"]
    assert picks and picks[0]["pid"] == 2
    assert tl["metadata"]["sources"]["fenced_requests"] == 2


def test_timeline_fault_instant_attributed_to_victim_replica(monkeypatch):
    from githubrepostorag_tpu.config import reload_settings
    from githubrepostorag_tpu.resilience.faults import get_registry, reset_faults

    _register_ledger("r0", time.monotonic())
    monkeypatch.setenv("FAULTS", "fleet.step.r0:error")
    reload_settings()
    reset_faults()
    action, _ = get_registry().decide("fleet.step.r0")
    assert action == "error"
    tl = build_timeline(window_s=60.0)
    faults = [e for e in tl["traceEvents"] if e.get("cat") == "fault"]
    assert len(faults) == 1
    assert faults[0]["name"] == "fault.error"
    assert faults[0]["args"]["site"] == "fleet.step.r0"
    assert faults[0]["pid"] == 10, \
        "a fault whose site names a replica belongs on that replica's track"


def test_timeline_window_bounds_and_max_events_drop_oldest():
    now = time.monotonic()
    ledger = TokenLedger("r0", flops_per_tok=1e9, peak_flops=1e12,
                         window_s=600.0)
    snap = {f: 0.0 for f in SNAPSHOT_FIELDS}
    for i in range(8):
        snap["committed_tokens"] += 4.0
        t0 = now - 100.0 + i * 10.0  # steps at -100s .. -30s
        ledger.on_step(dict(snap), t0, t0 + 0.05)
    get_slo_plane().register("r0", ledger=ledger, monitor=SLOMonitor("r0"))

    # a 35s window keeps only the newest step (t_end ~ now-30)
    tl = build_timeline(window_s=35.0, now=now)
    assert tl["metadata"]["sources"]["steps"] == 1
    full = build_timeline(window_s=120.0, now=now)
    assert full["metadata"]["sources"]["steps"] == 8
    assert full["metadata"]["dropped_events"] == 0

    total = len([e for e in full["traceEvents"] if e["ph"] != "M"])
    assert total >= 16  # X slice + C counter per step, plus ambient sources
    capped = build_timeline(window_s=120.0, now=now, max_events=3)
    non_meta = [e for e in capped["traceEvents"] if e["ph"] != "M"]
    assert len(non_meta) == 3
    assert capped["metadata"]["dropped_events"] == total - 3
    # oldest dropped, newest kept
    assert min(e["ts"] for e in non_meta) > int((now - 60.0) * 1e6)


def test_dump_timeline_writes_a_perfetto_loadable_file(tmp_path):
    import json as _json

    _register_ledger("r0", time.monotonic())
    path = tmp_path / "timeline.json"
    trace = dump_timeline(str(path), window_s=60.0)
    on_disk = _json.loads(path.read_text())
    assert on_disk["displayTimeUnit"] == "ms"
    assert on_disk["traceEvents"] == trace["traceEvents"]
    phs = {e["ph"] for e in on_disk["traceEvents"]}
    assert "M" in phs and {"X", "C"} & phs


def test_debug_timeline_schema_matches_committed_golden():
    import os

    proc = subprocess.run(
        [sys.executable, "scripts/check_timeline_schema.py"],
        cwd=REPO, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
