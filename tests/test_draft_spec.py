"""Draft-model speculative decoding (serving/draft_spec.py + engine
controller): the default serving path must be greedy-token-IDENTICAL to
plain decode for every workload — speculation changes scheduling, never
tokens — while the adaptive controller (EMA acceptance, deadline margin)
falls back to plain bursts instead of losing throughput, and live traffic
never pays an XLA compile the warmup ladder didn't predict.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params
from githubrepostorag_tpu.serving import Engine, SamplingParams


@pytest.fixture(scope="module")
def pair():
    """Target and an independently-initialized draft: same vocab, different
    weights — the draft disagrees often, exercising partial accepts."""
    cfg = Qwen2Config.tiny()
    target = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    draft = init_params(cfg, jax.random.PRNGKey(7), dtype=jnp.float32)
    return cfg, target, draft


def _engine(params, cfg, **kw):
    defaults = dict(max_num_seqs=2, num_pages=32, page_size=4, max_seq_len=64,
                    kv_dtype=jnp.float32, decode_burst=4)
    defaults.update(kw)
    return Engine(params, cfg, **defaults)


# ------------------------------------------------------------ construction --


def test_draft_requires_cfg_and_matching_vocab(pair):
    cfg, target, draft = pair
    with pytest.raises(ValueError, match="set together"):
        _engine(target, cfg, draft_params=draft)
    import dataclasses

    bad_cfg = dataclasses.replace(cfg, vocab_size=cfg.vocab_size + 1)
    with pytest.raises(ValueError, match="vocab"):
        _engine(target, cfg, draft_params=draft, draft_cfg=bad_cfg)
    with pytest.raises(ValueError, match="exclusive"):
        _engine(target, cfg, draft_params=draft, draft_cfg=cfg, spec_ngram_k=4)


# ----------------------------------------------------------- token parity --


def test_draft_spec_token_identical_perfect_draft(pair):
    """Draft == target: every proposal accepted, output byte-identical."""
    cfg, target, _ = pair
    prompt = list(range(1, 13))
    sp = SamplingParams(max_tokens=24, temperature=0.0, stop_token_ids=())
    plain = _engine(target, cfg).generate([prompt], sp)[0].output_tokens

    eng = _engine(target, cfg, draft_params=target, draft_cfg=cfg,
                  spec_k=4, spec_iters=2)
    res = eng.generate([prompt], sp)[0]
    assert res.output_tokens == plain
    assert eng.spec_proposed > 0
    # a perfect draft is fully accepted (the last round before max_tokens
    # may be truncated by the commit loop, so assert near-total)
    assert eng.spec_accepted / eng.spec_proposed > 0.8
    assert res.spec_proposed == eng.spec_proposed
    assert res.spec_accepted == eng.spec_accepted
    assert res.spec_fallback is None


def test_draft_spec_token_identical_disagreeing_draft(pair):
    """An unrelated draft mispredicts nearly always; the correction token
    machinery must still reproduce plain greedy output exactly."""
    cfg, target, draft = pair
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, cfg.vocab_size, n).tolist() for n in (9, 14)]
    sp = SamplingParams(max_tokens=16, temperature=0.0, stop_token_ids=())
    plain = _engine(target, cfg).generate(prompts, sp)
    # floor=0 keeps the controller from falling back mid-run: this test
    # pins PARITY of the speculative path itself under ~zero acceptance
    eng = _engine(target, cfg, draft_params=draft, draft_cfg=cfg,
                  spec_k=2, spec_iters=2, spec_accept_floor=0.0)
    got = eng.generate(prompts, sp)
    for a, b in zip(got, plain):
        assert a.output_tokens == b.output_tokens
    assert eng.spec_proposed > 0


def test_draft_spec_respects_stop_and_page_accounting(pair):
    """A stop token landing inside an accepted draft run ends the request
    at the stop; pages all return to the pool."""
    cfg, target, _ = pair
    prompt = [3, 4, 5, 6, 7]
    sp0 = SamplingParams(max_tokens=20, temperature=0.0, stop_token_ids=())
    ref = _engine(target, cfg).generate([prompt], sp0)[0].output_tokens
    stop = ref[6]
    sp = SamplingParams(max_tokens=20, temperature=0.0, stop_token_ids=(stop,))
    expect = _engine(target, cfg).generate([prompt], sp)[0]

    eng = _engine(target, cfg, draft_params=target, draft_cfg=cfg,
                  spec_k=4, spec_iters=2)
    got = eng.generate([prompt], sp)[0]
    assert got.output_tokens == expect.output_tokens
    assert got.finish_reason == expect.finish_reason == "stop"
    assert eng._allocator.free_count == eng._allocator.num_pages
    assert not eng.has_work()


def test_draft_spec_mixed_batch_demotes_then_resumes(pair):
    """A sampled row in the batch demotes the whole dispatch to plain
    decode (per-step, not sticky): the greedy row still matches the plain
    engine, and once the sampled row finishes, speculation resumes."""
    cfg, target, _ = pair
    rng = np.random.default_rng(5)
    prompts = [
        list(range(2, 12)),
        rng.integers(0, cfg.vocab_size, 8).tolist(),
    ]
    sps = [
        SamplingParams(max_tokens=24, temperature=0.0, stop_token_ids=()),
        SamplingParams(max_tokens=4, temperature=0.9, stop_token_ids=()),
    ]
    plain = _engine(target, cfg, rng_seed=11).generate(prompts, sps)
    eng = _engine(target, cfg, rng_seed=11, draft_params=target, draft_cfg=cfg,
                  spec_k=2, spec_iters=2)
    got = eng.generate(prompts, sps)
    assert got[0].output_tokens == plain[0].output_tokens
    assert len(got[1].output_tokens) == 4
    # the sampled row finished after 4 tokens; the greedy row's remaining
    # 20 tokens ran speculatively
    assert eng.spec_proposed > 0
    assert got[1].spec_proposed == 0  # sampled rows never propose


def test_draft_spec_with_prefix_cache_and_continuous_batching(pair):
    """Speculation composes with prefix caching + mid-run admission: the
    draft KV for a shared prefix was written by the prefill ride-along, so
    a cache-hit request resumes correctly on both pools."""
    cfg, target, _ = pair
    p1 = list(range(1, 17))
    p2 = list(range(1, 17)) + [20, 21]
    sp = SamplingParams(max_tokens=10, temperature=0.0, stop_token_ids=())
    plain = _engine(target, cfg)
    exp1 = plain.generate([p1], sp)[0].output_tokens
    exp2 = plain.generate([p2], sp)[0].output_tokens

    eng = _engine(target, cfg, draft_params=target, draft_cfg=cfg,
                  spec_k=2, spec_iters=2, prefix_caching=True)
    done = {}
    r1 = eng.add_request(p1, sp)
    # one step: prefill + the ride-along spec dispatch, then admit p2 so
    # it prefills (cache hit) while p1 is still decoding
    for res in eng.step():
        done[res.request_id] = res
    r2 = eng.add_request(p2, sp)
    while eng.has_work():
        for res in eng.step():
            done[res.request_id] = res
    assert done[r1].output_tokens == exp1
    assert done[r2].output_tokens == exp2
    assert eng._allocator.hit_tokens > 0


# -------------------------------------------------- adaptive controller --


def test_acceptance_collapse_falls_back_and_completes():
    """Chaos: an adversarial draft with GUARANTEED zero acceptance —
    target narrates the token cycle t -> t+1 (zero layers + rolled
    lm_head, the bench construction), the draft narrates t -> t+2, so
    every proposal disagrees.  The EMA collapses below the floor, the
    controller marks a STICKY per-request fallback, the fallback counter
    increments, and the request finishes on plain bursts with identical
    tokens — before its deadline."""
    import dataclasses

    cfg = dataclasses.replace(Qwen2Config.tiny(), tie_word_embeddings=False)
    tp = init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32)
    target = dict(tp, layers=jax.tree.map(jnp.zeros_like, tp["layers"]),
                  lm_head=jnp.roll(tp["embed"], 1, axis=0).T)
    dp = init_params(cfg, jax.random.PRNGKey(6), dtype=jnp.float32)
    draft = dict(dp, layers=jax.tree.map(jnp.zeros_like, dp["layers"]),
                 lm_head=jnp.roll(dp["embed"], 2, axis=0).T)

    prompt = [100, 101, 102]
    sp = SamplingParams(max_tokens=24, temperature=0.0, stop_token_ids=())
    plain = _engine(target, cfg).generate([prompt], sp)[0].output_tokens
    assert plain == list(range(103, 127))  # the narrator narrates

    eng = _engine(target, cfg, draft_params=draft, draft_cfg=cfg,
                  spec_k=2, spec_iters=2, spec_accept_floor=0.5)
    deadline = time.monotonic() + 60.0
    rid = eng.add_request(prompt, sp, deadline_s=deadline)
    done = {}
    while eng.has_work():
        for res in eng.step():
            done[res.request_id] = res
    assert time.monotonic() < deadline  # deadline still met
    assert done[rid].output_tokens == plain
    assert done[rid].finish_reason == "length"
    assert done[rid].spec_fallback == "acceptance"
    assert eng.spec_fallbacks.get("acceptance", 0) >= 1


def test_deadline_pressure_falls_back(pair):
    """A request whose remaining deadline budget is under the margin never
    enters the spec burst — plain decode's per-burst stop granularity wins
    near the wire."""
    cfg, target, _ = pair
    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
    eng = _engine(target, cfg, draft_params=target, draft_cfg=cfg,
                  spec_k=2, spec_iters=2, spec_deadline_margin_s=1e9)
    rid = eng.add_request(list(range(1, 9)), sp,
                          deadline_s=time.monotonic() + 60.0)
    done = {}
    while eng.has_work():
        for res in eng.step():
            done[res.request_id] = res
    assert done[rid].spec_fallback == "deadline"
    assert eng.spec_fallbacks.get("deadline", 0) == 1
    assert eng.spec_proposed == 0  # never speculated
    assert len(done[rid].output_tokens) == 8


def test_pick_spec_k_scales_with_acceptance(pair):
    cfg, target, draft = pair
    eng = _engine(target, cfg, draft_params=draft, draft_cfg=cfg, spec_k=4)
    assert eng._spec_k_ladder == [1, 2, 4]

    class R:  # minimal stand-in: _pick_spec_k only reads spec_accept_ema
        def __init__(self, ema):
            self.spec_accept_ema = ema

    assert eng._pick_spec_k([R(None)]) == 4  # no history: optimistic
    assert eng._pick_spec_k([R(1.0)]) == 4
    assert eng._pick_spec_k([R(0.5)]) == 2
    assert eng._pick_spec_k([R(0.05)]) == 1  # floor of 1, never 0


# ------------------------------------------------------- compile discipline --


def test_zero_recompiles_across_mixed_spec_plain_traffic(pair):
    """The acceptance criterion from the issue: after warmup, a mixed
    spec/plain traffic pattern (greedy batches at both buckets, a sampled
    row demoting a step, adaptive-k downshift) compiles ZERO new XLA
    programs."""
    from tests.helpers.compile_guard import compile_guard, watchdog_counter

    cfg, target, draft = pair
    eng = _engine(target, cfg, draft_params=target, draft_cfg=cfg,
                  spec_k=2, spec_iters=2)
    eng.warmup()

    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())
    sampled = SamplingParams(max_tokens=4, temperature=0.8, stop_token_ids=())
    with compile_guard(watchdog_counter(), label="mixed spec/plain traffic"):
        eng.generate([[1, 2, 3]], sp)                       # bucket 1, spec
        eng.generate([[4, 5, 6], [7, 8, 9]], sp)            # bucket 2, spec
        eng.generate([[1, 2, 3], [4, 5, 6]], [sp, sampled])  # mixed -> plain step
    # drive EMA down with a disagreeing draft on the SAME engine shapes:
    # k downshifts along the precompiled ladder
    eng2 = _engine(target, cfg, draft_params=draft, draft_cfg=cfg,
                   spec_k=2, spec_iters=2, spec_accept_floor=0.0)
    eng2.warmup()
    with compile_guard(watchdog_counter(), label="adaptive-k downshift"):
        eng2.generate([list(range(10, 18))], sp)
