"""Self-healing fleet controller (serving/controller.py): the guarded
sense -> decide -> act ladder under a fully simulated clock.

Every test drives ``tick(now=...)`` directly against a fake fleet, so
hysteresis, cooldown, the action budget, and liveness ages are exact
clock arithmetic — no sleeping, no flakes.  The chaos e2e (a FAULTS-
killed replica recovering through a real MultiAsyncEngine) lives in
test_chaos.py; this file proves the decision logic itself.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
from types import SimpleNamespace

from githubrepostorag_tpu.config import reload_settings
from githubrepostorag_tpu.metrics import (
    CTRL_ACTIONS,
    CTRL_FAILOPEN,
    CTRL_SUPPRESSED,
    counter_value,
)
from githubrepostorag_tpu.obs.slo import get_slo_plane
from githubrepostorag_tpu.resilience.faults import reset_faults
from githubrepostorag_tpu.serving.controller import FleetController


class _FakeReplica:
    """Just the surface the controller touches on an AsyncEngine row."""

    def __init__(self, rid: str, lifecycle: str = "active") -> None:
        self.replica = rid
        self.lifecycle = lifecycle
        self.heartbeat: float | None = None
        self.driver_error: str | None = None
        self.alive = False
        self._lock = threading.Lock()
        self.engine = SimpleNamespace(
            _allocator=SimpleNamespace(host_pool_pages=4, num_pages=8),
            _spec_k_ladder=[1, 2, 4],
            spec_k=4,
        )

    def driver_alive(self) -> bool:
        return self.alive


class _FakeFleet:
    """Records every actuator call the controller makes, in order."""

    def __init__(self, replicas: list[_FakeReplica]) -> None:
        self._engines = replicas
        self._by_id = {ae.replica: ae for ae in replicas}
        self.affinity_slack = 4.0
        self.calls: list[tuple[str, str]] = []

    def replicas(self) -> list[_FakeReplica]:
        return list(self._engines)

    def spare_replicas(self) -> list[str]:
        return [ae.replica for ae in self._engines if ae.lifecycle == "spare"]

    def set_affinity_slack(self, slack: float) -> float:
        self.affinity_slack = max(0.5, float(slack))
        return self.affinity_slack

    async def fence(self, replica: str) -> dict:
        self.calls.append(("fence", replica))
        return {"replica": replica, "lifecycle": "draining", "failed": 2}

    async def activate(self, replica: str) -> dict:
        self.calls.append(("activate", replica))
        self._by_id[replica].lifecycle = "active"
        return {"replica": replica, "lifecycle": "active"}

    async def retire(self, replica: str) -> dict:
        self.calls.append(("retire", replica))
        self._by_id[replica].lifecycle = "drained"
        return {"replica": replica, "lifecycle": "drained"}


def _ctrl(monkeypatch, fleet, *, restore=None, **env: str) -> FleetController:
    env.setdefault("CTRL_HYSTERESIS_TICKS", "2")
    env.setdefault("CTRL_COOLDOWN_S", "10")
    env.setdefault("CTRL_MAX_ACTIONS", "4")
    env.setdefault("CTRL_ACTION_WINDOW_S", "100")
    env.setdefault("CTRL_LIVENESS_TIMEOUT_S", "5")
    for key, value in env.items():
        monkeypatch.setenv(key, value)
    reload_settings()
    reset_faults()
    return FleetController(fleet, clock=lambda: 0.0, restore=restore)


def _sensed(rid: str, *, lifecycle: str = "active", thread_alive: bool = True,
            heartbeat_age_s: float | None = 0.1, breaker: str = "closed",
            burn: str = "ok", limiter: str = "none") -> dict:
    return {rid: {
        "ledger": {"limiter": limiter, "window_s": 60.0, "steps": 5,
                   "goodput_tok_s": 100.0},
        "burn": {"state": burn, "classes": {"interactive": burn}},
        "lifecycle": lifecycle,
        "liveness": {"started": True, "thread_alive": thread_alive,
                     "heartbeat_age_s": heartbeat_age_s,
                     "driver_error": None, "breaker": breaker},
    }}


def _script(monkeypatch, ctrl, snap: dict) -> None:
    monkeypatch.setattr(ctrl, "_sense", lambda now: snap)


# ------------------------------------------------------------------- sense --


def test_sense_merges_liveness_with_plane_snapshot(monkeypatch):
    r0, r1 = _FakeReplica("r0"), _FakeReplica("r1")
    ctrl = _ctrl(monkeypatch, _FakeFleet([r0, r1]))
    r0.heartbeat, r0.alive = 10.0, True
    r1.heartbeat, r1.alive, r1.driver_error = 3.0, False, "injected"
    sensed = ctrl._sense(now=11.0)
    assert sensed["r0"]["liveness"] == {
        "started": True, "thread_alive": True, "heartbeat_age_s": 1.0,
        "driver_error": None, "breaker": "closed"}
    assert sensed["r1"]["liveness"]["thread_alive"] is False
    assert sensed["r1"]["liveness"]["heartbeat_age_s"] == 8.0
    assert sensed["r1"]["liveness"]["driver_error"] == "injected"
    # no ledger registered on the (reset) plane: keys present, values None
    assert sensed["r0"]["ledger"] is None and sensed["r0"]["burn"] is None


def test_never_started_replica_is_not_declared_dead(monkeypatch):
    """A spare that has not run a driver yet has no heartbeat; the ladder
    must not failover a replica that was never alive."""
    r0 = _FakeReplica("r0")  # active but heartbeat None, thread dead
    ctrl = _ctrl(monkeypatch, _FakeFleet([r0]))
    assert ctrl.tick(now=0.0) == []
    assert ctrl.tick(now=1.0) == []
    assert ctrl._pending == {}


# -------------------------------------------------------------- hysteresis --


def test_failover_needs_two_consecutive_agreeing_ticks(monkeypatch):
    fleet = _FakeFleet([_FakeReplica("r0"),
                        _FakeReplica("r1", lifecycle="spare")])
    ctrl = _ctrl(monkeypatch, fleet)
    _script(monkeypatch, ctrl, _sensed("r0", thread_alive=False))
    before = counter_value(CTRL_SUPPRESSED, guard="hysteresis")
    before_acts = counter_value(CTRL_ACTIONS, action="failover", reason="dead")

    assert ctrl.tick(now=0.0) == []  # first agreeing tick: suppressed
    assert counter_value(CTRL_SUPPRESSED, guard="hysteresis") == before + 1
    assert ctrl.payload()["hysteresis"]["pending"] == {"r0:failover:dead": 1}

    acted = ctrl.tick(now=1.0)  # second agreeing tick: the ladder fires
    assert [a["action"] for a in acted] == ["failover"]
    assert acted[0]["reason"] == "dead" and acted[0]["ticks_agreed"] == 2
    # fence victim -> activate spare -> retire corpse, in that order
    assert fleet.calls == [("fence", "r0"), ("activate", "r1"),
                           ("retire", "r0")]
    assert counter_value(CTRL_ACTIONS, action="failover",
                         reason="dead") == before_acts + 1
    # the action log entry carries the justification that fired it
    entry = ctrl.payload()["log"][-1]
    assert entry["status"] == "dispatched"
    assert entry["justification"]["liveness"]["thread_alive"] is False
    assert entry["detail"]["spare"] == "r1"


def test_hysteresis_resets_when_the_decision_vanishes(monkeypatch):
    ctrl = _ctrl(monkeypatch, _FakeFleet([_FakeReplica("r0"),
                                          _FakeReplica("r1", lifecycle="spare")]))
    dead = _sensed("r0", thread_alive=False)
    healthy = _sensed("r0")
    monkeypatch.setattr(ctrl, "_sense",
                        lambda now: dead if now != 1.0 else healthy)
    assert ctrl.tick(now=0.0) == []    # dead: pending 1
    assert ctrl.tick(now=1.0) == []    # healthy: pending reset
    assert ctrl.payload()["hysteresis"]["pending"] == {}
    assert ctrl.tick(now=2.0) == []    # dead again: back to pending 1
    assert [a["action"] for a in ctrl.tick(now=3.0)] == ["failover"]


# ------------------------------------------------- cooldown / budget guards --


def test_cooldown_absorbs_oscillation_then_allows_refire(monkeypatch):
    fleet = _FakeFleet([_FakeReplica("r0")])
    ctrl = _ctrl(monkeypatch, fleet, CTRL_COOLDOWN_S="10",
                 CTRL_HOST_POOL_MAX_PAGES="64")
    _script(monkeypatch, ctrl, _sensed("r0", limiter="hbm_pages"))
    alloc = fleet._by_id["r0"].engine._allocator

    ctrl.tick(now=0.0)
    acted = ctrl.tick(now=1.0)
    assert [a["action"] for a in acted] == ["grow_host_pool"]
    assert alloc.host_pool_pages == 6  # 4 * 1.5
    before = counter_value(CTRL_SUPPRESSED, guard="cooldown")
    for t in (2.0, 5.0, 10.9):  # inside now=1 + 10s cooldown
        assert ctrl.tick(now=t) == []
    assert counter_value(CTRL_SUPPRESSED, guard="cooldown") == before + 3
    assert alloc.host_pool_pages == 6
    # cooldown expired: a fresh hysteresis run is still required
    assert ctrl.tick(now=11.1) == []
    acted = ctrl.tick(now=12.1)
    assert [a["action"] for a in acted] == ["grow_host_pool"]
    assert alloc.host_pool_pages == 9  # 6 * 1.5


def test_budget_caps_actions_per_sliding_window(monkeypatch):
    fleet = _FakeFleet([_FakeReplica("r0"), _FakeReplica("r1"),
                        _FakeReplica("r2", lifecycle="spare")])
    ctrl = _ctrl(monkeypatch, fleet, CTRL_MAX_ACTIONS="1",
                 CTRL_ACTION_WINDOW_S="100")
    both = {**_sensed("r0", thread_alive=False),
            **_sensed("r1", thread_alive=False)}
    _script(monkeypatch, ctrl, both)
    before = counter_value(CTRL_SUPPRESSED, guard="budget")

    ctrl.tick(now=0.0)
    acted = ctrl.tick(now=1.0)  # budget of 1: only the first decision fires
    assert [(a["replica"], a["action"]) for a in acted] == [("r0", "failover")]
    assert counter_value(CTRL_SUPPRESSED, guard="budget") == before + 1
    assert ctrl.payload()["budget"]["used"] == 1
    # past the window the budget refills and the starved decision fires
    acted = ctrl.tick(now=102.0)
    assert [(a["replica"], a["action"]) for a in acted] == [("r1", "failover")]


def test_inflight_failover_suppresses_stacked_actions(monkeypatch):
    fleet = _FakeFleet([_FakeReplica("r0"),
                        _FakeReplica("r1", lifecycle="spare")])
    ctrl = _ctrl(monkeypatch, fleet)
    _script(monkeypatch, ctrl, _sensed("r0", thread_alive=False))
    blocker: concurrent.futures.Future = concurrent.futures.Future()
    ctrl._inflight["r0"] = blocker
    before = counter_value(CTRL_SUPPRESSED, guard="inflight")
    assert ctrl.tick(now=0.0) == []
    assert ctrl.tick(now=1.0) == []
    assert counter_value(CTRL_SUPPRESSED, guard="inflight") == before + 2
    assert fleet.calls == []
    blocker.set_result(None)  # the in-flight failover lands
    ctrl.tick(now=2.0)
    assert [a["action"] for a in ctrl.tick(now=3.0)] == ["failover"]


# ------------------------------------------------------------- the ladder --


def test_hbm_pages_prefers_pool_growth_until_capped(monkeypatch):
    fleet = _FakeFleet([_FakeReplica("r0")])
    # cap == current pool: growth impossible, rung 2 (spec-k) is chosen
    ctrl = _ctrl(monkeypatch, fleet, CTRL_HOST_POOL_MAX_PAGES="4",
                 CTRL_COOLDOWN_S="0")
    _script(monkeypatch, ctrl, _sensed("r0", limiter="hbm_pages"))
    eng = fleet._by_id["r0"].engine

    ctrl.tick(now=0.0)
    acted = ctrl.tick(now=1.0)
    assert [a["action"] for a in acted] == ["spec_k_down"]
    assert eng._spec_k_ladder == [1, 2] and eng.spec_k == 2
    ctrl.tick(now=2.0)
    ctrl.tick(now=3.0)
    assert eng._spec_k_ladder == [1] and eng.spec_k == 1
    # at the floor the action is a stamped no-op, never an error
    ctrl.tick(now=4.0)
    acted = ctrl.tick(now=5.0)
    assert acted[0]["action"] == "spec_k_down"
    assert ctrl.payload()["log"][-1]["detail"] == {
        "noop": "spec-k ladder already at its floor"}
    assert eng.spec_k == 1


def test_swap_wait_halves_affinity_slack_with_floor(monkeypatch):
    fleet = _FakeFleet([_FakeReplica("r0")])
    ctrl = _ctrl(monkeypatch, fleet, CTRL_COOLDOWN_S="0")
    _script(monkeypatch, ctrl, _sensed("r0", limiter="swap_wait"))
    ctrl.tick(now=0.0)
    acted = ctrl.tick(now=1.0)
    assert [a["action"] for a in acted] == ["spread_affinity"]
    assert fleet.affinity_slack == 2.0
    for t in (2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 9.0):
        ctrl.tick(now=t)
    assert fleet.affinity_slack == 0.5  # clamped, never degenerate


def test_breaker_open_and_critical_burn_both_mean_failover(monkeypatch):
    for sensed, reason in (
        (_sensed("r0", breaker="open"), "breaker_open"),
        (_sensed("r0", burn="critical"), "burn_critical"),
        (_sensed("r0", heartbeat_age_s=6.0), "wedged"),
    ):
        fleet = _FakeFleet([_FakeReplica("r0"),
                            _FakeReplica("r1", lifecycle="spare")])
        ctrl = _ctrl(monkeypatch, fleet)
        _script(monkeypatch, ctrl, sensed)
        ctrl.tick(now=0.0)
        acted = ctrl.tick(now=1.0)
        assert [(a["action"], a["reason"]) for a in acted] == [
            ("failover", reason)]
        assert ("fence", "r0") in fleet.calls


def test_failover_without_spare_still_fences_and_retires(monkeypatch):
    """A dead driver with no spare must still be fenced and retired — its
    in-flight callers get error frames, never a hang."""
    fleet = _FakeFleet([_FakeReplica("r0")])
    ctrl = _ctrl(monkeypatch, fleet)
    _script(monkeypatch, ctrl, _sensed("r0", thread_alive=False))
    ctrl.tick(now=0.0)
    acted = ctrl.tick(now=1.0)
    assert acted and acted[0]["action"] == "failover"
    assert fleet.calls == [("fence", "r0"), ("retire", "r0")]
    assert ctrl.payload()["log"][-1]["detail"]["no_spare"] is True


def test_failover_restores_snapshot_before_activating_spare(monkeypatch):
    order: list[str] = []
    fleet = _FakeFleet([_FakeReplica("r0"),
                        _FakeReplica("r1", lifecycle="spare")])

    async def activate(replica):
        order.append(f"activate-{replica}")
        return {"replica": replica, "lifecycle": "active"}

    monkeypatch.setattr(fleet, "activate", activate)
    ctrl = _ctrl(monkeypatch, fleet,
                 restore=lambda: order.append("restore") or {"replayed": 3})
    _script(monkeypatch, ctrl, _sensed("r0", thread_alive=False))
    ctrl.tick(now=0.0)
    ctrl.tick(now=1.0)
    fut = ctrl.inflight()["r0"]
    assert fut.done() and order == ["restore", "activate-r1"]
    assert fut.result()["restored"] == {"replayed": 3}


def test_restore_failure_downgrades_to_cold_activate(monkeypatch):
    fleet = _FakeFleet([_FakeReplica("r0"),
                        _FakeReplica("r1", lifecycle="spare")])

    def broken_restore():
        raise RuntimeError("snapshot dir lost")

    ctrl = _ctrl(monkeypatch, fleet, restore=broken_restore)
    _script(monkeypatch, ctrl, _sensed("r0", thread_alive=False))
    ctrl.tick(now=0.0)
    ctrl.tick(now=1.0)
    out = ctrl.inflight()["r0"].result(timeout=5)
    assert out["restored"] == {"error": "snapshot dir lost"}
    # the spare still activated: degraded warm-up beats a down fleet
    assert ("activate", "r1") in fleet.calls


# ---------------------------------------------------------------- fail-open --


def test_sense_exception_fails_open_and_keeps_observing(monkeypatch):
    fleet = _FakeFleet([_FakeReplica("r0"),
                        _FakeReplica("r1", lifecycle="spare")])
    ctrl = _ctrl(monkeypatch, fleet)
    boom = {"on": True}

    def sense(now):
        if boom["on"]:
            raise RuntimeError("plane exploded")
        return _sensed("r0", thread_alive=False)

    monkeypatch.setattr(ctrl, "_sense", sense)
    before = counter_value(CTRL_FAILOPEN)
    assert ctrl.tick(now=0.0) == []
    assert ctrl.tick(now=1.0) == []
    assert counter_value(CTRL_FAILOPEN) == before + 2
    assert ctrl.payload()["failopen"] == 2
    assert ctrl.payload()["log"][-1]["status"] == "failopen"
    # the loop recovers the moment sensing does
    boom["on"] = False
    ctrl.tick(now=2.0)
    assert [a["action"] for a in ctrl.tick(now=3.0)] == ["failover"]


def test_action_exception_fails_open_without_poisoning_others(monkeypatch):
    fleet = _FakeFleet([_FakeReplica("r0"), _FakeReplica("r1")])
    ctrl = _ctrl(monkeypatch, fleet, CTRL_COOLDOWN_S="0")
    both = {**_sensed("r0", limiter="swap_wait"),
            **_sensed("r1", limiter="hbm_pages")}
    _script(monkeypatch, ctrl, both)
    monkeypatch.setattr(ctrl, "_act_spread_affinity",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    before = counter_value(CTRL_FAILOPEN)
    ctrl.tick(now=0.0)
    acted = ctrl.tick(now=1.0)
    # r0's broken rung failed open; r1's grow still executed this tick
    assert [(a["replica"], a["action"]) for a in acted] == [
        ("r1", "grow_host_pool")]
    assert counter_value(CTRL_FAILOPEN) == before + 1
    assert fleet._by_id["r1"].engine._allocator.host_pool_pages == 6


def test_controller_act_fault_seam_drops_the_action(monkeypatch):
    from tests.test_chaos import _enable

    fleet = _FakeFleet([_FakeReplica("r0"),
                        _FakeReplica("r1", lifecycle="spare")])
    ctrl = _ctrl(monkeypatch, fleet)
    _enable(monkeypatch, "fleet.controller.act:drop")
    _script(monkeypatch, ctrl, _sensed("r0", thread_alive=False))
    ctrl.tick(now=0.0)
    assert ctrl.tick(now=1.0) == []  # cleared the guards, dropped at the seam
    assert fleet.calls == []
    entry = ctrl.payload()["log"][-1]
    assert entry["status"] == "dropped" and entry["action"] == "failover"


# -------------------------------------------------------------- publication --


def test_payload_reaches_debug_fleet_via_the_plane(monkeypatch):
    fleet = _FakeFleet([_FakeReplica("r0"),
                        _FakeReplica("r1", lifecycle="spare")])
    ctrl = _ctrl(monkeypatch, fleet)
    _script(monkeypatch, ctrl, _sensed("r0", thread_alive=False))
    ctrl.tick(now=0.0)
    ctrl.tick(now=1.0)
    section = get_slo_plane().fleet_payload()["controller"]
    assert section["actions_total"] == 1 and section["ticks"] == 2
    assert section["log"][-1]["action"] == "failover"
    assert "r0:failover" in section["cooldowns"]
    assert section["budget"]["max_actions"] == 4


async def test_start_stop_runs_the_reconcile_thread(monkeypatch):
    fleet = _FakeFleet([_FakeReplica("r0")])
    for key, value in (("CTRL_TICK_S", "0.01"),):
        monkeypatch.setenv(key, value)
    reload_settings()
    ctrl = FleetController(fleet)  # real clock: thread smoke test
    r0 = fleet._by_id["r0"]
    r0.heartbeat, r0.alive = __import__("time").monotonic(), True
    await ctrl.start()
    try:
        for _ in range(200):
            if ctrl.payload()["ticks"] >= 3:
                break
            await asyncio.sleep(0.01)
        assert ctrl.payload()["ticks"] >= 3
        assert ctrl.payload()["running"] is True
    finally:
        ctrl.stop()
    ticks = ctrl.payload()["ticks"]
    await asyncio.sleep(0.05)
    assert ctrl.payload()["ticks"] == ticks  # genuinely stopped
    assert fleet.calls == []  # a healthy fleet gets no actions
