"""Vector store: cosine ANN, metadata filters, idempotent upsert, persistence."""

import numpy as np
import pytest

from githubrepostorag_tpu.store import Doc, MemoryVectorStore


def _doc(i, vec, **meta):
    return Doc(doc_id=f"d{i}", text=f"text {i}", metadata={k: str(v) for k, v in meta.items()},
               vector=np.asarray(vec, dtype=np.float32))


def test_search_ranks_by_cosine():
    s = MemoryVectorStore()
    s.upsert("embeddings", [
        _doc(0, [1, 0, 0]),
        _doc(1, [0.9, 0.1, 0]),
        _doc(2, [0, 1, 0]),
    ])
    hits = s.search("embeddings", np.array([1.0, 0.0, 0.0]), k=2)
    assert [h.doc.doc_id for h in hits] == ["d0", "d1"]
    assert hits[0].score == pytest.approx(1.0, abs=1e-6)


def test_metadata_filter_restricts_results():
    s = MemoryVectorStore()
    s.upsert("embeddings", [
        _doc(0, [1, 0], repo="alpha", namespace="default"),
        _doc(1, [1, 0], repo="beta", namespace="default"),
    ])
    hits = s.search("embeddings", np.array([1.0, 0.0]), k=5, filter={"repo": "alpha"})
    assert [h.doc.doc_id for h in hits] == ["d0"]


def test_upsert_is_idempotent():
    s = MemoryVectorStore()
    s.upsert("embeddings", [_doc(0, [1, 0])])
    s.upsert("embeddings", [_doc(0, [0, 1])])  # same id, new vector
    assert s.count("embeddings") == 1
    hits = s.search("embeddings", np.array([0.0, 1.0]), k=1)
    assert hits[0].score == pytest.approx(1.0, abs=1e-6)


def test_find_by_metadata_edge_traversal():
    s = MemoryVectorStore()
    s.upsert("embeddings_file", [
        _doc(0, [1, 0], module="ingest", repo="r1"),
        _doc(1, [0, 1], module="ingest", repo="r1"),
        _doc(2, [0, 1], module="api", repo="r1"),
    ])
    adjacent = s.find_by_metadata("embeddings_file", {"module": "ingest"})
    assert {d.doc_id for d in adjacent} == {"d0", "d1"}


def test_delete_and_count():
    s = MemoryVectorStore()
    s.upsert("t", [_doc(0, [1]), _doc(1, [1])])
    assert s.delete("t", ["d0", "nope"]) == 1
    assert s.count("t") == 1


def test_docs_without_vectors_are_stored_but_not_searched():
    s = MemoryVectorStore()
    s.upsert("t", [Doc("raw", "no vector yet"), _doc(1, [1, 0])])
    assert s.count("t") == 2
    hits = s.search("t", np.array([1.0, 0.0]), k=10)
    assert [h.doc.doc_id for h in hits] == ["d1"]


def test_persistence_roundtrip(tmp_path):
    s = MemoryVectorStore(persist_dir=str(tmp_path))
    s.upsert("embeddings", [_doc(0, [1, 0], repo="alpha")])
    s.save()
    s2 = MemoryVectorStore(persist_dir=str(tmp_path))
    assert s2.count("embeddings") == 1
    hit = s2.search("embeddings", np.array([1.0, 0.0]), k=1)[0]
    assert hit.doc.metadata["repo"] == "alpha"


def test_health_reports_tables():
    s = MemoryVectorStore()
    s.upsert("embeddings", [_doc(0, [1])])
    h = s.health()
    assert h["status"] == "UP"
    assert h["tables"] == {"embeddings": 1}


def test_shredded_topics_filter_matches_any_member():
    """Reference parity: ShreddingTransformer explodes list metadata so a
    topics=<member> equality filter matches (vector_write_service.py:118).
    The round-1 flatten-to-string made such filters silently return zero."""
    from githubrepostorag_tpu.ingest.vector_write import sanitize_metadata

    store = MemoryVectorStore()
    meta = sanitize_metadata(
        {"scope": "chunk", "topics": ["Kafka", "Streams", "Consumer-Groups"],
         "keywords": "Kafka, Streams", "file_path": "a.py"},
        "chunk",
    )
    # shredded entries present alongside the display value
    assert meta["topics"] == "Kafka, Streams, Consumer-Groups"
    assert meta["topics:kafka"] == "1" and meta["topics:consumer-groups"] == "1"
    assert meta["keywords:streams"] == "1"

    vec = np.asarray([1.0, 0.0], dtype=np.float32)
    store.upsert("embeddings", [Doc("d1", "kafka consumer", meta, vec)])
    store.upsert("embeddings", [Doc("d2", "other", {"topics": "redis"}, vec)])

    hits = store.search("embeddings", vec, k=10, filter={"topics": "kafka"})
    assert [h.doc.doc_id for h in hits] == ["d1"]
    # scalar topics docs still match exact-equality
    hits = store.search("embeddings", vec, k=10, filter={"topics": "redis"})
    assert [h.doc.doc_id for h in hits] == ["d2"]
    assert [d.doc_id for d in store.find_by_metadata("embeddings", {"topics": "streams"})] == ["d1"]


def test_tech_synonym_topics_filter_retrieves_end_to_end():
    """The agent's TECH_SYNONYMS plan filter (agent/graph.py) must retrieve
    extractor-enriched chunks whose topics LIST contains the tech."""
    from githubrepostorag_tpu.embedding import HashingTextEncoder
    from githubrepostorag_tpu.ingest.vector_write import sanitize_metadata
    from githubrepostorag_tpu.retrieval.retrievers import ScopeRetriever

    store, enc = MemoryVectorStore(), HashingTextEncoder()
    text = "consumer group rebalance handler"
    meta = sanitize_metadata(
        {"scope": "chunk", "namespace": "default", "repo": "svc",
         "module": "stream", "file_path": "stream/consumer.py",
         "topics": ["kafka", "rebalance", "consumer"]},
        "chunk",
    )
    store.upsert("embeddings", [Doc("k1", text, meta, enc.encode([text])[0])])
    r = ScopeRetriever(store, enc, "chunk")
    docs = r.retrieve("how does the kafka consumer rebalance?",
                      {"namespace": "default", "topics": "kafka"})
    assert [d.doc_id for d in docs][:1] == ["k1"]


def test_topk_partial_sort_matches_full_sort_reference():
    """The argpartition top-k path must return exactly what a full stable
    sort by (-score, insertion row) returns — including duplicate-vector
    ties — on randomized corpora, with and without filters."""
    rng = np.random.default_rng(42)
    store = MemoryVectorStore()
    docs = []
    for i in range(60):
        vec = rng.normal(size=12).astype(np.float32)
        if i % 7 == 0 and i:  # plant exact duplicates -> score ties
            vec = np.asarray(docs[i - 1].vector).copy()
        docs.append(Doc(f"d{i:03d}", f"text {i}", {"grp": str(i % 4)}, vec))
    store.upsert("embeddings", docs)
    mat, ids = store._tables["embeddings"].matrix()
    for trial in range(5):
        q = rng.normal(size=12).astype(np.float32)
        scores = mat @ (q / np.linalg.norm(q))
        for flt in (None, {"grp": "1"}):
            rows = [i for i in range(len(ids))
                    if flt is None or docs[i].metadata["grp"] == flt["grp"]]
            # reference: FULL stable sort, score desc then row asc
            ref = sorted(rows, key=lambda i: (-scores[i], i))[:9]
            got = store.search("embeddings", q, k=9, filter=flt)
            assert [h.doc.doc_id for h in got] == [ids[i] for i in ref]


def test_tie_order_is_insertion_order():
    store = MemoryVectorStore()
    v = np.array([0.6, 0.8], dtype=np.float32)
    store.upsert("embeddings", [Doc(f"t{i}", "same", {}, v.copy()) for i in range(5)])
    hits = store.search("embeddings", v, k=3)
    assert [h.doc.doc_id for h in hits] == ["t0", "t1", "t2"]


def test_search_k_nonpositive_returns_empty():
    store = MemoryVectorStore()
    store.upsert("embeddings", [Doc("d0", "x", {}, np.array([1.0, 0.0], dtype=np.float32))])
    assert store.search("embeddings", np.array([1.0, 0.0]), k=0) == []
