"""LLM-output parsing robustness (the load-bearing fallbacks)."""

from githubrepostorag_tpu.utils.json_utils import (
    extract_choice,
    extract_json,
    sanitize_llm_text,
    strip_fences,
    truncate,
)


def test_extract_json_direct():
    assert extract_json('{"scope": "repo"}') == {"scope": "repo"}


def test_extract_json_fenced():
    text = 'Here you go:\n```json\n{"scope": "file", "filters": {}}\n```\nDone.'
    assert extract_json(text) == {"scope": "file", "filters": {}}


def test_extract_json_embedded_in_prose():
    text = 'I think the plan is {"scope": "chunk", "filters": {"repo": "x"}} based on the query.'
    assert extract_json(text) == {"scope": "chunk", "filters": {"repo": "x"}}


def test_extract_json_nested_braces_and_strings():
    text = 'prefix {"a": {"b": "with } brace"}, "c": [1, 2]} suffix'
    assert extract_json(text) == {"a": {"b": "with } brace"}, "c": [1, 2]}


def test_extract_json_garbage_returns_default():
    assert extract_json("no json here", default={}) == {}


def test_sanitize_strips_think_blocks():
    out = sanitize_llm_text("<think>hmm let me reason</think>The answer is 42.")
    assert out == "The answer is 42."


def test_sanitize_strips_role_markers_and_chatty_prefix():
    out = sanitize_llm_text("assistant: Sure, here is the summary:\nIt does X.")
    assert "assistant" not in out.lower()
    assert "It does X." in out


def test_extract_choice_cascade():
    assert extract_choice("The best choice is 3 because...") == "3"
    assert extract_choice("2") == "2"
    assert extract_choice('{"choice": 4}') == "4"
    assert extract_choice("I pick option (2).") == "2"
    assert extract_choice("none of the above") == "1"
    assert extract_choice("") == "1"


def test_strip_fences_passthrough():
    assert strip_fences("plain text") == "plain text"


def test_truncate_budget():
    assert truncate("a" * 100, 10) == "a" * 10
    assert truncate("short", 10) == "short"
