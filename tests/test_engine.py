"""Generation engine: paged prefill/decode vs HF generate, continuous
batching, streaming callbacks, cancellation, page accounting."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from githubrepostorag_tpu.serving import Engine, SamplingParams

transformers = pytest.importorskip("transformers")
import torch  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    from githubrepostorag_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    hf_cfg = transformers.Qwen2Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg.to_dict())
    params = params_from_state_dict(model.state_dict(), cfg)
    return model, params, cfg


def _make_engine(params, cfg, **kw):
    defaults = dict(
        max_num_seqs=4, num_pages=64, page_size=8, max_seq_len=128,
        prefill_chunk=32, kv_dtype=jnp.float32,
    )
    defaults.update(kw)
    return Engine(params, cfg, **defaults)


def _hf_greedy(model, prompt, n):
    ids = torch.tensor([prompt])
    with torch.no_grad():
        out = model.generate(
            ids, max_new_tokens=n, do_sample=False,
            pad_token_id=0, eos_token_id=None, use_cache=True,
        )
    return out[0, len(prompt):].tolist()


def test_greedy_matches_hf(tiny):
    model, params, cfg = tiny
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, size=23).tolist()
    eng = _make_engine(params, cfg)
    res = eng.generate([prompt], SamplingParams(temperature=0.0, max_tokens=10))[0]
    assert res.finish_reason == "length"
    assert res.output_tokens == _hf_greedy(model, prompt, 10)


def test_concurrent_requests_match_individual(tiny):
    model, params, cfg = tiny
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, cfg.vocab_size, size=n).tolist() for n in (5, 17, 33)]
    eng = _make_engine(params, cfg)
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    results = eng.generate(prompts, sp)
    for prompt, res in zip(prompts, results):
        assert res.output_tokens == _hf_greedy(model, prompt, 8), "batched != individual"


def test_chunked_prefill_long_prompt(tiny):
    model, params, cfg = tiny
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, cfg.vocab_size, size=70).tolist()  # > prefill_chunk=32
    eng = _make_engine(params, cfg)
    res = eng.generate([prompt], SamplingParams(temperature=0.0, max_tokens=5))[0]
    assert res.output_tokens == _hf_greedy(model, prompt, 5)


def test_width_bucketed_prefill_matches_hf(tiny):
    """prefill_widths > 1 dispatches short waves at sub-chunk widths (the
    p50-TTFT fix for eval config #5) — tokens must be identical to the
    single-width engine and to HF, across short, bucket-boundary, and
    multi-chunk (resume) prompts, mixed in one batch."""
    model, params, cfg = tiny
    rng = np.random.default_rng(7)
    # chunk=32 -> buckets [32, 16] (floored at 16): 5 -> 16, 16 -> 16,
    # 17 -> 32, 70 -> chunks 32+32+6 (the 6-token resume chunk rides a
    # 16-wide wave)
    prompts = [rng.integers(0, cfg.vocab_size, size=n).tolist() for n in (5, 16, 17, 70)]
    eng = _make_engine(params, cfg, prefill_widths=3)
    assert eng.prefill_width_buckets == [32, 16]
    eng.warmup()
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    for prompt, res in zip(prompts, eng.generate(prompts, sp)):
        assert res.output_tokens == _hf_greedy(model, prompt, 8)


def test_streaming_callback_order(tiny):
    _, params, cfg = tiny
    eng = _make_engine(params, cfg)
    seen: list[tuple[str, int]] = []
    rid = eng.add_request(
        [1, 2, 3, 4], SamplingParams(temperature=0.0, max_tokens=6),
        on_token=lambda r, t: seen.append((r, t)),
    )
    done = []
    while eng.has_work():
        done.extend(eng.step())
    assert [t for _, t in seen] == done[0].output_tokens
    assert all(r == rid for r, _ in seen)


def test_stop_token_ends_generation(tiny):
    model, params, cfg = tiny
    prompt = [7, 8, 9, 10, 11]
    first = _hf_greedy(model, prompt, 1)[0]
    eng = _make_engine(params, cfg)
    res = eng.generate([prompt], SamplingParams(temperature=0.0, max_tokens=20, stop_token_ids=(first,)))[0]
    assert res.finish_reason == "stop"
    assert res.output_tokens == [first]


def test_cancellation(tiny):
    _, params, cfg = tiny
    eng = _make_engine(params, cfg)
    rid = eng.add_request([1, 2, 3], SamplingParams(temperature=0.0, max_tokens=50))
    eng.step()  # prefill + first token
    eng.cancel(rid)
    done = []
    while eng.has_work():
        done.extend(eng.step())
    assert done[0].finish_reason == "cancelled"
    assert eng._allocator.free_count == eng._allocator.num_pages  # pages recycled


def test_pages_exhaustion_queues_requests(tiny):
    _, params, cfg = tiny
    # only 8 pages of 8 tokens: two 20+16-token requests can't both fit
    eng = _make_engine(params, cfg, num_pages=8, max_seq_len=64)
    sp = SamplingParams(temperature=0.0, max_tokens=16)
    prompts = [[1] * 20, [2] * 20, [3] * 20]
    results = eng.generate(prompts, sp)
    assert all(r.finish_reason == "length" for r in results)
    assert all(len(r.output_tokens) == 16 for r in results)
    assert eng._allocator.free_count == eng._allocator.num_pages


def test_sampled_generation_respects_seed_and_temperature(tiny):
    _, params, cfg = tiny
    prompt = list(range(1, 12))
    sp = SamplingParams(temperature=0.8, top_p=0.95, max_tokens=12)
    r1 = _make_engine(params, cfg, rng_seed=7).generate([prompt], sp)[0]
    r2 = _make_engine(params, cfg, rng_seed=7).generate([prompt], sp)[0]
    r3 = _make_engine(params, cfg, rng_seed=8).generate([prompt], sp)[0]
    assert r1.output_tokens == r2.output_tokens  # deterministic per seed
    assert len(r3.output_tokens) == 12


def test_repetition_penalty_discourages_repeats(tiny):
    _, params, cfg = tiny
    prompt = [5] * 10
    base = _make_engine(params, cfg).generate(
        [prompt], SamplingParams(temperature=0.0, max_tokens=16, repetition_penalty=1.0)
    )[0]
    pen = _make_engine(params, cfg).generate(
        [prompt], SamplingParams(temperature=0.0, max_tokens=16, repetition_penalty=1.8)
    )[0]
    assert len(set(pen.output_tokens)) >= len(set(base.output_tokens))


def test_last_page_not_corrupted_by_padding_slots(tiny):
    """Regression: JAX scatter wraps negative indices, so the -1 padding
    slots of inactive rows must not overwrite the last pool slot while a
    live sequence occupies the last page."""
    model, params, cfg = tiny
    rng = np.random.default_rng(9)
    prompt = rng.integers(0, cfg.vocab_size, size=20).tolist()
    # exactly 3 pages of 8 -> the sequence owns the LAST page of the pool,
    # and 3 of the 4 batch rows are inactive (slot -1) every decode step
    eng = _make_engine(params, cfg, num_pages=3, page_size=8, max_seq_len=24, max_num_seqs=4)
    res = eng.generate([prompt], SamplingParams(temperature=0.0, max_tokens=4))[0]
    assert res.output_tokens == _hf_greedy(model, prompt, 4)


def test_bad_prompt_reports_error(tiny):
    _, params, cfg = tiny
    eng = _make_engine(params, cfg)
    res = eng.generate([[]], SamplingParams(max_tokens=4))[0]
    assert res.finish_reason == "error"
    assert "prompt" in res.error


def test_request_larger_than_pool_rejected_not_livelocked(tiny):
    """Regression: a request needing more pages than the whole pool must be
    rejected at intake, not spin the engine forever."""
    _, params, cfg = tiny
    eng = _make_engine(params, cfg, num_pages=4, page_size=8, max_seq_len=128)
    res = eng.generate(
        [[1] * 50, [2] * 10],
        [SamplingParams(temperature=0.0, max_tokens=30), SamplingParams(temperature=0.0, max_tokens=4)],
    )
    assert res[0].finish_reason == "error"
    assert "pages" in res[0].error
    assert res[1].finish_reason == "length"  # queue not head-of-line blocked


def test_rejected_request_surfaces_through_step(tiny):
    _, params, cfg = tiny
    eng = _make_engine(params, cfg)
    rid = eng.add_request([], SamplingParams(max_tokens=4))
    assert eng.has_work()
    finished = eng.step()
    assert [r.request_id for r in finished] == [rid]
    assert finished[0].finish_reason == "error"


def test_top_k_sampling(tiny):
    _, params, cfg = tiny
    prompt = list(range(1, 10))
    greedy = _make_engine(params, cfg).generate(
        [prompt], SamplingParams(temperature=0.0, max_tokens=6)
    )[0]
    k1 = _make_engine(params, cfg).generate(
        [prompt], SamplingParams(temperature=5.0, top_k=1, max_tokens=6)
    )[0]
    # top_k=1 at any temperature collapses to greedy
    assert k1.output_tokens == greedy.output_tokens


# ------------------------------------------------------- decode bursts ----


def test_burst_matches_single_step_greedy():
    """A fused 8-step burst must produce exactly the per-token greedy path."""
    from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(7))
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7]]
    sp = SamplingParams(max_tokens=12, temperature=0.0, stop_token_ids=())

    outs = []
    for burst in (1, 8):
        eng = Engine(params, cfg, max_num_seqs=2, num_pages=32, page_size=4,
                     max_seq_len=64, decode_burst=burst)
        outs.append([r.output_tokens for r in eng.generate(prompts, sp)])
    assert outs[0] == outs[1]


def test_burst_respects_stop_and_max_tokens():
    from githubrepostorag_tpu.models.qwen2 import Qwen2Config, init_params

    cfg = Qwen2Config.tiny()
    params = init_params(cfg, jax.random.PRNGKey(7))
    # find the greedy continuation first, then set its 3rd token as stop
    eng = Engine(params, cfg, max_num_seqs=1, num_pages=32, page_size=4,
                 max_seq_len=64, decode_burst=8)
    free = eng.generate([[1, 2, 3]], SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=()))[0]
    stop_tok = free.output_tokens[0]  # tiny random models repeat greedily; first token is safe

    eng2 = Engine(params, cfg, max_num_seqs=1, num_pages=32, page_size=4,
                  max_seq_len=64, decode_burst=8)
    res = eng2.generate([[1, 2, 3]], SamplingParams(max_tokens=6, temperature=0.0,
                                                    stop_token_ids=(stop_tok,)))[0]
    assert res.finish_reason == "stop"
    assert res.output_tokens == free.output_tokens[:1]  # stop included, burst tail discarded

    res3 = eng2.generate([[1, 2, 3]], SamplingParams(max_tokens=4, temperature=0.0,
                                                     stop_token_ids=()))[0]
    assert res3.finish_reason == "length" and len(res3.output_tokens) == 4


def test_warmup_precompiles_and_leaves_engine_clean(tiny):
    _, params, cfg = tiny
    eng = _make_engine(params, cfg)
    eng.warmup()
    assert not eng.has_work()
    assert eng._allocator.free_count == eng._allocator.num_pages
    # normal traffic after warmup behaves identically to a fresh engine
    prompt = [1, 2, 3, 4]
    sp = SamplingParams(max_tokens=5, temperature=0.0, stop_token_ids=())
    out = eng.generate([prompt], sp)[0].output_tokens
    ref = _make_engine(params, cfg).generate([prompt], sp)[0].output_tokens
    assert out == ref


def test_mid_decode_admission_keeps_pipeline(tiny):
    """A request arriving while others decode must be admitted WITHOUT
    draining the burst pipeline (free pages suffice), and every request's
    greedy output must match a solo run."""
    _, params, cfg = tiny
    sp = SamplingParams(max_tokens=10, temperature=0.0, stop_token_ids=())
    solo = {}
    for prompt in ([1, 2, 3, 4], [9, 8, 7]):
        eng = Engine(params, cfg, max_num_seqs=4, num_pages=64, page_size=4,
                     max_seq_len=64, decode_burst=4)
        solo[tuple(prompt)] = eng.generate([prompt], sp)[0].output_tokens

    eng = Engine(params, cfg, max_num_seqs=4, num_pages=64, page_size=4,
                 max_seq_len=64, decode_burst=4)
    drains = []  # drains that happened with a request still waiting = stalls
    orig = eng._drain_chain
    eng._drain_chain = lambda fin: (
        drains.append(len(eng._waiting)) if eng._waiting else None,
        orig(fin),
    )[1]

    r1 = eng.add_request([1, 2, 3, 4], sp)
    # a few steps so request 1 is mid-decode with a live chain
    for _ in range(3):
        eng.step()
    assert eng._chain is not None
    r2 = eng.add_request([9, 8, 7], sp)
    done = {}
    while eng.has_work():
        for res in eng.step():
            done[res.request_id] = res
    assert done[r1].output_tokens == solo[(1, 2, 3, 4)]
    assert done[r2].output_tokens == solo[(9, 8, 7)]
    # the admission itself must not have drained a live pipeline: a drain
    # while a request sat in the waiting queue means admission stalled decode
    assert not drains, f"admission drained the pipeline: {drains}"


def test_prefill_co_dispatches_with_decode(tiny):
    """A multi-chunk prompt admitted mid-decode must NOT stall running
    streams: every step that prefills a chunk also dispatches a decode
    burst, and all outputs stay token-identical to solo runs."""
    _, params, cfg = tiny
    sp = SamplingParams(max_tokens=24, temperature=0.0, stop_token_ids=())
    long_prompt = list(range(1, 49))  # 48 tokens -> 6 chunks at chunk=8
    solo = {}
    for prompt in ([1, 2, 3, 4], long_prompt):
        eng = Engine(params, cfg, max_num_seqs=4, num_pages=64, page_size=4,
                     max_seq_len=128, prefill_chunk=8, decode_burst=4)
        solo[tuple(prompt)] = eng.generate([prompt], sp)[0].output_tokens

    eng = Engine(params, cfg, max_num_seqs=4, num_pages=64, page_size=4,
                 max_seq_len=128, prefill_chunk=8, decode_burst=4)
    r1 = eng.add_request([1, 2, 3, 4], sp)
    for _ in range(3):
        eng.step()
    assert eng._chain is not None

    r2 = eng.add_request(long_prompt, sp)
    bursts_during_prefill = 0
    done = {}
    while eng.has_work():
        chain_before = eng._chain
        prefilling = any(r.state == "prefilling" for r in eng._row_req.values())
        for res in eng.step():
            done[res.request_id] = res
        req2 = eng._requests.get(r2)
        still_prefilling = req2 is not None and req2.state == "prefilling"
        if prefilling and still_prefilling and eng._chain is not chain_before:
            bursts_during_prefill += 1
    assert done[r1].output_tokens == solo[(1, 2, 3, 4)]
    assert done[r2].output_tokens == solo[tuple(long_prompt)]
    # r2 takes 6 prefill chunks; r1 must have decoded new bursts meanwhile
    assert bursts_during_prefill >= 3, (
        f"only {bursts_during_prefill} decode bursts dispatched while the "
        "long prompt prefilled — running streams stalled"
    )


def test_cancelled_pending_first_wave_does_not_corrupt_others(tiny):
    """Regression: a request cancelled after its prefill wave was queued but
    before the next decode dispatch has row == -1; the overlay must skip it
    (a negative scatter index would WRAP to the last row and corrupt an
    unrelated stream's last-token state)."""
    _, params, cfg = tiny
    sp = SamplingParams(max_tokens=12, temperature=0.0, stop_token_ids=())
    solo = Engine(params, cfg, max_num_seqs=4, num_pages=64, page_size=4,
                  max_seq_len=64, decode_burst=4).generate([[1, 2, 3, 4]], sp)[0]

    eng = Engine(params, cfg, max_num_seqs=4, num_pages=64, page_size=4,
                 max_seq_len=64, decode_burst=4)
    r1 = eng.add_request([1, 2, 3, 4], sp)
    for _ in range(3):  # r1 mid-decode with a live chain
        eng.step()
    assert eng._chain is not None
    r2 = eng.add_request([9, 8, 7], sp)
    # drive the prefill half of a step by hand: a full step() would consume
    # the wave into the co-dispatched decode burst, and this regression is
    # about a cancel landing in the window between those two dispatches
    eng._try_prefill([])
    assert eng._pending_first
    eng.cancel(r2)

    done = {}
    while eng.has_work():
        for res in eng.step():
            done[res.request_id] = res
    assert done[r2].finish_reason == "cancelled"
    # the victim stream must be byte-identical to its solo run
    assert done[r1].output_tokens == solo.output_tokens


def test_prefill_priority_same_outputs(tiny):
    """prefill_priority is a SCHEDULING change only: a wave of requests
    admitted together produces the same tokens as the co-dispatched
    default, and no deadlock occurs when the wave exceeds rows/pages."""
    _, params, cfg = tiny
    from githubrepostorag_tpu.serving import Engine, SamplingParams

    prompts = [[(7 * i + j) % cfg.vocab_size for j in range(6 + i)]
               for i in range(6)]
    sp = SamplingParams(max_tokens=10, temperature=0.0, stop_token_ids=())

    def run(**kw):
        eng = Engine(params, cfg, max_num_seqs=2, num_pages=16, page_size=4,
                     max_seq_len=32, kv_dtype=jnp.float32, decode_burst=4, **kw)
        return [r.output_tokens for r in eng.generate(prompts, sp)]

    assert run(prefill_priority=True) == run()
