"""KV page tiering: host-RAM swap tier + cross-user prefix-page dedup.

Covers: TieredPageAllocator residency mechanics (save/evict/fault-in,
rc-pinning, dual residency, claim dedup), the can_admit duplicate-hash and
need=0 edges on every allocator, engine round trips with token-identical
outputs across eviction + fault-in, deadline-reap accounting over both
tiers, and the zero-live-recompile discipline across mixed
resident/swapped traffic.
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from githubrepostorag_tpu.serving import Engine, SamplingParams
from githubrepostorag_tpu.serving.kv_cache import (
    OutOfPages,
    PageAllocator,
    PrefixCachingAllocator,
    TieredPageAllocator,
    page_hashes,
)

transformers = pytest.importorskip("transformers")
import torch  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    from githubrepostorag_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    hf_cfg = transformers.Qwen2Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg.to_dict())
    params = params_from_state_dict(model.state_dict(), cfg)
    return model, params, cfg


def _engine(params, cfg, **kw):
    # deliberately tiny device pool: 12 pages of 4 tokens, so a single
    # 40-token filler oversubscribes it and forces tier traffic
    defaults = dict(
        max_num_seqs=2, num_pages=12, page_size=4, max_seq_len=64,
        prefill_chunk=16, kv_dtype=jnp.float32, decode_burst=4,
        kv_tier="on", kv_host_pool_pages=32,
    )
    defaults.update(kw)
    return Engine(params, cfg, **defaults)


def _payload():
    # opaque page payload stand-in; the allocator never looks inside
    return (None, None, None, None, None, None)


def _saved_parked(al: TieredPageAllocator, hashes):
    """Park ``hashes``' pages and complete their writebacks (saved state)."""
    for page, h in al.evict(len(hashes)):
        al.complete_writeback(h, _payload())


# ----------------------------------------------------- can_admit edges --


def test_plain_allocator_can_admit_ignores_hashes_and_need_zero():
    al = PageAllocator(2)
    h = page_hashes(list(range(8)), 4)
    assert al.can_admit([], 0)
    assert al.can_admit(h + h, 2)  # duplicates never change the answer
    al.allocate(2)
    assert al.can_admit(h + h, 0)  # need=0 trivially admits, even exhausted
    assert not al.can_admit(h + h, 1)


@pytest.mark.parametrize("cls", [PrefixCachingAllocator, TieredPageAllocator])
def test_prefix_can_admit_duplicate_hash_matches_once(cls):
    """A degenerate prompt can repeat a chain hash; the matched run must
    stop at the first re-claim — double-counting the page would admit a
    request share() cannot actually back."""
    al = cls(2)
    [h0] = page_hashes(list(range(4)), 4)
    [page] = al.allocate(1)
    al.register(h0, page)
    al.release([page])  # parked; 1 plain-free page remains
    assert al.can_admit([h0, h0], 2)  # 1 match + 1 fresh: fits
    assert not al.can_admit([h0, h0], 3)  # dup must NOT count as 2 matches
    assert al.share([h0, h0]) == [page]  # and share agrees: one claim only
    al.release([page])


@pytest.mark.parametrize("cls", [PrefixCachingAllocator, TieredPageAllocator])
def test_prefix_can_admit_need_zero(cls):
    al = cls(1)
    al.allocate(1)  # pool exhausted
    assert al.can_admit([], 0)
    assert not al.can_admit([], 1)


# ------------------------------------------------- tiered allocator unit --


def test_tiered_host_hit_extends_admittable_run():
    """A host-resident hash consumes a device page (fault-in target) but
    keeps the shareable run going instead of breaking it."""
    al = TieredPageAllocator(4, host_pool_pages=8)
    h = page_hashes(list(range(8)), 4)  # 2-page chain
    pages = al.allocate(2)
    al.register(h[0], pages[0])
    al.register(h[1], pages[1])
    al.release(pages)
    _saved_parked(al, h)
    # drop both device copies: saved pages reclaim at zero cache cost
    held = al.allocate(4)
    assert al.tier_drops == 2 and al.host_pages == 2
    al.release(held)
    # both pages now host-only; the run still matches end to end
    assert al.can_admit(h, 4)  # 2 fault-in targets + 2 fresh = 4 free
    assert not al.can_admit(h, 5)
    shared = al.share(h)
    assert len(shared) == 2 and al.fault_ins == 2
    assert len(al.fault_in()) == 2  # both staged scatters drain once
    al.release(shared)


def test_rc_pinned_pages_never_evict():
    """A page another request still shares (rc>0) is pinned on device: it
    never enters the LRU, so neither evict() nor allocate() can take it."""
    al = TieredPageAllocator(2, host_pool_pages=8)
    [h0] = page_hashes(list(range(4)), 4)
    [page] = al.allocate(1)
    al.register(h0, page)
    assert al.share([h0]) == [page]  # rc 2
    al.release([page])  # rc 1: still live, still pinned
    assert al.evict(8) == []
    [other] = al.allocate(1)
    assert other != page  # the free page, not the pinned one
    with pytest.raises(OutOfPages):
        al.allocate(1)  # pinned page is not reclaimable
    al.release([other])
    al.release([page])  # rc 0: parked, NOW evictable
    assert [p for p, _ in al.evict(8)] == [page]


def test_refault_is_paid_once_for_n_claimants():
    """share() re-registers a faulting hash immediately, so N concurrent
    claimants of an evicted prefix resolve to the one faulting page: one
    migration, N-1 dedup hits."""
    al = TieredPageAllocator(6, host_pool_pages=8)
    h = page_hashes(list(range(8)), 4)
    pages = al.allocate(2)
    al.register(h[0], pages[0])
    al.register(h[1], pages[1])
    al.release(pages)
    _saved_parked(al, h)
    held = al.allocate(6)  # flush device copies (saved -> host-only)
    al.release(held)
    claims = [al.share(h) for _ in range(3)]
    assert al.fault_ins == 2  # first claimant faults the 2-page chain...
    assert all(c == claims[0] for c in claims)  # ...everyone gets its pages
    assert al.dedup_hits == 4  # 2 pages x 2 followers ride the same fault
    assert len(al.fault_in()) == 2  # one staged scatter per page, total
    for c in claims:
        al.release(c)
    assert al.free_count == al.num_pages


def test_writeback_respects_host_cap_and_lru():
    al = TieredPageAllocator(8, host_pool_pages=2)
    h = page_hashes(list(range(16)), 4)  # 4-page chain
    pages = al.allocate(4)
    for hh, p in zip(h, pages):
        al.register(hh, p)
    al.release(pages)
    plan = al.evict(8)
    assert len(plan) == 2  # host cap bounds the in-flight set
    for page, hh in plan:
        al.complete_writeback(hh, _payload())
    assert al.evict(8) == []  # at cap: nothing further to save
    assert al.host_pages == 2 and al.writebacks == 2


def test_claim_dedup_accounting():
    al = TieredPageAllocator(4)
    h = page_hashes(list(range(12)), 4)  # 3-page chain
    al.claim(h)
    al.claim(h[:1])
    assert al.pending_claim_pages(h) == 3
    al.unclaim(h[:1])
    assert al.pending_claim_pages(h) == 3  # first hash still claimed once
    al.unclaim(h)
    assert al.pending_claim_pages(h) == 0
    # a servable hash is never "pending" — nothing to wait for
    [page] = al.allocate(1)
    al.register(h[0], page)
    al.claim(h[1:])
    assert al.pending_claim_pages(h) == 2
    al.release([page])


# ---------------------------------------------------------------- engine --


def test_evicted_prefix_faults_in_token_identical(tiny):
    """The tentpole round trip: a prefix registered, written back to host,
    its device copies reclaimed by an oversubscribing filler, then
    re-admitted via fault-in — outputs stay token-identical to an untiered
    engine and the pool balances."""
    _, params, cfg = tiny
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, size=24).tolist()  # 6 pages
    filler = rng.integers(0, cfg.vocab_size, size=40).tolist()  # 10 pages
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=(),
                        repetition_penalty=1.2)

    ref = _engine(params, cfg, prefix_caching=False, kv_tier="off",
                  kv_host_pool_pages=0)
    expected = ref.generate([prompt], sp)[0].output_tokens

    eng = _engine(params, cfg)
    assert eng.generate([prompt], sp)[0].output_tokens == expected
    eng.flush_kv_migrations()  # save the parked prefix to the host tier
    wb = eng._allocator.writebacks
    assert wb >= 5  # (24-1)//4 registered pages all reached host RAM
    eng.generate([filler], sp)  # 11-page footprint: drops saved copies
    assert eng._allocator.tier_drops > 0
    res = eng.generate([prompt], sp)[0]
    assert res.output_tokens == expected  # faulted KV is byte-faithful
    assert res.faulted_pages > 0
    assert eng._allocator.fault_ins == res.faulted_pages
    assert eng.kv_fault_dispatches >= 1
    assert eng._allocator.free_count == eng._allocator.num_pages
    assert not eng.has_work()


def test_deadline_reap_frees_both_tiers(tiny):
    """A reaped request whose prefix just faulted in must return every
    device page and drop its pending claims; the host copies stay behind
    as cache (they are content, not capacity)."""
    _, params, cfg = tiny
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, size=24).tolist()
    filler = rng.integers(0, cfg.vocab_size, size=40).tolist()
    sp = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=())

    # chunk smaller than the un-cached tail, so the reap lands while the
    # request is still mid-prefill and still HOLDS registration claims
    eng = _engine(params, cfg, prefill_chunk=8)
    eng.generate([prompt], sp)
    eng.flush_kv_migrations()
    eng.generate([filler], sp)  # push the prefix to host-only residency
    # re-admit with a fresh tail so the admission also CLAIMS unregistered
    # hashes (the cross-user dedup path) before the reap hits
    tail = rng.integers(0, cfg.vocab_size, size=16).tolist()
    sp2 = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())
    rid = eng.add_request(prompt + tail, sp2,
                          deadline_s=time.monotonic() + 60.0)
    req = eng._requests[rid]
    eng.step()  # admits + dispatches the fault-in scatters
    assert req.faulted_pages > 0
    assert req.claimed_hashes  # the new tail's pages are claimed
    req.deadline_ts = time.monotonic() - 1.0
    finished = []
    while eng.has_work():
        finished.extend(eng.step())
    assert [r.finish_reason for r in finished] == ["deadline"]
    assert eng._allocator.free_count == eng._allocator.num_pages
    assert eng._allocator._claims == {}  # reap unclaimed the tail hashes
    assert eng._allocator._staged_faults == []
    assert eng._allocator.host_pages > 0  # the cache itself survives


def test_dedup_hold_waits_for_inflight_twin(tiny):
    """An identical-prefix follower admitted while the leader is still
    prefilling must HOLD (one registration dedups its whole prefix) rather
    than duplicate the footprint — and both must finish correct."""
    _, params, cfg = tiny
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, cfg.vocab_size, size=32).tolist()  # 8 pages
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())
    # pool fits the leader (9 pages) but not two full footprints (18)
    eng = _engine(params, cfg, num_pages=12, prefill_chunk=8)
    results = eng.generate([prompt, prompt], sp)
    assert results[0].output_tokens == results[1].output_tokens
    assert eng.dedup_holds > 0  # the follower waited instead of ballooning
    assert eng._allocator.free_count == eng._allocator.num_pages


def test_zero_recompiles_across_mixed_resident_swapped_traffic(tiny):
    """Migration must ride the warmup-precompiled gather/scatter buckets:
    a traffic mix spanning resident hits, writebacks, tier drops, and
    fault-ins compiles ZERO new XLA programs after warmup."""
    from tests.helpers.compile_guard import compile_guard, watchdog_counter

    _, params, cfg = tiny
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, cfg.vocab_size, size=24).tolist()
    filler = rng.integers(0, cfg.vocab_size, size=40).tolist()
    sp = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=())

    eng = _engine(params, cfg)
    eng.warmup()
    with compile_guard(watchdog_counter(), label="mixed tier traffic"):
        eng.generate([prompt], sp)  # cold prefill
        eng.flush_kv_migrations()  # writeback burst (gather)
        eng.generate([prompt], sp)  # resident cache hit
        eng.generate([filler], sp)  # oversubscribe: tier drops
        eng.flush_kv_migrations()
        eng.generate([prompt], sp)  # fault-in burst (scatter)
    assert eng._allocator.writebacks > 0
    assert eng._allocator.fault_ins > 0


def test_scatter_pages_padding_never_touches_the_last_page():
    """Regression: a non-full migration burst pads its index vector with
    -1, and jnp normalizes negative indices (-1 -> P-1) BEFORE the
    mode="drop" out-of-bounds check — an unfixed scatter zeroes the pool's
    last page on every padded fault-in burst, silently corrupting whatever
    request owns it (caught live: a 7-page fault-in bucketed to 8 garbled
    a re-admitted prefix's output through the serving API)."""
    from githubrepostorag_tpu.ops.page_migration import (
        gather_pages, scatter_pages)

    L, n_kv, P, ps, hd, nb = 2, 2, 6, 4, 8, 4
    rng = np.random.default_rng(17)
    k0 = jnp.asarray(rng.standard_normal((L, n_kv, P, ps, hd)), jnp.float32)
    v0 = jnp.asarray(rng.standard_normal((L, n_kv, P, ps, hd)), jnp.float32)
    payload_k = jnp.asarray(rng.standard_normal((L, n_kv, nb, ps, hd)),
                            jnp.float32)
    payload_v = jnp.asarray(rng.standard_normal((L, n_kv, nb, ps, hd)),
                            jnp.float32)
    idx = jnp.asarray(np.array([2, -1, -1, -1], np.int32))

    k1, v1, _, _ = scatter_pages(k0.copy(), v0.copy(), idx, payload_k,
                                 v_vals=payload_v)
    # the one real row landed...
    np.testing.assert_array_equal(k1[:, :, 2], payload_k[:, :, 0])
    np.testing.assert_array_equal(v1[:, :, 2], payload_v[:, :, 0])
    # ...and every other page — the LAST one above all — is untouched
    for p in [0, 1, 3, 4, 5]:
        np.testing.assert_array_equal(k1[:, :, p], k0[:, :, p])
        np.testing.assert_array_equal(v1[:, :, p], v0[:, :, p])

    # gather side: padding rows may hold anything, but the real rows must
    # read back exactly what the scatter committed
    gk, gv, _, _ = gather_pages(k1, v1, idx)
    np.testing.assert_array_equal(gk[:, :, 0], payload_k[:, :, 0])
    np.testing.assert_array_equal(gv[:, :, 0], payload_v[:, :, 0])
