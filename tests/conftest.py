"""Test harness: force JAX onto a virtual 8-device CPU mesh before any jax
import so sharding tests (pjit/shard_map over a Mesh) run without TPUs, and
give every test a clean in-process bus/store.
"""

import os
import sys

# Must happen before jax initializes its backends.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (no pytest-asyncio in image)."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {name: pyfuncitem.funcargs[name] for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(func(**kwargs))
        return True
    return None


@pytest.fixture(autouse=True)
def _fresh_state():
    """Reset process-wide singletons (bus hub, store, settings) per test."""
    from githubrepostorag_tpu.config import reload_settings
    from githubrepostorag_tpu.events.memory import reset_memory_hub
    from githubrepostorag_tpu.store.factory import reset_store

    reload_settings()
    reset_memory_hub()
    reset_store()
    yield
    reset_memory_hub()
    reset_store()
