"""Test harness: force JAX onto a virtual 8-device CPU mesh before any jax
import so sharding tests (pjit/shard_map over a Mesh) run without TPUs, and
give every test a clean in-process bus/store.
"""

import os
import sys

# Tests always run on a virtual 8-device CPU mesh; the real chip is for
# bench.py only.  The env vars must be set before jax initializes its
# backends, and because this machine's sitecustomize imports jax at
# interpreter startup (pinning JAX_PLATFORMS=axon -> the TPU), we must ALSO
# override via jax.config after import.
_TPU_TESTS = os.environ.get("TPU_TESTS") == "1"  # integration runs on the chip

if not _TPU_TESTS:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if not _TPU_TESTS:
    jax.config.update("jax_platforms", "cpu")
    assert jax.devices()[0].platform == "cpu", "tests must run on the CPU backend"
    assert jax.device_count() == 8, "tests expect the virtual 8-device CPU mesh"

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (no pytest-asyncio in image)."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {name: pyfuncitem.funcargs[name] for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(func(**kwargs))
        return True
    return None


@pytest.fixture(autouse=True)
def _fresh_state():
    """Reset process-wide singletons (bus hub, store, settings) per test."""
    from githubrepostorag_tpu.config import reload_settings
    from githubrepostorag_tpu.events.memory import reset_memory_hub
    from githubrepostorag_tpu.obs.continuous import reset_profilers
    from githubrepostorag_tpu.obs.hbm import reset_hbm_plane
    from githubrepostorag_tpu.obs.slo import reset_slo_plane
    from githubrepostorag_tpu.obs.timeline import reset_fleet_events_provider
    from githubrepostorag_tpu.resilience.faults import reset_faults
    from githubrepostorag_tpu.resilience.policy import reset_breakers
    from githubrepostorag_tpu.store.factory import reset_store

    def _reset_obs():
        reset_profilers()
        reset_hbm_plane()
        reset_fleet_events_provider()

    reload_settings()
    reset_memory_hub()
    reset_store()
    reset_faults()
    reset_breakers()
    reset_slo_plane()
    _reset_obs()
    yield
    reset_memory_hub()
    reset_store()
    reset_faults()
    reset_breakers()
    reset_slo_plane()
    _reset_obs()
