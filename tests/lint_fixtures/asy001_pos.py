"""ASY001 positive: blocking calls parked on the event loop."""
import subprocess
import time

import requests


async def poll_backend(url):
    time.sleep(1.0)  # freezes every coroutine on the loop
    resp = requests.get(url, timeout=5)  # sync HTTP on the loop
    subprocess.run(["true"], check=True)  # sync child process on the loop
    return resp


async def retry_with_backoff(fn, attempts=3):
    """The resilience-layer bug class: a retry helper whose backoff sleep
    blocks the event loop, stalling every other in-flight job between
    attempts (must be asyncio.sleep)."""
    for n in range(attempts):
        try:
            return await fn()
        except ConnectionError:
            time.sleep(0.05 * (2 ** n))  # parks the whole loop per retry
    raise ConnectionError("out of attempts")
