"""ASY001 positive: blocking calls parked on the event loop."""
import subprocess
import time

import requests


async def poll_backend(url):
    time.sleep(1.0)  # freezes every coroutine on the loop
    resp = requests.get(url, timeout=5)  # sync HTTP on the loop
    subprocess.run(["true"], check=True)  # sync child process on the loop
    return resp
