"""WPA002 router negative: the same driver-writes / router-reads digest
pattern, but both sites swap through one lock (the ReplicaDigest
publish/snapshot discipline)."""
