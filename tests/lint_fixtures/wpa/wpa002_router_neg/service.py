import threading


class Replica:
    def __init__(self):
        self.resident = frozenset()
        self._lock = threading.Lock()

    def _drive(self):
        while True:
            with self._lock:
                self.resident = frozenset([b"page"])

    async def pick(self, hashes):
        with self._lock:
            resident = self.resident
        n = 0
        for h in hashes:
            if h not in resident:
                break
            n += 1
        return n
