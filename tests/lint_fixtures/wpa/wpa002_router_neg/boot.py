import threading

from wpa002_router_neg.service import Replica


def launch(rep: Replica):
    threading.Thread(target=rep._drive, daemon=True).start()
