from wpa004_pos.pool import PagePool


class Cache:
    def __init__(self):
        self.pool = PagePool()

    def reserve(self, req, n):
        pages = self.pool.allocate(n)
        if n > 4:
            return None  # drops the owned handle: leak
        req.pages = pages
        return req

    def drop_one(self):
        pages = self.pool.allocate(1)
        self.pool.release(pages)
        self.pool.release(pages)  # double free

    def teardown(self, req):
        self.pool.release(req.pages)
