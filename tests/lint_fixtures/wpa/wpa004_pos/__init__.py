"""WPA004 positive: a page handle leaked by an early return and a
double-free — the allocate/release pairing broken both ways."""
