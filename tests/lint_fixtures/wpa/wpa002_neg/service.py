import threading


class Service:
    def __init__(self):
        self.status = "idle"
        self._lock = threading.Lock()

    async def update(self):
        with self._lock:
            self.status = "busy"

    def _run(self):
        while True:
            with self._lock:
                if self.status == "busy":
                    return
