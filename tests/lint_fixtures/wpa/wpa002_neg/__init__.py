"""WPA002 negative: the same cross-domain access pattern, but both sites
acquire the same lock."""
