"""WPA002 positive: status attribute written on the event loop, read on
the driver thread, no common lock.  The Thread spawn lives in a second
module — the domain seed is cross-module."""
