class Service:
    def __init__(self):
        self.status = "idle"

    async def update(self):
        self.status = "busy"

    def _run(self):
        while True:
            if self.status == "busy":
                return
