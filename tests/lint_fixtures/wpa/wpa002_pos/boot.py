import threading

from wpa002_pos.service import Service


def launch(svc: Service):
    threading.Thread(target=svc._run, daemon=True).start()
