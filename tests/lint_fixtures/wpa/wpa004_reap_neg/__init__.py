"""WPA004 reap negative (int4 flavor): the correct reap sweep — int4
nibble planes share one page handle, released exactly once, with the
per-page scale table cleared alongside."""
