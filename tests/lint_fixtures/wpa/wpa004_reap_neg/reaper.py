from wpa004_reap_neg.pool import PagePool


class Reaper:
    def __init__(self):
        self.pool = PagePool()
        self.scales = {}

    def reap_int4_request(self, n):
        pages = self.pool.allocate(n)
        # one handle covers both nibble planes: exactly one release
        self.scales.pop(id(pages), None)
        self.pool.release(pages)
