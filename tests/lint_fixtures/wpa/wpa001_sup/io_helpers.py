import time


def refresh_cache():
    # tpulint: disable=WPA001 -- startup-only path; the loop serves no traffic until this returns
    time.sleep(0.5)
    return {}
