"""WPA001 suppressed: same shape as the positive, silenced with a
justified directive at the blocking call site."""
