from wpa001_sup.io_helpers import refresh_cache


async def handle_request(request):
    data = refresh_cache()
    return data
