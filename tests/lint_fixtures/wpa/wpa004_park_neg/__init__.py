"""WPA004 park negative: both legal closes of a parked handle — the
resume path (victim re-admits, ownership returns, eventually released)
and the reap path (released while parked)."""
