from wpa004_park_neg.pool import PagePool


class Scheduler:
    def __init__(self):
        self.pool = PagePool()

    def preempt_then_resume(self, n):
        pages = self.pool.allocate(n)
        self.pool.park(pages)  # victim parked to the host tier
        self.pool.resume(pages)  # re-admitted: ownership returns
        self.pool.release(pages)

    def preempt_then_reap(self, n):
        pages = self.pool.allocate(n)
        self.pool.park(pages)
        self.pool.release(pages)  # reaped while parked: legal close
