"""WPA002 router suppressed: lock-free digest swap silenced with a
justification (single frozenset reference store, stale-tolerant reader)."""
