"""WPA004 tier positive: a freed handle passed to a tier migration
(use-after-release) and a handle parked on the host tier then dropped —
evict() moves pages, it does not release them."""
