class OutOfPages(Exception):
    pass


class PagePool:
    def __init__(self, n=8):
        self.free = list(range(n))
        self.host = []

    def allocate(self, n):
        if n > len(self.free):
            raise OutOfPages()
        out, rest = self.free[:n], self.free[n:]
        self.free = rest
        return out

    def evict(self, pages):
        self.host.extend(pages)

    def fault_in(self, pages):
        self.host = [p for p in self.host if p not in pages]

    def release(self, pages):
        self.free.extend(pages)
