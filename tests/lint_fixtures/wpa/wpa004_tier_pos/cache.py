from wpa004_tier_pos.pool import PagePool


class Cache:
    def __init__(self):
        self.pool = PagePool()

    def evict_after_free(self):
        pages = self.pool.allocate(2)
        self.pool.release(pages)
        self.pool.evict(pages)  # use-after-release: pages already freed

    def park(self, n):
        pages = self.pool.allocate(n)
        self.pool.evict(pages)
        return None  # evict moved pages to host, never released: leak
