class Replica:
    def __init__(self):
        self.resident = frozenset()

    def _drive(self):
        while True:
            self.resident = frozenset([b"page"])

    async def pick(self, hashes):
        n = 0
        for h in hashes:
            if h not in self.resident:
                break
            n += 1
        return n
