"""WPA002 router positive: per-replica digest attributes written on the
driver thread, read by the event-loop router's pick path, no common lock —
the exact cross-domain handoff serving/routing.py exists to make safe."""
