class OutOfPages(Exception):
    pass


class PagePool:
    def __init__(self, n=8):
        self.free = list(range(n))

    def allocate(self, n):
        if n > len(self.free):
            raise OutOfPages()
        out, rest = self.free[:n], self.free[n:]
        self.free = rest
        return out

    def release(self, pages):
        self.free.extend(pages)
