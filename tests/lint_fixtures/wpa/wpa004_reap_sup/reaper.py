from wpa004_reap_sup.pool import PagePool


class Reaper:
    def __init__(self):
        self.pool = PagePool()

    def reap_int4_request(self, n):
        pages = self.pool.allocate(n)
        self.pool.release(pages)
        # tpulint: disable=WPA004 -- idempotent shutdown sweep: release() tolerates already-freed pages during teardown only
        self.pool.release(pages)
