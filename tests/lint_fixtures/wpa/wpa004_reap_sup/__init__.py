"""WPA004 reap suppressed (int4 flavor): the double-free shape silenced
with a justified directive at the second release."""
