"""WPA003 positive: a threading.Lock held across an await — the driver
thread contending for the same lock deadlocks against the loop."""
