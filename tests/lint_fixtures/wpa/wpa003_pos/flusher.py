import threading

from wpa003_pos.sink import Sink


class Flusher:
    def __init__(self):
        self._lock = threading.Lock()
        self.sink = Sink()

    async def flush(self, batch):
        with self._lock:
            await self.sink.send(batch)
