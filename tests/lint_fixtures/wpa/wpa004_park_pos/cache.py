from wpa004_park_pos.pool import PagePool


class Scheduler:
    def __init__(self):
        self.pool = PagePool()

    def preempt_and_forget(self, n):
        pages = self.pool.allocate(n)
        self.pool.park(pages)
        return None  # parked, never resumed nor released: the victim leaks

    def park_after_free(self, n):
        pages = self.pool.allocate(n)
        self.pool.release(pages)
        self.pool.park(pages)  # use-after-release: pages already freed
