class OutOfPages(Exception):
    pass


class PagePool:
    def __init__(self, n=8):
        self.free = list(range(n))
        self.parked = []

    def allocate(self, n):
        if n > len(self.free):
            raise OutOfPages()
        out, rest = self.free[:n], self.free[n:]
        self.free = rest
        return out

    def park(self, pages):
        self.parked.extend(pages)

    def resume(self, pages):
        self.parked = [p for p in self.parked if p not in pages]

    def release(self, pages):
        self.free.extend(pages)
