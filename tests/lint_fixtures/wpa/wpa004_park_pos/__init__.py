"""WPA004 park positive: a victim parked and then dropped (never resumed
nor released — the parked-leak shape) and a freed handle parked
afterwards (use-after-release)."""
