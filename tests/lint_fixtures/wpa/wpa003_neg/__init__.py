"""WPA003 negative: awaiting under an asyncio.Lock (async with) is the
intended pattern — only sync locks held across awaits are flagged."""
