class Sink:
    async def send(self, batch):
        return len(batch)
