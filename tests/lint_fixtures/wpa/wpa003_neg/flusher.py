import asyncio

from wpa003_neg.sink import Sink


class Flusher:
    def __init__(self):
        self._lock = asyncio.Lock()
        self.sink = Sink()

    async def flush(self, batch):
        async with self._lock:
            await self.sink.send(batch)
