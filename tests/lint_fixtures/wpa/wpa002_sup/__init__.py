"""WPA002 suppressed: lock-free flag write silenced with a justification
(the GIL-atomic-bool-signal idiom)."""
