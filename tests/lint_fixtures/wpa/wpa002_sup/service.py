class Service:
    def __init__(self):
        self.status = "idle"

    async def update(self):
        # tpulint: disable=WPA002 -- GIL-atomic string store; the driver polls it and tolerates one stale iteration
        self.status = "busy"

    def _run(self):
        while True:
            if self.status == "busy":
                return
