"""WPA004 transfer positive: an export dropped without ever landing
(dangling export), a payload imported twice (double-import), and an
export of already-released pages (use-after-release)."""
