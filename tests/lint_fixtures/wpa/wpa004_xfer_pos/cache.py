from wpa004_xfer_pos.pool import PagePool


class Handoff:
    def __init__(self):
        self.src_pool = PagePool()
        self.dst_pool = PagePool()

    def drop_in_flight(self, n):
        pages = self.src_pool.allocate(n)
        self.src_pool.export_pages(pages)
        return None  # dangling export: never imported nor released

    def double_land(self, n):
        pages = self.src_pool.allocate(n)
        self.src_pool.export_pages(pages)
        self.dst_pool.import_pages(pages)
        self.dst_pool.import_pages(pages)  # second landing clobbers the first

    def export_freed(self, n):
        pages = self.src_pool.allocate(n)
        self.src_pool.release(pages)
        self.src_pool.export_pages(pages)  # ships pages already reused
