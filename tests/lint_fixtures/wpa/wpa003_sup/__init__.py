"""WPA003 suppressed: sync lock across an await, silenced with a
justification at the await site."""
