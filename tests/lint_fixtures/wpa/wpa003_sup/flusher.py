import threading

from wpa003_sup.sink import Sink


class Flusher:
    def __init__(self):
        self._lock = threading.Lock()
        self.sink = Sink()

    async def flush(self, batch):
        with self._lock:
            # tpulint: disable=WPA003 -- single-writer lock; no other domain ever acquires it (profiling-only build)
            await self.sink.send(batch)
