from wpa001_pos.io_helpers import refresh_cache


async def handle_request(request):
    # direct call from a coroutine: refresh_cache inherits event_loop
    data = refresh_cache()
    return data
