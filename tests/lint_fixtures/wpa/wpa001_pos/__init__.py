"""WPA001 positive: a sync helper two modules away from the async def
blocks — only the whole-program pass can see it."""
