import time


def refresh_cache():
    # blocking primitive in a sync function; harmless in isolation
    time.sleep(0.5)
    return {}
