import time


def refresh_cache():
    time.sleep(0.5)
    return {}
