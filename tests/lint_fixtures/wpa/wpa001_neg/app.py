import asyncio

from wpa001_neg.io_helpers import refresh_cache


async def handle_request(request):
    loop = asyncio.get_running_loop()
    data = await loop.run_in_executor(None, refresh_cache)
    return data
