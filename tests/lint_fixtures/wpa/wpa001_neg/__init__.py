"""WPA001 negative: the same blocking helper, but only ever reached
through run_in_executor — it runs in the pool, not on the loop."""
