"""WPA004 tier suppressed: the park-on-host leak silenced with a
justified directive at the return site."""
