from wpa004_tier_sup.pool import PagePool


class Cache:
    def __init__(self):
        self.pool = PagePool()

    def park(self, n):
        pages = self.pool.allocate(n)
        self.pool.evict(pages)
        # tpulint: disable=WPA004 -- warm-pool prefill: the host tier owns parked pages until the next generation sweep releases them in bulk
        return None
