from wpa004_xfer_neg.pool import PagePool


class Handoff:
    def __init__(self):
        self.src_pool = PagePool()
        self.dst_pool = PagePool()

    def ship(self, n):
        pages = self.src_pool.allocate(n)
        self.src_pool.export_pages(pages)  # in flight toward the peer
        self.dst_pool.import_pages(pages)  # exactly one landing
        self.src_pool.release(pages)  # source copy reclaimed

    def abandoned(self, n):
        pages = self.src_pool.allocate(n)
        self.src_pool.export_pages(pages)
        self.src_pool.release(pages)  # transfer gave up: legal close
