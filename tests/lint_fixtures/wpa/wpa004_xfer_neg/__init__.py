"""WPA004 transfer negative: export/import done right — every exported
handle reaches exactly one import (or a release on the abandon path) and
the source copy is released after the landing."""
