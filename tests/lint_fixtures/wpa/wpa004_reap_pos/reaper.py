from wpa004_reap_pos.pool import PagePool


class Reaper:
    def __init__(self):
        self.pool = PagePool()
        self.scales = {}

    def reap_int4_request(self, n):
        pages = self.pool.allocate(n)
        # int4 pools store k and v as nibble planes of the SAME pages:
        # sweeping "per plane" returns the one handle twice
        self.pool.release(pages)  # k-plane sweep
        self.pool.release(pages)  # v-plane sweep: double-free

    def reap_on_deadline(self, rid, n):
        pages = self.pool.allocate(n)
        self.scales.pop(rid, None)  # per-page scale table cleared...
        return None  # ...but the pages never release: reap leak
