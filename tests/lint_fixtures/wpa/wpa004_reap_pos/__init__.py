"""WPA004 reap positive (int4 flavor): the reap sweep frees an int4
request's pages once per nibble plane — the k-plane and v-plane views
share ONE page handle, so the second release is a double-free — and a
deadline reap that drops the handle after clearing the scale table
without ever releasing (the int4 reap leak)."""
