"""WPA004 negative: the alloc-absorb-commit-release shape done right —
every path from allocate reaches exactly one release."""
