from wpa004_neg.pool import OutOfPages, PagePool


class Cache:
    def __init__(self):
        self.pool = PagePool()

    def reserve(self, req, n):
        shared = self.pool.share(req.key)
        try:
            pages = shared + self.pool.allocate(n - len(shared))
        except OutOfPages:
            self.pool.release(shared)
            return None
        req.pages = pages
        return req

    def teardown(self, req):
        self.pool.release(req.pages)
