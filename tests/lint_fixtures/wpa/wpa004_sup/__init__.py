"""WPA004 suppressed: the early-return leak silenced with a justified
directive at the return site."""
