from wpa004_sup.pool import PagePool


class Cache:
    def __init__(self):
        self.pool = PagePool()

    def reserve(self, req, n):
        pages = self.pool.allocate(n)
        if n > 4:
            # tpulint: disable=WPA004 -- admission-reject path; the caller reclaims the whole pool generation on reject
            return None
        req.pages = pages
        return req

    def teardown(self, req):
        self.pool.release(req.pages)
