"""WPA004 tier negative: evict/fault_in round trip done right — the
handle stays owned across tier moves and still reaches exactly one
release."""
