from wpa004_tier_neg.pool import PagePool


class Cache:
    def __init__(self):
        self.pool = PagePool()

    def rebalance(self, req, n):
        pages = self.pool.allocate(n)
        self.pool.evict(pages)  # parked on the host tier, still owned
        self.pool.fault_in(pages)  # back to device, still the same handle
        req.pages = pages
        return req

    def teardown(self, req):
        self.pool.release(req.pages)
