from wpa004_park_sup.pool import PagePool


class Scheduler:
    def __init__(self):
        self.pool = PagePool()

    def preempt_for_drain(self, n):
        pages = self.pool.allocate(n)
        self.pool.park(pages)
        # tpulint: disable=WPA004 -- drain-mode park: the shutdown sweep releases every parked handle in bulk after the fleet quiesces
        return None
