"""WPA004 park suppressed: the parked-leak shape silenced with a
justified directive at the drop site."""
