class OutOfPages(Exception):
    pass


class PagePool:
    def __init__(self, n=8):
        self.free = list(range(n))
        self.inflight = []

    def allocate(self, n):
        if n > len(self.free):
            raise OutOfPages()
        out, rest = self.free[:n], self.free[n:]
        self.free = rest
        return out

    def export_pages(self, pages):
        self.inflight.extend(pages)

    def import_pages(self, pages):
        self.inflight = [p for p in self.inflight if p not in pages]

    def release(self, pages):
        self.free.extend(pages)
