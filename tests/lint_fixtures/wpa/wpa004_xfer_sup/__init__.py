"""WPA004 transfer suppressed: the dangling-export shape silenced with a
justified directive at the return site."""
