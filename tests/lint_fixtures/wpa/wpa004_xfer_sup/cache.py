from wpa004_xfer_sup.pool import PagePool


class Handoff:
    def __init__(self):
        self.src_pool = PagePool()
        self.dst_pool = PagePool()

    def replicate(self, n):
        pages = self.src_pool.allocate(n)
        self.src_pool.export_pages(pages)
        # tpulint: disable=WPA004 -- fire-and-forget replication: the peer acks asynchronously and the janitor sweep releases unacked exports in bulk
        return None
