"""SHP004 negative: the literal is wrapped in the operand's dtype — the
documented fix."""
