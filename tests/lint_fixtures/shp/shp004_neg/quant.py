import jax
import jax.numpy as jnp


@jax.jit
def scale_rows(x):
    return x


def apply_scale(x, cfg):
    return scale_rows(cfg.kv_scale * jnp.float32(0.5))
