import jax


def _model(x):
    return x + 1


class Engine:
    def decode_step(self, x):
        f = jax.jit(_model)  # tpulint: disable=SHP003 -- one-shot offline tool, never on the serving path
        return f(x)
