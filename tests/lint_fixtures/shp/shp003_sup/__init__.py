"""SHP003 suppressed: per-step jit construction with a justified inline
suppression."""
