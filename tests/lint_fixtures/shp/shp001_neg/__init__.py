"""SHP001 negative: the same cross-module flow, but the length passes a
bucketing barrier before reaching the shape position."""
