from shp001_neg.shapes import pad_batch


def handle_batch(requests):
    n = len(requests)
    return pad_batch(n)
