import jax.numpy as jnp


def next_bucket(n, cap, minimum=16):
    b = minimum
    while b < n:
        b *= 2
    return min(b, cap)


def pad_batch(rows):
    rows = next_bucket(rows, 64)
    return jnp.zeros((rows, 128), jnp.float32)
