import jax.numpy as jnp


def ring_buffer(width):
    # width is tainted via the caller in scheduler.py; a ring buffer sized
    # by the packed-wave token count recompiles per wave composition
    return jnp.zeros((1, width), jnp.int32)
