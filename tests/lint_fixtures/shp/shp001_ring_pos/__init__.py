"""SHP001 positive (ring-prefill flavor): the token count of a packed
ring wave is len() of request-sized data; sizing the [1, width] ring
buffer by it compiles a fresh XLA ring program for every distinct wave
composition.  The source is in scheduler.py, the sink in pack.py."""
