from shp001_ring_pos.pack import ring_buffer


def pack_wave(tokens):
    # len() of the packed wave's flattened tokens is the taint source: it
    # changes with every mix of long prompts sharing one ring pass
    width = len(tokens)
    return ring_buffer(width)
