"""SHP004 positive: a bare Python literal mixed with a config-dtyped
operand in a traced argument — the weak type resolves per config and
keys dtype recompiles."""
