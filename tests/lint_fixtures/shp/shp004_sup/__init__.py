"""SHP004 suppressed: weak-type mix with a justified inline
suppression."""
