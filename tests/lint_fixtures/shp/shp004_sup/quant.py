import jax


@jax.jit
def scale_rows(x):
    return x


def apply_scale(x, cfg):
    return scale_rows(cfg.kv_scale * 0.5)  # tpulint: disable=SHP004 -- kv_scale dtype is pinned to float32 at load time
