"""SHP002 positive (ring-prefill flavor): a serving class dispatches its
jitted ring pass at ladder-bucketed widths on the hot path but defines no
warmup routine — the whole ring ladder compiles under live traffic."""
