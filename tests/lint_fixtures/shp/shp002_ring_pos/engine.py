import jax
import jax.numpy as jnp


@jax.jit
def ring_pass(buf):
    return buf + 1


def ring_width_ladder(total, cap, minimum=64):
    w = minimum
    while w < total:
        w *= 2
    return min(w, cap)


class RingPrefillServer:
    def prefill_step(self, prompts):
        total = sum(len(p) for p in prompts)
        width = ring_width_ladder(total, 256)
        buf = jnp.zeros((1, width), jnp.int32)
        return ring_pass(buf)
