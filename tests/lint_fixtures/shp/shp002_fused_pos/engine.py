import jax
import jax.numpy as jnp


@jax.jit
def fused_step_burst(hist):
    return hist + 1


def row_bucket(n, cap, minimum=1):
    b = minimum
    while b < n:
        b *= 2
    return min(b, cap)


class FusedStepEngine:
    def decode_step(self, running):
        rb = row_bucket(len(running), 8)
        hist = jnp.zeros((rb, 64), jnp.int32)
        return fused_step_burst(hist)
