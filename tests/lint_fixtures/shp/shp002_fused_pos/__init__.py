"""SHP002 positive (fused-decode flavor): a serving class dispatches its
jitted fused step at row-bucketed shapes on the hot path but defines no
warmup routine — the (bucket, has_prefill, filter) variant set compiles
under live traffic."""
