"""SHP002 positive: a serving class runs bucketed jit dispatches on its
hot path but defines no warmup routine — the whole ladder compiles under
live traffic."""
