"""SHP002 negative (ring-prefill flavor): the same serving class, but
warmup() precompiles the jitted ring pass at every ladder width the hot
path can dispatch."""
