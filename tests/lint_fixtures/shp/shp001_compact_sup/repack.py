import jax.numpy as jnp


def repack_src(rows):
    return jnp.zeros((rows,), jnp.int32)  # tpulint: disable=SHP001 -- one-shot offline repack tool, recompile cost paid once at exit
