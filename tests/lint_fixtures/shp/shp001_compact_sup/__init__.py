"""SHP001 suppressed (compaction flavor): the positive flow with a
justified inline suppression on the sink line."""
