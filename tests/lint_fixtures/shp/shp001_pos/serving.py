from shp001_pos.shapes import pad_batch


def handle_batch(requests):
    # len() of request data is the taint source
    n = len(requests)
    return pad_batch(n)
