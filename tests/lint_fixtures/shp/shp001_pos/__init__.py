"""SHP001 positive: a request-derived length crosses a module boundary
and reaches a device allocation with no bucketing barrier — only the
interprocedural taint pass can see it."""
