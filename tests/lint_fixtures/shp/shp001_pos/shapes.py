import jax.numpy as jnp


def pad_batch(rows):
    # rows is tainted via the caller in serving.py; the shape position
    # compiles a fresh XLA program for every distinct request count
    return jnp.zeros((rows, 128), jnp.float32)
