"""SHP002 negative (fused-decode flavor): the same serving class, but
warmup() precompiles the jitted fused step at every row bucket the hot
path can dispatch."""
