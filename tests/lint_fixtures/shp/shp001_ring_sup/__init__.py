"""SHP001 suppressed (ring-prefill flavor): the positive flow with a
justified inline suppression on the sink line."""
