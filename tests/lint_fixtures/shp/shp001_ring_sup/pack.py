import jax.numpy as jnp


def ring_buffer(width):
    return jnp.zeros((1, width), jnp.int32)  # tpulint: disable=SHP001 -- offline repro harness replays one captured wave, single compile
