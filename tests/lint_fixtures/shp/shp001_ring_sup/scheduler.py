from shp001_ring_sup.pack import ring_buffer


def pack_wave(tokens):
    width = len(tokens)
    return ring_buffer(width)
