from shp001_compact_pos.repack import repack_src


def sweep(docs):
    # len() of the surviving docs is the taint source: it changes with
    # every delete batch the compactor drains
    live = len(docs)
    return repack_src(live)
