import jax.numpy as jnp


def repack_src(rows):
    # rows is tainted via the caller in compactor.py; a gather source
    # vector sized by the live-row count recompiles per survivor count
    return jnp.zeros((rows,), jnp.int32)
