"""SHP001 positive (compaction flavor): the live-row count surviving a
tombstone sweep is len() of request-sized data; sizing the repack gather
vector by it compiles a fresh XLA program for every distinct survivor
count.  The source is in compactor.py, the sink in repack.py."""
