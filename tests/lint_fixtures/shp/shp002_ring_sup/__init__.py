"""SHP002 suppressed (ring-prefill flavor): no-warmup ring class with a
justified inline suppression on the class line."""
