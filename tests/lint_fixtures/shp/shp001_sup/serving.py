from shp001_sup.shapes import pad_batch


def handle_batch(requests):
    n = len(requests)
    return pad_batch(n)
