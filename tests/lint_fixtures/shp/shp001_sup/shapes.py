import jax.numpy as jnp


def pad_batch(rows):
    return jnp.zeros((rows, 128), jnp.float32)  # tpulint: disable=SHP001 -- admission control bounds the batch to one size upstream
