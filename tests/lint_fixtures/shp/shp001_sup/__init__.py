"""SHP001 suppressed: the positive flow with a justified inline
suppression on the sink line."""
