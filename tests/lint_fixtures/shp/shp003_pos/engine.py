import jax


def _model(x):
    return x + 1


class Engine:
    def decode_step(self, x):
        f = jax.jit(_model)
        return f(x)
