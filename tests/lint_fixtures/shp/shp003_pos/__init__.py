"""SHP003 positive: jax.jit constructed inside a per-step method — the
compile cache dies with the wrapper on every call."""
