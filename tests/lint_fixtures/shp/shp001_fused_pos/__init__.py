"""SHP001 positive (fused-decode flavor): the spec-verify window width of
the fused step grid is len() of the live n-gram draft; sizing the
[rows, width] window buffer by it compiles a fresh fused program for
every draft length traffic produces.  The source is in burst.py, the
sink in grid.py."""
