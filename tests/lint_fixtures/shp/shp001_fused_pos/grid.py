import jax.numpy as jnp


def window_grid(rows, width):
    # width is tainted via the caller in burst.py: a verify window sized
    # by the live draft length mints a new fused-kernel grid per draft
    return jnp.zeros((rows, width), jnp.int32)
