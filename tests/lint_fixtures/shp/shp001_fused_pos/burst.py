from shp001_fused_pos.grid import window_grid


def fused_burst(rows, draft_tokens):
    # len() of the n-gram draft is the taint source: it varies with every
    # history match, so the fused window shape follows live traffic
    width = len(draft_tokens) + 1
    return window_grid(rows, width)
