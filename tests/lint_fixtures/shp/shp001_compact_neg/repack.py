import jax.numpy as jnp


def next_bucket(n, cap, minimum=16):
    b = minimum
    while b < n:
        b *= 2
    return min(b, cap)


def repack_src(rows):
    rows = next_bucket(rows, 256)
    return jnp.zeros((rows,), jnp.int32)
