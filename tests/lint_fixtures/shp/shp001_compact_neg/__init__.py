"""SHP001 negative (compaction flavor): the same survivor-count flow, but
the repack vector is padded to the capacity bucket before it reaches the
shape position — one program per capacity rung, not per survivor count."""
