from shp001_compact_neg.repack import repack_src


def sweep(docs):
    live = len(docs)
    return repack_src(live)
