"""SHP002 suppressed: no-warmup class with a justified inline
suppression on the class line."""
