import jax.numpy as jnp

SPEC_K = 4


def window_grid(rows, width):
    width = SPEC_K + 1  # static window: short drafts pad, never resize
    return jnp.zeros((rows, width), jnp.int32)
