"""SHP001 negative (fused-decode flavor): the same draft flow, but the
verify window is padded to the static k+1 the engine compiled — one
fused program per (row bucket, k), any draft length."""
