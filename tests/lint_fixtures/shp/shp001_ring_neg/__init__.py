"""SHP001 negative (ring-prefill flavor): the same packed-wave flow, but
the ring buffer is padded to a ladder width before it reaches the shape
position — one ring program per ladder rung, any wave composition."""
