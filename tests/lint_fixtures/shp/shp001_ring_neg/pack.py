import jax.numpy as jnp


def ring_width_ladder(total, cap, minimum=64):
    w = minimum
    while w < total:
        w *= 2
    return min(w, cap)


def ring_buffer(width):
    width = ring_width_ladder(width, 256)
    return jnp.zeros((1, width), jnp.int32)
