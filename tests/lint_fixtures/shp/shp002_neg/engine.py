import jax
import jax.numpy as jnp


@jax.jit
def run_model(batch):
    return batch * 2


def bucketize(n, cap):
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


class Server:
    def warmup(self):
        for rows in (1, 2, 4, 8):
            run_model(jnp.zeros((rows, 4), jnp.float32))

    def decode_step(self, xs):
        rows = bucketize(len(xs), 8)
        batch = jnp.zeros((rows, 4), jnp.float32)
        return run_model(batch)
