"""SHP002 negative: the same serving class, but warmup() precompiles the
jitted callee the hot path dispatches."""
