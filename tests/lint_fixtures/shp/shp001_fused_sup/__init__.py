"""SHP001 suppressed (fused-decode flavor): the positive flow with a
justified inline suppression on the sink line."""
