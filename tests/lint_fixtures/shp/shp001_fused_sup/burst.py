from shp001_fused_sup.grid import window_grid


def fused_burst(rows, draft_tokens):
    width = len(draft_tokens) + 1
    return window_grid(rows, width)
