import jax.numpy as jnp


def window_grid(rows, width):
    return jnp.zeros((rows, width), jnp.int32)  # tpulint: disable=SHP001 -- kernel parity harness replays one captured draft, single compile
