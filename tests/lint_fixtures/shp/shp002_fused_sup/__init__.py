"""SHP002 suppressed (fused-decode flavor): no-warmup fused-step class
with a justified inline suppression on the class line."""
