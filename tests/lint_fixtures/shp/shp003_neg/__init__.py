"""SHP003 negative: the jit wrapper is memoized on self in __init__ —
the documented fix."""
