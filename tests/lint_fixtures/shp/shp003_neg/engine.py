import jax


def _model(x):
    return x + 1


class Engine:
    def __init__(self):
        self._model_jit = jax.jit(_model)

    def decode_step(self, x):
        return self._model_jit(x)
