"""OBS003 negative: label values drawn from small closed vocabularies."""
from prometheus_client import Counter, Gauge

CALLS = Counter("rag_calls_total", "calls", ["replica", "status"])
DEPTH = Gauge("rag_depth", "queue depth", ["replica", "priority"])


def handle(replica, ok):
    CALLS.labels(replica=replica, status="ok" if ok else "error").inc()


def publish(replica, priority, n, status_code):
    DEPTH.labels(replica=replica, priority=priority).set(n)
    # str() of a bounded enum-ish value is fine; only id-like args fire
    CALLS.labels(replica=replica, status=str(status_code)).inc()


def not_a_metric(rows, request_id):
    # .labels() on a dataframe-ish object with a non-metric meaning: the
    # keyword is what fires, and 'axis' isn't an id token
    return rows.labels(axis=0)
