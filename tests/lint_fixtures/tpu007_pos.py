"""Positive fixture for TPU007: the speculative-decode controller reads
the per-row acceptance array from device INSIDE the commit loop — one
blocking transfer per request per step."""
import numpy as np


def commit_decode_step(accepted_d, toks_d, reqs):
    out = []
    for i, req in enumerate(reqs):
        accepted = np.asarray(accepted_d)  # fetches the whole batch per row
        toks = np.array(toks_d)
        out.append((req, int(accepted[i]), int(toks[i])))
    return out
