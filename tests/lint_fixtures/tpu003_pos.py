"""TPU003 positive: shape-varying Python scalars cross the jit boundary."""
import jax
import jax.numpy as jnp


@jax.jit
def make_buffer(n):
    return jnp.zeros(n)  # traced param used as a shape


@jax.jit
def regrid(x, rows):
    return x.reshape(rows, -1)  # traced param in reshape


def caller(tokens, pad_batch):
    # len() straight into a jitted callable: recompiles per distinct length
    return make_buffer(len(tokens))
