"""TPU002 positive: numpy executed inside a jitted function."""
import jax
import numpy as np


@jax.jit
def host_math(x):
    y = np.asarray(x)  # device -> host transfer at trace time
    return np.sum(y)  # host-side reduction baked into the trace
