from jax import lax
from jax.sharding import Mesh

AXES = ("dp", "sp")


def make_mesh(devices):
    return Mesh(devices, AXES)


def rotate(x, axis_size):
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    return lax.ppermute(x, "sp", perm=perm)


def swap_pair(x):
    perm = [(0, 1), (1, 0)]
    return lax.ppermute(x, "sp", perm=perm)
