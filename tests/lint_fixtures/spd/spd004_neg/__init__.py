"""SPD004 negative: canonical modular cyclic shift, plus an explicit
constant permutation that covers both ranks exactly once."""
