"""SPD002 positive: a buffer donated to a jitted call (defined in
ops.py) is read again in engine.py — once directly, once through a
helper that consumes its parameter, so the witness must chain the
helper hop."""
