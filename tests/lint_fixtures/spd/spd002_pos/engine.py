from spd002_pos.ops import update_pool


def step(pool, delta):
    new_pool = update_pool(pool, delta)
    return pool.sum() + new_pool


def _flush(pool, delta):
    update_pool(pool, delta)


def drive(pool, delta):
    _flush(pool, delta)
    return pool * 2
