"""SPD001 positive: the shard_map body psums over an axis the mesh does
not bind — the axis universe comes from mesh.py, the collective sits in
collect.py, so only the cross-module pass can connect them."""
