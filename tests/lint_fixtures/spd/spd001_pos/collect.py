from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def _sum_body(x):
    return lax.psum(x, "pp")


def gather_stats(mesh, x):
    f = shard_map(_sum_body, mesh, in_specs=(P(),), out_specs=P())
    return f(x)
