"""SPD005 positive: the shard_map body indexes a module-level
jnp.arange table through its closure — the trace captures it as a
constant and every shard materializes a full replicated copy."""
