"""SPD004 suppressed: the unwrapped shift is silenced with a justified
directive on the ppermute line the finding anchors to."""
