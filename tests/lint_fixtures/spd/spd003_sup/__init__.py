"""SPD003 suppressed: the psum/out_specs mismatch is silenced with a
justified directive on the return line the finding anchors to."""
