from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AXES = ("dp", "tp")


def make_mesh(devices):
    return Mesh(devices, AXES)


def _reduce_body(x):
    total = lax.psum(x, "tp")
    return total  # tpulint: disable=SPD003 -- downstream re-shards on purpose to feed the per-shard debug dump


def all_reduce(mesh, x):
    f = shard_map(_reduce_body, mesh,
                  in_specs=(P(None, "tp"),), out_specs=P(None, "tp"))
    return f(x)
