"""SPD005 suppressed: the closed-over table read is silenced with a
justified directive on the read line."""
