import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AXES = ("dp",)

_TABLE = jnp.arange(1024)


def make_mesh(devices):
    return Mesh(devices, AXES)


def _lookup_body(idx):
    return _TABLE[idx]  # tpulint: disable=SPD005 -- the rope table is tiny and intentionally replicated on every shard


def lookup(mesh, idx):
    f = shard_map(_lookup_body, mesh,
                  in_specs=(P("dp"),), out_specs=P("dp"))
    return f(idx)
