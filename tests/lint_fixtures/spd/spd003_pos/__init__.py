"""SPD003 positive: one body psums over tp but out_specs still
partitions tp (the replicated result is re-scattered); a second body
returns an unreduced per-shard accumulator under a replicated spec."""
