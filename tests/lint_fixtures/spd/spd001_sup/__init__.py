"""SPD001 suppressed: same hazard as the positive, silenced with a
justified directive on the collective line."""
