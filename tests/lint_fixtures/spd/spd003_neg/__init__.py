"""SPD003 negative: the psum-reduced value returns under a replicated
spec, the partitioned passthrough keeps its axis in out_specs, and a
branch-reduced value is returned inside the reduced arm only."""
