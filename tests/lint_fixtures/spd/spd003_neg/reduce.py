from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AXES = ("dp", "tp")


def make_mesh(devices):
    return Mesh(devices, AXES)


def _reduce_body(x):
    total = lax.psum(x, "tp")
    return total


def all_reduce(mesh, x):
    f = shard_map(_reduce_body, mesh,
                  in_specs=(P(None, "tp"),), out_specs=P())
    return f(x)


def _passthrough_body(x):
    return x * 2


def passthrough(mesh, x):
    f = shard_map(_passthrough_body, mesh,
                  in_specs=(P(None, "tp"),), out_specs=P(None, "tp"))
    return f(x)
