from jax import lax
from jax.sharding import Mesh

AXES = ("dp", "sp")


def make_mesh(devices):
    return Mesh(devices, AXES)


def rotate(x, axis_size):
    perm = [(j, j + 1) for j in range(axis_size)]
    return lax.ppermute(x, "sp", perm=perm)
