"""SPD004 positive: the ring permutation misses the % axis_size wrap,
so the last rank's destination falls off the ring."""
