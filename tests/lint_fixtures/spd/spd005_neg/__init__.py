"""SPD005 negative: the table arrives through the body's arguments with
its own in_specs entry; the closed-over module binding is a plain float
scale, not a device array."""
