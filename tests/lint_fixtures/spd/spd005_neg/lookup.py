from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AXES = ("dp",)

_SCALE = 1.0 / 1024.0


def make_mesh(devices):
    return Mesh(devices, AXES)


def _lookup_body(table, idx):
    return table[idx] * _SCALE


def lookup(mesh, table, idx):
    f = shard_map(_lookup_body, mesh,
                  in_specs=(P(), P("dp")), out_specs=P("dp"))
    return f(table, idx)
