from spd002_neg.ops import update_pool


def step(pool, delta):
    pool = update_pool(pool, delta)
    return pool.sum()


def branchy(pool, delta, fast):
    if fast:
        pool = update_pool(pool, delta)
    else:
        pool = update_pool(pool, delta * 2)
    return pool * 2
