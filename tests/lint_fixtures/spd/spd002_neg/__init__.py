"""SPD002 negative: every donation is followed only by the rebinding
idiom (`pool = f(pool)`) or by no further read; a branch that donates
rebinds on both arms before the next read."""
