from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def update_pool(pool, delta):
    return pool + delta
