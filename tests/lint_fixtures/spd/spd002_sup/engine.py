from spd002_sup.ops import update_pool


def step(pool, delta):
    new_pool = update_pool(pool, delta)
    return pool.sum() + new_pool  # tpulint: disable=SPD002 -- donation is a no-op on the CPU smoke path this helper serves
