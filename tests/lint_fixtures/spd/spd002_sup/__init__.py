"""SPD002 suppressed: the stale read is silenced with a justified
directive on the read line."""
