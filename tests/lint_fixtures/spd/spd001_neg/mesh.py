from jax.sharding import Mesh

AXIS_NAMES = ("dp", "tp")


def make_mesh(devices):
    return Mesh(devices, AXIS_NAMES)
