"""SPD001 negative: every collective names an axis the mesh binds,
including one resolved through an axis_name= parameter default and a
partial() binding."""
