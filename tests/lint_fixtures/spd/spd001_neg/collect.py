from functools import partial

from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def _sum_body(x, axis_name="dp"):
    return lax.psum(x, axis_name)


def gather_stats(mesh, x):
    f = shard_map(partial(_sum_body, axis_name="tp"), mesh,
                  in_specs=(P(),), out_specs=P())
    return f(x)
