"""TPU003 negative: shapes declared static, call sites bucketed."""
from functools import partial

import jax
import jax.numpy as jnp


def next_bucket(n):
    return max(8, 1 << (n - 1).bit_length())


@partial(jax.jit, static_argnames=("n",))
def make_buffer(x, n):
    return x + jnp.zeros(n)  # static shape arg — one compile per bucket


@jax.jit
def from_own_shape(x):
    return x.reshape(x.shape[0], -1)  # shapes of traced args are static


def caller(x, tokens):
    return make_buffer(x, next_bucket(len(tokens)))
