"""ASY001 negative: async equivalents and executor hand-off."""
import asyncio
import time

import requests


async def poll_backend(url):
    await asyncio.sleep(1.0)
    loop = asyncio.get_running_loop()

    def fetch():
        # nested sync def is shipped to the executor — blocking is fine here
        time.sleep(0.01)
        return requests.get(url, timeout=5)

    return await loop.run_in_executor(None, fetch)


def sync_probe(url):
    time.sleep(0.1)  # not async: blocking is the caller's problem
    return requests.get(url, timeout=5)
