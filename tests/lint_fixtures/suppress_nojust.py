"""Suppression without a justification: finding stays, LNT000 is added."""
import time


async def shutdown_grace():
    time.sleep(0.05)  # tpulint: disable=ASY001
