"""TPU004 negative: keys split before every consumption."""
import jax


def double_sample(key, shape):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, shape)
    b = jax.random.uniform(k2, shape)
    return a + b


def loop_sample(key, steps):
    out = []
    for _ in range(steps):
        key, sub = jax.random.split(key)  # re-bound inside the loop
        out.append(jax.random.normal(sub, ()))
    return out


def chain(key, shape):
    key = jax.random.split(key, 2)[0]
    return jax.random.normal(key, shape)  # key re-bound between uses
